"""Command-line entry points: ``train``, ``sweep``, ``plot``.

TPU-native replacement for the reference's L4/L5 layers: ``train`` mirrors
``python main.py`` (reference ``main.py:22-121``) with the same flag names
and artifact outputs; ``sweep`` replaces the SGE job-array orchestration
(``simulation_results/raw_data/*/job.sh``, SURVEY.md C15) with one sharded
on-device run over scenario x H x seed; ``plot`` replaces
``plot_results.py``. Unlike the reference — where ``--agent_label`` and
``--in_nodes`` were unoverridable argparse defaults (SURVEY.md §5) —
topology and cast are real flags here, plus ``--scenario`` presets for the
published experiment matrix.
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import itertools
import json
import os
import sys
import time
from datetime import datetime
from pathlib import Path

import math

import numpy as np

from rcmarl_tpu.config import (
    CONSENSUS_IMPLS,
    ENV_NAMES,
    GRAPH_SCHEDULES,
    Config,
    Roles,
    circulant_in_nodes,
    full_in_nodes,
)

#: The published experiment matrix (reference README "four scenarios" and
#: raw_data/ layout): the adversary, when present, is node 4 (verified in
#: raw_data/*/H=1/seed=100/out.txt config dumps), plus this framework's
#: 'adaptive' cast — the colluding omniscient adversary crafting its
#: payload against the trimmed mean (Roles.ADAPTIVE, QUALITY.md
#: "Adaptive colluding adversary").
SCENARIOS = {
    "coop": ["Cooperative"] * 5,
    "greedy": ["Cooperative"] * 4 + ["Greedy"],
    "faulty": ["Cooperative"] * 4 + ["Faulty"],
    "malicious": ["Cooperative"] * 4 + ["Malicious"],
    "adaptive": ["Cooperative"] * 4 + ["Adaptive"],
}


def scenario_labels(name: str):
    base = name.removesuffix("_global")
    if base not in SCENARIOS:
        raise SystemExit(
            f"unknown scenario {name!r}; pick from "
            f"{sorted(SCENARIOS)} (+ '_global' suffix for team-average reward)"
        )
    return SCENARIOS[base], name.endswith("_global")


def _add_config_flags(p: argparse.ArgumentParser) -> None:
    """Reference main.py:25-44 flag surface (same names/defaults), with the
    list-valued flags made real."""
    p.add_argument("--n_agents", type=int, default=5)
    p.add_argument(
        "--agent_label",
        nargs="+",
        default=None,
        help="per-agent role labels (Cooperative/Greedy/Faulty/Malicious)",
    )
    p.add_argument(
        "--in_degree",
        type=int,
        default=4,
        help="circulant-graph in-degree incl. self (reference default graph)",
    )
    p.add_argument(
        "--in_nodes",
        type=str,
        default=None,
        help="explicit topology as JSON, e.g. '[[0,1,2,3],[1,2,3,4],...]'",
    )
    p.add_argument(
        "--env",
        type=str,
        default="grid_world",
        choices=list(ENV_NAMES),
        help="environment to train in (the env-zoo registry, "
        "rcmarl_tpu.envs: grid_world = the reference task, pursuit = "
        "chase a fleeing evader, coverage = spread over a landmark "
        "layout, congestion = goal routing with literal load costs on "
        "shared cells)",
    )
    p.add_argument("--n_actions", type=int, default=5)
    p.add_argument("--n_states", type=int, default=2)
    p.add_argument("--n_episodes", type=int, default=7000)
    p.add_argument("--max_ep_len", type=int, default=20)
    p.add_argument("--n_ep_fixed", type=int, default=50)
    p.add_argument("--n_epochs", type=int, default=10)
    p.add_argument("--slow_lr", type=float, default=0.01)
    p.add_argument("--fast_lr", type=float, default=0.01)
    p.add_argument("--batch_size", type=int, default=200)
    p.add_argument("--buffer_size", type=int, default=2000)
    p.add_argument("--gamma", type=float, default=0.9)
    p.add_argument("--H", type=int, default=0)
    p.add_argument("--common_reward", action="store_true")
    p.add_argument("--eps", type=float, default=0.1, help="exploration mix")
    p.add_argument("--nrow", type=int, default=5, help="grid rows")
    p.add_argument("--ncol", type=int, default=5, help="grid columns")
    p.add_argument(
        "--reference_clip",
        action="store_true",
        help="reference-exact move clipping (both coordinates bounded by "
        "nrow-1, reference grid_world.py:55); only matters when nrow != ncol",
    )
    p.add_argument(
        "--hidden",
        nargs="+",
        type=int,
        default=[20, 20],
        help="hidden layer widths of every net (reference default: 20 20; "
        "the BASELINE scale-out configs widen this)",
    )
    p.add_argument(
        "--scenario",
        type=str,
        default=None,
        help="preset cast: coop/greedy/faulty/malicious[_global]",
    )
    p.add_argument(
        "--consensus_impl",
        type=str,
        default="xla",
        choices=list(CONSENSUS_IMPLS),
        help="consensus aggregation backend: xla/pallas = selection-based "
        "trim bounds, *_sort = full-sort comparison arms, auto = measured "
        "3-way crossover (ops/aggregation.py)",
    )
    p.add_argument(
        "--consensus_layout",
        type=str,
        default="flat",
        choices=["flat", "per_leaf"],
        help="consensus message-tree layout: flat = every leaf raveled "
        "into one (n_in, P_total) launch per tree (default), per_leaf = "
        "the historical leaf-by-leaf dispatch (comparison arm); bitwise "
        "identical outputs",
    )
    p.add_argument(
        "--netstack",
        type=str,
        default="auto",
        choices=["auto", "on", "off"],
        help="critic+TR netstack: on = the whole critic/TR epoch runs on "
        "ONE stacked parameter block (single (net, agent)-vmapped "
        "phase-I fits, combined (n_in, P_critic + P_tr) consensus "
        "block); off = the historical dual-launch comparison arm (the "
        "only arm --consensus_layout affects); auto (default) = the "
        "measured backend policy — stacked on TPU, dual elsewhere "
        "(PERF.md 'netstack'). Outputs are pinned equivalent either way",
    )
    p.add_argument(
        "--fitstack",
        type=str,
        default="auto",
        choices=["auto", "on", "off", "pallas", "pallas_interpret"],
        help="cross-flavor fused fit scan: on = every phase-I fit flavor "
        "sharing a schedule shape (coop full-batch pair vs the "
        "greedy/malicious minibatch flavors) runs as ONE stacked "
        "(flavor·net, agent) scan; off = the PR-4 per-flavor arms; auto "
        "(default) = the measured backend policy — fused on TPU, "
        "per-flavor elsewhere (PERF.md 'fitstack / bf16'); pallas / "
        "pallas_interpret = the fused rows through the fit-scan Pallas "
        "kernel (ops/pallas_fit.py: params VMEM-resident across the "
        "whole schedule; interpret = CPU test arm). Outputs are "
        "pinned bitwise either way",
    )
    p.add_argument(
        "--compute_dtype",
        type=str,
        default="float32",
        choices=["float32", "bfloat16"],
        help="matmul compute precision: float32 = reference-parity, "
        "bfloat16 = MXU-native inputs with f32 accumulation (scale-out)",
    )
    g = p.add_argument_group("time-varying communication graphs")
    g.add_argument(
        "--graph_schedule",
        type=str,
        default="static",
        choices=list(GRAPH_SCHEDULES),
        help="communication-graph schedule: static (default) = the "
        "fixed --in_nodes/--in_degree topology, bit-for-bit the seed "
        "behavior; random_geometric = resample the in-neighborhoods "
        "every --graph_every blocks as a deterministic random-"
        "geometric digraph (gather indices are DATA — zero recompiles, "
        "lint --retrace case). Solo trainer only.",
    )
    g.add_argument(
        "--graph_every",
        type=int,
        default=1,
        help="resample the time-varying graph every K blocks",
    )
    g.add_argument(
        "--graph_degree",
        type=int,
        default=0,
        help="in-degree (incl. self) of the resampled graph; 0 = reuse "
        "the static graph's n_in (needs 2H <= degree-1)",
    )
    g.add_argument(
        "--graph_seed",
        type=int,
        default=0,
        help="graph-schedule namespace (independent of the training "
        "seed; resumed runs replay their exact graph sequence)",
    )
    p.add_argument(
        "--congestion_weight",
        type=float,
        default=1.0,
        help="congestion-world toll per OTHER agent sharing a cell "
        "(envs/congestion.py; 1.0 = the env's historical default, "
        "bit-for-bit)",
    )
    p.add_argument(
        "--fit_clip",
        type=float,
        default=0.0,
        help="global-gradient-norm ceiling for the phase-I critic/TR "
        "SGD fits (0.0 = off, bit-for-bit the reference program). The "
        "mega-population stability rail: past n~64 the fixed fast_lr "
        "exceeds the raw full-batch fit's SGD stability bound and "
        "clean training diverges; the n>=256 cells use 1.0",
    )
    t = p.add_argument_group("Diff-DAC multitask axis")
    t.add_argument(
        "--task_axis",
        action="store_true",
        help="turn the vmapped replica axis into a TASK axis (Diff-DAC): "
        "replica r trains the congestion world at load level "
        "--task_levels[r] (traced data — one compiled program for the "
        "whole task family), with the gossip mix doubling as the "
        "cross-task consensus step. Requires --replicas >= 2, "
        "--env congestion, a static graph schedule, no pipeline tier, "
        "and the XLA consensus family",
    )
    t.add_argument(
        "--task_levels",
        nargs="+",
        type=float,
        default=None,
        help="one positive congestion-toll multiplier per replica "
        "(default: an even spread over [0.5, 2.0])",
    )
    p.add_argument(
        "--adaptive_scale",
        type=float,
        default=10.0,
        help="payload magnitude of Adaptive colluding adversaries, in "
        "units of the cooperative messages' per-coordinate spread: "
        "small = just inside the trim bounds (residual-influence "
        "stress test for H), large = the unbounded coordinated-mean "
        "attack H=0 cannot absorb (rcmarl_tpu.faults."
        "adaptive_payload_tree)",
    )
    _add_pipeline_flags(p)
    _add_fault_flags(p)


def _add_pipeline_flags(p: argparse.ArgumentParser) -> None:
    """Async actor-learner pipeline knobs (rcmarl_tpu.pipeline)."""
    g = p.add_argument_group("async actor-learner pipeline")
    g.add_argument(
        "--pipeline_depth",
        type=int,
        default=0,
        help="rollout blocks the actor tier runs AHEAD of the learner "
        "(rcmarl_tpu.pipeline): 0 = synchronous handoff (the fused "
        "reference block, bitwise the historical trainer), >= 2 = "
        "rollout dispatched into the epoch's shadow at depth-1 epochs "
        "of measured parameter staleness (counted per block in "
        "df.attrs['pipeline'] and the summary line)",
    )
    g.add_argument(
        "--publish_every",
        type=int,
        default=1,
        help="the learner publishes its params to the actor tier every "
        "K blocks (validate-then-swap-wholesale, the in-memory twin of "
        "the serving hot-swap chain); K > 1 adds up to K-1 blocks of "
        "staleness — the off-policy axis the staleness quality cell "
        "sweeps (QUALITY.md)",
    )


def _add_fault_flags(p: argparse.ArgumentParser) -> None:
    """Transport-fault injection + graceful-degradation knobs
    (rcmarl_tpu.faults; all probabilities are per directed link per
    consensus epoch, the self link is never faulted)."""
    g = p.add_argument_group("transport faults")
    g.add_argument("--fault_drop_p", type=float, default=0.0,
                   help="P(link delivers nothing -> NaN payload)")
    g.add_argument("--fault_stale_p", type=float, default=0.0,
                   help="P(link replays the sender's stale pre-fit weights)")
    g.add_argument("--fault_corrupt_p", type=float, default=0.0,
                   help="P(additive Gaussian corruption of the payload)")
    g.add_argument("--fault_corrupt_scale", type=float, default=1.0,
                   help="stddev of the additive corruption noise")
    g.add_argument("--fault_flip_p", type=float, default=0.0,
                   help="P(sign-flip corruption of the payload)")
    g.add_argument("--fault_nan_p", type=float, default=0.0,
                   help="P(all-NaN payload bomb)")
    g.add_argument("--fault_inf_p", type=float, default=0.0,
                   help="P(±Inf payload bomb, random sign)")
    g.add_argument("--fault_seed", type=int, default=0,
                   help="fault-stream namespace (independent of the "
                   "training seed)")
    g.add_argument(
        "--sanitize",
        action="store_true",
        help="non-finite-hardened consensus: NaN/±Inf neighbor entries "
        "become per-element exclusions; elements with fewer than 2H+1 "
        "finite survivors keep the agent's own value "
        "(ops/aggregation.py sanitize mode)",
    )


def fault_plan_from_args(args):
    """The CLI fault flags as a FaultPlan, or None when all-zero (the
    clean transport — bit-for-bit the unfaulted seed behavior)."""
    from rcmarl_tpu.faults import FaultPlan

    plan = FaultPlan(
        drop_p=args.fault_drop_p,
        stale_p=args.fault_stale_p,
        corrupt_p=args.fault_corrupt_p,
        corrupt_scale=args.fault_corrupt_scale,
        flip_p=args.fault_flip_p,
        nan_p=args.fault_nan_p,
        inf_p=args.fault_inf_p,
        seed=args.fault_seed,
    )
    return plan if plan.active else None


def _add_gossip_flags(p: argparse.ArgumentParser) -> None:
    """Gossip-replicated learners + the replica-level threat model
    (rcmarl_tpu.parallel.gossip / rcmarl_tpu.faults.ReplicaFaultPlan)."""
    g = p.add_argument_group("gossip-replicated learners")
    g.add_argument(
        "--replicas",
        type=int,
        default=0,
        help="train this many learner replicas as one vmapped seed-axis "
        "program, mixing their parameters by trimmed-mean gossip "
        "(0 = the solo trainer, unchanged)",
    )
    g.add_argument(
        "--gossip_every",
        type=int,
        default=1,
        help="mix the replicas every K blocks (0 = never mix: "
        "independent replicas, bitwise the seed-axis behavior)",
    )
    g.add_argument(
        "--gossip_graph",
        type=str,
        default="ring",
        choices=["ring", "full", "random_geometric"],
        help="replica communication graph (random_geometric: "
        "deterministic unit-square positions from --gossip_seed, "
        "degree-1 nearest neighbors)",
    )
    g.add_argument(
        "--gossip_degree",
        type=int,
        default=3,
        help="replica in-degree incl. self for ring/random_geometric "
        "graphs (full ignores it)",
    )
    g.add_argument(
        "--gossip_H",
        type=int,
        default=1,
        help="replica-level trim parameter: up to H Byzantine/corrupted "
        "replicas per gossip neighborhood are trimmed away "
        "(needs 2H <= degree-1)",
    )
    g.add_argument(
        "--gossip_mix",
        type=str,
        default="trimmed",
        choices=["trimmed", "mean"],
        help="mixing operator: trimmed = the sanitized resilient "
        "clip-and-average (hardened default), mean = plain mean (the "
        "unhardened comparison arm one NaN replica poisons)",
    )
    g.add_argument(
        "--gossip_seed",
        type=int,
        default=0,
        help="gossip-stream namespace (graph positions + replica fault "
        "draws), independent of the training seeds",
    )
    g.add_argument(
        "--gossip_readmit_after",
        type=int,
        default=0,
        help="sticky-quarantine readmission: a guard-excluded replica "
        "re-enters the gossip mix only after this many CONSECUTIVE "
        "healthy probe rounds (an unhealthy segment resets the "
        "streak — the flapping-sender defense); 0 (default) = the "
        "historical one-round exclusion, bit-for-bit "
        "(rcmarl_tpu.parallel.gossip, run-local knob like the serve "
        "flags — not a Config field)",
    )
    gl = p.add_argument_group(
        "pipelined gossip fleet (--replicas + --pipeline_depth composed)"
    )
    gl.add_argument(
        "--canary_band",
        type=float,
        default=0.0,
        help="composed-topology deploy gate: after each gossip segment "
        "the winning replica's policy is offered to the fleet-facing "
        "deploy publisher, and with band > 0 a CanaryGate rejects any "
        "candidate whose frozen return falls more than this relative "
        "band below the incumbent (0 = gate off: every finite winner "
        "publishes; requires --replicas > 0 AND --pipeline_depth > 0)",
    )
    gl.add_argument(
        "--canary_blocks",
        type=int,
        default=1,
        help="frozen-policy evaluation blocks per composed canary "
        "decision (rcmarl_tpu.serve.canary eval cadence)",
    )
    rf = p.add_argument_group(
        "replica faults (per directed gossip link per round)"
    )
    rf.add_argument("--replica_fault_drop_p", type=float, default=0.0,
                    help="P(gossip link delivers nothing -> NaN payload)")
    rf.add_argument("--replica_fault_stale_p", type=float, default=0.0,
                    help="P(link replays the sender's LAST-round params)")
    rf.add_argument("--replica_fault_corrupt_p", type=float, default=0.0,
                    help="P(additive Gaussian corruption of the payload)")
    rf.add_argument("--replica_fault_corrupt_scale", type=float, default=1.0,
                    help="stddev of the additive corruption noise")
    rf.add_argument("--replica_fault_flip_p", type=float, default=0.0,
                    help="P(sign-flip corruption of the payload)")
    rf.add_argument("--replica_fault_nan_p", type=float, default=0.0,
                    help="P(all-NaN payload bomb)")
    rf.add_argument("--replica_fault_inf_p", type=float, default=0.0,
                    help="P(+Inf payload bomb)")
    rf.add_argument("--replica_fault_seed", type=int, default=0,
                    help="replica-fault-stream namespace")
    rf.add_argument(
        "--replica_byzantine",
        nargs="+",
        type=int,
        default=None,
        help="replica indices that are ALWAYS adversarial: every payload "
        "they send is replaced per --replica_byzantine_mode "
        "(deterministic, not probabilistic)",
    )
    rf.add_argument(
        "--replica_byzantine_mode",
        type=str,
        default="nan",
        choices=["nan", "sign_flip", "inf"],
        help="what a Byzantine replica sends: all-NaN bombs, the "
        "negation of its current params, or +Inf bombs",
    )


def replica_fault_plan_from_args(args):
    """The CLI replica-fault flags as a ReplicaFaultPlan, or None when
    inactive (clean gossip links, bitwise the fault-free mix)."""
    from rcmarl_tpu.faults import ReplicaFaultPlan

    plan = ReplicaFaultPlan(
        drop_p=getattr(args, "replica_fault_drop_p", 0.0),
        stale_p=getattr(args, "replica_fault_stale_p", 0.0),
        corrupt_p=getattr(args, "replica_fault_corrupt_p", 0.0),
        corrupt_scale=getattr(args, "replica_fault_corrupt_scale", 1.0),
        flip_p=getattr(args, "replica_fault_flip_p", 0.0),
        nan_p=getattr(args, "replica_fault_nan_p", 0.0),
        inf_p=getattr(args, "replica_fault_inf_p", 0.0),
        byzantine_replicas=tuple(
            getattr(args, "replica_byzantine", None) or ()
        ),
        byzantine_mode=getattr(args, "replica_byzantine_mode", "nan"),
        seed=getattr(args, "replica_fault_seed", 0),
    )
    return plan if plan.active else None


def _netstack_value(arm: str):
    """CLI arm string -> Config.netstack / Config.fitstack value (the
    two gates share the on/off/'auto' vocabulary; fitstack additionally
    accepts the fit-scan kernel arms 'pallas'/'pallas_interpret', which
    pass through verbatim — only the fitstack flags list them)."""
    if arm in ("pallas", "pallas_interpret"):
        return arm
    return {"on": True, "off": False}.get(arm, "auto")


def config_from_args(args) -> Config:
    labels = args.agent_label
    common = args.common_reward
    if args.scenario:
        if labels is not None:
            raise SystemExit(
                "--scenario and --agent_label conflict: the preset would "
                "replace your explicit cast; pass only one of them"
            )
        labels, is_global = scenario_labels(args.scenario)
        common = common or is_global
    if labels is None:
        labels = ["Cooperative"] * args.n_agents
    if len(labels) != args.n_agents:
        raise SystemExit(
            f"--agent_label has {len(labels)} entries for --n_agents={args.n_agents}"
        )
    bad = [l for l in labels if l not in Roles.BY_NAME]
    if bad:
        raise SystemExit(
            f"unknown agent label(s) {bad}; valid: {sorted(Roles.BY_NAME)}"
        )
    if args.in_nodes is not None:
        in_nodes = tuple(tuple(n) for n in json.loads(args.in_nodes))
    else:
        in_nodes = circulant_in_nodes(args.n_agents, args.in_degree)
    return Config(
        n_agents=args.n_agents,
        agent_roles=tuple(Roles.BY_NAME[l] for l in labels),
        in_nodes=in_nodes,
        env=getattr(args, "env", "grid_world"),
        graph_schedule=getattr(args, "graph_schedule", "static"),
        graph_every=getattr(args, "graph_every", 1),
        graph_degree=getattr(args, "graph_degree", 0),
        graph_seed=getattr(args, "graph_seed", 0),
        adaptive_scale=getattr(args, "adaptive_scale", 10.0),
        congestion_weight=getattr(args, "congestion_weight", 1.0),
        fit_clip=getattr(args, "fit_clip", 0.0),
        task_axis=getattr(args, "task_axis", False),
        task_levels=tuple(getattr(args, "task_levels", None) or ()),
        n_actions=args.n_actions,
        n_states=args.n_states,
        n_episodes=args.n_episodes,
        max_ep_len=args.max_ep_len,
        n_ep_fixed=args.n_ep_fixed,
        n_epochs=args.n_epochs,
        slow_lr=args.slow_lr,
        fast_lr=args.fast_lr,
        batch_size=args.batch_size,
        buffer_size=args.buffer_size,
        gamma=args.gamma,
        H=args.H,
        common_reward=common,
        eps_explore=args.eps,
        nrow=args.nrow,
        ncol=args.ncol,
        reference_clip=args.reference_clip,
        hidden=tuple(getattr(args, "hidden", None) or (20, 20)),
        seed=getattr(args, "random_seed", 300),
        consensus_impl=args.consensus_impl,
        consensus_layout=getattr(args, "consensus_layout", "flat"),
        netstack=_netstack_value(getattr(args, "netstack", "auto")),
        fitstack=_netstack_value(getattr(args, "fitstack", "auto")),
        compute_dtype=args.compute_dtype,
        pipeline_depth=getattr(args, "pipeline_depth", 0),
        publish_every=getattr(args, "publish_every", 1),
        fault_plan=fault_plan_from_args(args),
        consensus_sanitize=args.sanitize,
        replicas=getattr(args, "replicas", 0),
        gossip_every=getattr(args, "gossip_every", 1),
        gossip_graph=getattr(args, "gossip_graph", "ring"),
        gossip_degree=getattr(args, "gossip_degree", 3),
        gossip_H=getattr(args, "gossip_H", 1),
        gossip_mix=getattr(args, "gossip_mix", "trimmed"),
        gossip_seed=getattr(args, "gossip_seed", 0),
        replica_fault_plan=replica_fault_plan_from_args(args),
        # the serve parser exposes its OWN --canary_band (watcher-side,
        # default None) — `or 0.0` keeps a serve-args Namespace mapping
        # onto the Config default instead of a None type error
        canary_band=getattr(args, "canary_band", 0.0) or 0.0,
        canary_blocks=getattr(args, "canary_blocks", 1),
    )


# --------------------------------------------------------------------------
# train
# --------------------------------------------------------------------------


def cmd_train(argv) -> int:
    p = argparse.ArgumentParser(
        prog="rcmarl_tpu train",
        description="Train RPBCAC agents (reference main.py equivalent)",
    )
    _add_config_flags(p)
    _add_gossip_flags(p)
    p.add_argument("--random_seed", type=int, default=300)
    p.add_argument("--summary_dir", type=str, default="./simulation_results/")
    p.add_argument(
        "--pretrained_agents",
        type=str,
        default=None,
        help="resume source: a checkpoint .npz or a directory holding "
        "reference-format pretrained_weights.npy + desired_state.npy",
    )
    p.add_argument(
        "--checkpoint_every",
        type=int,
        default=0,
        help="save checkpoint.npz every K blocks (0 = only at the end)",
    )
    p.add_argument(
        "--phase",
        type=int,
        default=None,
        help="write sim_data<phase>.pkl (reference two-phase protocol); "
        "default: next free phase number, so resumed runs never clobber "
        "earlier phases' metrics",
    )
    p.add_argument("--quiet", action="store_true")
    p.add_argument(
        "--guard",
        type=str,
        default="auto",
        choices=["auto", "on", "off"],
        help="per-block non-finite guard rails (rollback to the last "
        "good state, bounded retry, then skip); auto = on exactly when "
        "a fault plan is active",
    )
    p.add_argument(
        "--max_retries",
        type=int,
        default=1,
        help="guard retry budget per block before the block is skipped",
    )
    p.add_argument(
        "--profile",
        action="store_true",
        help="print per-phase timing breakdown before training "
        "(utils/profiling.py)",
    )
    p.add_argument(
        "--trace_dir",
        type=str,
        default=None,
        help="record a TensorBoard/Perfetto device trace of the run",
    )
    args = p.parse_args(argv)

    import jax

    from rcmarl_tpu.training.trainer import init_train_state, train
    from rcmarl_tpu.utils.checkpoint import (
        import_reference_weights,
        load_checkpoint_with_meta,
        save_checkpoint,
        save_reference_artifacts,
    )

    cfg = config_from_args(args)
    out = Path(args.summary_dir)
    out.mkdir(parents=True, exist_ok=True)

    state = None
    ckpt_meta = {}
    if args.pretrained_agents:
        src = Path(args.pretrained_agents)
        if not src.exists():
            raise SystemExit(f"--pretrained_agents: {src} does not exist")
        if cfg.replicas and not src.is_file():
            raise SystemExit(
                "--replicas resume needs a checkpoint .npz (the "
                "reference artifact layout has no replica axis)"
            )
        if src.is_file():  # our checkpoint
            # Checksum-verified; a corrupted/truncated file falls back to
            # the rotated <src>.prev instead of crashing the resume (the
            # same discovery chain the serve watcher uses).
            state, ckpt_cfg, loaded, ckpt_meta = load_checkpoint_with_meta(
                src, cfg
            )
            if loaded != src:
                print(
                    f"WARNING: {src} is corrupted; resumed the previous "
                    f"good checkpoint {loaded}"
                )
            ckpt_replicas = int(ckpt_meta.get("replicas", 0))
            if ckpt_replicas != cfg.replicas:
                # the loaded state's replica axis comes from the FILE's
                # meta; running it under a different --replicas would
                # mix/train a mismatched world (gather indices silently
                # clamp inside jit) — fail loudly instead
                raise SystemExit(
                    f"--pretrained_agents: checkpoint {loaded} was saved "
                    f"with replicas={ckpt_replicas}, this run requests "
                    f"--replicas {cfg.replicas}; replica counts must match"
                )
            block_no = int(np.asarray(state.block).reshape(-1)[0])
            print(f"resumed checkpoint {loaded} at block {block_no}")
            # Shapes were validated by load_checkpoint; non-structural
            # hyperparameters (H, lrs, gamma, schedule...) come from the
            # CLI and may silently differ from the stored run — surface it.
            diffs = {
                f.name: (getattr(ckpt_cfg, f.name), getattr(cfg, f.name))
                for f in dataclasses.fields(Config)
                if getattr(ckpt_cfg, f.name) != getattr(cfg, f.name)
            }
            if diffs:
                print(
                    "WARNING: resumed run overrides checkpointed config "
                    "(stored -> active): "
                    + ", ".join(
                        f"{k}: {a!r} -> {b!r}" for k, (a, b) in diffs.items()
                    )
                )
        else:  # reference-format artifact directory (main.py:52-54,83-92)
            weights = np.load(src / "pretrained_weights.npy", allow_pickle=True)
            desired = np.load(src / "desired_state.npy", allow_pickle=True)
            state = init_train_state(
                cfg, jax.random.PRNGKey(cfg.seed), desired=np.asarray(desired)
            )
            params = import_reference_weights(weights, cfg, state.params)
            state = state._replace(params=params)
            print(f"warm-started from reference artifacts in {src}")

    def checkpoint_cb(s, b):
        if args.checkpoint_every and (b + 1) % args.checkpoint_every == 0:
            save_checkpoint(out / "checkpoint.npz", s, cfg)

    if args.profile:
        from rcmarl_tpu.utils.profiling import profile_phases

        for name, secs in profile_phases(cfg).items():
            print(f"profile {name:18s} {secs * 1e3:9.2f} ms")

    final_meta = None
    t0 = time.perf_counter()
    with contextlib.ExitStack() as stack:
        if args.trace_dir:
            from rcmarl_tpu.utils.profiling import trace as profiler_trace

            stack.enter_context(profiler_trace(args.trace_dir))
        if cfg.replicas and cfg.pipeline_depth:
            from rcmarl_tpu.parallel.gala import train_gala

            def gala_cb(s, b, meta):
                # fires once per gossip SEGMENT, the gossip_cb cadence
                every = args.checkpoint_every
                seg = meta.get("segment_blocks", 1)
                if every and (b + 1) // every > (b + 1 - seg) // every:
                    save_checkpoint(
                        out / "checkpoint.npz",
                        s,
                        cfg,
                        meta={k: meta[k] for k in
                              ("replicas", "gossip_round", "excluded")},
                    )

            state, sim_data = train_gala(
                cfg,
                states=state,
                verbose=not args.quiet,
                block_callback=gala_cb,
                guard={"auto": None, "on": True, "off": False}[args.guard],
                max_retries=args.max_retries,
                start_round=int(ckpt_meta.get("gossip_round", 0)),
                excluded=ckpt_meta.get("excluded"),
                readmit_after=args.gossip_readmit_after,
            )
            g = sim_data.attrs["gossip"]
            final_meta = {
                "replicas": g["replicas"],
                "gossip_round": g["gossip_round"],
                "excluded": g["excluded_mask"],
            }
        elif cfg.replicas:
            from rcmarl_tpu.parallel.gossip import train_gossip

            def gossip_cb(s, b, meta):
                # the callback fires once per SEGMENT (not per block):
                # checkpoint when the segment crossed a multiple of
                # checkpoint_every, so misaligned cadences still save
                every = args.checkpoint_every
                seg = meta.get("segment_blocks", 1)
                if every and (b + 1) // every > (b + 1 - seg) // every:
                    save_checkpoint(
                        out / "checkpoint.npz",
                        s,
                        cfg,
                        meta={k: meta[k] for k in
                              ("replicas", "gossip_round", "excluded")},
                    )

            state, sim_data = train_gossip(
                cfg,
                states=state,
                verbose=not args.quiet,
                block_callback=gossip_cb,
                guard={"auto": None, "on": True, "off": False}[args.guard],
                start_round=int(ckpt_meta.get("gossip_round", 0)),
                excluded=ckpt_meta.get("excluded"),
                readmit_after=args.gossip_readmit_after,
            )
            g = sim_data.attrs["gossip"]
            final_meta = {
                "replicas": g["replicas"],
                "gossip_round": g["gossip_round"],
                # the LIVE mask: a replica quarantined in a trailing
                # unmixed segment must still sit out its next mix after
                # a resume
                "excluded": g["excluded_mask"],
            }
        elif cfg.pipeline_depth:
            from rcmarl_tpu.pipeline.trainer import train_pipelined

            state, sim_data = train_pipelined(
                cfg,
                state=state,
                verbose=not args.quiet,
                block_callback=checkpoint_cb,
                guard={"auto": None, "on": True, "off": False}[args.guard],
                max_retries=args.max_retries,
            )
        else:
            state, sim_data = train(
                cfg,
                state=state,
                verbose=not args.quiet,
                block_callback=checkpoint_cb,
                guard={"auto": None, "on": True, "off": False}[args.guard],
                max_retries=args.max_retries,
            )
    dt = time.perf_counter() - t0
    if "gala" in sim_data.attrs:
        # the composed fleet's ONE merged counters line (staleness +
        # gossip + canary) — the CI smoke cell greps this
        from rcmarl_tpu.parallel.gala import gala_summary

        print(gala_summary(sim_data.attrs))
    elif "pipeline" in sim_data.attrs:
        from rcmarl_tpu.pipeline.trainer import pipeline_summary

        print(pipeline_summary(sim_data.attrs["pipeline"]))
    if "guard" in sim_data.attrs:
        g = sim_data.attrs["guard"]
        print(
            f"guard: {g['retries']} retries, {g['skipped']} skipped "
            f"blocks, {g['nonfinite']} non-finite payload entries, "
            f"{g['deficit']} degree-deficit fallbacks"
        )
    if "gossip" in sim_data.attrs:
        g = sim_data.attrs["gossip"]
        print(
            f"gossip: {g['replicas']} replicas ({g['graph']}, "
            f"{g['mix']} mix, H={g['H']}), {g['rounds']} rounds, "
            f"{g['rollbacks']} rollbacks, {g['excluded']} exclusions, "
            f"{g['nonfinite']} non-finite payload entries, "
            f"{g['deficit']} degree-deficit fallbacks; healthy: "
            f"{sum(g['replica_healthy'])}/{g['replicas']}"
            + (
                f"; readmissions: {g['readmitted']} "
                f"(readmit_after={g['readmit_after']}, quarantined: "
                f"{sum(g['quarantined'])})"
                if g.get("readmit_after")
                else ""
            )
            + (f" (byzantine: {g['byzantine']})" if g["byzantine"] else "")
        )

    phase = args.phase
    if phase is None:  # next free number: phase 1 fresh, 2 after resume, ...
        existing = [
            int(p.stem.removeprefix("sim_data"))
            for p in out.glob("sim_data*.pkl")
            if p.stem.removeprefix("sim_data").isdigit()
        ]
        phase = max(existing, default=0) + 1
    sim_data.to_pickle(out / f"sim_data{phase}.pkl")
    save_checkpoint(out / "checkpoint.npz", state, cfg, meta=final_meta)
    if not cfg.replicas:
        # reference interop expects the solo (unstacked) param layout
        save_reference_artifacts(out, state, cfg)
    steps = cfg.n_episodes * cfg.max_ep_len
    print(
        f"done: {cfg.n_episodes} episodes in {dt:.1f}s "
        f"({steps / dt:.1f} env-steps/s) -> {out}"
    )
    return 0


# --------------------------------------------------------------------------
# sweep
# --------------------------------------------------------------------------


class _CellUnhealthy(RuntimeError):
    """A sweep cell produced non-finite params or metrics — it diverged
    (or an injected fault plan poisoned it). Deterministic in the
    cell's seeds, so the isolation loop records it WITHOUT the retry it
    grants crashes; nothing is written for the cell."""


def _replica_param_health(states) -> np.ndarray:
    """(n_replicas,) bool: per-replica all-finite check over the batched
    final params (leading axis = replica). The sharded in-jit trainers
    have no host loop to roll back in, so divergence is detected here,
    after the fact."""
    import jax

    ok = None
    for l in jax.tree.leaves(states.params):
        a = np.asarray(l)
        if not np.issubdtype(a.dtype, np.floating):
            continue
        fin = np.isfinite(a).reshape(a.shape[0], -1).all(axis=1)
        ok = fin if ok is None else (ok & fin)
    return np.ones(1, bool) if ok is None else ok


def _check_cell_finite(states, phase_metrics, label: str) -> None:
    """The sweep-side guard rail: non-finite final params or metric rows
    fail the cell loudly BEFORE any artifact is written, instead of
    exiting rc=0 over silently corrupt sim_data. (Metrics alone are not
    enough: a poisoning in the run's LAST update block never reaches a
    rollout row.) Injected-fault sweeps want --sanitize."""
    bad = not _replica_param_health(states).all()
    bad = bad or any(
        not all(np.all(np.isfinite(np.asarray(l))) for l in metrics)
        for metrics in phase_metrics
    )
    if bad:
        raise _CellUnhealthy(
            f"{label}: non-finite params/metrics (diverged or "
            "fault-poisoned; for fault-injection sweeps run with "
            "--sanitize)"
        )


def _run_phases(phases: int, train_fresh, train_resume, reset):
    """The published multi-phase restart protocol, shared by the
    sequential and fused sweeps: phase 1 trains fresh; each later phase
    applies the restart boundary (weights + goal kept; Adam moments,
    buffer, RNG reset) and resumes. The host fetch per phase is the
    completion barrier (dispatch is async). Returns (final batched
    states, host-side metrics per phase, wall seconds)."""
    t0 = time.perf_counter()
    states, out = None, []
    for _ in range(phases):
        if states is None:
            states, metrics = train_fresh()
        else:
            states, metrics = train_resume(reset(states))
        out.append(type(metrics)(*(np.asarray(l) for l in metrics)))
    return states, out, time.perf_counter() - t0


def _write_sim_data(out_root, scen, H, seed, df, phase_no) -> None:
    """One cell-seed-phase artifact in the reference raw_data layout."""
    cell_dir = out_root / scen / f"H={H}" / f"seed={seed}"
    cell_dir.mkdir(parents=True, exist_ok=True)
    df.to_pickle(cell_dir / f"sim_data{phase_no}.pkl")


def _sweep_fused(args, cell_config, cell_done, out_root) -> int:
    """The whole scenario x H x seed matrix as ONE program per phase
    (``sweep --fused``): cells become replicas with traced scenario knobs
    (:mod:`rcmarl_tpu.parallel.matrix`), so the chip batches
    n_cells x n_seeds replicas instead of running cells sequentially."""
    from rcmarl_tpu.parallel.matrix import (
        _check_fusable,
        reset_matrix_for_phase,
        split_matrix_metrics,
        train_matrix,
    )
    from rcmarl_tpu.training.trainer import metrics_to_dataframe

    cells = [
        (scen, H)
        for scen in args.scenarios
        for H in args.H
        if not (args.skip_existing and cell_done(scen, H))
    ]
    for scen, H in set(
        (s, h) for s in args.scenarios for h in args.H
    ) - set(cells):
        print(f"{scen} H={H}: complete on disk, skipping")
    if not cells:
        return 0
    cfgs = [cell_config(scen, H) for scen, H in cells]
    base = cfgs[0]
    n_blocks = args.n_episodes // base.n_ep_fixed

    # Pre-validate fusability (pallas impl, ragged graphs, divergent
    # cells) as an argument error, like cmd_sweep's other validation —
    # WITHOUT wrapping execution, so a genuine runtime ValueError from
    # the training path stays a loud traceback, not a usage message.
    try:
        _check_fusable(base, cfgs)
    except ValueError as e:
        raise SystemExit(f"sweep --fused: {e}")

    states, phase_metrics, dt = _run_phases(
        args.phases,
        train_fresh=lambda: train_matrix(base, cfgs, args.seeds, n_blocks),
        train_resume=lambda st: train_matrix(
            base, cfgs, args.seeds, n_blocks, states=st
        ),
        reset=lambda st: reset_matrix_for_phase(base, st, cfgs, args.seeds),
    )

    # Same guard rail as the sequential sweep's _check_cell_finite, at
    # replica granularity (cell-major layout): never write non-finite
    # results (fault-injection sweeps want --sanitize). Params checked
    # too — a poisoning in the last update block never reaches metrics.
    healthy = _replica_param_health(states)
    unhealthy = set()
    for ph, metrics in enumerate(phase_metrics):
        rows = split_matrix_metrics(metrics, len(cells), len(args.seeds))
        for c, ((scen, H), row) in enumerate(zip(cells, rows)):
            for s, (seed, m) in enumerate(zip(args.seeds, row)):
                ok = healthy[c * len(args.seeds) + s] and all(
                    np.all(np.isfinite(np.asarray(l))) for l in m
                )
                if not ok:
                    unhealthy.add((scen, H, seed))
                    continue
                _write_sim_data(
                    out_root, scen, H, seed,
                    metrics_to_dataframe(m), args.phase + ph,
                )
    total_eps = args.n_episodes * args.phases
    n_rep = len(cells) * len(args.seeds)
    sps = n_rep * total_eps * base.max_ep_len / dt
    print(
        f"fused matrix: {len(cells)} cells x {len(args.seeds)} seeds "
        f"({n_rep} replicas) x {total_eps} eps ({args.phases} phase(s)) "
        f"as one program per phase in {dt:.1f}s "
        f"({sps:.0f} env-steps/s aggregate)"
    )
    if unhealthy:
        print(
            f"sweep --fused: {len(unhealthy)} replica(s) produced "
            "non-finite metrics and were NOT written (diverged or "
            "fault-poisoned params; injected-fault sweeps want "
            "--sanitize): "
            + ", ".join(f"{s} H={h} seed={sd}" for s, h, sd in sorted(unhealthy)),
            file=sys.stderr,
        )
        return 1
    return 0


def cmd_sweep(argv) -> int:
    p = argparse.ArgumentParser(
        prog="rcmarl_tpu sweep",
        description="Run the experiment matrix on-device (replaces the "
        "reference's SGE job arrays)",
    )
    p.add_argument(
        "--scenarios",
        nargs="+",
        default=["coop", "greedy", "faulty", "malicious"],
        help="scenario names; append '_global' for team-average reward",
    )
    p.add_argument(
        "--env",
        type=str,
        default="grid_world",
        choices=list(ENV_NAMES),
        help="environment every cell trains in (the env-zoo registry); "
        "artifacts land in the same raw_data layout, so the "
        "parity/quality pipeline applies per env tree",
    )
    p.add_argument("--H", nargs="+", type=int, default=[0, 1])
    p.add_argument("--seeds", nargs="+", type=int, default=[100, 200, 300])
    p.add_argument("--n_episodes", type=int, default=4000)
    p.add_argument("--max_ep_len", type=int, default=20)
    p.add_argument("--n_ep_fixed", type=int, default=50)
    p.add_argument("--n_epochs", type=int, default=10)
    p.add_argument("--buffer_size", type=int, default=2000)
    p.add_argument("--slow_lr", type=float, default=0.002)
    p.add_argument("--fast_lr", type=float, default=0.01)
    p.add_argument(
        "--eps",
        type=float,
        default=0.1,
        help="exploration mix (snapshot value 0.1; the published artifact "
        "logs record eps: 0.05 from a newer reference revision — see "
        "DRIFT.md)",
    )
    p.add_argument("--out", type=str, default="./simulation_results/raw_data")
    p.add_argument("--phase", type=int, default=1, help="sim_data<phase>.pkl")
    p.add_argument(
        "--phases",
        type=int,
        default=1,
        help="run this many phases of --n_episodes each, with the "
        "reference's restart semantics at each boundary (weights + goal "
        "kept; Adam moments, buffer, and RNG reset — the published runs "
        "are --phases 2 --n_episodes 4000); writes sim_data<k>.pkl per phase",
    )
    p.add_argument(
        "--consensus_impl",
        type=str,
        default="xla",
        choices=list(CONSENSUS_IMPLS),
        help="consensus aggregation backend: xla/pallas = selection-based "
        "trim bounds, *_sort = full-sort comparison arms, auto = measured "
        "3-way crossover (ops/aggregation.py)",
    )
    p.add_argument(
        "--netstack",
        type=str,
        default="auto",
        choices=["auto", "on", "off"],
        help="critic+TR netstack (on: one stacked critic+TR program per "
        "epoch; off: the dual-launch comparison arm; auto, the default: "
        "the measured backend policy — stacked on TPU, dual elsewhere)",
    )
    p.add_argument(
        "--fitstack",
        type=str,
        default="auto",
        choices=["auto", "on", "off", "pallas", "pallas_interpret"],
        help="cross-flavor fused fit scan (on: every same-scheduled "
        "phase-I flavor in one stacked scan; off: the PR-4 per-flavor "
        "arms; auto, the default: fused on TPU, per-flavor elsewhere; "
        "pallas/pallas_interpret: the fit-scan Pallas kernel arms)",
    )
    p.add_argument(
        "--compute_dtype",
        type=str,
        default="float32",
        choices=["float32", "bfloat16"],
        help="matmul compute precision for every cell: float32 = "
        "reference-parity, bfloat16 = MXU-native inputs with f32 "
        "accumulation (scale-out; gate quality against the f32 arm — "
        "QUALITY.md 'Mixed precision')",
    )
    p.add_argument(
        "--skip_existing",
        action="store_true",
        help="skip cells whose sim_data files are all already on disk, so "
        "a crashed or interrupted matrix run can be re-issued verbatim and "
        "only computes what is missing",
    )
    p.add_argument(
        "--fused",
        action="store_true",
        help="run the ENTIRE scenario x H matrix as one sharded program "
        "(cells become replicas with traced roles/H/common_reward — "
        "parallel/matrix.py) instead of one program per cell; requires "
        "consensus_impl xla/auto",
    )
    g = p.add_argument_group("time-varying communication graphs")
    g.add_argument(
        "--graph_schedule",
        type=str,
        default="static",
        choices=list(GRAPH_SCHEDULES),
        help="communication-graph schedule for every cell: static "
        "(default) = the fixed scenario topology, bit-for-bit the seed "
        "behavior; random_geometric = the sparse scheduled exchange "
        "(gather indices as DATA — ops/exchange.py). Scheduled cells "
        "run one host-looped train() per seed (the vmapped seed "
        "program cannot regenerate the per-block resample); "
        "incompatible with --fused",
    )
    g.add_argument(
        "--graph_every",
        type=int,
        default=1,
        help="resample the time-varying graph every K blocks",
    )
    g.add_argument(
        "--graph_degree",
        type=int,
        default=0,
        help="in-degree (incl. self) of the resampled graph; 0 = reuse "
        "the scenario's static n_in (needs 2H <= degree-1)",
    )
    g.add_argument(
        "--graph_seed",
        type=int,
        default=0,
        help="graph-schedule namespace (independent of the training "
        "seeds; resumed runs replay their exact graph sequence)",
    )
    _add_fault_flags(p)
    args = p.parse_args(argv)
    if args.fused and args.graph_schedule != "static":
        raise SystemExit(
            "--fused cannot run a time-varying graph_schedule: the fused "
            "matrix is one device-scanned program and cannot regenerate "
            "the per-block host resample — drop --fused (scheduled "
            "cells run per-seed host loops)"
        )
    if args.n_episodes <= 0 or args.n_episodes % args.n_ep_fixed != 0:
        raise SystemExit(
            f"--n_episodes={args.n_episodes} must be a positive multiple of "
            f"--n_ep_fixed={args.n_ep_fixed}"
        )
    if args.phases < 1:
        raise SystemExit(f"--phases={args.phases} must be >= 1")

    from rcmarl_tpu.parallel.seeds import (
        reset_state_for_phase,
        reset_states_for_phase,
        train_parallel,
    )
    from rcmarl_tpu.training.trainer import metrics_to_dataframe, train

    def cell_config(scen: str, H: int) -> Config:
        labels, is_global = scenario_labels(scen)
        return Config.from_labels(
            labels,
            H=H,
            common_reward=is_global,
            env=args.env,
            n_episodes=args.n_episodes,
            max_ep_len=args.max_ep_len,
            n_ep_fixed=args.n_ep_fixed,
            n_epochs=args.n_epochs,
            buffer_size=args.buffer_size,
            slow_lr=args.slow_lr,
            fast_lr=args.fast_lr,
            eps_explore=args.eps,
            consensus_impl=args.consensus_impl,
            netstack=_netstack_value(args.netstack),
            fitstack=_netstack_value(args.fitstack),
            compute_dtype=args.compute_dtype,
            fault_plan=fault_plan_from_args(args),
            consensus_sanitize=args.sanitize,
            graph_schedule=args.graph_schedule,
            graph_every=args.graph_every,
            graph_degree=args.graph_degree,
            graph_seed=args.graph_seed,
        )

    out_root = Path(args.out)

    def cell_done(scen: str, H: int) -> bool:
        return all(
            (
                out_root / scen / f"H={H}" / f"seed={seed}"
                / f"sim_data{args.phase + ph}.pkl"
            ).exists()
            for seed in args.seeds
            for ph in range(args.phases)
        )

    if args.fused:
        return _sweep_fused(args, cell_config, cell_done, out_root)

    def run_cell_scheduled(cfg: Config, scen: str, H: int) -> None:
        """The time-varying-graph cell: one host-looped solo train() per
        seed (the vmapped seed program cannot regenerate the per-block
        host resample), same restart protocol at phase boundaries, same
        finite guard rail BEFORE any artifact is written, same raw_data
        artifacts."""
        import jax

        t0 = time.perf_counter()
        for seed in args.seeds:
            scfg = cfg.replace(seed=seed)
            state, dfs = None, []
            for _ in range(args.phases):
                if state is not None:
                    state = reset_state_for_phase(scfg, state, seed)
                state, df = train(
                    scfg, n_episodes=args.n_episodes, state=state
                )
                dfs.append(df)
            params_ok = all(
                bool(np.all(np.isfinite(np.asarray(l))))
                for l in jax.tree.leaves(state.params)
                if np.issubdtype(np.asarray(l).dtype, np.floating)
            )
            if not params_ok or not all(
                bool(np.isfinite(df.to_numpy()).all()) for df in dfs
            ):
                raise _CellUnhealthy(
                    f"{scen} H={H} seed={seed}: non-finite params/metrics "
                    "(diverged or fault-poisoned; for fault-injection "
                    "sweeps run with --sanitize)"
                )
            for ph, df in enumerate(dfs):
                _write_sim_data(out_root, scen, H, seed, df, args.phase + ph)
        dt = time.perf_counter() - t0
        total_eps = args.n_episodes * args.phases
        sps = len(args.seeds) * total_eps * cfg.max_ep_len / dt
        print(
            f"{scen} H={H}: {len(args.seeds)} seeds x {total_eps} eps "
            f"({args.phases} phase(s), {cfg.graph_schedule} graph, "
            f"degree {cfg.resolved_graph_degree}) in {dt:.1f}s "
            f"({sps:.0f} env-steps/s aggregate)"
        )

    def run_cell(scen: str, H: int) -> None:
        cfg = cell_config(scen, H)
        if cfg.graph_schedule != "static":
            run_cell_scheduled(cfg, scen, H)
            return
        n_blocks = args.n_episodes // cfg.n_ep_fixed
        # all seeds of a cell run as ONE sharded/vmapped program
        states, phase_metrics, dt = _run_phases(
            args.phases,
            train_fresh=lambda cfg=cfg: train_parallel(
                cfg, seeds=args.seeds, n_blocks=n_blocks
            ),
            train_resume=lambda st, cfg=cfg: train_parallel(
                cfg, states=st, n_blocks=n_blocks
            ),
            reset=lambda st, cfg=cfg: reset_states_for_phase(
                cfg, st, args.seeds
            ),
        )
        _check_cell_finite(states, phase_metrics, f"{scen} H={H}")
        for ph, metrics in enumerate(phase_metrics):
            for i, seed in enumerate(args.seeds):
                _write_sim_data(
                    out_root, scen, H, seed,
                    metrics_to_dataframe(
                        type(metrics)(*(l[i] for l in metrics))
                    ),
                    args.phase + ph,
                )
        total_eps = args.n_episodes * args.phases
        sps = len(args.seeds) * total_eps * cfg.max_ep_len / dt
        print(
            f"{scen} H={H}: {len(args.seeds)} seeds x {total_eps} eps "
            f"({args.phases} phase(s)) in {dt:.1f}s "
            f"({sps:.0f} env-steps/s aggregate)"
        )

    # Per-cell fault isolation (same contract as `bench`/`profile`): one
    # failing cell is retried once, then recorded and skipped, so a
    # crash (OOM, lowering failure, a fault-plan run diverging past the
    # guard) costs that cell — not the rest of the matrix.
    failed = []
    for scen in args.scenarios:
        for H in args.H:
            if args.skip_existing and cell_done(scen, H):
                print(f"{scen} H={H}: complete on disk, skipping")
                continue
            for attempt in (0, 1):
                try:
                    run_cell(scen, H)
                    break
                except _CellUnhealthy as e:
                    # deterministic in the cell's seeds — a retry would
                    # reproduce the same divergence; record and move on
                    failed.append((scen, H, str(e)))
                    print(f"{e} — skipping cell", file=sys.stderr)
                    break
                except Exception as e:  # noqa: BLE001 — cell isolation
                    if attempt == 0:
                        print(
                            f"{scen} H={H}: {type(e).__name__}: "
                            f"{str(e)[:200]} — retrying once",
                            file=sys.stderr,
                        )
                        continue
                    failed.append((scen, H, f"{type(e).__name__}: {e}"))
                    print(
                        f"{scen} H={H}: failed twice, skipping cell "
                        f"({type(e).__name__}: {str(e)[:200]})",
                        file=sys.stderr,
                    )
    if failed:
        print(
            f"sweep: {len(failed)} cell(s) failed: "
            + ", ".join(f"{s} H={h}" for s, h, _ in failed),
            file=sys.stderr,
        )
        # Completed cells' artifacts are already on disk; a nonzero rc
        # tells drivers the matrix is incomplete (re-issue with
        # --skip_existing to compute only the missing cells).
        return 1
    return 0


# --------------------------------------------------------------------------
# bench
# --------------------------------------------------------------------------

#: BASELINE.json's scaling matrix. ``degree`` = non-self in-neighbors on a
#: circulant ring (None = full graph); reference topology is the first row
#: (n_in=4 incl. self, main.py:28).
BENCH_CONFIGS = {
    "ref5_ring": dict(n_agents=5, hidden=(20, 20), degree=3, H=1),
    "n16_ring": dict(n_agents=16, hidden=(20, 20), degree=4, H=1),
    "n16_full": dict(n_agents=16, hidden=(20, 20), degree=None, H=1),
    "n64_ring": dict(n_agents=64, hidden=(20, 20), degree=4, H=1),
    "n64_full": dict(n_agents=64, hidden=(20, 20), degree=None, H=1),
    "n64_large_h2": dict(n_agents=64, hidden=(256, 256, 256), degree=8, H=2),
    # one axis beyond BASELINE.json's matrix: does the batched consensus
    # sort keep scaling past N=64? (16x16 grid, deg-8 ring, H=2)
    "n256_ring": dict(n_agents=256, hidden=(20, 20), degree=8, H=2),
    # a MIXED cast (12 coop + 2 greedy + 2 malicious): the cell where
    # phase I runs every fit flavor, so the fitstack fused-scan A/B and
    # the per-flavor fit_coop/fit_adv micro split have adversary work
    # to attribute (all-coop cells never launch the minibatch flavors)
    "n16_mixed": dict(
        n_agents=16,
        hidden=(20, 20),
        degree=None,
        H=1,
        roles=("Cooperative",) * 12 + ("Greedy",) * 2 + ("Malicious",) * 2,
    ),
    # Mega-population cells (round 18): the static circulant in-degree
    # stays tiny (the compiled anchor topology) while consensus rides
    # the sparse random-geometric schedule as DATA (ops/exchange.py) —
    # past DENSE_DEGREE_LIMIT a dense static graph refuses to construct,
    # so these cells measure the O(n·deg·P) exchange, never the n² one.
    # Since round 19 scheduled cells default to the stacked-schedule
    # scan (one (S, N, deg) window operand, S blocks per launch); the
    # round-18 host-looped train() arm stays available via
    # `--sched_harness host_loop` (or `both` for the A/B). Pair with
    # `--env congestion pursuit` for the env-zoo scale-up rows.
    "n256_sparse": dict(
        n_agents=256, hidden=(16, 16), degree=4, H=2,
        schedule="random_geometric", graph_degree=9, fit_clip=1.0,
    ),
    "n1024_sparse": dict(
        n_agents=1024, hidden=(4,), degree=4, H=2,
        schedule="random_geometric", graph_degree=8, fit_clip=1.0,
    ),
}


def _emit(line: str, out_path: str | None, *, err: bool = False) -> None:
    """Print one JSONL row and append it to the artifact file, if any."""
    print(line, file=sys.stderr if err else sys.stdout)
    if out_path:
        with open(out_path, "a") as f:
            f.write(line + "\n")


def _bench_config(
    name: str,
    impl: str,
    n_ep_fixed: int,
    compute_dtype: str = "float32",
    layout: str = "flat",
    netstack: "bool | str" = "auto",
    fitstack: "bool | str" = "auto",
    env: str = "grid_world",
    graph_schedule: str = "static",
    graph_every: int = 1,
    graph_degree: int = 0,
    graph_seed: int = 0,
) -> Config:
    spec = BENCH_CONFIGS[name]
    n = spec["n_agents"]
    side = max(3, int(round(math.sqrt(n))))  # BASELINE: sqrt(N) x sqrt(N) grid
    if spec["degree"] is None:
        in_nodes = full_in_nodes(n)
    else:
        in_nodes = circulant_in_nodes(n, spec["degree"] + 1)
    roles = tuple(
        Roles.BY_NAME[l] for l in spec.get("roles", ("Cooperative",) * n)
    )
    # Cells carrying their own schedule keys (the mega-population
    # entries) pin them: they ARE the measured sparse arm; the CLI graph
    # axis applies to the historically static cells only.
    if "schedule" in spec:
        graph_schedule = spec["schedule"]
        graph_degree = spec.get("graph_degree", graph_degree)
        graph_every = spec.get("graph_every", graph_every)
    return Config(
        fit_clip=spec.get("fit_clip", 0.0),
        n_agents=n,
        agent_roles=roles,
        in_nodes=in_nodes,
        env=env,
        nrow=side,
        ncol=side,
        hidden=spec["hidden"],
        H=spec["H"],
        n_episodes=n_ep_fixed,
        n_ep_fixed=n_ep_fixed,
        slow_lr=0.002,
        consensus_impl=impl,
        consensus_layout=layout,
        netstack=netstack,
        fitstack=fitstack,
        compute_dtype=compute_dtype,
        graph_schedule=graph_schedule,
        graph_every=graph_every,
        graph_degree=graph_degree,
        graph_seed=graph_seed,
    )


def _netstack_arm_flag(p: argparse.ArgumentParser) -> None:
    """The shared bench/profile netstack A/B arm."""
    p.add_argument(
        "--netstack",
        nargs="+",
        default=["auto"],
        choices=["auto", "on", "off"],
        help="critic+TR netstack arm(s) to compare: on = one stacked "
        "critic+TR program per epoch, off = the historical dual-launch "
        "comparison arm, auto (default) = the measured backend policy "
        "(stacked on TPU, dual elsewhere); pass 'on off' for the A/B. "
        "A per_leaf layout row only exists on the dual arm (netstack "
        "always uses the combined flat block), so stacked+per_leaf "
        "combinations are skipped.",
    )
    p.add_argument(
        "--fitstack",
        nargs="+",
        default=["auto"],
        choices=["auto", "on", "off", "pallas", "pallas_interpret"],
        help="cross-flavor fused fit scan arm(s) to compare: on = every "
        "same-scheduled phase-I flavor in ONE stacked (flavor·net, "
        "agent) scan, off = the PR-4 per-flavor arms, auto (default) = "
        "the measured backend policy (fused on TPU, per-flavor "
        "elsewhere), pallas/pallas_interpret = the fit-scan Pallas "
        "kernel arms (params VMEM-resident across the schedule; "
        "interpret rows are honest headline:false on a CPU host); pass "
        "'on off' for the A/B",
    )


def _bench_pipeline_cell(args, name: str, cfg, depth: int) -> int:
    """One sync-vs-pipelined bench cell (`bench --pipeline_depth ...`):
    ``args.blocks`` training blocks through the host-looped pipelined
    trainer — depth 0 dispatches the fused synchronous block through
    the SAME harness, so the depth-0 row is the honest sync arm of the
    A/B — best-of-``reps`` wall clock, rows carrying the measured
    staleness counters and the combined actor+learner
    ``cost_fingerprint``. Returns 1 on cell failure (the bench
    fault-isolation discipline), else 0."""
    import jax

    from rcmarl_tpu.ops.aggregation import resolve_impl
    from rcmarl_tpu.pipeline.trainer import (
        pipeline_fingerprint,
        train_pipelined,
    )
    from rcmarl_tpu.training.update import fitstack_enabled, netstack_enabled
    from rcmarl_tpu.utils.profiling import Timer, train_block_fingerprint

    pcfg = cfg.replace(
        pipeline_depth=depth, publish_every=args.publish_every
    )
    n_eps = args.blocks * pcfg.n_ep_fixed
    try:
        fingerprint = (
            train_block_fingerprint(pcfg)
            if depth == 0
            else pipeline_fingerprint(pcfg)
        )
        state, df = train_pipelined(pcfg, n_episodes=n_eps)  # compile + warm
        attrs = df.attrs["pipeline"]
        best = float("inf")
        for _ in range(args.reps):
            t = Timer().start()
            state, df = train_pipelined(pcfg, n_episodes=n_eps, state=state)
            best = min(best, t.stop(state.params))
            attrs = df.attrs["pipeline"]
    except Exception as e:  # noqa: BLE001 — bench fault isolation
        _emit(
            json.dumps(
                {
                    "config": name,
                    "pipeline_depth": depth,
                    "publish_every": args.publish_every,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }
            ),
            args.out,
            err=True,
        )
        return 1
    steps = args.blocks * pcfg.block_steps
    row = json.dumps(
        {
            "kind": "pipeline",
            "config": name,
            "env": pcfg.env,
            "impl": pcfg.consensus_impl,
            "impl_resolved": resolve_impl(
                pcfg.consensus_impl, pcfg.n_in,
                n_agents=pcfg.n_agents, H=pcfg.H,
            ),
            "netstack": netstack_enabled(pcfg),
            "fitstack": fitstack_enabled(pcfg),
            "compute_dtype": pcfg.compute_dtype,
            "n_agents": pcfg.n_agents,
            "n_in": pcfg.n_in,
            "hidden": list(pcfg.hidden),
            "H": pcfg.H,
            "pipeline_depth": depth,
            "publish_every": args.publish_every,
            "staleness_mean": round(attrs["staleness_mean"], 3),
            "staleness_max": attrs["staleness_max"],
            "publishes": attrs["publishes"],
            "cost_fingerprint": fingerprint,
            "env_steps_per_sec": round(steps / best, 1),
            "sec_per_block": round(best / args.blocks, 4),
            "workload": {
                "blocks": args.blocks,
                "reps": args.reps,
                "block_steps": pcfg.block_steps,
            },
            "platform": jax.devices()[0].platform,
            # headline discipline: only an on-chip row is a TPU
            # shadow-overlap claim; CPU rows are honest fallbacks
            "headline": jax.devices()[0].platform == "tpu",
            "timestamp": datetime.now().isoformat(timespec="seconds"),
        }
    )
    _emit(row, args.out)
    return 0


def cmd_bench(argv) -> int:
    p = argparse.ArgumentParser(
        prog="rcmarl_tpu bench",
        description="Scaling benchmark over BASELINE.json's config matrix "
        "(agent count, graph density, model size, consensus impl)",
    )
    p.add_argument(
        "--configs",
        nargs="+",
        default=list(BENCH_CONFIGS),
        choices=list(BENCH_CONFIGS),
    )
    p.add_argument(
        "--impl",
        nargs="+",
        default=["xla"],
        choices=list(CONSENSUS_IMPLS),
        help="consensus implementation(s) to compare",
    )
    p.add_argument(
        "--env",
        nargs="+",
        default=["grid_world"],
        choices=list(ENV_NAMES),
        help="environment arm(s) to measure (the env-zoo registry); "
        "every row is tagged with the resolved env name",
    )
    p.add_argument("--n_ep_fixed", type=int, default=10)
    p.add_argument("--blocks", type=int, default=3, help="timed blocks per rep")
    p.add_argument("--reps", type=int, default=3)
    p.add_argument(
        "--layout",
        nargs="+",
        default=["flat"],
        choices=["flat", "per_leaf"],
        help="consensus message-tree layout(s) to compare: flat = one "
        "raveled (n_in, P_total) launch per tree, per_leaf = historical "
        "leaf-by-leaf dispatch (bitwise-identical comparison arm)",
    )
    _netstack_arm_flag(p)
    g = p.add_argument_group("time-varying communication graphs")
    g.add_argument(
        "--graph_schedule",
        nargs="+",
        default=["static"],
        choices=list(GRAPH_SCHEDULES),
        help="graph-schedule arm(s) as a cell axis: static (default) = "
        "the compiled --configs topology, random_geometric = the sparse "
        "scheduled exchange (gather indices as DATA — ops/exchange.py); "
        "pass 'static random_geometric' for the sparse-vs-dense A/B. "
        "Mega cells (n256_sparse/n1024_sparse) pin their own schedule "
        "and ignore this axis' static value",
    )
    g.add_argument(
        "--sched_harness",
        type=str,
        default="scanned",
        choices=["host_loop", "scanned", "both"],
        help="harness for the scheduled cells: scanned (default) = the "
        "stacked-schedule window (config.schedule_window) rides ONE "
        "lax.scan launch per rep — S blocks per dispatch, graphs as "
        "scan data; host_loop = the historical per-block host loop "
        "(resample + validate + one dispatch per block); both = the "
        "host-loop-vs-scanned A/B (PERF.md round 19). Rows are tagged "
        "with sched_harness and the window length so the two arms "
        "sharing a cost_fingerprint stay distinguishable",
    )
    g.add_argument(
        "--graph_every",
        type=int,
        default=1,
        help="resample the time-varying graph every K blocks",
    )
    g.add_argument(
        "--graph_degree",
        type=int,
        default=0,
        help="in-degree (incl. self) of the resampled graph; 0 = reuse "
        "the cell's static n_in (needs 2H <= degree-1)",
    )
    g.add_argument(
        "--graph_seed",
        type=int,
        default=0,
        help="graph-schedule namespace (independent of the training seed)",
    )
    p.add_argument(
        "--shard_agents",
        nargs="+",
        type=int,
        default=None,
        choices=(0, 1),
        help="run on an all-devices ('seed'=1, 'agent'=D) mesh with the "
        "agent axis unsharded (0) and/or sharded (1) — the wall-clock A/B "
        "behind PARALLELISM.md's halo-exchange traffic numbers. Default: "
        "single-device path, no mesh.",
    )
    p.add_argument(
        "--compute_dtype",
        nargs="+",
        default=["float32"],
        choices=["float32", "bfloat16"],
        help="matmul compute precision(s) to compare (bfloat16 = "
        "MXU-native inputs, f32 accumulation)",
    )
    p.add_argument(
        "--pipeline_depth",
        nargs="+",
        type=int,
        default=[0],
        help="async-pipeline arm(s) to compare (rcmarl_tpu.pipeline): "
        "any nonzero depth switches the WHOLE depth list to the "
        "host-looped pipelined harness, so the depth-0 row is the "
        "synchronous fused block measured through the SAME harness "
        "(the honest A/B); rows carry the measured staleness counters "
        "and a combined actor+learner cost_fingerprint. Default [0]: "
        "the historical device-scanned path, untouched",
    )
    p.add_argument(
        "--publish_every",
        type=int,
        default=1,
        help="learner->actor publish cadence for the pipelined arms "
        "(blocks; see rcmarl_tpu.pipeline.publish)",
    )
    p.add_argument(
        "--out",
        type=str,
        default=None,
        help="append each result as a JSON line to this file (so scaling "
        "runs leave a reviewable artifact, not just stdout)",
    )
    args = p.parse_args(argv)
    if args.blocks < 1 or args.reps < 1 or args.n_ep_fixed < 1:
        raise SystemExit("--blocks, --reps, and --n_ep_fixed must be >= 1")
    if any(d < 0 for d in args.pipeline_depth) or args.publish_every < 1:
        raise SystemExit(
            "--pipeline_depth arms must be >= 0 and --publish_every >= 1"
        )

    import jax

    from rcmarl_tpu.ops.aggregation import resolve_impl
    from rcmarl_tpu.parallel.seeds import make_mesh, train_parallel
    from rcmarl_tpu.training.update import fitstack_enabled, netstack_enabled
    from rcmarl_tpu.training.trainer import (
        init_train_state,
        train,
        train_scanned,
    )
    from rcmarl_tpu.utils.profiling import (
        Timer,
        mesh_fingerprint,
        train_block_fingerprint,
    )

    shard_modes = [None] if args.shard_agents is None else args.shard_agents
    # any nonzero depth switches the WHOLE list to the host-looped
    # pipelined harness (the depth-0 row then measures the fused sync
    # block through the same harness — the honest sync-vs-pipelined A/B)
    pipeline_mode = any(d > 0 for d in args.pipeline_depth)
    harness_arms = (
        ["host_loop", "scanned"]
        if args.sched_harness == "both"
        else [args.sched_harness]
    )
    n_failed = 0
    for name, env, dtype, impl, layout, ns, fs, shard, depth, gsched, harn in (
        itertools.product(
            args.configs, args.env, args.compute_dtype, args.impl,
            args.layout, args.netstack, args.fitstack, shard_modes,
            args.pipeline_depth, args.graph_schedule, harness_arms,
        )
    ):
        cfg = _bench_config(
            name, impl, args.n_ep_fixed, dtype, layout,
            netstack=_netstack_value(ns),
            fitstack=_netstack_value(fs),
            env=env,
            graph_schedule=gsched,
            graph_every=args.graph_every,
            graph_degree=args.graph_degree,
            graph_seed=args.graph_seed,
        )
        scheduled = cfg.graph_schedule != "static"
        if not scheduled and harn != harness_arms[0]:
            # the sched_harness axis only exists for scheduled cells;
            # static cells would emit duplicate rows under 'both'
            continue
        if (
            gsched != "static"
            and "schedule" in BENCH_CONFIGS[name]
        ):
            # the mega cells pin their own schedule; running them again
            # under the CLI schedule axis would duplicate the same row
            print(
                f"# skip {name} graph_schedule={gsched}: cell pins its "
                "own schedule spec",
                file=sys.stderr,
            )
            continue
        if netstack_enabled(cfg) and layout == "per_leaf":
            print(
                f"# skip {name} netstack={ns} layout=per_leaf: the "
                "per-leaf layout only exists on the dual-launch arm",
                file=sys.stderr,
            )
            continue
        if scheduled and (pipeline_mode or shard is not None):
            arm = "pipeline_depth" if pipeline_mode else "shard_agents"
            print(
                f"# skip {name} graph_schedule={cfg.graph_schedule} "
                f"{arm}: the device-scanned/pipelined harnesses cannot "
                "regenerate the per-block host resample — scheduled "
                "cells run the host-looped train()",
                file=sys.stderr,
            )
            continue
        if pipeline_mode:
            if shard is not None:
                print(
                    f"# skip {name} pipeline_depth={depth} "
                    "shard_agents: the pipelined harness is the "
                    "single-device host loop (the sharded pipeline "
                    "rides the TPU session)",
                    file=sys.stderr,
                )
                continue
            n_failed += _bench_pipeline_cell(args, name, cfg, depth)
            continue
        fingerprint = None
        if scheduled and harn == "host_loop":
            # the historical scheduled arm: per-block graphs are
            # host-resampled DATA and every block is its own dispatch —
            # wall clock around the whole loop (resample + validate +
            # block dispatch included: the cost the pre-scan scheduled
            # path paid, the round-19 A/B reference)
            from types import SimpleNamespace

            state = None

            def run(s, cfg=cfg):
                st, df = train(
                    cfg, n_episodes=args.blocks * cfg.n_ep_fixed, state=s
                )
                return st, SimpleNamespace(
                    true_team_returns=df["True_team_returns"].to_numpy()
                )
        elif scheduled:
            # the STACKED-SCHEDULE scan: the (S, N, degree) window
            # (config.schedule_window — bitwise the host loop's
            # per-block resample sequence) rides ONE lax.scan launch
            # per rep. The window build stays inside the timed call:
            # that host work is part of what a scanned production run
            # pays, and it is O(S·N·deg) next to the device scan
            from rcmarl_tpu.config import schedule_window

            state = init_train_state(cfg, jax.random.PRNGKey(0))
            scan_jit = jax.jit(
                lambda s, g, cfg=cfg: train_scanned(
                    cfg, s, args.blocks, graphs=g
                )
            )

            def run(s, cfg=cfg, scan_jit=scan_jit):
                start = int(jax.device_get(s.block))
                w = schedule_window(cfg, start, args.blocks)
                return scan_jit(s, w)
        elif shard is None:
            state = init_train_state(cfg, jax.random.PRNGKey(0))
            run = jax.jit(
                lambda s, cfg=cfg: train_scanned(cfg, s, args.blocks)
            )
        else:
            mesh = make_mesh(seed_axis=1)
            if shard and cfg.n_agents % mesh.shape["agent"] != 0:
                print(
                    f"# skip {name} shard_agents=1: {cfg.n_agents} "
                    f"agents do not tile over {mesh.shape['agent']} "
                    "devices",
                    file=sys.stderr,
                )
                continue
            state = None

            def run(s, cfg=cfg, mesh=mesh, shard=shard):
                st, metrics = train_parallel(
                    cfg,
                    seeds=[0] if s is None else None,
                    states=s,
                    n_blocks=args.blocks,
                    mesh=mesh,
                    shard_agents=bool(shard),
                )
                return st, metrics

        try:
            if scheduled:
                # both scheduled harnesses anchor to the steady-state
                # data-graph block program (train_block_fingerprint
                # lowers it WITH the (N, degree) graph operand) — the
                # scan is S dispatches of that same block, so the rows
                # share the fingerprint and differ by sched_harness /
                # window tags
                fingerprint = train_block_fingerprint(cfg)
            elif shard is None:
                # tie the row to the EXACT program being timed (the
                # ledger convention, lint/cost.py): the hash of this
                # lowering is what catches "benched arm A, shipped arm
                # B" drift. Inside the fault-isolation block: a
                # lowering failure is a cell failure, not a matrix one.
                from rcmarl_tpu.utils.profiling import program_fingerprint

                fingerprint = program_fingerprint(run.lower(state))
            state, metrics = run(state)  # compile + warm
            jax.device_get(metrics.true_team_returns)
            best = float("inf")
            for _ in range(args.reps):
                t = Timer().start()
                state, metrics = run(state)
                best = min(best, t.stop(metrics.true_team_returns))
        except Exception as e:  # noqa: BLE001
            # One cell must not cost the rest of the matrix (e.g.
            # a pallas lowering failure on new hardware while the
            # xla rows are still to come). Record it and move on.
            err = json.dumps(
                {
                    "config": name,
                    "env": cfg.env,
                    "impl": impl,
                    "layout": layout,
                    "netstack": netstack_enabled(cfg),
                    "fitstack": fitstack_enabled(cfg),
                    "compute_dtype": dtype,
                    **({} if shard is None else {"shard_agents": bool(shard)}),
                    "error": f"{type(e).__name__}: {e}"[:300],
                }
            )
            _emit(err, args.out, err=True)
            n_failed += 1
            continue
        steps = args.blocks * cfg.block_steps
        resolved = resolve_impl(
            impl, cfg.n_in, n_agents=cfg.n_agents, H=cfg.H
        )
        # headline discipline (BENCH_* convention): only an on-chip row
        # with REAL kernel lowerings is a hardware claim — interpreter
        # arms (consensus or fit-scan) are honest headline:false rows
        # wherever they run
        interp_arm = resolved.endswith("interpret") or (
            cfg.fitstack == "pallas_interpret"
        )
        row = json.dumps(
            {
                "config": name,
                "env": cfg.env,
                "impl": impl,
                "impl_resolved": resolved,
                "headline": (
                    jax.devices()[0].platform == "tpu" and not interp_arm
                ),
                "layout": cfg.consensus_layout,
                "netstack": netstack_enabled(cfg),
                "fitstack": fitstack_enabled(cfg),
                "compute_dtype": cfg.compute_dtype,
                "n_agents": cfg.n_agents,
                "n_in": cfg.n_in,
                "hidden": list(cfg.hidden),
                "H": cfg.H,
                **(
                    {}
                    if not scheduled
                    else {
                        "graph_schedule": cfg.graph_schedule,
                        "graph_degree": cfg.resolved_graph_degree,
                        "graph_every": cfg.graph_every,
                        # host_loop = one dispatch per block (window 1);
                        # scanned = S blocks per lax.scan launch — the
                        # tags that keep the two arms sharing a
                        # cost_fingerprint distinguishable
                        "sched_harness": harn,
                        "window": args.blocks if harn == "scanned" else 1,
                    }
                ),
                **(
                    {}
                    if shard is None
                    else {
                        "shard_agents": bool(shard),
                        "mesh_devices": len(jax.devices()),
                        # ties the row to the mesh it actually executed
                        # on (device count + axis sizes), next to the
                        # program hash — MULTICHIP evidence without it
                        # can't distinguish a 2-chip from a pod mesh
                        "mesh_fingerprint": mesh_fingerprint(mesh),
                    }
                ),
                "cost_fingerprint": fingerprint,
                "env_steps_per_sec": round(steps / best, 1),
                "sec_per_block": round(best / args.blocks, 4),
                "workload": {
                    "blocks": args.blocks,
                    "reps": args.reps,
                    "block_steps": cfg.block_steps,
                },
                "platform": jax.devices()[0].platform,
                "timestamp": datetime.now().isoformat(timespec="seconds"),
            }
        )
        _emit(row, args.out)
    # Completed rows are already flushed; a nonzero rc signals that some
    # cells failed so drivers judging by exit code don't record a clean
    # benchmark over missing measurements.
    return 1 if n_failed else 0


# --------------------------------------------------------------------------
# profile
# --------------------------------------------------------------------------


def cmd_profile(argv) -> int:
    p = argparse.ArgumentParser(
        prog="rcmarl_tpu profile",
        description="Per-phase timing breakdown of the training block "
        "(utils/profiling.py) over BASELINE.json's config matrix — the "
        "regenerable artifact behind PERF.md",
    )
    p.add_argument(
        "--configs",
        nargs="+",
        default=["ref5_ring"],
        choices=list(BENCH_CONFIGS),
    )
    p.add_argument(
        "--impl",
        nargs="+",
        default=["xla"],
        choices=list(CONSENSUS_IMPLS),
    )
    p.add_argument(
        "--env",
        nargs="+",
        default=["grid_world"],
        choices=list(ENV_NAMES),
        help="environment arm(s) to profile (the env-zoo registry)",
    )
    p.add_argument(
        "--compute_dtype",
        nargs="+",
        default=["float32"],
        choices=["float32", "bfloat16"],
        help="matmul compute precision(s) to profile",
    )
    p.add_argument(
        "--layout",
        nargs="+",
        default=["flat"],
        choices=["flat", "per_leaf"],
        help="consensus message-tree layout(s) to profile (flat = one "
        "raveled launch per tree; per_leaf = comparison arm)",
    )
    _netstack_arm_flag(p)
    p.add_argument(
        "--consensus_micro",
        action="store_true",
        help="additionally emit a consensus micro-breakdown row per cell "
        "(gather vs trim-bounds vs clip/mean vs phase-I fits vs the "
        "whole epoch and its epoch_other residual, "
        "utils/profiling.py:profile_consensus) tagged with n_in/H/"
        "gathered volume — the component-level rows crossover refits "
        "(SELECT_MAX_N_IN, PALLAS_CROSSOVER_VOLUME) and the netstack "
        "A/B key on",
    )
    p.add_argument(
        "--window",
        type=int,
        default=1,
        help="stacked-schedule window tag for scheduled configs: the "
        "number of blocks per lax.scan launch the profiled arm "
        "represents (config.schedule_window / train_scanned). 1 "
        "(default) = the host-looped per-block dispatch; >1 tags the "
        "rows as the scanned-window arm, so micro rows from the two "
        "harnesses sharing a cost_fingerprint stay distinguishable "
        "next to graph_every. Ignored on static configs",
    )
    p.add_argument(
        "--serve_micro",
        action="store_true",
        help="emit a SERVING micro-breakdown row per (config, env, "
        "dtype, serve_impl) cell INSTEAD of the training breakdown "
        "(utils/profiling.py:profile_serve): forward vs key-derivation "
        "vs sample vs the whole launch as the resolved --serve_impl "
        "arm runs it, plus queue_wait from a short seeded replay at "
        "half capacity — each row tagged with the active arm's "
        "cost_fingerprint. Under the fused arm the per-stage keys are "
        "an honest 0.0 (the stages run in-register inside ONE kernel)",
    )
    p.add_argument(
        "--serve_impl",
        nargs="+",
        default=["auto"],
        choices=["auto", "xla", "pallas", "pallas_interpret"],
        help="serving arm(s) to micro-profile (--serve_micro)",
    )
    p.add_argument(
        "--serve_batch",
        type=int,
        default=512,
        help="requests per launch for --serve_micro",
    )
    p.add_argument(
        "--serve_mode",
        type=str,
        default="sample",
        choices=["sample", "greedy"],
        help="serving mode for --serve_micro (greedy zeroes the "
        "key-derivation/sample stages on every arm)",
    )
    p.add_argument("--n_ep_fixed", type=int, default=10)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument(
        "--pipeline_depth",
        type=int,
        default=0,
        help="tag the breakdown rows with an async-pipeline depth "
        "(rcmarl_tpu.pipeline) — the per-phase timings are what the "
        "shadow math reads: rollout_block is the cost depth >= 2 hides "
        "inside ms_epochs_total (the rollout_shadow_fraction field)",
    )
    p.add_argument(
        "--publish_every",
        type=int,
        default=1,
        help="learner->actor publish cadence tag for pipelined rows",
    )
    p.add_argument(
        "--out",
        type=str,
        default=None,
        help="append each breakdown as a JSON line to this file",
    )
    args = p.parse_args(argv)
    if args.reps < 1 or args.n_ep_fixed < 1:
        raise SystemExit("--reps and --n_ep_fixed must be >= 1")
    if args.pipeline_depth < 0 or args.publish_every < 1:
        raise SystemExit(
            "--pipeline_depth must be >= 0 and --publish_every >= 1"
        )
    if args.window < 1:
        raise SystemExit("--window must be >= 1")

    import jax

    from rcmarl_tpu.ops.aggregation import resolve_impl
    from rcmarl_tpu.training.update import fitstack_enabled, netstack_enabled
    from rcmarl_tpu.utils.profiling import (
        consensus_tags,
        profile_consensus,
        profile_phases,
        train_block_fingerprint,
    )

    if args.serve_micro:
        import jax.numpy as jnp

        from rcmarl_tpu.ops.pallas_serve import (
            fused_serve_block,
            resolve_serve_impl,
        )
        from rcmarl_tpu.serve.engine import serve_block, stack_actor_rows
        from rcmarl_tpu.training.trainer import init_train_state
        from rcmarl_tpu.utils.profiling import (
            profile_serve,
            program_fingerprint,
            serve_tags,
        )

        if args.serve_batch < 1:
            raise SystemExit("--serve_batch must be >= 1")
        n_failed = 0
        for name, env, dtype, impl in itertools.product(
            args.configs, args.env, args.compute_dtype, args.serve_impl
        ):
            cfg = _bench_config(name, "xla", args.n_ep_fixed, dtype, env=env)
            try:
                resolved = resolve_serve_impl(impl)
                block = stack_actor_rows(
                    init_train_state(cfg, jax.random.PRNGKey(cfg.seed)).params,
                    cfg,
                )
                # fingerprint the ACTIVE arm on the exact shapes the
                # micro rows time (the ledger convention: a row cites
                # the program it measured, never a stand-in)
                obs = jnp.zeros(
                    (args.serve_batch, cfg.n_agents, cfg.obs_dim),
                    jnp.float32,
                )
                skey = jax.random.PRNGKey(0)
                if resolved == "xla":
                    lowered = serve_block.lower(
                        cfg, block, obs, skey, mode=args.serve_mode
                    )
                else:
                    lowered = fused_serve_block.lower(
                        cfg, block, obs, skey, mode=args.serve_mode,
                        interpret=resolved == "pallas_interpret",
                    )
                fingerprint = program_fingerprint(lowered)
                micro = profile_serve(
                    cfg, block,
                    batch=args.serve_batch,
                    mode=args.serve_mode,
                    serve_impl=impl,
                    reps=args.reps,
                )
            except Exception as e:  # noqa: BLE001 — bench fault isolation
                err = json.dumps(
                    {
                        "kind": "serve_micro",
                        "config": name,
                        "env": env,
                        "serve_impl": impl,
                        "compute_dtype": dtype,
                        "error": f"{type(e).__name__}: {e}"[:300],
                    }
                )
                _emit(err, args.out, err=True)
                n_failed += 1
                continue
            row = json.dumps(
                {
                    "kind": "serve_micro",
                    "config": name,
                    "env": cfg.env,
                    "mode": args.serve_mode,
                    "serve_impl": impl,
                    "serve_impl_resolved": resolved,
                    "compute_dtype": cfg.compute_dtype,
                    "cost_fingerprint": fingerprint,
                    **serve_tags(cfg, args.serve_batch, args.serve_mode),
                    "ms": {
                        k: round(v * 1e3, 3) for k, v in micro.items()
                    },
                    "workload": {"reps": args.reps},
                    "platform": jax.devices()[0].platform,
                    "timestamp": datetime.now().isoformat(
                        timespec="seconds"
                    ),
                }
            )
            _emit(row, args.out)
        return 1 if n_failed else 0

    n_failed = 0
    for name, env, dtype, impl, layout, ns, fs in itertools.product(
        args.configs, args.env, args.compute_dtype, args.impl, args.layout,
        args.netstack, args.fitstack,
    ):
        cfg = _bench_config(
            name, impl, args.n_ep_fixed, dtype, layout,
            netstack=_netstack_value(ns),
            fitstack=_netstack_value(fs),
            env=env,
        ).replace(
            pipeline_depth=args.pipeline_depth,
            publish_every=args.publish_every,
        )
        if netstack_enabled(cfg) and layout == "per_leaf":
            print(
                f"# skip {name} netstack={ns} layout=per_leaf: the "
                "per-leaf layout only exists on the dual-launch arm",
                file=sys.stderr,
            )
            continue
        try:
            fingerprint = train_block_fingerprint(cfg)
            phases = profile_phases(cfg, reps=args.reps)
            micro = (
                profile_consensus(cfg, reps=args.reps)
                if args.consensus_micro
                else None
            )
        except Exception as e:  # noqa: BLE001 — same fault isolation as bench
            err = json.dumps(
                {
                    "config": name,
                    "impl": impl,
                    "layout": layout,
                    "netstack": netstack_enabled(cfg),
                    "fitstack": fitstack_enabled(cfg),
                    "compute_dtype": dtype,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }
            )
            _emit(err, args.out, err=True)
            n_failed += 1
            continue
        # The un-fused sub-programs (utils/profiling.py) vs the fused
        # production block. full_block additionally contains the buffer
        # push, so fusion_speedup slightly UNDERSTATES the pure
        # fusion/dispatch savings — a conservative lower bound.
        unfused = (
            phases["rollout_block"]
            + cfg.n_epochs * phases["critic_tr_epoch"]
            + phases["actor_phase"]
        )
        row = json.dumps(
            {
                "config": name,
                "env": cfg.env,
                "impl": impl,
                "impl_resolved": resolve_impl(impl, cfg.n_in, n_agents=cfg.n_agents, H=cfg.H),
                "layout": cfg.consensus_layout,
                "netstack": netstack_enabled(cfg),
                "fitstack": fitstack_enabled(cfg),
                "compute_dtype": cfg.compute_dtype,
                "n_agents": cfg.n_agents,
                "hidden": list(cfg.hidden),
                "H": cfg.H,
                **(
                    {}
                    if cfg.graph_schedule == "static"
                    else {
                        "graph_schedule": cfg.graph_schedule,
                        "graph_degree": cfg.resolved_graph_degree,
                        # the WINDOW schedule tags: graph_every (the
                        # resample cadence) next to the blocks-per-scan
                        # window length — scanned-window rows (window>1)
                        # vs host-looped rows (window=1) share a
                        # cost_fingerprint and differ only here
                        "graph_every": cfg.graph_every,
                        "window": args.window,
                        "sched_harness": (
                            "scanned" if args.window > 1 else "host_loop"
                        ),
                    }
                ),
                "pipeline_depth": cfg.pipeline_depth,
                "publish_every": cfg.publish_every,
                "cost_fingerprint": fingerprint,
                "ms": {k: round(v * 1e3, 3) for k, v in phases.items()},
                "ms_epochs_total": round(
                    cfg.n_epochs * phases["critic_tr_epoch"] * 1e3, 3
                ),
                "ms_unfused_sum": round(unfused * 1e3, 3),
                "fusion_speedup": round(unfused / phases["full_block"], 3),
                # the async-pipeline shadow budget: the rollout cost a
                # depth>=2 pipeline hides inside the epoch run, as a
                # fraction of the epochs it hides in (< 1 means the
                # shadow fully covers it on overlap-capable hardware)
                "rollout_shadow_fraction": round(
                    phases["rollout_block"]
                    / max(cfg.n_epochs * phases["critic_tr_epoch"], 1e-9),
                    4,
                ),
                "workload": {
                    "n_ep_fixed": args.n_ep_fixed,
                    "reps": args.reps,
                    "n_epochs": cfg.n_epochs,
                    "block_steps": cfg.block_steps,
                },
                "platform": jax.devices()[0].platform,
                "timestamp": datetime.now().isoformat(timespec="seconds"),
            }
        )
        _emit(row, args.out)
        if micro is not None:
            mrow = json.dumps(
                {
                    "kind": "consensus_micro",
                    "config": name,
                    "env": cfg.env,
                    "impl": impl,
                    "impl_resolved": resolve_impl(
                        impl, cfg.n_in, n_agents=cfg.n_agents, H=cfg.H
                    ),
                    "layout": cfg.consensus_layout,
                    "netstack": netstack_enabled(cfg),
                    "fitstack": fitstack_enabled(cfg),
                    "compute_dtype": cfg.compute_dtype,
                    "cost_fingerprint": fingerprint,
                    **consensus_tags(cfg),
                    **(
                        {}
                        if cfg.graph_schedule == "static"
                        else {
                            "graph_schedule": cfg.graph_schedule,
                            "graph_degree": cfg.resolved_graph_degree,
                            "graph_every": cfg.graph_every,
                        }
                    ),
                    "ms": {k: round(v * 1e3, 3) for k, v in micro.items()},
                    "platform": jax.devices()[0].platform,
                    "timestamp": datetime.now().isoformat(timespec="seconds"),
                }
            )
            _emit(mrow, args.out)
    return 1 if n_failed else 0


# --------------------------------------------------------------------------
# serve / evaluate
# --------------------------------------------------------------------------


def cmd_serve(argv) -> int:
    p = argparse.ArgumentParser(
        prog="rcmarl_tpu serve",
        description="Serve a trained policy checkpoint: compile-once "
        "batched inference (ONE launch per request batch) with optional "
        "checkpoint hot-swap and guarded degradation — the 'heavy "
        "traffic' benchmark axis, distinct from train steps/sec "
        "(rcmarl_tpu.serve). --fleet serves F checkpoints in ONE "
        "jitted launch with routing as data (per-member bitwise parity "
        "verified); --canary_band gates hot-swaps on the candidate's "
        "frozen-policy return vs the serving incumbent",
    )
    p.add_argument(
        "--checkpoint",
        type=str,
        default="./simulation_results/checkpoint.npz",
        help="trained checkpoint .npz (the checksummed format; a "
        "corrupted primary falls back to <path>.prev)",
    )
    p.add_argument(
        "--fleet",
        nargs="+",
        type=str,
        default=None,
        help="serve a FLEET: the full member checkpoint list (overrides "
        "--checkpoint) — F policy versions/tenants stacked along a "
        "leading fleet axis and served by ONE jitted launch with "
        "per-request round-robin routing as data "
        "(rcmarl_tpu.serve.fleet); per-member probs are verified "
        "BITWISE against solo serving before the timed loop, and each "
        "member hot-swaps/degrades independently under --watch_every",
    )
    p.add_argument(
        "--canary_band",
        type=float,
        default=None,
        help="enable the canary deployment gate in front of hot-swaps "
        "(solo path, needs --watch_every): a candidate whose "
        "frozen-policy return falls below incumbent - band*|incumbent| "
        "is REJECTED and the incumbent keeps serving "
        "(rcmarl_tpu.serve.canary)",
    )
    p.add_argument(
        "--canary_blocks",
        type=int,
        default=1,
        help="eval blocks (n_ep_fixed episodes each) averaged per "
        "canary measurement",
    )
    p.add_argument(
        "--batch",
        type=int,
        default=1024,
        help="requests per launch (B global states; every launch "
        "produces B x n_agents actions)",
    )
    p.add_argument("--steps", type=int, default=50, help="timed launches per rep")
    p.add_argument("--reps", type=int, default=3)
    p.add_argument(
        "--mode",
        type=str,
        default="sample",
        choices=["sample", "greedy"],
        help="serving arm: sample = categorical per (request, agent) "
        "under the fold_in key discipline, greedy = deterministic argmax",
    )
    p.add_argument(
        "--serve_impl",
        type=str,
        default="auto",
        choices=["auto", "xla", "pallas", "pallas_interpret"],
        help="serving program arm (rcmarl_tpu.ops.pallas_serve): xla = "
        "the serve_block launch chain; pallas = the ONE fused "
        "forward+key-derivation+sample kernel; pallas_interpret = the "
        "fused kernel's interpreter arm (CPU CI); auto = pallas on TPU "
        "else xla. A fused arm is verified BITWISE against the XLA "
        "chain (actions AND probs) on the real batch before anything "
        "is timed",
    )
    p.add_argument(
        "--autoscale",
        type=int,
        default=0,
        metavar="SEG_REQUESTS",
        help="additionally replay the SLO autoscaler "
        "(rcmarl_tpu.serve.autoscale) over a seeded 1x->10x->1x "
        "offered-load swing (SEG_REQUESTS Poisson arrivals per "
        "segment) through THIS checkpoint's resolved serving arm, "
        "against the static scale-1 baseline on the same plan; emits a "
        "serve_autoscale row and prints the grep-able summary line "
        "('SLO held' only when every window met the p99 target "
        "shed-free). 0 = off",
    )
    p.add_argument(
        "--slo_ms",
        type=float,
        default=0.0,
        help="p99 SLO for --autoscale, in milliseconds (0 = auto: 4x "
        "the measured per-launch service time of the resolved arm)",
    )
    p.add_argument(
        "--max_scale",
        type=int,
        default=16,
        help="autoscaler fleet-size ceiling (--autoscale)",
    )
    p.add_argument(
        "--eval_seed",
        type=int,
        default=0,
        help="deterministic serve-stream namespace (replaying the same "
        "seed + launch indices replays the exact action stream)",
    )
    p.add_argument(
        "--watch_every",
        type=int,
        default=0,
        help="poll the checkpoint for hot-swap every K launches "
        "(0 = off); corrupted/non-finite candidates are rejected and "
        "the engine keeps serving the last good params",
    )
    p.add_argument(
        "--obs_buffers",
        type=int,
        default=4,
        help="distinct pre-generated observation batches cycled through "
        "the timed loop (keeps the measurement off a single cached input)",
    )
    p.add_argument(
        "--out",
        type=str,
        default=None,
        help="append the serve row as a JSON line to this file "
        "(BENCH_SERVE.jsonl convention)",
    )
    args = p.parse_args(argv)
    if args.batch < 1 or args.steps < 1 or args.reps < 1 or args.obs_buffers < 1:
        raise SystemExit(
            "--batch, --steps, --reps, and --obs_buffers must be >= 1"
        )
    if args.canary_band is not None and args.fleet:
        raise SystemExit(
            "--canary_band gates the SOLO serving path (one incumbent, "
            "one candidate stream); fleet members are independent "
            "deployments — gate each member's publish pipeline instead"
        )
    if args.canary_band is not None and not args.watch_every:
        raise SystemExit(
            "--canary_band needs --watch_every: the gate sits in front "
            "of the hot-swap poll"
        )

    import jax
    import jax.numpy as jnp

    from rcmarl_tpu.envs.api import env_obs, env_reset
    from rcmarl_tpu.ops.pallas_serve import (
        fused_fleet_block,
        fused_serve_block,
    )
    from rcmarl_tpu.serve.engine import ServeEngine, serve_block, serve_keys
    from rcmarl_tpu.serve.fleet import FleetEngine, fleet_block
    from rcmarl_tpu.serve.swap import CheckpointWatcher
    from rcmarl_tpu.training.trainer import make_env
    from rcmarl_tpu.utils.profiling import Timer, program_fingerprint

    if args.fleet:
        engine = FleetEngine(
            args.fleet, mode=args.mode, eval_seed=args.eval_seed,
            serve_impl=args.serve_impl,
        )
        watcher = None  # FleetEngine.poll drives the per-member watchers
    else:
        engine = ServeEngine(
            args.checkpoint, mode=args.mode, eval_seed=args.eval_seed,
            serve_impl=args.serve_impl,
        )
        if args.watch_every and args.canary_band is not None:
            from rcmarl_tpu.serve.canary import CanaryGate, CanaryWatcher
            from rcmarl_tpu.utils.checkpoint import load_checkpoint_with_meta

            inc_state, _, _, _ = load_checkpoint_with_meta(
                engine.checkpoint_path, engine.cfg
            )
            gate = CanaryGate(
                engine.cfg,
                inc_state.desired,
                inc_state.initial,
                band=args.canary_band,
                blocks=args.canary_blocks,
                eval_seed=args.eval_seed,
            )
            # pin the incumbent from the state already in hand — the
            # watcher then skips its own (third) checksummed load of
            # the same file
            gate.set_incumbent(inc_state.params)
            watcher = CanaryWatcher(engine, gate)
        elif args.watch_every:
            watcher = CheckpointWatcher(engine)
        else:
            watcher = None
    cfg = engine.cfg
    env = make_env(cfg)

    def obs_batch(i: int) -> jnp.ndarray:
        """B random global states (env-reset draws, scaled exactly as
        the rollout scales them) broadcast to every agent's view —
        the (B, N, obs_dim) layout serve_block consumes."""
        ks = jax.random.split(jax.random.PRNGKey(args.eval_seed + i), args.batch)
        pos = jax.vmap(lambda k: env_reset(env, k))(ks)  # (B, N, 2)
        flat = jax.vmap(lambda q: env_obs(env, q))(pos).reshape(
            args.batch, -1
        )  # (B, obs_dim)
        return jnp.broadcast_to(
            flat[:, None, :], (args.batch, cfg.n_agents, cfg.obs_dim)
        )

    buffers = [obs_batch(i) for i in range(args.obs_buffers)]
    fleet_fields = {}
    if args.fleet:
        F = engine.n_members
        # distinct per-launch routes, cycled as DATA through the timed
        # loop (a re-route is never a recompile — the retrace-audited
        # fleet contract)
        routes = [
            (jnp.arange(args.batch, dtype=jnp.int32) + r) % F
            for r in range(min(F, 4))
        ]
        # tie the row to the EXACT program being timed (ledger
        # convention): the ACTIVE arm's lowering, not a fixed one
        key0 = serve_keys(args.eval_seed, 0)
        if engine.serve_impl == "xla":
            fingerprint = program_fingerprint(
                fleet_block.lower(
                    cfg, engine.fleet, buffers[0], key0, routes[0],
                    mode=args.mode,
                )
            )
            _, fleet_probs = fleet_block(
                cfg, engine.fleet, buffers[0], key0, routes[0],
                mode=args.mode,
            )
        else:
            interp = engine.serve_impl == "pallas_interpret"
            fingerprint = program_fingerprint(
                fused_fleet_block.lower(
                    cfg, engine.fleet, buffers[0], key0, routes[0],
                    mode=args.mode, interpret=interp,
                )
            )
            # fused-arm gate: the ONE-kernel fleet program must be
            # BITWISE the XLA chain (actions AND probs) on the real
            # batch before anything is timed — the row's parity claim
            # is proven by this run, not assumed
            fused_a, fleet_probs = fused_fleet_block(
                cfg, engine.fleet, buffers[0], key0, routes[0],
                mode=args.mode, interpret=interp,
            )
            ref_a, ref_p = fleet_block(
                cfg, engine.fleet, buffers[0], key0, routes[0],
                mode=args.mode,
            )
            np.testing.assert_array_equal(
                np.asarray(fused_a), np.asarray(ref_a)
            )
            np.testing.assert_array_equal(
                np.asarray(fleet_probs), np.asarray(ref_p)
            )
        # per-member BITWISE parity vs solo serving, verified on the
        # real batch BEFORE anything is timed: the emitted fleet row
        # carries a parity claim the run itself proved (a mismatch is a
        # hard error, so the row can never lie)
        r0 = np.asarray(routes[0])
        for f, member in enumerate(engine.members):
            _, solo_probs = serve_block(
                cfg, member.block, buffers[0], key0, mode=args.mode
            )
            idx = np.nonzero(r0 == f)[0]
            np.testing.assert_array_equal(
                np.asarray(fleet_probs)[idx], np.asarray(solo_probs)[idx]
            )
        fleet_fields = {
            "fleet": F,
            "fleet_members": [str(p) for p in args.fleet],
            "member_parity": "bitwise",
            "route": "round_robin(rotating)",
        }
        if engine.serve_impl != "xla":
            fleet_fields["fused_parity"] = "bitwise"

        def launch(s: int):
            return engine.serve(
                buffers[s % len(buffers)], route=routes[s % len(routes)]
            )

        poll = engine.poll if args.watch_every else None
    else:
        # tie the row to the EXACT program being timed (ledger
        # convention): the ACTIVE arm's lowering, not a fixed one
        key0 = serve_keys(args.eval_seed, 0)
        if engine.serve_impl == "xla":
            fingerprint = program_fingerprint(
                serve_block.lower(
                    cfg, engine.block, buffers[0], key0, mode=args.mode
                )
            )
        else:
            interp = engine.serve_impl == "pallas_interpret"
            fingerprint = program_fingerprint(
                fused_serve_block.lower(
                    cfg, engine.block, buffers[0], key0,
                    mode=args.mode, interpret=interp,
                )
            )
            # fused-arm gate: the ONE-kernel program must be BITWISE
            # the XLA serve_block chain (actions AND probs) on the real
            # batch before anything is timed — the row's parity claim
            # is proven by this run, not assumed
            fused_a, fused_p = fused_serve_block(
                cfg, engine.block, buffers[0], key0,
                mode=args.mode, interpret=interp,
            )
            ref_a, ref_p = serve_block(
                cfg, engine.block, buffers[0], key0, mode=args.mode
            )
            np.testing.assert_array_equal(
                np.asarray(fused_a), np.asarray(ref_a)
            )
            np.testing.assert_array_equal(
                np.asarray(fused_p), np.asarray(ref_p)
            )
            fleet_fields["fused_parity"] = "bitwise"

        def launch(s: int):
            return engine.serve(buffers[s % len(buffers)])

        poll = watcher.poll if watcher is not None else None
    # ONE timing discipline for both arms: warmup (compile + one
    # execution), then best-of-reps over the steps loop with the
    # hot-swap poll riding the same cadence
    jax.device_get(launch(0)[0])
    best = float("inf")
    for _ in range(args.reps):
        t = Timer().start()
        actions = None
        for s in range(args.steps):
            actions, _ = launch(s)
            if poll is not None and (s + 1) % args.watch_every == 0:
                poll()
        best = min(best, t.stop(actions))
    actions_per_launch = args.batch * cfg.n_agents
    canary_fields = {}
    if args.canary_band is not None:
        canary_fields = {
            "canary": {
                "band": args.canary_band,
                "blocks": args.canary_blocks,
                **watcher.gate.counters,
                "incumbent_return": watcher.gate.incumbent_return,
                "last": watcher.gate.last,
            }
        }
    row = json.dumps(
        {
            "kind": "serve",
            "checkpoint": (
                str(args.fleet[0]) if args.fleet else str(args.checkpoint)
            ),
            "env": cfg.env,
            "mode": args.mode,
            "serve_impl": engine.serve_impl,
            "n_agents": cfg.n_agents,
            "hidden": list(cfg.hidden),
            "compute_dtype": cfg.compute_dtype,
            "batch": args.batch,
            "actions_per_sec": round(args.steps * actions_per_launch / best, 1),
            "launches_per_sec": round(args.steps / best, 2),
            "sec_per_launch": round(best / args.steps, 6),
            "cost_fingerprint": fingerprint,
            "degradation": engine.summary(),
            **fleet_fields,
            **canary_fields,
            "workload": {
                "steps": args.steps,
                "reps": args.reps,
                "obs_buffers": args.obs_buffers,
                "watch_every": args.watch_every,
            },
            "platform": jax.devices()[0].platform,
            # headline discipline (bench.py): only an on-chip row is a
            # TPU serving claim; CPU rows are honest fallbacks
            "headline": jax.devices()[0].platform == "tpu",
            "timestamp": datetime.now().isoformat(timespec="seconds"),
        }
    )
    _emit(row, args.out)
    print(engine.summary_line())
    if args.canary_band is not None:
        print(watcher.gate.summary_line())
    if args.autoscale:
        from rcmarl_tpu.serve.autoscale import (
            SLOController,
            autoscale_replay,
            swing_arrivals,
        )
        from rcmarl_tpu.serve.autoscale import summary_line as autoscale_line
        from rcmarl_tpu.serve.load import serve_service_fn

        block0 = engine.members[0].block if args.fleet else engine.block
        service = serve_service_fn(
            cfg, block0, args.batch, mode=args.mode,
            seed=args.eval_seed, serve_impl=engine.serve_impl,
        )
        per_launch = best / args.steps  # the timed loop already measured it
        slo = (args.slo_ms / 1e3) if args.slo_ms > 0 else 4.0 * per_launch
        # base = HALF one member's batch capacity: the swing's 10x peak
        # then offers 5x a static member's capacity — the plan where
        # the autoscaled fleet must hold the SLO while the static
        # baseline saturates
        base_rate = 0.5 * args.batch / per_launch
        arrivals = swing_arrivals(args.eval_seed, base_rate, args.autoscale)
        window = (float(arrivals[-1]) - float(arrivals[0])) / 40.0
        replay_kw = dict(
            window=window,
            max_batch=args.batch,
            max_wait=2.0 * per_launch,
            # the deadline IS the SLO: shed only what would already
            # miss it — on BOTH arms, so the shed comparison is honest
            shed_after=slo,
            slo_p99=slo,
        )
        auto = autoscale_replay(
            service, arrivals,
            SLOController(slo_p99=slo, max_scale=args.max_scale),
            **replay_kw,
        )
        static = autoscale_replay(service, arrivals, None, **replay_kw)

        def _peak_ms(res):
            v = max((w["p99"] for w in res["windows"]), default=float("nan"))
            return round(v * 1e3, 3) if math.isfinite(v) else None

        arow = json.dumps(
            {
                "kind": "serve_autoscale",
                "checkpoint": (
                    str(args.fleet[0]) if args.fleet else str(args.checkpoint)
                ),
                "env": cfg.env,
                "mode": args.mode,
                "serve_impl": engine.serve_impl,
                "batch": args.batch,
                "slo_ms": round(slo * 1e3, 4),
                "base_rate": round(base_rate, 1),
                "seg_requests": args.autoscale,
                "window_ms": round(window * 1e3, 3),
                "max_scale": args.max_scale,
                "autoscaled": {
                    "slo_held": auto["slo_held"],
                    "max_scale_used": auto["max_scale_used"],
                    "final_scale": auto["final_scale"],
                    "resizes": len(auto["resizes"]),
                    "windows": len(auto["windows"]),
                    "requests": auto["requests"],
                    "shed": auto["shed"],
                    "shed_fraction": round(
                        auto["shed"] / max(1, auto["requests"]), 4
                    ),
                    "peak_p99_ms": _peak_ms(auto),
                },
                "static": {
                    "scale": 1,
                    "slo_held": static["slo_held"],
                    "shed": static["shed"],
                    "shed_fraction": round(
                        static["shed"] / max(1, static["requests"]), 4
                    ),
                    "peak_p99_ms": _peak_ms(static),
                },
                "cost_fingerprint": fingerprint,
                "platform": jax.devices()[0].platform,
                "headline": jax.devices()[0].platform == "tpu",
                "timestamp": datetime.now().isoformat(timespec="seconds"),
            }
        )
        _emit(arow, args.out)
        print(autoscale_line(auto))
    return 0


def cmd_evaluate(argv) -> int:
    p = argparse.ArgumentParser(
        prog="rcmarl_tpu evaluate",
        description="Roll a trained policy checkpoint through its env "
        "(frozen params, no updates): team/adversary returns + "
        "per-agent discounted-return stats as JSONL "
        "(rcmarl_tpu.serve.engine.eval_block)",
    )
    p.add_argument(
        "--checkpoint",
        type=str,
        default="./simulation_results/checkpoint.npz",
        help="trained checkpoint .npz (solo layout; replica worlds are "
        "rejected loudly)",
    )
    p.add_argument(
        "--episodes",
        type=int,
        default=100,
        help="evaluation episodes (rounded up to whole n_ep_fixed "
        "blocks — each block is ONE compiled launch)",
    )
    p.add_argument(
        "--eps",
        type=float,
        default=0.0,
        help="exploration mix during evaluation (default 0: pure "
        "policy, unlike training's 0.1)",
    )
    p.add_argument("--seed", type=int, default=0, help="evaluation RNG namespace")
    p.add_argument(
        "--out",
        type=str,
        default=None,
        help="append the evaluation row as a JSON line to this file",
    )
    args = p.parse_args(argv)
    if args.episodes < 1:
        raise SystemExit("--episodes must be >= 1")

    import jax
    import jax.numpy as jnp

    from rcmarl_tpu.serve.engine import eval_block
    from rcmarl_tpu.utils.checkpoint import load_checkpoint_with_meta
    from rcmarl_tpu.utils.profiling import Timer, program_fingerprint

    state, cfg, loaded, meta = load_checkpoint_with_meta(args.checkpoint)
    if int(meta.get("replicas", 0)):
        raise SystemExit(
            f"--checkpoint: {loaded} holds a replica gossip world; "
            "evaluate expects a solo policy checkpoint"
        )
    if loaded != Path(args.checkpoint):
        print(
            f"WARNING: {args.checkpoint} is corrupted; evaluating the "
            f"previous good checkpoint {loaded}"
        )
    cfg = cfg.replace(eps_explore=args.eps)
    n_blocks = -(-args.episodes // cfg.n_ep_fixed)  # ceil
    key = jax.random.PRNGKey(args.seed)
    fingerprint = program_fingerprint(
        eval_block.lower(
            cfg, state.params, state.desired, key, state.initial
        )
    )
    team, adv, est, per_agent = [], [], [], []
    t = Timer().start()
    out = None
    for b in range(n_blocks):
        metrics, agent_returns = out = eval_block(
            cfg,
            state.params,
            state.desired,
            jax.random.fold_in(key, b),
            state.initial,
        )
        team.append(metrics.true_team_returns)
        adv.append(metrics.true_adv_returns)
        est.append(metrics.est_team_returns)
        per_agent.append(agent_returns)
    dt = t.stop(out)
    team = np.concatenate([np.asarray(x) for x in team])
    adv = np.concatenate([np.asarray(x) for x in adv])
    est = np.concatenate([np.asarray(x) for x in est])
    per_agent = np.mean(np.stack([np.asarray(x) for x in per_agent]), axis=0)
    episodes = n_blocks * cfg.n_ep_fixed
    row = json.dumps(
        {
            "kind": "evaluate",
            "checkpoint": str(args.checkpoint),
            "env": cfg.env,
            "episodes": int(episodes),
            "eps_explore": args.eps,
            "seed": args.seed,
            "n_agents": cfg.n_agents,
            "team_return_mean": round(float(team.mean()), 6),
            "team_return_std": round(float(team.std()), 6),
            "adv_return_mean": round(float(adv.mean()), 6),
            "est_return_mean": round(float(est.mean()), 6),
            "per_agent_returns": [round(float(v), 6) for v in per_agent],
            "episodes_per_sec": round(episodes / dt, 2),
            "cost_fingerprint": fingerprint,
            "platform": jax.devices()[0].platform,
            "timestamp": datetime.now().isoformat(timespec="seconds"),
        }
    )
    _emit(row, args.out)
    print(
        f"evaluate: {episodes} episodes, team return "
        f"{float(team.mean()):.4f} ± {float(team.std()):.4f} "
        f"({episodes / dt:.1f} eps/s)"
    )
    return 0


# --------------------------------------------------------------------------
# lint
# --------------------------------------------------------------------------


def cmd_lint(argv) -> int:
    p = argparse.ArgumentParser(
        prog="rcmarl_tpu lint",
        description="graftlint: static analysis + compiled-artifact "
        "audits enforcing the framework's bitwise-reproducibility and "
        "compile-once contracts (rcmarl_tpu.lint). The AST passes run "
        "by default; the runtime audits are opt-in flags. Exit 0 = "
        "zero findings.",
    )
    p.add_argument(
        "--root",
        type=str,
        default=None,
        help="source tree to lint (default: the installed rcmarl_tpu "
        "package)",
    )
    p.add_argument(
        "--retrace",
        action="store_true",
        help="also run the retrace auditor: tiny guarded+faulted train "
        "runs on both netstack arms plus a clean donated run; every "
        "jitted entry point must compile exactly once after warmup "
        "(rcmarl_tpu.lint.retrace)",
    )
    p.add_argument(
        "--donation",
        action="store_true",
        help="also audit the compiled donated entry points: declared "
        "donate_argnums must survive to input_output_alias metadata in "
        "the executable (rcmarl_tpu.lint.donation)",
    )
    p.add_argument(
        "--backends",
        action="store_true",
        help="also audit the jaxprs of all six aggregation backends "
        "(x sanitize) and both netstack epoch arms for forbidden "
        "primitives and dtype/weak-type drift (rcmarl_tpu.lint.backends)",
    )
    p.add_argument(
        "--cost",
        action="store_true",
        help="also run the compiled-cost gate: lower+compile every "
        "jitted entry point (both netstack arms, donated + guarded "
        "variants, all six aggregation-backend modes) and fail when "
        "XLA's cost/memory analysis grew beyond --cost_tol vs the "
        "--baseline ledger (rcmarl_tpu.lint.cost)",
    )
    p.add_argument(
        "--collectives",
        action="store_true",
        help="also run the HLO collective census of the seed×agent "
        "sharded programs: zero collectives on the seed-only program, "
        "the enumerated bounded set + ledger-exact counts when the "
        "agent axis is sharded, and no host transfer anywhere "
        "(rcmarl_tpu.lint.collectives)",
    )
    p.add_argument(
        "--sharding",
        action="store_true",
        help="also run the sharding arm over the seed×agent programs "
        "and the sharded gossip mix at mesh sizes {1,2,8}: big-operand "
        "sharding annotations audited off the compiled SPMD modules "
        "(sharding-replicated / sharding-reshard-chain), per-device "
        "memory_analysis() gated vs the AUDIT.jsonl device_memory rows "
        "and required to SHRINK with mesh size "
        "(device-memory-regression), and the determinism census over "
        "entry-point lowerings + all six aggregation backends + the "
        "compiled sharded modules (nondeterminism) "
        "(rcmarl_tpu.lint.sharding)",
    )
    p.add_argument(
        "--contract",
        action="store_true",
        help="also run the Config⇄CLI⇄docs contract pass: every Config "
        "field reachable from a CLI flag (or exempted with a reason), "
        "surviving the checkpoint-header JSON round-trip, and present "
        "in the docs/api.md table — contract-drift with the field's "
        "config.py line (rcmarl_tpu.lint.contract)",
    )
    p.add_argument(
        "--kernels",
        action="store_true",
        help="also run the static kernel-budget audit: derive every "
        "Pallas kernel_plan()'s per-grid-step VMEM/SMEM residency, "
        "tile packing, and DMA traffic across the tiny-lint + bench + "
        "tpu_session.sh shape matrix, re-derive the committed "
        "*_dma_bytes closed forms, and gate the kernel_budget rows vs "
        "--baseline — pure shape arithmetic, no backend "
        "(rcmarl_tpu.lint.kernels)",
    )
    p.add_argument(
        "--tpu_gen",
        type=str,
        default=None,
        choices=sorted(("v4", "v5e", "v5p")),
        help="TPU generation whose VMEM/SMEM budget table the --kernels "
        "arm enforces (default: v4, the strictest — a plan that fits "
        "there fits everywhere; the ledger records verdicts for every "
        "generation regardless)",
    )
    p.add_argument(
        "--feasibility",
        action="store_true",
        help="print the per-session-step kernel feasibility verdicts "
        "('step:<tag> kernel=... shape=... verdict=...') at --tpu_gen "
        "and exit 0 — the scripts/tpu_session.sh preflight feed "
        "(implies --kernels; verdicts only, no baseline gate)",
    )
    p.add_argument(
        "--baseline",
        type=str,
        default="AUDIT.jsonl",
        help="the committed cost/collective/device-memory/kernel-budget "
        "ledger the --cost/--collectives/--sharding/--kernels gates "
        "compare against (default: ./AUDIT.jsonl); "
        "on gate failure the fresh ledger is written to <baseline>.new "
        "so the diff is one click away",
    )
    p.add_argument(
        "--write_baseline",
        action="store_true",
        help="regenerate the requested --cost/--collectives rows and "
        "write them to --baseline (rows of kinds not being regenerated "
        "are kept) instead of gating — the ledger-update step of a "
        "legitimate perf PR; unconditional invariants (host transfers, "
        "out-of-set collectives) still fail",
    )
    p.add_argument(
        "--cost_tol",
        type=float,
        default=None,
        help="relative growth tolerance for the --cost gate (default: "
        "rcmarl_tpu.lint.cost.COST_TOLERANCE = 0.01)",
    )
    p.add_argument(
        "--all",
        action="store_true",
        help="shorthand for --retrace --donation --backends --cost "
        "--collectives --sharding --contract --kernels",
    )
    p.add_argument(
        "--rules",
        action="store_true",
        help="print the rule-id table and the pragma escape syntax, "
        "then exit",
    )
    args = p.parse_args(argv)

    from rcmarl_tpu.lint import (
        AUDIT_RULES,
        SOURCE_RULES,
        run_source_lint,
    )

    if args.rules:
        print("AST rules (escape: '# lint: disable=<rule>' on the line,")
        print("or '# lint: disable-file=<rule>' in the first 10 lines):")
        for r in SOURCE_RULES:
            print(f"  {r}")
        print("runtime-audit rules (no pragma escape):")
        for r in AUDIT_RULES:
            print(f"  {r}")
        return 0

    if args.feasibility:
        # the session preflight feed: machine-readable verdicts only,
        # always exit 0 — the script gates on the verdict text, and a
        # broken preflight must not silently veto a whole session
        from rcmarl_tpu.lint.cost import COST_TOLERANCE
        from rcmarl_tpu.lint.kernels import feasibility_lines

        tol = COST_TOLERANCE if args.cost_tol is None else args.cost_tol
        for line in feasibility_lines(args.tpu_gen, tol):
            print(line)
        return 0

    any_audit = (
        args.retrace or args.donation or args.backends or args.cost
        or args.collectives or args.sharding or args.contract
        or args.kernels or args.all
    )
    if args.collectives or args.sharding or args.all:
        # The collective census needs a multi-device mesh. Mirror
        # tests/conftest.py: force a virtual 8-device host platform.
        # XLA reads this at BACKEND INIT, not jax import, so setting it
        # here (before the first audit touches a device) still works
        # under main()'s eager _honor_platform_env import; if a backend
        # was somehow already initialized, the census notes the entries
        # it cannot measure instead of passing them. No-op on real TPU
        # backends.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    findings = run_source_lint(args.root)
    if findings and any_audit:
        # fail fast: the runtime audits cost minutes of tiny training
        # runs and compiles, and the exit status is already decided
        for f in findings:
            print(f)
        print(
            f"lint: {len(findings)} source finding(s); runtime audits "
            "skipped (fix the source findings first)",
            file=sys.stderr,
        )
        return 1
    n_sections = 1
    notes = []
    if args.retrace or args.all:
        from rcmarl_tpu.lint.retrace import audit_retrace

        findings += audit_retrace()
        n_sections += 1
    if args.donation or args.all:
        from rcmarl_tpu.lint.donation import audit_donation

        f, nts = audit_donation()
        findings += f
        notes += nts
        n_sections += 1
    if args.backends or args.all:
        from rcmarl_tpu.lint.backends import audit_backends

        findings += audit_backends()
        n_sections += 1
    fresh_rows = []
    skipped_entries = set()
    gate_findings = 0
    if args.cost or args.all:
        from rcmarl_tpu.lint.cost import COST_TOLERANCE, audit_cost, cost_rows

        tol = COST_TOLERANCE if args.cost_tol is None else args.cost_tol
        if args.write_baseline:
            rows, nts, skipped = cost_rows()
            fresh_rows += rows
            skipped_entries |= skipped
        else:
            f, nts, rows = audit_cost(args.baseline, tol)
            findings += f
            gate_findings += len(f)
            fresh_rows += rows
        notes += nts
        n_sections += 1
    if args.collectives or args.all:
        from rcmarl_tpu.lint.collectives import audit_collectives, census_rows

        if args.write_baseline:
            # invariants (host transfers, out-of-set kinds) still enforced
            rows, f, nts, skipped = census_rows()
            findings += f
            fresh_rows += rows
            skipped_entries |= skipped
        else:
            f, nts, rows = audit_collectives(args.baseline)
            findings += f
            gate_findings += len(f)
            fresh_rows += rows
        notes += nts
        n_sections += 1
    if args.sharding or args.all:
        from rcmarl_tpu.lint.sharding import (
            audit_determinism,
            audit_sharding,
            sharding_rows,
        )

        if args.write_baseline:
            # the shrink/replication/reshard invariants still enforced
            rows, f, nts, skipped = sharding_rows()
            findings += f
            fresh_rows += rows
            skipped_entries |= skipped
        else:
            f, nts, rows = audit_sharding(args.baseline, args.cost_tol)
            findings += f
            gate_findings += len(f)
            fresh_rows += rows
        notes += nts
        df, dnts = audit_determinism()
        findings += df
        notes += dnts
        n_sections += 1
    if args.contract or args.all:
        from rcmarl_tpu.lint.contract import audit_contract

        f, nts = audit_contract()
        findings += f
        notes += nts
        n_sections += 1
    if args.kernels or args.all:
        from rcmarl_tpu.lint.cost import COST_TOLERANCE
        from rcmarl_tpu.lint.kernels import audit_kernels, kernel_rows

        tol = COST_TOLERANCE if args.cost_tol is None else args.cost_tol
        if args.write_baseline:
            # invariants (tile packing, model drift, must-fit budget
            # busts) still enforced while regenerating
            rows, f, nts, skipped = kernel_rows(args.tpu_gen, tol)
            findings += f
            fresh_rows += rows
            skipped_entries |= skipped
        else:
            f, nts, rows = audit_kernels(args.baseline, tol, args.tpu_gen)
            findings += f
            gate_findings += len(f)
            fresh_rows += rows
        notes += nts
        n_sections += 1
    if args.write_baseline and fresh_rows:
        from rcmarl_tpu.lint.cost import read_ledger, write_ledger

        regenerated = {r["kind"] for r in fresh_rows}
        # rows of regenerated kinds are replaced — EXCEPT entries this
        # host could not measure (noted as skipped, e.g. a real Pallas
        # backend on CPU or a too-small census mesh): their rows from a
        # platform that COULD measure them stay in the ledger, matching
        # the skipped-is-not-stale exemption in the comparison
        kept = [
            r
            for r in read_ledger(args.baseline)
            if r.get("kind") not in regenerated
            or r.get("entry") in skipped_entries
        ]
        write_ledger(args.baseline, kept + fresh_rows)
        print(
            f"wrote {len(fresh_rows)} fresh + {len(kept)} kept row(s) "
            f"to {args.baseline}"
        )
    elif gate_findings and fresh_rows:
        from rcmarl_tpu.lint.cost import write_ledger

        write_ledger(f"{args.baseline}.new", fresh_rows)
        print(
            f"# fresh ledger written to {args.baseline}.new — diff it "
            f"against {args.baseline}; if the cost change is "
            "intentional, regenerate with --write_baseline and commit",
            file=sys.stderr,
        )
    for note in notes:
        print(f"# note: {note}", file=sys.stderr)
    for f in findings:
        print(f)
    if findings:
        print(f"lint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print(f"lint: OK ({n_sections} layer(s) clean)")
    return 0


# --------------------------------------------------------------------------
# chaos
# --------------------------------------------------------------------------


def cmd_chaos(argv) -> int:
    p = argparse.ArgumentParser(
        prog="rcmarl_tpu chaos",
        description="Chaos campaign: sweep the fault-surface registry "
        "(rcmarl_tpu.chaos) as short real runs and gate the committed "
        "RESILIENCE.jsonl ledger — a cell that previously survived and "
        "now fails, or whose degradation envelope widened past "
        "tolerance, is a finding (exit 1). The AUDIT.jsonl discipline "
        "applied to resilience.",
    )
    p.add_argument(
        "--check",
        action="store_true",
        help="run the campaign (or the --cells subset) and compare "
        "against --baseline: outcome regressions (survived->degraded/"
        "failed, degraded->failed), widened degradation envelopes, "
        "unbaselined registry cells, and stale committed rows are "
        "findings; improvements and skipped-on-this-host cells are "
        "notes (cost-arm discipline). On failure the fresh rows land "
        "in <baseline>.new",
    )
    p.add_argument(
        "--run",
        action="store_true",
        help="regenerate the ledger: run the campaign (or the --cells "
        "subset, merged over the kept rows) and write --baseline — the "
        "ledger-update step of a legitimate resilience PR (commit it "
        "in the same PR)",
    )
    p.add_argument(
        "--list",
        action="store_true",
        help="print the fault-surface registry (point, subsystem, "
        "cells, guard, test pin) and exit",
    )
    p.add_argument(
        "--cells",
        nargs="+",
        default=None,
        metavar="POINT[@INTENSITY]",
        help="restrict to these cells (e.g. 'link_nan@0.5 ckpt_bitflip' "
        "— a bare point name selects all its intensities); a subset "
        "--check judges only what it ran",
    )
    p.add_argument(
        "--baseline",
        type=str,
        default="RESILIENCE.jsonl",
        help="the committed resilience ledger (default ./RESILIENCE.jsonl)",
    )
    args = p.parse_args(argv)
    if sum((args.check, args.run, args.list)) != 1:
        raise SystemExit(
            "chaos: pass exactly one of --check / --run / --list"
        )

    from rcmarl_tpu.chaos.registry import CHAOS_POINTS

    if args.list:
        for pt in CHAOS_POINTS:
            cells = ", ".join(
                f"{label}->{exp}" for label, exp in pt.cells
            )
            print(f"{pt.name} [{pt.subsystem}] — {pt.description}")
            print(f"    injector: {pt.injector}")
            print(f"    guard:    {pt.guard}")
            print(f"    pinned:   {pt.test_pin}")
            print(f"    cells:    {cells}")
        return 0

    from rcmarl_tpu.chaos.campaign import (
        check_campaign,
        read_resilience,
        run_campaign,
        write_resilience,
    )

    if args.run:
        from rcmarl_tpu.chaos.registry import registry_cells

        rows, notes = run_campaign(args.cells)
        ran = {(r["point"], r["intensity"]) for r in rows}
        known = set(registry_cells())
        # kept rows: cells outside a --cells subset AND cells this host
        # skipped — a partial regenerate (or a host that cannot run a
        # cell) must not silently drop measured rows. Rows naming NO
        # registry cell are dropped here: they are what the check
        # reports chaos-stale for, and --run is its documented remedy
        kept = [
            r
            for r in read_resilience(args.baseline)
            if (r["point"], r["intensity"]) not in ran
            and (r["point"], r["intensity"]) in known
        ]
        write_resilience(args.baseline, kept + rows)
        for note in notes:
            print(f"# note: {note}", file=sys.stderr)
        print(
            f"wrote {len(rows)} fresh + {len(kept)} kept row(s) to "
            f"{args.baseline}"
        )
        return 0

    findings, notes, fresh = check_campaign(args.baseline, args.cells)
    for note in notes:
        print(f"# note: {note}", file=sys.stderr)
    if findings and fresh:
        write_resilience(f"{args.baseline}.new", fresh)
        print(
            f"# fresh rows written to {args.baseline}.new — diff against "
            f"{args.baseline}; if the resilience change is intentional, "
            "regenerate with `chaos --run` and commit",
            file=sys.stderr,
        )
    for f in findings:
        print(f)
    if findings:
        print(f"chaos: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    n = len(fresh)
    subsystems = len({r["subsystem"] for r in fresh})
    print(f"chaos: OK ({n} cell(s) across {subsystems} subsystem(s) clean)")
    return 0


# --------------------------------------------------------------------------
# plot
# --------------------------------------------------------------------------


def cmd_plot(argv) -> int:
    p = argparse.ArgumentParser(
        prog="rcmarl_tpu plot",
        description="Aggregate sweep results and render figures "
        "(plot_results.py equivalent)",
    )
    p.add_argument("--raw_data", type=str, default="./simulation_results/raw_data")
    p.add_argument("--out", type=str, default="./simulation_results/figures")
    p.add_argument("--drop", type=int, default=500)
    p.add_argument("--rolling", type=int, default=200)
    p.add_argument(
        "--H",
        nargs="+",
        type=int,
        default=None,
        help="H cells to plot (default: every H=* directory found)",
    )
    p.add_argument("--summary", action="store_true", help="print final-return table")
    p.add_argument(
        "--drift",
        nargs="*",
        default=None,
        metavar="SCENARIO:H",
        help="also render ours-vs-reference-artifact overlay figures "
        "(DRIFT.md evidence); no args = coop:0, or pass cells like "
        "'greedy:1 malicious:1'",
    )
    from rcmarl_tpu.analysis.plots import DEFAULT_REF_RAW_DATA as _REF_DEFAULT

    p.add_argument(
        "--ref_raw_data",
        type=str,
        default=_REF_DEFAULT,
        help="reference artifact tree for --drift overlays "
        "(same convention as `parity`)",
    )
    p.add_argument(
        "--quality",
        nargs="*",
        default=None,
        metavar="SCENARIO:H",
        help="also render episodes-to-reference-quality crossing figures "
        "(QUALITY.md evidence); no args = coop:1 malicious:1, or pass "
        "cells like 'greedy:1 faulty:0'",
    )
    p.add_argument(
        "--window",
        type=int,
        default=500,
        help="final-episode window for the --quality threshold (must "
        "match the `quality` run the figures are cited under)",
    )
    p.add_argument(
        "--tolerance",
        type=float,
        default=0.05,
        help="--quality threshold tolerance (same convention as `quality`)",
    )
    args = p.parse_args(argv)

    from rcmarl_tpu.analysis.plots import (
        final_returns,
        plot_drift_comparison,
        plot_returns,
    )

    if args.summary:
        print(final_returns(args.raw_data).to_string(index=False))
    if args.drift is not None:
        cells = args.drift or ["coop:0"]
        for cell in cells:
            scen, _, h = cell.partition(":")
            try:
                h_val = int(h) if h else 0
            except ValueError:
                raise SystemExit(
                    f"--drift: bad cell spec {cell!r}; expected SCENARIO:H "
                    "like 'coop:0' or 'malicious:1'"
                )
            path = plot_drift_comparison(
                args.raw_data,
                args.ref_raw_data,
                Path(args.out) / f"drift_{scen}_h{h_val}.png",
                scenario=scen,
                H=h_val,
                rolling=args.rolling,
            )
            print(path)
    if args.quality is not None:
        from rcmarl_tpu.analysis.quality import plot_quality_crossing

        for cell in args.quality or ["coop:1", "malicious:1"]:
            scen, _, h = cell.partition(":")
            try:
                h_val = int(h) if h else 1
            except ValueError:
                raise SystemExit(
                    f"--quality: bad cell spec {cell!r}; expected "
                    "SCENARIO:H like 'coop:1'"
                )
            path = plot_quality_crossing(
                args.raw_data,
                args.ref_raw_data,
                Path(args.out) / f"quality_{scen}_h{h_val}.png",
                scenario=scen,
                H=h_val,
                window=args.window,
                tol=args.tolerance,
                rolling=args.rolling,
            )
            print(path)
    written = plot_returns(
        args.raw_data,
        args.out,
        H_values=None if args.H is None else tuple(args.H),
        drop=args.drop,
        rolling=args.rolling,
    )
    for w in written:
        print(w)
    return 0


def _related_artifacts_section(summary_out, out_dir) -> str:
    """Cross-reference block for the generated PARITY.md, listing only
    artifacts that actually exist on disk at generation time — a
    regenerated evidence document must not point at dead files.

    Relative candidates resolve against ``out_dir`` (where PARITY.md is
    written, i.e. where its links are relative to when read), not the
    process CWD."""
    out_dir = Path(out_dir)
    candidates = [
        (
            summary_out,
            "the per-seed numbers behind every row above, regenerated by "
            "the same command",
        ),
        (
            "DRIFT.md",
            "root-cause analysis of the private-reward cells' "
            "late-training delta (the reference's shipped artifacts come "
            "from a newer revision with `eps: 0.05` exploration)",
        ),
        ("simulation_results/figures", "curve figures incl. `drift_*.png` overlays"),
        ("BENCH_SHARD.jsonl", "agent-sharding wall-clock A/B (PARALLELISM.md)"),
        ("BENCH_SCALING.jsonl", "scaling matrix incl. xla-vs-pallas consensus"),
        (
            "PARITY_SEEDS456.md",
            "the same pipeline over three UNSEEN seeds {400,500,600} "
            "(robustness check, DRIFT.md)",
        ),
        (
            "QUALITY.md",
            "episodes-to-return-threshold matrix (BASELINE.json's second "
            "metric): episodes and wall-clock to reach the reference's "
            "converged returns, `python -m rcmarl_tpu quality`",
        ),
    ]
    lines = [
        f"- `{p}` — {desc}"
        for p, desc in candidates
        if p
        and (Path(p) if Path(p).is_absolute() else out_dir / p).exists()
    ]
    if not lines:
        return ""
    return "## Related artifacts\n\n" + "\n".join(lines) + "\n"


def cmd_parity(argv) -> int:
    p = argparse.ArgumentParser(
        prog="rcmarl_tpu parity",
        description="Regenerate PARITY.md from the sweep artifacts: ours "
        "vs the reference's shipped raw_data, same aggregation pipeline "
        "for both sides (no hand-maintained rows)",
    )
    from rcmarl_tpu.analysis.plots import DEFAULT_REF_RAW_DATA

    p.add_argument(
        "--raw_data",
        type=str,
        nargs="+",
        default=[
            "./simulation_results/raw_data",
            "./simulation_results/raw_data_seeds456",
        ],
        help="one or more sim_data trees; per-seed rows are pooled, so "
        "the default folds the original seeds {100,200,300} and the "
        "round-3 robustness seeds {400,500,600} into n=6 per cell",
    )
    p.add_argument("--ref_raw_data", type=str, default=DEFAULT_REF_RAW_DATA)
    p.add_argument("--out", type=str, default="./PARITY.md")
    p.add_argument(
        "--summary_out",
        type=str,
        default="./simulation_results/summary.json",
        help="recomputable per-seed summary artifact (the committed "
        "evidence behind PARITY.md's aggregated rows)",
    )
    p.add_argument("--window", type=int, default=500)
    p.add_argument("--tolerance", type=float, default=0.05)
    args = p.parse_args(argv)

    from rcmarl_tpu.analysis.plots import (
        parity_table,
        per_seed_final_returns,
        qualitative_claims_section,
        write_parity_md,
    )

    import pandas as pd

    # Parse the sim_data trees once; the table and the summary artifact
    # are both derived from these frames. Multiple --raw_data trees pool
    # their per-seed rows (n = sum of seeds across trees, per cell) in
    # ONE per_seed_final_returns call so its cross-tree duplicate-seed
    # guard applies — per-tree calls concatenated afterwards would let a
    # seed present in two trees double-count silently, deflating the std
    # every verdict depends on. A tree that does not exist contributes
    # nothing rather than failing, so the default works before the
    # seeds456 sweep has been run.
    mine_dir = ", ".join(args.raw_data)
    mine_seeds = per_seed_final_returns(args.raw_data, args.window)
    ref_seeds = per_seed_final_returns(args.ref_raw_data, args.window)
    table = parity_table(
        mine_dir,
        args.ref_raw_data,
        args.window,
        args.tolerance,
        mine=mine_seeds,
        ref=ref_seeds,
    )
    # Summary artifact first: the PARITY.md cross-reference section lists
    # only files that exist at generation time, and this is one of them.
    if args.summary_out:
        def records(df):
            # NaN (e.g. adv_return of all-cooperative cells) -> null so the
            # artifact is strict JSON, not Python-only NaN literals.
            return [
                {
                    k: (None if isinstance(v, float) and math.isnan(v) else v)
                    for k, v in row.items()
                }
                for row in df.to_dict(orient="records")
            ]

        # No timestamp: identical inputs must produce a byte-identical
        # artifact, so re-running `parity` on unchanged raw_data leaves the
        # committed evidence file untouched.
        summary = {
            "generated_by": "python -m rcmarl_tpu parity",
            "window": args.window,
            "tolerance": args.tolerance,
            "raw_data": args.raw_data,
            "ref_raw_data": args.ref_raw_data,
            "per_seed": {
                "mine": records(mine_seeds),
                "reference": records(ref_seeds),
            },
            "cells": records(table),
        }
        out = Path(args.summary_out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(summary, indent=1, default=float) + "\n")
        print(f"wrote {args.summary_out}")
    write_parity_md(
        table,
        args.out,
        args.window,
        args.tolerance,
        mine_dir=mine_dir,
        ref_dir=args.ref_raw_data,
        extra_sections=(
            qualitative_claims_section(table)
            + "\n"
            + _related_artifacts_section(args.summary_out, Path(args.out).parent)
        ),
    )
    print(table.to_string(index=False))
    print(f"wrote {args.out}")
    return 0


def cmd_quality(argv) -> int:
    p = argparse.ArgumentParser(
        prog="rcmarl_tpu quality",
        description="Regenerate QUALITY.md: episodes (and wall-clock) to "
        "reach the reference's converged returns — BASELINE.json's "
        "'episodes-to-return-threshold' metric, both sides computed from "
        "the same artifact trees as PARITY.md",
    )
    from rcmarl_tpu.analysis.plots import DEFAULT_REF_RAW_DATA

    p.add_argument("--raw_data", type=str, default="./simulation_results/raw_data")
    p.add_argument("--ref_raw_data", type=str, default=DEFAULT_REF_RAW_DATA)
    p.add_argument("--out", type=str, default="./QUALITY.md")
    p.add_argument(
        "--bench_jsonl",
        type=str,
        default="./BENCH_SCALING.jsonl",
        help="measured production-block rows backing the wall-clock columns",
    )
    p.add_argument("--window", type=int, default=500)
    p.add_argument("--tolerance", type=float, default=0.05)
    p.add_argument("--rolling", type=int, default=200)
    args = p.parse_args(argv)

    from rcmarl_tpu.analysis.quality import (
        episode_throughput_from_bench,
        quality_table,
        write_quality_md,
    )

    table = quality_table(
        args.raw_data,
        args.ref_raw_data,
        window=args.window,
        tol=args.tolerance,
        rolling=args.rolling,
    )
    throughput = episode_throughput_from_bench(args.bench_jsonl)
    write_quality_md(
        table,
        args.out,
        throughput,
        window=args.window,
        tol=args.tolerance,
        rolling=args.rolling,
        mine_dir=args.raw_data,
        ref_dir=args.ref_raw_data,
        bench_jsonl=args.bench_jsonl,
    )
    print(table.to_string(index=False))
    print(f"wrote {args.out}")
    return 0


def _honor_platform_env() -> None:
    """Make an explicit ``JAX_PLATFORMS=cpu`` stick.

    This machine's sitecustomize registers the axon TPU tunnel plugin and
    re-sets jax's platform config at interpreter start, silently overriding
    the user's environment choice — so ``JAX_PLATFORMS=cpu python -m
    rcmarl_tpu bench`` (e.g. the virtual 8-device mesh A/B with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8``) would still
    dial the TPU. Deregister the plugin and restore the requested platform,
    exactly as tests/conftest.py does for the test suite.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip() != "cpu":
        return
    try:
        import jax
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # jax internals moved; the env var still applies
        pass


def main(argv=None) -> int:
    _honor_platform_env()
    argv = sys.argv[1:] if argv is None else argv
    cmds = {
        "train": cmd_train,
        "sweep": cmd_sweep,
        "plot": cmd_plot,
        "bench": cmd_bench,
        "profile": cmd_profile,
        "serve": cmd_serve,
        "evaluate": cmd_evaluate,
        "parity": cmd_parity,
        "quality": cmd_quality,
        "lint": cmd_lint,
        "chaos": cmd_chaos,
    }
    if not argv or argv[0] in ("-h", "--help"):
        print(f"usage: python -m rcmarl_tpu {{{','.join(cmds)}}} [flags]")
        return 0 if argv else 2
    cmd = argv[0]
    if cmd not in cmds:
        print(f"unknown command {cmd!r}; expected one of {sorted(cmds)}")
        return 2
    return cmds[cmd](argv[1:])
