"""Async actor-learner pipeline (ROADMAP item 1).

Runs the actor tier (the serving engine's compile-once rollout program,
:func:`rcmarl_tpu.serve.engine.actor_block`) and the learner tier (the
donated block-stepping epoch) as decoupled stages of ONE continuous
system: rollout blocks are dispatched up to ``Config.pipeline_depth``
blocks ahead of the learner through a bounded on-device queue with
``block_until_ready``-free handoff, acting on parameters the learner
publishes every ``Config.publish_every`` blocks through a
validate-then-swap-wholesale publisher (the in-memory twin of the
serving checkpoint hot-swap chain). Off-policy staleness is a counted,
first-class quantity — never an accident (``df.attrs['pipeline']``).
"""

from rcmarl_tpu.pipeline.publish import PolicyPublisher  # noqa: F401
from rcmarl_tpu.pipeline.queue import BlockQueue  # noqa: F401
from rcmarl_tpu.pipeline.trainer import (  # noqa: F401
    learner_block,
    learner_block_donated,
    pipeline_summary,
    train_pipelined,
)
