"""Learner->actor parameter publishing — the in-memory hot-swap chain.

The serving side already solved this problem once: the
:class:`~rcmarl_tpu.serve.swap.CheckpointWatcher` validates a candidate
COMPLETELY, then replaces the engine's single block reference wholesale,
so a consumer can never observe a torn tree and a poisoned candidate is
rejected with the consumer kept on its last good parameters. The
pipeline's publisher is that exact discipline with the file system cut
out: the learner offers its parameter tree at publish boundaries
(``Config.publish_every``), the actor tier always acts on ONE acting
reference, and the swap is a single Python rebind — atomic with respect
to actor dispatches by construction.

Two knobs mirror the two trainer regimes:

- ``copy=True`` (the donated learner loop): the published tree is
  device-copied at offer time, because the learner's next donated block
  will consume the source buffers in place — the copies are dispatched
  asynchronously, so the handoff stays ``block_until_ready``-free.
- ``validate=True`` (guarded runs): the shared publish-candidate guard
  (:func:`rcmarl_tpu.faults.params_finite`) runs in front of the swap —
  a NaN-poisoned learner can degrade its own metrics, but it can never
  poison the acting tier; rejects are counted, the actor keeps the last
  good parameters. Validation host-syncs, which guarded runs already do
  per block; unguarded runs skip it to keep the pipeline free-running.

A third, opt-in guard closes the deployment loop (ROADMAP item 4c):
``canary=`` takes a policy-level admission callable — canonically
:meth:`rcmarl_tpu.serve.canary.CanaryGate.admit`, the frozen-policy
return gate — run AFTER the finiteness guard and before the swap. A
candidate whose frozen return degrades beyond the gate's band is
rejected (``canary_rejects`` counted) and the actor tier keeps acting
on the last published parameters: "bad policy" gets the same
reject/last-good treatment "corrupt file" and "poisoned tree" always
had. The canary host-syncs an eval rollout per publish boundary, so it
is a deployment-cadence knob, not a per-block one.
"""

from __future__ import annotations

from typing import Any


class PolicyPublisher:
    """Single-reference acting-parameter publisher with staleness
    bookkeeping.

    ``acting`` is the tree the actor tier dispatches against;
    ``published_block`` the learner block count it corresponds to —
    ``dispatch_block - published_block`` is the pipeline's measured
    staleness, counted by the trainer at every actor dispatch.
    """

    def __init__(
        self,
        params: Any,
        publish_every: int = 1,
        *,
        copy: bool = False,
        validate: bool = False,
        canary: Any = None,
        learner_block: int = 0,
    ) -> None:
        if publish_every < 1:
            raise ValueError(
                f"publish_every={publish_every} must be >= 1"
            )
        self.publish_every = publish_every
        self.copy = copy
        self.validate = validate
        self.canary = canary
        self.acting = self._prepare(params)
        self.published_block = learner_block
        self.counters = {"publishes": 0, "rejects": 0, "canary_rejects": 0}

    def _prepare(self, params: Any) -> Any:
        if not self.copy:
            return params
        import jax
        import jax.numpy as jnp

        # async device copies: dispatched BEFORE the learner's next
        # donated block can consume the source buffers, completed by
        # XLA's ordinary dependency ordering — never a host sync
        return jax.tree.map(jnp.copy, params)

    def offer(
        self, params: Any, learner_block: int, *, force: bool = False
    ) -> bool:
        """Offer the learner's parameters after ``learner_block``
        completed blocks; publish iff this is a publish boundary and
        (under ``validate``) the candidate is fully finite.

        ``force=True`` waives only the cadence check — the composed
        fleet's gossip mix and rollback are publish events whatever
        ``publish_every`` says (an actor tier acting on pre-mix params
        would roll windows under a policy no learner holds), but a
        forced candidate still runs the full finiteness and canary
        guards. Cadence is a throttle; the guards are the contract.

        Returns True iff the acting reference was swapped. A rejected
        candidate leaves the actor tier on the last good parameters
        with ``rejects`` incremented — the watcher's degradation
        contract, one level down the stack.
        """
        if not force and learner_block % self.publish_every != 0:
            return False
        if self.validate:
            from rcmarl_tpu.faults import params_finite

            if not params_finite(params):
                self.counters["rejects"] += 1
                return False
        if self.canary is not None and not self.canary(params):
            # bad POLICY (a finite, checksum-clean candidate whose
            # frozen return fell out of the gate's band): same
            # reject/keep-last-good outcome as the finiteness guard,
            # ledgered separately so deployment dashboards can tell
            # "learner diverged" from "learner published a regression"
            self.counters["canary_rejects"] += 1
            return False
        # validate fully, then swap the single reference wholesale: an
        # actor dispatched before this line acts on the old tree, one
        # dispatched after acts on the new tree, and no dispatch can
        # ever see a mix (the CheckpointWatcher atomicity contract)
        self.acting = self._prepare(params)
        self.published_block = learner_block
        self.counters["publishes"] += 1
        return True
