"""Bounded on-device block queue — the actor->learner handoff.

The TorchBeast/Sebulba shape (PAPERS.md 1910.03552, 2104.06272): actors
feed the learner through a bounded queue so the two tiers can run out of
phase. Here both tiers live in one host process over one JAX device
stream, so the queue holds *dispatched-but-possibly-unfinished* device
values (jax arrays are futures): ``put``/``get`` move references only —
no ``block_until_ready``, no host fetch — and XLA's own data
dependencies order the actual execution. The bound IS the pipeline
depth: a full queue means the actor tier is the configured number of
blocks ahead, and the host simply stops dispatching more rollout until
the learner drains one.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Tuple


class BlockQueue:
    """FIFO of at most ``depth`` in-flight rollout blocks.

    Overflow and underflow raise: the pipeline trainer's dispatch
    schedule is deterministic, so either is a driver bug, not a
    backpressure condition to paper over.
    """

    def __init__(self, depth: int) -> None:
        if depth < 1:
            raise ValueError(f"BlockQueue depth={depth} must be >= 1")
        self.depth = depth
        self._q: deque = deque()

    def __len__(self) -> int:
        return len(self._q)

    @property
    def full(self) -> bool:
        return len(self._q) >= self.depth

    def put(self, item: Tuple[int, Any, Any]) -> None:
        """Enqueue one ``(block_index, fresh, metrics)`` payload —
        reference handoff only, never a device sync."""
        if self.full:
            raise RuntimeError(
                f"BlockQueue overflow: {len(self._q)} in-flight blocks "
                f"at depth {self.depth} — the actor tier dispatched "
                "ahead of schedule"
            )
        self._q.append(item)

    def get(self) -> Tuple[int, Any, Any]:
        """Dequeue the oldest payload (the learner consumes strictly in
        block order)."""
        if not self._q:
            raise RuntimeError(
                "BlockQueue underflow: the learner asked for a block "
                "the actor tier never dispatched"
            )
        return self._q.popleft()
