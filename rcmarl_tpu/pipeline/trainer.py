"""The pipelined trainer: rollout in the epoch's shadow.

The synchronous trainer (:func:`rcmarl_tpu.training.trainer.train`)
fuses rollout + update into one launch per block, so the ~ms rollout
serializes with the ~s epoch and the compile-once acting program sits
idle while the learner runs. This module runs the two tiers out of
phase (the Podracer/Sebulba split, PAPERS.md 2104.06272; TorchBeast's
queue decoupling, 1910.03552):

- **actor tier** — :func:`rcmarl_tpu.serve.engine.actor_block`, the
  serving engine's compile-once rollout program, dispatched up to
  ``Config.pipeline_depth`` blocks ahead of the learner against the
  parameters the learner last PUBLISHED
  (:class:`~rcmarl_tpu.pipeline.publish.PolicyPublisher`, the in-memory
  twin of the serving checkpoint hot-swap chain).
- **learner tier** — :data:`learner_block` /
  :data:`learner_block_donated`: the existing block-stepping epoch
  (``update_batch`` -> ``update_block`` -> ``buffer_push_block``) minus
  the rollout, with the same state-donation policy as the synchronous
  loop.
- **handoff** — a bounded
  :class:`~rcmarl_tpu.pipeline.queue.BlockQueue` of in-flight device
  values; no stage ever calls ``block_until_ready``, so XLA's data
  dependencies are the only ordering and rollout executes in the shadow
  of the epoch wherever the hardware has the parallelism to pay for it.

**RNG discipline.** The per-block key chain is EXACTLY the synchronous
trainer's (``key, k_roll, k_upd = split(key, 3)`` per block), walked
host-side ahead of the dispatch schedule — a pipelined run differs from
its synchronous twin ONLY through which parameters act, never through
different random draws.

**Staleness is counted, not accidental.** At every actor dispatch the
trainer records ``block - published_block``: steady state is
``depth - 1`` extra epochs of off-policy lag (plus up to
``publish_every - 1`` of publish lag), ramping 0,1,... over the first
``depth`` blocks. Counters land in ``df.attrs['pipeline']`` and the
train summary line; the FaultPlan ``stale_p`` machinery
(:mod:`rcmarl_tpu.faults`) models the same replay semantics per link —
this module makes it a whole-policy, schedule-level knob, and the
staleness quality cell (QUALITY.md) measures what it costs in return.

**depth=0 is the reference arm.** Synchronous handoff DELEGATES to
:func:`~rcmarl_tpu.training.trainer.train` itself and attaches the
degenerate pipeline counters — bitwise the synchronous trainer by
construction (and still pinned leaf-for-leaf in tests/test_pipeline.py
and ci_tier1.sh as the regression net), so the synchronous trainer
remains the trusted baseline every pipelined arm is judged against.

**Guard semantics at depth > 0.** The guard is two-sided, keyed on
WHERE the poison lives:

- **Poisoned learner output** (finite rollout window, non-finite
  update): roll back and retry with a perturbed update key — the
  rollout batch is kept, because a different update draw can genuinely
  succeed against the same window — then skip.
- **Poisoned rollout window** (the actor tier delivered a non-finite
  batch/metrics): retrying the UPDATE against it is structurally
  futile — no ``k_upd`` perturbation can launder NaN inputs — so the
  guard SKIPS-AND-REDRAWS instead: re-dispatch the actor block with a
  per-attempt folded rollout key (deterministic in ``(key, block,
  attempt)``, a dedicated stream off the block's roll key), up to
  ``max_retries`` times, against the CURRENT published params; if every
  redraw is still poisoned, the block is skipped without ever paying a
  learner launch. Historically the learner retry loop burned its whole
  budget of ~s epochs re-consuming the same poisoned window — the
  chaos campaign's ``pipeline_window`` cells pin the fixed behavior.

Either way the publisher validates every candidate, and a skipped block
publishes NOTHING (the rolled-back tree is what the actor already acts
on), so a poisoned tier can never reach the acting side and skips
lengthen the measured staleness instead of silently resetting it. After
a skip the in-flight dispatch chain stays unperturbed (later rollouts
are already queued on it) while the STORED key folds exactly like the
synchronous skip, so a checkpoint taken at a skipped block never
replays the failing draws on resume — the depth-0 arm keeps the
synchronous skip semantics exactly.

``window_fault`` is the chaos-injection seam (:mod:`rcmarl_tpu.chaos`):
a callable applied to every window the learner picks up — dispatches
AND redraws — modeling an actor tier that delivers poisoned (or,
equivalently, dropped) rollout windows in transit between the tiers.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from rcmarl_tpu.config import Config
from rcmarl_tpu.pipeline.publish import PolicyPublisher
from rcmarl_tpu.pipeline.queue import BlockQueue
from rcmarl_tpu.training.buffer import buffer_push_block, update_batch
from rcmarl_tpu.training.trainer import (
    TrainState,
    _block_healthy,
    init_train_state,
    metrics_to_dataframe,
    train,
)
from rcmarl_tpu.training.update import update_block


def _learner_block(
    cfg: Config, state: TrainState, fresh, k_upd, new_key,
    with_diag: bool = False,
):
    """One LEARNER block: consume a rollout window the actor tier
    produced — replay-window assembly, the ``n_epochs`` critic/TR
    consensus epochs + actor phase, buffer push — and advance the
    block counter. The synchronous ``_train_block`` minus the rollout:
    ``new_key`` is the next chain key the host pre-derived, stored so
    checkpoints stay resume-compatible with the synchronous format."""
    batch = update_batch(state.buffer, fresh)
    if with_diag:
        params, diag = update_block(
            cfg, state.params, batch, fresh, k_upd, with_diag=True
        )
    else:
        params = update_block(cfg, state.params, batch, fresh, k_upd)
    buffer = buffer_push_block(state.buffer, fresh)
    out = TrainState(
        params, buffer, state.desired, state.initial, new_key,
        state.block + 1,
    )
    if with_diag:
        return out, diag
    return out


#: The standard jitted learner block (inputs stay alive — the guarded
#: retry path re-runs from the same pre-block state).
learner_block = partial(
    jax.jit, static_argnums=0, static_argnames=("with_diag",)
)(_learner_block)

#: Same program with ``state`` DONATED — the steady-state pipelined
#: loop's allocation saver, exactly the synchronous trainer's
#: ``train_block_donated`` policy (the publisher holds COPIES of
#: published params, so donation can never invalidate the acting tier's
#: buffers). The passed ``state`` is consumed.
learner_block_donated = jax.jit(
    _learner_block,
    static_argnums=0,
    static_argnames=("with_diag",),
    donate_argnums=(1,),
)


def pipeline_fingerprint(cfg: Config) -> str:
    """The ``cost_fingerprint`` of a pipelined measurement: one hash
    over BOTH tier programs (the actor-tier rollout block and the
    donated learner block — the steady-state pair a clean pipelined run
    dispatches), abstract lowering only (no allocation, no compile) —
    the ledger convention of
    :func:`rcmarl_tpu.utils.profiling.train_block_fingerprint`, for the
    two-program arm."""
    from rcmarl_tpu.serve.engine import actor_block
    from rcmarl_tpu.training.rollout import rollout_block
    from rcmarl_tpu.training.trainer import make_env
    from rcmarl_tpu.utils.profiling import program_fingerprint

    key = jax.random.PRNGKey(0)
    state = jax.eval_shape(lambda k: init_train_state(cfg, k), key)
    fresh, _ = jax.eval_shape(
        lambda p, d, k, i: rollout_block(cfg, make_env(cfg), p, d, k, i),
        state.params, state.desired, key, state.initial,
    )
    actor = actor_block.lower(
        cfg, state.params, state.desired, key, state.initial
    )
    learner = learner_block_donated.lower(cfg, state, fresh, key, key)
    return program_fingerprint(actor.as_text() + learner.as_text())


def pipeline_summary(attrs: dict) -> str:
    """The one-line pipeline summary (cmd_train prints it; the CI
    smoke cell greps the staleness counters off it)."""
    return (
        f"pipeline: depth {attrs['depth']}, publish_every "
        f"{attrs['publish_every']} — staleness mean "
        f"{attrs['staleness_mean']:.2f} / max {attrs['staleness_max']} "
        f"over {attrs['blocks']} blocks, {attrs['publishes']} publishes, "
        f"{attrs['rejects']} rejects"
    )


def _window_healthy(fresh, m) -> bool:
    """Host bool: the actor-tier rollout window (batch + metrics) is
    fully finite — the learner-side pickup guard. A poisoned window
    fails here BEFORE any learner launch is paid (the update retry
    cannot succeed against non-finite inputs)."""
    from rcmarl_tpu.faults import tree_all_finite

    return bool(tree_all_finite((fresh, m)))


#: fold_in tag deriving the window-REDRAW rollout-key stream from the
#: block's chain key — a dedicated stream (distinct from the learner
#: retry's bare fold_in(chain, attempt) update keys), so redraw and
#: retry draws can never collide.
_REDRAW_STREAM = 0x5EED

#: the synchronous skip's stored-key fold tag (training/trainer.py's
#: protocol, shared verbatim so checkpoint-resume semantics match).
_SKIP_STREAM = 0x5C1B


def _skip_stored_key(state: TrainState, b: int) -> TrainState:
    """The skip protocol's stored-state update, shared by the
    window-skip and learner-skip paths (exactly ONE fires per block):
    fold the STORED key like the synchronous skip and advance the block
    counter — a checkpoint taken at a skipped block never replays the
    failing draws on resume, while the in-flight dispatch chain stays
    unperturbed."""
    return state._replace(
        key=jax.random.fold_in(state.key, _SKIP_STREAM + b),
        block=state.block + 1,
    )


def train_pipelined(
    cfg: Config,
    n_episodes: Optional[int] = None,
    state: Optional[TrainState] = None,
    verbose: bool = False,
    block_callback=None,
    guard: Optional[bool] = None,
    max_retries: int = 1,
    window_fault=None,
):
    """Host-looped pipelined training run (see module docstring).

    The :func:`~rcmarl_tpu.training.trainer.train` signature and return
    contract, plus ``df.attrs['pipeline']``: ``depth``/
    ``publish_every``/``blocks``, the per-block ``staleness`` list with
    its ``staleness_mean``/``staleness_max``, and the publisher's
    ``publishes``/``rejects`` counters. ``cfg.pipeline_depth == 0`` is
    the synchronous-handoff reference arm, bitwise the synchronous
    trainer; ``verbose`` adds host fetches per block (quiet runs keep
    the pipeline free-running).

    ``window_fault`` (depth > 0 only) is the chaos-injection seam:
    ``window_fault(block, attempt, fresh, metrics) -> (fresh, metrics)``
    applied to every window the learner picks up — the first dispatch
    is ``attempt=0``, guard redraws count up from 1 — so the chaos
    campaign can model an actor tier delivering poisoned/dropped
    rollout windows (:mod:`rcmarl_tpu.chaos`); guarded runs then
    exercise the skip-and-redraw path for real. ``df.attrs['guard']``
    grows a ``redraws`` counter next to the synchronous stats.
    """
    n_eps = cfg.n_episodes if n_episodes is None else n_episodes
    if n_eps % cfg.n_ep_fixed != 0:
        raise ValueError(
            f"n_episodes={n_eps} must be a multiple of "
            f"n_ep_fixed={cfg.n_ep_fixed}"
        )
    if max_retries < 0:
        raise ValueError(f"max_retries={max_retries} must be >= 0")
    n_blocks = n_eps // cfg.n_ep_fixed
    depth = cfg.pipeline_depth
    if guard is None:
        guard = cfg.fault_plan is not None
    with_diag = cfg.fault_plan is not None and cfg.fault_plan.active

    if depth == 0:
        if window_fault is not None:
            raise ValueError(
                "window_fault is the decoupled tiers' transit seam; "
                "the depth-0 synchronous handoff has no actor->learner "
                "transit to fault (run pipeline_depth >= 1)"
            )
        # ---- synchronous handoff IS the synchronous trainer: delegate,
        # so the depth-0 reference arm is bitwise by CONSTRUCTION, not
        # by a hand-maintained twin loop (publish accounting is
        # degenerate: every block's parameters act immediately)
        state, df = train(
            cfg,
            n_episodes=n_eps,
            state=state,
            verbose=verbose,
            block_callback=block_callback,
            guard=guard,
            max_retries=max_retries,
        )
        df.attrs["pipeline"] = {
            "depth": 0,
            "publish_every": cfg.publish_every,
            "blocks": n_blocks,
            "staleness": [0] * n_blocks,
            "staleness_mean": 0.0,
            "staleness_max": 0,
            "publishes": n_blocks,
            "rejects": 0,
        }
        return state, df

    if state is None:
        state = init_train_state(cfg, jax.random.PRNGKey(cfg.seed))
    elif not guard:
        # the donated entries below CONSUME their input state; work on a
        # one-time copy so the caller's resume state stays alive (the
        # synchronous trainer's exact policy)
        state = jax.tree.map(jnp.copy, state)
    stats = {
        "retries": 0, "redraws": 0, "skipped": 0, "nonfinite": 0,
        "deficit": 0,
    }
    all_metrics = []
    staleness = []

    # ---- the decoupled pipeline
    donate = not guard
    # no validate= here: only ACCEPTED (health-checked) blocks ever
    # reach offer() below, so a trainer-side publish validation
    # would re-reduce a tree the guard just proved finite and pay a
    # host sync for a check that cannot fail; PolicyPublisher's
    # validate arm stays for standalone publisher users
    publisher = PolicyPublisher(
        state.params, cfg.publish_every, copy=donate
    )
    # actor-tier stable buffers: desired/initial never change, but
    # the donated learner aliases the state's copies every block —
    # the actor dispatches against its own never-donated pair
    desired0 = jnp.copy(state.desired)
    initial0 = jnp.copy(state.initial)
    # the synchronous per-block key chain, walked ahead of the
    # dispatch schedule: chain[b] is block b's state.key, keys[b]
    # its (k_roll, k_upd) — identical draws to the sync trainer
    from rcmarl_tpu.serve.engine import actor_block

    chain = [state.key]
    keys = []

    def block_keys(j: int):
        while len(keys) <= j:
            nk, kr, ku = jax.random.split(chain[-1], 3)
            chain.append(nk)
            keys.append((kr, ku))
        return keys[j]

    queue = BlockQueue(depth)

    def dispatch_actor(j: int) -> None:
        k_roll, _ = block_keys(j)
        fresh, m = actor_block(
            cfg, publisher.acting, desired0, k_roll, initial0
        )
        staleness.append(j - publisher.published_block)
        queue.put((j, fresh, m))

    for j in range(min(depth, n_blocks)):
        dispatch_actor(j)

    learner = learner_block if guard else learner_block_donated
    for b in range(n_blocks):
        j, fresh, m = queue.get()
        assert j == b, f"pipeline order broke: got block {j} at {b}"
        if window_fault is not None:
            fresh, m = window_fault(b, 0, fresh, m)
        _, k_upd = block_keys(b)
        new_key = chain[b + 1]
        attempt = 0
        accepted = True
        diag = None
        # ---- window pickup guard: a non-finite rollout window makes
        # every learner retry structurally futile (the batch would be
        # kept) — redraw the WINDOW instead, fresh actor launches under
        # per-attempt folded roll keys against the current published
        # params, then skip the block without paying a learner launch.
        window_ok = True
        if guard:
            window_ok = _window_healthy(fresh, m)
            redraw = 0
            while not window_ok and redraw < max_retries:
                redraw += 1
                stats["redraws"] += 1
                if verbose:
                    print(
                        f"| Block {b + 1} | non-finite rollout window "
                        f"— redrawing (redraw {redraw}/{max_retries})"
                    )
                k_roll = jax.random.fold_in(
                    jax.random.fold_in(chain[b], _REDRAW_STREAM), redraw
                )
                fresh, m = actor_block(
                    cfg, publisher.acting, desired0, k_roll, initial0
                )
                if window_fault is not None:
                    fresh, m = window_fault(b, redraw, fresh, m)
                window_ok = _window_healthy(fresh, m)
        if not window_ok:
            stats["skipped"] += 1
            if verbose:
                print(
                    f"| Block {b + 1} | rollout window still "
                    f"non-finite after {max_retries} redraws — "
                    "skipping (no learner launch, nothing published)"
                )
            state = _skip_stored_key(state, b)
            accepted = False
        else:
            while True:
                if attempt:
                    # the synchronous retry discipline applied to the
                    # learner side: deterministic in (key, block,
                    # attempt), rollout batch kept as produced — the
                    # window is finite here, so a fresh update draw can
                    # genuinely succeed against it
                    k_upd = jax.random.fold_in(chain[b], attempt)
                diag = None
                if with_diag:
                    new_state, diag = learner(
                        cfg, state, fresh, k_upd, new_key, with_diag=True
                    )
                else:
                    new_state = learner(cfg, state, fresh, k_upd, new_key)
                if not guard or _block_healthy(new_state, m):
                    state = new_state
                    break
                if attempt < max_retries:
                    attempt += 1
                    stats["retries"] += 1
                    if verbose:
                        print(
                            f"| Block {b + 1} | non-finite learner "
                            f"output — rolling back (retry "
                            f"{attempt}/{max_retries})"
                        )
                    continue
                stats["skipped"] += 1
                if verbose:
                    print(
                        f"| Block {b + 1} | still non-finite after "
                        f"{max_retries} retries — skipping (params "
                        "rolled back)"
                    )
                state = _skip_stored_key(state, b)
                accepted = False
                break
        if diag is not None:
            stats["nonfinite"] += int(diag.nonfinite)
            stats["deficit"] += int(diag.deficit)
        all_metrics.append(m)
        if accepted:
            # a skipped block publishes NOTHING: the rolled-back
            # tree is what the actor already acts on, and counting
            # it as a fresh publish would silently understate the
            # measured staleness of every later dispatch
            publisher.offer(state.params, b + 1)
        if b + depth < n_blocks:
            dispatch_actor(b + depth)
        if verbose:
            _print_block(cfg, state, m, b)
        if block_callback is not None:
            block_callback(state, b)
    publishes = publisher.counters["publishes"]
    rejects = publisher.counters["rejects"]

    metrics = jax.tree.map(lambda *xs: jnp.concatenate(xs), *all_metrics)
    df = metrics_to_dataframe(metrics)
    df.attrs["pipeline"] = {
        "depth": depth,
        "publish_every": cfg.publish_every,
        "blocks": n_blocks,
        "staleness": staleness,
        "staleness_mean": (
            sum(staleness) / len(staleness) if staleness else 0.0
        ),
        "staleness_max": max(staleness, default=0),
        "publishes": publishes,
        "rejects": rejects,
    }
    if guard or with_diag:
        df.attrs["guard"] = stats
    return state, df


def _print_block(cfg: Config, state: TrainState, m, b: int) -> None:
    """The synchronous trainer's per-block verbose line (host-syncing —
    verbose runs trade the free-running pipeline for live output)."""
    tt = float(jnp.mean(m.true_team_returns))
    et = float(jnp.mean(m.est_team_returns))
    print(
        f"| Block {int(state.block)} | episodes "
        f"{(b + 1) * cfg.n_ep_fixed} | team return {tt:.3f} | "
        f"est return {et:.3f}"
    )
