"""Optimizers with TensorFlow/Keras-exact semantics.

The reference trains actors with ``keras.optimizers.Adam`` and critics /
team-reward nets with stateless ``keras.optimizers.SGD``
(``resilient_CAC_agents.py:36-38``). Curve parity hinges on TF's Adam
formulation (SURVEY.md §7 contract 5), which differs from optax's default:

  TF:    lr_t = lr * sqrt(1 - b2^t) / (1 - b1^t)
         theta -= lr_t * m_t / (sqrt(v_t) + eps),   eps = 1e-7
  optax: theta -= lr * m_hat / (sqrt(v_hat) + eps), eps = 1e-8

i.e. TF adds the (unscaled) epsilon AFTER folding the bias correction into
the step size, and defaults to eps=1e-7. We implement TF's form exactly.

All functions are pure pytree transforms — vmappable over the agent axis.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


def sgd_update(params, grads, lr: float):
    """Plain SGD: theta -= lr * g (keras.optimizers.SGD, no momentum)."""
    return jax.tree.map(lambda p, g: p - lr * g, params, grads)


def clip_grads(grads, clip: float):
    """Global-norm gradient clip: g * min(1, clip / ||g||_2).

    ``clip`` is a STATIC Python float; ``clip <= 0`` returns ``grads``
    untouched with NO extra ops traced, so the default-off program is
    bit-for-bit the reference op sequence (the fitstack/netstack pins
    rely on this). The clip exists for the mega-population path
    (``Config.fit_clip``): the phase-I full-batch MSE gradient's
    Lipschitz constant grows with the population's input width, so
    past the reference scale the fixed ``fast_lr`` crosses the SGD
    stability bound (lr > 2/L) and the raw 5-step fit diverges.
    """
    if clip <= 0.0:
        return grads
    leaves = jax.tree.leaves(grads)
    norm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
    scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-16))
    return jax.tree.map(lambda g: g * scale, grads)


class AdamState(NamedTuple):
    count: jnp.ndarray  # scalar int32 step counter (t in TF's formula)
    m: object  # first-moment pytree, same structure as params
    v: object  # second-moment pytree


def adam_init(params) -> AdamState:
    return AdamState(
        count=jnp.zeros((), jnp.int32),
        m=jax.tree.map(jnp.zeros_like, params),
        v=jax.tree.map(jnp.zeros_like, params),
    )


def adam_update(
    params,
    grads,
    state: AdamState,
    lr: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-7,
) -> Tuple[object, AdamState]:
    """One TF-semantics Adam step. Returns (new_params, new_state)."""
    t = state.count + 1
    tf_ = t.astype(jnp.float32)
    lr_t = lr * jnp.sqrt(1.0 - b2**tf_) / (1.0 - b1**tf_)
    new_m = jax.tree.map(lambda m, g: b1 * m + (1.0 - b1) * g, state.m, grads)
    new_v = jax.tree.map(lambda v, g: b2 * v + (1.0 - b2) * g * g, state.v, grads)
    new_params = jax.tree.map(
        lambda p, m, v: p - lr_t * m / (jnp.sqrt(v) + eps), params, new_m, new_v
    )
    return new_params, AdamState(count=t, m=new_m, v=new_v)
