"""Fit-scan Pallas kernel: VMEM-resident netstacked params across the
whole minibatch schedule.

The fitstack scan (:func:`rcmarl_tpu.ops.fit.fused_fit_scan`) runs
``epochs x n_batches`` SGD steps per (flavor-row, agent) cell as a
``lax.scan`` whose carry — the stacked parameter block — round-trips
HBM every step: XLA double-buffers while-loop carries, so each of the
~600 steps of the adversary schedule reads and writes the full
parameter state. This kernel gives each (row, agent) grid cell its
parameters ONCE as VMEM residents, runs the entire schedule as an
in-kernel ``fori_loop`` over the precomputed shuffle plans, and writes
the fitted parameters back at the end: parameter HBM traffic drops
from ``2 * steps * P`` to ``2 * P`` (the ``fit_scan[...]`` AUDIT.jsonl
rows carry the model; the fit data and plans are read once either
way).

Bitwise discipline (the fitstack contract,
tests/test_fitstack_properties.py): the shuffle plans are drawn
XLA-side with :func:`~rcmarl_tpu.ops.fit.valid_first_shuffle` /
:func:`~rcmarl_tpu.ops.fit.identity_plan` under the EXACT per-epoch
key structure ``fit_minibatch`` draws (uniform bits + argsort are
integer-exact, immune to fusion-context rounding), and each kernel
step traces the same ``value_and_grad(weighted_mse(forward(p,
x[idx]), target[idx], mask=bval))`` + ``sgd_update`` +
skip-empty-batch select op sequence as the scan body. Fitted
parameters are pinned against the XLA scan leaf-for-leaf
(tests/test_fused_epoch.py); the returned first-epoch loss is a
logging value whose weighted-mean reduction may differ by f32
rounding across fusion contexts and is pinned at allclose.

Lands as ``Config.fitstack='pallas'`` (real lowering — queued for the
TPU session) and ``'pallas_interpret'`` (the CPU test arm). VMEM
budget: one cell holds its parameter leaves + the (B, width) fit data
+ the (epochs, n_batches, batch) plans — ~2.5 MB at the BASELINE
256-wide scale, inside a v5e core's 128 MB.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

import numpy as np

from rcmarl_tpu.ops.dma_model import BlockOperand, KernelPlan
from rcmarl_tpu.ops.fit import (
    FitSchedule,
    identity_plan,
    valid_first_shuffle,
)
from rcmarl_tpu.ops.losses import weighted_mse
from rcmarl_tpu.ops.optim import clip_grads, sgd_update


def _fit_plans(keys, mask, schedule: FitSchedule, n_batches: int):
    """(idx, bvalid) of shape (R, N, epochs, n_batches, batch_size) —
    the exact per-(row, agent, epoch) batch plans ``fit_minibatch``
    would draw, precomputed XLA-side (threefry + argsort: bit-exact in
    any fusion context)."""
    R, N = keys.shape[0], keys.shape[1]
    bs = schedule.batch_size
    if not schedule.shuffle:
        idx1, bv1 = identity_plan(mask, n_batches, bs)
        shape = (R, N, schedule.epochs, n_batches, bs)
        return (
            jnp.broadcast_to(idx1, shape),
            jnp.broadcast_to(bv1, shape),
        )

    def plans_one(key):
        ekeys = jax.random.split(key, schedule.epochs)
        if schedule.assume_valid:
            f = lambda ek: valid_first_shuffle(
                ek, mask, n_batches, bs, assume_valid=True
            )
        else:
            # positional call, no flag: mirrors fit_minibatch's hook
            f = lambda ek: valid_first_shuffle(ek, mask, n_batches, bs)
        return jax.vmap(f)(ekeys)

    return jax.vmap(jax.vmap(plans_one))(keys)


def _fit_kernel(
    *refs,
    treedef,
    n_leaves: int,
    forward,
    lr: float,
    epochs: int,
    n_batches: int,
    shuffle: bool,
    clip: float,
):
    """One (row, agent) cell: params live in registers/VMEM across the
    whole ``epochs x n_batches`` schedule; each step is the scan body's
    exact op sequence on the precomputed plan row."""
    leaf_refs = refs[:n_leaves]
    x_ref, tgt_ref, idx_ref, bval_ref = refs[n_leaves : n_leaves + 4]
    out_leaf_refs = refs[n_leaves + 4 : n_leaves + 4 + n_leaves]
    loss_ref = refs[-1]

    params = jax.tree.unflatten(
        treedef, [r[...][0, 0] for r in leaf_refs]
    )
    x = x_ref[...][0]  # (B, W)
    tgt = tgt_ref[...][0, 0]  # (B, 1)
    idx_all = idx_ref[...][0, 0]  # (epochs, n_batches, bs)
    bval_all = bval_ref[...][0, 0]

    def step(s, carry):
        p, losses0, counts0 = carry
        e = s // n_batches
        b = s % n_batches
        bidx = idx_all[e, b]
        bval = bval_all[e, b]

        def batch_loss(p):
            return weighted_mse(forward(p, x[bidx]), tgt[bidx], mask=bval)

        loss, g = jax.value_and_grad(batch_loss)(p)
        g = clip_grads(g, clip)
        nonempty = jnp.sum(bval) > 0
        newp = sgd_update(p, g, lr)
        p = jax.tree.map(lambda a, b_: jnp.where(nonempty, b_, a), p, newp)
        # epoch-0 per-batch (loss, count) rows for the returned
        # first-epoch loss (a (1, n_batches) select — no scatter)
        slot = (
            jax.lax.broadcasted_iota(jnp.int32, (1, n_batches), 1) == b
        ) & (e == 0)
        losses0 = jnp.where(slot, loss, losses0)
        counts0 = jnp.where(slot, jnp.sum(bval), counts0)
        return p, losses0, counts0

    zeros = jnp.zeros((1, n_batches), jnp.float32)
    params, losses0, counts0 = jax.lax.fori_loop(
        0, epochs * n_batches, step, (params, zeros, zeros)
    )
    if not shuffle and n_batches == 1:
        # the full-batch flavor: "epoch loss" IS the one batch loss
        first_loss = losses0[0, 0]
    else:
        first_loss = jnp.sum(losses0 * counts0) / jnp.maximum(
            jnp.sum(counts0), 1.0
        )
    for r, leaf in zip(out_leaf_refs, jax.tree.leaves(params)):
        r[...] = leaf[None, None]
    loss_ref[...] = first_loss.reshape(1, 1)


def kernel_plan(
    params_rows, x_rows, targets_rows, schedule: FitSchedule
) -> KernelPlan:
    """The fit scan's static BlockSpec plan — the ONE derivation both
    :func:`pallas_fit_scan` (which builds its ``pl.BlockSpec`` lists
    from these operands) and ``lint --kernels`` consume. Accepts real
    arrays or ``jax.ShapeDtypeStruct`` leaves (only shapes/dtypes are
    read), so the lint arm prices bench cells via ``jax.eval_shape``
    without allocating a batch.

    Grid ``(R, N)`` — one cell per (flavor-row, agent); each cell's
    parameter leaves, target column, and plan rows vary with both axes,
    while the fit-data block revisits every agent of a row
    (``refetch='on_change'``: the model's traffic is fetch-on-index-
    change, the revisit-aware reading :func:`fit_scan_hbm_bytes`
    commits to). ``scratch`` is the in-cell live set: one gradient +
    one updated-parameter copy of the cell's leaves, plus the two
    ``(1, n_batches)`` epoch-0 loss/count rows.
    """
    leaves = jax.tree.leaves(params_rows)
    R, N = leaves[0].shape[:2]
    cap = x_rows.shape[1]
    n_batches = math.ceil(cap / schedule.batch_size)
    plan_shape = (schedule.epochs, n_batches, schedule.batch_size)

    inputs = []
    for i, leaf in enumerate(leaves):
        nd = leaf.ndim - 2
        inputs.append(
            BlockOperand(
                f"param_leaf_{i}",
                (1, 1) + tuple(leaf.shape[2:]),
                str(np.dtype(leaf.dtype)),
                (True, True),
                index_map=lambda r, n, nd=nd: (r, n) + (0,) * nd,
            )
        )
    inputs.append(
        BlockOperand(
            "x_rows",
            (1,) + tuple(x_rows.shape[1:]),
            str(np.dtype(x_rows.dtype)),
            (True, False),
            index_map=lambda r, n: (r, 0, 0),
        )
    )
    inputs.append(
        BlockOperand(
            "targets_rows",
            (1, 1) + tuple(targets_rows.shape[2:]),
            str(np.dtype(targets_rows.dtype)),
            (True, True),
            index_map=lambda r, n: (r, n, 0, 0),
        )
    )
    for name, dt in (("plan_idx", "int32"), ("plan_bvalid", "float32")):
        inputs.append(
            BlockOperand(
                name,
                (1, 1) + plan_shape,
                dt,
                (True, True),
                index_map=lambda r, n: (r, n, 0, 0, 0),
            )
        )
    outputs = [
        BlockOperand(
            f"fitted_leaf_{i}",
            op.block_shape,
            op.dtype,
            (True, True),
            index_map=op.index_map,
        )
        for i, op in enumerate(inputs[: len(leaves)])
    ]
    outputs.append(
        BlockOperand(
            "first_epoch_loss",
            (1, 1),
            "float32",
            (True, True),
            index_map=lambda r, n: (r, n),
        )
    )
    cell_bytes = sum(
        int(math.prod(l.shape[2:])) * np.dtype(l.dtype).itemsize
        for l in leaves
    )
    scratch = (
        BlockOperand(
            "grad_update_live_set", (2 * cell_bytes,), "uint8", (False, False)
        ),
        BlockOperand("loss_rows", (2, n_batches), "float32", (False, False)),
    )
    return KernelPlan(
        name="fit_scan",
        grid=(R, N),
        inputs=tuple(inputs),
        outputs=tuple(outputs),
        scratch=scratch,
        refetch="on_change",
    )


def pallas_fit_scan(
    keys,
    params_rows,
    forward,
    x_rows: jnp.ndarray,
    targets_rows: jnp.ndarray,
    mask: jnp.ndarray,
    schedule: FitSchedule,
    lr: float,
    clip: float = 0.0,
    *,
    interpret: bool = False,
):
    """Drop-in Pallas twin of :func:`rcmarl_tpu.ops.fit.fused_fit_scan`
    (same arguments + ``interpret``): one grid cell per (flavor-row,
    agent), parameters VMEM-resident across the whole schedule.

    Returns ``(fitted rows, (R, N) first-epoch losses)`` — fitted rows
    leaf-for-leaf the XLA scan's, losses allclose (module docstring).
    """
    R, N = keys.shape[0], keys.shape[1]
    cap = x_rows.shape[1]
    n_batches = math.ceil(cap / schedule.batch_size)
    idx, bvalid = _fit_plans(keys, mask, schedule, n_batches)
    targets_rows = jax.lax.stop_gradient(targets_rows)

    leaves, treedef = jax.tree.flatten(params_rows)
    n_leaves = len(leaves)

    # the pl.BlockSpec lists are BUILT from the introspectable plan —
    # one derivation for launch and lint alike
    launch_plan = kernel_plan(params_rows, x_rows, targets_rows, schedule)
    in_specs = [
        pl.BlockSpec(op.block_shape, op.index_map)
        for op in launch_plan.inputs
    ]
    out_specs = [
        pl.BlockSpec(op.block_shape, op.index_map)
        for op in launch_plan.outputs
    ]
    out_shape = [
        jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves
    ] + [jax.ShapeDtypeStruct((R, N), jnp.float32)]

    kernel = functools.partial(
        _fit_kernel,
        treedef=treedef,
        n_leaves=n_leaves,
        forward=forward,
        lr=lr,
        epochs=schedule.epochs,
        n_batches=n_batches,
        shuffle=schedule.shuffle,
        clip=clip,
    )
    outs = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shape),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        grid=launch_plan.grid,
        interpret=interpret,
    )(*leaves, x_rows, targets_rows, idx, bvalid)
    fitted = jax.tree.unflatten(treedef, list(outs[:-1]))
    return fitted, outs[-1]


def fit_scan_hbm_bytes(
    params_rows, x_rows, targets_rows, schedule: FitSchedule, resident: bool
) -> float:
    """The analytic parameter-traffic model behind the ``fit_scan``
    ledger rows: an XLA ``lax.scan`` round-trips its carry — the full
    stacked parameter block — through HBM every step
    (``resident=False``: ``2 * steps * P`` bytes), while the kernel
    reads and writes it once per cell (``resident=True``: ``2 * P``).
    Fit data, targets, and the shuffle plans are counted once for both
    arms. Deterministic shape arithmetic, tagged ``bytes_model:
    'analytic-scan-carry'`` on the rows — a model of the structural
    difference, not a compiled measurement.
    """
    cap = x_rows.shape[1]
    n_batches = math.ceil(cap / schedule.batch_size)
    steps = schedule.epochs * n_batches
    p_bytes = sum(
        l.size * l.dtype.itemsize for l in jax.tree.leaves(params_rows)
    )
    R, N = jax.tree.leaves(params_rows)[0].shape[:2]
    plan_bytes = 2 * R * N * schedule.epochs * n_batches * schedule.batch_size * 4
    data_bytes = x_rows.size * 4 + targets_rows.size * 4 + plan_bytes
    carries = 2.0 if resident else 2.0 * steps
    return carries * p_bytes + data_bytes
