"""One-kernel serving: forward + key-derivation + sample in a single
VMEM-resident Pallas program.

PR 10's ``--consensus_micro``-style measurement showed the serving hot
path is NOT the 20-wide actor forward: per-request ``fold_in`` key
derivation plus the categorical sample dominates service time (greedy
runs 2.5x sample throughput at B=4096). The XLA arm materializes the
``(B, N, 2)`` uint32 key block and the ``(B, N, A)`` probability block
in HBM between launches-worth of fusion boundaries; this kernel keeps a
batch tile resident in VMEM across the whole chain — the row-stacked
actor forward (the exact :func:`rcmarl_tpu.serve.engine.batch_probs`
vmap), an in-kernel threefry2x32 ``fold_in(fold_in(key, b), n)`` per
(request, agent), and the gumbel-argmax categorical draw — writing only
actions and probabilities back. ``AUDIT.jsonl``'s
``serve_path[pallas_fused]`` vs ``serve_path[xla_chain]`` rows carry
the traffic claim as a CI-gated ledger fact
(:func:`rcmarl_tpu.lint.cost.fused_serve_cost_rows`, the PR-13 gate
discipline).

Bitwise contract (tests/test_pallas_serve.py): probabilities AND action
streams are pinned BITWISE against the XLA
:func:`~rcmarl_tpu.serve.engine.serve_block` arm across the
{sample, greedy} x {f32, bf16-dot} x {solo, fleet-stacked} matrix.
Two facts make that possible:

- The forward is the SAME vmapped :func:`rcmarl_tpu.models.mlp.actor_probs`
  op sequence the XLA arm runs (one implementation to drift, the
  ``batch_probs`` rule); batch tiling is safe because every request row
  is computed independently.
- The sampling chain is integer-exact: threefry2x32 is pure ARX on
  uint32 (reimplemented here op-for-op against jax's lowering — Pallas
  cannot call the ``threefry2x32`` primitive), and the uniform→gumbel
  mantissa chain mirrors ``jax.random.uniform``/``gumbel`` bit for bit,
  so ``argmax(gumbel + log(probs))`` selects the identical action.

The fleet arm (:func:`fused_fleet_block`) mirrors
:func:`rcmarl_tpu.serve.fleet.fleet_block` the same way: per-member
probabilities via the one vmapped core, the route gather as DATA, the
solo key discipline — so fleet serving of one member stays bitwise its
solo serve.

``serve_impl`` policy (:func:`resolve_serve_impl`, the netstack/fitstack
``auto`` tradition): ``'auto'`` resolves to the fused kernel on TPU —
where the AUDIT.jsonl bytes ledger shows the reduced HBM traffic — and
to the XLA arm elsewhere (on CPU the kernel only runs interpreted;
there is no win to select). ``'pallas_interpret'`` is the explicit
CPU-test arm.

Real lowering rides the queued TPU session (scripts/tpu_session.sh,
step 12); on this host the kernel runs in interpreter mode, and the
lint cost arm records real-Pallas-on-CPU compiles as notes, never
passes.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from rcmarl_tpu.config import Config
from rcmarl_tpu.models.mlp import MLPParams, actor_probs, pad_features
from rcmarl_tpu.ops.dma_model import (
    BlockOperand,
    KernelPlan,
    serve_model_bytes,
    tile_rows,
)

#: The serve implementation arms. 'auto' is the measured policy
#: (:func:`resolve_serve_impl`); 'pallas_interpret' is the CPU test arm
#: (interpreter mode — the house pattern for kernels whose real
#: lowering rides the queued TPU session).
SERVE_IMPLS = ("auto", "xla", "pallas", "pallas_interpret")

#: Default batch rows per grid step. 128 keeps a tile's activations +
#: the broadcast actor block comfortably VMEM-resident at the published
#: reference shape (5 agents, 20-wide nets); the host wrapper shrinks
#: it to a divisor of B so no request row is ever padded (padding a
#: batch row would perturb nothing — rows are independent — but an
#: exact grid keeps the DMA arithmetic exact too).
_DEFAULT_BLOCK_B = 128


def resolve_serve_impl(impl: str = "auto", platform: Optional[str] = None) -> str:
    """The measured ``serve_impl='auto'`` policy (netstack/fitstack
    tradition): the fused kernel where its bytes-ledger win is real —
    TPU — and the XLA arm elsewhere (on CPU the kernel only runs
    interpreted, which is a correctness arm, not a fast one).
    Explicit arms pass through unchanged."""
    if impl not in SERVE_IMPLS:
        raise ValueError(f"serve_impl={impl!r}: expected one of {SERVE_IMPLS}")
    if impl != "auto":
        return impl
    if platform is None:
        platform = jax.devices()[0].platform
    return "pallas" if platform == "tpu" else "xla"


# --------------------------------------------------------------------------
# In-kernel threefry2x32 — op-for-op against jax's lowering
# --------------------------------------------------------------------------
#
# Pallas kernels cannot bind the ``threefry2x32`` primitive, so the
# block cipher is restated as the pure ARX chain jax lowers it to
# (rotation schedule and the five key-injection rounds copied from
# jax._src.prng's threefry2x32 lowering; verified bit-exact against
# jax.random.fold_in / categorical before this module was written).
# Everything below is uint32 adds, xors, and shifts — integer-exact on
# every backend, immune to the fusion-context rounding that rules
# floating-point reassociation out of bitwise contracts.

_ROTATIONS = ((13, 15, 26, 6), (17, 29, 16, 24))
_PARITY = np.uint32(0x1BD11BDA)


def _rotl(x: jnp.ndarray, d: int) -> jnp.ndarray:
    return (x << np.uint32(d)) | (x >> np.uint32(32 - d))


def _threefry2x32(
    k0: jnp.ndarray, k1: jnp.ndarray, x0: jnp.ndarray, x1: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One threefry2x32 block: key (k0, k1), counter (x0, x1) -> two
    uint32 output words. Elementwise — all operands broadcast."""
    ks = (k0, k1, k0 ^ k1 ^ _PARITY)
    x0 = x0 + ks[0]
    x1 = x1 + ks[1]
    # five groups of four ARX rounds, key injection after each group
    injections = (
        (ks[1], ks[2], 1),
        (ks[2], ks[0], 2),
        (ks[0], ks[1], 3),
        (ks[1], ks[2], 4),
        (ks[2], ks[0], 5),
    )
    for g, (i0, i1, c) in enumerate(injections):
        for r in _ROTATIONS[g % 2]:
            x0 = x0 + x1
            x1 = _rotl(x1, r)
            x1 = x0 ^ x1
        x0 = x0 + i0
        x1 = x1 + i1 + np.uint32(c)
    return x0, x1


def _fold_in(
    k0: jnp.ndarray, k1: jnp.ndarray, data: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``jax.random.fold_in`` on raw key words: threefry with counter
    ``(0, uint32(data))`` (jax's ``threefry_seed`` puts the 32-bit data
    word in the low half, zero in the high)."""
    zero = jnp.zeros_like(data, dtype=jnp.uint32)
    return _threefry2x32(k0, k1, zero, data.astype(jnp.uint32))


_TINY = np.float32(np.finfo(np.float32).tiny)


def _gumbel_bits(
    k0: jnp.ndarray, k1: jnp.ndarray, n_actions: int
) -> jnp.ndarray:
    """The per-key gumbel row ``jax.random.categorical`` would draw:
    ``random_bits(key, (A,))`` via threefry over ``iota(uint32, A)``
    (odd sizes zero-padded then trimmed, exactly jax's split), the
    mantissa-fill uniform on ``[tiny, 1)``, and ``-log(-log(u))``.

    ``k0``/``k1`` are ``(..., 1)`` so the static counter rows broadcast;
    returns ``(..., n_actions)`` f32.
    """
    odd = n_actions % 2
    counts = jax.lax.iota(jnp.uint32, n_actions + odd)
    half = (n_actions + odd) // 2
    x0, x1 = counts[:half], counts[half:]
    if odd:
        # jax pads an odd counter row with a ZERO word before splitting
        x1 = x1.at[-1].set(np.uint32(0))
    o0, o1 = _threefry2x32(k0, k1, x0, x1)
    bits = jnp.concatenate([o0, o1], axis=-1)[..., :n_actions]
    # jax.random.uniform(minval=tiny, maxval=1.0) for f32: 23 mantissa
    # bits ORed into the [1, 2) exponent, minus 1, affine to the range
    mantissa = (bits >> np.uint32(9)) | np.uint32(0x3F800000)
    floats = jax.lax.bitcast_convert_type(mantissa, jnp.float32) - 1.0
    u = jnp.maximum(_TINY, floats * (1.0 - _TINY) + _TINY)
    return -jnp.log(-jnp.log(u))


def _sample_tile(
    k0: jnp.ndarray,
    k1: jnp.ndarray,
    probs: jnp.ndarray,
    base_b: jnp.ndarray,
) -> jnp.ndarray:
    """The in-kernel twin of the XLA sample arm for one batch tile:
    per-(request, agent) keys ``fold_in(fold_in(key, b), n)`` with the
    GLOBAL request index b (``base_b`` + tile row), then
    ``argmax(gumbel + log(probs))`` — bitwise
    ``jax.random.categorical(keys, jnp.log(probs))``."""
    bb, n_agents, n_actions = probs.shape
    b_idx = base_b + jax.lax.iota(jnp.uint32, bb)
    kb0, kb1 = _fold_in(k0, k1, b_idx)  # (bb,)
    logits = jnp.log(probs)
    cols = []
    for n in range(n_agents):
        kn0, kn1 = _fold_in(kb0, kb1, jnp.full((bb,), n, jnp.uint32))
        g = _gumbel_bits(kn0[:, None], kn1[:, None], n_actions)
        cols.append(jnp.argmax(g + logits[:, n, :], axis=-1))
    return jnp.stack(cols, axis=1).astype(jnp.int32)


# --------------------------------------------------------------------------
# The kernel
# --------------------------------------------------------------------------


def _tile_probs(block: MLPParams, x: jnp.ndarray, alpha, dtype) -> jnp.ndarray:
    """The one batched policy core on a tile — textually
    :func:`rcmarl_tpu.serve.engine.batch_probs`'s vmap (row n = agent
    n), restated here only because the kernel cannot import the engine
    (the engine imports this module for the arm dispatch)."""
    return jax.vmap(
        lambda p, xn: actor_probs(p, xn, alpha, dtype),
        in_axes=(0, 1),
        out_axes=1,
    )(block, x)


def _serve_kernel(
    *refs,
    treedef,
    n_leaves: int,
    mode: str,
    alpha,
    dtype,
    block_b: int,
    fleet: bool,
):
    """One ``(block_b, N, W)`` batch tile: forward, key derivation, and
    sample, VMEM-resident end to end — only actions + probabilities
    leave the tile."""
    it = iter(refs)
    leaves = [next(it)[...] for _ in range(n_leaves)]
    x = next(it)[...]  # (block_b, N, W)
    route = next(it)[...] if fleet else None
    key_ref = next(it) if mode == "sample" else None
    actions_ref = next(it)
    probs_ref = next(it)

    block = jax.tree.unflatten(treedef, leaves)
    if fleet:
        # the fleet_block op sequence: the one solo core vmapped over
        # the fleet axis, routing as a gather on DATA
        probs_all = jax.vmap(lambda blk: _tile_probs(blk, x, alpha, dtype))(
            block
        )  # (F, block_b, N, A)
        probs = probs_all[route, jnp.arange(x.shape[0])]
    else:
        probs = _tile_probs(block, x, alpha, dtype)

    if mode == "greedy":
        actions = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    else:
        base_b = (pl.program_id(0) * block_b).astype(jnp.uint32)
        actions = _sample_tile(key_ref[0, 0], key_ref[0, 1], probs, base_b)
    actions_ref[...] = actions
    probs_ref[...] = probs


def _tile_rows(batch: int, block_b: int) -> int:
    """The largest tile height <= ``block_b`` dividing ``batch`` (an
    exact grid — no padded request rows, exact DMA arithmetic). The
    arithmetic lives in :func:`rcmarl_tpu.ops.dma_model.tile_rows`, the
    consolidated grid-arithmetic core."""
    return tile_rows(batch, block_b)


def kernel_plan(
    block: MLPParams,
    batch: int,
    n_agents: int,
    *,
    mode: str = "sample",
    fleet: bool = False,
    block_b: int = _DEFAULT_BLOCK_B,
) -> KernelPlan:
    """The serve launch's static BlockSpec plan — the ONE derivation
    both :func:`_fused_serve` (which builds its ``pl.BlockSpec`` lists
    from these operands) and ``lint --kernels`` consume. ``block``
    takes real arrays or ``jax.ShapeDtypeStruct`` leaves (only
    shapes/dtypes are read), so the lint arm prices serve cells via
    ``jax.eval_shape`` of the stacked init without allocating a fleet.

    Operands in launch order: the broadcast actor/fleet leaves (full
    shape, re-DMAd every grid step — ``refetch='always'``, the
    conservative reading the committed model commits to), the
    ``(bb, N, W)`` observation tile, ``[route]`` (fleet), ``[key
    words]`` (sample). ``scratch`` is the tile's live activation set
    (two ping-pong layers at the widest dim) plus, on the fleet path,
    the all-members probability block the route gathers from.
    """
    leaves = jax.tree.leaves(block)
    width = leaves[0].shape[-2]
    n_actions = block[-1][1].shape[-1]
    bb = tile_rows(batch, block_b)
    grid = (batch // bb,)

    inputs = []
    for i, l in enumerate(leaves):
        inputs.append(
            BlockOperand(
                f"actor_leaf_{i}",
                tuple(l.shape),
                str(np.dtype(l.dtype)),
                (False,),
                index_map=functools.partial(
                    lambda nd, i: (0,) * nd, l.ndim
                ),
            )
        )
    inputs.append(
        BlockOperand(
            "obs_tile",
            (bb, n_agents, width),
            "float32",
            (True,),
            tiled_dims=(0,),
            index_map=lambda i: (i, 0, 0),
        )
    )
    if fleet:
        inputs.append(
            BlockOperand(
                "route",
                (bb,),
                "int32",
                (True,),
                tiled_dims=(0,),
                index_map=lambda i: (i,),
            )
        )
    if mode == "sample":
        inputs.append(
            BlockOperand(
                "key_words",
                (1, 2),
                "uint32",
                (False,),
                index_map=lambda i: (0, 0),
            )
        )
    outputs = (
        BlockOperand(
            "actions",
            (bb, n_agents),
            "int32",
            (True,),
            tiled_dims=(0,),
            index_map=lambda i: (i, 0),
        ),
        BlockOperand(
            "probs",
            (bb, n_agents, n_actions),
            "float32",
            (True,),
            tiled_dims=(0,),
            index_map=lambda i: (i, 0, 0),
        ),
    )
    max_width = max(
        [width, n_actions] + [int(l.shape[-1]) for l in leaves]
    )
    scratch = [
        BlockOperand(
            "activations_live_set",
            (2, bb, n_agents, max_width),
            "float32",
            (False,),
        )
    ]
    if fleet:
        n_members = leaves[0].shape[0]
        scratch.append(
            BlockOperand(
                "fleet_probs_all",
                (n_members, bb, n_agents, n_actions),
                "float32",
                (False,),
            )
        )
    return KernelPlan(
        name="fused_fleet" if fleet else "fused_serve",
        grid=grid,
        inputs=tuple(inputs),
        outputs=outputs,
        scratch=tuple(scratch),
        refetch="always",
    )


def _key_words(key: jax.Array) -> jnp.ndarray:
    """The raw (1, 2) uint32 key words of a legacy or typed PRNG key."""
    kd = key if jnp.issubdtype(key.dtype, jnp.integer) else jax.random.key_data(key)
    return kd.astype(jnp.uint32).reshape(1, 2)


def _fused_serve(
    cfg: Config,
    block: MLPParams,
    obs: jnp.ndarray,
    key: Optional[jax.Array],
    route: Optional[jnp.ndarray],
    mode: str,
    block_b: int,
    interpret: bool,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Shared host wrapper behind :func:`fused_serve_block` /
    :func:`fused_fleet_block`: feature padding (host-side, exactly the
    XLA arm's), the exact batch grid, broadcast BlockSpecs for the
    actor block, and the Pallas launch."""
    from rcmarl_tpu.serve.engine import SERVE_MODES

    if mode not in SERVE_MODES:
        raise ValueError(f"mode={mode!r}: expected one of {SERVE_MODES}")
    fleet = route is not None
    B, N = obs.shape[0], obs.shape[1]
    width_leaf = block[0][0]
    x = pad_features(obs, width_leaf.shape[-2])
    n_actions = block[-1][1].shape[-1]
    bb = _tile_rows(B, block_b)
    grid = (B // bb,)

    leaves, treedef = jax.tree.flatten(block)
    # the pl.BlockSpec lists are BUILT from the introspectable plan —
    # one derivation for launch and lint alike
    launch_plan = kernel_plan(
        block, B, N, mode=mode, fleet=fleet, block_b=block_b
    )
    in_specs = [
        pl.BlockSpec(op.block_shape, op.index_map)
        for op in launch_plan.inputs
    ]
    inputs = list(leaves)
    inputs.append(x)
    if fleet:
        inputs.append(route.astype(jnp.int32))
    if mode == "sample":
        inputs.append(_key_words(key))

    kernel = functools.partial(
        _serve_kernel,
        treedef=treedef,
        n_leaves=len(leaves),
        mode=mode,
        alpha=cfg.leaky_alpha,
        dtype=cfg.dot_dtype,
        block_b=bb,
        fleet=fleet,
    )
    actions, probs = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, N), jnp.int32),
            jax.ShapeDtypeStruct((B, N, n_actions), jnp.float32),
        ),
        in_specs=in_specs,
        out_specs=tuple(
            pl.BlockSpec(op.block_shape, op.index_map)
            for op in launch_plan.outputs
        ),
        grid=launch_plan.grid,
        interpret=interpret,
    )(*inputs)
    return actions, probs


def _fused_serve_block(
    cfg: Config,
    block: MLPParams,
    obs: jnp.ndarray,
    key: jax.Array,
    mode: str = "sample",
    block_b: int = _DEFAULT_BLOCK_B,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The fused serving program: ``(B, N, obs_dim)`` observations ->
    ``(actions, probs)`` in ONE Pallas launch, bitwise the XLA
    :func:`~rcmarl_tpu.serve.engine.serve_block` arm (module
    docstring). ``cfg``/``mode``/``block_b``/``interpret`` are static —
    one program per arm, zero steady-state recompiles across batches
    and hot-swaps (the retrace-audited contract)."""
    return _fused_serve(cfg, block, obs, key, None, mode, block_b, interpret)


#: The jitted fused serving entry point (registered in
#: ``utils/profiling.py:jit_entry_points`` — retrace/cost audited like
#: every hot path). Block, observations, and key are DATA.
fused_serve_block = functools.partial(
    jax.jit,
    static_argnums=0,
    static_argnames=("mode", "block_b", "interpret"),
)(_fused_serve_block)


def _fused_fleet_block(
    cfg: Config,
    fleet: MLPParams,
    obs: jnp.ndarray,
    key: jax.Array,
    route: jnp.ndarray,
    mode: str = "sample",
    block_b: int = _DEFAULT_BLOCK_B,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The fused fleet serving program — the
    :func:`~rcmarl_tpu.serve.fleet.fleet_block` twin: F fleet-stacked
    members, per-request routing as DATA, the solo key discipline, one
    Pallas launch. Row b is member ``route[b]``'s output, bitwise its
    solo :func:`fused_serve_block` row (and therefore bitwise the solo
    XLA ``serve_block`` row — the per-member parity contract)."""
    return _fused_serve(cfg, fleet, obs, key, route, mode, block_b, interpret)


#: The jitted fused fleet entry point. Fleet, observations, key, AND
#: the route are data, so re-routes and member hot-swaps re-dispatch
#: the SAME executable.
fused_fleet_block = functools.partial(
    jax.jit,
    static_argnums=0,
    static_argnames=("mode", "block_b", "interpret"),
)(_fused_fleet_block)


# --------------------------------------------------------------------------
# Cost model — the kernel's exact DMA arithmetic
# --------------------------------------------------------------------------


def fused_serve_dma_bytes(
    cfg: Config,
    batch: int,
    mode: str = "sample",
    n_members: int = 0,
    block_b: int = _DEFAULT_BLOCK_B,
) -> float:
    """The kernel's exact HBM traffic in bytes, from its BlockSpecs:
    the observation tile is DMAd once per grid step (once per request
    row total), the broadcast actor block + key words once PER GRID
    STEP (the conservative reading, as the consensus kernel counts its
    mask planes), and the action/probability tiles written once. What
    never touches HBM at all — the ``(B, N, 2)`` key block and any
    probability re-read — is exactly the fused win the
    ``serve_path[pallas_fused]`` ledger row claims. Deterministic
    arithmetic, not an estimate (``bytes_model:
    'pallas-blockspec-dma'``). The closed form lives in
    :func:`rcmarl_tpu.ops.dma_model.serve_model_bytes` (the
    consolidated grid-arithmetic core); ``lint --kernels`` re-derives
    it from :func:`kernel_plan` and gates the drift."""
    return serve_model_bytes(
        cfg.n_agents,
        cfg.obs_dim,
        tuple(cfg.hidden),
        cfg.n_actions,
        batch,
        mode=mode,
        n_members=n_members,
        block_b=block_b,
    )
