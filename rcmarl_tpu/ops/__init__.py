from rcmarl_tpu.ops.aggregation import (  # noqa: F401
    ravel_neighbor_tree,
    resilient_aggregate,
    resilient_aggregate_tree,
    resolve_impl,
)
from rcmarl_tpu.ops.fit import (  # noqa: F401
    fit_full_batch,
    fit_minibatch,
    valid_first_shuffle,
)
from rcmarl_tpu.ops.losses import weighted_mse, weighted_sparse_ce  # noqa: F401
from rcmarl_tpu.ops.optim import (  # noqa: F401
    AdamState,
    adam_init,
    adam_update,
    sgd_update,
)
