"""Resilient (trimmed clip-and-average) aggregation — the hot kernel.

Rebuild of the reference's ``_resilient_aggregation``
(``resilient_CAC_agents.py:42-58``), the single function used for BOTH
per-parameter hidden-layer consensus and per-sample projected-estimate
consensus (SURVEY.md §3.4). Semantics, with own value at neighbor index 0:

    sorted = sort(values, axis=0)
    lower  = min(sorted[H], own)
    upper  = max(sorted[n_in - H - 1], own)
    out    = mean(clip(values, lower, upper), axis=0)

Values are *clipped into* [lower, upper], not discarded — a clipped mean
(~trimmed mean) guaranteed to keep the agent's own value inside the
bounds. H=0 degenerates to the plain mean.

TPU shape: one fused ``sort -> clip -> mean`` over a small leading
neighbor axis, batched over everything else (all parameters of a whole
pytree in one call; all samples of a projection batch in another), and
vmapped over the agent axis by the consensus layer. XLA lowers the tiny
fixed-size sort to a vectorized sorting network; no Pallas needed at
reference scale (SURVEY.md §7 hard part (e)).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def resilient_aggregate(
    values: jnp.ndarray, H: int, impl: str = "xla"
) -> jnp.ndarray:
    """Clip-and-average over the leading neighbor axis.

    Args:
      values: (n_in, ...) stacked neighbor values, own value at index 0.
      H: max number of adversaries tolerated in the neighborhood (static).
      impl: 'xla' (default), 'pallas' (fused TPU kernel,
        :mod:`rcmarl_tpu.ops.pallas_aggregation`), or 'pallas_interpret'.

    Returns:
      (...) aggregated values.
    """
    if impl != "xla":
        from rcmarl_tpu.ops.pallas_aggregation import fused_resilient_aggregate

        return fused_resilient_aggregate(
            values, H, interpret=impl == "pallas_interpret"
        )
    n_in = values.shape[0]
    if not 0 <= 2 * H <= n_in - 1:
        raise ValueError(f"H={H} invalid for n_in={n_in}: need 0 <= 2H <= n_in-1")
    own = values[0]
    if H == 0:
        # sort/clip are the identity w.r.t. the mean when H == 0
        return jnp.mean(values, axis=0)
    sorted_vals = jnp.sort(values, axis=0)
    lower = jnp.minimum(sorted_vals[H], own)
    upper = jnp.maximum(sorted_vals[n_in - H - 1], own)
    return jnp.mean(jnp.clip(values, lower, upper), axis=0)


def resilient_aggregate_tree(tree, H: int, impl: str = "xla"):
    """Apply :func:`resilient_aggregate` to every leaf of a pytree whose
    leaves carry a leading neighbor axis (e.g. a gathered parameter
    pytree with leaves (n_in, ...)). With a pallas impl the whole tree is
    flattened into ONE fused kernel launch instead of one sort per leaf."""
    if impl != "xla":
        from rcmarl_tpu.ops.pallas_aggregation import (
            fused_resilient_aggregate_tree,
        )

        return fused_resilient_aggregate_tree(
            tree, H, interpret=impl == "pallas_interpret"
        )
    return jax.tree.map(lambda v: resilient_aggregate(v, H), tree)
