"""Resilient (trimmed clip-and-average) aggregation — the hot kernel.

Rebuild of the reference's ``_resilient_aggregation``
(``resilient_CAC_agents.py:42-58``), the single function used for BOTH
per-parameter hidden-layer consensus and per-sample projected-estimate
consensus (SURVEY.md §3.4). Semantics, with own value at neighbor index 0:

    sorted = sort(values, axis=0)
    lower  = min(sorted[H], own)
    upper  = max(sorted[n_in - H - 1], own)
    out    = mean(clip(values, lower, upper), axis=0)

Values are *clipped into* [lower, upper], not discarded — a clipped mean
(~trimmed mean) guaranteed to keep the agent's own value inside the
bounds. H=0 degenerates to the plain mean.

The aggregation only ever reads TWO order statistics out of the sort —
``sorted[H]`` (the (H+1)-th smallest) and ``sorted[n_in-H-1]`` (the
(H+1)-th largest) — so the default implementation here computes exactly
those via **log-depth tournament selection** (``impl='xla'``): the
stacked neighbor axis is split into power-of-two chunks, each chunk is
sorted by a bitonic network of whole-block ``jnp.minimum``/``maximum``
ops on the STACKED arrays (strided axis-0 slices, never per-row
unstacking), and the sorted k-prefixes/suffixes are pairwise-merged up a
binary tree — ⌈log₂n⌉ merge levels of O(k) block ops
(:func:`_k_smallest` / :func:`_k_largest`). The bounds are **bitwise
identical** to the sort's (both produce exact input values), so the two
paths are interchangeable; ``impl='xla_sort'`` keeps the full sort as
the measured-comparison arm.

History of the selection strategy (PERF.md "sort vs select"): the PR-1
implementation streamed 2(H+1) running min/max registers over the n_in
UNSTACKED rows — O(k·n) compare-exchanges, measurably faster than the
sort up to n_in=16 but 0.64x at n_in=64, because inside the vmapped
consensus layer XLA materialized all 64 unstacked row slices the
register chain read. The tournament issues only whole-block ops on the
stacked array, erasing that regression (measured: the n64_full epoch
now wins vs the sort — see PERF.md); the register helpers remain in this
module because the Pallas kernel still uses them (inside a kernel the
rows live in VMEM registers and the slicing cost does not exist).
``lax.top_k`` was measured and rejected earlier: on CPU the TopK custom
call plus the neighbor-axis transpose ran ~2x SLOWER than the sort.

TPU shape: one fused ``select -> clip -> mean`` over a small leading
neighbor axis, batched over everything else — all parameters of a whole
pytree ride in ONE flattened (n_in, P_total) launch
(:func:`resilient_aggregate_tree` ravels every leaf, the layout the
Pallas path pioneered), and the consensus layer vmaps the whole thing
over the agent axis. At scale-out the selection runs inside the Pallas
kernel's registers (:mod:`rcmarl_tpu.ops.pallas_aggregation`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from rcmarl_tpu.config import CONSENSUS_IMPLS

#: Measured TPU crossover (BENCH_SCALING.jsonl, v5e), keyed on the total
#: gathered-block volume ``n_in * n_agents`` — NOT on n_in alone: at
#: identical n_in=5 the winner flips with the agent count (n16_ring: xla
#: 1.67x faster at volume 80; n64_ring: pallas 1.64x faster at volume
#: 320), so the deciding variable is how much data one fused launch
#: processes across the vmapped agent axis. Measured xla wins at volumes
#: {20, 80}; measured pallas wins at {256, 320, 4096}; the threshold
#: sits at the smallest measured pallas win (n16_full, 1.09x). Parameter
#: volume per agent is held constant across these rows (the reference's
#: 20-20 nets), so P is deliberately not in the key; refit if a
#: measured row at a different architecture contradicts it. The rows
#: behind this value predate the selection impls (both arms ran full
#: sorts); re-run ``bench --impl pallas pallas_sort xla xla_sort`` on
#: TPU to refit it on selection-vs-selection measurements.
PALLAS_CROSSOVER_VOLUME = 256

#: Measured CPU sort-vs-select crossover (PERF.md "sort vs select",
#: 2026-08-04 tournament rows): with log-depth tournament selection the
#: epoch-level measurement favors selection at EVERY measured n_in —
#: ref5_ring, n16_full, and n64_full all win, including the dense
#: n_in=64 shape where the PR-1 register chain lost 0.64x to its
#: unstacked-row-slice traffic. ``None`` therefore means "no upper
#: bound: selection always"; set a finite n_in to re-introduce a
#: sort-above-threshold crossover if a future host/backend measures one
#: (the comparison arm ``impl='xla_sort'`` exists exactly for that
#: refit).
SELECT_MAX_N_IN = None


#: The six concrete backend modes of the aggregation contract, as
#: (name, kwargs-recipe) rows: every cross-backend bitwise pin (tests)
#: and the purity/dtype audit (rcmarl_tpu.lint.backends) iterate THIS
#: table instead of hand-maintaining the list, so a seventh backend
#: cannot ship unaudited. ``masked``/``traced_h`` are recipe flags the
#: caller expands (a padded-graph validity mask / a traced H scalar);
#: the Pallas arms audit in interpreter-traceable form on any host.
AUDIT_BACKEND_MODES = (
    ("xla", {"impl": "xla"}),
    ("xla_sort", {"impl": "xla_sort"}),
    ("masked", {"impl": "xla", "masked": True}),
    ("traced_h", {"impl": "xla", "traced_h": True}),
    ("pallas_select", {"impl": "pallas_interpret"}),
    ("pallas_sort", {"impl": "pallas_sort"}),
    # the one-kernel-epoch name, audited in its interpreter-traceable
    # form: at the LEAF level it aliases the selection kernel (the
    # fused gather+fault chain is an epoch-level property audited via
    # the consensus_block entry point), but registering the name here
    # keeps "a new backend cannot ship unaudited" literally true.
    ("pallas_fused", {"impl": "pallas_fused_interpret"}),
)


def _selection_favored(n_in: int, H: int) -> bool:
    """Measured rule for where tournament selection beats the full sort
    at epoch granularity (see :data:`SELECT_MAX_N_IN`; ``H`` stays in
    the signature because the policy is keyed on (H, n_in, volume) —
    the measured tournament rows show neither H nor n_in flips the
    verdict, so both are currently unused)."""
    return SELECT_MAX_N_IN is None or n_in <= SELECT_MAX_N_IN


def _check_impl(impl: str) -> None:
    """Reject unknown impl strings up front: anything not in
    CONSENSUS_IMPLS would otherwise be routed to the Pallas kernel with
    interpret=False and die in lowering with an obscure error."""
    if impl not in CONSENSUS_IMPLS:
        raise ValueError(
            f"unknown consensus impl {impl!r}; expected one of {CONSENSUS_IMPLS}"
        )


def resolve_impl(
    impl: str, n_in: int, dtype=None, n_agents: int = 1, H: int | None = None
) -> str:
    """Resolve ``'auto'`` to a concrete implementation at trace time.

    ``'auto'`` is a 3-way measured-crossover policy keyed on
    ``(H, n_in, volume)``:

    1. on a TPU backend with a gathered-block volume ``n_in * n_agents``
       of at least :data:`PALLAS_CROSSOVER_VOLUME`, the fused Pallas
       selection kernel (``'pallas'``) — hardware measurement says the
       kernel wins there regardless of trim strategy;
    2. otherwise the XLA tournament-selection path (``'xla'``) wherever
       the measured CPU epoch rows favor it (:func:`_selection_favored`:
       currently every measured shape);
    3. the full XLA sort (``'xla_sort'``) beyond a measured
       :data:`SELECT_MAX_N_IN` crossover, if one is ever refit (none
       with the tournament strategy — the constant is ``None``).

    f64 inputs never route to the Pallas kernel (it computes in f32, a
    silent precision loss the XLA paths don't have — see
    ``fused_resilient_aggregate``); they take the same xla-vs-xla_sort
    rule. ``n_agents`` is the vmapped agent-axis size of the surrounding
    consensus layer; it must be passed by the caller because inside the
    vmap the agent axis is invisible to the kernel (callers that
    aggregate one agent at a time, like the reference-API twins,
    correctly use the default 1). ``H`` feeds rule 2/3 (currently
    without effect — the measured rows key on n_in alone; ``None`` means
    unknown, e.g. informational callers). Concrete impl strings pass
    through unchanged, so explicit choices always stick.
    """
    _check_impl(impl)
    if impl != "auto":
        return impl
    select = (
        "xla"
        if _selection_favored(n_in, 0 if H is None else H)
        else "xla_sort"
    )
    if dtype is not None and jnp.dtype(dtype) == jnp.float64:
        return select
    if (
        jax.default_backend() == "tpu"
        and n_in * n_agents >= PALLAS_CROSSOVER_VOLUME
    ):
        return "pallas"
    return select


# --------------------------------------------------------------------------
# Selection strategies
# --------------------------------------------------------------------------
#
# Two interchangeable ways to read the k smallest / k largest rows out of
# a stacked (n, ...) block, both bitwise-equal to ``jnp.sort`` (selection
# returns exact input values):
#
# - the REGISTER CHAIN (:func:`_running_extrema`): 2k running min/max
#   registers streamed over the n unstacked rows — O(k·n) vectorized
#   compare-exchanges with only ~2k live arrays. This is what the Pallas
#   kernel runs (rows are VMEM tiles there, unstacking is free), and the
#   seed sorting network doubles as the kernel's 'sort' variant.
# - the TOURNAMENT (:func:`_k_smallest` / :func:`_k_largest`): chunk the
#   STACKED neighbor axis, bitonic-sort within chunks, then pairwise-
#   merge sorted k-prefixes/suffixes up a binary tree — ⌈log₂n⌉ merge
#   levels of O(k) whole-block ops with no unstacked row slices. This is
#   what every XLA path runs: under the consensus layer's vmap, XLA
#   materialized each unstacked slice the register chain read, and at
#   n_in=64 that traffic measurably swamped the saved compare-exchanges
#   (PERF.md "sort vs select").


def _sorting_network(rows):
    """Odd-even transposition sort of a static list of equal-shape arrays.

    n rounds of adjacent compare-exchange; fully unrolled (n is tiny and
    static), so it lowers to pure vectorized min/max with no control
    flow. Used by :func:`_running_extrema`'s seed step and the Pallas
    sort-variant kernel (:mod:`rcmarl_tpu.ops.pallas_aggregation`).
    """
    s = list(rows)
    n = len(s)
    for rnd in range(n):
        for j in range(rnd % 2, n - 1, 2):
            s[j], s[j + 1] = (
                jnp.minimum(s[j], s[j + 1]),
                jnp.maximum(s[j], s[j + 1]),
            )
    return s


def _running_extrema(rows, k: int):
    """The k smallest and k largest of ``rows`` via running registers.

    ``rows`` is a static-length sequence of equal-shape arrays (the
    unstacked neighbor axis). Maintains k ascending "smallest" registers
    and k ascending "largest" registers; each remaining row is inserted
    with a chain of k vectorized compare-exchanges per side — O(k·n)
    ``minimum``/``maximum`` VPU ops total, fully unrolled (k and n are
    tiny and static), no data-dependent control flow, and only ~2k live
    register arrays instead of the n-array block a sort materializes.
    This is the Pallas kernel's strategy (registers/VMEM); the XLA paths
    use the tournament instead (see the section comment).

    Returns ``(small, large)``: lists of length k, each sorted
    ascending. ``small[j]`` is the (j+1)-th smallest of the rows —
    ``sorted[j]`` — and ``large[j]`` is ``sorted[n-k+j]``, so
    ``small[k-1]`` / ``large[0]`` are the k-th smallest / k-th largest.
    All outputs are exact input values (selection, not arithmetic), so
    they are bitwise identical to the corresponding sort entries.
    """
    return _running_small(rows, k), _running_large(rows, k)


def _running_small(rows, k: int):
    """The ``small`` half of :func:`_running_extrema` alone."""
    small = _sorting_network(rows[:k])  # seed: first k rows, sorted
    for x in rows[k:]:
        for j in range(k):  # ascending insert: x carries the displaced max
            small[j], x = jnp.minimum(small[j], x), jnp.maximum(small[j], x)
    return small


def _running_large(rows, k: int):
    """The ``large`` half of :func:`_running_extrema` alone."""
    large = _sorting_network(rows[:k])
    for y in rows[k:]:
        for j in range(k - 1, -1, -1):  # descending: y carries the min
            large[j], y = jnp.maximum(large[j], y), jnp.minimum(large[j], y)
    return large


def _next_pow2(x: int) -> int:
    return 1 if x <= 1 else 1 << (x - 1).bit_length()


def _bitonic_merge(M: jnp.ndarray) -> jnp.ndarray:
    """Sort each length-K bitonic sequence along axis 1 of ``(m, K, ...)``
    ascending. K must be a power of two. The classic half-cleaner
    recursion, expressed as reshape + two whole-block min/max per level:
    compare rows j and j+step within groups of 2·step, halving step —
    log₂K levels, every op touching the full (m, step, ...) block at
    once. Outputs are exact input values (compare-exchange only)."""
    K = M.shape[1]
    step = K // 2
    while step >= 1:
        G = M.reshape(M.shape[0], K // (2 * step), 2, step, *M.shape[2:])
        a, b = G[:, :, 0], G[:, :, 1]
        M = jnp.stack([jnp.minimum(a, b), jnp.maximum(a, b)], axis=2).reshape(
            M.shape
        )
        step //= 2
    return M


def _sort_stacked_chunks(S: jnp.ndarray) -> jnp.ndarray:
    """Sort along axis 1 of ``(m, kp, ...)``, kp a power of two, by
    doubling bitonic merges: adjacent sorted L-runs are joined as
    ``concat(A, reverse(B))`` (a bitonic 2L-sequence) and merged — all
    whole-block ops, vectorized over the m chunks."""
    m, kp = S.shape[0], S.shape[1]
    L = 1
    while L < kp:
        G = S.reshape(m, kp // (2 * L), 2, L, *S.shape[2:])
        A, B = G[:, :, 0], G[:, :, 1][:, :, ::-1]
        M = jnp.concatenate([A, B], axis=2)  # (m, kp//2L, 2L, ...) bitonic
        M = _bitonic_merge(
            M.reshape(m * (kp // (2 * L)), 2 * L, *S.shape[2:])
        )
        S = M.reshape(S.shape)
        L *= 2
    return S


def _tournament(values: jnp.ndarray, k: int, largest: bool) -> jnp.ndarray:
    """Log-depth tournament selection over axis 0 of a STACKED array.

    Pads the neighbor axis to a multiple of ``kp = next_pow2(k)`` with
    ±inf sentinels (which can never displace a surviving value — and
    when the data itself carries ±inf sentinel sinks, a padded inf is
    bitwise identical to a real one), sorts each kp-chunk with
    :func:`_sort_stacked_chunks`, then pairwise-merges sorted
    kp-prefixes (suffixes for ``largest``) up a binary tree: per merge,
    one whole-block ``minimum``/``maximum`` of A against reversed B
    yields the kp extreme values of the union as a bitonic sequence
    (Batcher's half-cleaner lemma), and :func:`_bitonic_merge` re-sorts
    it — ⌈log₂(n/kp)⌉ levels of O(kp) block ops. No unstacked row
    slices anywhere: every op processes half the surviving rows at once.
    """
    n = values.shape[0]
    kp = _next_pow2(k)
    m = -(-n // kp)
    pad = m * kp - n
    if pad:
        fill = jnp.full(
            (pad,) + values.shape[1:],
            -jnp.inf if largest else jnp.inf,
            values.dtype,
        )
        values = jnp.concatenate([values, fill], axis=0)
    S = _sort_stacked_chunks(values.reshape(m, kp, *values.shape[1:]))
    while S.shape[0] > 1:
        carry = None
        if S.shape[0] % 2:
            carry, S = S[-1:], S[:-1]
        A, B = S[0::2], S[1::2][:, ::-1]
        S = _bitonic_merge(jnp.maximum(A, B) if largest else jnp.minimum(A, B))
        if carry is not None:
            S = jnp.concatenate([S, carry], axis=0)
    return S[0][kp - k :] if largest else S[0][:k]


def _k_smallest(values: jnp.ndarray, k: int) -> jnp.ndarray:
    """``sort(values, axis=0)[:k]`` as a stacked (k, ...) array, by
    tournament selection — bitwise identical to the sort prefix."""
    return _tournament(values, k, largest=False)


def _k_largest(values: jnp.ndarray, k: int) -> jnp.ndarray:
    """``sort(values, axis=0)[n-k:]`` as a stacked (k, ...) array
    (ascending), by tournament selection — bitwise identical to the
    sort suffix."""
    return _tournament(values, k, largest=True)


def _trim_bounds(values: jnp.ndarray, H: int, impl: str):
    """The raw trim bounds ``(sorted[H], sorted[n_in-H-1])`` over axis 0,
    by the impl's strategy — bitwise identical between the two."""
    n_in = values.shape[0]
    if impl == "xla_sort":
        sorted_vals = jnp.sort(values, axis=0)
        return sorted_vals[H], sorted_vals[n_in - H - 1]
    return _k_smallest(values, H + 1)[H], _k_largest(values, H + 1)[0]


# --------------------------------------------------------------------------
# Sanitized (non-finite-hardened) aggregation
# --------------------------------------------------------------------------
#
# Transport faults (rcmarl_tpu.faults) and genuinely diverged neighbors
# deliver NaN/±Inf payloads. The plain kernel has NO defense: a single
# NaN poisons the sort/selection bounds and then the clipped mean of
# every backend. ``sanitize=True`` converts non-finite entries into
# per-element EXCLUSIONS via the same ±inf-sentinel trick the masked
# (padded-graph) path already uses — non-finite values sink to +inf on
# the lower-bound side and -inf on the upper-bound side, so the trim
# bounds are order statistics of the surviving finite values only — and
# the mean runs over the finite entries. When fewer than ``2H+1`` finite
# values survive at an element (a degree deficit: the H-trimming
# guarantee needs 2H+1 honest-capable inputs), the aggregate gracefully
# KEEPS THE AGENT'S OWN VALUE instead of computing undefined clipping;
# rcmarl_tpu.faults.fault_diagnostics counts exactly these events for
# the trainer's per-block diagnostics.
#
# Cross-backend contract (pinned by tests/test_faults.py): the sanitize
# epilogue below is written as an explicit slot-ordered chain of adds —
# the same association order the Pallas kernel's accumulator uses — and
# the bounds are exact selections on the sinked arrays, so all six
# impls (xla, xla_sort, masked, traced-H, pallas select, pallas sort)
# produce BITWISE-identical f32 aggregates. The tournament's ±inf pads
# coexist with the sentinel sinks because identical infinities share one
# bit pattern: a pad displacing a sunk entry changes nothing.


def _sanitize_parts(values: jnp.ndarray, valid: jnp.ndarray | None):
    """(finite, sink_lo, sink_hi): the elementwise finite mask (ANDed
    with the padded-graph edge validity, when given) and the ±inf-sunk
    copies whose order statistics see only surviving entries."""
    n_in = values.shape[0]
    finite = jnp.isfinite(values)
    if valid is not None:
        shape = (n_in,) + (1,) * (values.ndim - 1)
        finite = finite & (valid.reshape(shape) > 0)
    sink_lo = jnp.where(finite, values, jnp.inf)
    sink_hi = jnp.where(finite, values, -jnp.inf)
    return finite, sink_lo, sink_hi


def _sanitized_epilogue(values, finite, count, lower_raw, upper_raw, need):
    """Shared tail of every sanitized backend: own-anchored bounds over
    surviving entries, slot-ordered clip-and-accumulate, finite-count
    mean, and the degree-deficit fallback to the agent's own value.
    ``need`` may be traced (the fused-matrix path's 2H+1)."""
    n_in = values.shape[0]
    own = values[0]
    # Own-anchoring (own value always inside the bounds) via the sunk
    # own row: a non-finite own value anchors nothing instead of
    # poisoning both bounds.
    lower = jnp.minimum(lower_raw, jnp.where(finite[0], own, jnp.inf))
    upper = jnp.maximum(upper_raw, jnp.where(finite[0], own, -jnp.inf))
    acc = jnp.where(finite[0], jnp.clip(values[0], lower, upper), 0.0)
    for i in range(1, n_in):
        acc = acc + jnp.where(
            finite[i], jnp.clip(values[i], lower, upper), 0.0
        )
    # Deficit fallback: < 2H+1 finite survivors void the H-trimming
    # guarantee — keep own value (which may itself be non-finite; the
    # trainer guard, not the kernel, owns that failure).
    return jnp.where(count >= need, acc / count, own)


def _finite_count(finite, dtype):
    """Slot-ordered sequential count of surviving entries — the same
    association order as the Pallas kernel's accumulator (bitwise
    contract, see the section comment)."""
    n_in = finite.shape[0]
    count = finite[0].astype(dtype)
    for i in range(1, n_in):
        count = count + finite[i].astype(dtype)
    return count


def _sanitized_aggregate(
    values: jnp.ndarray, H: int, impl: str, valid: jnp.ndarray | None = None
) -> jnp.ndarray:
    """Static-H sanitized clip-and-average (xla / xla_sort / masked)."""
    n_in = values.shape[0]
    if not 0 <= 2 * H <= n_in - 1:
        raise ValueError(f"H={H} invalid for n_in={n_in}: need 0 <= 2H <= n_in-1")
    finite, sink_lo, sink_hi = _sanitize_parts(values, valid)
    count = _finite_count(finite, values.dtype)
    if impl == "xla_sort":
        lower_raw = jnp.sort(sink_lo, axis=0)[H]
        upper_raw = jnp.sort(sink_hi, axis=0)[n_in - 1 - H]
    else:
        lower_raw = _k_smallest(sink_lo, H + 1)[H]
        upper_raw = _k_largest(sink_hi, H + 1)[0]
    return _sanitized_epilogue(
        values, finite, count, lower_raw, upper_raw, 2 * H + 1
    )


def _sanitized_dynamic(values: jnp.ndarray, H, impl: str) -> jnp.ndarray:
    """Traced-H sanitized clip-and-average: the legal-range trick of
    :func:`_dynamic_h_aggregate` (k_max selections / dynamic sort index)
    over the ±inf-sunk copies, same epilogue, traced deficit threshold."""
    if impl not in ("xla", "xla_sort"):
        raise ValueError(
            f"traced H requires the xla consensus family (xla/xla_sort), "
            f"got {impl!r} (the Pallas kernel fixes its trim indices at "
            "lowering time)"
        )
    H = jnp.asarray(H, jnp.int32)
    n_in = values.shape[0]
    finite, sink_lo, sink_hi = _sanitize_parts(values, None)
    count = _finite_count(finite, values.dtype)
    if impl == "xla_sort":
        lower_raw = jnp.take(jnp.sort(sink_lo, axis=0), H, axis=0)
        upper_raw = jnp.take(jnp.sort(sink_hi, axis=0), n_in - 1 - H, axis=0)
    else:
        k_max = (n_in - 1) // 2 + 1
        lower_raw = jnp.take(_k_smallest(sink_lo, k_max), H, axis=0)
        upper_raw = jnp.take(
            _k_largest(sink_hi, k_max), k_max - 1 - H, axis=0
        )
    return _sanitized_epilogue(
        values, finite, count, lower_raw, upper_raw, 2 * H + 1
    )


def resilient_aggregate(
    values: jnp.ndarray,
    H: int,
    impl: str = "xla",
    valid: jnp.ndarray | None = None,
    n_agents: int = 1,
    sanitize: bool = False,
) -> jnp.ndarray:
    """Clip-and-average over the leading neighbor axis.

    Args:
      values: (n_in, ...) stacked neighbor values, own value at index 0.
      H: max number of adversaries tolerated in the neighborhood. A
        Python int traces the specialized kernel (H=0 short-circuits to
        a plain mean); a TRACED scalar (the heterogeneous-cell matrix
        path, where replicas with different H share one program) runs
        the general select/clip/mean with dynamic trim indices — exactly
        equivalent, since at H=0 the clip bounds are the min/max and the
        clip is the identity. Traced H is XLA-only (the Pallas kernel
        unrolls its trim indices at lowering time) and cannot be
        range-checked at trace time — callers validate 2H <= deg-1 per
        cell (Config does this for its static H).
      impl: 'xla' (default; log-depth tournament selection, bitwise-equal
        to the sort), 'xla_sort' (full jnp.sort — the measured-comparison
        arm), 'pallas' (fused TPU selection kernel,
        :mod:`rcmarl_tpu.ops.pallas_aggregation`), 'pallas_sort' (the
        kernel's sorting-network arm), 'pallas_interpret' (selection
        kernel in the interpreter, CPU tests), or 'auto' (the 3-way
        measured-crossover choice, :func:`resolve_impl`).
      valid: optional (n_in,) edge-validity mask for heterogeneous
        in-degree graphs (reference ``main.py:28`` accepts arbitrary
        adjacency lists): neighborhoods are padded to the graph's max
        in-degree and padded slots masked out. Index 0 (self) must be
        valid, and ``2H <= sum(valid) - 1`` must hold (checked statically
        per agent by ``Config``). May be traced (vmapped over agents).
        The masked path is XLA-only: padded graphs route past the Pallas
        kernel (irregular graphs are host-defined, small-scale usage).
      n_agents: vmapped agent-axis size of the calling consensus layer,
        used only to resolve ``'auto'`` (see :func:`resolve_impl`).
      sanitize: harden against non-finite payloads — NaN/±Inf entries
        become per-element exclusions (±inf-sentinel sinks, like padded
        slots), the mean runs over surviving finite entries, and an
        element with fewer than 2H+1 finite survivors keeps the agent's
        own value (degree-deficit fallback). Bitwise-identical across
        every backend; see the "Sanitized aggregation" section comment.

    Returns:
      (...) aggregated values.
    """
    if not is_static_h(H):
        if valid is not None:
            raise ValueError(
                "traced H is not supported together with a padded-graph "
                "validity mask (matrix cells must share one uniform graph)"
            )
        concrete = _resolve_dynamic(impl, values.shape[0])
        if sanitize:
            return _sanitized_dynamic(values, H, concrete)
        return _dynamic_h_aggregate(values, H, concrete)
    if valid is not None:
        concrete = _resolve_masked(impl, values.shape[0], H)
        if sanitize:
            return _sanitized_aggregate(values, H, concrete, valid=valid)
        return _masked_aggregate(values, H, valid, concrete)
    impl = resolve_impl(impl, values.shape[0], values.dtype, n_agents, H)
    if impl not in ("xla", "xla_sort"):
        from rcmarl_tpu.ops.pallas_aggregation import fused_resilient_aggregate

        # the one-kernel-epoch names alias the plain kernel at the leaf
        # level — the extra fusion (in-kernel gather + fault chain) is
        # an EPOCH-level property owned by training/update.py
        return fused_resilient_aggregate(
            values,
            H,
            variant="sort" if impl == "pallas_sort" else "select",
            interpret=impl in ("pallas_interpret", "pallas_fused_interpret"),
            sanitize=sanitize,
        )
    if sanitize:
        return _sanitized_aggregate(values, H, impl)
    n_in = values.shape[0]
    if not 0 <= 2 * H <= n_in - 1:
        raise ValueError(f"H={H} invalid for n_in={n_in}: need 0 <= 2H <= n_in-1")
    own = values[0]
    if H == 0:
        # select/clip are the identity w.r.t. the mean when H == 0
        return jnp.mean(values, axis=0)
    lo, hi = _trim_bounds(values, H, impl)
    lower = jnp.minimum(lo, own)
    upper = jnp.maximum(hi, own)
    return jnp.mean(jnp.clip(values, lower, upper), axis=0)


def is_static_h(H) -> bool:
    """Python/NumPy ints are trace-time constants; anything else (a jnp
    scalar, a tracer) selects the dynamic-trim-index path."""
    return isinstance(H, (int, np.integer))


def _resolve_masked(impl: str, n_in: int, H: int) -> str:
    """Impl resolution for the padded-graph (masked) path, which is
    XLA-only by design (irregular graphs are host-defined, small-scale
    usage; the Pallas kernel never lowers for them): the sort arms
    ('xla_sort'/'pallas_sort') keep the sort strategy, every other
    concrete impl means selection, and 'auto' applies the measured n_in
    crossover — never the TPU volume rule, which would otherwise route
    a dense masked graph to a kernel that cannot lower for it."""
    _check_impl(impl)
    if impl == "auto":
        return "xla" if _selection_favored(n_in, H) else "xla_sort"
    return "xla_sort" if impl in ("xla_sort", "pallas_sort") else "xla"


def _resolve_dynamic(impl: str, n_in: int) -> str:
    """Impl resolution for the traced-H path: only the two XLA arms can
    lower (the Pallas kernel fixes its trim indices at lowering time),
    and 'auto' applies the measured crossover with the STATIC worst-case
    trim k_max = (n_in-1)//2 + 1 — H is data here, so the policy must
    hold for every H the cells might carry. (With the tournament the
    k_max selection is ⌈log₂n⌉ merge levels of block ops, so large-n
    traced cells no longer force the sort the way the PR-1 register
    chain's k_max·n unroll did.) An explicit pallas choice still errors
    rather than silently downgrading (callers' tests pin this)."""
    _check_impl(impl)
    if impl == "auto":
        k_max = (n_in - 1) // 2 + 1
        return "xla" if _selection_favored(n_in, k_max - 1) else "xla_sort"
    return impl


def _dynamic_h_aggregate(values: jnp.ndarray, H, impl: str) -> jnp.ndarray:
    """Clip-and-average with a TRACED trim parameter H.

    The general formula — ``lower = min(sorted[H], own)``, ``upper =
    max(sorted[n_in-1-H], own)`` — is exact for every H including 0
    (there the bounds are the global min/max, so the clip is the
    identity and the mean is plain), so no data-dependent branching is
    needed: the trim indices just become dynamic. This is what lets
    training cells with different H values share one compiled program
    (vmapped over the cell axis).

    Selection variant (``impl='xla'``): H is traced, but its legal range
    is static — 2H <= n_in-1 — so a k_max = (n_in-1)//2 + 1 tournament
    covers every possible trim: :func:`_k_smallest` holds
    ``sorted[0:k_max]`` stacked and :func:`_k_largest` holds
    ``sorted[n_in-k_max:]``, and the traced H dynamic-indexes into the
    stacked selections (``lower = small[H]``, ``upper =
    large[k_max-1-H]``) instead of into a full sorted copy.
    """
    if impl not in ("xla", "xla_sort"):
        raise ValueError(
            f"traced H requires the xla consensus family (xla/xla_sort), "
            f"got {impl!r} (the Pallas kernel fixes its trim indices at "
            "lowering time)"
        )
    H = jnp.asarray(H, jnp.int32)
    n_in = values.shape[0]
    own = values[0]
    if impl == "xla_sort":
        sorted_vals = jnp.sort(values, axis=0)
        lower_raw = jnp.take(sorted_vals, H, axis=0)
        upper_raw = jnp.take(sorted_vals, n_in - 1 - H, axis=0)
    else:
        k_max = (n_in - 1) // 2 + 1
        lower_raw = jnp.take(_k_smallest(values, k_max), H, axis=0)
        upper_raw = jnp.take(_k_largest(values, k_max), k_max - 1 - H, axis=0)
    lower = jnp.minimum(lower_raw, own)
    upper = jnp.maximum(upper_raw, own)
    return jnp.mean(jnp.clip(values, lower, upper), axis=0)


def _masked_aggregate(
    values: jnp.ndarray, H: int, valid: jnp.ndarray, impl: str = "xla"
) -> jnp.ndarray:
    """Clip-and-average over only the valid neighbor slots.

    Exactly :func:`resilient_aggregate` restricted to the ``d = sum(valid)``
    valid entries. Selection variant (the default): masking invalid
    slots to +inf makes the (H+1)-th smallest *valid* entry fall out of
    the small tournament directly, and masking to -inf does the same for
    the (H+1)-th largest on the large side — both static index
    ``[H]``/``[0]`` picks, replacing the sort variant's
    dynamic-index-into-full-sort for the upper bound (``sorted[d-H-1]``
    with d traced under vmap). Config's per-agent ``2H <= d-1`` check
    guarantees H+1 valid entries exist on each side. The mean runs over
    the d valid entries only.
    """
    n_in = values.shape[0]
    # Same static sanity check as the unmasked path (vs the padded size;
    # the exact per-neighborhood 2H <= count-1 requirement is enforced
    # statically per agent by Config, since counts are traced data here).
    if not 0 <= 2 * H <= n_in - 1:
        raise ValueError(f"H={H} invalid for n_in={n_in}: need 0 <= 2H <= n_in-1")
    shape = (n_in,) + (1,) * (values.ndim - 1)
    v = valid.astype(values.dtype).reshape(shape)
    count = jnp.sum(valid.astype(values.dtype))
    if H == 0:
        # where (not multiply): padded slots may hold arbitrary values
        # (even non-finite) and must not poison the sum
        return jnp.sum(jnp.where(v > 0, values, 0.0), axis=0) / count
    own = values[0]
    if impl == "xla_sort":
        masked = jnp.where(v > 0, values, jnp.inf)
        sorted_vals = jnp.sort(masked, axis=0)
        lower = jnp.minimum(sorted_vals[H], own)
        upper_idx = count.astype(jnp.int32) - H - 1
        upper_row = jax.lax.dynamic_index_in_dim(
            sorted_vals, upper_idx, axis=0, keepdims=False
        )
        upper = jnp.maximum(upper_row, own)
    else:
        sink_lo = jnp.where(v > 0, values, jnp.inf)  # invalid sinks high
        sink_hi = jnp.where(v > 0, values, -jnp.inf)  # invalid sinks low
        lower = jnp.minimum(_k_smallest(sink_lo, H + 1)[H], own)
        upper = jnp.maximum(_k_largest(sink_hi, H + 1)[0], own)
    clipped = jnp.where(v > 0, jnp.clip(values, lower, upper), 0.0)
    return jnp.sum(clipped, axis=0) / count


# --------------------------------------------------------------------------
# Whole-tree (flattened one-launch) aggregation
# --------------------------------------------------------------------------


def ravel_neighbor_tree(tree):
    """Flatten a pytree of (n_in, ...) leaves into ONE (n_in, P_total)
    block plus an ``unravel`` closure mapping an aggregated (P_total,)
    array back to the tree structure (leaves without the neighbor axis).

    This is the layout both the Pallas kernel launch and the XLA
    one-launch paths share: raveling is pure reshape/concat (bitwise
    no-ops per element), so aggregating the flattened block is bitwise
    identical to aggregating leaf by leaf — every select/clip/mean op is
    elementwise along the trailing axis — while issuing ONE op sequence
    for the whole message tree instead of one per leaf.

    The raveling composes across TREES exactly the same way: any pytree
    works, including a tuple of several message trees — the netstack
    consensus (``Config.netstack``, training/update.py) ravels the
    critic AND team-reward trees into one ``(n_in, P_critic + P_tr)``
    super-block this way, halving the per-epoch launch count again on
    top of the per-tree flattening, still bitwise column for column.
    """
    leaves, treedef = jax.tree.flatten(tree)
    n_in = leaves[0].shape[0]
    bad = [l.shape for l in leaves if l.shape[0] != n_in]
    if bad:
        raise ValueError(
            f"all leaves must share the leading neighbor dim {n_in}; "
            f"got leaves with shapes {bad[:3]}"
        )
    sizes = [int(np.prod(l.shape[1:], dtype=np.int64)) for l in leaves]
    if len(leaves) == 1:
        flat = leaves[0].reshape(n_in, -1)
    else:
        flat = jnp.concatenate([l.reshape(n_in, -1) for l in leaves], axis=1)

    def unravel(agg):
        out, off = [], 0
        for leaf, size in zip(leaves, sizes):
            out.append(agg[off : off + size].reshape(leaf.shape[1:]))
            off += size
        return jax.tree.unflatten(treedef, out)

    return flat, unravel


def resilient_aggregate_tree(
    tree,
    H: int,
    impl: str = "xla",
    valid: jnp.ndarray | None = None,
    n_agents: int = 1,
    sanitize: bool = False,
    layout: str = "flat",
):
    """Apply :func:`resilient_aggregate` to every leaf of a pytree whose
    leaves carry a leading neighbor axis (e.g. a gathered parameter
    pytree with leaves (n_in, ...)).

    ``layout='flat'`` (default) ravels every leaf into ONE
    (n_in, P_total) block (:func:`ravel_neighbor_tree`) so the whole
    message tree is aggregated in a single select/clip/mean op sequence
    — on every backend: the Pallas impls always launched this way, and
    the XLA impls (all modes: static-H, traced-H, masked, sanitize) now
    share the layout instead of dispatching one small op chain per leaf.
    ``layout='per_leaf'`` keeps the historical leaf-by-leaf ``tree.map``
    (the comparison arm; also the automatic fallback when leaves carry
    mixed dtypes, which a single flat block cannot hold). Both layouts
    are bitwise identical — raveling is elementwise-neutral.

    ``valid`` masks padded neighbor slots (see
    :func:`resilient_aggregate`; masked trees take the XLA path).
    ``n_agents`` is the vmapped agent-axis size, used only to resolve
    ``'auto'``. ``sanitize`` hardens every leaf against non-finite
    payloads (see :func:`resilient_aggregate`)."""
    if layout not in ("flat", "per_leaf"):
        raise ValueError(
            f"unknown layout {layout!r}; expected 'flat' or 'per_leaf'"
        )
    leaves = jax.tree.leaves(tree)
    if not leaves:  # e.g. the trunk tree of a head-only (hidden=()) net
        _check_impl(impl)
        return tree
    one_block = layout == "flat" and len({l.dtype for l in leaves}) == 1

    def apply(fn):
        if one_block:
            flat, unravel = ravel_neighbor_tree(tree)
            return unravel(fn(flat))
        return jax.tree.map(fn, tree)

    if not is_static_h(H):
        if valid is not None:
            raise ValueError(
                "traced H is not supported together with a padded-graph "
                "validity mask (matrix cells must share one uniform graph)"
            )
        concrete = _resolve_dynamic(impl, leaves[0].shape[0])
        if sanitize:
            return apply(lambda v: _sanitized_dynamic(v, H, concrete))
        return apply(lambda v: _dynamic_h_aggregate(v, H, concrete))
    if valid is not None:
        concrete = _resolve_masked(impl, leaves[0].shape[0], H)
        if sanitize:
            return apply(
                lambda v: _sanitized_aggregate(v, H, concrete, valid=valid)
            )
        return apply(lambda v: _masked_aggregate(v, H, valid, concrete))
    impl = resolve_impl(
        impl, leaves[0].shape[0], leaves[0].dtype, n_agents, H
    )
    if impl not in ("xla", "xla_sort"):
        from rcmarl_tpu.ops.pallas_aggregation import (
            fused_resilient_aggregate,
        )

        # ONE ravel path for every backend: the pallas impls go through
        # the same apply() as the XLA ones, so the flat block enters the
        # kernel without a second pack, the mixed-dtype guard applies
        # uniformly, and layout='per_leaf' is an honest per-leaf
        # comparison arm on the kernel too (bitwise — raveling is
        # elementwise-neutral, pinned in tests/test_fused_epoch.py).
        return apply(
            lambda v: fused_resilient_aggregate(
                v,
                H,
                variant="sort" if impl == "pallas_sort" else "select",
                interpret=impl
                in ("pallas_interpret", "pallas_fused_interpret"),
                sanitize=sanitize,
            )
        )
    if sanitize:
        return apply(lambda v: _sanitized_aggregate(v, H, impl))
    return apply(lambda v: resilient_aggregate(v, H, impl))
