"""Resilient (trimmed clip-and-average) aggregation — the hot kernel.

Rebuild of the reference's ``_resilient_aggregation``
(``resilient_CAC_agents.py:42-58``), the single function used for BOTH
per-parameter hidden-layer consensus and per-sample projected-estimate
consensus (SURVEY.md §3.4). Semantics, with own value at neighbor index 0:

    sorted = sort(values, axis=0)
    lower  = min(sorted[H], own)
    upper  = max(sorted[n_in - H - 1], own)
    out    = mean(clip(values, lower, upper), axis=0)

Values are *clipped into* [lower, upper], not discarded — a clipped mean
(~trimmed mean) guaranteed to keep the agent's own value inside the
bounds. H=0 degenerates to the plain mean.

TPU shape: one fused ``sort -> clip -> mean`` over a small leading
neighbor axis, batched over everything else (all parameters of a whole
pytree in one call; all samples of a projection batch in another), and
vmapped over the agent axis by the consensus layer. XLA lowers the tiny
fixed-size sort to a vectorized sorting network; no Pallas needed at
reference scale (SURVEY.md §7 hard part (e)).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from rcmarl_tpu.config import CONSENSUS_IMPLS


#: Measured TPU crossover (BENCH_SCALING.jsonl, v5e), keyed on the total
#: gathered-block volume ``n_in * n_agents`` — NOT on n_in alone: at
#: identical n_in=5 the winner flips with the agent count (n16_ring: xla
#: 1.67x faster at volume 80; n64_ring: pallas 1.64x faster at volume
#: 320), so the deciding variable is how much data one fused launch
#: processes across the vmapped agent axis. Measured xla wins at volumes
#: {20, 80}; measured pallas wins at {256, 320, 4096}; the threshold
#: sits at the smallest measured pallas win (n16_full, 1.09x). Parameter
#: volume per agent is held constant across these rows (the reference's
#: 20-20 nets), so P is deliberately not in the key; refit if a
#: measured row at a different architecture contradicts it.
PALLAS_CROSSOVER_VOLUME = 256


def _check_impl(impl: str) -> None:
    """Reject unknown impl strings up front: anything not in
    CONSENSUS_IMPLS would otherwise be routed to the Pallas kernel with
    interpret=False and die in lowering with an obscure error."""
    if impl not in CONSENSUS_IMPLS:
        raise ValueError(
            f"unknown consensus impl {impl!r}; expected one of {CONSENSUS_IMPLS}"
        )


def resolve_impl(impl: str, n_in: int, dtype=None, n_agents: int = 1) -> str:
    """Resolve ``'auto'`` to a concrete implementation at trace time.

    ``'auto'`` picks the Pallas kernel exactly where hardware
    measurement says it wins — on a TPU backend with a gathered-block
    volume ``n_in * n_agents`` of at least
    :data:`PALLAS_CROSSOVER_VOLUME` — and the XLA sort everywhere else:
    small total volumes, CPU/interpreter platforms where the kernel
    cannot lower, and f64 inputs (the kernel computes in f32, a silent
    precision loss the XLA path doesn't have — see
    ``fused_resilient_aggregate``). ``n_agents`` is the vmapped
    agent-axis size of the surrounding consensus layer; it must be
    passed by the caller because inside the vmap the agent axis is
    invisible to the kernel (callers that aggregate one agent at a
    time, like the reference-API twins, correctly use the default 1).
    Concrete impl strings pass through unchanged, so explicit choices
    always stick.
    """
    _check_impl(impl)
    if impl != "auto":
        return impl
    if dtype is not None and jnp.dtype(dtype) == jnp.float64:
        return "xla"
    if (
        jax.default_backend() == "tpu"
        and n_in * n_agents >= PALLAS_CROSSOVER_VOLUME
    ):
        return "pallas"
    return "xla"


def resilient_aggregate(
    values: jnp.ndarray,
    H: int,
    impl: str = "xla",
    valid: jnp.ndarray | None = None,
    n_agents: int = 1,
) -> jnp.ndarray:
    """Clip-and-average over the leading neighbor axis.

    Args:
      values: (n_in, ...) stacked neighbor values, own value at index 0.
      H: max number of adversaries tolerated in the neighborhood. A
        Python int traces the specialized kernel (H=0 short-circuits to
        a plain mean); a TRACED scalar (the heterogeneous-cell matrix
        path, where replicas with different H share one program) runs
        the general sort/clip/mean with dynamic trim indices — exactly
        equivalent, since at H=0 the clip bounds are the min/max and the
        clip is the identity. Traced H is XLA-only (the Pallas kernel
        unrolls its trim indices at lowering time) and cannot be
        range-checked at trace time — callers validate 2H <= deg-1 per
        cell (Config does this for its static H).
      impl: 'xla' (default), 'pallas' (fused TPU kernel,
        :mod:`rcmarl_tpu.ops.pallas_aggregation`), 'pallas_interpret',
        or 'auto' (measured-crossover choice, :func:`resolve_impl`).
      valid: optional (n_in,) edge-validity mask for heterogeneous
        in-degree graphs (reference ``main.py:28`` accepts arbitrary
        adjacency lists): neighborhoods are padded to the graph's max
        in-degree and padded slots masked out. Index 0 (self) must be
        valid, and ``2H <= sum(valid) - 1`` must hold (checked statically
        per agent by ``Config``). May be traced (vmapped over agents).
        The masked path is XLA-only: padded graphs route past the Pallas
        kernel (irregular graphs are host-defined, small-scale usage).
      n_agents: vmapped agent-axis size of the calling consensus layer,
        used only to resolve ``'auto'`` (see :func:`resolve_impl`).

    Returns:
      (...) aggregated values.
    """
    if not is_static_h(H):
        if valid is not None:
            raise ValueError(
                "traced H is not supported together with a padded-graph "
                "validity mask (matrix cells must share one uniform graph)"
            )
        # 'auto' must pick an impl that CAN lower, so with a traced H it
        # is xla by definition; an explicit pallas choice still errors
        _check_impl(impl)
        return _dynamic_h_aggregate(values, H, "xla" if impl == "auto" else impl)
    impl = resolve_impl(impl, values.shape[0], values.dtype, n_agents)
    if valid is not None:
        return _masked_aggregate(values, H, valid)
    if impl != "xla":
        from rcmarl_tpu.ops.pallas_aggregation import fused_resilient_aggregate

        return fused_resilient_aggregate(
            values, H, interpret=impl == "pallas_interpret"
        )
    n_in = values.shape[0]
    if not 0 <= 2 * H <= n_in - 1:
        raise ValueError(f"H={H} invalid for n_in={n_in}: need 0 <= 2H <= n_in-1")
    own = values[0]
    if H == 0:
        # sort/clip are the identity w.r.t. the mean when H == 0
        return jnp.mean(values, axis=0)
    sorted_vals = jnp.sort(values, axis=0)
    lower = jnp.minimum(sorted_vals[H], own)
    upper = jnp.maximum(sorted_vals[n_in - H - 1], own)
    return jnp.mean(jnp.clip(values, lower, upper), axis=0)


def is_static_h(H) -> bool:
    """Python/NumPy ints are trace-time constants; anything else (a jnp
    scalar, a tracer) selects the dynamic-trim-index path."""
    return isinstance(H, (int, np.integer))


def _dynamic_h_aggregate(values: jnp.ndarray, H, impl: str) -> jnp.ndarray:
    """Clip-and-average with a TRACED trim parameter H.

    The general formula — ``lower = min(sorted[H], own)``, ``upper =
    max(sorted[n_in-1-H], own)`` — is exact for every H including 0
    (there the bounds are the global min/max, so the clip is the
    identity and the mean is plain), so no data-dependent branching is
    needed: ``sorted[H]`` just becomes a dynamic index. This is what
    lets training cells with different H values share one compiled
    program (vmapped over the cell axis).
    """
    if impl != "xla":
        raise ValueError(
            f"traced H requires the xla consensus impl, got {impl!r} "
            "(the Pallas kernel fixes its trim indices at lowering time)"
        )
    H = jnp.asarray(H, jnp.int32)
    n_in = values.shape[0]
    own = values[0]
    sorted_vals = jnp.sort(values, axis=0)
    lower = jnp.minimum(jnp.take(sorted_vals, H, axis=0), own)
    upper = jnp.maximum(jnp.take(sorted_vals, n_in - 1 - H, axis=0), own)
    return jnp.mean(jnp.clip(values, lower, upper), axis=0)


def _masked_aggregate(
    values: jnp.ndarray, H: int, valid: jnp.ndarray
) -> jnp.ndarray:
    """Clip-and-average over only the valid neighbor slots.

    Exactly :func:`resilient_aggregate` restricted to the ``d = sum(valid)``
    valid entries: invalid slots sort to the end as +inf, so
    ``sorted[H]`` is the H-th smallest valid value and the upper bound is
    ``sorted[d - H - 1]`` (a dynamic index — d is data under vmap, H is
    static); the mean runs over the d valid entries only.
    """
    n_in = values.shape[0]
    # Same static sanity check as the unmasked path (vs the padded size;
    # the exact per-neighborhood 2H <= count-1 requirement is enforced
    # statically per agent by Config, since counts are traced data here).
    if not 0 <= 2 * H <= n_in - 1:
        raise ValueError(f"H={H} invalid for n_in={n_in}: need 0 <= 2H <= n_in-1")
    shape = (n_in,) + (1,) * (values.ndim - 1)
    v = valid.astype(values.dtype).reshape(shape)
    count = jnp.sum(valid.astype(values.dtype))
    if H == 0:
        # where (not multiply): padded slots may hold arbitrary values
        # (even non-finite) and must not poison the sum
        return jnp.sum(jnp.where(v > 0, values, 0.0), axis=0) / count
    own = values[0]
    masked = jnp.where(v > 0, values, jnp.inf)
    sorted_vals = jnp.sort(masked, axis=0)
    lower = jnp.minimum(sorted_vals[H], own)
    upper_idx = count.astype(jnp.int32) - H - 1
    upper_row = jax.lax.dynamic_index_in_dim(
        sorted_vals, upper_idx, axis=0, keepdims=False
    )
    upper = jnp.maximum(upper_row, own)
    clipped = jnp.where(v > 0, jnp.clip(values, lower, upper), 0.0)
    return jnp.sum(clipped, axis=0) / count


def resilient_aggregate_tree(
    tree,
    H: int,
    impl: str = "xla",
    valid: jnp.ndarray | None = None,
    n_agents: int = 1,
):
    """Apply :func:`resilient_aggregate` to every leaf of a pytree whose
    leaves carry a leading neighbor axis (e.g. a gathered parameter
    pytree with leaves (n_in, ...)). With a pallas impl the whole tree is
    flattened into ONE fused kernel launch instead of one sort per leaf.
    ``valid`` masks padded neighbor slots (see :func:`resilient_aggregate`;
    masked trees take the XLA path). ``n_agents`` is the vmapped
    agent-axis size, used only to resolve ``'auto'``."""
    leaves = jax.tree.leaves(tree)
    if not leaves:  # e.g. the trunk tree of a head-only (hidden=()) net
        _check_impl(impl)
        return tree
    if not is_static_h(H):
        if valid is not None:
            raise ValueError(
                "traced H is not supported together with a padded-graph "
                "validity mask (matrix cells must share one uniform graph)"
            )
        _check_impl(impl)
        concrete = "xla" if impl == "auto" else impl
        return jax.tree.map(
            lambda v: _dynamic_h_aggregate(v, H, concrete), tree
        )
    impl = resolve_impl(impl, leaves[0].shape[0], leaves[0].dtype, n_agents)
    if valid is not None:
        return jax.tree.map(lambda v: _masked_aggregate(v, H, valid), tree)
    if impl != "xla":
        from rcmarl_tpu.ops.pallas_aggregation import (
            fused_resilient_aggregate_tree,
        )

        return fused_resilient_aggregate_tree(
            tree, H, interpret=impl == "pallas_interpret"
        )
    return jax.tree.map(lambda v: resilient_aggregate(v, H), tree)
