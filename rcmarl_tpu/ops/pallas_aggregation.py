"""Fused Pallas TPU kernel for resilient (clip-and-average) aggregation.

Same semantics as :func:`rcmarl_tpu.ops.aggregation.resilient_aggregate`
(the reference's ``_resilient_aggregation``, ``resilient_CAC_agents.py:
42-58``): sort over the leading neighbor axis, clip every value into
``[min(sorted[H], own), max(sorted[n_in-H-1], own)]`` with own value at
index 0, then mean over neighbors.

Why a kernel at all: at reference scale (5 agents, 20-unit MLPs) XLA's
``sort -> clip -> mean`` is already fine (SURVEY.md §7 hard part (e)).
At scale-out (N=64 agents, 256x256 trunks — BASELINE.json config 5) the
consensus pass is HBM-bandwidth-bound: XLA materializes the full sorted
copy of the gathered (n_in, P) parameter block in HBM between the sort
and the clip/mean. This kernel streams each (n_in, rows, 128) tile
through VMEM once, runs an odd-even transposition sorting network over
the tiny static neighbor axis entirely in registers/VMEM (n_in
compare-exchange rounds of (rows, 128) ``minimum``/``maximum`` VPU ops
— no data-dependent control flow), and writes only the aggregated tile
back — one HBM read + one HBM write total.

The public entry points mirror the XLA versions and are exact drop-ins:

- :func:`fused_resilient_aggregate` — one (n_in, ...) array.
- :func:`fused_resilient_aggregate_tree` — a whole pytree with (n_in,
  ...) leaves, flattened into ONE kernel launch (vs one XLA sort per
  leaf), then split back.

Both fall back to nothing special on CPU: pass ``interpret=True`` (the
tests do) or keep ``Config.consensus_impl='xla'``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 128


def _sorting_network(rows):
    """Odd-even transposition sort of a static list of equal-shape arrays.

    n rounds of adjacent compare-exchange; fully unrolled (n is tiny and
    static), so it lowers to pure vectorized min/max with no control flow.
    """
    s = list(rows)
    n = len(s)
    for rnd in range(n):
        for j in range(rnd % 2, n - 1, 2):
            lo = jnp.minimum(s[j], s[j + 1])
            hi = jnp.maximum(s[j], s[j + 1])
            s[j], s[j + 1] = lo, hi
    return s


def _agg_kernel(vals_ref, out_ref, *, n_in: int, H: int):
    """One (n_in, rows, LANES) tile: sort over axis 0, clip, mean."""
    rows = [vals_ref[i] for i in range(n_in)]  # each (rows, LANES)
    own = rows[0]
    if H > 0:
        s = _sorting_network(rows)
        lower = jnp.minimum(s[H], own)
        upper = jnp.maximum(s[n_in - 1 - H], own)
        clipped = [jnp.clip(r, lower, upper) for r in rows]
    else:  # H=0: clip bounds span the whole range -> plain mean
        clipped = rows
    acc = clipped[0]
    for r in clipped[1:]:
        acc = acc + r
    out_ref[...] = acc * (1.0 / n_in)


@functools.partial(
    jax.jit, static_argnames=("H", "block_rows", "interpret")
)
def fused_resilient_aggregate(
    values: jnp.ndarray,
    H: int,
    *,
    block_rows: int = 32,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas twin of :func:`~rcmarl_tpu.ops.aggregation.resilient_aggregate`.

    Args:
      values: (n_in, ...) stacked neighbor values, own value at index 0.
      H: trim parameter (static); 0 <= 2H <= n_in-1.
      block_rows: sublane rows per grid step (VMEM tile is
        n_in x block_rows x 128 floats).
      interpret: run in the Pallas interpreter (for CPU tests).

    Returns:
      (...) aggregated values in ``values.dtype``. Sort/clip/mean are
      computed in f32 (the VPU-native width) regardless of input dtype
      and cast back: exact for f32, an upcast for bf16, and a silent
      precision LOSS for f64 inputs under x64 — use the XLA path there.
    """
    n_in = values.shape[0]
    if not 0 <= 2 * H <= n_in - 1:
        raise ValueError(f"H={H} invalid for n_in={n_in}: need 0 <= 2H <= n_in-1")
    out_shape = values.shape[1:]
    flat = values.reshape(n_in, -1).astype(jnp.float32)
    m = flat.shape[1]
    tile = block_rows * _LANES
    padded = ((m + tile - 1) // tile) * tile
    if padded != m:
        flat = jnp.pad(flat, ((0, 0), (0, padded - m)))
    rows_total = padded // _LANES
    v3 = flat.reshape(n_in, rows_total, _LANES)
    grid = (rows_total // block_rows,)
    out = pl.pallas_call(
        functools.partial(_agg_kernel, n_in=n_in, H=H),
        out_shape=jax.ShapeDtypeStruct((rows_total, _LANES), jnp.float32),
        in_specs=[
            pl.BlockSpec((n_in, block_rows, _LANES), lambda i: (0, i, 0))
        ],
        out_specs=pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0)),
        grid=grid,
        interpret=interpret,
    )(v3)
    return out.reshape(-1)[:m].reshape(out_shape).astype(values.dtype)


def fused_resilient_aggregate_tree(
    tree, H: int, *, block_rows: int = 32, interpret: bool = False
):
    """Aggregate every (n_in, ...) leaf of ``tree`` in ONE kernel launch.

    Ravels all leaves along their trailing dims, concatenates into a
    single (n_in, P) block, runs :func:`fused_resilient_aggregate` once,
    and splits back — the whole hidden-layer consensus of an agent's
    trunk (reference ``resilient_CAC_agents.py:142-166``) becomes a
    single HBM pass instead of one sort per weight array.
    """
    leaves, treedef = jax.tree.flatten(tree)
    n_in = leaves[0].shape[0]
    bad = [l.shape for l in leaves if l.shape[0] != n_in]
    if bad:
        raise ValueError(
            f"all leaves must share the leading neighbor dim {n_in}; "
            f"got leaves with shapes {bad[:3]}"
        )
    sizes = [l[0].size for l in leaves]
    flat = jnp.concatenate(
        [l.reshape(n_in, -1) for l in leaves], axis=1
    )
    agg = fused_resilient_aggregate(
        flat, H, block_rows=block_rows, interpret=interpret
    )
    out, off = [], 0
    for leaf, size in zip(leaves, sizes):
        out.append(agg[off : off + size].reshape(leaf.shape[1:]))
        off += size
    return jax.tree.unflatten(treedef, out)
