"""Fused Pallas TPU kernel for resilient (clip-and-average) aggregation.

Same semantics as :func:`rcmarl_tpu.ops.aggregation.resilient_aggregate`
(the reference's ``_resilient_aggregation``, ``resilient_CAC_agents.py:
42-58``): find the trim bounds ``[min(sorted[H], own),
max(sorted[n_in-H-1], own)]`` over the leading neighbor axis with own
value at index 0, clip every value into them, then mean over neighbors.

Why a kernel at all: at reference scale (5 agents, 20-unit MLPs) XLA's
``select -> clip -> mean`` is already fine (SURVEY.md §7 hard part (e)).
At scale-out (N=64 agents, 256x256 trunks — BASELINE.json config 5) the
consensus pass is HBM-bandwidth-bound: XLA materializes intermediate
copies of the gathered (n_in, P) parameter block in HBM between the
bound computation and the clip/mean. This kernel streams each (n_in,
rows, 128) tile through VMEM once and writes only the aggregated tile
back — one HBM read + one HBM write total.

Two trim-bound variants share the clip/mean epilogue:

- ``variant='select'`` (default): dual top-(H+1) selection with
  2(H+1) running min/max registers streamed over the n_in rows
  (:func:`rcmarl_tpu.ops.aggregation._running_extrema` — the same
  helper the XLA path uses, pure vectorized ``minimum``/``maximum`` VPU
  ops). Only ~2(H+1) live (rows, 128) register arrays instead of the
  n_in-array sorted block, which shrinks VMEM pressure and lets the
  default tile grow to ``block_rows=64``.
- ``variant='sort'``: the original odd-even transposition sorting
  network (n_in compare-exchange rounds, the full sorted block live) —
  kept as the measured-comparison arm for refitting crossovers.

Both variants produce bitwise-identical bounds (selection picks exact
input values, just fewer of them).

The public entry points mirror the XLA versions and are exact drop-ins:

- :func:`fused_resilient_aggregate` — one (n_in, ...) array.
- :func:`fused_resilient_aggregate_tree` — a whole pytree with (n_in,
  ...) leaves, flattened into ONE kernel launch (vs one selection per
  leaf), then split back.

The kernel is agnostic to how many message trees a block carries: under
``Config.netstack`` (the default) the consensus layer hands it the
COMBINED critic+TR trunk block — ``(n_in, P_critic + P_tr)`` columns in
one launch — and the tiled grid just covers the wider trailing axis, so
the dual-tree epoch costs one kernel dispatch where the per-tree layout
cost two. Aggregation is elementwise along the trailing axis, so the
combined launch is bitwise the two per-tree launches column for column.

Both fall back to nothing special on CPU: pass ``interpret=True`` (the
tests do) or keep ``Config.consensus_impl='xla'``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from rcmarl_tpu.ops.aggregation import (
    _running_extrema,
    _running_large,
    _running_small,
    _sorting_network,
    ravel_neighbor_tree,
)
from rcmarl_tpu.ops.dma_model import BlockOperand, KernelPlan, pad_to_tile

_LANES = 128

#: Default sublane rows per grid step, per variant: the selection kernel
#: keeps only ~2(H+1) live register arrays so it affords a 2x larger
#: tile than the sorting network (which holds all n_in rows twice —
#: input block + sorted copy).
_DEFAULT_BLOCK_ROWS = {"select": 64, "sort": 32}


def _clip_mean(rows, lower, upper):
    """Shared epilogue: clip every row into [lower, upper], mean."""
    acc = jnp.clip(rows[0], lower, upper)
    for r in rows[1:]:
        acc = acc + jnp.clip(r, lower, upper)
    return acc * (1.0 / len(rows))


def _sort_bounds(rows, H: int):
    """Raw trim bounds from the full odd-even sorting network: all n_in
    rows stay live twice (input + sorted copy)."""
    s = _sorting_network(rows)
    return s[H], s[len(rows) - 1 - H]


def _select_bounds(rows, H: int):
    """Raw trim bounds from dual top-(H+1) register selection: the
    2(H+1) running min/max registers replace the materialized sorted
    block — O((H+1)·n_in) compare-exchanges instead of the network's
    O(n_in²), and the only live arrays besides the input tile are the
    registers and the accumulator."""
    small, large = _running_extrema(rows, H + 1)
    return small[H], large[0]


_BOUNDS = {"select": _select_bounds, "sort": _sort_bounds}


def kernel_plan(
    n_in: int,
    flat_cols: int,
    H: int,
    *,
    variant: str = "select",
    block_rows: int | None = None,
    sanitize: bool = False,
) -> KernelPlan:
    """The leaf-aggregation launch's static BlockSpec plan — the ONE
    derivation both :func:`fused_resilient_aggregate` (which builds its
    ``pl.BlockSpec`` list from these operands) and ``lint --kernels``
    consume. ``flat_cols`` is the raveled trailing-axis width the tile
    grid covers. ``scratch`` is the variant's extra in-tile live set
    beyond the input block: the selection kernel's ``2(H+1)`` running
    registers (or the sorting network's full n_in-row sorted copy) plus
    the accumulator, with the ±inf sentinel sinks and the finite-count
    row riding along under sanitize. This kernel carries no committed
    DMA model — the lint arm prices residency and tiling only.
    """
    if block_rows is None:
        block_rows = _DEFAULT_BLOCK_ROWS[variant]
    tile = block_rows * _LANES
    rows_total = pad_to_tile(flat_cols, tile) // _LANES
    grid = (rows_total // block_rows,)
    inputs = (
        BlockOperand(
            "values",
            (n_in, block_rows, _LANES),
            "float32",
            (True,),
            tiled_dims=(1, 2),
            index_map=lambda i: (0, i, 0),
        ),
    )
    outputs = (
        BlockOperand(
            "aggregate",
            (block_rows, _LANES),
            "float32",
            (True,),
            tiled_dims=(0, 1),
            index_map=lambda i: (i, 0),
        ),
    )
    live_rows = (n_in if variant == "sort" else 2 * (H + 1)) + 1
    if sanitize:
        live_rows += 2 * n_in + 1
    scratch = (
        BlockOperand(
            "bounds_live_set",
            (live_rows, block_rows, _LANES),
            "float32",
            (False,),
        ),
    )
    return KernelPlan(
        name=f"aggregation_{variant}",
        grid=grid,
        inputs=inputs,
        outputs=outputs,
        scratch=scratch,
        refetch="always",
    )


def _agg_kernel(vals_ref, out_ref, *, n_in: int, H: int, bounds):
    """One (n_in, rows, LANES) tile: trim bounds via ``bounds`` (the
    variant's strategy), clip, mean."""
    rows = [vals_ref[i] for i in range(n_in)]  # each (rows, LANES)
    own = rows[0]
    if H > 0:
        lo, hi = bounds(rows, H)
        lower = jnp.minimum(lo, own)
        upper = jnp.maximum(hi, own)
        out_ref[...] = _clip_mean(rows, lower, upper)
    else:  # H=0: clip bounds span the whole range -> plain mean
        acc = rows[0]
        for r in rows[1:]:
            acc = acc + r
        out_ref[...] = acc * (1.0 / n_in)


def _sanitized_agg_kernel(vals_ref, out_ref, *, n_in: int, H: int, variant: str):
    """Non-finite-hardened tile: NaN/±Inf entries become per-element
    exclusions (±inf-sentinel sinks), the mean runs over surviving
    finite entries, and elements with fewer than 2H+1 finite survivors
    keep the agent's own value. The op sequence — sinks, exact-selection
    bounds, slot-ordered accumulate, count division, deficit select —
    mirrors ``aggregation._sanitized_aggregate`` exactly, so the outputs
    are BITWISE identical to the XLA backends (the cross-backend
    contract tests/test_faults.py pins)."""
    rows = [vals_ref[i] for i in range(n_in)]  # each (rows, LANES)
    own = rows[0]
    finite = [jnp.isfinite(r) for r in rows]
    count = finite[0].astype(jnp.float32)
    for f in finite[1:]:
        count = count + f.astype(jnp.float32)
    sink_lo = [jnp.where(f, r, jnp.inf) for f, r in zip(finite, rows)]
    sink_hi = [jnp.where(f, r, -jnp.inf) for f, r in zip(finite, rows)]
    if variant == "sort":
        lower_raw = _sorting_network(sink_lo)[H]
        upper_raw = _sorting_network(sink_hi)[n_in - 1 - H]
    else:
        lower_raw = _running_small(sink_lo, H + 1)[H]
        upper_raw = _running_large(sink_hi, H + 1)[0]
    lower = jnp.minimum(lower_raw, sink_lo[0])
    upper = jnp.maximum(upper_raw, sink_hi[0])
    acc = jnp.where(finite[0], jnp.clip(rows[0], lower, upper), 0.0)
    for r, f in zip(rows[1:], finite[1:]):
        acc = acc + jnp.where(f, jnp.clip(r, lower, upper), 0.0)
    out_ref[...] = jnp.where(count >= 2 * H + 1, acc / count, own)


@functools.partial(
    jax.jit,
    static_argnames=("H", "variant", "block_rows", "interpret", "sanitize"),
)
def fused_resilient_aggregate(
    values: jnp.ndarray,
    H: int,
    *,
    variant: str = "select",
    block_rows: int | None = None,
    interpret: bool = False,
    sanitize: bool = False,
) -> jnp.ndarray:
    """Pallas twin of :func:`~rcmarl_tpu.ops.aggregation.resilient_aggregate`.

    Args:
      values: (n_in, ...) stacked neighbor values, own value at index 0.
      H: trim parameter (static); 0 <= 2H <= n_in-1.
      variant: 'select' (default; dual top-(H+1) running registers) or
        'sort' (the original sorting network) — bitwise-identical
        outputs, kept side by side for measured comparisons.
      block_rows: sublane rows per grid step (VMEM tile is
        n_in x block_rows x 128 floats); default per variant
        (:data:`_DEFAULT_BLOCK_ROWS`).
      interpret: run in the Pallas interpreter (for CPU tests).
      sanitize: non-finite-hardened epilogue (NaN/±Inf entries excluded
        per element, degree-deficit fallback to own value) — bitwise
        identical to the XLA backends' sanitize mode
        (:func:`_sanitized_agg_kernel`).

    Returns:
      (...) aggregated values in ``values.dtype``. Selection/clip/mean
      are computed in f32 (the VPU-native width) regardless of input
      dtype and cast back: exact for f32, an upcast for bf16, and a
      silent precision LOSS for f64 inputs under x64 — use the XLA path
      there.
    """
    if variant not in _BOUNDS:
        raise ValueError(
            f"unknown kernel variant {variant!r}; expected one of "
            f"{tuple(_BOUNDS)}"
        )
    if block_rows is None:
        block_rows = _DEFAULT_BLOCK_ROWS[variant]
    n_in = values.shape[0]
    if not 0 <= 2 * H <= n_in - 1:
        raise ValueError(f"H={H} invalid for n_in={n_in}: need 0 <= 2H <= n_in-1")
    out_shape = values.shape[1:]
    flat = values.reshape(n_in, -1).astype(jnp.float32)
    m = flat.shape[1]
    tile = block_rows * _LANES
    padded = ((m + tile - 1) // tile) * tile
    if padded != m:
        flat = jnp.pad(flat, ((0, 0), (0, padded - m)))
    rows_total = padded // _LANES
    v3 = flat.reshape(n_in, rows_total, _LANES)
    # the pl.BlockSpec list is BUILT from the introspectable plan — one
    # derivation for launch and lint alike
    launch_plan = kernel_plan(
        n_in, m, H, variant=variant, block_rows=block_rows, sanitize=sanitize
    )
    if sanitize:
        kernel = functools.partial(
            _sanitized_agg_kernel, n_in=n_in, H=H, variant=variant
        )
    else:
        kernel = functools.partial(
            _agg_kernel, n_in=n_in, H=H, bounds=_BOUNDS[variant]
        )
    in_op, out_op = launch_plan.inputs[0], launch_plan.outputs[0]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows_total, _LANES), jnp.float32),
        in_specs=[pl.BlockSpec(in_op.block_shape, in_op.index_map)],
        out_specs=pl.BlockSpec(out_op.block_shape, out_op.index_map),
        grid=launch_plan.grid,
        interpret=interpret,
    )(v3)
    return out.reshape(-1)[:m].reshape(out_shape).astype(values.dtype)


def fused_resilient_aggregate_tree(
    tree,
    H: int,
    *,
    variant: str = "select",
    block_rows: int | None = None,
    interpret: bool = False,
    sanitize: bool = False,
):
    """Aggregate every (n_in, ...) leaf of ``tree`` in ONE kernel launch.

    Ravels all leaves along their trailing dims into a single (n_in, P)
    block through the ONE shared ravel path
    (``aggregation.ravel_neighbor_tree`` — the exact layout the XLA
    one-launch paths and the fused-epoch pair block use, so the flat
    block enters the kernel without a second pack), runs
    :func:`fused_resilient_aggregate` once, and splits back — the whole
    hidden-layer consensus of an agent's trunk (reference
    ``resilient_CAC_agents.py:142-166``) becomes a single HBM pass
    instead of one selection per weight array. Bitwise the per-leaf
    dispatch (raveling is elementwise-neutral); mixed-dtype trees must
    go through :func:`~rcmarl_tpu.ops.aggregation.resilient_aggregate_tree`,
    whose layout guard falls back to per-leaf kernel launches.
    """
    flat, unravel = ravel_neighbor_tree(tree)
    return unravel(
        fused_resilient_aggregate(
            flat,
            H,
            variant=variant,
            block_rows=block_rows,
            interpret=interpret,
            sanitize=sanitize,
        )
    )
