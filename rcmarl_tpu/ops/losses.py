"""Losses with Keras reduction semantics, extended with validity masks.

Keras losses reduce with SUM_OVER_BATCH_SIZE: per-sample losses (already
averaged over output dims for MSE) are multiplied by optional sample
weights, summed, and divided by the NUMBER OF SAMPLES — not by the weight
sum (SURVEY.md §7 contracts 3/5). The reference always fits on fully-valid
batches; our buffers are fixed-capacity with a validity mask (so jitted
update blocks keep static shapes while the reference's buffer grows
1000 -> 2000 -> 3000 over the first three update blocks,
``train_agents.py:158-163``). Masked rows contribute zero to the sum and
are excluded from the sample count, which reproduces Keras numbers exactly
on the valid prefix.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

# Keras clips probabilities to [eps, 1-eps] before log in categorical
# cross-entropy (keras.backend.epsilon() == 1e-7).
KERAS_EPSILON = 1e-7


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _pick_label_probs(p, labels, n_classes):
    """``p[i, labels[i]]`` with a deterministic backward pass.

    Forward is the UNMODIFIED historical ``take_along_axis`` gather —
    bitwise the old path for every label value, including the loud
    non-finite result an out-of-range garbage label produces (the
    repo's non-finite guard rails key on that signal). The default VJP
    of that gather is a float scatter-add with
    ``unique_indices=false`` — an HLO whose duplicate-index
    accumulation order is implementation-defined, which the graftlint
    determinism census (`nondeterminism`) forbids in the hot path. One
    label per row means the indices ARE unique, so the cotangent is an
    exact one-hot product instead: ``g * 1.0`` at the label, ``g *
    0.0`` elsewhere — bitwise the scatter's result for every in-range
    label (the only kind the env can produce: actions are sampled from
    ``0..n_actions-1``). For garbage labels the two backwards differ
    (one-hot zeroes the row where the scatter transpose would wrap a
    negative index), but the forward is already non-finite there and
    the guards own that case.
    """
    return jnp.take_along_axis(p, labels[:, None], axis=-1)[:, 0]


def _pick_fwd(p, labels, n_classes):
    return _pick_label_probs(p, labels, n_classes), labels


def _pick_bwd(n_classes, labels, g):
    return (g[:, None] * jax.nn.one_hot(labels, n_classes, dtype=g.dtype), None)


_pick_label_probs.defvjp(_pick_fwd, _pick_bwd)


def _masked_mean(per_sample: jnp.ndarray, mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    if mask is None:
        return jnp.mean(per_sample)
    n = jnp.maximum(jnp.sum(mask), 1.0)
    # where() not multiply: garbage in masked rows must not poison the sum
    return jnp.sum(jnp.where(mask > 0, per_sample, 0.0)) / n


def weighted_mse(
    pred: jnp.ndarray,
    target: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """keras.losses.MeanSquaredError with sample weights and validity mask.

    pred/target: (B, out); sample_weight/mask: (B,) or None.
    """
    diff = pred - target
    if mask is not None:
        # sanitize BEFORE squaring: a plain where() on the loss would still
        # propagate NaN/inf from masked rows through the gradient
        diff = jnp.where(mask[:, None] > 0, diff, 0.0)
    per = jnp.mean(diff**2, axis=-1)  # mean over output dims
    if sample_weight is not None:
        per = per * sample_weight
    return _masked_mean(per, mask)


def weighted_sparse_ce(
    probs: jnp.ndarray,
    labels: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """keras.losses.SparseCategoricalCrossentropy (from_logits=False) with
    sample weights — the actor loss (``resilient_CAC_agents.py:38``).

    probs: (B, A) softmax outputs; labels: (B,) int class indices.
    """
    if mask is not None:
        # sanitize masked rows to a uniform distribution so NaN/garbage
        # cannot reach log() or its gradient
        probs = jnp.where(
            mask[:, None] > 0, probs, jnp.ones_like(probs) / probs.shape[-1]
        )
    # tf.keras normalizes to a distribution, then clips to [eps, 1-eps]
    p = probs / jnp.sum(probs, axis=-1, keepdims=True)
    p = jnp.clip(p, KERAS_EPSILON, 1.0 - KERAS_EPSILON)
    per = -jnp.log(
        _pick_label_probs(p, labels.astype(jnp.int32), p.shape[-1])
    )
    if sample_weight is not None:
        per = per * sample_weight
    return _masked_mean(per, mask)
