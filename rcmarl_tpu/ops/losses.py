"""Losses with Keras reduction semantics, extended with validity masks.

Keras losses reduce with SUM_OVER_BATCH_SIZE: per-sample losses (already
averaged over output dims for MSE) are multiplied by optional sample
weights, summed, and divided by the NUMBER OF SAMPLES — not by the weight
sum (SURVEY.md §7 contracts 3/5). The reference always fits on fully-valid
batches; our buffers are fixed-capacity with a validity mask (so jitted
update blocks keep static shapes while the reference's buffer grows
1000 -> 2000 -> 3000 over the first three update blocks,
``train_agents.py:158-163``). Masked rows contribute zero to the sum and
are excluded from the sample count, which reproduces Keras numbers exactly
on the valid prefix.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

# Keras clips probabilities to [eps, 1-eps] before log in categorical
# cross-entropy (keras.backend.epsilon() == 1e-7).
KERAS_EPSILON = 1e-7


def _masked_mean(per_sample: jnp.ndarray, mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    if mask is None:
        return jnp.mean(per_sample)
    n = jnp.maximum(jnp.sum(mask), 1.0)
    # where() not multiply: garbage in masked rows must not poison the sum
    return jnp.sum(jnp.where(mask > 0, per_sample, 0.0)) / n


def weighted_mse(
    pred: jnp.ndarray,
    target: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """keras.losses.MeanSquaredError with sample weights and validity mask.

    pred/target: (B, out); sample_weight/mask: (B,) or None.
    """
    diff = pred - target
    if mask is not None:
        # sanitize BEFORE squaring: a plain where() on the loss would still
        # propagate NaN/inf from masked rows through the gradient
        diff = jnp.where(mask[:, None] > 0, diff, 0.0)
    per = jnp.mean(diff**2, axis=-1)  # mean over output dims
    if sample_weight is not None:
        per = per * sample_weight
    return _masked_mean(per, mask)


def weighted_sparse_ce(
    probs: jnp.ndarray,
    labels: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """keras.losses.SparseCategoricalCrossentropy (from_logits=False) with
    sample weights — the actor loss (``resilient_CAC_agents.py:38``).

    probs: (B, A) softmax outputs; labels: (B,) int class indices.
    """
    if mask is not None:
        # sanitize masked rows to a uniform distribution so NaN/garbage
        # cannot reach log() or its gradient
        probs = jnp.where(
            mask[:, None] > 0, probs, jnp.ones_like(probs) / probs.shape[-1]
        )
    # tf.keras normalizes to a distribution, then clips to [eps, 1-eps]
    p = probs / jnp.sum(probs, axis=-1, keepdims=True)
    p = jnp.clip(p, KERAS_EPSILON, 1.0 - KERAS_EPSILON)
    per = -jnp.log(jnp.take_along_axis(p, labels[:, None].astype(jnp.int32), axis=-1))[
        :, 0
    ]
    if sample_weight is not None:
        per = per * sample_weight
    return _masked_mean(per, mask)
