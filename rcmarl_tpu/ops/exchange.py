"""Sparse neighbor exchange — the O(n·deg·P) mega-population gather.

The consensus exchange has two regimes. Small populations compile the
static ``Config.in_nodes`` topology into the program (rolls for
rotation-symmetric graphs, a constant fancy index otherwise —
:func:`rcmarl_tpu.training.update.gather_neighbor_messages`); the
gathered block is ``(N, n_in, P)``, and for the dense graphs the
reference favors, ``n_in`` grows with ``N`` — the exchange is
**quadratic** in the population. Mega-population cells (n=256/1024,
ROADMAP item 3) instead ride the time-varying random-geometric schedule
(PR 12, :func:`rcmarl_tpu.config.scheduled_in_nodes`): every agent
keeps exactly ``graph_degree`` scheduled in-neighbors, the indices flow
in as DATA, and the gather here touches only ``n · graph_degree · P``
elements — the cost the AUDIT.jsonl ``consensus_exchange`` ledger rows
pin (sparse strictly below dense at n=256, gated every ``lint --cost``
run).

This module is THE sparse exchange layer: one gather primitive shared
by both netstack arms (the dual-launch epoch and the combined
``(N, P_critic + P_tr)`` pair block both delegate their data-indexed
branch here), plus the host-side guard rails the schedule's hypothesis
twins pin — a scheduled graph that reaches the device is regular,
self-first, in-range, duplicate-free, and wide enough for the
configured trim (``2H + 1 <= degree``). Transport faults and sanitize
compose downstream unchanged: faulting operates on the *gathered*
block (``apply_link_faults_flat``), so the sparse block passes through
the exact fault/trim/clip/mean chain the dense block does — the
bitwise sparse-vs-dense pins in tests/test_exchange.py hold across the
whole sanitize/fault matrix.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def sparse_gather(tree, in_arr):
    """Gather each agent's scheduled in-neighborhood from ``tree``.

    ``tree``: pytree of ``(N, ...)`` leaves (stacked per-agent
    messages). ``in_arr``: ``(N, degree)`` integer gather indices, own
    index first per row — TRACED data, so per-block resampling
    re-dispatches one compiled program. Returns ``(N, degree, ...)``
    leaves, own message at neighbor slot 0.

    This is deliberately the plain advanced-indexing gather: XLA lowers
    it to one dynamic-gather op whose cost scales with the OUTPUT
    ``N * degree * P``, never with a dense ``N * N`` neighborhood — the
    scaling the cost ledger's ``consensus_exchange[sparse]`` row proves
    against its ``[dense]`` twin. On matching indices it is bitwise
    identical to the static constant-index gather (same op, indices as
    data instead of literals).
    """
    idx = jnp.asarray(in_arr)
    return jax.tree.map(lambda l: l[idx], tree)


def validate_graph(graph, n_agents: int, degree: int | None = None,
                   H: int | None = None) -> np.ndarray:
    """Host-side guard rails for a scheduled communication graph.

    Checks the invariants every array the device gather consumes must
    hold (the hypothesis twins in tests/test_exchange.py pin that
    :func:`rcmarl_tpu.config.scheduled_in_nodes` always produces them):

    - shape ``(n_agents, degree)`` with an integer dtype;
    - every row lists the agent itself FIRST (the reference's
      own-at-slot-0 convention the trim's own-anchoring relies on);
    - all indices in ``[0, n_agents)``;
    - no duplicate in-neighbors within a row (a duplicated sender would
      double its vote in the mean — a silent resilience regression);
    - ``2H + 1 <= degree`` when ``H`` is given (the trimming guarantee
      needs 2H+1 honest-capable inputs in every neighborhood).

    Returns the validated graph as an int32 numpy array; raises
    ``ValueError`` on any violation. The solo trainer's host loop and
    the CLI cells call this once per resample — O(N·deg) host work,
    nothing on device.
    """
    g = np.asarray(graph)  # lint: disable=host-sync (host-side guard)
    if g.ndim != 2 or g.shape[0] != n_agents:
        raise ValueError(
            f"scheduled graph must be (n_agents={n_agents}, degree); "
            f"got shape {g.shape}"
        )
    if not np.issubdtype(g.dtype, np.integer):
        raise ValueError(
            f"scheduled graph must be integer gather indices; got "
            f"dtype {g.dtype}"
        )
    deg = g.shape[1]
    if degree is not None and deg != degree:
        raise ValueError(
            f"scheduled graph degree {deg} != expected {degree}"
        )
    if deg < 1:
        raise ValueError("scheduled graph needs degree >= 1 (self)")
    if H is not None and not 0 <= 2 * H <= deg - 1:
        raise ValueError(
            f"H={H} too large for scheduled degree {deg}: need "
            "2H <= degree-1 in every neighborhood"
        )
    if (g < 0).any() or (g >= n_agents).any():
        bad = np.argwhere((g < 0) | (g >= n_agents))[0]
        raise ValueError(
            f"scheduled graph index out of range at row {bad[0]} slot "
            f"{bad[1]}: {g[bad[0], bad[1]]} not in [0, {n_agents})"
        )
    if (g[:, 0] != np.arange(n_agents)).any():
        bad = int(  # lint: disable=host-sync (host-side guard)
            np.argwhere(g[:, 0] != np.arange(n_agents))[0][0]
        )
        raise ValueError(
            f"scheduled graph row {bad} must list the agent itself "
            f"first (got {g[bad, 0]}; own-at-slot-0 convention)"
        )
    for i in range(n_agents):
        if len(set(g[i].tolist())) != deg:
            raise ValueError(
                f"scheduled graph row {i} has duplicate in-neighbors "
                f"({g[i].tolist()}); a duplicated sender would double "
                "its vote in the trimmed mean"
            )
    return np.asarray(g, dtype=np.int32)  # lint: disable=host-sync


def validate_graph_window(window, n_agents: int, degree: int | None = None,
                          H: int | None = None) -> np.ndarray:
    """:func:`validate_graph` over every slice of an ``(S, N, degree)``
    stacked-schedule operand (:func:`rcmarl_tpu.config.schedule_window`)
    — the window-level guard rail ``train_scanned`` applies before the
    stacked graphs become scan data. Same invariants, applied per
    block; returns the validated int32 window."""
    w = np.asarray(window)  # lint: disable=host-sync (host-side guard)
    if w.ndim != 3:
        raise ValueError(
            f"stacked-schedule window must be (S, n_agents, degree); "
            f"got shape {w.shape}"
        )
    return np.stack(
        [validate_graph(w[b], n_agents, degree=degree, H=H)
         for b in range(w.shape[0])]
    )


def exchange_cost_model(n_agents: int, degree: int, p_total: int,
                        itemsize: int = 4) -> dict:
    """The analytic byte cost of one sparse exchange, for honest row
    tags next to the compiled-cost measurements (the fused-gate rows'
    ``bytes_model`` convention, lint/cost.py): the gather reads the
    ``(N, P)`` message block plus the ``(N, deg)`` int32 indices and
    writes the ``(N, deg, P)`` gathered block — every term linear in
    ``n_agents * degree``, never ``n_agents**2``."""
    out = n_agents * degree * p_total * itemsize
    # all-Python shape math — nothing traced reaches this module
    return {
        "read_block": float(n_agents * p_total * itemsize),  # lint: disable=host-sync
        "read_indices": float(n_agents * degree * 4),  # lint: disable=host-sync
        "write_gathered": float(out),  # lint: disable=host-sync
        "total": float(  # lint: disable=host-sync
            n_agents * p_total * itemsize + n_agents * degree * 4 + out
        ),
    }
