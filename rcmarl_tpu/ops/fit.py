"""Keras ``model.fit`` semantics as jittable scan programs.

The reference relies on three distinct Keras fit shapes (SURVEY.md §7
contract 3):

1. Cooperative local critic/TR fits: ``fit(batch_size=FULL, epochs=5)`` —
   5 full-batch gradient steps against a FIXED pre-computed target
   (``resilient_CAC_agents.py:118,136``) -> :func:`fit_full_batch`.
2. Adversary critic/TR fits: ``fit(epochs=10, batch_size=32)`` — shuffled
   mini-batch SGD incl. a partial final batch
   (``adversarial_CAC_agents.py:133,150,163,239,251``) -> :func:`fit_minibatch`.
3. Adversary actor fits: ``fit(batch_size=200, epochs=1)`` with Adam
   (``adversarial_CAC_agents.py:41,116,224``) -> :func:`fit_minibatch`
   with an Adam step function.

All run under ``jit`` with STATIC shapes over fixed-capacity buffers with
validity masks. The key trick for exact Keras semantics: each epoch draws
a "valid-first shuffle" — a permutation that places all valid rows first
in uniform random order, padding rows last — so sequential batch slicing
visits exactly the batches Keras would visit (including the same-sized
partial final batch), and trailing all-padding batches contribute zero
gradient. Keras's SUM_OVER_BATCH_SIZE division by the per-batch sample
count is reproduced by dividing by the batch's valid count.
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from rcmarl_tpu.ops.losses import weighted_mse
from rcmarl_tpu.ops.optim import clip_grads, sgd_update


def fit_full_batch(
    params,
    loss_fn: Callable[[object], jnp.ndarray],
    n_steps: int,
    lr: float,
    clip: float = 0.0,
):
    """``n_steps`` full-batch SGD steps on a fixed objective.

    ``loss_fn`` closes over data, target, and mask; the target must be
    pre-computed by the caller (the reference computes the TD target once,
    BEFORE the 5-step fit, ``resilient_CAC_agents.py:114-118``).

    ``clip`` (static, default 0.0 = off, bit-for-bit the reference op
    sequence) bounds each step's global gradient norm — the
    mega-population stability rail (:func:`rcmarl_tpu.ops.optim.clip_grads`).

    Returns (final_params, first_step_loss) — the reference logs
    ``history['loss'][0]`` (``resilient_CAC_agents.py:122``).
    """
    grad_fn = jax.value_and_grad(loss_fn)

    def step(p, _):
        loss, g = grad_fn(p)
        return sgd_update(p, clip_grads(g, clip), lr), loss

    final, losses = jax.lax.scan(step, params, None, length=n_steps)
    return final, losses[0]


def valid_first_shuffle(
    key: jax.Array,
    mask: jnp.ndarray,
    n_batches: int,
    batch_size: int,
    assume_valid: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-epoch shuffled batch index plan.

    Args:
      key: PRNG key for this epoch's shuffle.
      mask: (capacity,) float/bool validity of each buffer row.
      n_batches/batch_size: static batch plan; n_batches * batch_size >=
        capacity (indices beyond capacity are padding).
      assume_valid: static promise that ``mask`` is all-ones (rows with
        no invalid tail, e.g. the always-full on-policy actor window).
        Skips the valid-first penalty on the shuffle scores and derives
        the slot validity statically — BITWISE the same plan (adding an
        exact 0.0 penalty cannot reorder the argsort, and
        ``sum(ones(cap)) == cap``), minus the permutation bookkeeping.

    Returns:
      (idx, batch_valid): idx (n_batches, batch_size) int32 row indices;
      batch_valid (n_batches, batch_size) float32, 1.0 where the slot holds
      a real (valid) sample. Valid rows occupy a uniformly-shuffled prefix,
      exactly like Keras's shuffle over the dense array.
    """
    cap = mask.shape[0]
    pad = n_batches * batch_size - cap
    scores = jax.random.uniform(key, (cap,))
    if not assume_valid:
        scores = scores + (1.0 - mask.astype(jnp.float32)) * 2.0
    order = jnp.argsort(scores).astype(jnp.int32)  # valid rows first, shuffled
    n_valid = cap if assume_valid else jnp.sum(mask)
    slot_valid = (jnp.arange(n_batches * batch_size) < n_valid).astype(
        jnp.float32
    )
    order_padded = jnp.concatenate([order, jnp.zeros((pad,), jnp.int32)])
    return (
        order_padded.reshape(n_batches, batch_size),
        slot_valid.reshape(n_batches, batch_size),
    )


def identity_plan(
    mask: jnp.ndarray, n_batches: int, batch_size: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The NO-shuffle epoch plan: row i stays in slot i, slot validity is
    the row's own mask. With ``n_batches == 1`` and ``batch_size ==
    capacity`` this makes one "minibatch" step visit the whole buffer in
    storage order under the buffer mask — exactly the full-batch fit's
    loss (gathering with an iota index is value-identical to no gather),
    which is how :func:`fused_fit_scan` runs the full-batch flavor
    through the shared minibatch step body bitwise."""
    cap = mask.shape[0]
    pad = n_batches * batch_size - cap
    idx = jnp.concatenate(
        [jnp.arange(cap, dtype=jnp.int32), jnp.zeros((pad,), jnp.int32)]
    )
    bvalid = jnp.concatenate(
        [mask.astype(jnp.float32), jnp.zeros((pad,), jnp.float32)]
    )
    return (
        idx.reshape(n_batches, batch_size),
        bvalid.reshape(n_batches, batch_size),
    )


def fit_minibatch(
    key: jax.Array,
    params,
    batch_loss_fn: Callable[[object, jnp.ndarray, jnp.ndarray], jnp.ndarray],
    capacity: int,
    mask: jnp.ndarray,
    epochs: int,
    batch_size: int,
    lr: float = 0.0,
    opt_state=None,
    opt_update: Optional[Callable] = None,
    shuffle: bool = True,
    assume_valid: bool = False,
    clip: float = 0.0,
):
    """Shuffled mini-batch fit with Keras epoch/batch structure.

    Args:
      batch_loss_fn(params, idx, batch_valid) -> scalar loss for the rows
        ``idx`` (gathering data it closes over), dividing by the batch's
        valid count (use ops.losses with ``mask=batch_valid``).
      epochs/batch_size: Keras fit arguments (static).
      lr: SGD learning rate, used when ``opt_update`` is None.
      opt_state/opt_update: optional stateful optimizer (e.g. TF-Adam);
        ``opt_update(params, grads, state) -> (params, state)``.
      shuffle: static; True (default, the Keras semantics) draws a
        fresh :func:`valid_first_shuffle` per epoch; False runs the
        :func:`identity_plan` instead — with ``batch_size >= capacity``
        that is a full-batch SGD fit expressed in this scan body,
        bitwise :func:`fit_full_batch` (``key`` is then never consumed).
      assume_valid: static promise that ``mask`` is all-ones; the
        shuffle skips the valid-first penalty work (bitwise-identical
        plan — see :func:`valid_first_shuffle`).
      clip: static global-gradient-norm ceiling applied before the
        update (either optimizer); 0.0 (default) traces no extra ops —
        the reference-exact program.

    Returns (final_params, final_opt_state, first_epoch_mean_loss) —
    Keras's ``history['loss'][0]`` is the mean of per-batch losses over the
    first epoch's real batches.
    """
    n_batches = math.ceil(capacity / batch_size)
    grad_fn = jax.value_and_grad(batch_loss_fn)
    # shuffle=False consumes no randomness: scan over a dummy axis so
    # the key is provably untouched (the fused coop rows pass a zero).
    ekeys = (
        jax.random.split(key, epochs)
        if shuffle
        else jnp.zeros((epochs,), jnp.int32)
    )

    def epoch(carry, ekey):
        p, ostate = carry
        if shuffle and assume_valid:
            idx, bvalid = valid_first_shuffle(
                ekey, mask, n_batches, batch_size, assume_valid=True
            )
        elif shuffle:
            # positional call, no flag: tests monkeypatch this hook
            # with 4-arg twins
            idx, bvalid = valid_first_shuffle(
                ekey, mask, n_batches, batch_size
            )
        else:
            idx, bvalid = identity_plan(mask, n_batches, batch_size)

        def mb(carry, xs):
            p, ostate = carry
            bidx, bval = xs
            loss, g = grad_fn(p, bidx, bval)
            g = clip_grads(g, clip)
            nonempty = jnp.sum(bval) > 0
            if opt_update is None:
                newp = sgd_update(p, g, lr)
                newstate = ostate
            else:
                newp, newstate = opt_update(p, g, ostate)
            # Keras never runs an empty batch: skip update AND optimizer
            # state advance for all-padding batches.
            p = jax.tree.map(lambda a, b: jnp.where(nonempty, b, a), p, newp)
            if opt_update is not None:
                ostate = jax.tree.map(
                    lambda a, b: jnp.where(nonempty, b, a), ostate, newstate
                )
            else:
                ostate = newstate
            return (p, ostate), (loss, jnp.sum(bval))

        (p, ostate), (losses, counts) = jax.lax.scan(mb, (p, ostate), (idx, bvalid))
        if not shuffle and n_batches == 1:
            # the full-batch flavor: "epoch loss" IS the one batch loss
            # (the weighted-mean arithmetic below would round it —
            # fit_full_batch's first-step loss must come back bitwise)
            return (p, ostate), losses[0]
        # Keras's epoch loss is the sample-count-weighted mean of batch losses
        mean_loss = jnp.sum(losses * counts) / jnp.maximum(jnp.sum(counts), 1.0)
        return (p, ostate), mean_loss

    (params, opt_state), epoch_losses = jax.lax.scan(epoch, (params, opt_state), ekeys)
    return params, opt_state, epoch_losses[0]


# --------------------------------------------------------------------------
# Targeted regression fits (the shape every critic/TR fit reduces to)
# --------------------------------------------------------------------------
#
# All four critic/TR fit flavors in agents/updates.py are "regress
# forward(params, x) onto a FIXED precomputed target under a validity
# mask" — the TD bootstrap (when any) happens once, before the fit.
# Expressing that shape directly (data as ARGUMENTS, not closures) is
# what lets the netstack vmap ONE fit program over a leading (net,
# agent) axis with per-net inputs/targets, instead of tracing one scan
# per net family.


def fit_mse_full_batch(
    params,
    forward: Callable[[object, jnp.ndarray], jnp.ndarray],
    x: jnp.ndarray,
    target: jnp.ndarray,
    mask: jnp.ndarray,
    n_steps: int,
    lr: float,
    clip: float = 0.0,
):
    """:func:`fit_full_batch` specialized to masked-MSE regression of
    ``forward(params, x)`` onto a fixed ``target``. Identical op
    sequence to the closure form (same grads, same scan)."""
    target = jax.lax.stop_gradient(target)
    return fit_full_batch(
        params,
        lambda p: weighted_mse(forward(p, x), target, mask=mask),
        n_steps,
        lr,
        clip=clip,
    )


def fit_mse_minibatch(
    key: jax.Array,
    params,
    forward: Callable[[object, jnp.ndarray], jnp.ndarray],
    x: jnp.ndarray,
    target: jnp.ndarray,
    mask: jnp.ndarray,
    epochs: int,
    batch_size: int,
    lr: float,
    clip: float = 0.0,
):
    """:func:`fit_minibatch` specialized the same way (the adversary
    critic/TR fit shape: Keras ``fit(epochs, batch_size)`` with shuffled
    minibatches toward a fixed target)."""
    target = jax.lax.stop_gradient(target)
    out, _, loss = fit_minibatch(
        key,
        params,
        lambda p, idx, bval: weighted_mse(
            forward(p, x[idx]), target[idx], mask=bval
        ),
        capacity=x.shape[0],
        mask=mask,
        epochs=epochs,
        batch_size=batch_size,
        lr=lr,
        clip=clip,
    )
    return out, loss


# --------------------------------------------------------------------------
# Fitstack: every same-scheduled fit flavor as ONE stacked scan
# --------------------------------------------------------------------------
#
# The Podracer/Anakin recipe (arXiv:2104.06272): batch every SAME-SHAPED
# program into one device-resident launch. The four critic/TR fit
# flavors come in exactly two schedule shapes — the cooperative
# full-batch fit (``coop_fit_steps`` whole-buffer SGD steps) and the
# adversary minibatch fit (``adv_fit_epochs`` x shuffled
# ``adv_fit_batch`` batches) — and :class:`FitSchedule` names a shape
# statically. :func:`fused_fit_scan` then runs EVERY flavor of one
# shape as a single (row, agent)-vmapped scan over a stacked parameter
# block, through the ONE unified step body of :func:`fit_minibatch`:
# full-batch rows use the identity plan (one "minibatch" covering the
# buffer — value-identical to no gather), minibatch rows draw their
# valid-first shuffles from the exact keys the dual-launch arm would
# draw. Rows are pinned leaf-for-leaf bitwise against the PR-4 pair-fit
# arm (tests/test_fitstack_properties.py). The stacked (rows, agent,
# batch) layout is deliberately kernel-friendly: a follow-up Pallas fit
# kernel can tile the row axis without re-plumbing the schedule.


class FitSchedule(NamedTuple):
    """One fit flavor's STATIC schedule shape (hashable, jit-static).

    epochs/batch_size: the Keras fit arguments; ``n_batches`` is derived
    (``ceil(capacity / batch_size)``). ``shuffle=False`` selects the
    identity plan (the full-batch flavor: set ``batch_size`` to the
    buffer capacity). ``assume_valid`` statically promises an all-ones
    mask (skips the valid-first penalty work, bitwise-identical plan).
    Flavors sharing a ``FitSchedule`` stack into one
    :func:`fused_fit_scan` launch.
    """

    epochs: int
    batch_size: int
    shuffle: bool = True
    assume_valid: bool = False


def fit_mse_sched(
    key: jax.Array,
    params,
    forward: Callable[[object, jnp.ndarray], jnp.ndarray],
    x: jnp.ndarray,
    target: jnp.ndarray,
    mask: jnp.ndarray,
    schedule: FitSchedule,
    lr: float,
    clip: float = 0.0,
):
    """Masked-MSE regression of ``forward(params, x)`` onto a fixed
    ``target`` under an arbitrary :class:`FitSchedule` — the ONE row
    program of the fused scan. With ``schedule.shuffle`` this is exactly
    :func:`fit_mse_minibatch` (same delegation, same op sequence);
    without it, :func:`fit_mse_full_batch` expressed through the same
    scan body (``key`` unread). Returns (params, first_epoch_loss)."""
    target = jax.lax.stop_gradient(target)
    out, _, loss = fit_minibatch(
        key,
        params,
        lambda p, idx, bval: weighted_mse(
            forward(p, x[idx]), target[idx], mask=bval
        ),
        capacity=x.shape[0],
        mask=mask,
        epochs=schedule.epochs,
        batch_size=schedule.batch_size,
        lr=lr,
        shuffle=schedule.shuffle,
        assume_valid=schedule.assume_valid,
        clip=clip,
    )
    return out, loss


def fused_fit_scan(
    keys,
    params_rows,
    forward: Callable[[object, jnp.ndarray], jnp.ndarray],
    x_rows: jnp.ndarray,
    targets_rows: jnp.ndarray,
    mask: jnp.ndarray,
    schedule: FitSchedule,
    lr: float,
    clip: float = 0.0,
):
    """ALL fit flavors of one schedule shape as ONE stacked scan.

    Args:
      keys: (R, N) PRNG keys, row r agent i's minibatch shuffle stream
        (pass zeros-shaped keys for ``shuffle=False`` schedules — never
        consumed).
      params_rows: stacked nets, leaves (R, N, ...) — first-layer rows
        zero-padded to a common input width
        (:func:`rcmarl_tpu.models.mlp.netstack_stack_rows`).
      x_rows: (R, B, width) per-row fit inputs (padded to match).
      targets_rows: (R, N, B, 1) per-row precomputed regression targets.
      mask: (B,) shared buffer validity.
      schedule: the rows' SHARED static schedule shape.

    Returns (fitted rows, (R, N) first-epoch losses).
    """
    def fit_one(k, p, x, t):
        return fit_mse_sched(k, p, forward, x, t, mask, schedule, lr, clip)

    per_agent = jax.vmap(fit_one, in_axes=(0, 0, None, 0))
    return jax.vmap(per_agent, in_axes=(0, 0, 0, 0))(
        keys, params_rows, x_rows, targets_rows
    )
