"""One-kernel phase-II consensus: gather -> fault -> trim -> clip/mean
in a single VMEM-resident Pallas program.

The netstack epoch's phase II is HBM-bandwidth-bound: the XLA arm
materializes the gathered ``(N, n_in, P_trunk)`` neighbor block in HBM
(the gather's output), rewrites it through the transport-fault
transform, and re-reads it for the trim/clip/mean — every intermediate
is ``n_in`` times the parameter state. This kernel keeps each column
tile of the COMBINED ``(N, P_critic + P_tr)`` pair block resident in
VMEM across the whole chain: the neighbor gather happens in-register
(static row selects from the VMEM-resident agent axis), the per-link
fault chain applies scalar masks drawn host-side from the exact
:func:`rcmarl_tpu.faults.apply_link_faults_flat` key structure, and the
2(H+1)-register trim chain + clip/mean epilogue
(:mod:`rcmarl_tpu.ops.aggregation`'s register helpers — the strategy
the Pallas tradition here has always used) write only the aggregated
``(N, P_trunk)`` tile back. HBM traffic: one read of the stacked
messages (+ the stale-replay block when ``stale_p > 0``), one write of
the aggregate —
vs the two-launch arm's gather write + fault rewrite + aggregation
re-read, each ``n_in``-fold. ``AUDIT.jsonl``'s
``consensus_trunk[pallas_fused]`` vs ``consensus_trunk[two_launch]``
rows carry that claim as a CI-gated ledger fact
(:func:`rcmarl_tpu.lint.cost.fused_consensus_cost_rows`).

Bitwise contract (the house discipline, tests/test_fused_epoch.py):
every trim bound is an exact input-value selection (register chain ≡
tournament ≡ sort), and the SANITIZE epilogue mirrors the XLA
reference op-for-op — the slot-ordered finite count and clip
accumulate that the six-backend contract was *designed* around
(ops/aggregation.py "Sanitized aggregation": an explicit chain of
binary adds is the one reduction XLA can never reassociate). The
fused epoch is therefore pinned leaf-for-leaf BITWISE against
``consensus_impl='xla'`` across the whole sanitize matrix —
{regular, ragged} x {clean, drop/NaN/stale/flip/inf faulted} x
{H=0, H>0, traced H} x mixed casts. PLAIN (sanitize-off) cells keep
the historical kernel contract instead — allclose at f32 rounding —
because their ``jnp.mean`` epilogue is reassociated freely by XLA's
fusion pass (measured: the same gathered block means to 1-2 ULP
different bits in different fusion contexts), exactly the tolerance
``tests/test_pallas_aggregation.py`` has always pinned the leaf
kernel with. One documented fallback to the XLA arm: ``corrupt_p >
0`` plans (the additive-noise draw's erfinv tail gets FMA-fused into
whatever consumes it, so its BITS are fusion-context-dependent — and
the ``(N, n_in, P)`` noise is n_in-fold the block, structurally
halving the kernel's traffic win anyway).

Time-varying (scheduled) communication graphs — the SPARSE one-kernel
epoch: a traced ``(N, degree)`` gather-index array
(:func:`rcmarl_tpu.config.scheduled_in_nodes`) rides the kernel as a
SCALAR-PREFETCH operand (``pltpu.PrefetchScalarGridSpec``) instead of
being unrolled into the program: the in-kernel gather becomes dynamic
row selects off the SMEM-resident index block, so per-block graph
resampling re-dispatches ONE compiled kernel and the ``(N, deg, P)``
gathered block still never materializes in HBM — the sparse analogue
of the static win, pinned bitwise against the
``ops/exchange.py:sparse_gather`` XLA arm across the same matrix
(tests/test_sparse_fused.py) and carried by the
``sparse_consensus[xla_chain]`` vs ``[pallas_fused]`` ledger rows
(:func:`rcmarl_tpu.lint.cost.sparse_consensus_cost_rows`). Scheduled
graphs are regular by construction, so the sparse path never sees a
validity mask.

What stays XLA (by design, documented in README "One-kernel epoch"):
the tiny head-column gather+fault (``P_head = 2(h+1)`` floats per
agent), the projection einsum + per-sample estimate aggregation
(MXU matmuls over the batch, already fused well by XLA), and the
normalized team head step. The kernel emits the post-consensus trunk
block; ``training/update.py`` runs the tail.

Real lowering rides the queued TPU session (scripts/tpu_session.sh);
on this host the kernel runs in interpreter mode
(``consensus_impl='pallas_fused_interpret'``), and the lint cost arm
records real-Pallas-on-CPU as notes, never passes.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from rcmarl_tpu.faults import FaultPlan, _link_masks
from rcmarl_tpu.ops.aggregation import _running_large, _running_small
from rcmarl_tpu.ops.dma_model import (
    BlockOperand,
    KernelPlan,
    consensus_model_bytes,
    pad_to_tile,
    sparse_consensus_model_bytes,
)

_LANES = 128

#: Default sublane rows per grid step: the register-chain trim keeps
#: only ~2(H+1) live (rows, 128) arrays per agent, but the whole agent
#: axis is VMEM-resident (the in-kernel gather reads it), so the tile
#: is kept a notch under the leaf kernel's 64.
_DEFAULT_BLOCK_ROWS = 8


class FaultFields(NamedTuple):
    """The per-epoch transport-fault draw, precomputed XLA-side so the
    kernel's fault chain is BITWISE the two-launch arm's.

    masks: ``(2, 4, N, n_in)`` f32 0/1 — per tree (0 = critic, 1 = TR),
    the stale / flip / bomb(drop|nan) / inf link masks of
    :func:`rcmarl_tpu.faults._link_masks` (bomb pre-ORed exactly as
    ``_fault_payload`` does). inf_sign: ``(2, N, n_in)`` f32 ±inf.
    Corruption noise never reaches the kernel: ``corrupt_p > 0`` plans
    take the XLA reference arm (module docstring).
    """

    masks: jnp.ndarray
    inf_sign: jnp.ndarray


_MASK_ORDER = ("stale", "flip", "bomb", "inf")


def draw_fault_fields(
    fkey: jax.Array,
    plan: FaultPlan,
    n_agents: int,
    n_in: int,
    segments,
) -> FaultFields:
    """Draw the per-link fault fields for one epoch's combined block.

    ``fkey`` is the epoch fault key (pre per-tree fold_in), ``segments``
    the :func:`training.update._pair_segments` rows; the key structure
    mirrors :func:`rcmarl_tpu.faults.apply_link_faults_flat` draw for
    draw, so a mask plane here is bitwise the flat transform's. Masks
    are bernoulli threshold compares on threefry bits — integer-exact,
    immune to the fusion-context rounding that rules the corruption
    noise out of the kernel.
    """
    shape = (n_agents, n_in)
    tree_ids = sorted({t for t, *_ in segments})
    keys = {
        t: jax.random.fold_in(jax.random.fold_in(fkey, t), plan.seed)
        for t in tree_ids
    }
    raw = {t: _link_masks(keys[t], plan, shape) for t in tree_ids}
    masks = jnp.stack(
        [
            jnp.stack(
                [
                    (
                        (raw[t]["drop"] | raw[t]["nan"])
                        if kind == "bomb"
                        else raw[t][kind]
                    ).astype(jnp.float32)
                    for kind in _MASK_ORDER
                ]
            )
            for t in tree_ids
        ]
    )  # (2, 4, N, n_in)
    inf_sign = jnp.stack([raw[t]["inf_sign"] for t in tree_ids])
    return FaultFields(masks=masks, inf_sign=inf_sign)


def kernel_compatible_plan(plan: Optional[FaultPlan]) -> bool:
    """True when the fused kernel can carry ``plan`` in-kernel with the
    bitwise contract intact: any plan without additive corruption
    (``corrupt_p > 0`` routes the epoch to the XLA reference arm —
    module docstring)."""
    return plan is None or not plan.active or float(plan.corrupt_p) <= 0.0


def head_segments(segments, n_trunk: int):
    """The head-column rows of a ``_pair_segments`` tuple, re-offset to
    the sliced head block — what the XLA-side head fault transform
    consumes (``leaf_idx`` preserved, so the per-leaf noise streams stay
    bitwise the full-block transform's)."""
    return tuple(
        (t, leaf_idx, off - n_trunk, size)
        for t, leaf_idx, off, size in segments
        if off >= n_trunk
    )


# --------------------------------------------------------------------------
# In-kernel aggregation epilogues — each mirrors its XLA twin op-for-op
# --------------------------------------------------------------------------


def _plain_agg(rows, H):
    """Twin of the static-H ``resilient_aggregate`` xla branch."""
    vals = jnp.stack(rows)
    if H == 0:
        return jnp.mean(vals, axis=0)
    lo = _running_small(rows, H + 1)[H]
    hi = _running_large(rows, H + 1)[0]
    lower = jnp.minimum(lo, rows[0])
    upper = jnp.maximum(hi, rows[0])
    return jnp.mean(jnp.clip(vals, lower, upper), axis=0)


def _dynamic_agg(rows, H):
    """Twin of ``_dynamic_h_aggregate`` (traced H, plain): the full
    legal-range k_max register chain, traced trim index into the
    stacked selections."""
    n_in = len(rows)
    k_max = (n_in - 1) // 2 + 1
    small = jnp.stack(_running_small(rows, k_max))
    large = jnp.stack(_running_large(rows, k_max))
    lower_raw = jnp.take(small, H, axis=0)
    upper_raw = jnp.take(large, k_max - 1 - H, axis=0)
    lower = jnp.minimum(lower_raw, rows[0])
    upper = jnp.maximum(upper_raw, rows[0])
    return jnp.mean(jnp.clip(jnp.stack(rows), lower, upper), axis=0)


def _masked_agg(rows, H, va):
    """Twin of ``_masked_aggregate`` with the agent's STATIC validity
    row ``va`` (padded ragged graphs): identical value content — a
    where() under a compile-time mask is the select it lowers to."""
    count = jnp.float32(sum(va))  # static valid-slot count (exact in f32)
    zeros = jnp.zeros_like(rows[0])
    if H == 0:
        kept = [r if va[k] else zeros for k, r in enumerate(rows)]
        return jnp.sum(jnp.stack(kept), axis=0) / count
    inf = jnp.full_like(rows[0], jnp.inf)
    sink_lo = [r if va[k] else inf for k, r in enumerate(rows)]
    sink_hi = [r if va[k] else -inf for k, r in enumerate(rows)]
    lower = jnp.minimum(_running_small(sink_lo, H + 1)[H], rows[0])
    upper = jnp.maximum(_running_large(sink_hi, H + 1)[0], rows[0])
    clipped = [
        jnp.clip(r, lower, upper) if va[k] else zeros
        for k, r in enumerate(rows)
    ]
    return jnp.sum(jnp.stack(clipped), axis=0) / count


def _sanitized_agg(rows, H, va, traced_h: bool):
    """Twin of ``_sanitized_aggregate`` / ``_sanitized_dynamic``: the
    slot-ordered finite count, ±inf sentinel sinks, exact-selection
    bounds, own-anchoring via the sunk own row, slot-ordered clip
    accumulate, and the 2H+1 degree-deficit fallback — the op sequence
    every backend's bitwise contract pins (tests/test_faults.py)."""
    n_in = len(rows)
    own = rows[0]
    finite = [jnp.isfinite(r) for r in rows]
    if va is not None:
        false = jnp.zeros_like(finite[0])
        finite = [f if va[k] else false for k, f in enumerate(finite)]
    count = finite[0].astype(jnp.float32)
    for f in finite[1:]:
        count = count + f.astype(jnp.float32)
    sink_lo = [jnp.where(f, r, jnp.inf) for f, r in zip(finite, rows)]
    sink_hi = [jnp.where(f, r, -jnp.inf) for f, r in zip(finite, rows)]
    if traced_h:
        k_max = (n_in - 1) // 2 + 1
        lower_raw = jnp.take(
            jnp.stack(_running_small(sink_lo, k_max)), H, axis=0
        )
        upper_raw = jnp.take(
            jnp.stack(_running_large(sink_hi, k_max)), k_max - 1 - H, axis=0
        )
    else:
        lower_raw = _running_small(sink_lo, H + 1)[H]
        upper_raw = _running_large(sink_hi, H + 1)[0]
    lower = jnp.minimum(lower_raw, jnp.where(finite[0], own, jnp.inf))
    upper = jnp.maximum(upper_raw, jnp.where(finite[0], own, -jnp.inf))
    acc = jnp.where(finite[0], jnp.clip(rows[0], lower, upper), 0.0)
    for r, f in zip(rows[1:], finite[1:]):
        acc = acc + jnp.where(f, jnp.clip(r, lower, upper), 0.0)
    return jnp.where(count >= 2 * H + 1, acc / count, own)


# --------------------------------------------------------------------------
# The kernel
# --------------------------------------------------------------------------


def _fault_chain(v, stale_row, masks, inf_sign, tree0, plan, a, k):
    """In-kernel twin of :func:`rcmarl_tpu.faults._fault_payload` for
    one (agent ``a``, slot ``k``) payload row: the per-tree scalar link
    masks are broadcast per element through the static column->tree
    select ``tree0`` (the combined block carries both trees)."""

    def m(kind):
        i = _MASK_ORDER.index(kind)
        return jnp.where(tree0, masks[0, i, a, k], masks[1, i, a, k]) > 0

    if float(plan.stale_p) > 0.0:
        v = jnp.where(m("stale"), stale_row, v)
    if float(plan.flip_p) > 0.0:
        v = jnp.where(m("flip"), -v, v)
    if float(plan.drop_p) > 0.0 or float(plan.nan_p) > 0.0:
        v = jnp.where(m("bomb"), jnp.nan, v)
    if float(plan.inf_p) > 0.0:
        sign = jnp.where(tree0, inf_sign[0, a, k], inf_sign[1, a, k])
        v = jnp.where(m("inf"), sign, v)
    return v


def _consensus_kernel(
    *refs,
    n_agents: int,
    n_in: int,
    in_arr,
    H,
    traced_h: bool,
    sanitize: bool,
    valid,
    plan,
    tree_split: int,
    block_rows: int,
    has_stale: bool,
):
    """One (N, block_rows, LANES) column tile: in-register gather of
    every agent's neighborhood, the per-link fault chain, and the
    agent's trim/clip/mean epilogue — nothing but the aggregate leaves
    the tile.

    ``in_arr`` is either the STATIC nested index tuples (the gather
    unrolls compile-time row selects) or None — the SPARSE path, where
    the leading ref is the scalar-prefetched ``(N, degree)`` int32
    schedule block and each row select is a dynamic slice off it."""
    it = iter(refs)
    idx_ref = next(it) if in_arr is None else None
    msgs_ref = next(it)
    stale_ref = next(it) if has_stale else None
    masks_ref = next(it) if plan is not None else None
    sign_ref = next(it) if plan is not None else None
    h_ref = next(it) if traced_h else None
    out_ref = next(it)

    blk = msgs_ref[...]  # (N, block_rows, LANES) — the VMEM residents
    stale_blk = stale_ref[...] if has_stale else None
    masks = masks_ref[...] if plan is not None else None
    inf_sign = sign_ref[...] if plan is not None else None
    h_val = h_ref[0, 0] if traced_h else H

    tree0 = None
    if plan is not None:
        # global flat column index of each tile element -> tree select
        base = pl.program_id(0) * block_rows * _LANES
        col = (
            base
            + jax.lax.broadcasted_iota(jnp.int32, (block_rows, _LANES), 0)
            * _LANES
            + jax.lax.broadcasted_iota(jnp.int32, (block_rows, _LANES), 1)
        )
        tree0 = col < tree_split

    def _row(src, a, k):
        # static graphs: compile-time row select (unrolled); sparse
        # graphs: dynamic row select off the prefetched schedule block
        if in_arr is not None:
            return src[in_arr[a][k]]
        return jax.lax.dynamic_index_in_dim(
            src, idx_ref[a, k], axis=0, keepdims=False
        )

    out_rows = []
    for a in range(n_agents):
        rows = []
        for k in range(n_in):
            v = _row(blk, a, k)
            if plan is not None:
                rows.append(
                    _fault_chain(
                        v,
                        _row(stale_blk, a, k) if has_stale else None,
                        masks,
                        inf_sign,
                        tree0,
                        plan,
                        a,
                        k,
                    )
                )
            else:
                rows.append(v)
        va = None if valid is None else valid[a]
        if sanitize:
            agg = _sanitized_agg(rows, h_val, va, traced_h)
        elif va is not None:
            agg = _masked_agg(rows, H, va)
        elif traced_h:
            agg = _dynamic_agg(rows, h_val)
        else:
            agg = _plain_agg(rows, H)
        out_rows.append(agg)
    out_ref[...] = jnp.stack(out_rows)


def _pad_cols(x, tile):
    m = x.shape[-1]
    padded = ((m + tile - 1) // tile) * tile
    if padded != m:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, padded - m)])
    return x, padded


def kernel_plan(
    n_agents: int,
    n_in: int,
    n_trunk: int,
    *,
    active: bool = False,
    has_stale: bool = False,
    traced_h: bool = False,
    sparse: bool = False,
    trim_h: int = 1,
    sanitize: bool = False,
    block_rows: int = _DEFAULT_BLOCK_ROWS,
) -> KernelPlan:
    """The launch's static BlockSpec plan — the ONE derivation both
    :func:`fused_pair_consensus` (which builds its ``pl.BlockSpec`` list
    from these operands) and ``lint --kernels`` (which prices residency
    and re-derives the committed DMA model from them) consume.

    Operands ride in launch order: ``[schedule_idx (sparse only)]``,
    ``msgs``, ``[stale]``, ``[fault_masks, inf_sign]`` (active plans),
    ``[trim_h]`` (traced H). ``scratch`` is the kernel's in-register
    live set per grid step: the ``n_in`` gathered rows (×3 under
    sanitize — the ±inf sentinel sink copies), the trim chain's
    register pairs (``trim_h + 1`` per side static, the full legal
    ``k_max`` range traced), and the accumulator row.
    """
    tile = block_rows * _LANES
    rows_total = pad_to_tile(n_trunk, tile) // _LANES
    grid = (rows_total // block_rows,)

    def _tile_map(i, *_):
        return (0, i, 0)

    tile_shape = (n_agents, block_rows, _LANES)
    inputs = []
    if sparse:
        inputs.append(
            BlockOperand(
                "schedule_idx",
                (n_agents, n_in),
                "int32",
                (False,),
                memory="smem",
            )
        )
    inputs.append(
        BlockOperand(
            "msgs",
            tile_shape,
            "float32",
            (True,),
            tiled_dims=(1, 2),
            index_map=_tile_map,
        )
    )
    if has_stale:
        inputs.append(
            BlockOperand(
                "stale",
                tile_shape,
                "float32",
                (True,),
                tiled_dims=(1, 2),
                index_map=_tile_map,
            )
        )
    if active:
        inputs.append(
            BlockOperand(
                "fault_masks",
                (2, 4, n_agents, n_in),
                "float32",
                (False,),
                index_map=lambda i, *_: (0, 0, 0, 0),
            )
        )
        inputs.append(
            BlockOperand(
                "inf_sign",
                (2, n_agents, n_in),
                "float32",
                (False,),
                index_map=lambda i, *_: (0, 0, 0),
            )
        )
    if traced_h:
        inputs.append(
            BlockOperand(
                "trim_h",
                (1, 1),
                "int32",
                (False,),
                index_map=lambda i, *_: (0, 0),
            )
        )
    outputs = (
        BlockOperand(
            "aggregate",
            tile_shape,
            "float32",
            (True,),
            tiled_dims=(1, 2),
            index_map=_tile_map,
        ),
    )
    # trim_h is a host int on this branch (callers pass 1 for traced H)
    k_regs = (
        ((n_in - 1) // 2 + 1)
        if traced_h
        else (int(trim_h) + 1)  # lint: disable=host-sync
    )
    live_rows = n_in * (3 if sanitize else 1) + 2 * k_regs + 1
    scratch = (
        BlockOperand(
            "epilogue_live_set",
            (live_rows, block_rows, _LANES),
            "float32",
            (False,),
        ),
    )
    return KernelPlan(
        name="sparse_consensus" if sparse else "fused_consensus",
        grid=grid,
        inputs=tuple(inputs),
        outputs=outputs,
        scratch=scratch,
        refetch="always",
    )


def fused_pair_consensus(
    msgs: jnp.ndarray,
    H,
    *,
    in_nodes,
    tree_split: int,
    valid=None,
    sanitize: bool = False,
    plan: Optional[FaultPlan] = None,
    stale: Optional[jnp.ndarray] = None,
    fields: Optional[FaultFields] = None,
    block_rows: int = _DEFAULT_BLOCK_ROWS,
    interpret: bool = False,
) -> jnp.ndarray:
    """Gather + fault + trim/clip/mean over the trunk columns of the
    combined pair block, as ONE Pallas launch.

    Args:
      msgs: ``(N, P_trunk)`` f32 — the trunk columns of the raveled
        critic+TR pair block (``training.update._pair_block``).
      H: trim parameter — a Python int traces the specialized kernel
        (H=0 short-circuits to the plain mean); a traced int32 scalar
        runs the k_max-register dynamic-trim chain (the fused-matrix
        path), fed to the kernel as a scalar input.
      in_nodes: the gather rows, in one of two forms. STATIC nested
        tuples (``cfg.padded_in_nodes()[0]``) unroll compile-time row
        selects into the kernel. A TRACED ``(N, degree)`` int32 array
        (the scheduled time-varying graph,
        :func:`rcmarl_tpu.config.scheduled_in_nodes`) rides as a
        SCALAR-PREFETCH operand instead — the SPARSE path: indices are
        data, each row select is a dynamic slice off the SMEM-resident
        schedule block, and per-block resampling re-dispatches one
        compiled kernel. Either way the ``(N, deg, P)`` gathered block
        never materializes in HBM.
      tree_split: static column index where the TR trunk begins (the
        per-tree fault masks select on it).
      valid: STATIC ``cfg.padded_in_nodes()[1]`` rows (ragged graphs)
        or None. Must be None on the sparse path (scheduled graphs are
        regular by construction).
      sanitize: the non-finite-hardened epilogue (bitwise the XLA
        backends' sanitize mode).
      plan / stale / fields: the active FaultPlan with its stale-replay
        trunk block (``stale_p > 0`` only) and the precomputed
        :class:`FaultFields`; all None for clean transport.
      block_rows / interpret: tile height and the Pallas interpreter
        flag (CPU tests; real lowering rides the TPU session).

    Returns the ``(N, P_trunk)`` post-consensus trunk block.
    """
    N, P = msgs.shape
    sparse = not isinstance(in_nodes, (tuple, list, np.ndarray))
    if sparse:
        # traced (N, degree) schedule block — the scalar-prefetch path
        idx = jnp.asarray(in_nodes, jnp.int32)
        if idx.ndim != 2 or idx.shape[0] != N:
            raise ValueError(
                f"traced in_nodes must be (N={N}, degree) int32 gather "
                f"rows; got shape {idx.shape}"
            )
        if valid is not None:
            raise ValueError(
                "a traced (scheduled) graph is regular by construction; "
                "the sparse kernel path takes no validity mask"
            )
        in_arr = None
        n_in = int(idx.shape[1])
    else:
        # static host tuples (cfg.padded_in_nodes rows) — kept as-is for
        # the unrolled in-kernel row selects
        idx = None
        # static host rows by the isinstance gate above — int() here
        # normalizes np integer scalars, it never touches a traced value
        in_arr = tuple(
            tuple(int(v) for v in row)  # lint: disable=host-sync
            for row in in_nodes
        )
        n_in = len(in_arr[0])
    traced_h = not isinstance(H, (int, np.integer))
    if traced_h and valid is not None:
        raise ValueError(
            "traced H is not supported together with a padded-graph "
            "validity mask (matrix cells must share one uniform graph)"
        )
    active = plan is not None and plan.active
    if active and not kernel_compatible_plan(plan):
        raise ValueError(
            "corrupt_p > 0 plans take the XLA reference arm (the noise "
            "draw's bits are fusion-context-dependent — module docstring); "
            "the epoch routes them there before reaching the kernel"
        )
    has_stale = active and float(plan.stale_p) > 0.0
    if active and fields is None:
        raise ValueError("an active FaultPlan needs precomputed FaultFields")

    launch_plan = kernel_plan(
        N,
        n_in,
        P,
        active=active,
        has_stale=has_stale,
        traced_h=traced_h,
        sparse=sparse,
        trim_h=1 if traced_h else int(H),
        sanitize=sanitize,
        block_rows=block_rows,
    )
    tile = block_rows * _LANES
    flat, padded = _pad_cols(msgs.astype(jnp.float32), tile)
    rows_total = padded // _LANES
    v3 = flat.reshape(N, rows_total, _LANES)
    grid = launch_plan.grid

    # the pl.BlockSpec list is BUILT from the introspectable plan — one
    # derivation for launch and lint alike. Index maps take (*grid,
    # *scalar_refs) under the scalar-prefetch grid spec (the trailing
    # *_ keeps one set of maps for both paths); the plan's smem entry
    # is the scalar-prefetch operand, passed positionally ahead of the
    # tiles rather than through in_specs.
    in_specs = [
        pl.BlockSpec(op.block_shape, op.index_map)
        for op in launch_plan.inputs
        if op.memory == "vmem"
    ]
    inputs = [v3]
    if has_stale:
        inputs.append(
            _pad_cols(stale.astype(jnp.float32), tile)[0].reshape(
                N, rows_total, _LANES
            )
        )
    if active:
        inputs.extend([fields.masks, fields.inf_sign])
    if traced_h:
        inputs.append(jnp.asarray(H, jnp.int32).reshape(1, 1))

    valid_rows = (
        None
        if valid is None
        else tuple(tuple(v > 0 for v in row) for row in valid)
    )
    kernel = functools.partial(
        _consensus_kernel,
        n_agents=N,
        n_in=n_in,
        in_arr=in_arr,
        H=None if traced_h else int(H),
        traced_h=traced_h,
        sanitize=sanitize,
        valid=valid_rows,
        plan=plan if active else None,
        tree_split=tree_split,
        block_rows=block_rows,
        has_stale=has_stale,
    )
    out_shape = jax.ShapeDtypeStruct((N, rows_total, _LANES), jnp.float32)
    out_op = launch_plan.outputs[0]
    out_spec = pl.BlockSpec(out_op.block_shape, out_op.index_map)
    if sparse:
        # the schedule block rides as the scalar-prefetch operand: DMAd
        # to SMEM once per launch, ahead of the first tile's data DMAs
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_spec,
        )
        out = pl.pallas_call(
            kernel,
            out_shape=out_shape,
            grid_spec=grid_spec,
            interpret=interpret,
        )(idx, *inputs)
    else:
        out = pl.pallas_call(
            kernel,
            out_shape=out_shape,
            in_specs=in_specs,
            out_specs=out_spec,
            grid=grid,
            interpret=interpret,
        )(*inputs)
    return out.reshape(N, -1)[:, :P]


# --------------------------------------------------------------------------
# Cost model — the ledger rows' programs and the kernel's DMA arithmetic
# --------------------------------------------------------------------------


def fused_consensus_dma_bytes(
    n_agents: int,
    n_in: int,
    n_trunk: int,
    plan: Optional[FaultPlan],
    block_rows: int = _DEFAULT_BLOCK_ROWS,
) -> float:
    """The kernel's exact HBM traffic in bytes, from its BlockSpecs:
    every input tile is DMAd once per grid step and the output written
    once — deterministic arithmetic, not an estimate (the honesty tag
    on the ledger row is ``bytes_model: 'pallas-blockspec-dma'``).
    Broadcast inputs (masks, sign planes, the traced-H scalar) are
    counted once PER GRID STEP — the conservative reading. The closed
    form lives in :func:`rcmarl_tpu.ops.dma_model.consensus_model_bytes`
    (the consolidated grid-arithmetic core); ``lint --kernels``
    re-derives it from :func:`kernel_plan` and gates the drift."""
    active = plan is not None and plan.active
    return consensus_model_bytes(
        n_agents,
        n_in,
        n_trunk,
        active=active,
        has_stale=active and float(plan.stale_p) > 0.0,
        block_rows=block_rows,
    )


def sparse_fused_dma_bytes(
    n_agents: int,
    degree: int,
    n_trunk: int,
    plan: Optional[FaultPlan],
    block_rows: int = _DEFAULT_BLOCK_ROWS,
) -> float:
    """HBM traffic of the SPARSE (traced-graph) kernel launch: the
    static kernel's tile DMAs plus ONE ``(N, degree)`` int32
    scalar-prefetch DMA of the schedule block — prefetched to SMEM
    ahead of the grid, not re-read per tile. Same deterministic
    BlockSpec arithmetic, same ``bytes_model: 'pallas-blockspec-dma'``
    honesty tag; the ``(N, deg, P)`` gathered block the XLA sparse
    chain materializes never appears in either term. Closed form:
    :func:`rcmarl_tpu.ops.dma_model.sparse_consensus_model_bytes`."""
    active = plan is not None and plan.active
    return sparse_consensus_model_bytes(
        n_agents,
        degree,
        n_trunk,
        active=active,
        has_stale=active and float(plan.stale_p) > 0.0,
        block_rows=block_rows,
    )


# The two-launch/math-twin comparison programs behind the
# ``consensus_trunk`` / ``sparse_consensus`` ledger rows live with the
# audit that compiles them (:func:`rcmarl_tpu.lint.cost
# .consensus_cost_programs` / ``sparse_consensus_cost_rows``) — this
# module only owns the deterministic DMA arithmetic above.
