"""The ONE grid-arithmetic core behind every Pallas kernel's byte math.

Two things live here, deliberately jax-free (numpy + dataclasses only,
so ``lint --kernels`` and the TPU-session preflight can price every
queued shape without touching a backend):

1. **The introspectable kernel-plan datatype.** Every Pallas module
   (:mod:`.pallas_consensus`, :mod:`.pallas_fit`, :mod:`.pallas_serve`,
   :mod:`.pallas_aggregation`) exports a ``kernel_plan()`` seam that
   returns a :class:`KernelPlan`: the launch grid plus one
   :class:`BlockOperand` per input/output/live-scratch array — block
   shape, dtype, memory space (VMEM tile vs SMEM scalar-prefetch),
   which grid axes the index map varies with, and which block dims are
   CHOSEN tile sizes (vs problem-determined). The launch wrappers build
   their real ``pl.BlockSpec`` lists FROM the plan (``index_map`` rides
   along on each operand), so the lint arm and ``pallas_call`` consume
   one derivation — a plan that drifts from the kernel breaks the
   kernel, not just the audit.

2. **The shared traffic core + the committed closed-form DMA models.**
   :func:`plan_dma_bytes` prices a plan's HBM traffic from pure grid
   arithmetic under the plan's refetch discipline (``'always'``: every
   pipelined block is re-DMAd each grid step — the conservative reading
   the consensus/serve models commit to; ``'on_change'``: a block is
   re-fetched only when its index-map output changes between
   consecutive steps — the revisit-aware reading the fit scan model
   commits to). The three historically copy-pasted ``*_dma_bytes``
   helpers are consolidated below as closed forms over the same tile
   arithmetic (:func:`consensus_model_bytes`,
   :func:`sparse_consensus_model_bytes`, :func:`serve_model_bytes`);
   the ops modules' public helpers delegate here bitwise. ``lint
   --kernels`` re-derives each closed form from the plan via
   :func:`plan_dma_bytes` and fires ``kernel-dma-model-drift`` when
   model and derivation disagree — the models are verified, not
   asserted.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple

import numpy as np

#: TPU vector lane width — the trailing-axis tile quantum every kernel
#: here pads its flat column axis to.
LANES = 128


def pad_to_tile(n: int, tile: int) -> int:
    """``n`` rounded up to a multiple of ``tile`` (the column padding
    every flat-block kernel applies before reshaping to lanes)."""
    return ((n + tile - 1) // tile) * tile


def tile_rows(batch: int, block_b: int) -> int:
    """The largest tile height <= ``block_b`` dividing ``batch`` — an
    exact grid with no padded rows (the serve kernel's batch tiling)."""
    bb = max(1, min(block_b, batch))
    while batch % bb:
        bb -= 1
    return bb


@dataclass(frozen=True)
class BlockOperand:
    """One pipelined array of a Pallas launch, as the plan sees it.

    ``block_shape`` is the per-grid-step block; ``varies`` marks, per
    grid axis, whether the operand's index map depends on it (all-False
    = a broadcast block); ``memory`` is ``'vmem'`` for pipelined tiles
    and ``'smem'`` for scalar-prefetch operands (DMAd once per launch,
    resident in scalar memory); ``tiled_dims`` are the block-shape
    positions holding a CHOSEN tile size (``block_rows``, ``block_b``)
    rather than a problem-determined extent — the dims the
    dtype-packing lint rule applies to. ``index_map`` is the actual
    callable the launch hands to ``pl.BlockSpec`` (ignored by the
    arithmetic; ``None`` for scratch entries).
    """

    name: str
    block_shape: Tuple[int, ...]
    dtype: str
    varies: Tuple[bool, ...]
    memory: str = "vmem"
    tiled_dims: Tuple[int, ...] = ()
    index_map: Optional[Callable] = field(default=None, compare=False)

    def block_bytes(self) -> int:
        # static block shapes by construction — host shape arithmetic
        return int(  # lint: disable=host-sync
            math.prod(self.block_shape) * np.dtype(self.dtype).itemsize
        )


@dataclass(frozen=True)
class KernelPlan:
    """A Pallas launch, statically: grid + every operand's block plan.

    ``scratch`` entries are the kernel-local live set (gathered row
    copies, trim-selection registers, gradient/accumulator arrays) —
    they never DMA but they occupy VMEM alongside the pipelined blocks,
    so the residency model counts them. ``refetch`` is the traffic
    discipline the kernel's committed byte model uses (module
    docstring).
    """

    name: str
    grid: Tuple[int, ...]
    inputs: Tuple[BlockOperand, ...]
    outputs: Tuple[BlockOperand, ...]
    scratch: Tuple[BlockOperand, ...] = ()
    refetch: str = "always"

    def grid_steps(self) -> int:
        # static launch grid by construction — host shape arithmetic
        return int(math.prod(self.grid))  # lint: disable=host-sync


def operand_fetches(
    grid: Tuple[int, ...], varies: Tuple[bool, ...], refetch: str
) -> int:
    """How many times one pipelined operand's block is DMAd over the
    whole grid. ``'always'``: once per grid step (Mosaic's worst case —
    broadcast blocks re-read every step). ``'on_change'``: once per
    step at which the index-map output differs from the previous step,
    under the lexicographic traversal (last grid axis fastest) — a
    block varying only with outer axes is fetched once per outer
    iteration, however many inner steps revisit it."""
    # grids are static python tuples — host shape arithmetic throughout
    steps = int(math.prod(grid))  # lint: disable=host-sync
    if refetch == "always" or not grid:
        return max(1, steps)
    if not any(varies):
        return 1
    last_varying = max(i for i, v in enumerate(varies) if v)
    trailing = int(math.prod(grid[last_varying + 1 :]))  # lint: disable=host-sync
    return max(1, steps // trailing)


def plan_dma_bytes(plan: KernelPlan) -> float:
    """The launch's total HBM traffic in bytes, from the plan's grid
    arithmetic alone: every VMEM operand pays ``block_bytes x fetches``
    under the plan's refetch discipline (outputs are written on the
    same schedule their index maps revolve on); every SMEM
    scalar-prefetch operand pays ONE DMA per launch."""
    total = 0.0
    for op in plan.inputs + plan.outputs:
        if op.memory == "smem":
            total += float(op.block_bytes())
            continue
        total += float(op.block_bytes()) * operand_fetches(
            plan.grid, op.varies, plan.refetch
        )
    return total


# --------------------------------------------------------------------------
# The committed closed-form models (the ops wrappers delegate here)
# --------------------------------------------------------------------------


def consensus_model_bytes(
    n_agents: int,
    n_in: int,
    n_trunk: int,
    *,
    active: bool = False,
    has_stale: bool = False,
    block_rows: int = 8,
) -> float:
    """The fused dense-consensus kernel's HBM traffic: every input tile
    DMAd once per grid step, the output written once, broadcast fault
    planes (masks + sign planes) counted once PER GRID STEP — the
    conservative ``refetch='always'`` reading. Bitwise the historical
    ``pallas_consensus.fused_consensus_dma_bytes``."""
    tile = block_rows * LANES
    padded = pad_to_tile(n_trunk, tile)
    n_tiles = padded // tile
    bytes_total = n_agents * padded * 4.0  # messages read
    bytes_total += n_agents * padded * 4.0  # aggregate written
    if active:
        if has_stale:
            bytes_total += n_agents * padded * 4.0  # stale-replay read
        masks_bytes = (2 * 4 * n_agents * n_in + 2 * n_agents * n_in) * 4.0
        bytes_total += masks_bytes * n_tiles  # re-DMAd per tile
    return bytes_total


def sparse_consensus_model_bytes(
    n_agents: int,
    degree: int,
    n_trunk: int,
    *,
    active: bool = False,
    has_stale: bool = False,
    block_rows: int = 8,
) -> float:
    """The SPARSE (traced-graph) consensus launch: the dense kernel's
    tile DMAs plus ONE ``(N, degree)`` int32 scalar-prefetch DMA of the
    schedule block. Bitwise the historical
    ``pallas_consensus.sparse_fused_dma_bytes``."""
    return (
        consensus_model_bytes(
            n_agents,
            degree,
            n_trunk,
            active=active,
            has_stale=has_stale,
            block_rows=block_rows,
        )
        + n_agents * degree * 4.0
    )


def serve_model_bytes(
    n_agents: int,
    obs_dim: int,
    hidden: Tuple[int, ...],
    n_actions: int,
    batch: int,
    *,
    mode: str = "sample",
    n_members: int = 0,
    block_b: int = 128,
) -> float:
    """The fused serve/fleet kernel's HBM traffic: observation tiles
    once per request row, the broadcast actor block + key words once
    per grid step, action/probability tiles written once. Bitwise the
    historical ``pallas_serve.fused_serve_dma_bytes``."""
    dims = [obs_dim, *hidden, n_actions]
    bb = tile_rows(batch, block_b)
    n_tiles = batch // bb
    stack = max(1, n_members) * n_agents
    param_bytes = (
        sum(
            (d_in * d_out + d_out) * 4.0
            for d_in, d_out in zip(dims[:-1], dims[1:])
        )
        * stack
    )
    bytes_total = batch * n_agents * dims[0] * 4.0  # observations read once
    bytes_total += param_bytes * n_tiles  # block re-DMAd per tile
    bytes_total += batch * n_agents * 4.0  # actions written
    bytes_total += batch * n_agents * dims[-1] * 4.0  # probs written
    if n_members:
        bytes_total += batch * 4.0  # route read
    if mode == "sample":
        bytes_total += 8.0 * n_tiles  # key words per tile
    return bytes_total
