"""``python -m rcmarl_tpu`` — the reference's ``python main.py`` entry."""

import sys

from rcmarl_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
