"""Results aggregation and figures (the reference ``plot_results.py`` twin).

Walks an experiment tree laid out as
``raw_data/<scenario>/H=<h>/seed=<s>/sim_data*.pkl`` (the layout the
reference's SGE sweeps produced and :mod:`rcmarl_tpu.cli` ``sweep``
reproduces), aggregates per-(scenario, H) seed-mean curves with a rolling
mean, and renders the README-style figures.

Two deliberate fixes over the reference (``plot_results.py:10-59``,
SURVEY.md §3.5): (a) private-reward and ``_global`` (team-average-reward)
runs are paired EXPLICITLY by name, not by ``os.listdir`` adjacency; (b)
aggregation is exposed as a pure function returning DataFrames so tests and
notebooks can use it without touching matplotlib.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

#: Columns written by the trainer (reference ``train_agents.py:175-183``).
COLUMNS = ("True_team_returns", "True_adv_returns", "Estimated_team_returns")

#: Where the reference's shipped experiment artifacts live — the single
#: definition the CLI default and the PARITY.md provenance text both use.
DEFAULT_REF_RAW_DATA = "/root/reference/simulation_results/raw_data"


def _h_cells(scenario_dir) -> List[int]:
    """Sorted H values of the ``H=<int>`` cell directories under
    ``scenario_dir``; stray files and non-numeric names are skipped."""
    return sorted(
        int(d.name.split("=")[1])
        for d in Path(scenario_dir).glob("H=*")
        if d.is_dir() and d.name.split("=")[1].lstrip("-").isdigit()
    )


def load_run(run_dir) -> List[pd.DataFrame]:
    """Load one seed's ``sim_data*.pkl`` phases in numeric order, one
    DataFrame per phase (the reference's two-phase 4000+4000 runs store
    sim_data1 + sim_data2; per-phase warm-up dropping and concatenation
    happen in :func:`aggregate_scenario`)."""
    run_dir = Path(run_dir)
    # Numbered phases only (the files plot_results.py:28-29 reads); a bare
    # sim_data.pkl — a duplicate in reference run dirs — is the fallback,
    # never mixed with phases. Non-numeric suffixes (sim_data_old.pkl) are
    # stray files, not phases: ignore them.
    numbered = [
        (int(p.stem.removeprefix("sim_data")), p)
        for p in run_dir.glob("sim_data*.pkl")
        if p.stem.removeprefix("sim_data").isdigit()
    ]
    paths = [p for _, p in sorted(numbered)]
    if not paths and (run_dir / "sim_data.pkl").exists():
        paths = [run_dir / "sim_data.pkl"]
    if not paths:
        raise FileNotFoundError(f"no sim_data*.pkl under {run_dir}")
    return [pd.read_pickle(p).reset_index(drop=True) for p in paths]


def _seed_runs(h_dir):
    """Yield ``(seed_dir, phases)`` for every seed run under one
    ``H=<h>`` cell directory — the single walk shared by curve
    aggregation, per-seed summaries, and the parity table, so all
    consumers agree on which runs exist."""
    h_dir = Path(h_dir)
    if not h_dir.is_dir():
        return
    for seed_dir in sorted(h_dir.iterdir()):
        if not seed_dir.is_dir():
            continue
        try:
            yield seed_dir, load_run(seed_dir)
        except FileNotFoundError:
            continue


def aggregate_scenario(
    scenario_dir, H: int, drop: int = 500, rolling: int = 200
) -> Optional[pd.DataFrame]:
    """Seed-mean curve for one (scenario, H) cell.

    Mirrors the reference pipeline (``plot_results.py:28-39``): per seed,
    drop the first ``drop`` episodes of each phase, concatenate phases;
    then mean across seeds index-wise and apply a ``rolling`` mean.
    Returns None if the cell has no runs.
    """
    per_seed = []
    for _, phases in _seed_runs(Path(scenario_dir) / f"H={H}"):
        kept = [df.iloc[drop:].reset_index(drop=True) for df in phases]
        per_seed.append(pd.concat(kept, ignore_index=True))
    if not per_seed:
        return None
    stacked = pd.concat(per_seed, keys=range(len(per_seed)))
    mean = stacked.groupby(level=1).mean()
    return mean.rolling(rolling, min_periods=1).mean()


def final_returns(
    raw_data_dir, window: int = 500
) -> pd.DataFrame:
    """BASELINE-style summary table: mean True_team_returns (and adv) over
    the final ``window`` episodes, per (scenario, H) — the quantity
    SURVEY.md §6's convergence table reports."""
    rows = []
    root = Path(raw_data_dir)
    for scen_dir in sorted(p for p in root.iterdir() if p.is_dir()):
        for H in _h_cells(scen_dir):
            agg = aggregate_scenario(scen_dir, H, drop=0, rolling=1)
            if agg is None or len(agg) < 1:
                continue
            tail = agg.iloc[-window:]
            rows.append(
                {
                    "scenario": scen_dir.name,
                    "H": H,
                    "team_return": tail["True_team_returns"].mean(),
                    "adv_return": tail["True_adv_returns"].mean(),
                    "est_return": tail["Estimated_team_returns"].mean(),
                    "episodes": len(agg),
                }
            )
    return pd.DataFrame(rows)


def _as_roots(raw_data_dir) -> List[Path]:
    """One tree or several: a str/PathLike is a single root, any other
    iterable is a list of roots whose per-seed rows are pooled."""
    if isinstance(raw_data_dir, (str, Path)):
        return [Path(raw_data_dir)]
    return [Path(d) for d in raw_data_dir]


def per_seed_final_returns(raw_data_dir, window: int = 500) -> pd.DataFrame:
    """Per-(scenario, H, seed) final-``window`` mean returns — the
    disaggregated form of :func:`final_returns`, exposing the seed spread
    (VERDICT.md round-1: parity deltas need error bars to separate 3-seed
    noise from systematic drift).

    ``raw_data_dir`` may be a list of trees; their rows are pooled (the
    n=6 parity basis: original seeds + the round-3 robustness seeds). A
    (scenario, H, seed) collision across trees raises — double-counting
    a seed would silently deflate the std every verdict depends on. A
    tree that does not exist contributes nothing.
    """
    rows = []
    for root in _as_roots(raw_data_dir):
        scen_dirs = (
            sorted(p for p in root.iterdir() if p.is_dir())
            if root.is_dir()
            else []
        )
        for scen_dir in scen_dirs:
            for H in _h_cells(scen_dir):
                for seed_dir, phases in _seed_runs(scen_dir / f"H={H}"):
                    run = pd.concat(phases, ignore_index=True)
                    tail = run.iloc[-window:]
                    rows.append(
                        {
                            "scenario": scen_dir.name,
                            "H": H,
                            "seed": seed_dir.name.split("=")[-1],
                            "team_return": tail["True_team_returns"].mean(),
                            "adv_return": tail["True_adv_returns"].mean(),
                            "episodes": len(run),
                        }
                    )
    df = pd.DataFrame(
        rows,
        columns=["scenario", "H", "seed", "team_return", "adv_return", "episodes"],
    )
    dup = df.duplicated(subset=["scenario", "H", "seed"])
    if dup.any():
        clash = df[dup][["scenario", "H", "seed"]].to_dict(orient="records")
        raise ValueError(
            f"duplicate (scenario, H, seed) across raw_data trees: {clash}"
        )
    return df


def parity_table(
    mine_dir,
    ref_dir,
    window: int = 500,
    tolerance: float = 0.05,
    mine: Optional[pd.DataFrame] = None,
    ref: Optional[pd.DataFrame] = None,
) -> pd.DataFrame:
    """Cell-by-cell convergence comparison of two experiment trees with
    identical layout (ours vs the reference's shipped
    ``simulation_results/raw_data``) — the reference numbers are computed
    from its artifacts by the SAME pipeline, not transcribed by hand.

    ``mine``/``ref`` accept precomputed :func:`per_seed_final_returns`
    frames so callers that also emit the per-seed summary parse each
    pickle tree only once.

    Columns: reference/mine team returns (seed mean), seed std-devs,
    delta, relative delta, and a within-``tolerance`` verdict.
    """
    if mine is None:
        mine = per_seed_final_returns(mine_dir, window)
    if ref is None:
        ref = per_seed_final_returns(ref_dir, window)
    # Union of cells from BOTH trees: a cell we trained that the reference
    # never shipped must still appear (as 'no reference'), and a reference
    # cell we haven't run yet appears as 'missing'.
    cells = sorted(
        set(map(tuple, ref[["scenario", "H"]].itertuples(index=False)))
        | set(map(tuple, mine[["scenario", "H"]].itertuples(index=False)))
    )
    rows = []
    for scen, H in cells:
        r = ref[(ref.scenario == scen) & (ref.H == H)]
        m = mine[(mine.scenario == scen) & (mine.H == H)]
        row = {
            "scenario": scen,
            "H": H,
            "ref_mean": r.team_return.mean() if len(r) else np.nan,
            "ref_std": r.team_return.std(ddof=0) if len(r) else np.nan,
            "ref_seeds": len(r),
            "mine_mean": m.team_return.mean() if len(m) else np.nan,
            "mine_std": m.team_return.std(ddof=0) if len(m) else np.nan,
            "mine_seeds": len(m),
            "ref_adv": r.adv_return.mean() if len(r) else np.nan,
            "mine_adv": m.adv_return.mean() if len(m) else np.nan,
        }
        row["delta"] = row["mine_mean"] - row["ref_mean"]
        row["rel"] = (
            abs(row["delta"]) / abs(row["ref_mean"])
            if np.isfinite(row["delta"]) and row["ref_mean"] != 0
            else np.nan
        )
        # disjoint per-seed supports (every one of our seeds beyond every
        # reference seed) refute the seed-noise explanation no matter
        # what the std overlap heuristic says
        row["supports_separated"] = bool(
            len(r)
            and len(m)
            and (
                m.team_return.min() > r.team_return.max()
                or m.team_return.max() < r.team_return.min()
            )
        )
        if not len(r):
            row["verdict"] = "no reference"
        elif not np.isfinite(row["delta"]):
            row["verdict"] = "missing"
        elif row["rel"] <= tolerance:
            row["verdict"] = "within"
        elif row["supports_separated"] and min(len(r), len(m)) >= 3:
            # systematic: not attributable to seed noise. The override
            # needs >= 3 seeds PER SIDE — with n=2 on either side (the
            # reference ships only 2 seeds for some _global cells),
            # disjoint supports are weak evidence, so those cells fall
            # through to the std-overlap heuristic instead of taking the
            # hard label (the supports_separated column still records
            # the disjointness for the reader).
            row["verdict"] = "outside"
        else:
            # outside tolerance on the mean — is the reference mean inside
            # our seed spread (2 std)? then it's plausibly seed noise
            spread = 2 * row["mine_std"] if np.isfinite(row["mine_std"]) else 0
            row["verdict"] = (
                "outside (seed-noise-compatible)"
                if abs(row["delta"]) <= spread + 2 * row["ref_std"]
                else "outside"
            )
        rows.append(row)
    cols = [
        "scenario", "H", "ref_mean", "ref_std", "ref_seeds", "mine_mean",
        "mine_std", "mine_seeds", "ref_adv", "mine_adv", "delta", "rel",
        "supports_separated", "verdict",
    ]
    return (
        pd.DataFrame(rows, columns=cols)
        .sort_values(["scenario", "H"])
        .reset_index(drop=True)
    )


def qualitative_claims_section(table: pd.DataFrame) -> str:
    """The reference README's headline claims, computed from the SAME
    parity table for both sides (reference README.md:22-29): adversaries
    degrade H=0 training, and H=1 trimming recovers near-cooperative
    returns. Reported as deltas vs the all-cooperative cell of the same
    H, so any uniform late-training offset (DRIFT.md) cancels."""

    def cell(scen, H, col):
        r = table[(table.scenario == scen) & (table.H == H)]
        return float(r[col].iloc[0]) if len(r) else np.nan

    def fmt(x):
        return f"{x:+.2f}" if np.isfinite(x) else "—"

    #: An H=1 run "recovers" when trimming undoes at least this fraction
    #: of the same adversary's H=0 degradation (reference recoveries are
    #: 87-95% by this measure; ours 88-92%).
    RECOVERY_FRACTION = 0.75
    #: H=0 "degrades" when the adversary costs at least this much return.
    DEGRADE_THRESHOLD = 0.5

    lines = [
        "## Qualitative claims (reference README)",
        "",
        "Attack impact = adversary-cell team return minus the coop cell at",
        "the same H (0 = no impact; more negative = more damage). Both",
        "columns computed from the table above. Verdicts are measured, not",
        f"asserted: H=0 'degrades' needs ≥{DEGRADE_THRESHOLD} return cost;",
        f"H=1 'recovers' needs ≥{RECOVERY_FRACTION:.0%} of that cell's own",
        "H=0 degradation undone by trimming.",
        "",
        "| Scenario | H | reference impact | ours | claim | verdict |",
        "|---|---|---|---|---|---|",
    ]
    for scen in ("greedy", "faulty", "malicious"):
        imp = {
            (side, H): cell(scen, H, col) - cell("coop", H, col)
            for side, col in (("ref", "ref_mean"), ("mine", "mine_mean"))
            for H in (0, 1)
        }
        for H in (0, 1):
            ref, mine = imp[("ref", H)], imp[("mine", H)]
            if H == 0:
                claim = "degrades training (H=0, no defense)"
                ok = mine <= -DEGRADE_THRESHOLD
                testable = True
            else:
                claim = "trimming recovers near-coop returns"
                # Recovery is relative to this adversary's own measured
                # H=0 damage; without a material H=0 degradation on our
                # side there is nothing to recover from.
                base = imp[("mine", 0)]
                testable = np.isfinite(base) and abs(base) >= DEGRADE_THRESHOLD
                ok = testable and abs(mine) <= (1 - RECOVERY_FRACTION) * abs(base)
            if not np.isfinite(mine):
                verdict = "missing"
            elif not testable:
                verdict = "untestable (no measured H=0 degradation)"
            else:
                verdict = "holds" if ok else "**FAILS**"
            lines.append(
                f"| {scen} | {H} | {fmt(ref)} | {fmt(mine)} | {claim} "
                f"| {verdict} |"
            )
    return "\n".join(lines) + "\n"


def write_parity_md(
    table: pd.DataFrame,
    path,
    window: int = 500,
    tolerance: float = 0.05,
    extra_sections: str = "",
    mine_dir: str = "simulation_results/raw_data",
    ref_dir: str = DEFAULT_REF_RAW_DATA,
) -> None:
    """Render PARITY.md entirely from :func:`parity_table` output — no
    hand-maintained result rows (VERDICT.md round-1 weakness 1)."""
    lines = [
        "# PARITY — measured convergence vs the reference's shipped artifacts",
        "",
        "**Generated by `python -m rcmarl_tpu parity` — do not edit result",
        "rows by hand.** Both columns are computed by the same pipeline",
        f"(`analysis/plots.py:per_seed_final_returns`, final-{window} episode",
        "window) from `sim_data*.pkl` trees: ours from",
        f"`{mine_dir}`, the reference's from",
        f"`{ref_dir}` (its shipped two-phase 4000+4000",
        "runs; phases concatenated, exactly as `plot_results.py` reads them).",
        "",
        "RNG streams cannot match the reference's global-NumPy sequencing",
        "under JAX's split-based PRNG, so parity is statistical over the",
        "seed set (the paper's own protocol, SURVEY.md §7 hard part (c)).",
        "",
        f"Parity target: seed-mean team return within ±{tolerance:.0%}",
        "(BASELINE.json). `outside (seed-noise-compatible)` = mean delta",
        "exceeds the target but lies within 2·(ref std + our std) AND the",
        "per-seed supports overlap — i.e. not distinguishable from seed",
        "noise at these sample sizes. Cells whose per-seed supports are",
        "fully disjoint are labeled plain `outside` regardless of the std",
        "heuristic: disjoint supports refute the seed-noise explanation",
        "(the systematic cells are root-caused in DRIFT.md).",
        "",
        "| Scenario | H | reference (±std, n) | this framework (±std, n) | Δ | rel | verdict |",
        "|---|---|---|---|---|---|---|",
    ]
    for _, r in table.iterrows():
        mine = (
            f"{r.mine_mean:.2f} ±{r.mine_std:.2f} (n={int(r.mine_seeds)})"
            if np.isfinite(r.mine_mean)
            else "—"
        )
        ref = (
            f"{r.ref_mean:.2f} ±{r.ref_std:.2f} (n={int(r.ref_seeds)})"
            if np.isfinite(r.ref_mean)
            else "—"
        )
        delta = f"{r.delta:+.2f}" if np.isfinite(r.delta) else "—"
        rel = f"{r.rel:.1%}" if np.isfinite(r.rel) else "—"
        lines.append(
            f"| {r.scenario} | {int(r.H)} | {ref} | {mine} | {delta} | {rel} "
            f"| {r.verdict} |"
        )
    n_done = int((~table.verdict.isin(["missing", "no reference"])).sum())
    n_within = int((table.verdict == "within").sum())
    n_noise = int((table.verdict == "outside (seed-noise-compatible)").sum())
    lines += [
        "",
        f"**{n_done}/{len(table)} cells measured; {n_within} within "
        f"±{tolerance:.0%}, {n_noise} outside-but-seed-noise-compatible, "
        f"{n_done - n_within - n_noise} outside.**",
    ]
    if extra_sections:
        lines += ["", extra_sections]
    Path(path).write_text("\n".join(lines) + "\n")


def save_figure(fig, out_path) -> str:
    """The one figure-writing convention (layout, dpi, parent dirs,
    close) shared by every plot in this package."""
    import matplotlib.pyplot as plt

    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return str(out_path)


def _phase_boundaries(scenario_dir, H: int) -> List[int]:
    """Episode indices where a new phase starts (first seed run's phase
    lengths, cumulative, excluding 0 and the end) — where the restart
    protocol's Adam/buffer/RNG reset happened."""
    for _, phases in _seed_runs(Path(scenario_dir) / f"H={H}"):
        bounds, total = [], 0
        for df in phases[:-1]:
            total += len(df)
            bounds.append(total)
        return bounds
    return []


def plot_drift_comparison(
    mine_dir,
    ref_dir,
    out_path,
    scenario: str = "coop",
    H: int = 0,
    rolling: int = 200,
    mine_label: str = "this framework",
    ref_label: str = "reference artifacts",
) -> str:
    """Overlay OUR seed-mean curve with the reference artifacts' for one
    cell, actual phase boundaries marked per tree — the visual evidence
    behind DRIFT.md (phase-1 agreement, phase-2 divergence). Uses drop=0
    so the curves stay episode-aligned. Labels are parameters: the caller
    knows what protocol (e.g. which eps) each tree was run with."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    mine = aggregate_scenario(Path(mine_dir) / scenario, H, drop=0, rolling=rolling)
    ref = aggregate_scenario(Path(ref_dir) / scenario, H, drop=0, rolling=rolling)
    if mine is None or ref is None:
        raise FileNotFoundError(
            f"cell {scenario}/H={H} missing under {mine_dir} or {ref_dir}"
        )
    fig, ax = plt.subplots(figsize=(7, 4))
    (ref_line,) = ax.plot(ref["True_team_returns"], label=ref_label)
    (mine_line,) = ax.plot(mine["True_team_returns"], label=mine_label)
    # Mark each tree's ACTUAL restart boundaries (from its phase files) in
    # that tree's color; single-phase trees get no line.
    for tree_dir, line in ((ref_dir, ref_line), (mine_dir, mine_line)):
        for b in _phase_boundaries(Path(tree_dir) / scenario, H):
            ax.axvline(b, color=line.get_color(), linestyle=":", alpha=0.6)
    ax.set_xlabel("Episode (dotted = phase restart)")
    ax.set_ylabel(f"True team return (rolling {rolling})")
    ax.set_title(f"{scenario}, H={H}: ours vs shipped artifacts")
    ax.legend(fontsize=8)
    return save_figure(fig, out_path)


def plot_returns(
    raw_data_dir,
    out_dir,
    scenarios: Optional[List[str]] = None,
    H_values: Optional[Tuple[int, ...]] = None,
    drop: int = 500,
    rolling: int = 200,
) -> List[str]:
    """Render per-(scenario, H) figures overlaying the private-reward run
    with its explicitly-paired ``<scenario>_global`` run, Estimated vs True
    team returns — the reference README's figure set. ``H_values=None``
    plots every ``H=*`` cell found on disk, so sweeps with nonstandard H
    are never silently skipped. Returns the written paths."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    root = Path(raw_data_dir)
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    if scenarios is None:
        scenarios = sorted(
            p.name
            for p in root.iterdir()
            if p.is_dir() and not p.name.endswith("_global")
        )
    written = []
    for scen in scenarios:
        cells = _h_cells(root / scen) if H_values is None else list(H_values)
        for H in cells:
            base = aggregate_scenario(root / scen, H, drop, rolling)
            if base is None:
                continue
            paired = None
            if (root / f"{scen}_global").is_dir():
                paired = aggregate_scenario(
                    root / f"{scen}_global", H, drop, rolling
                )
            fig, ax = plt.subplots(figsize=(6, 4))
            ax.plot(base["True_team_returns"], label="True team returns")
            ax.plot(
                base["Estimated_team_returns"],
                label="Estimated team returns",
                linestyle="--",
            )
            if paired is not None:
                ax.plot(
                    paired["True_team_returns"],
                    label="True team returns (team-avg reward)",
                )
            ax.set_xlabel("Episode (post warm-up)")
            ax.set_ylabel("Discounted return")
            ax.set_title(f"{scen}, H={H}")
            ax.legend(fontsize=8)
            written.append(save_figure(fig, out_dir / f"{scen}_h{H}.png"))
    return written
