"""Offline results aggregation and figures (reference ``plot_results.py``)."""

from rcmarl_tpu.analysis.plots import (
    aggregate_scenario,
    final_returns,
    load_run,
    plot_returns,
)
from rcmarl_tpu.analysis.quality import episodes_to_threshold, quality_table

__all__ = [
    "aggregate_scenario",
    "final_returns",
    "load_run",
    "plot_returns",
    "episodes_to_threshold",
    "quality_table",
]
