"""Offline results aggregation and figures (reference ``plot_results.py``)."""

from rcmarl_tpu.analysis.plots import (
    aggregate_scenario,
    final_returns,
    load_run,
    plot_returns,
)

__all__ = ["aggregate_scenario", "final_returns", "load_run", "plot_returns"]
