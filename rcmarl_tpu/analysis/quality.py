"""Time-to-quality: episodes (and wall-clock) to reach reference returns.

BASELINE.json names TWO benchmark metrics: "env steps/sec/chip" (covered
by ``bench.py`` / BENCH_SCALING.jsonl) and **"episodes-to-return-
threshold"** — this module is the second one. Raw throughput can hide a
sample-efficiency regression: a rebuild that runs 1000x faster but needs
10x the episodes to learn would be a much weaker result than the steps/s
headline suggests. This closes that gap with a measured, regenerable
artifact (QUALITY.md via ``python -m rcmarl_tpu quality``).

Definition (per scenario x H cell):

- **Threshold**: the reference's own converged team return for that cell
  (seed-mean over the final ``window`` episodes of its shipped 8000-
  episode runs — exactly PARITY.md's ``ref_mean``), relaxed by
  ``tol`` of its magnitude: ``threshold = T - tol * |T|``. With the
  default ``tol=0.05`` this is "within 5% of the reference's final
  quality", the same tolerance the parity matrix uses.
- **Episodes to threshold**: the first episode at which the seed-mean,
  rolling(``rolling``)-smoothed True_team_returns curve reaches the
  threshold, with a FULL smoothing window required (``min_periods =
  rolling``): a crossing can only be declared once an entire window of
  episodes supports it, so single-episode startup noise cannot count as
  "reaching quality". Computed by the SAME code for both trees (the
  reference's shipped artifacts and ours), like every parity artifact in
  this repo — no hand-transcribed numbers.
- **Degenerate cells**: in the undefended adversary cells (H=0) the
  attack drives the reference's converged return down to within
  tolerance of *starting* performance — there is no learning progress to
  time, and the metric is meaningless by construction. The at-threshold-
  from-the-start test is applied to EACH side's curve; a cell is flagged
  ``degenerate`` only when BOTH curves are already at threshold at their
  first fully-smoothed point. When exactly one side starts at threshold
  while the other climbs (or never arrives), the cell is flagged
  ``asymmetric`` and reported as an explicit finding — a one-sided rule
  would silently hide, e.g., a cell where the reference starts converged
  but this framework needs thousands of episodes. Both kinds are
  excluded from the summary ratio (an at-start crossing makes the ratio
  meaningless) but printed, and asymmetric cells get a dedicated
  findings paragraph.
- **Wall-clock to threshold**: episodes / measured episode throughput.
  The reference side uses its derived 2.5 env-steps/s (BASELINE.md, SGE
  ``info`` log timestamps). Our side uses measured ``ref5_ring``
  production-block rows from BENCH_SCALING.jsonl (per platform, best
  impl), so the number is tied to committed, self-describing
  measurements rather than an asserted constant.

The reference has no analog of this analysis; SURVEY.md §7 step 8 calls
for "episodes-to-threshold" as part of the benchmark harness.
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Dict

import numpy as np
import pandas as pd

from rcmarl_tpu.analysis.plots import (
    DEFAULT_REF_RAW_DATA,
    _h_cells,
    _seed_runs,
    save_figure,
)

__all__ = [
    "episodes_to_threshold",
    "quality_table",
    "episode_throughput_from_bench",
    "gala_section",
    "write_quality_md",
    "plot_quality_crossing",
]

#: Steps per episode at the reference configuration (max_ep_len,
#: ``/root/reference/main.py:33``): converts steps/s rows to episodes/s.
REF_STEPS_PER_EPISODE = 20

#: The reference implementation's derived throughput (BASELINE.md):
#: ~2.5 env-steps/s on a 4-core SGE slot.
REF_BASELINE_STEPS_PER_SEC = 2.5


def episodes_to_threshold(curve: pd.Series, threshold: float) -> float:
    """First episode index (0-based) at which ``curve`` >= ``threshold``.

    Returns come negative ("cost to go"), improving toward zero, so
    reaching quality means crossing the threshold from below. NaN values
    in the curve (the unfilled head of a full-window rolling mean) never
    count as crossings. NaN if the curve never reaches the threshold.
    """
    values = np.asarray(curve.values, dtype=np.float64)
    hit = np.nonzero(~np.isnan(values) & (values >= threshold))[0]
    return float(hit[0]) if hit.size else float("nan")


def _tree_cells(root) -> set:
    """(scenario, H) cells present in one experiment tree."""
    root = Path(root)
    if not root.is_dir():
        return set()
    return {
        (scen_dir.name, H)
        for scen_dir in root.iterdir()
        if scen_dir.is_dir()
        for H in _h_cells(scen_dir)
    }


def _cell_curves(root, scen, H) -> list:
    """One cell's per-seed team-return curves (phases concatenated),
    loading each sim_data pickle exactly once."""
    return [
        pd.concat(
            [df["True_team_returns"] for df in phases], ignore_index=True
        )
        for _, phases in _seed_runs(Path(root) / scen / f"H={H}")
    ]


def _smoothed_mean(curves: list, rolling: int) -> pd.Series:
    """Seed-mean curve under a FULL-window rolling mean (``min_periods =
    rolling``: no startup noise from partially-filled windows). The ONE
    smoothing used by the table and the figures alike."""
    mean = pd.concat(
        [c.reset_index(drop=True) for c in curves], axis=1
    ).mean(axis=1)
    return mean.rolling(rolling, min_periods=rolling).mean()


def _threshold_from_ref(ref_curves: list, window: int, tol: float):
    """(ref_final, threshold): the reference's converged seed-mean and
    the within-``tol`` quality bar derived from it — the ONE threshold
    definition shared by the table and the figures."""
    T = float(np.mean([c.iloc[-window:].mean() for c in ref_curves]))
    return T, T - tol * abs(T)


def _crossing(curves: list, threshold: float, rolling: int) -> float:
    """Episodes-to-threshold of the smoothed seed-mean curve."""
    if not curves:
        return float("nan")
    return episodes_to_threshold(_smoothed_mean(curves, rolling), threshold)


def _majority_spans_window(curves: list, rolling: int) -> bool:
    """True when MORE THAN HALF of a side's per-seed curves span at
    least one full rolling window. Used to decide whether an all-NaN
    smoothed crossing is a genuine never-crosses verdict: the smoothed
    seed-mean averages every curve, so with only one full-length seed
    among truncated ones its tail rests on partial data — a hard
    behavioral label (``asymmetric``) needs the majority of seeds to
    actually cover the window."""
    if not curves:
        return False
    spanning = sum(len(c) >= rolling for c in curves)
    return 2 * spanning > len(curves)


def quality_table(
    mine_dir,
    ref_dir=DEFAULT_REF_RAW_DATA,
    window: int = 500,
    tol: float = 0.05,
    rolling: int = 200,
) -> pd.DataFrame:
    """Episodes-to-reference-quality for the union of cells in both trees.

    Each tree's pickles are loaded once per cell; the reference curves
    yield both the threshold base (seed-mean of the final-``window``
    means, exactly PARITY.md's ref column) and the reference's own
    crossing. A cell present only in our tree has no threshold to time
    against and appears as an all-NaN row (so coverage gaps are visible,
    not silently dropped).

    Columns: the threshold and its base, episodes-to-threshold for the
    reference curve and ours, their ratio (>1 = we reach the reference's
    quality in fewer episodes), and the ``degenerate`` flag.
    """
    rows = []
    mine_root, ref_root = Path(mine_dir), Path(ref_dir)
    cells = sorted(_tree_cells(ref_root) | _tree_cells(mine_root))
    for scen, H in cells:
        ref_curves = _cell_curves(ref_root, scen, H)
        mine_curves = _cell_curves(mine_root, scen, H)
        # seed counts let the renderer distinguish "no data" (cell absent
        # from a tree — e.g. a mistyped --raw_data) from a genuine
        # "not reached" verdict on existing curves
        row = {
            "scenario": scen,
            "H": H,
            "ref_final": float("nan"),
            "threshold": float("nan"),
            "ep_ref": float("nan"),
            "ep_mine": float("nan"),
            "ref_seeds": len(ref_curves),
            "mine_seeds": len(mine_curves),
        }
        if ref_curves:
            row["ref_final"], row["threshold"] = _threshold_from_ref(
                ref_curves, window, tol
            )
            row["ep_ref"] = _crossing(ref_curves, row["threshold"], rolling)
            row["ep_mine"] = _crossing(
                mine_curves, row["threshold"], rolling
            )
        # "at threshold from the first fully-smoothed point" (index
        # rolling-1) is judged PER SIDE: a cell is only degenerate —
        # nothing to time — when BOTH curves start there (the undefended-
        # attack cells). One side at-start while the other climbs for
        # thousands of episodes is an asymmetry, and must surface as a
        # finding, not vanish under a one-sided exclusion.
        row["degenerate_ref"] = (
            np.isfinite(row["ep_ref"]) and row["ep_ref"] < rolling
        )
        row["degenerate_mine"] = (
            np.isfinite(row["ep_mine"]) and row["ep_mine"] < rolling
        )
        row["degenerate"] = row["degenerate_ref"] and row["degenerate_mine"]
        # both orientations count, including "one side at-start, the
        # other never arrives" (ep NaN) — but an ep NaN is a genuine
        # never-crosses verdict only when a MAJORITY of the side's
        # curves span at least one full rolling window: truncated /
        # in-progress runs also smooth to all-NaN, and when most of a
        # side's seeds are partial the smoothed seed-mean tail rests on
        # incomplete data, which must not be reported as a behavioral
        # finding on the strength of a single full-length seed
        ref_spans_window = _majority_spans_window(ref_curves, rolling)
        mine_spans_window = _majority_spans_window(mine_curves, rolling)
        row["asymmetric"] = (
            ref_spans_window
            and mine_spans_window
            and row["degenerate_ref"] != row["degenerate_mine"]
        )
        if math.isnan(row["ep_mine"]):
            row["ep_ratio"] = float("nan")
        elif row["ep_mine"] == 0:
            # a legitimate crossing at index 0 (possible when rolling=1):
            # the ratio is division-by-zero; inf when the reference
            # needed any episodes at all, undefined when both were at 0
            row["ep_ratio"] = (
                float("inf") if row["ep_ref"] > 0 else float("nan")
            )
        else:
            row["ep_ratio"] = row["ep_ref"] / row["ep_mine"]
        rows.append(row)
    return pd.DataFrame(
        rows,
        columns=[
            "scenario", "H", "ref_final", "threshold", "ep_ref", "ep_mine",
            "ep_ratio", "degenerate", "degenerate_ref", "degenerate_mine",
            "asymmetric", "ref_seeds", "mine_seeds",
        ],
    )


def episode_throughput_from_bench(
    bench_jsonl, config: str = "ref5_ring"
) -> Dict[str, dict]:
    """Best measured episodes/s per platform for ``config`` rows of a
    BENCH_SCALING.jsonl file — the committed evidence the wall-clock
    columns are derived from. Returns ``{platform: {episodes_per_sec,
    impl, timestamp}}``; empty if the file or config rows are absent."""
    best: Dict[str, dict] = {}
    path = Path(bench_jsonl)
    if not path.exists():
        return best
    for line in path.read_text().splitlines():
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if row.get("config") != config or "env_steps_per_sec" not in row:
            continue
        # single-replica production-block rows only: sharded A/B rows
        # measure a different (multi-device) program
        if row.get("shard_agents") is not None:
            continue
        # the episode counts come from exact-f32 parity runs, so the
        # wall-clock rows must be f32 too — no mixed-provenance numbers
        # from a faster bfloat16 row (rows predating the compute_dtype
        # field are f32, the config default)
        if row.get("compute_dtype", "float32") != "float32":
            continue
        platform = row.get("platform", "unknown")
        eps = row["env_steps_per_sec"] / REF_STEPS_PER_EPISODE
        if platform not in best or eps > best[platform]["episodes_per_sec"]:
            best[platform] = {
                "episodes_per_sec": eps,
                "impl": row.get("impl"),
                "timestamp": row.get("timestamp"),
            }
    return best


def _fmt_seconds(s: float) -> str:
    if not np.isfinite(s):
        return "—"
    if s >= 3600:
        return f"{s / 3600:.1f} h"
    if s >= 60:
        return f"{s / 60:.1f} min"
    return f"{s:.1f} s"


def _fmt_ep(e: float, n_seeds: int) -> str:
    """An absent cell ('no data') must not read as a sample-efficiency
    verdict ('not reached')."""
    if np.isfinite(e):
        return f"{int(e)}"
    return "not reached" if n_seeds else "no data"


def _fmt_val(x: float) -> str:
    return f"{x:.2f}" if np.isfinite(x) else "—"


def plot_quality_crossing(
    mine_dir,
    ref_dir,
    out_path,
    scenario: str = "coop",
    H: int = 1,
    window: int = 500,
    tol: float = 0.05,
    rolling: int = 200,
) -> str:
    """The visual behind one QUALITY.md row: both smoothed seed-mean
    curves, the threshold line (within ``tol`` of the reference's
    converged return), and each curve's first crossing marked. Same
    full-window smoothing and threshold math as :func:`quality_table`."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    ref_curves = _cell_curves(Path(ref_dir), scenario, H)
    mine_curves = _cell_curves(Path(mine_dir), scenario, H)
    if not ref_curves or not mine_curves:
        raise FileNotFoundError(
            f"cell {scenario}/H={H} missing under {mine_dir} or {ref_dir}"
        )
    T, threshold = _threshold_from_ref(ref_curves, window, tol)

    fig, ax = plt.subplots(figsize=(7, 4))
    for label, curves in (
        ("reference artifacts", ref_curves),
        ("this framework", mine_curves),
    ):
        curve = _smoothed_mean(curves, rolling)
        (line,) = ax.plot(curve, label=label)
        ep = episodes_to_threshold(curve, threshold)
        if np.isfinite(ep):
            ax.axvline(
                ep, color=line.get_color(), linestyle=":", alpha=0.7
            )
            ax.plot([ep], [curve.iloc[int(ep)]], "o", color=line.get_color())
    ax.axhline(
        threshold,
        color="gray",
        linestyle="--",
        label=f"threshold ({tol:.0%} of ref final {T:.2f})",
    )
    ax.set_xlabel("Episode (dotted = first crossing)")
    ax.set_ylabel(f"True team return (rolling {rolling}, full window)")
    ax.set_title(f"{scenario}, H={H}: episodes to reference quality")
    ax.legend(fontsize=8)
    return save_figure(fig, out_path)


def gossip_evidence_section(artifact_path) -> list:
    """QUALITY.md lines for the Byzantine gossip-replica experiment,
    rendered from the committed ``scripts/gossip_experiment.py``
    artifact (``simulation_results/gossip_byzantine.json``) — like the
    wall-clock columns, the section regenerates byte-stably from the
    evidence file instead of hand-maintained rows. Empty when the
    artifact does not exist."""
    import json

    p = Path(artifact_path)
    if not p.exists():
        return []
    data = json.loads(p.read_text())
    cfg = data["config"]
    lines = [
        "",
        "## Replica-level degradation (gossip)",
        "",
        "`--replicas` runs train R learner replicas mixed by trimmed-mean "
        "gossip (README \"Replica-level resilience\"); their degradation "
        "counters (`df.attrs['gossip']`: mix rounds, per-replica "
        "rollbacks, mix exclusions, non-finite payload entries, "
        "degree-deficit fallbacks) are read exactly like the link-fault "
        "curves above — per-replica rollbacks > 0 mean degradation came "
        "from replica-level containment (lost segments on the poisoned "
        "replica only), while healthy replicas' curves should track the "
        "clean baseline. The committed Byzantine experiment "
        f"(`{p.name}`, `scripts/gossip_experiment.py`: R={cfg['replicas']} "
        f"replicas, full graph, gossip_H={cfg['gossip_H']}, replicas "
        f"{cfg['byzantine']} always-adversarial):",
        "",
        "| mix | byzantine mode | healthy replicas finite | team return "
        "(first→last window) | non-finite payload entries |",
        "|---|---|---|---|---|",
    ]
    for row in data["arms"]:
        n_ok = sum(
            1
            for r, h in enumerate(row["replica_healthy"])
            if h and r not in set(row["byzantine"])
        )
        ret = (
            f"{row['team_return_first']} → {row['team_return_last']}"
            if row["team_return_last"] is not None
            else "poisoned (NaN)"
        )
        lines.append(
            f"| {row['mix']} | "
            f"{row['byzantine_mode'] or 'none (control)'} | "
            f"{n_ok}/{row['n_healthy_expected']} | {ret} | "
            f"{row['nonfinite_payload_entries']} |"
        )
    lines += [
        "",
        "Reading: NaN-bombing destroys the plain-mean arm outright — "
        "every replica's POST-MIX parameters go non-finite (its return "
        "column stays finite only where the per-replica guard keeps "
        "re-serving each replica's last good parameters; the training "
        "signal is gone) — while the trimmed arm absorbs the same "
        "payload bombs as elementwise exclusions and tracks the clean "
        "control. Finite-value attacks (sign_flip) cannot NaN a mean, "
        "so both arms stay finite there; the trimmed arm's clip bounds "
        "keep the healthy replicas inside their own envelope "
        "(hypothesis-pinned) where the mean arm is dragged by the "
        "adversarial payloads.",
    ]
    ov = data.get("overhead")
    if ov:
        lines += [
            "",
            f"Gossip overhead on this host ({ov['platform']}): "
            f"{ov['ms_per_mix']} ms per mix — "
            f"{100 * ov['overhead_per_block']:.2f}% of block time at "
            f"`gossip_every={ov['gossip_every']}` (the `gossip_overhead` "
            "row in PERF.jsonl).",
        ]
    return lines


def bf16_parity_section(artifact_path) -> list:
    """QUALITY.md lines for the bf16 compute-arm parity cell, rendered
    from the committed ``scripts/bf16_parity.py`` artifact
    (``simulation_results/bf16_parity.json``) — same byte-stable
    render-from-evidence contract as the gossip section. Empty when the
    artifact does not exist."""
    p = Path(artifact_path)
    if not p.exists():
        return []
    d = json.loads(p.read_text())
    cfg = d["config"]
    ep32 = d["ep_to_threshold_f32"]
    ep16 = d["ep_to_threshold_bf16"]
    verdict = (
        "**within the f32 quality band**"
        if d["bf16_within_band"]
        else "**OUTSIDE the f32 quality band — do not enable bf16 for "
        "this workload without re-measuring**"
    )
    return [
        "",
        "## Mixed precision (bfloat16) parity",
        "",
        "`Config(compute_dtype='bfloat16')` narrows ONLY the matmul "
        "inputs (f32 accumulation; params/optimizer state stay f32 — "
        "README \"Mixed precision\"), so its gate is behavioral: trained "
        "on the same seed and schedule, the bf16 returns curve must land "
        "inside the f32 reference arm's own converged quality band "
        f"(final-{cfg['window']}-episode mean, relaxed by "
        f"{cfg['tol']:.0%} of its magnitude — the PARITY.md tolerance). "
        f"The committed cell (`{p.name}`, `scripts/bf16_parity.py`: "
        f"{cfg['scenario']}, {cfg['episodes']} episodes, seed "
        f"{cfg['seed']}, measured on {d['platform']}):",
        "",
        "| arm | final return | episodes to f32 threshold "
        f"({d['threshold']}) | verdict |",
        "|---|---|---|---|",
        f"| float32 (reference) | {d['f32_final']} | "
        f"{ep32 if ep32 is not None else 'not reached'} | — |",
        f"| bfloat16 | {d['bf16_final']} | "
        f"{ep16 if ep16 is not None else 'not reached'} | {verdict} |",
        "",
        "Reading: the two arms' trajectories diverge sample-by-sample "
        "(a ~1e-2-relative matmul rounding flips individual softmax "
        "action draws, and the rollout is chaotic), so pointwise curve "
        "deltas are meaningless — the gate compares CONVERGED quality "
        "and time-to-quality, exactly how QUALITY.md reads every other "
        f"cell. Max smoothed-tail deviation {d['tail_max_abs_dev']} "
        "return units. The f32 arm stays the bitwise-pinned parity "
        "path; bf16 is the opt-in throughput arm whose win only "
        "materializes on MXU-bearing hardware (PERF.md \"fitstack / "
        "bf16\" — on CPU the casts are pure overhead).",
    ]


def staleness_section(artifact_path) -> list:
    """QUALITY.md lines for the pipeline staleness quality cell,
    rendered from the committed ``scripts/staleness_quality.py``
    artifact (``simulation_results/staleness_quality.json``) — same
    byte-stable render-from-evidence contract as the gossip/bf16
    sections. Empty when the artifact does not exist."""
    p = Path(artifact_path)
    if not p.exists():
        return []
    d = json.loads(p.read_text())
    cfg = d["config"]
    lines = [
        "",
        "## Pipeline staleness vs return",
        "",
        "The async actor-learner pipeline (`Config.pipeline_depth`, "
        "README \"Async pipeline\") buys rollout-in-the-epoch's-shadow "
        "throughput by letting the actor tier act on parameters the "
        "learner published up to depth-1 (+ publish-lag) blocks ago — "
        "the same replay semantics the `stale_p` link-fault knob "
        "injects per link, lifted to the whole policy and made a "
        "SCHEDULED quantity the trainer counts per block "
        "(`df.attrs['pipeline']`). The committed sweep "
        f"(`{p.name}`, `scripts/staleness_quality.py`: "
        f"{cfg['scenario']}, {cfg['episodes']} episodes, seed "
        f"{cfg['seed']}, depth {cfg['depth']}, measured on "
        f"{d['platform']}) holds the depth fixed and sweeps "
        "`publish_every`, so the off-policy axis is isolated from the "
        "overlap machinery:",
        "",
        "| arm | measured staleness (mean / max blocks) | final return "
        f"| episodes to sync threshold ({d['threshold']}) | verdict |",
        "|---|---|---|---|---|",
    ]
    for arm in d["arms"]:
        ep = arm["ep_to_threshold"]
        verdict = (
            "within the sync band"
            if arm["within_band"]
            else "**OUTSIDE the sync band**"
        )
        if arm["pipeline_depth"] == 0:
            verdict = "— (threshold source)"
        lines.append(
            f"| {arm['label']} | {arm['staleness_mean']} / "
            f"{arm['staleness_max']} | {arm['final_return']} | "
            f"{ep if ep is not None else 'not reached'} | {verdict} |"
        )
    lines += [
        "",
        "Reading: exactly like the `stale_p` degradation curves above, "
        "the cost of staleness shows up FIRST as sample efficiency "
        "(episodes-to-threshold stretches monotonically with the "
        "measured staleness) and only later as converged quality — an "
        "arm is usable as long as its final return stays inside the "
        "synchronous arm's own quality band (the PARITY.md tolerance "
        f"of {cfg['tol']:.0%}). The staleness column is the MEASURED "
        "per-run counter, not the configured knob: depth and "
        "publish_every compose (steady state ≈ depth-1 + the average "
        "publish lag), and the ramp blocks at the start pull the mean "
        "below the steady state. Pick the publish cadence by this "
        "table, not by intuition; the TPU session re-measures the "
        "throughput side of the trade (tpu_session.sh).",
    ]
    return lines


def env_zoo_section(artifact_path) -> list:
    """QUALITY.md lines for the env-zoo training/evaluation cells,
    rendered from the committed ``scripts/env_zoo_quality.py`` artifact
    (``simulation_results/env_zoo.json``) — same byte-stable
    render-from-evidence contract as the gossip/bf16/staleness
    sections. Empty when the artifact does not exist."""
    p = Path(artifact_path)
    if not p.exists():
        return []
    d = json.loads(p.read_text())
    cfg = d["config"]
    lines = [
        "",
        "## Environment zoo — per-env training and evaluation",
        "",
        "The env registry (`Config.env`, README \"Environment zoo\") "
        "runs every world through the SAME generic rollout/trainer/"
        "serving stack, so each new environment gets this table for "
        "free. The committed cells "
        f"(`{p.name}`, `scripts/env_zoo_quality.py`: {cfg['cast']}, "
        f"{cfg['episodes']} episodes, seed {cfg['seed']}, measured on "
        f"{d['platform']}) drive the REAL CLI end to end — `train "
        "--env <name>` to a checksummed checkpoint, then the frozen-"
        "policy `evaluate` CLI on it:",
        "",
        "| env | first-window return | final-window return | learning? "
        "| evaluate (mean ± std, eps=0) | eval episodes/s |",
        "|---|---|---|---|---|---|",
    ]
    for c in d["cells"]:
        ev = c["evaluate"]
        lines.append(
            f"| {c['env']} | {c['first_window_return']} | "
            f"{c['final_window_return']} | "
            f"{'improving' if c['improved'] else '**not improving**'} | "
            f"{ev['team_return_mean']} ± {ev['team_return_std']} | "
            f"{ev['episodes_per_sec']} |"
        )
    lines += [
        "",
        "Reading: return SCALES differ per env (each world's reward "
        "geometry is its own), so compare a cell only against its own "
        "first-window column — final > first on every row is the "
        "end-to-end learning signal the acceptance criteria ask for. "
        "The evaluate column is the serving-side measurement (pure "
        "policy, `--eps 0`) off the run's checkpoint, proving the "
        "whole CLI -> registry -> rollout -> checkpoint -> frozen-"
        "policy chain per env; `bench --env <name>` adds the "
        "throughput axis (PERF.jsonl rows tagged with the resolved "
        "env name).",
    ]
    return lines


def canary_section(artifact_path) -> list:
    """QUALITY.md lines for the canary-gated deployment experiment,
    rendered from the committed ``scripts/canary_experiment.py``
    artifact (``simulation_results/canary_gate.json``) — same
    byte-stable render-from-evidence contract as the
    gossip/bf16/staleness sections. Empty when the artifact does not
    exist."""
    p = Path(artifact_path)
    if not p.exists():
        return []
    d = json.loads(p.read_text())
    cfg = d["config"]
    lines = [
        "",
        "## Canary-gated deployment",
        "",
        "The reject/last-good machinery guards two fault classes — a "
        "bad FILE (checksum chain, `.prev` fallback) and a poisoned "
        "TREE (`params_finite`); the canary gate "
        "(`rcmarl_tpu.serve.canary`, README \"Serving at production "
        "scale\") extends it to the one that actually ships: a "
        "checksum-valid, fully finite checkpoint whose POLICY "
        "regressed. Every publish is measured by its FROZEN-policy "
        "return (the deterministic `eval_block` stream) against the "
        "serving incumbent's own band — below "
        "`incumbent - band*|incumbent|` the candidate is REJECTED and "
        "the incumbent keeps serving. The committed experiment "
        f"(`{p.name}`, `scripts/canary_experiment.py`: "
        f"{cfg['scenario']}, incumbent at "
        f"{cfg['episodes_incumbent']} episodes, band {cfg['band']:.0%}, "
        f"{cfg['eval_blocks']} eval blocks per measurement, measured "
        f"on {d['platform']}) drives the REAL file-watcher deployment "
        "loop — after every rejection the engine's serving block is "
        "verified BITWISE against the last promoted policy:",
        "",
        "| publish | candidate frozen return | band floor | verdict |",
        "|---|---|---|---|",
    ]
    for a in d["arms"]:
        cand = (
            a["candidate_return"]
            if a["candidate_return"] is not None
            else "— (guard reject, no eval paid)"
        )
        verdict = (
            "promoted"
            if a["promoted"]
            else f"**REJECTED** ({a['reason']})"
        )
        lines.append(f"| {a['label']} | {cand} | {a['floor']} | {verdict} |")
    g = d["gate_counters"]
    lines += [
        "",
        f"Reading: the incumbent's own frozen return "
        f"({d['incumbent_return']}) sets the bar, exactly how every "
        "other QUALITY cell reads its clean band. The healthy publish "
        "(a genuinely newer policy) clears it and BECOMES the "
        "incumbent reference — which is why the stale snapshot is then "
        "judged against the promoted policy's floor, the production "
        "semantics (you canary against what is serving, not against "
        "history). The stale publish is the case no file/finiteness "
        "guard can catch: a perfectly valid checkpoint that is simply "
        "a worse policy — caught by the band alone. The poisoned "
        "publish never reaches an eval (the shared `params_finite` "
        "guard runs first), and the re-publish proves the gate does "
        f"not wedge after rejections ({g['accepts']} accepted / "
        f"{g['rejects']} band-rejected over {g['evals']} evals; the "
        "engine's degradation counters carry the same history on the "
        "serve row). The same gate binds to the in-memory pipeline "
        "chain as `PolicyPublisher(..., canary=gate.admit)` — a "
        "pipelined learner's degraded candidate never reaches the "
        "acting tier either.",
    ]
    return lines


def gossip_readmission_section(artifact_path) -> list:
    """QUALITY.md lines for the gossip readmission experiment, rendered
    from the committed ``scripts/gossip_readmission.py`` artifact
    (``simulation_results/gossip_readmission.json``) — same byte-stable
    render-from-evidence contract as the gossip/canary sections. Empty
    when the artifact does not exist."""
    p = Path(artifact_path)
    if not p.exists():
        return []
    d = json.loads(p.read_text())
    cfg = d["config"]
    lines = [
        "",
        "## Gossip readmission under flapping senders",
        "",
        "The PR-7 guard excludes a rolled-back replica for exactly ONE "
        "mix — right for transient poisonings, but a FLAPPING sender "
        "(probabilistically poisoned segment by segment) re-enters the "
        "mix every time its luck turns. `train_gossip(readmit_after=K)` "
        "(`--gossip_readmit_after`) makes the quarantine sticky: an "
        "excluded replica must prove K consecutive healthy probe rounds "
        "before its payloads re-enter; it keeps training and keeps "
        "RECEIVING mixes meanwhile, so readmission is recovery, not "
        "resurrection. The committed experiment "
        f"(`{p.name}`, `scripts/gossip_readmission.py`: "
        f"R={cfg['replicas']} full graph, gossip_H={cfg['gossip_H']}, "
        f"agent-level nan_p={cfg['nan_p']} without sanitize — the "
        f"flapping injection — {cfg['n_episodes']} episodes, measured "
        f"on {d['platform']}):",
        "",
        "| arm | rollbacks | excluded replica-rounds | readmitted | "
        "all replicas finite | final return | verdict |",
        "|---|---|---|---|---|---|---|",
    ]
    for a in d["arms"]:
        if a["label"] == "clean":
            verdict = "— (clean band source)"
        elif a["within_band"]:
            verdict = "within the clean band"
        else:
            verdict = "**OUTSIDE the clean band**"
        lines.append(
            f"| {a['label']} | {a['rollbacks']} | "
            f"{a['excluded_replica_rounds']} | {a['readmitted']} | "
            f"{sum(a['replica_healthy'])}/{len(a['replica_healthy'])} | "
            f"{a['final_return']} | {verdict} |"
        )
    n_readmit = max(a["readmitted"] for a in d["arms"])
    lines += [
        "",
        "Reading: the excluded-replica-rounds column is the containment "
        "price — the sticky arm pays MORE excluded rounds than the "
        "legacy arm on the same fault draws (a quarantined replica "
        "serves its probation instead of bouncing straight back), and "
        "the readmitted column proves re-entry actually happens "
        f"({n_readmit} readmissions in the sticky arm). The envelope "
        "holds under the flapping: every replica ends finite in every "
        "arm and both faulted arms' returns sit inside the clean band "
        f"(tolerance {cfg['tol']:.0%}) — quarantine costs mixing "
        "freshness, not convergence. `readmit_after=0` (the default) "
        "is pinned bit-for-bit to the PR-7 one-round behavior "
        "(tests/test_gossip.py); the scripted-flap twins pin the "
        "streak-reset semantics, and the chaos campaign's "
        "`gossip_flapping` cell gates the live behavior in "
        "RESILIENCE.jsonl.",
    ]
    return lines


def gala_section(artifact_path) -> list:
    """QUALITY.md lines for the pipelined-gossip-fleet experiment,
    rendered from the committed ``scripts/gala_experiment.py`` artifact
    (``simulation_results/gala_composed.json``): the composed topology
    (pipeline x gossip x canary) next to its flat pieces, with the
    degradation bands side by side. Empty when the artifact does not
    exist."""
    import json

    p = Path(artifact_path)
    if not p.exists():
        return []
    data = json.loads(p.read_text())
    cfg = data["config"]
    v = data["verdict"]
    lines = [
        "",
        "## Pipelined gossip fleets (composed degradation)",
        "",
        "`--replicas R --pipeline_depth D` composes the gossip replica "
        "layer with the async pipeline and the canary-gated deploy "
        "publish into one topology (README \"Pipelined gossip "
        "fleets\"). The committed composed experiment (`" + p.name + "`, "
        "`scripts/gala_experiment.py`: "
        f"R={cfg['replicas']} replicas, full graph, "
        f"gossip_H={cfg['gossip_H']}, depth={cfg['pipeline_depth']}, "
        f"mix every {cfg['gossip_every']} blocks, canary band "
        f"{cfg['canary_band']}, replica {cfg['byzantine']} "
        "always-NaN) runs the Byzantine cell FLAT and COMPOSED so the "
        "degradation envelopes sit side by side:",
        "",
        "| arm | mix | depth | healthy replicas finite | team return "
        "(first\u2192last window) | rollbacks | deploy rejects |",
        "|---|---|---|---|---|---|---|",
    ]
    for row in data["arms"]:
        n_ok = sum(
            1
            for r, h in enumerate(row["replica_healthy"])
            if h and r not in set(row["byzantine"])
        )
        n_exp = row["replicas"] - len(row["byzantine"])
        ret = (
            f"{row['team_return_first']} \u2192 {row['team_return_last']}"
            if row["team_return_last"] is not None
            else "poisoned (NaN)"
        )
        canary = row.get("canary")
        rej = canary["deploy_rejects"] if canary else "\u2014"
        lines.append(
            f"| {row['arm']} | {row['mix']} | {row['pipeline_depth']} "
            f"| {n_ok}/{n_exp} | {ret} | {row['rollbacks']} | {rej} |"
        )
    lines += [
        "",
        "Reading: the composed Byzantine arm must hold the SAME "
        "chaos-band contract against its composed clean twin that the "
        "flat arm holds against its own \u2014 composition degrading no "
        "worse than its pieces "
        f"(flat in band: {v['flat_in_band']}, composed in band: "
        f"{v['composed_in_band']}; the RESILIENCE.jsonl "
        "`gala_byzantine` cells gate this every CI run). The mean-mix "
        "arm is the same documented fail it is flat "
        f"(poisoned: {v['mean_poisoned']}) \u2014 but the canary-gated "
        "deploy publisher rejects every poisoned winner, so serving "
        "keeps the last good policy even while training is lost "
        f"(serving contained: {v['serving_contained']}; the "
        "`gala_canary_race` cell).",
    ]
    perf = data.get("perf")
    if perf:
        lines += [
            "",
            f"Composed throughput on this host ({perf['platform']}): "
            f"{perf['env_steps_per_sec']} env steps/s across the fleet "
            f"(the `gala_composed` row in PERF.jsonl"
            + (", headline:false \u2014 a serial CPU core runs every "
               "replica's two tiers back to back"
               if perf["platform"] == "cpu" else "")
            + ").",
        ]
    return lines


def autoscale_slo_section(artifact_path) -> list:
    """QUALITY.md lines for the autoscale-SLO experiment, rendered from
    the committed ``scripts/autoscale_experiment.py`` artifact
    (``simulation_results/autoscale_slo.json``) — same byte-stable
    render-from-evidence contract as the gossip/canary sections. Empty
    when the artifact does not exist."""
    p = Path(artifact_path)
    if not p.exists():
        return []
    d = json.loads(p.read_text())
    cfg = d["config"]
    auto, static = d["arms"][0], d["arms"][1]

    def _ms(x) -> str:
        return "∞ (all shed)" if x is None else f"{x}"

    lines = [
        "",
        "## SLO-driven autoscaling under a 10x load swing",
        "",
        "The serving tier's latency harness measures ONE fleet size; "
        "the SLO control loop (`rcmarl_tpu.serve.autoscale`, README "
        "\"One-kernel serving + SLO autoscaling\") closes it: windowed "
        "p99/demand/shed telemetry drives `SLOController` resize "
        "decisions that land exactly at window boundaries — breach or "
        "shed doubles the fleet, sustained high demand resizes "
        "proportionally, and scale-down waits out hysteresis plus a "
        "projected-demand gate so releasing capacity never causes the "
        "next breach. The committed experiment "
        f"(`{p.name}`, `scripts/autoscale_experiment.py`: "
        f"{cfg['scenario']}, measured per-launch "
        f"{cfg['per_launch_ms']}ms on the `{cfg['serve_impl_resolved']}` "
        f"arm at batch {cfg['batch']}, p99 SLO {cfg['slo_ms']}ms, "
        f"deadline shedding at the SLO on BOTH arms, seeded "
        f"1x→10x→1x Poisson swing of "
        f"{auto['requests']} requests, {cfg['n_windows']} control "
        f"windows of {cfg['window_ms']}ms, measured on "
        f"{d['platform']}):",
        "",
        "| offered load | req/s | autoscaled p99 (ms) | fleet scale | "
        "autoscaled shed | static p99 (ms) | static shed |",
        "|---|---|---|---|---|---|---|",
    ]
    for c in d["curve"]:
        lines.append(
            f"| {c['factor']}x | {c['offered_rps']} | "
            f"{_ms(c['auto_p99_ms'])} | {c['auto_scale']} | "
            f"{c['auto_shed']} | {_ms(c['static_p99_ms'])} | "
            f"{c['static_shed']} |"
        )
    lines += [
        "",
        f"Reading: the autoscaled fleet holds the p99 SLO in every "
        f"window and sheds {auto['shed']} of {auto['requests']} "
        f"requests (peak scale {auto['max_scale_used']}, back to "
        f"{auto['final_scale']} after the swing — the scale column "
        "shows capacity following load in BOTH directions), while the "
        "static scale-1 fleet on the identical seeded plan saturates: "
        f"p99 past the {cfg['slo_ms']}ms target in the violated "
        f"windows and {static['shed_fraction']:.0%} of all requests "
        "shed at the deadline — the price of not scaling is paid in "
        "dropped requests, exactly what the deadline-shedding ledger "
        "exists to count. The 10x peak offers 5x the static fleet's "
        "capacity by construction, so saturation is arithmetic, not "
        "bad luck. The service model is the measured MEDIAN launch "
        "time of the real compiled serving program (100 timed "
        "launches), replayed deterministically — the committed curve "
        "isolates queueing (what scaling fixes) from this host's "
        "dispatch jitter (what it cannot); live-launch billing rides "
        "`serve --autoscale`, tests/test_autoscale.py pins the same "
        "claims on an injected service model, and the chaos "
        "campaign's `serve_overload@autoscale` cell gates the "
        "scale-out response in RESILIENCE.jsonl.",
    ]
    return lines


def chaos_campaign_section(ledger_path) -> list:
    """QUALITY.md lines summarizing the committed RESILIENCE.jsonl
    chaos ledger (``python -m rcmarl_tpu chaos --run``) — rendered from
    the ledger itself so the section can never disagree with the gated
    artifact. Empty when the ledger does not exist."""
    p = Path(ledger_path)
    if not p.exists():
        return []
    rows = [
        json.loads(line)
        for line in p.read_text().splitlines()
        if line.strip()
    ]
    if not rows:
        return []
    by_subsystem: Dict[str, list] = {}
    for r in rows:
        by_subsystem.setdefault(r["subsystem"], []).append(r)
    lines = [
        "",
        "## Chaos campaign (RESILIENCE.jsonl)",
        "",
        "The fault surface as ONE swept, CI-gated artifact "
        "(`rcmarl_tpu.chaos`): every injectable fault in the repo is a "
        "registered point, each (point, intensity) cell runs as a short "
        "REAL run through the actual subsystem entry points, and the "
        f"committed ledger (`{p.name}`, {len(rows)} cells across "
        f"{len(by_subsystem)} subsystems) is gated every CI run by "
        "`chaos --check` — a cell that previously survived and now "
        "fails, or whose degradation envelope widens past tolerance, "
        "is a finding. Cells EXPECTED to fail (the undefended "
        "comparison arms: plain-mean gossip, H=0 under collusion) are "
        "part of the documented surface — a regression that silently "
        "fixed them would be as suspicious as one that broke a "
        "defended cell.",
        "",
        "| subsystem | cells | survived | degraded | failed (documented "
        "undefended arms) | unexpected outcomes |",
        "|---|---|---|---|---|---|",
    ]
    for sub in sorted(by_subsystem):
        rs = by_subsystem[sub]
        counts = {o: sum(1 for r in rs if r["outcome"] == o)
                  for o in ("survived", "degraded", "failed")}
        unexpected = sum(1 for r in rs if r["outcome"] != r["expected"])
        lines.append(
            f"| {sub} | {len(rs)} | {counts['survived']} | "
            f"{counts['degraded']} | {counts['failed']} | {unexpected} |"
        )
    lines += [
        "",
        "Reading: `survived` = the guards contained the fault "
        "completely (finite, in-band, bitwise-correct serving); "
        "`degraded` = contained but measurably reduced (skipped "
        "blocks, a quarantined replica, latency past the bound on the "
        "shed-free overload arm); `failed` = containment broke — every "
        "committed `failed` row is an EXPECTED undefended arm, and the "
        "unexpected-outcomes column is 0 by construction on a clean "
        "ledger. Per-cell intensities, guard counters, and the "
        "final-vs-clean return deltas live in the ledger rows; "
        "`python -m rcmarl_tpu chaos --list` prints the registry with "
        "each point's guard and test pin, and README's unified "
        "fault-surface table cross-references every row.",
    ]
    return lines


def mega_population_section(artifact_path) -> list:
    """QUALITY.md lines for the mega-population sparse-consensus
    experiment, rendered from the committed
    ``scripts/mega_population.py`` artifact
    (``simulation_results/mega_population.json``). Empty when the
    artifact does not exist."""
    p = Path(artifact_path)
    if not p.exists():
        return []
    d = json.loads(p.read_text())
    cfg = d["config"]
    clean = next(a for a in d["arms"] if a["adversaries"] == 0)
    lines = [
        "",
        "## Mega-population: sparse consensus at n=256 under attack",
        "",
        "The n-scale twin of the adaptive-adversary cell above, with "
        "consensus riding the SPARSE time-varying exchange "
        "(`ops/exchange.py`, README \"Mega-population scenarios\") and "
        "the `fit_clip` stability rail on. Two gates per arm: the "
        "return band (as every other cell), and `values_sane` — the "
        "largest |parameter| across the COOPERATIVE agents' consensus "
        "critic+TR rows, gated at 100x the clean arm's magnitude. The "
        "second gate exists because the first is BLIND here: Adam's "
        "scale invariance normalizes blown-up advantages away in the "
        "actor step, so arms whose value nets are poisoned by orders "
        "of magnitude still sample near-identical actions for the "
        "whole committed horizon. The committed run "
        f"(`{p.name}`, `scripts/mega_population.py`: "
        f"{cfg['scenario']}, {cfg['episodes']} episodes, seed "
        f"{cfg['seed']}, scale {cfg['adaptive_scale']}, measured on "
        f"{d['platform']}):",
        "",
        f"| arm | H | adversaries | final return (last {cfg['window']}) "
        "| coop consensus max \\|param\\| | verdict |",
        "|---|---|---|---|---|---|",
    ]
    for a in d["arms"]:
        sane = a["values_sane"]
        if a["collapsed_at_episode"] is not None:
            verdict = (
                f"**collapsed** (non-finite at episode "
                f"{a['collapsed_at_episode']})"
            )
        elif a["adversaries"] == 0:
            verdict = (
                "— (clean band source; "
                f"{'improved' if a.get('improved') else 'DID NOT improve'} "
                f"{a['first_window']} → {a['final_return']})"
            )
        elif a["within_clean_band"] and sane:
            verdict = "returns in band, values sane"
        elif a["within_clean_band"]:
            verdict = "**returns in band, VALUES POISONED**"
        else:
            verdict = "**DEGRADED — outside the clean band**"
        lines.append(
            f"| {a['label']} | {a['H']} | {a['adversaries']} | "
            f"{a['final_return']} | {a['consensus_abs_max']} | "
            f"{verdict} |"
        )
    lines += [
        "",
        "Reading: training IMPROVES at n=256 on the sparse path (the "
        "clean arm's verdict), and the consensus-magnitude column is a "
        "graded provisioning ladder the return column is blind to. The "
        "provisioned trim holds both gates BY CONSTRUCTION — with "
        "total colluders <= H, no neighborhood can ever contain more "
        "than H of them, under any schedule. The H=1 arm is "
        "under-provisioned: whenever both colluders land in one "
        "resampled neighborhood they beat a 1-per-side trim, and the "
        "measured magnitude is visibly elevated over clean — the leak "
        "is real, merely slow enough at 2 colluders to stay bounded "
        "over this horizon. It does NOT stay bounded as the colluder "
        "count grows: at 8 colluders the same under-provisioning "
        "(including H=2, which a few >=3-colluder neighborhoods per 60 "
        "resamples defeat) compounds geometrically to non-finite, "
        "because each leaked payload (scale x the healthy spread) "
        "widens the next epoch's spread and per-block resampling mixes "
        "the exposure across ALL agents. The H=0 arm is the blindness "
        "finding at full strength: its returns sit IN the clean band — "
        "Adam normalizes the blown-up advantages away in the actor "
        "step — while its healthy agents' value nets are non-finite. "
        "The trimmed-mean guarantee is <=H Byzantine PER NEIGHBORHOOD, "
        "not per population — provision `H` against the worst possible "
        "neighborhood, and gate deployments on value-net magnitude, "
        "never on returns alone (the sparse exchange itself changes "
        "nothing here: the gather is bitwise the dense one).",
    ]
    return lines


def adaptive_adversary_section(artifact_path) -> list:
    """QUALITY.md lines for the adaptive colluding-adversary
    experiment, rendered from the committed
    ``scripts/adaptive_adversary.py`` artifact
    (``simulation_results/adaptive_adversary.json``). Empty when the
    artifact does not exist."""
    p = Path(artifact_path)
    if not p.exists():
        return []
    d = json.loads(p.read_text())
    cfg = d["config"]
    lines = [
        "",
        "## Adaptive colluding adversary vs the trimmed mean",
        "",
        "The `Adaptive` role (`Config.adaptive_scale`, "
        "`rcmarl_tpu.faults.adaptive_payload_tree`) is the omniscient "
        "colluding adversary the three scripted labels never were: "
        "every epoch it reads the CURRENT cooperative messages and "
        "transmits `mean_coop + scale * (max_coop - min_coop)` on "
        "every parameter coordinate — coordinated placement against a "
        "clip-and-average consensus, the natural stress test for `H`. "
        f"The committed sweep (`{p.name}`, "
        f"`scripts/adaptive_adversary.py`: {cfg['scenario']}, "
        f"{cfg['episodes']} episodes, seed {cfg['seed']}, scale "
        f"{cfg['adaptive_scale']}, measured on {d['platform']}):",
        "",
        "| arm | H | adversaries | final return "
        f"(last {cfg['window']}) | verdict |",
        "|---|---|---|---|---|",
    ]
    for a in d["arms"]:
        if a["collapsed_at_episode"] is not None:
            verdict = (
                f"**collapsed** (non-finite at episode "
                f"{a['collapsed_at_episode']})"
            )
        elif a["label"] == "clean_h1":
            verdict = "— (clean band source)"
        elif a["adversaries"] == 0:
            verdict = "— (clean control)"
        elif a["within_clean_band"]:
            verdict = "within the clean band"
        else:
            verdict = "**DEGRADED — outside the clean band**"
        scale = a["adaptive_scale"] if a["adversaries"] else "—"
        lines.append(
            f"| {a['label']} | {a['H']} | {a['adversaries']} "
            f"(scale {scale}) | {a['final_return']} | {verdict} |"
        )
    lines += [
        "",
        "Reading: the clean H=1 arm pins the band; the clean H=0 "
        "control shows the degradation below is the ATTACK's doing, "
        "not H=0's (without an adversary the untrimmed arm converges "
        "fine — slightly better, since trimming healthy extremes "
        "discards a little signal). Under the colluding payload, the "
        f"trimmed mean at H=1 stays within the {cfg['tol']:.0%} clean "
        "band — the ≤H colluding copies stack on one side of every "
        "coordinate's order statistics, exactly where the trim cuts — "
        "while the plain H=0 clip-and-average (whose clip bounds are "
        "the gathered min/max the adversary itself sets) degrades. "
        "The small-scale arm is the residual-influence check: a "
        "payload placed just inside the trim bounds survives trimming "
        "by construction, and its influence is bounded by the healthy "
        "spread itself. At much larger scales the H=0 arm's fits "
        "overflow to non-finite within a block (the guard-rail path); "
        "the committed scale is chosen so the degradation is a "
        "measured return gap, not a crash.",
    ]
    return lines


def write_quality_md(
    table: pd.DataFrame,
    out_path,
    throughput: Dict[str, dict],
    window: int,
    tol: float,
    rolling: int,
    mine_dir,
    ref_dir,
    bench_jsonl,
) -> None:
    """Render QUALITY.md: the episodes-to-threshold matrix plus wall-clock
    columns derived from the measured throughput rows."""
    ref_eps_per_sec = REF_BASELINE_STEPS_PER_SEC / REF_STEPS_PER_EPISODE
    platforms = sorted(throughput)
    lines = [
        "# QUALITY — episodes and wall-clock to reach the reference's "
        "converged returns",
        "",
        "**Generated by `python -m rcmarl_tpu quality` — do not edit "
        "result rows by hand.** This is BASELINE.json's second metric, "
        '"episodes-to-return-threshold": raw steps/s cannot tell whether '
        "a rebuild also *learns* at the reference's sample efficiency, "
        "so this artifact measures, per scenario cell, how many episodes "
        "each implementation needs to first reach within "
        f"{tol:.0%} of the reference's own converged team return "
        f"(its final-{window}-episode seed mean, PARITY.md's ref column), "
        f"on the rolling({rolling}) seed-mean curve — both sides computed "
        "by the same pipeline from the same artifact trees as PARITY.md "
        f"(ours: `{mine_dir}`, reference: `{ref_dir}`).",
        "",
        "Wall-clock columns: the reference's derived ~2.5 env-steps/s "
        "(= 8 s/episode, BASELINE.md); "
        + (
            "ours from the measured "
            f"`ref5_ring` production-block rows in `{bench_jsonl}` "
            + "; ".join(
                f"{p}: {t['episodes_per_sec']:.1f} eps/s ({t['impl']}, "
                f"{t['timestamp']})"
                for p, t in sorted(throughput.items())
            )
            + ". Single-replica timings — replica batching (bench.py's "
            "headline) multiplies aggregate throughput further without "
            "changing any per-replica number below."
            if throughput
            else "no measured `ref5_ring` single-replica f32 "
            f"production-block rows found in `{bench_jsonl}`, so the "
            "'ours' wall-clock columns are omitted — run "
            "`python -m rcmarl_tpu bench --configs ref5_ring` to "
            "produce them."
        ),
        "",
        "| Scenario | H | ref final | threshold | ref episodes | our "
        "episodes | episode ratio | ref wall-clock |"
        + "".join(f" ours ({p}) |" for p in platforms),
        "|---|---|---|---|---|---|---|---|" + "---|" * len(platforms),
    ]
    for _, row in table.iterrows():
        degenerate = bool(row.get("degenerate", False))
        asymmetric = bool(row.get("asymmetric", False))
        ref_seeds = int(row.get("ref_seeds", 1))
        mine_seeds = int(row.get("mine_seeds", 1))
        if degenerate:
            verdict = "degenerate†"
        elif asymmetric:
            verdict = "asymmetric‡"
        elif np.isfinite(row.ep_ratio):
            verdict = f"{row.ep_ratio:.2f}"
        else:
            verdict = "—"
        cells = [
            "",
            row.scenario,
            str(int(row.H)),
            _fmt_val(row.ref_final),
            _fmt_val(row.threshold),
            _fmt_ep(row.ep_ref, ref_seeds),
            _fmt_ep(row.ep_mine, mine_seeds),
            verdict,
            _fmt_seconds(row.ep_ref / ref_eps_per_sec),
        ]
        for p in platforms:
            cells.append(
                _fmt_seconds(
                    row.ep_mine / throughput[p]["episodes_per_sec"]
                )
            )
        lines.append(" | ".join(cells).strip() + " |")

    def _flag(col: str) -> pd.Series:
        return (
            table[col].fillna(False).astype(bool)
            if col in table
            else pd.Series(False, index=table.index)
        )

    degen, asym = _flag("degenerate"), _flag("asymmetric")
    # a learning signal needs a reference threshold AND a two-sided
    # crossing to compare: mine-only cells (NaN threshold) have nothing
    # to time against, and degenerate/asymmetric cells have an at-start
    # crossing on at least one side that makes the ratio meaningless
    meaningful = table[~degen & ~asym & table["threshold"].notna()]
    finite = meaningful.dropna(subset=["ep_ref", "ep_mine"])
    if len(finite):
        med = float(finite.ep_ratio.median())
        lines += [
            "",
            f"**Of the {len(meaningful)} cells with a real learning "
            f"signal, {len(finite)} are reached by both implementations; "
            f"median episode ratio {med:.2f}** "
            "(>1 = fewer episodes than the reference to reach its own "
            "converged quality; ~1 = matched sample efficiency — the "
            "wall-clock advantage is then pure throughput).",
        ]
    asym_rows = table[asym]
    if len(asym_rows):
        findings = []
        for _, row in asym_rows.iterrows():
            if bool(row.get("degenerate_ref", False)):
                at_start, other, other_ep = (
                    "the reference", "this framework", row.ep_mine
                )
            else:
                at_start, other, other_ep = (
                    "this framework", "the reference", row.ep_ref
                )
            arrives = (
                f"first reaches it at episode {int(other_ep)}"
                if np.isfinite(other_ep)
                else "never reaches it in the swept budget"
            )
            findings.append(
                f"- **{row.scenario} H={int(row.H)}**: {at_start} is at "
                f"threshold from its first fully-smoothed point, but "
                f"{other} {arrives}."
            )
        lines += [
            "",
            f"**Asymmetric cells ({len(asym_rows)}):** one side starts "
            "at threshold while the other does not — a real behavioral "
            "difference the ratio cannot express:",
            "",
            *findings,
        ]
    if len(table):
        lines += [
            "",
            "† degenerate: BOTH curves' converged returns are within "
            "tolerance of STARTING performance (the undefended H=0 "
            "attack cells — the attack erases learning progress), so "
            "there is nothing to time; excluded from the summary "
            "statistic. ‡ asymmetric: exactly ONE side starts at "
            "threshold (see the findings list above); also excluded "
            "from the summary ratio, but reported as a finding rather "
            "than hidden by the exclusion. Cells marked 'not reached' "
            "never touch the threshold on the smoothed seed-mean curve "
            "within the swept episode budget; see PARITY.md for how far "
            "outside they converge and DRIFT.md for the root-cause "
            "arbitration of the private-reward cells.",
        ]
    lines += [
        "",
        "## Reading degradation-under-injection curves",
        "",
        "Sweeps run with a transport-fault plan (`sweep --fault_nan_p "
        "... --sanitize`, rcmarl_tpu.faults) produce the SAME sim_data "
        "layout, so this pipeline applies unchanged — but the rows "
        "measure graceful degradation, not clean-run parity. Read them "
        "against the clean baseline above, not against the reference. "
        "Cells whose metrics go non-finite (a fault plan without "
        "`--sanitize`) are never written as results: the sweep records "
        "and skips them and exits nonzero, so every row below is a "
        "genuinely completed run. Then: "
        "(1) the delta in converged return between a faulted cell and "
        "its clean twin is the cost of the injected fault rate; "
        "(2) a faulted cell that still CROSSES the clean threshold "
        "shows the sanitize/guard stack contains the fault class at "
        "that rate; (3) a curve that flattens far below threshold "
        "while the trainer's guard counters (`train` prints retries / "
        "skipped blocks / non-finite payloads / degree-deficit "
        "fallbacks) stay near zero means the faults are absorbed as "
        "silent trim-exclusions — raise `--fault_*` rates or drop "
        "`--sanitize` to locate the cliff; (4) skipped blocks > 0 "
        "means degradation came from ROLLBACK (lost update blocks), "
        "not from consensus noise, so episodes-to-threshold inflates "
        "roughly by the skip fraction. Degenerate/asymmetric labels "
        "keep their clean-run meaning.",
    ]
    gossip_artifact = (
        Path(out_path).parent / "simulation_results/gossip_byzantine.json"
    )
    lines += gossip_evidence_section(gossip_artifact)
    readmission_artifact = (
        Path(out_path).parent / "simulation_results/gossip_readmission.json"
    )
    lines += gossip_readmission_section(readmission_artifact)
    bf16_artifact = (
        Path(out_path).parent / "simulation_results/bf16_parity.json"
    )
    lines += bf16_parity_section(bf16_artifact)
    staleness_artifact = (
        Path(out_path).parent / "simulation_results/staleness_quality.json"
    )
    lines += staleness_section(staleness_artifact)
    env_zoo_artifact = (
        Path(out_path).parent / "simulation_results/env_zoo.json"
    )
    lines += env_zoo_section(env_zoo_artifact)
    adaptive_artifact = (
        Path(out_path).parent / "simulation_results/adaptive_adversary.json"
    )
    lines += adaptive_adversary_section(adaptive_artifact)
    canary_artifact = (
        Path(out_path).parent / "simulation_results/canary_gate.json"
    )
    lines += canary_section(canary_artifact)
    gala_artifact = (
        Path(out_path).parent / "simulation_results/gala_composed.json"
    )
    lines += gala_section(gala_artifact)
    autoscale_artifact = (
        Path(out_path).parent / "simulation_results/autoscale_slo.json"
    )
    lines += autoscale_slo_section(autoscale_artifact)
    resilience_ledger = Path(out_path).parent / "RESILIENCE.jsonl"
    lines += chaos_campaign_section(resilience_ledger)
    megapop_artifact = (
        Path(out_path).parent / "simulation_results/mega_population.json"
    )
    lines += mega_population_section(megapop_artifact)
    lines += [
        "",
        "## Related artifacts",
        "",
        "- `PARITY.md` — converged-return parity matrix (same trees, "
        "same pipeline)",
        f"- `{bench_jsonl}` — the measured block-time rows behind the "
        "wall-clock columns",
        "- `BENCH_SCALING.md` — scaling matrix narrative",
        "- `simulation_results/figures/quality_*.png` — per-cell "
        "crossing figures (`python -m rcmarl_tpu plot --quality`)",
    ]
    if gossip_artifact.exists():
        lines.append(
            "- `simulation_results/gossip_byzantine.json` — the "
            "Byzantine gossip-replica experiment behind the replica-"
            "level degradation section (`scripts/gossip_experiment.py`)"
        )
    if gala_artifact.exists():
        lines.append(
            "- `simulation_results/gala_composed.json` — the composed "
            "pipelined-gossip-fleet experiment behind the composed "
            "degradation section (`scripts/gala_experiment.py`)"
        )
    if bf16_artifact.exists():
        lines.append(
            "- `simulation_results/bf16_parity.json` — the measured "
            "bf16-vs-f32 returns-curve agreement cell behind the mixed-"
            "precision section (`scripts/bf16_parity.py`)"
        )
    if staleness_artifact.exists():
        lines.append(
            "- `simulation_results/staleness_quality.json` — the "
            "measured staleness-vs-return sweep behind the pipeline "
            "staleness section (`scripts/staleness_quality.py`)"
        )
    if env_zoo_artifact.exists():
        lines.append(
            "- `simulation_results/env_zoo.json` — the per-env CLI "
            "train+evaluate cells behind the environment-zoo section "
            "(`scripts/env_zoo_quality.py`)"
        )
    if adaptive_artifact.exists():
        lines.append(
            "- `simulation_results/adaptive_adversary.json` — the "
            "adaptive colluding-adversary sweep behind the trimmed-"
            "mean stress-test section (`scripts/adaptive_adversary.py`)"
        )
    if canary_artifact.exists():
        lines.append(
            "- `simulation_results/canary_gate.json` — the deployment-"
            "loop experiment behind the canary-gate section "
            "(`scripts/canary_experiment.py`)"
        )
    if readmission_artifact.exists():
        lines.append(
            "- `simulation_results/gossip_readmission.json` — the "
            "flapping-sender readmission experiment behind the gossip-"
            "readmission section (`scripts/gossip_readmission.py`)"
        )
    if autoscale_artifact.exists():
        lines.append(
            "- `simulation_results/autoscale_slo.json` — the measured "
            "p99-vs-load swing behind the SLO-autoscaling section "
            "(`scripts/autoscale_experiment.py`)"
        )
    if resilience_ledger.exists():
        lines.append(
            "- `RESILIENCE.jsonl` — the CI-gated chaos-campaign ledger "
            "behind the chaos section (`python -m rcmarl_tpu chaos`)"
        )
    if megapop_artifact.exists():
        lines.append(
            "- `simulation_results/mega_population.json` — the n=256 "
            "sparse-consensus attack arms behind the mega-population "
            "section (`scripts/mega_population.py`)"
        )
    # like cmd_parity's related-artifacts list: only link the robustness
    # companion when it exists, and never from itself
    companion = Path(out_path).parent / "QUALITY_SEEDS456.md"
    if companion.exists() and Path(out_path).name != companion.name:
        lines.append(
            "- `QUALITY_SEEDS456.md` — the same pipeline over the three "
            "UNSEEN seeds {400,500,600} (robustness companion, like "
            "PARITY_SEEDS456.md)"
        )
    lines.append("")
    Path(out_path).write_text("\n".join(lines))
