"""Checkpoint hot-swap with guarded degradation.

The trainer writes checkpoints through the write-then-rename + ``.prev``
rotation (:mod:`rcmarl_tpu.utils.checkpoint`), so at every instant there
is a loadable primary and a rotated fallback. This watcher closes the
loop on the serving side, mirroring the trainer's guard-rail pattern
(PR 2): poll the file, and when it changes run the candidate through a
fault guard BEFORE it can reach the engine —

- unreadable / truncated / checksum-failing primary: the shared
  discovery chain falls back to ``.prev`` (counted as a ``fallback``);
  if BOTH are bad the candidate is REJECTED and the engine keeps
  serving the last good block (counted as a ``reject``);
- non-finite parameters (a poisoned but checksum-valid file): rejected,
  last good block kept;
- solo↔replica world mismatch or a structural/shape mismatch against
  the engine's config: fails LOUDLY (an operator error, not a transport
  fault — degrading over it would silently serve the wrong policy).

A swap is atomic by construction: the new stacked block is built and
validated COMPLETELY, then the engine's single block reference is
replaced wholesale — a serve launched before the assignment uses the
old tree, one launched after uses the new tree, and no launch can ever
observe a mix (pinned in tests/test_serve.py).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

from rcmarl_tpu.serve.engine import ServeEngine, stack_actor_rows
from rcmarl_tpu.utils.checkpoint import CheckpointError


class CheckpointWatcher:
    """Poll a checkpoint file and hot-swap validated params into an
    engine, maintaining its degradation counters."""

    def __init__(
        self, engine: ServeEngine, path: Optional[os.PathLike] = None
    ) -> None:
        self.engine = engine
        self.path = Path(path) if path is not None else engine.checkpoint_path
        self._sig = self._signature()

    def _signature(self):
        """(mtime_ns, size, inode) of the primary — the cheap change
        probe; the rename-based checkpoint write always moves all
        three."""
        try:
            st = os.stat(self.path)
        except FileNotFoundError:
            return None
        return (st.st_mtime_ns, st.st_size, st.st_ino)

    def poll(self, force: bool = False) -> bool:
        """Check the file; attempt a swap when it changed (or ``force``).

        Returns True iff a swap was APPLIED. A changed-but-rejected
        candidate returns False with ``rejects`` incremented — the
        engine keeps serving the last good block either way.
        """
        sig = self._signature()
        if not force and sig == self._sig:
            return False
        self._sig = sig
        return self._try_swap()

    def _load_candidate(self):
        """Load + fault-guard a swap candidate: ``(state, loaded_path)``
        on success, ``None`` when the candidate was REJECTED (counters
        incremented, engine degraded — it keeps serving the last good
        block). The deployment-gate seam: the canary watcher
        (:class:`rcmarl_tpu.serve.canary.CanaryWatcher`) runs its
        frozen-policy return gate between this load and
        :meth:`_apply`."""
        from rcmarl_tpu.faults import params_finite
        from rcmarl_tpu.utils.checkpoint import load_checkpoint_with_meta

        eng = self.engine
        try:
            state, _, loaded, meta = load_checkpoint_with_meta(
                self.path, eng.cfg
            )
        except (FileNotFoundError, CheckpointError):
            # bad FILE (missing, truncated, checksum-failed — and the
            # .prev fallback too): degrade, keep serving the last good
            # block
            eng.counters["rejects"] += 1
            eng.degraded = True
            return None
        # A replica-world checkpoint appearing under a solo serving
        # path is an operator error — loud, exactly like the engine's
        # constructor (structure/shape mismatches already raised above).
        n_rep = int(meta.get("replicas", 0))
        if n_rep:
            raise ValueError(
                f"hot-swap candidate {loaded} holds a {n_rep}-replica "
                "gossip world; the serving layout is solo — refusing "
                "the swap loudly (this is a deployment error, not a "
                "transport fault)"
            )
        # fault guard in front of the swap: a checksum-valid file can
        # still carry poisoned (non-finite) params — never serve them
        # (the shared publish-candidate guard, rcmarl_tpu.faults)
        if not params_finite(state.params):
            eng.counters["rejects"] += 1
            eng.degraded = True
            return None
        return state, loaded

    def _apply(self, state, loaded) -> bool:
        """Apply a fully validated candidate: build the stacked block
        COMPLETELY, then swap the engine's single reference — no serve
        can ever observe a torn tree."""
        eng = self.engine
        eng.block = stack_actor_rows(state.params, eng.cfg)
        eng.counters["swaps"] += 1
        eng.degraded = False  # serving the newest candidate again
        if Path(loaded) != self.path:
            eng.counters["fallbacks"] += 1
        return True

    def _try_swap(self) -> bool:
        candidate = self._load_candidate()
        if candidate is None:
            return False
        return self._apply(*candidate)
