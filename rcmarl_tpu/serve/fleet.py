"""Fleet serving — many policy versions in ONE jitted launch.

PR 10 netstacked AGENTS: all actor heads row-stacked so one compiled
program serves every agent of one policy. This module applies the same
move one level up, to CHECKPOINTS: F policy versions / tenants /
per-scenario policies stacked along a new leading fleet axis and served
by ONE jitted program (:func:`fleet_block`), with per-request routing as
DATA — an A/B split, a tenant map, or a scenario router changes the
route array between launches and the SAME executable re-dispatches
(retrace-certified, like every hot path here). The cost ledger's
``fleet_block@fleet`` row pins the stacked program's FLOPs: each member
computes the full batch (the Podracer one-program discipline,
PAPERS.md 2104.06272), so cost scales linearly in F and the routing
gather adds selection, not arithmetic.

Contracts:

- **Per-member bitwise parity**: member f's probabilities inside the
  fleet launch are BITWISE the solo :func:`serve_block` probabilities on
  the same checkpoint, and a request routed to f samples with the same
  ``fold_in(fold_in(key, b), n)`` key it would get solo — so fleet
  serving of one member is indistinguishable from solo serving it
  (pinned in tests/test_serve_fleet.py).
- **Member-isolated degradation**: every member loads through the
  checksummed discovery chain (its own :class:`ServeEngine`) and
  hot-swaps independently through the
  :class:`~rcmarl_tpu.serve.swap.CheckpointWatcher` discipline; a
  corrupt/poisoned member candidate degrades THAT member to its
  last-good slice — the fleet keeps serving, the other members keep
  swapping.
- **Config homogeneity is loud**: members must share one serving config
  (the fleet is one stacked program; mixing shapes would be a silent
  deployment error, the replica-world rule one level up).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from rcmarl_tpu.config import Config
from rcmarl_tpu.models.mlp import MLPParams, pad_features
from rcmarl_tpu.serve.engine import (
    SERVE_MODES,
    ServeEngine,
    batch_probs,
    serve_keys,
    serve_request_keys,
)
from rcmarl_tpu.serve.swap import CheckpointWatcher


def fleet_stack(blocks: Sequence[MLPParams]) -> MLPParams:
    """F row-stacked actor blocks (each
    :func:`~rcmarl_tpu.serve.engine.stack_actor_rows` output, leading
    agent axis) stacked along a NEW leading fleet axis: leaf shapes
    ``(N, ...) -> (F, N, ...)``, row f = member f. Mismatched member
    shapes fail loudly in the stack — a fleet is one program."""
    if not blocks:
        raise ValueError("fleet_stack needs at least one member block")
    return jax.tree.map(lambda *ls: jnp.stack(ls), *blocks)


def fleet_set_member(fleet: MLPParams, f: int, block: MLPParams) -> MLPParams:
    """A NEW fleet with member ``f``'s slice replaced wholesale by
    ``block`` — the hot-swap primitive: built completely, then the
    caller rebinds its single fleet reference (the CheckpointWatcher
    atomicity contract, per member)."""
    return jax.tree.map(lambda fl, nb: fl.at[f].set(nb), fleet, block)


def _fleet_block(
    cfg: Config,
    fleet: MLPParams,
    obs: jnp.ndarray,
    key: jax.Array,
    route: jnp.ndarray,
    mode: str = "sample",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """ONE compiled launch serving a request batch across F members.

    Args:
      cfg: static config (the compile key, like :func:`serve_block`).
      fleet: the fleet-stacked actor blocks (:func:`fleet_stack`),
        leading axis F.
      obs: (B, N, obs_dim) batched observations, exactly the solo
        layout.
      route: (B,) int32 — request b is served by member ``route[b]``.
        DATA, not structure: a re-route re-dispatches the same
        executable (the retrace-audited contract).
      key: base PRNG key; per-(request, agent) keys derive via
        :func:`serve_request_keys` exactly as solo, so routing to a
        member samples the actions that member would sample solo.
      mode: 'sample' or 'greedy' (static — one program per arm).

    Returns ``(actions, probs)``: (B, N) int32 and (B, N, n_actions) —
    row b is member ``route[b]``'s output, bitwise its solo
    :func:`serve_block` row.
    """
    if mode not in SERVE_MODES:
        raise ValueError(f"mode={mode!r}: expected one of {SERVE_MODES}")
    B, N = obs.shape[0], obs.shape[1]
    x = pad_features(obs, fleet[0][0].shape[-2])
    # the ONE solo serve_block core (engine.batch_probs) vmapped over
    # the fleet axis — the per-member parity pin holds bitwise because
    # there is exactly one implementation to drift
    probs_all = jax.vmap(
        lambda blk: batch_probs(cfg, blk, x)
    )(fleet)  # (F, B, N, n_actions)
    probs = probs_all[route, jnp.arange(B)]  # routing is a gather on DATA
    if mode == "greedy":
        actions = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    else:
        keys = serve_request_keys(key, B, N)
        actions = jax.vmap(jax.vmap(jax.random.categorical))(
            keys, jnp.log(probs)
        ).astype(jnp.int32)
    return actions, probs


#: The jitted fleet serving entry point (registered in
#: ``utils/profiling.py:jit_entry_points`` — retrace/cost audited like
#: every hot path). ``cfg`` and ``mode`` are static; fleet,
#: observations, key, AND the route are data, so re-routes and member
#: hot-swaps re-dispatch the SAME executable.
fleet_block = partial(
    jax.jit, static_argnums=0, static_argnames=("mode",)
)(_fleet_block)


class FleetEngine:
    """Host shell around :func:`fleet_block`: F checkpoints, one
    compiled launch, member-isolated degradation.

    Each member is a full :class:`~rcmarl_tpu.serve.engine.ServeEngine`
    (checksummed load, ``.prev`` fallback, loud replica/non-finite
    rejection) with its own
    :class:`~rcmarl_tpu.serve.swap.CheckpointWatcher`; the engine keeps
    ONE stacked fleet reference built from the members' blocks. A
    member hot-swap rebuilds only that member's slice and rebinds the
    fleet wholesale — a launch before the rebind serves the old fleet,
    one after serves the new, and no launch can ever observe a torn
    member. A REJECTED member candidate (corrupt file, NaN params)
    leaves that member's last-good slice serving: the fleet never
    degrades past the one bad member.
    """

    def __init__(
        self,
        checkpoints: Sequence,
        cfg: Optional[Config] = None,
        mode: str = "sample",
        eval_seed: int = 0,
        serve_impl: str = "auto",
    ) -> None:
        from rcmarl_tpu.ops.pallas_serve import resolve_serve_impl

        if not checkpoints:
            raise ValueError("FleetEngine needs at least one checkpoint")
        if mode not in SERVE_MODES:
            raise ValueError(f"mode={mode!r}: expected one of {SERVE_MODES}")
        #: the resolved serving arm — the fused Pallas fleet program
        #: (:func:`rcmarl_tpu.ops.pallas_serve.fused_fleet_block`) or
        #: the XLA :func:`fleet_block` chain, bitwise interchangeable
        #: (the pinned contract); an engine attribute, not Config state
        self.serve_impl = resolve_serve_impl(serve_impl)
        self.members: List[ServeEngine] = [
            ServeEngine(p, cfg=cfg, mode=mode, eval_seed=eval_seed)
            for p in checkpoints
        ]
        cfg0 = self.members[0].cfg
        for m in self.members[1:]:
            if m.cfg != cfg0:
                raise ValueError(
                    f"fleet members must share ONE serving config: "
                    f"{m.checkpoint_path} was trained under a different "
                    "Config than member 0 — a mixed-shape fleet is a "
                    "deployment error, not a transport fault"
                )
        self.cfg = cfg0
        self.mode = mode
        self.eval_seed = eval_seed
        self.watchers = [CheckpointWatcher(m) for m in self.members]
        self.fleet = fleet_stack([m.block for m in self.members])
        self.counters = {"launches": 0, "actions": 0}

    @property
    def n_members(self) -> int:
        return len(self.members)

    def round_robin_route(self, B: int) -> jnp.ndarray:
        """The default (B,) route: request b -> member b % F."""
        return jnp.arange(B, dtype=jnp.int32) % self.n_members

    def serve(
        self,
        obs: jnp.ndarray,
        route: Optional[jnp.ndarray] = None,
        key: Optional[jax.Array] = None,
        step: Optional[int] = None,
        mode: Optional[str] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Serve one (B, N, obs_dim) batch through the fleet ->
        (actions, probs). ``route=None`` round-robins; ``key=None``
        uses the deterministic serve stream exactly like the solo
        engine."""
        if route is None:
            route = self.round_robin_route(obs.shape[0])
        if key is None:
            key = serve_keys(
                self.eval_seed,
                self.counters["launches"] if step is None else step,
            )
        if self.serve_impl == "xla":
            out = fleet_block(
                self.cfg, self.fleet, obs, key, route, mode=mode or self.mode
            )
        else:
            from rcmarl_tpu.ops.pallas_serve import fused_fleet_block

            out = fused_fleet_block(
                self.cfg, self.fleet, obs, key, route,
                mode=mode or self.mode,
                interpret=(self.serve_impl == "pallas_interpret"),
            )
        self.counters["launches"] += 1
        self.counters["actions"] += int(obs.shape[0]) * int(obs.shape[1])
        return out

    # -- member hot-swap ---------------------------------------------------

    def poll(self, force: bool = False) -> List[int]:
        """Poll every member's checkpoint; returns the member indices
        whose swap APPLIED. Rejected candidates degrade only their own
        member (counters on that member's engine); applied swaps
        rebuild the affected slices and rebind the fleet wholesale."""
        swapped = [
            f
            for f, w in enumerate(self.watchers)
            if w.poll(force=force)
        ]
        if swapped:
            fleet = self.fleet
            for f in swapped:
                fleet = fleet_set_member(fleet, f, self.members[f].block)
            self.fleet = fleet  # single rebind: no torn fleet mid-loop
        return swapped

    # -- observability -----------------------------------------------------

    def summary(self) -> dict:
        """Fleet counters + the per-member degradation ledgers."""
        return {
            **self.counters,
            "members": [m.summary() for m in self.members],
            "degraded_members": [
                f for f, m in enumerate(self.members) if m.degraded
            ],
        }

    def summary_line(self) -> str:
        """One line the CI cell greps: fleet traffic plus which members
        are serving last-good (member-isolated degradation)."""
        c = self.counters
        per = ", ".join(
            f"m{f}:{'last-good' if m.degraded else 'fresh'}"
            f"({m.counters['swaps']}s/{m.counters['rejects']}r)"
            for f, m in enumerate(self.members)
        )
        return (
            f"fleet: {self.n_members} members, {c['launches']} launches, "
            f"{c['actions']} actions [{per}]"
        )
