"""SLO-driven autoscaling — the capacity control loop over the load
harness.

PR 14 measured the latency knee and PR 15 bounded the overload tail
with deadline shedding, but the fleet itself never RESIZED: a static
deployment either wastes capacity at the trough or saturates at the
peak of a load swing. This module closes the loop in the deterministic
tradition of :mod:`rcmarl_tpu.serve.load`: a pure controller
(:class:`SLOController`) reads one load window's report and decides
the next window's fleet scale, and :func:`autoscale_replay` replays a
SEEDED arrival plan through windowed
:func:`~rcmarl_tpu.serve.load._simulate_queue` runs under that
controller — so every scale-up/scale-down decision is unit-testable,
chaos-sweepable (the ``serve_overload@autoscale`` cells), and
replayable bit-for-bit from ``(seed, plan, controller)`` alone. No
wall clock, no RNG, no thresholds hidden in the serving path.

Mechanics:

- **Scale = fleet members.** ``scale`` independent micro-batching
  queues (one per member, each with its own compiled-launch service
  model) split each window's arrivals round-robin — the fleet axis of
  :mod:`rcmarl_tpu.serve.fleet`, simulated. Capacity scales linearly;
  the window report merges the members' RAW latency arrays, so the
  windowed percentiles are exact, not percentile-of-percentiles.
- **Resizes happen ONLY at window boundaries.** Every batch launched
  inside a window runs to completion inside that window's simulation,
  and each member's server-free time carries across windows
  (:func:`~rcmarl_tpu.serve.load._simulate_queue`'s ``t0``), so a
  resize can never tear a batch mid-flight — the
  never-resizes-mid-batch contract is structural, and
  tests/test_autoscale.py pins it.
- **Control signals lead the SLO.** Scale-up fires on a p99 breach or
  a shed (multiplicative — the fleet was already late), but ALSO on
  the DEMAND early signal: offered load x measured service time over
  the fleet's batch capacity (``rate * service_mean / (scale *
  max_batch)``) — the busy fraction the window would need with FULL
  batches. Demand is the honest capacity signal where raw utilization
  is not: a lightly loaded member still burns a launch every
  ``max_wait`` on a small fill, so measured busy-time floors near
  ``service / max_wait`` at ANY scale, while demand falls linearly
  with scale. Under a ramped swing the demand trigger grows capacity
  ahead of the breach, which is how the replay holds a p99 SLO across
  a 10x offered-load swing that saturates the static fleet (the
  committed ``simulation_results/autoscale_slo.json`` evidence).
  Scale-down waits out ``hysteresis`` consecutive low-demand windows
  and only steps when the SMALLER fleet's projected demand stays under
  the low-water mark — no flapping at a capacity edge.

The summary line (:func:`summary_line`) is what the CI cell greps:
``autoscale: SLO held ...`` only when EVERY window met the p99 target
with zero sheds.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from rcmarl_tpu.serve.load import _simulate_queue

#: Controller defaults: the high/low DEMAND water marks (offered load x
#: service time over ``scale * max_batch`` — module docstring) and the
#: scale-down hysteresis (consecutive low-demand windows before one
#: step down). Demand exceeds 1.0 in overload — itself a scale-up
#: signal.
HIGH_UTILIZATION = 0.60
LOW_UTILIZATION = 0.35
HYSTERESIS = 3


class SLOController:
    """The pure capacity controller: one :meth:`decide` per load
    window, deterministic in the window report alone.

    Args:
      slo_p99: the latency objective (seconds) the fleet must hold.
      min_scale / max_scale: the fleet-size envelope.
      high_utilization / low_utilization: the demand water marks — the
        scale-up early signal and the scale-down eligibility mark
        (module docstring: demand, not raw busy-time, is the signal
        that scales with fleet size).
      hysteresis: consecutive healthy low-demand windows required
        before ONE step down (the anti-flap guard).
    """

    def __init__(
        self,
        slo_p99: float,
        min_scale: int = 1,
        max_scale: int = 16,
        high_utilization: float = HIGH_UTILIZATION,
        low_utilization: float = LOW_UTILIZATION,
        hysteresis: int = HYSTERESIS,
    ) -> None:
        if not slo_p99 > 0.0:
            raise ValueError(f"slo_p99={slo_p99} must be > 0")
        if not 1 <= min_scale <= max_scale:
            raise ValueError(
                f"need 1 <= min_scale <= max_scale "
                f"(got {min_scale}, {max_scale})"
            )
        if not 0.0 < low_utilization < high_utilization:
            raise ValueError(
                f"need 0 < low_utilization < high_utilization "
                f"(got {low_utilization}, {high_utilization})"
            )
        if hysteresis < 1:
            raise ValueError(f"hysteresis={hysteresis} must be >= 1")
        self.slo_p99 = float(slo_p99)
        self.min_scale = int(min_scale)
        self.max_scale = int(max_scale)
        self.high_utilization = float(high_utilization)
        self.low_utilization = float(low_utilization)
        self.hysteresis = int(hysteresis)
        self.scale = self.min_scale
        self._healthy = 0

    def decide(self, report: Dict[str, float]) -> Optional[str]:
        """Consume one window report (keys ``p99``, ``demand``,
        ``shed``); mutates :attr:`scale` for the NEXT window. Returns
        the resize reason (``'p99-breach'``, ``'shed'``,
        ``'high-demand'``, ``'scale-down'``) or None when the scale
        holds.

        Up moves are multiplicative on a breach (the fleet was already
        late — recover in one step) and PROPORTIONAL on the demand
        early-signal: the next scale is sized so the measured demand
        would land back at the low-water mark (a ramp that doubles
        offered load in one window gets a doubled fleet, not one more
        member); down moves are single steps gated by hysteresis AND by
        the smaller fleet's projected demand staying under the
        LOW-water mark."""
        p99 = float(report["p99"])
        demand = float(report["demand"])
        shed = int(report.get("shed", 0))
        if shed > 0 or p99 > self.slo_p99:
            self._healthy = 0
            if self.scale < self.max_scale:
                self.scale = min(self.max_scale, self.scale * 2)
                return "shed" if shed > 0 else "p99-breach"
            return None
        if demand >= self.high_utilization:
            self._healthy = 0
            if self.scale < self.max_scale:
                needed = math.ceil(
                    demand * self.scale / self.low_utilization
                )
                self.scale = min(
                    self.max_scale, max(self.scale + 1, needed)
                )
                return "high-demand"
            return None
        if self.scale > self.min_scale:
            projected = demand * self.scale / (self.scale - 1)
            if projected < self.low_utilization:
                self._healthy += 1
                if self._healthy >= self.hysteresis:
                    self._healthy = 0
                    self.scale -= 1
                    return "scale-down"
                return None
        self._healthy = 0
        return None


def swing_arrivals(
    seed: int,
    base_rate: float,
    seg_requests: int,
    factors: Sequence[float] = (1, 2, 4, 8, 10, 10, 8, 4, 2, 1),
) -> np.ndarray:
    """A deterministic offered-load SWING: consecutive Poisson segments
    of ``seg_requests`` requests each at ``factor * base_rate``, glued
    end to end in absolute simulated seconds. The default profile ramps
    1x -> 10x -> 1x — the evidence plan where the autoscaled fleet must
    hold the SLO while the static fleet saturates at the peak.
    Deterministic in ``(seed, base_rate, seg_requests, factors)``."""
    from rcmarl_tpu.serve.load import poisson_arrivals

    if seg_requests < 1:
        raise ValueError(f"seg_requests={seg_requests} must be >= 1")
    out: List[np.ndarray] = []
    t0 = 0.0
    for k, f in enumerate(factors):
        seg = poisson_arrivals(seed + k, seg_requests, f * base_rate)
        out.append(t0 + seg)
        t0 += float(seg[-1])
    return np.concatenate(out)


def autoscale_replay(
    service_fn: Callable[[int], float],
    arrivals: np.ndarray,
    controller: Optional[SLOController],
    window: float,
    max_batch: int,
    max_wait: float,
    shed_after: float = math.inf,
    static_scale: int = 1,
    slo_p99: Optional[float] = None,
) -> Dict[str, object]:
    """Replay one seeded arrival plan through the windowed fleet under
    the controller — the unit the tests, the chaos ``@autoscale`` arm,
    and the committed SLO evidence all share.

    Args:
      service_fn: seconds per launch of ONE member's padded
        ``max_batch`` program (an injected deterministic model in the
        unit/chaos cells; a measured
        :func:`~rcmarl_tpu.serve.load.serve_service_fn` closure for the
        evidence rows — every simulated member bills the same solo
        launch cost, the fleet-axis reading).
      arrivals: absolute arrival times (seeded plan).
      controller: the :class:`SLOController` — or None for the STATIC
        baseline fleet at ``static_scale`` (the comparison arm).
      window: the decision epoch in simulated seconds; resizes apply
        only at window boundaries (module docstring).
      max_batch / max_wait / shed_after: the per-member queue knobs
        (:func:`~rcmarl_tpu.serve.load.run_load` semantics).
      slo_p99: the objective for the per-window ``slo_ok`` verdict;
        defaults to the controller's.

    Returns ``{"slo_p99", "windows": [...], "resizes": [...],
    "slo_held", "requests", "served", "shed", "max_scale_used",
    "final_scale"}`` — windows carry ``scale``, exact merged
    ``p50/p95/p99``, ``utilization`` (busy over ``scale * window``),
    shed counts, and ``slo_ok`` (p99 under the SLO AND shed-free).
    """
    arrivals = np.asarray(arrivals, dtype=np.float64)
    if arrivals.size == 0:
        raise ValueError("autoscale_replay needs at least one arrival")
    if not window > 0.0:
        raise ValueError(f"window={window} must be > 0")
    slo = (
        float(slo_p99)
        if slo_p99 is not None
        else (controller.slo_p99 if controller is not None else math.inf)
    )
    scale = controller.scale if controller is not None else int(static_scale)
    if scale < 1:
        raise ValueError(f"static_scale={static_scale} must be >= 1")
    t_lo = float(arrivals[0])
    # each member's server-free time, carried across windows so a
    # window that ran long keeps its member busy into the next one
    free = [t_lo] * scale
    windows: List[Dict[str, float]] = []
    resizes: List[Dict[str, object]] = []
    shed_total = 0
    served_total = 0
    n_win = int(math.ceil((float(arrivals[-1]) - t_lo) / window)) or 1
    for w in range(n_win):
        w_lo = t_lo + w * window
        w_hi = w_lo + window
        sel = (arrivals >= w_lo) & (
            arrivals < w_hi if w + 1 < n_win else arrivals <= w_hi
        )
        win_arr = arrivals[sel]
        if win_arr.size == 0:
            continue
        lats: List[np.ndarray] = []
        services: List[float] = []
        busy = 0.0
        shed = 0
        for m in range(scale):
            member_arr = win_arr[m::scale]  # round-robin split
            if member_arr.size == 0:
                continue
            raw = _simulate_queue(
                service_fn, member_arr, max_batch, max_wait, shed_after,
                t0=max(free[m], w_lo),
            )
            free[m] = raw["t_end"]
            lats.append(raw["lat"])
            services.extend(raw["services"])
            busy += raw["busy"]
            shed += raw["shed"]
        lat = np.concatenate(lats)
        served = lat[~np.isnan(lat)]
        shed_total += shed
        served_total += int(served.size)
        if served.size:
            p50, p95, p99 = np.percentile(served, [50.0, 95.0, 99.0])
        else:
            p50 = p95 = p99 = math.inf  # every request shed: a breach
        offered = win_arr.size / window
        service_mean = float(np.mean(services)) if services else 0.0
        row = {
            "window": w,
            "t0": round(w_lo - t_lo, 6),
            "requests": int(win_arr.size),
            "scale": scale,
            "offered_load": float(offered),
            "service_mean": service_mean,
            "p50": float(p50),
            "p95": float(p95),
            "p99": float(p99),
            "utilization": float(busy / (scale * window)),
            "demand": float(
                offered * service_mean / (scale * max_batch)
            ),
            "shed": int(shed),
            "shed_fraction": float(shed / win_arr.size),
            "slo_ok": bool(p99 <= slo and shed == 0),
        }
        windows.append(row)
        if controller is not None:
            prev = controller.scale
            reason = controller.decide(row)
            if controller.scale != prev:
                resizes.append(
                    {
                        "after_window": w,
                        "from": prev,
                        "to": controller.scale,
                        "reason": reason,
                    }
                )
                if controller.scale > prev:
                    # new members come up free at the NEXT boundary
                    free.extend([w_hi] * (controller.scale - prev))
                else:
                    free = free[: controller.scale]
                scale = controller.scale
    return {
        "slo_p99": slo,
        "windows": windows,
        "resizes": resizes,
        "slo_held": bool(windows) and all(r["slo_ok"] for r in windows),
        "requests": int(arrivals.size),
        "served": served_total,
        "shed": shed_total,
        "max_scale_used": max(r["scale"] for r in windows) if windows else scale,
        "final_scale": scale,
    }


def summary_line(result: Dict[str, object]) -> str:
    """The one grep-able line (the CI cell's contract): ``SLO held``
    appears ONLY when every window met the p99 target shed-free."""
    wins = result["windows"]
    n_bad = sum(1 for r in wins if not r["slo_ok"])
    peak = max((r["p99"] for r in wins), default=float("nan"))
    span = (
        f"scale {wins[0]['scale']}->{result['max_scale_used']}"
        if wins
        else "no windows"
    )
    if result["slo_held"]:
        return (
            f"autoscale: SLO held (p99 <= {result['slo_p99'] * 1e3:.3g}ms) "
            f"across {len(wins)} windows, {span}, "
            f"{result['shed']} shed, peak p99 {peak * 1e3:.3g}ms"
        )
    return (
        f"autoscale: SLO violated in {n_bad}/{len(wins)} windows "
        f"(p99 target {result['slo_p99'] * 1e3:.3g}ms, peak p99 "
        f"{peak * 1e3:.3g}ms), {span}, {result['shed']} shed"
    )
