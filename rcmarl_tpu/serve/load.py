"""Request-arrival latency harness — serving under load, measured.

The serving engine's throughput rows (``bench.py --serve``) answer "how
many actions/sec can one compiled launch sustain at a fixed batch?" —
the offline half of the TF-Agents batched-inference tradition
(PAPERS.md 1709.02878). The production question is different: requests
ARRIVE, a micro-batching queue in front of :func:`serve_block` trades
latency for batch efficiency, and the benchmark is p50/p99 latency vs
offered load up to the knee where batching saturates. This module is
that harness:

- :func:`poisson_arrivals` / :func:`bursty_arrivals` — DETERMINISTIC
  arrival plans (seeded ``numpy`` generators, host-side: no wall-clock
  and no RNG anywhere near jitted code), in absolute simulated seconds.
  Replaying the same ``(seed, n, rate)`` replays the exact plan.
- :func:`run_load` — the single-server micro-batching queue over one
  arrival plan: a batch closes when it FILLS (``max_batch`` requests)
  or when the oldest waiting request has waited ``max_wait`` simulated
  seconds, never before the server is free; every launch is the PADDED
  ``max_batch`` shape whatever the fill, so the compile-once contract
  holds across every load point (the ``lint --retrace`` fleet case
  drives exactly this shape discipline). Service time per launch comes
  from ``service_fn(fill)`` — a REAL measured launch on the serving
  path, or an injected model in the unit tests — and the report carries
  the latency percentiles, queue depth, fill, and utilization.
- **Deadline shedding** (``shed_after``): past the saturation knee a
  shed-free queue's latency is unbounded backlog — every request is
  eventually served, arbitrarily late. With ``shed_after`` set, a
  request whose queue wait already exceeds the deadline when the server
  frees is SHED (dropped unserved, counted) instead of dragging the
  percentiles into the backlog: a served request's latency is then
  bounded by ``shed_after + max_wait + service``, so p99 stays pinned
  near the knee-point p99 at ANY offered load, and the cost is an
  explicit ``shed_fraction`` on the row instead of a hidden latency
  cliff (the graceful-degradation trade the chaos campaign's overload
  cells gate, ``rcmarl_tpu.chaos``). ``shed_after=inf`` (the default)
  is bitwise the historical shed-free queue; every report row carries
  ``shed``/``shed_fraction`` either way.
- :func:`sweep_load` / :func:`saturation_knee` — the offered-load sweep
  and the knee extraction: the highest swept load whose p99 stays
  inside ``knee_factor`` x the lightest load's p99 with the server
  still under-utilized; the first load past it is saturated (arrivals
  outpace batch capacity and latency is backlog, not service).
- :func:`serve_service_fn` / :func:`fleet_service_fn` — the real
  service models: one wall-clock-timed dispatch of the compiled
  :func:`~rcmarl_tpu.serve.engine.serve_block` /
  :func:`~rcmarl_tpu.serve.fleet.fleet_block` program at the padded
  ``max_batch`` shape (compile happens once, outside the timed
  launches, like every bench harness here).

The clock is SIMULATED (arrivals are a plan, not a socket), the service
times are MEASURED — so a row is an honest hybrid: deterministic,
replayable queueing over real launch costs on this host. Rows land in
``BENCH_SERVE.jsonl`` via ``python bench.py --serve_load`` with the
``cost_fingerprint`` + ``headline`` discipline every serving row
carries.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

#: Default saturation criterion: a load point is past the knee when its
#: p99 exceeds ``KNEE_FACTOR`` x the lightest swept load's p99 (latency
#: has become backlog) or the server is effectively always busy.
KNEE_FACTOR = 4.0
KNEE_UTILIZATION = 0.98


# --------------------------------------------------------------------------
# Deterministic arrival plans
# --------------------------------------------------------------------------


def poisson_arrivals(seed: int, n: int, rate: float) -> np.ndarray:
    """``n`` absolute arrival times (simulated seconds) of a Poisson
    stream at ``rate`` requests/s — exponential inter-arrival gaps from
    ``default_rng(seed)``, cumulatively summed. Deterministic in
    ``(seed, n, rate)``."""
    if n < 1 or rate <= 0.0:
        raise ValueError(f"need n >= 1 and rate > 0 (got n={n}, rate={rate})")
    rng = np.random.default_rng(seed)
    return np.cumsum(rng.exponential(1.0 / rate, size=n))


def bursty_arrivals(
    seed: int, n: int, rate: float, burst: int = 8
) -> np.ndarray:
    """``n`` arrival times of a BURSTY stream at long-run offered load
    ``rate``: bursts of ``burst`` simultaneous requests, burst starts
    Poisson at ``rate / burst`` bursts/s — the same mean load as
    :func:`poisson_arrivals` concentrated into spikes (the adversarial
    arrival pattern for a micro-batching queue). Deterministic in
    ``(seed, n, rate, burst)``."""
    if burst < 1:
        raise ValueError(f"burst={burst} must be >= 1")
    n_bursts = math.ceil(n / burst)
    starts = poisson_arrivals(seed, n_bursts, rate / burst)
    return np.repeat(starts, burst)[:n]


# --------------------------------------------------------------------------
# The micro-batching queue (simulated clock, measured service)
# --------------------------------------------------------------------------


def run_load(
    service_fn: Callable[[int], float],
    arrivals: np.ndarray,
    max_batch: int,
    max_wait: float,
    shed_after: float = math.inf,
) -> Dict[str, float]:
    """Run one arrival plan through the single-server micro-batching
    queue; returns the latency/queue report.

    Close rule: with the server free at ``t`` and request ``i`` the
    oldest waiting, the batch closes at
    ``max(t, min(fill_time, arrivals[i] + max_wait))`` — when it fills
    to ``max_batch``, or when the oldest request's ``max_wait`` budget
    expires, whichever first, but never before the server frees (a
    backlogged queue launches immediately). ``service_fn(fill)`` is the
    seconds one launch of the padded ``max_batch`` program takes with
    ``fill`` real requests; request latency = completion - arrival.

    Shed rule (``shed_after < inf``): each time the server frees,
    waiting requests whose queue wait already exceeds ``shed_after``
    are dropped head-of-line WITHOUT service (counted, never billed a
    latency). Every SERVED request's queue wait at batch close is then
    at most ``shed_after + max_wait``, so latency stays bounded by
    ``shed_after + max_wait + service`` at any offered load — the
    backlog turns into an explicit shed fraction instead of an
    unbounded p99. ``shed_after=inf`` (default) is bitwise the
    historical shed-free queue.

    Report keys: ``p50/p95/p99`` latency (seconds, over SERVED
    requests), ``mean_latency``, ``launches``, ``fill_mean`` (real
    requests per launch), ``queue_depth_mean``/``queue_depth_max``
    (waiting requests at each close, incl. beyond ``max_batch``),
    ``utilization`` (service busy fraction of the makespan),
    ``service_mean`` (seconds/launch), ``served``/``shed``/
    ``shed_fraction`` (the deadline-shedding ledger — present on EVERY
    row, 0.0 when shedding is off or never fires).
    """
    raw = _simulate_queue(service_fn, arrivals, max_batch, max_wait, shed_after)
    arrivals = np.asarray(arrivals, dtype=np.float64)
    lat, fills, depths, services = (
        raw["lat"], raw["fills"], raw["depths"], raw["services"]
    )
    n, shed, t, busy = arrivals.shape[0], raw["shed"], raw["t_end"], raw["busy"]
    served = lat[~np.isnan(lat)]
    if served.size == 0:
        raise ValueError(
            f"run_load shed every request (shed_after={shed_after}): the "
            "deadline is shorter than one service time — no latency to "
            "report"
        )
    makespan = t - float(arrivals[0])
    p50, p95, p99 = np.percentile(served, [50.0, 95.0, 99.0])
    return {
        "requests": int(n),
        "p50": float(p50),
        "p95": float(p95),
        "p99": float(p99),
        "mean_latency": float(served.mean()),
        "launches": len(fills),
        "fill_mean": float(np.mean(fills)),
        "queue_depth_mean": float(np.mean(depths)),
        "queue_depth_max": int(np.max(depths)),
        "utilization": float(busy / makespan) if makespan > 0 else 1.0,
        "service_mean": float(np.mean(services)),
        "served": int(served.size),
        "shed": int(shed),
        "shed_fraction": float(shed / n),
    }


def _simulate_queue(
    service_fn: Callable[[int], float],
    arrivals: np.ndarray,
    max_batch: int,
    max_wait: float,
    shed_after: float = math.inf,
    t0: float = 0.0,
) -> Dict[str, object]:
    """The raw queue simulation behind :func:`run_load` — identical
    close/shed rules, but returning the UNREDUCED per-request latency
    array plus the busy/fill/depth ledgers, and starting with the
    server free at ``t0`` (so a windowed replay can carry a server's
    free time across window boundaries). :func:`run_load` is exactly
    this with ``t0=0`` reduced to the percentile report; the autoscale
    replay (:mod:`rcmarl_tpu.serve.autoscale`) merges the raw arrays
    across fleet members for exact merged percentiles."""
    if max_batch < 1:
        raise ValueError(f"max_batch={max_batch} must be >= 1")
    if max_wait < 0.0:
        raise ValueError(f"max_wait={max_wait} must be >= 0")
    if not shed_after > 0.0:
        raise ValueError(f"shed_after={shed_after} must be > 0")
    arrivals = np.asarray(arrivals, dtype=np.float64)
    n = arrivals.shape[0]
    lat = np.full(n, np.nan, dtype=np.float64)
    i = 0
    t = float(t0)
    busy = 0.0
    shed = 0
    fills: List[int] = []
    depths: List[int] = []
    services: List[float] = []
    while i < n:
        if math.isfinite(shed_after):
            # head-of-line deadline drop at server-free time: a request
            # that has already waited past its deadline is hopeless —
            # serving it would only push every later request further
            # past the knee
            while i < n and arrivals[i] <= t and t - arrivals[i] > shed_after:
                shed += 1
                i += 1
            if i >= n:
                break
        open_t = max(t, float(arrivals[i]))
        fill_t = (
            float(arrivals[i + max_batch - 1])
            if i + max_batch <= n
            else math.inf
        )
        close_t = max(open_t, min(fill_t, float(arrivals[i]) + max_wait))
        j = i
        while j < n and j - i < max_batch and arrivals[j] <= close_t:
            j += 1
        fill = j - i
        depths.append(
            int(np.searchsorted(arrivals, close_t, side="right")) - i
        )
        s = float(service_fn(fill))
        if not (s > 0.0 and math.isfinite(s)):
            raise ValueError(f"service_fn({fill}) returned {s}")
        lat[i:j] = (close_t + s) - arrivals[i:j]
        busy += s
        services.append(s)
        fills.append(fill)
        t = close_t + s
        i = j
    return {
        "lat": lat,
        "busy": busy,
        "fills": fills,
        "depths": depths,
        "services": services,
        "shed": shed,
        "t_end": t,
    }


def sweep_load(
    service_fn: Callable[[int], float],
    loads: Sequence[float],
    n_requests: int,
    max_batch: int,
    max_wait: float,
    seed: int = 0,
    arrival: str = "poisson",
    burst: int = 8,
    shed_after: float = math.inf,
) -> List[Dict[str, float]]:
    """One :func:`run_load` report per offered load (requests/s), each
    tagged with its ``offered_load`` and arrival process — the
    latency-vs-load curve ``bench.py --serve_load`` emits. The SAME
    seed namespaces every point, so the sweep is replayable end to
    end; ``shed_after`` applies the deadline-shedding rule at every
    point (the shed fraction rides each row)."""
    if arrival not in ("poisson", "bursty"):
        raise ValueError(
            f"arrival={arrival!r}: expected 'poisson' or 'bursty'"
        )
    points = []
    for load in loads:
        arr = (
            poisson_arrivals(seed, n_requests, load)
            if arrival == "poisson"
            else bursty_arrivals(seed, n_requests, load, burst)
        )
        rep = run_load(service_fn, arr, max_batch, max_wait, shed_after)
        rep["offered_load"] = float(load)
        rep["arrival"] = arrival
        points.append(rep)
    return points


def saturation_knee(
    points: Sequence[Dict[str, float]],
    factor: float = KNEE_FACTOR,
    max_utilization: float = KNEE_UTILIZATION,
) -> Optional[float]:
    """The saturation knee of a :func:`sweep_load` curve: the highest
    ``offered_load`` still UNDER the knee — p99 within ``factor`` x the
    lightest load's p99 and utilization below ``max_utilization``.
    Returns None when even the lightest point is saturated (sweep
    started past the knee)."""
    if not points:
        return None
    ordered = sorted(points, key=lambda p: p["offered_load"])
    base_p99 = ordered[0]["p99"]
    knee = None
    for p in ordered:
        if p["p99"] > factor * base_p99 or p["utilization"] >= max_utilization:
            break
        knee = p["offered_load"]
    return knee


# --------------------------------------------------------------------------
# Real service models (measured launches at the padded shape)
# --------------------------------------------------------------------------


def _pad_fill(obs_pool, fill: int):
    """The padded launch input for ``fill`` real requests: the pool IS
    the ``max_batch`` shape — rows past ``fill`` are padding the
    latency accounting ignores (the queue bills only real requests),
    so the launch shape never changes with the fill."""
    del fill  # the launch shape is fixed; fill only feeds the accounting
    return obs_pool


def serve_service_fn(
    cfg,
    block,
    max_batch: int,
    mode: str = "sample",
    seed: int = 0,
    serve_impl: str = "xla",
) -> Callable[[int], float]:
    """A measured service model over the compiled serving program at
    the padded ``(max_batch, N, obs_dim)`` shape: compile + warm once
    here, then each call is ONE wall-clock-timed launch (device-fetch
    barrier). ``serve_impl`` selects the arm the launches are billed on
    — the XLA :func:`~rcmarl_tpu.serve.engine.serve_block` chain or the
    fused Pallas program
    (:func:`~rcmarl_tpu.ops.pallas_serve.fused_serve_block`; bitwise
    the same actions, so the queue curves differ only in service time).
    The returned closure is what :func:`run_load` bills batches with."""
    import jax

    from rcmarl_tpu.ops.pallas_serve import fused_serve_block, resolve_serve_impl
    from rcmarl_tpu.serve.engine import serve_block, serve_keys

    impl = resolve_serve_impl(serve_impl)

    def launch(obs, key):
        if impl == "xla":
            return serve_block(cfg, block, obs, key, mode=mode)
        return fused_serve_block(
            cfg, block, obs, key, mode=mode,
            interpret=(impl == "pallas_interpret"),
        )

    obs = jax.random.normal(
        jax.random.PRNGKey(seed), (max_batch, cfg.n_agents, cfg.obs_dim)
    )
    key = serve_keys(seed, 0)
    # compile + one warm execution OUTSIDE the billed launches
    jax.device_get(launch(obs, key)[0])
    counter = {"launch": 0}

    def service(fill: int) -> float:
        counter["launch"] += 1
        k = serve_keys(seed, counter["launch"])
        t0 = time.perf_counter()
        actions, _ = launch(_pad_fill(obs, fill), k)
        jax.device_get(actions)
        return time.perf_counter() - t0

    return service


def fleet_service_fn(
    cfg,
    fleet,
    n_members: int,
    max_batch: int,
    mode: str = "sample",
    seed: int = 0,
    serve_impl: str = "xla",
) -> Callable[[int], float]:
    """The fleet twin of :func:`serve_service_fn`: one timed launch of
    the compiled :func:`~rcmarl_tpu.serve.fleet.fleet_block` program
    (or its fused Pallas twin
    :func:`~rcmarl_tpu.ops.pallas_serve.fused_fleet_block`, per
    ``serve_impl``) at the padded shape, with a round-robin route
    (DATA — the route could change per launch without a recompile; the
    harness keeps it fixed so the billed cost is the steady-state
    one)."""
    import jax
    import jax.numpy as jnp

    from rcmarl_tpu.ops.pallas_serve import fused_fleet_block, resolve_serve_impl
    from rcmarl_tpu.serve.engine import serve_keys
    from rcmarl_tpu.serve.fleet import fleet_block

    impl = resolve_serve_impl(serve_impl)

    def launch(obs, key, route):
        if impl == "xla":
            return fleet_block(cfg, fleet, obs, key, route, mode=mode)
        return fused_fleet_block(
            cfg, fleet, obs, key, route, mode=mode,
            interpret=(impl == "pallas_interpret"),
        )

    obs = jax.random.normal(
        jax.random.PRNGKey(seed), (max_batch, cfg.n_agents, cfg.obs_dim)
    )
    route = jnp.arange(max_batch, dtype=jnp.int32) % n_members
    key = serve_keys(seed, 0)
    jax.device_get(launch(obs, key, route)[0])
    counter = {"launch": 0}

    def service(fill: int) -> float:
        counter["launch"] += 1
        k = serve_keys(seed, counter["launch"])
        t0 = time.perf_counter()
        actions, _ = launch(_pad_fill(obs, fill), k, route)
        jax.device_get(actions)
        return time.perf_counter() - t0

    return service
