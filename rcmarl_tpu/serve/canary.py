"""Canary-gated deployment — "bad policy" joins "corrupt file".

The reject/last-good machinery guards two fault classes already: a bad
FILE (checksum chain, ``.prev`` fallback) and a poisoned TREE
(``params_finite``). Neither catches the production failure that
actually ships: a checksum-valid, fully finite checkpoint whose POLICY
regressed — a stale publish, a diverged learner, a bad hyperparameter
push. This module closes the learner → publish → canary → accept/reject
loop:

- :class:`CanaryGate` — the decision: a candidate's FROZEN-policy
  return (:func:`~rcmarl_tpu.serve.engine.eval_block`, deterministic
  eval stream — no exploration, no updates) must stay within a
  configurable band of the serving INCUMBENT's return. Below the floor
  (or non-finite): REJECTED, the incumbent keeps serving. At or above:
  promoted, and the candidate's return becomes the new incumbent
  reference. Counters + the last decision ride the serve rows.
- :class:`CanaryWatcher` — the deployment loop on files: the
  :class:`~rcmarl_tpu.serve.swap.CheckpointWatcher` discipline with the
  gate spliced between candidate validation and the atomic swap — a
  published checkpoint that fails the canary never reaches the engine.
- ``PolicyPublisher(..., canary=gate.admit)`` — the same gate bound to
  the in-memory publish chain (:mod:`rcmarl_tpu.pipeline.publish`), so
  a pipelined learner's degraded candidate never reaches the acting
  tier either.

The committed experiment (``scripts/canary_experiment.py`` →
``simulation_results/canary_gate.json``, QUALITY.md "Canary-gated
deployment") drives a healthy publish to promotion and a
poisoned/stale/band-violating publish to rejection through this exact
code.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from rcmarl_tpu.serve.swap import CheckpointWatcher


class CanaryGate:
    """Frozen-policy return gate over the evaluate program.

    ``band`` is relative: a candidate is rejected when its mean frozen
    return falls below ``incumbent - band * |incumbent|`` (the
    QUALITY.md tolerance recipe — 0.05 is "within 5% of the serving
    policy's own quality"). ``blocks`` eval blocks of ``n_ep_fixed``
    episodes each are averaged per measurement; the eval stream is
    deterministic in ``(eval_seed, block)``, so the same candidate
    always measures the same return (a gate decision is replayable).

    ``counters``: ``evals`` (gate measurements), ``accepts``,
    ``rejects``; ``last`` holds the most recent decision record
    (candidate/incumbent returns, floor, reason).
    """

    def __init__(
        self,
        cfg,
        desired,
        initial,
        band: float = 0.05,
        blocks: int = 1,
        eval_seed: int = 0,
    ) -> None:
        if band < 0.0:
            raise ValueError(f"band={band} must be >= 0")
        if blocks < 1:
            raise ValueError(f"blocks={blocks} must be >= 1")
        self.cfg = cfg
        self.desired = desired
        self.initial = initial
        self.band = float(band)
        self.blocks = int(blocks)
        self.eval_seed = int(eval_seed)
        self.incumbent_return: Optional[float] = None
        self.counters = {"evals": 0, "accepts": 0, "rejects": 0}
        self.last: Optional[dict] = None

    # -- measurement -------------------------------------------------------

    def frozen_return(self, params) -> float:
        """Mean team return of ``params`` under the frozen-policy eval
        program: ``blocks`` launches of
        :func:`~rcmarl_tpu.serve.engine.eval_block` on the
        deterministic ``fold_in(PRNGKey(eval_seed), block)`` stream."""
        import jax

        from rcmarl_tpu.serve.engine import eval_block

        key = jax.random.PRNGKey(self.eval_seed)
        vals = []
        for b in range(self.blocks):
            metrics, _ = eval_block(
                self.cfg,
                params,
                self.desired,
                jax.random.fold_in(key, b),
                self.initial,
            )
            vals.append(np.asarray(metrics.true_team_returns))
        return float(np.mean(np.concatenate(vals)))

    def set_incumbent(self, params) -> float:
        """Measure ``params`` and pin it as the serving incumbent the
        next candidates are judged against; returns its frozen
        return."""
        self.incumbent_return = self.frozen_return(params)
        return self.incumbent_return

    # -- the decision ------------------------------------------------------

    def floor(self) -> float:
        """The acceptance floor: ``incumbent - band * |incumbent|``."""
        if self.incumbent_return is None:
            raise RuntimeError(
                "canary gate has no incumbent; call set_incumbent() "
                "with the serving policy's params first"
            )
        return self.incumbent_return - self.band * abs(self.incumbent_return)

    def admit(self, params) -> bool:
        """Gate one candidate: measure its frozen return against the
        incumbent's floor. Accept -> the candidate's return becomes the
        new incumbent reference (it is about to serve); reject -> the
        incumbent reference is untouched (it keeps serving). Non-finite
        params are rejected WITHOUT paying an eval (the shared
        publish-candidate guard runs first)."""
        from rcmarl_tpu.faults import params_finite

        floor = self.floor()
        if not params_finite(params):
            # poisoned but maybe checksum-valid: the file guards can
            # miss it on the in-memory publish chain — reject before
            # the eval could propagate NaNs into a return
            self.counters["rejects"] += 1
            self.last = {
                "accepted": False,
                "reason": "non-finite candidate params",
                "candidate_return": None,
                "incumbent_return": self.incumbent_return,
                "floor": floor,
            }
            return False
        self.counters["evals"] += 1
        cand = self.frozen_return(params)
        ok = bool(np.isfinite(cand)) and cand >= floor
        self.last = {
            "accepted": ok,
            "reason": (
                "within band"
                if ok
                else (
                    "non-finite frozen return"
                    if not np.isfinite(cand)
                    else "frozen return below the band floor"
                )
            ),
            "candidate_return": cand if np.isfinite(cand) else None,
            "incumbent_return": self.incumbent_return,
            "floor": floor,
            "degradation": (
                round(self.incumbent_return - cand, 6)
                if np.isfinite(cand)
                else None
            ),
        }
        if ok:
            self.counters["accepts"] += 1
            self.incumbent_return = cand
        else:
            self.counters["rejects"] += 1
        return ok

    def summary_line(self) -> str:
        """One line the CI cell greps: accept/reject counters + the
        last decision ('... rejected (frozen return below the band
        floor)')."""
        c = self.counters
        tail = ""
        if self.last is not None:
            verdict = "promoted" if self.last["accepted"] else "rejected"
            tail = f" — last candidate {verdict} ({self.last['reason']})"
        inc = (
            f"{self.incumbent_return:.4f}"
            if self.incumbent_return is not None
            else "unset"
        )
        return (
            f"canary: {c['accepts']} accepted, {c['rejects']} rejected "
            f"over {c['evals']} evals (band {self.band:g}, incumbent "
            f"return {inc}){tail}"
        )


class CanaryWatcher(CheckpointWatcher):
    """The closed deployment loop on checkpoint files: poll → validate
    (the full CheckpointWatcher chain: checksum, ``.prev`` fallback,
    replica/finite guards) → CANARY eval → atomic swap or
    keep-incumbent.

    A candidate rejected by the GATE counts on both ledgers: the gate's
    ``rejects`` (with the return/floor record in ``gate.last``) and the
    engine's ``rejects`` (the serving row's degradation counter — the
    summary line reads ``served: last-good``, exactly like a corrupt
    file, because operationally it is the same outcome: the newest
    publish is not serving). The gate's incumbent reference is pinned
    from the engine's initial checkpoint at construction.
    """

    def __init__(self, engine, gate: CanaryGate, path=None) -> None:
        super().__init__(engine, path)
        self.gate = gate
        if gate.incumbent_return is None:
            # the serving policy at watcher construction IS the
            # incumbent: re-load it through the same discovery chain
            # the engine used (the engine keeps only the stacked actor
            # block; the gate needs the full params tree to roll out)
            from rcmarl_tpu.utils.checkpoint import load_checkpoint_with_meta

            state, _, _, _ = load_checkpoint_with_meta(
                engine.checkpoint_path, engine.cfg
            )
            gate.set_incumbent(state.params)

    def _try_swap(self) -> bool:
        candidate = self._load_candidate()
        if candidate is None:
            return False  # file/finite rejection — already counted
        state, loaded = candidate
        if not self.gate.admit(state.params):
            # bad POLICY: same degradation outcome as a bad file — the
            # incumbent keeps serving, the reject is on the ledger
            eng = self.engine
            eng.counters["rejects"] += 1
            eng.degraded = True
            return False
        return self._apply(state, loaded)
