"""Policy serving engine — compile-once batched inference.

Training artifacts used to dead-end in checkpoints; this module is the
"heavy traffic" half of the north star (ROADMAP item 5). The design is
the TF-Agents batched-inference tradition (PAPERS.md 1709.02878) fused
with the Podracer device-resident program style (2104.06272): serve by
compiling ONE stacked program over a huge batch axis, never by looping
per-agent per-request.

- :func:`stack_actor_rows` — ALL agents' actor heads netstacked into one
  row-stacked parameter block
  (:func:`rcmarl_tpu.models.mlp.netstack_stack_rows`, row i = agent i).
  For the homogeneous actor family the result is bitwise the
  checkpoint's stacked actor layout; the netstack construction is what
  keeps the block well-defined if per-agent input widths ever diverge
  (padded rows are exactly neutral, the PR-4 contract).
- :func:`serve_block` — the jitted serving program: ``(B, N, obs_dim)``
  batched observations -> ``(actions, probs)`` in ONE launch (vmapped
  :func:`~rcmarl_tpu.models.mlp.actor_probs` over the stacked block +
  per-request categorical sampling). ``mode='greedy'`` is the argmax
  arm; sampling draws NO exploration mix (serving exploits — the
  trainer's ε-mix is a training-time knob).
- :func:`serve_request_keys` — the per-(request, agent) key discipline:
  ``fold_in(fold_in(key, b), n)``, order-independent and reproducible
  per request, so a per-agent reference path handed the same keys
  samples IDENTICAL actions (the parity pin in tests/test_serve.py).
- :func:`eval_block` — the evaluate rollout program: ``n_ep_fixed``
  episodes under frozen params plus per-agent discounted returns
  (the `evaluate` CLI's unit of work).
- :class:`ServeEngine` — host shell: checksummed checkpoint load
  (solo↔replica mismatch fails loudly), the stacked block, the
  deterministic replayable eval stream, and the degradation counters
  the hot-swap watcher (:mod:`rcmarl_tpu.serve.swap`) maintains.

``serve_block`` and ``eval_block`` are registered jitted entry points
(:func:`rcmarl_tpu.utils.profiling.jit_entry_points`): the retrace
auditor proves exactly-once compilation across repeated batches AND
across a hot-swap of same-shaped params, and the cost/determinism arms
certify the compiled program like every other hot path.
"""

from __future__ import annotations

from functools import partial
from pathlib import Path
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from rcmarl_tpu.config import Config
from rcmarl_tpu.models.mlp import (
    MLPParams,
    actor_probs,
    agent_slice,
    netstack_stack_rows,
    pad_features,
)

#: The two serving arms: 'sample' draws one categorical action per
#: (request, agent) under the fold_in key discipline; 'greedy' is the
#: deterministic argmax arm (no keys consumed).
SERVE_MODES = ("sample", "greedy")


def stack_actor_rows(params, cfg: Config) -> MLPParams:
    """All agents' actor nets as ONE row-stacked parameter block.

    Row i is agent i's actor, stacked through
    :func:`~rcmarl_tpu.models.mlp.netstack_stack_rows` (first-layer
    rows zero-padded to the widest input, exactly gradient/forward
    neutral). The actor family is homogeneous (every agent observes the
    same flattened global state), so today the result is bitwise the
    checkpoint's stacked ``params.actor`` leaves — pinned in
    tests/test_serve.py, which is what makes the construction safe to
    keep on the netstack machinery.
    """
    rows = tuple(
        agent_slice(params.actor, i) for i in range(cfg.n_agents)
    )
    return netstack_stack_rows(rows)


def serve_request_keys(key: jax.Array, B: int, N: int) -> jax.Array:
    """The ``(B, N)`` per-(request, agent) sampling keys:
    ``fold_in(fold_in(key, b), n)`` — order-independent, so the batched
    program and a per-agent per-request loop handed the same ``key``
    draw IDENTICAL actions (the serve parity contract)."""
    rows = jax.vmap(lambda b: jax.random.fold_in(key, b))(jnp.arange(B))
    return jax.vmap(
        lambda kr: jax.vmap(lambda n: jax.random.fold_in(kr, n))(
            jnp.arange(N)
        )
    )(rows)


def serve_keys(eval_seed: int, step) -> jax.Array:
    """The deterministic serve stream: launch ``step``'s base key,
    namespaced by ``eval_seed``. Replaying the same (seed, step) pair
    replays the exact action stream — the eval arm's parity/pinning
    discipline (the engine folds this per launch)."""
    return jax.random.fold_in(jax.random.PRNGKey(eval_seed), step)


def batch_probs(cfg: Config, block: MLPParams, x: jnp.ndarray) -> jnp.ndarray:
    """THE batched policy core: ``(B, N, padded_obs)`` features through
    one row-stacked actor block -> ``(B, N, n_actions)`` probabilities
    (vmapped :func:`~rcmarl_tpu.models.mlp.actor_probs`, row n = agent
    n). The SINGLE implementation both :func:`serve_block` and the
    fleet program (:func:`rcmarl_tpu.serve.fleet.fleet_block`) compute
    probabilities with — the per-member bitwise-parity contract holds
    by construction because there is exactly one copy to drift."""
    return jax.vmap(
        lambda p, xn: actor_probs(p, xn, cfg.leaky_alpha, cfg.dot_dtype),
        in_axes=(0, 1),
        out_axes=1,
    )(block, x)


def _serve_block(
    cfg: Config,
    block: MLPParams,
    obs: jnp.ndarray,
    key: jax.Array,
    mode: str = "sample",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """ONE compiled launch serving a whole request batch.

    Args:
      cfg: static config (hashable — the compile key, like every entry
        point).
      block: the row-stacked actor block (:func:`stack_actor_rows`).
      obs: (B, N, obs_dim) batched observations — row b is one request
        (a global state), column n the view agent n's actor consumes.
      key: base PRNG key for this launch (``mode='sample'``); the
        per-request keys derive via :func:`serve_request_keys`.
      mode: 'sample' (categorical per request/agent) or 'greedy'
        (argmax; deterministic, key unused). Static — one program per
        arm, zero steady-state recompiles across batches and hot-swaps.

    Returns ``(actions, probs)``: (B, N) int32 and (B, N, n_actions)
    policy probabilities (bitwise the per-agent ``actor_probs`` path —
    the parity pin).
    """
    if mode not in SERVE_MODES:
        raise ValueError(f"mode={mode!r}: expected one of {SERVE_MODES}")
    B, N = obs.shape[0], obs.shape[1]
    # width of the stacked first layer (== obs_dim for the homogeneous
    # actor family; pad_features is the identity then)
    x = pad_features(obs, block[0][0].shape[-2])
    probs = batch_probs(cfg, block, x)  # (B, N, n_actions)
    if mode == "greedy":
        actions = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    else:
        keys = serve_request_keys(key, B, N)
        actions = jax.vmap(jax.vmap(jax.random.categorical))(
            keys, jnp.log(probs)
        ).astype(jnp.int32)
    return actions, probs


#: The jitted serving entry point (registered in
#: ``utils/profiling.py:jit_entry_points`` — retrace/cost/determinism
#: audited like every hot path). ``cfg`` and ``mode`` are static; the
#: block, observations, and key are data, so a hot-swap of same-shaped
#: params re-dispatches the SAME executable.
serve_block = partial(
    jax.jit, static_argnums=0, static_argnames=("mode",)
)(_serve_block)


def _eval_block(cfg: Config, params, desired, key, initial):
    """The evaluate rollout program: ``n_ep_fixed`` episodes under
    FROZEN parameters (no updates), returning the per-episode metrics
    plus each agent's mean discounted return — the `evaluate` CLI's
    per-block unit, ONE launch per block."""
    from rcmarl_tpu.training.rollout import rollout_block
    from rcmarl_tpu.training.trainer import make_env

    fresh, metrics = rollout_block(
        cfg, make_env(cfg), params, desired, key, initial
    )
    # fresh.r: (block_steps, N, 1) in episode order -> per-episode
    # per-agent discounted returns, averaged over the block's episodes
    r = fresh.r.reshape(cfg.n_ep_fixed, cfg.max_ep_len, cfg.n_agents)
    disc = cfg.gamma ** jnp.arange(cfg.max_ep_len, dtype=jnp.float32)
    agent_returns = jnp.mean(
        jnp.sum(r * disc[None, :, None], axis=1), axis=0
    )  # (N,)
    return metrics, agent_returns


#: The jitted evaluate entry point (registered next to serve_block).
eval_block = partial(jax.jit, static_argnums=0)(_eval_block)


def _actor_block(cfg: Config, params, desired, key, initial):
    """The ACTOR-TIER rollout program of the async pipeline
    (:mod:`rcmarl_tpu.pipeline`): one full rollout block —
    ``n_ep_fixed`` episodes — acted under the parameters the learner
    last PUBLISHED, returning the fresh on-policy window plus the
    block's episode metrics. The acting/serving twin of
    :func:`eval_block`: same frozen-params rollout program, but it
    keeps the ``(block_steps, N, ...)`` batch the learner tier
    consumes instead of reducing to returns. Like :func:`serve_block`,
    the parameters are DATA (one compile; every publish/hot-swap
    re-dispatches the same executable — the retrace-audited contract),
    and the sampling path is the exact training rollout
    (:func:`rcmarl_tpu.training.rollout.rollout_block`, ε-mix
    included), so a pipelined run differs from the synchronous trainer
    ONLY through parameter staleness, never through a different acting
    program."""
    from rcmarl_tpu.training.rollout import rollout_block
    from rcmarl_tpu.training.trainer import make_env

    return rollout_block(cfg, make_env(cfg), params, desired, key, initial)


#: The jitted actor-tier entry point (registered next to eval_block;
#: the pipeline trainer dispatches it ahead of the learner).
actor_block = partial(jax.jit, static_argnums=0)(_actor_block)


class ServeEngine:
    """Host shell around :func:`serve_block`: load once, serve forever.

    Loads a checksummed checkpoint through the shared discovery chain
    (:func:`rcmarl_tpu.utils.checkpoint.load_checkpoint_with_meta` —
    primary, then the rotated ``.prev`` fallback), builds the stacked
    actor block, and dispatches the compiled program per batch. The
    engine only ever holds ONE block reference; the hot-swap watcher
    (:class:`rcmarl_tpu.serve.swap.CheckpointWatcher`) replaces it
    wholesale after fully validating a candidate, so a swap can never
    expose a torn tree mid-loop.

    A replica-world checkpoint (``__meta__`` ``replicas > 0``) fails
    loudly: the serving layout is the SOLO stacked one, and silently
    serving replica 0 of a gossip run would misreport what was
    deployed. Non-finite initial params fail loudly too (there is no
    last-good block to degrade to at construction time).

    ``counters`` is the degradation ledger the summary line reports:
    ``launches``/``actions`` (traffic), ``swaps`` (hot-swaps applied),
    ``rejects`` (corrupted / non-finite candidates refused — the engine
    kept serving the last good block), ``fallbacks`` (loads served by
    the rotated ``.prev`` instead of the primary).

    ``serve_impl`` selects the serving arm
    (:data:`rcmarl_tpu.ops.pallas_serve.SERVE_IMPLS`): the XLA
    :func:`serve_block` chain or the fused one-kernel Pallas program
    (:func:`~rcmarl_tpu.ops.pallas_serve.fused_serve_block`, bitwise
    the same probabilities AND actions — the pinned contract), with
    ``'auto'`` resolving by the measured policy
    (:func:`~rcmarl_tpu.ops.pallas_serve.resolve_serve_impl`). The
    resolved arm is an engine attribute, not a Config field, so
    existing checkpoints and audit rows are untouched.
    """

    def __init__(
        self,
        checkpoint,
        cfg: Optional[Config] = None,
        mode: str = "sample",
        eval_seed: int = 0,
        serve_impl: str = "auto",
    ) -> None:
        from rcmarl_tpu.faults import params_finite
        from rcmarl_tpu.utils.checkpoint import load_checkpoint_with_meta

        if mode not in SERVE_MODES:
            raise ValueError(f"mode={mode!r}: expected one of {SERVE_MODES}")
        self.checkpoint_path = Path(checkpoint)
        state, stored_cfg, loaded, meta = load_checkpoint_with_meta(
            self.checkpoint_path, cfg
        )
        n_rep = int(meta.get("replicas", 0))
        if n_rep:
            raise ValueError(
                f"checkpoint {loaded} holds a {n_rep}-replica gossip "
                "world; the serve engine expects a SOLO policy "
                "checkpoint (replica worlds must be exported/collapsed "
                "explicitly, never served implicitly)"
            )
        if not params_finite(state.params):
            raise ValueError(
                f"checkpoint {loaded} holds non-finite parameters; "
                "refusing to serve a poisoned policy"
            )
        from rcmarl_tpu.ops.pallas_serve import resolve_serve_impl

        self.cfg = stored_cfg if cfg is None else cfg
        self.mode = mode
        self.eval_seed = eval_seed
        self.serve_impl = resolve_serve_impl(serve_impl)
        self.block = stack_actor_rows(state.params, self.cfg)
        #: True while the engine is serving an OLDER block than the
        #: newest candidate it saw (a rejected swap); cleared by the
        #: next successful swap — what the summary line's
        #: 'served: last-good' vs 'served: fresh' status reports.
        self.degraded = False
        self.counters = {
            "launches": 0,
            "actions": 0,
            "swaps": 0,
            "rejects": 0,
            "fallbacks": 1 if Path(loaded) != self.checkpoint_path else 0,
        }

    # -- serving -----------------------------------------------------------

    def serve(
        self,
        obs: jnp.ndarray,
        key: Optional[jax.Array] = None,
        step: Optional[int] = None,
        mode: Optional[str] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Serve one (B, N, obs_dim) batch -> (actions, probs).

        ``key=None`` uses the deterministic eval stream
        (:func:`serve_keys` on ``eval_seed`` and the launch counter —
        or an explicit ``step`` to REPLAY a past launch bit-for-bit).
        """
        if key is None:
            key = serve_keys(
                self.eval_seed,
                self.counters["launches"] if step is None else step,
            )
        if self.serve_impl == "xla":
            out = serve_block(
                self.cfg, self.block, obs, key, mode=mode or self.mode
            )
        else:
            from rcmarl_tpu.ops.pallas_serve import fused_serve_block

            out = fused_serve_block(
                self.cfg, self.block, obs, key, mode=mode or self.mode,
                interpret=(self.serve_impl == "pallas_interpret"),
            )
        self.counters["launches"] += 1
        self.counters["actions"] += int(obs.shape[0]) * int(obs.shape[1])
        return out

    # -- observability -----------------------------------------------------

    def summary(self) -> dict:
        """The degradation/traffic counters (a copy)."""
        return dict(self.counters)

    def summary_line(self) -> str:
        """The one-line serve summary (the CI cell greps
        ``served: last-good`` off it after a corrupted-swap sequence).
        The status reflects the CURRENT block: ``last-good`` while the
        newest candidate was rejected, back to ``fresh`` once a later
        swap applies."""
        c = self.counters
        status = "last-good" if self.degraded else "fresh"
        return (
            f"serve: {c['launches']} launches, {c['actions']} actions, "
            f"{c['swaps']} swaps, {c['rejects']} rejects, "
            f"{c['fallbacks']} fallbacks (served: {status})"
        )
