"""Policy serving: compile-once batched inference with checkpoint
hot-swap and guarded degradation (ROADMAP item 5 — the "heavy traffic"
half of the north star, distinct from the training benchmark axis),
plus the production tier (ROADMAP item 4): the request-arrival latency
harness (:mod:`rcmarl_tpu.serve.load`), fleet-stacked multi-policy
serving (:mod:`rcmarl_tpu.serve.fleet`), and the canary-gated
deployment loop (:mod:`rcmarl_tpu.serve.canary`)."""

from rcmarl_tpu.serve.canary import CanaryGate, CanaryWatcher  # noqa: F401
from rcmarl_tpu.serve.engine import (  # noqa: F401
    SERVE_MODES,
    ServeEngine,
    actor_block,
    eval_block,
    serve_block,
    serve_keys,
    serve_request_keys,
    stack_actor_rows,
)
from rcmarl_tpu.serve.fleet import (  # noqa: F401
    FleetEngine,
    fleet_block,
    fleet_set_member,
    fleet_stack,
)
from rcmarl_tpu.serve.load import (  # noqa: F401
    bursty_arrivals,
    fleet_service_fn,
    poisson_arrivals,
    run_load,
    saturation_knee,
    serve_service_fn,
    sweep_load,
)
from rcmarl_tpu.serve.swap import CheckpointWatcher  # noqa: F401
