"""Policy serving: compile-once batched inference with checkpoint
hot-swap and guarded degradation (ROADMAP item 5 — the "heavy traffic"
half of the north star, distinct from the training benchmark axis)."""

from rcmarl_tpu.serve.engine import (  # noqa: F401
    SERVE_MODES,
    ServeEngine,
    actor_block,
    eval_block,
    serve_block,
    serve_keys,
    serve_request_keys,
    stack_actor_rows,
)
from rcmarl_tpu.serve.swap import CheckpointWatcher  # noqa: F401
