"""Tracing and per-phase timing (SURVEY.md §5 "Tracing / profiling").

The reference has no profiling at all — TF logging is silenced and the
only observable is the per-episode console print. Here profiling is a
first-class utility:

- :func:`trace` — context manager around ``jax.profiler.trace``; writes a
  TensorBoard/XProf-compatible trace of every XLA launch inside the block.
- :func:`profile_phases` — a diagnostic that times the training
  sub-programs SEPARATELY (rollout block, one phase I+II critic/TR epoch,
  phase III actor update, full fused block), each jitted on its own with
  a host-fetch barrier. In production the whole block is ONE fused XLA
  program, so per-phase cost cannot be observed from the host; this
  deliberately un-fused breakdown exists for performance work, not
  training.
- :class:`Timer` — tiny wall-clock timer with forced completion, used by
  the benchmark harness and the phase profiler.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict

import jax

from rcmarl_tpu.training.update import team_average_reward


@contextlib.contextmanager
def trace(logdir: str, *, create_perfetto_link: bool = False):
    """Record a device trace of everything run inside the block.

    View with TensorBoard's profile plugin or Perfetto:
    ``tensorboard --logdir <logdir>``.
    """
    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Timer:
    """Wall-clock timer whose stop forces device completion of ``value``."""

    def __init__(self) -> None:
        self._t0 = 0.0
        self.elapsed = 0.0

    def start(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def stop(self, value=None) -> float:
        """Stop after fetching ``value`` (a jax array/pytree), if given.

        A host-side fetch is used rather than ``block_until_ready``
        because some remote backends complete the latter early.
        """
        if value is not None:
            jax.device_get(value)
        self.elapsed = time.perf_counter() - self._t0
        return self.elapsed


def _timeit(fn: Callable, *args, warmup: int = 1, reps: int = 3) -> float:
    """Best-of-``reps`` wall time after ``warmup`` compile/warm calls."""
    for _ in range(warmup):
        # fetch, don't just dispatch: queued warmup work would otherwise
        # drain inside the first timed rep
        jax.device_get(fn(*args))
    best = float("inf")
    for _ in range(reps):
        t = Timer().start()
        out = fn(*args)
        best = min(best, t.stop(out))
    return best


def profile_phases(cfg, state=None, *, reps: int = 3) -> Dict[str, float]:
    """Time each training sub-program separately; returns seconds per call.

    Keys: ``rollout_block`` (n_ep_fixed scanned episodes),
    ``critic_tr_epoch`` (ONE phase I+II epoch over the replay window —
    the production block runs ``cfg.n_epochs`` of these),
    ``actor_phase`` (phase III over the fresh window), and
    ``full_block`` (the production fused program: rollout + n_epochs
    epochs + actor + buffer push).
    """
    from rcmarl_tpu.training.buffer import update_batch
    from rcmarl_tpu.training.rollout import rollout_block
    from rcmarl_tpu.training.trainer import (
        init_train_state,
        make_env,
        train_block,
    )
    from rcmarl_tpu.training.update import actor_phase, critic_tr_epoch

    if state is None:
        state = init_train_state(cfg, jax.random.PRNGKey(cfg.seed))
    # one production block first: warm the buffer to steady-state occupancy
    state, _ = train_block(cfg, state)

    env = make_env(cfg)
    key = jax.random.PRNGKey(0)
    out: Dict[str, float] = {}

    roll = jax.jit(
        lambda s, k: rollout_block(cfg, env, s.params, s.desired, k, s.initial)
    )
    out["rollout_block"] = _timeit(roll, state, key, reps=reps)

    fresh, _ = roll(state, key)
    batch = jax.jit(update_batch)(state.buffer, fresh)
    r_coop = team_average_reward(cfg, batch.r)

    epoch = jax.jit(
        lambda p, b, rc, k: critic_tr_epoch(
            cfg, (p.critic, p.tr, p.critic_local), b, rc, k
        )
    )
    out["critic_tr_epoch"] = _timeit(epoch, state.params, batch, r_coop, key, reps=reps)

    actor = jax.jit(lambda p, f, k: actor_phase(cfg, p, f, k))
    out["actor_phase"] = _timeit(actor, state.params, fresh, key, reps=reps)

    out["full_block"] = _timeit(lambda s: train_block(cfg, s), state, reps=reps)
    return out
