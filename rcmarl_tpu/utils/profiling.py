"""Tracing and per-phase timing (SURVEY.md §5 "Tracing / profiling").

The reference has no profiling at all — TF logging is silenced and the
only observable is the per-episode console print. Here profiling is a
first-class utility:

- :func:`trace` — context manager around ``jax.profiler.trace``; writes a
  TensorBoard/XProf-compatible trace of every XLA launch inside the block.
- :func:`profile_phases` — a diagnostic that times the training
  sub-programs SEPARATELY (rollout block, one phase I+II critic/TR epoch,
  phase III actor update, full fused block), each jitted on its own with
  a host-fetch barrier. In production the whole block is ONE fused XLA
  program, so per-phase cost cannot be observed from the host; this
  deliberately un-fused breakdown exists for performance work, not
  training.
- :func:`profile_consensus` — one level deeper: the consensus epoch's
  own components (neighbor gather vs trim-bound selection vs clip/mean
  epilogue vs the phase-I local fits), each timed standalone on the
  flattened one-launch layout, tagged with the knobs the crossover
  policies key on (n_in, H, gathered volume) — so refits of
  ``SELECT_MAX_N_IN`` / ``PALLAS_CROSSOVER_VOLUME`` measure the
  component they tune instead of inferring it from whole-epoch deltas.
- :class:`Timer` — tiny wall-clock timer with forced completion, used by
  the benchmark harness and the phase profiler.
"""

from __future__ import annotations

import contextlib
import hashlib
import time
from typing import Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from rcmarl_tpu.training.update import team_average_reward


def jit_entry_points() -> Dict[str, object]:
    """The framework's jitted steady-state entry points, by name.

    The canonical registry for compile-count accounting: these are the
    programs whose compile-once contract the retrace auditor
    (:mod:`rcmarl_tpu.lint.retrace`) enforces — every other jit in the
    package is a diagnostic/benchmark standalone. Imported lazily so
    ``utils`` stays cheap to import.
    """
    from rcmarl_tpu.ops.pallas_serve import (
        fused_fleet_block,
        fused_serve_block,
    )
    from rcmarl_tpu.parallel.gala import gala_mix_block
    from rcmarl_tpu.parallel.gossip import gossip_mix_block
    from rcmarl_tpu.pipeline.trainer import (
        learner_block,
        learner_block_donated,
    )
    from rcmarl_tpu.serve.engine import actor_block, eval_block, serve_block
    from rcmarl_tpu.serve.fleet import fleet_block
    from rcmarl_tpu.training.trainer import train_block, train_block_donated
    from rcmarl_tpu.training.update import (
        consensus_block,
        fit_block,
        update_block,
        update_block_donated,
    )

    return {
        "update_block": update_block,
        "update_block_donated": update_block_donated,
        "train_block": train_block,
        "train_block_donated": train_block_donated,
        "gossip_mix_block": gossip_mix_block,
        "gala_mix_block": gala_mix_block,
        "fit_block": fit_block,
        "consensus_block": consensus_block,
        "serve_block": serve_block,
        "fleet_block": fleet_block,
        "fused_serve_block": fused_serve_block,
        "fused_fleet_block": fused_fleet_block,
        "eval_block": eval_block,
        "actor_block": actor_block,
        "learner_block": learner_block,
        "learner_block_donated": learner_block_donated,
    }


def compile_counts() -> Dict[str, int]:
    """Tracing-cache sizes of :func:`jit_entry_points` — how many
    distinct programs each entry point has compiled in this process.
    The retrace auditor diffs snapshots of this; it is also handy
    interactively ("did my sweep really share one program?")."""
    return {
        name: int(fn._cache_size())
        for name, fn in jit_entry_points().items()
    }


# --------------------------------------------------------------------------
# Shared entry-point lowering/compilation (the graftlint artifact arms)
# --------------------------------------------------------------------------
#
# The compiled-artifact audits (lint --donation/--backends/--cost) all need
# the SAME programs: the :func:`jit_entry_points` registry lowered over
# real tiny inputs. Each arm going through these memoized helpers means a
# `lint --all` run compiles every (config, entry) pair at most ONCE per
# process and pays each artifact view at most once — one make_jaxpr trace
# (the purity walk) and one lowering (the compile pipeline) per pair;
# the two views are distinct jax artifacts, so a pair audited by both
# the backends and cost arms traces twice, but never re-per-arm. Only
# the retrace auditor stays on the live jit caches, because auditing
# those caches is its entire job.


class CompiledEntry(NamedTuple):
    """One AOT-compiled entry point plus the audit metadata the lint
    arms read off it: the lowered-text fingerprint (what `bench` rows
    cite as ``cost_fingerprint``) and any donation-related warnings XLA
    raised while compiling (the donation audit's evidence)."""

    name: str
    compiled: object  # jax.stages.Compiled
    fingerprint: str
    warnings: Tuple[str, ...]  # raised during lowering OR compiling


def program_fingerprint(lowered_or_text) -> str:
    """sha256[:16] of a lowered program's StableHLO text — the stable id
    tying a PERF/AUDIT row to the EXACT program it describes (catches
    "benched arm A, shipped arm B" drift)."""
    text = (
        lowered_or_text
        if isinstance(lowered_or_text, str)
        else lowered_or_text.as_text()
    )
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def mesh_fingerprint(mesh) -> str:
    """Canonical id of the device mesh a sharded row executed on —
    device count plus the named axis sizes, e.g. ``'8d:seed=2,agent=4'``.
    `bench`/PERF.jsonl sharded rows and the AUDIT.jsonl device-memory
    rows carry this next to ``cost_fingerprint``, so a MULTICHIP number
    is tied to the exact mesh that produced it (catches "measured on a
    2-chip mesh, claimed for the pod" drift the program hash alone
    cannot see)."""
    shape = dict(mesh.shape)
    n_dev = 1
    for extent in shape.values():
        n_dev *= int(extent)
    return f"{n_dev}d:" + ",".join(f"{k}={int(v)}" for k, v in shape.items())


def config_fingerprint(cfg) -> str:
    """sha256[:12] of the Config's canonical field repr — the ledger key
    component that invalidates every AUDIT.jsonl row when the canonical
    audit shape itself changes (so a stale ledger can never be compared
    against a different program family silently)."""
    import dataclasses

    fields = tuple(
        (f.name, repr(getattr(cfg, f.name)))
        for f in dataclasses.fields(cfg)
    )
    return hashlib.sha256(repr(fields).encode()).hexdigest()[:12]


def train_block_fingerprint(cfg) -> str:
    """The :func:`program_fingerprint` of the steady-state
    ``train_block`` program for ``cfg`` — what `bench`/`profile` rows
    record as ``cost_fingerprint`` so every PERF.jsonl row is tied to
    the exact compiled program family it measured. Abstract lowering
    only (eval_shape avals): no allocation, no compile."""
    from rcmarl_tpu.training.trainer import init_train_state, train_block

    shapes = jax.eval_shape(
        lambda k: init_train_state(cfg, k), jax.random.PRNGKey(0)
    )
    if cfg.graph_schedule != "static":
        # time-varying graphs: the measured program is the one whose
        # gather indices arrive as DATA (ops/exchange.py), so the
        # fingerprint must lower with the (N, degree) graph operand —
        # fingerprinting the static-topology program would tie the row
        # to an arm that never ran
        graph = jax.ShapeDtypeStruct(
            (cfg.n_agents, cfg.resolved_graph_degree), jnp.int32
        )
        return program_fingerprint(train_block.lower(cfg, shapes, graph=graph))
    return program_fingerprint(train_block.lower(cfg, shapes))


_ENTRY_INPUT_CACHE: dict = {}
_ENTRY_LOWERED_CACHE: dict = {}
_ENTRY_COMPILED_CACHE: dict = {}


def entry_point_inputs(cfg):
    """(state, batch, fresh, key): real tiny-config inputs for lowering
    the jitted entry points, memoized per config (shared by the
    donation and cost arms and their regression tests)."""
    if cfg not in _ENTRY_INPUT_CACHE:
        from rcmarl_tpu.training.buffer import update_batch
        from rcmarl_tpu.training.rollout import rollout_block
        from rcmarl_tpu.training.trainer import init_train_state, make_env

        state = init_train_state(cfg, jax.random.PRNGKey(0))
        env = make_env(cfg)
        key = jax.random.PRNGKey(1)
        fresh, _ = jax.jit(
            lambda s, k: rollout_block(
                cfg, env, s.params, s.desired, k, s.initial
            )
        )(state, key)
        batch = jax.jit(update_batch)(state.buffer, fresh)
        _ENTRY_INPUT_CACHE[cfg] = (state, batch, fresh, key)
    return _ENTRY_INPUT_CACHE[cfg]


_GOSSIP_INPUT_CACHE: dict = {}


def gossip_entry_inputs(cfg):
    """(replica-stacked params, round, exclude): real tiny inputs for
    lowering the gossip-mix entry point (``cfg.replicas`` must be set),
    memoized per config like :func:`entry_point_inputs`."""
    if cfg not in _GOSSIP_INPUT_CACHE:
        import jax.numpy as jnp

        from rcmarl_tpu.parallel.gossip import replica_seeds
        from rcmarl_tpu.parallel.seeds import init_states

        states = init_states(cfg, replica_seeds(cfg))
        _GOSSIP_INPUT_CACHE[cfg] = (
            states.params,
            jnp.zeros((), jnp.int32),
            jnp.zeros((cfg.replicas,), bool),
        )
    return _GOSSIP_INPUT_CACHE[cfg]


_GALA_INPUT_CACHE: dict = {}


def gala_entry_inputs(cfg):
    """(tuple of R solo params, round, exclude): real tiny inputs for
    lowering the composed-fleet mix entry point — the SAME replica
    parameters :func:`gossip_entry_inputs` stacks, kept as the solo
    trees the composed trainer actually holds (``cfg.replicas`` must
    be set), memoized per config."""
    if cfg not in _GALA_INPUT_CACHE:
        import jax.numpy as jnp

        from rcmarl_tpu.parallel.gossip import replica_seeds
        from rcmarl_tpu.training.trainer import init_train_state

        params = tuple(
            init_train_state(cfg, jax.random.PRNGKey(s)).params
            for s in replica_seeds(cfg)
        )
        _GALA_INPUT_CACHE[cfg] = (
            params,
            jnp.zeros((), jnp.int32),
            jnp.zeros((cfg.replicas,), bool),
        )
    return _GALA_INPUT_CACHE[cfg]


_SERVE_INPUT_CACHE: dict = {}

#: Canonical serving batch for the audit arms — tiny (the cost rows'
#: full relative sensitivity is the point), but > 1 so the batch axis
#: is real in the audited program.
SERVE_AUDIT_BATCH = 4


def serve_entry_inputs(cfg):
    """(actor block, obs, key): tiny serving inputs for lowering the
    serve entry point, memoized per config. Derives the block from the
    SAME memoized :func:`entry_point_inputs` state the other arms use,
    so a ``lint --all`` run pays no extra init."""
    if cfg not in _SERVE_INPUT_CACHE:
        from rcmarl_tpu.serve.engine import stack_actor_rows

        state, _, _, _ = entry_point_inputs(cfg)
        block = stack_actor_rows(state.params, cfg)
        obs = jnp.zeros(
            (SERVE_AUDIT_BATCH, cfg.n_agents, cfg.obs_dim), jnp.float32
        )
        _SERVE_INPUT_CACHE[cfg] = (block, obs, jax.random.PRNGKey(2))
    return _SERVE_INPUT_CACHE[cfg]


_FLEET_INPUT_CACHE: dict = {}

#: Canonical fleet size for the audit arms — two members is the
#: smallest shape where the fleet axis and the routing gather are real
#: in the audited program.
FLEET_AUDIT_MEMBERS = 2


def fleet_entry_inputs(cfg):
    """(fleet, obs, key, route): tiny fleet-serving inputs for lowering
    the fleet entry point, memoized per config. Member 0 is the SAME
    memoized :func:`serve_entry_inputs` block (so a ``lint --all`` run
    pays no extra init for it); member 1 is an independent fresh init —
    a real second policy version, not a copy."""
    if cfg not in _FLEET_INPUT_CACHE:
        from rcmarl_tpu.serve.engine import stack_actor_rows
        from rcmarl_tpu.serve.fleet import fleet_stack
        from rcmarl_tpu.training.trainer import init_train_state

        block, obs, key = serve_entry_inputs(cfg)
        members = [block] + [
            stack_actor_rows(
                init_train_state(cfg, jax.random.PRNGKey(100 + f)).params,
                cfg,
            )
            for f in range(1, FLEET_AUDIT_MEMBERS)
        ]
        route = (
            jnp.arange(SERVE_AUDIT_BATCH, dtype=jnp.int32)
            % FLEET_AUDIT_MEMBERS
        )
        _FLEET_INPUT_CACHE[cfg] = (fleet_stack(members), obs, key, route)
    return _FLEET_INPUT_CACHE[cfg]


_PAIR_TRUNK_CACHE: dict = {}


def pair_trunk_struct(cfg) -> Tuple[int, int, int]:
    """``(n_trunk, tree_split, p_pair)``: the combined critic+TR pair
    block's static column geometry for ``cfg`` — the shapes the fused
    consensus ``kernel_plan()`` is priced at. Derived through
    ``jax.eval_shape`` of the parameter init (abstract avals only:
    nothing allocates, so ``lint --kernels`` can price bench- and
    session-scale cells on any host), memoized per config."""
    if cfg not in _PAIR_TRUNK_CACHE:
        from rcmarl_tpu.training.trainer import init_train_state
        from rcmarl_tpu.training.update import (
            _pair_segments,
            _pair_trunk_split,
        )

        params = jax.eval_shape(
            lambda k: init_train_state(cfg, k), jax.random.PRNGKey(0)
        ).params
        segs = _pair_segments(params.critic, params.tr)
        n_trunk, split = _pair_trunk_split(segs)
        p_pair = sum(size for *_, size in segs)
        _PAIR_TRUNK_CACHE[cfg] = (int(n_trunk), int(split), int(p_pair))
    return _PAIR_TRUNK_CACHE[cfg]


_FIT_STRUCT_CACHE: dict = {}


def fit_row_structs(cfg):
    """``(keys_rows, params_rows, x_rows, targets_rows, schedule)``
    with ``ShapeDtypeStruct`` leaves: the adversary fused-fit row block
    exactly as :func:`rcmarl_tpu.agents.updates.adv_fused_row_block`
    assembles it, derived through ONE ``jax.eval_shape`` of the whole
    build chain (init -> rollout -> batch -> pair inputs -> row block).
    Abstract avals only — no rollout executes, so ``lint --kernels``
    prices the fit-scan ``kernel_plan()`` at bench scale without
    paying a bench run. Memoized per config; raises on configs with no
    adversary flavors (there is no fused row block to price — the
    caller records a note, not a pass)."""
    if cfg not in _FIT_STRUCT_CACHE:
        from rcmarl_tpu.agents.updates import (
            adv_fit_schedule,
            adv_fused_row_block,
            netstack_pair_inputs,
        )
        from rcmarl_tpu.training.buffer import update_batch
        from rcmarl_tpu.training.rollout import rollout_block
        from rcmarl_tpu.training.trainer import init_train_state, make_env

        env = make_env(cfg)

        def build(key):
            state = init_train_state(cfg, key)
            fresh, _ = rollout_block(
                cfg, env, state.params, state.desired, key, state.initial
            )
            batch = update_batch(state.buffer, fresh)
            p = state.params
            x2 = netstack_pair_inputs(cfg, batch.s, batch.sa)
            r_agents = jnp.moveaxis(batch.r, 1, 0)
            r_coop = team_average_reward(cfg, batch.r)
            keys_rows, params_rows, x_rows, targets_rows, _ = (
                adv_fused_row_block(
                    cfg, p.critic, p.tr, p.critic_local, x2, batch.ns,
                    r_agents, r_coop, jax.random.split(key, 5),
                )
            )
            return keys_rows, params_rows, x_rows, targets_rows

        structs = jax.eval_shape(build, jax.random.PRNGKey(0))
        _FIT_STRUCT_CACHE[cfg] = structs + (adv_fit_schedule(cfg),)
    return _FIT_STRUCT_CACHE[cfg]


_COOP_FIT_STRUCT_CACHE: dict = {}


def coop_fit_row_structs(cfg):
    """``(keys_rows, params_rows, x_rows, targets_rows, schedule)`` for
    the FULL-BATCH cooperative fit launch (critic + TR as one stacked
    pair, zero keys, identity plan) — the twin of :func:`fit_row_structs`
    for configs with no adversary flavors, where the fused-fit kernel
    still runs via ``coop_fused_fit``. Same ``jax.eval_shape`` chain,
    same memoization; works on EVERY config (the cooperative group
    always exists)."""
    if cfg not in _COOP_FIT_STRUCT_CACHE:
        from rcmarl_tpu.agents.updates import (
            coop_fit_schedule,
            netstack_pair_inputs,
            netstack_stack,
            pair_bootstrap_targets,
        )
        from rcmarl_tpu.training.buffer import update_batch
        from rcmarl_tpu.training.rollout import rollout_block
        from rcmarl_tpu.training.trainer import init_train_state, make_env

        env = make_env(cfg)

        def build(key):
            state = init_train_state(cfg, key)
            fresh, _ = rollout_block(
                cfg, env, state.params, state.desired, key, state.initial
            )
            batch = update_batch(state.buffer, fresh)
            p = state.params
            x2 = netstack_pair_inputs(cfg, batch.s, batch.sa)
            r_agents = jnp.moveaxis(batch.r, 1, 0)
            targets2 = pair_bootstrap_targets(
                cfg, p.critic, batch.ns, r_agents
            )
            keys = jnp.zeros((2, cfg.n_agents, 2), jnp.uint32)
            return keys, netstack_stack(p.critic, p.tr), x2, targets2

        structs = jax.eval_shape(build, jax.random.PRNGKey(0))
        _COOP_FIT_STRUCT_CACHE[cfg] = structs + (
            coop_fit_schedule(cfg, int(structs[2].shape[1])),
        )
    return _COOP_FIT_STRUCT_CACHE[cfg]


_SERVE_STRUCT_CACHE: dict = {}


def serve_block_struct(cfg):
    """The stacked actor block's ``ShapeDtypeStruct`` pytree — the
    exact leaves :func:`rcmarl_tpu.serve.engine.stack_actor_rows` hands
    the serve launch, via ``jax.eval_shape`` of the init chain (nothing
    allocates), memoized per config. What the serve ``kernel_plan()``
    is priced over."""
    if cfg not in _SERVE_STRUCT_CACHE:
        from rcmarl_tpu.serve.engine import stack_actor_rows
        from rcmarl_tpu.training.trainer import init_train_state

        _SERVE_STRUCT_CACHE[cfg] = jax.eval_shape(
            lambda k: stack_actor_rows(init_train_state(cfg, k).params, cfg),
            jax.random.PRNGKey(0),
        )
    return _SERVE_STRUCT_CACHE[cfg]


def lowered_entry_points(
    cfg, with_diag: bool = False, names: Optional[Tuple[str, ...]] = None
) -> Dict[str, object]:
    """Lower the registered jitted entry points over the tiny inputs:
    ``{name: jax.stages.Lowered}``, memoized per (config, with_diag,
    name). ``names`` selects a subset (default: the whole registry).
    Warnings raised DURING lowering are recorded in the cache — jax
    emits 'Some donated buffers were not usable' at lower() time, not
    compile() time, so trapping only around compile would leave the
    donation audit's warning prong permanently empty."""
    import warnings as _warnings

    entries = jit_entry_points()
    names = tuple(entries) if names is None else tuple(names)
    out: Dict[str, object] = {}
    for name in names:
        cache_key = (cfg, with_diag, name)
        if cache_key not in _ENTRY_LOWERED_CACHE:
            fn = entries[name]
            if name not in ("gossip_mix_block", "gala_mix_block"):
                state, batch, fresh, key = entry_point_inputs(cfg)
            with _warnings.catch_warnings(record=True) as caught:
                _warnings.simplefilter("always")
                if name == "gossip_mix_block":
                    params, rnd, excl = gossip_entry_inputs(cfg)
                    lowered = fn.lower(cfg, params, params, rnd, excl)
                elif name == "gala_mix_block":
                    params, rnd, excl = gala_entry_inputs(cfg)
                    lowered = fn.lower(cfg, params, params, rnd, excl)
                elif name == "serve_block":
                    block, obs, skey = serve_entry_inputs(cfg)
                    lowered = fn.lower(cfg, block, obs, skey)
                elif name == "fleet_block":
                    fleet, obs, skey, route = fleet_entry_inputs(cfg)
                    lowered = fn.lower(cfg, fleet, obs, skey, route)
                elif name == "fused_serve_block":
                    # off-TPU the fused program only lowers interpreted
                    # (Mosaic is TPU-only) — the correctness arm, which
                    # is exactly what the CPU-side audits pin
                    block, obs, skey = serve_entry_inputs(cfg)
                    lowered = fn.lower(
                        cfg, block, obs, skey,
                        interpret=jax.default_backend() != "tpu",
                    )
                elif name == "fused_fleet_block":
                    fleet, obs, skey, route = fleet_entry_inputs(cfg)
                    lowered = fn.lower(
                        cfg, fleet, obs, skey, route,
                        interpret=jax.default_backend() != "tpu",
                    )
                elif name in ("eval_block", "actor_block"):
                    lowered = fn.lower(
                        cfg, state.params, state.desired, key, state.initial
                    )
                elif name.startswith("learner_block"):
                    lowered = fn.lower(
                        cfg,
                        state,
                        fresh,
                        key,
                        jax.random.fold_in(key, 1),
                        with_diag=with_diag,
                    )
                elif name == "fit_block":
                    p = state.params
                    lowered = fn.lower(
                        cfg,
                        (p.critic, p.tr, p.critic_local),
                        batch,
                        team_average_reward(cfg, batch.r),
                        key,
                    )
                elif name == "consensus_block":
                    p = state.params
                    lowered = fn.lower(
                        cfg, (p.critic, p.tr, p.critic_local), batch, key
                    )
                elif name.startswith("update_block"):
                    lowered = fn.lower(
                        cfg,
                        state.params,
                        batch,
                        fresh,
                        key,
                        with_diag=with_diag,
                    )
                else:
                    lowered = fn.lower(cfg, state, with_diag=with_diag)
            _ENTRY_LOWERED_CACHE[cache_key] = (
                lowered,
                tuple(str(w.message) for w in caught),
            )
        out[name] = _ENTRY_LOWERED_CACHE[cache_key][0]
    return out


def compiled_entry_points(
    cfg, with_diag: bool = False, names: Optional[Tuple[str, ...]] = None
) -> Dict[str, CompiledEntry]:
    """Compile the lowered entry points: ``{name: CompiledEntry}``,
    memoized like :func:`lowered_entry_points`. Warnings from BOTH the
    lowering (where jax reports unusable donations) and the compile are
    stored on the entry, so the donation audit sees them even when the
    cost arm lowered/compiled first."""
    import warnings as _warnings

    lowered = lowered_entry_points(cfg, with_diag, names)
    out: Dict[str, CompiledEntry] = {}
    for name, low in lowered.items():
        cache_key = (cfg, with_diag, name)
        if cache_key not in _ENTRY_COMPILED_CACHE:
            with _warnings.catch_warnings(record=True) as caught:
                _warnings.simplefilter("always")
                compiled = low.compile()
            lower_warnings = _ENTRY_LOWERED_CACHE[cache_key][1]
            _ENTRY_COMPILED_CACHE[cache_key] = CompiledEntry(
                name=name,
                compiled=compiled,
                fingerprint=program_fingerprint(low),
                warnings=lower_warnings
                + tuple(str(w.message) for w in caught),
            )
        out[name] = _ENTRY_COMPILED_CACHE[cache_key]
    return out


_ENTRY_JAXPR_CACHE: dict = {}


def _traced_entry(cfg, with_diag: bool, name: str):
    """(closed jaxpr, abstract output pytree) for one entry point,
    memoized per (config, with_diag, name) — ``make_jaxpr`` bypasses
    the live jit trace cache, so without this cache every repeat audit
    would pay a full re-trace."""
    cache_key = (cfg, with_diag, name)
    if cache_key not in _ENTRY_JAXPR_CACHE:
        entries = jit_entry_points()
        fn = getattr(entries[name], "__wrapped__", entries[name])
        if name == "gossip_mix_block":
            params, rnd, excl = gossip_entry_inputs(cfg)
            closed, out_shape = jax.make_jaxpr(
                lambda p, q, r, e: fn(cfg, p, q, r, e), return_shape=True
            )(params, params, rnd, excl)
            _ENTRY_JAXPR_CACHE[cache_key] = (closed, out_shape)
            return _ENTRY_JAXPR_CACHE[cache_key]
        if name == "gala_mix_block":
            params, rnd, excl = gala_entry_inputs(cfg)
            closed, out_shape = jax.make_jaxpr(
                lambda p, q, r, e: fn(cfg, p, q, r, e), return_shape=True
            )(params, params, rnd, excl)
            _ENTRY_JAXPR_CACHE[cache_key] = (closed, out_shape)
            return _ENTRY_JAXPR_CACHE[cache_key]
        state, batch, fresh, key = entry_point_inputs(cfg)
        if name == "serve_block":
            block, obs, skey = serve_entry_inputs(cfg)
            closed, out_shape = jax.make_jaxpr(
                lambda bl, o, k: fn(cfg, bl, o, k), return_shape=True
            )(block, obs, skey)
        elif name == "fleet_block":
            fleet, obs, skey, route = fleet_entry_inputs(cfg)
            closed, out_shape = jax.make_jaxpr(
                lambda fl, o, k, r: fn(cfg, fl, o, k, r), return_shape=True
            )(fleet, obs, skey, route)
        elif name == "fused_serve_block":
            block, obs, skey = serve_entry_inputs(cfg)
            interp = jax.default_backend() != "tpu"
            closed, out_shape = jax.make_jaxpr(
                lambda bl, o, k: fn(cfg, bl, o, k, interpret=interp),
                return_shape=True,
            )(block, obs, skey)
        elif name == "fused_fleet_block":
            fleet, obs, skey, route = fleet_entry_inputs(cfg)
            interp = jax.default_backend() != "tpu"
            closed, out_shape = jax.make_jaxpr(
                lambda fl, o, k, r: fn(cfg, fl, o, k, r, interpret=interp),
                return_shape=True,
            )(fleet, obs, skey, route)
        elif name in ("eval_block", "actor_block"):
            closed, out_shape = jax.make_jaxpr(
                lambda p, d, k, i: fn(cfg, p, d, k, i), return_shape=True
            )(state.params, state.desired, key, state.initial)
        elif name.startswith("learner_block"):
            closed, out_shape = jax.make_jaxpr(
                lambda s, f, k, nk: fn(cfg, s, f, k, nk, with_diag=with_diag),
                return_shape=True,
            )(state, fresh, key, jax.random.fold_in(key, 1))
        elif name == "fit_block":
            p = state.params
            closed, out_shape = jax.make_jaxpr(
                lambda c, b, rc, k: fn(cfg, c, b, rc, k),
                return_shape=True,
            )(
                (p.critic, p.tr, p.critic_local),
                batch,
                team_average_reward(cfg, batch.r),
                key,
            )
        elif name == "consensus_block":
            p = state.params
            closed, out_shape = jax.make_jaxpr(
                lambda c, b, k: fn(cfg, c, b, k),
                return_shape=True,
            )((p.critic, p.tr, p.critic_local), batch, key)
        elif name.startswith("update_block"):
            closed, out_shape = jax.make_jaxpr(
                lambda p, b, f, k: fn(cfg, p, b, f, k, with_diag=with_diag),
                return_shape=True,
            )(state.params, batch, fresh, key)
        else:
            closed, out_shape = jax.make_jaxpr(
                lambda s: fn(cfg, s, with_diag=with_diag),
                return_shape=True,
            )(state)
        _ENTRY_JAXPR_CACHE[cache_key] = (closed, out_shape)
    return _ENTRY_JAXPR_CACHE[cache_key]


def entry_jaxprs(
    cfg, with_diag: bool = False, names: Optional[Tuple[str, ...]] = None
) -> Dict[str, object]:
    """Closed jaxprs of the entry points over the tiny inputs (the
    backend purity audit's view), traced through the same memoized
    input pipeline — one trace per (config, entry) per process."""
    entries = jit_entry_points()
    names = tuple(entries) if names is None else tuple(names)
    return {n: _traced_entry(cfg, with_diag, n)[0] for n in names}


def entry_out_shapes(
    cfg, with_diag: bool = False, names: Optional[Tuple[str, ...]] = None
) -> Dict[str, object]:
    """Abstract output pytrees (ShapeDtypeStruct leaves, ORIGINAL tree
    structure) of the entry points, from the same cached trace as
    :func:`entry_jaxprs` — what the backend audit compares across the
    netstack arms so a re-nesting with identical flat leaves still
    reads as structure drift."""
    entries = jit_entry_points()
    names = tuple(entries) if names is None else tuple(names)
    return {n: _traced_entry(cfg, with_diag, n)[1] for n in names}


@contextlib.contextmanager
def trace(logdir: str, *, create_perfetto_link: bool = False):
    """Record a device trace of everything run inside the block.

    View with TensorBoard's profile plugin or Perfetto:
    ``tensorboard --logdir <logdir>``.
    """
    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Timer:
    """Wall-clock timer whose stop forces device completion of ``value``."""

    def __init__(self) -> None:
        self._t0 = 0.0
        self.elapsed = 0.0

    def start(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def stop(self, value=None) -> float:
        """Stop after fetching ``value`` (a jax array/pytree), if given.

        A host-side fetch is used rather than ``block_until_ready``
        because some remote backends complete the latter early.
        """
        if value is not None:
            jax.device_get(value)
        self.elapsed = time.perf_counter() - self._t0
        return self.elapsed


def _timeit(fn: Callable, *args, warmup: int = 1, reps: int = 3) -> float:
    """Best-of-``reps`` wall time after ``warmup`` compile/warm calls."""
    for _ in range(warmup):
        # fetch, don't just dispatch: queued warmup work would otherwise
        # drain inside the first timed rep
        jax.device_get(fn(*args))
    best = float("inf")
    for _ in range(reps):
        t = Timer().start()
        out = fn(*args)
        best = min(best, t.stop(out))
    return best


def profile_phases(cfg, state=None, *, reps: int = 3) -> Dict[str, float]:
    """Time each training sub-program separately; returns seconds per call.

    Keys: ``rollout_block`` (n_ep_fixed scanned episodes),
    ``critic_tr_epoch`` (ONE phase I+II epoch over the replay window —
    the production block runs ``cfg.n_epochs`` of these),
    ``actor_phase`` (phase III over the fresh window), and
    ``full_block`` (the production fused program: rollout + n_epochs
    epochs + actor + buffer push).

    Scheduled configs (``graph_schedule != 'static'``) are measured on
    the program they actually run: the block-0 resample rides in as a
    TRACED ``graph`` operand (never a baked constant), so the timed
    gather is the indices-as-data sparse exchange, matching the
    fingerprint :func:`train_block_fingerprint` cites. ``None`` is an
    empty pytree to jit, so the static arm shares the code path.
    """
    from rcmarl_tpu.config import scheduled_in_nodes
    from rcmarl_tpu.training.buffer import update_batch
    from rcmarl_tpu.training.rollout import rollout_block
    from rcmarl_tpu.training.trainer import (
        init_train_state,
        make_env,
        train_block,
    )
    from rcmarl_tpu.training.update import actor_phase, critic_tr_epoch

    graph = (
        jnp.asarray(scheduled_in_nodes(cfg, 0))
        if cfg.graph_schedule != "static"
        else None
    )
    if state is None:
        state = init_train_state(cfg, jax.random.PRNGKey(cfg.seed))
    # one production block first: warm the buffer to steady-state occupancy
    state, _ = train_block(cfg, state, graph=graph)

    env = make_env(cfg)
    key = jax.random.PRNGKey(0)
    out: Dict[str, float] = {}

    roll = jax.jit(
        lambda s, k: rollout_block(cfg, env, s.params, s.desired, k, s.initial)
    )
    out["rollout_block"] = _timeit(roll, state, key, reps=reps)

    fresh, _ = roll(state, key)
    batch = jax.jit(update_batch)(state.buffer, fresh)
    r_coop = team_average_reward(cfg, batch.r)

    epoch = jax.jit(
        lambda p, b, rc, k, g: critic_tr_epoch(
            cfg, (p.critic, p.tr, p.critic_local), b, rc, k, graph=g
        )
    )
    out["critic_tr_epoch"] = _timeit(
        epoch, state.params, batch, r_coop, key, graph, reps=reps
    )

    actor = jax.jit(lambda p, f, k: actor_phase(cfg, p, f, k))
    out["actor_phase"] = _timeit(actor, state.params, fresh, key, reps=reps)

    out["full_block"] = _timeit(
        lambda s: train_block(cfg, s, graph=graph), state, reps=reps
    )
    return out


def consensus_tags(cfg) -> Dict[str, int]:
    """The static knobs every consensus crossover policy keys on, for
    tagging micro-breakdown rows: the neighbor-axis size, the trim
    parameter, the agent count, the volume key ``n_in * n_agents`` that
    :data:`~rcmarl_tpu.ops.aggregation.PALLAS_CROSSOVER_VOLUME` uses,
    and the total element count of one gathered critic message tree
    (the actual bytes a consensus launch streams)."""
    from rcmarl_tpu.models.mlp import init_stacked_mlp

    params = init_stacked_mlp(
        jax.random.PRNGKey(0), cfg.n_agents, cfg.obs_dim, cfg.hidden, 1
    )
    per_agent = sum(
        int(l.size) // cfg.n_agents for l in jax.tree.leaves(params)
    )
    # Scheduled configs gather along the schedule's degree axis, not the
    # static anchor's n_in — tag the volume the launch actually streams.
    n_in = (
        cfg.n_in
        if cfg.graph_schedule == "static"
        else cfg.resolved_graph_degree
    )
    return {
        "n_in": n_in,
        "H": cfg.H,
        "n_agents": cfg.n_agents,
        "volume": n_in * cfg.n_agents,
        "gathered_numel": cfg.n_agents * n_in * per_agent,
    }


def profile_consensus(cfg, state=None, *, reps: int = 3) -> Dict[str, float]:
    """Time the components of ONE consensus epoch separately.

    Where :func:`profile_phases` stops at whole sub-programs, this
    breaks the dominant one (the critic/TR epoch, 92-100% of block time
    at every measured scale — PERF.md) into the pieces the crossover
    policies tune:

    - ``gather`` — the neighbor-message gather of the critic tree
      ((N, ...) leaves -> (N, n_in, ...) leaves; rolls or fancy index).
    - ``trim_bounds`` — the sort-vs-selection trim-bound computation
      alone, on the flattened (N, n_in, P_total) gathered block (the
      one-launch layout), by ``cfg.consensus_impl``'s strategy.
    - ``clip_mean`` — the clip-and-average epilogue given precomputed
      bounds (the part every strategy shares).
    - ``consensus`` — the full phase-II update of BOTH nets as the
      epoch runs it: with ``cfg.netstack`` one fused
      critic+TR pair update on the combined block, otherwise the two
      per-tree vmapped updates back to back. Under the ONE-KERNEL arm
      (``consensus_impl='pallas_fused*'``) this is the standalone
      ``consensus_block`` program — fault-field draw + VMEM-resident
      kernel + XLA tail — and ``gather`` is an honest 0.0 (the gather
      happens in-register inside this number), so the fused arm's rows
      attribute per phase exactly as it launches.
    - ``fit_coop`` / ``fit_adv`` — the phase-I local fits that produce
      the messages, PER FLAVOR FAMILY and as the active fit arm runs
      them (``cfg.fitstack`` fused scans, the netstack pair fits, or
      the dual per-tree fits): ``fit_coop`` is the cooperative
      full-batch critic+TR family, ``fit_adv`` every adversary
      minibatch flavor present (greedy pair, malicious compromised
      pair, malicious private critic). Keys appear only for roles the
      config actually casts, so a fused-scan win is attributable per
      flavor. ``phase1_fits`` stays their sum (continuity with the
      pre-split rows).
    - ``epoch`` — the whole ``critic_tr_epoch`` sub-program (same
      number as :func:`profile_phases`' ``critic_tr_epoch``).
    - ``epoch_other`` — the residual ``epoch - gather - consensus -
      fit_coop - fit_adv``: a TRUE residual (select/mask plumbing,
      dispatch) now that the gather and every fit flavor are measured
      components. Can be slightly negative on tiny configs (standalone
      timings amortize dispatch differently than the fused epoch).

    Each component is jitted standalone with host-fetch barriers, like
    the phase profiler. Use :func:`consensus_tags` for the row tags.
    """
    from rcmarl_tpu.agents.updates import (
        adv_critic_fit,
        adv_fit_schedule,
        adv_fused_row_block,
        adv_pair_fit,
        adv_tr_fit,
        consensus_update_one,
        consensus_update_pair,
        coop_fused_fit,
        coop_local_critic_fit,
        coop_local_tr_fit,
        coop_pair_fit,
        fused_fit_rows,
        netstack_pair_inputs,
        pair_bootstrap_targets,
    )
    from rcmarl_tpu.config import Roles
    from rcmarl_tpu.models.mlp import netstack_stack
    from rcmarl_tpu.ops.aggregation import _trim_bounds, resolve_impl
    from rcmarl_tpu.training.buffer import update_batch
    from rcmarl_tpu.training.rollout import rollout_block
    from rcmarl_tpu.training.trainer import init_train_state, make_env
    from rcmarl_tpu.training.update import (
        _pair_block,
        critic_tr_epoch,
        fitstack_enabled,
        gather_neighbor_messages,
        netstack_enabled,
        team_average_reward,
    )

    from rcmarl_tpu.config import FUSED_CONSENSUS_IMPLS, scheduled_in_nodes
    from rcmarl_tpu.training.update import consensus_block

    # scheduled configs: measure the indices-as-data sparse exchange —
    # the block-0 resample rides every gather/epoch arm as a TRACED
    # operand (profile_phases discipline; None = static, empty pytree)
    graph = (
        jnp.asarray(scheduled_in_nodes(cfg, 0))
        if cfg.graph_schedule != "static"
        else None
    )
    if state is None:
        state = init_train_state(cfg, jax.random.PRNGKey(cfg.seed))
    env = make_env(cfg)
    key = jax.random.PRNGKey(0)
    fresh, _ = jax.jit(
        lambda s, k: rollout_block(cfg, env, s.params, s.desired, k, s.initial)
    )(state, key)
    batch = jax.jit(update_batch)(state.buffer, fresh)
    critic, tr = state.params.critic, state.params.tr
    out: Dict[str, float] = {}

    fused_family = cfg.consensus_impl in FUSED_CONSENSUS_IMPLS
    stacked = netstack_enabled(cfg)  # True whenever fused_family is
    # the neighbor-message gather AS THE ARM PAYS IT: one combined
    # (N, n_in, P_c + P_t) block gather on the netstack arm, the two
    # per-tree gathers on the dual arm — so epoch_other below is a true
    # residual rather than silently holding half the gather traffic.
    # Under the ONE-KERNEL arm there is no separate gather launch at
    # all (the kernel reads the stacked messages in-register), so the
    # key is an honest 0.0 and the whole gather+fault+trim chain is
    # attributed to ``consensus`` below.
    if fused_family:
        out["gather"] = 0.0
    elif stacked:
        gather_arm = jax.jit(
            lambda c, t, g: gather_neighbor_messages(
                cfg, _pair_block(c, t), g
            )
        )
        out["gather"] = _timeit(gather_arm, critic, tr, graph, reps=reps)
    else:
        gather_arm = jax.jit(
            lambda c, t, g: (
                gather_neighbor_messages(cfg, c, g),
                gather_neighbor_messages(cfg, t, g),
            )
        )
        out["gather"] = _timeit(gather_arm, critic, tr, graph, reps=reps)
    gather = jax.jit(lambda t, g: gather_neighbor_messages(cfg, t, g))
    nbr = gather(
        critic, graph
    )  # (N, n_in, ...) leaves — the trim-bound/clip diagnostics' input

    # the flattened one-launch layout: ONE (N, n_in, P_total) block
    # (scheduled arm: the neighbor axis is the schedule's degree)
    N = cfg.n_agents
    n_in = cfg.n_in if graph is None else int(graph.shape[1])
    flat = jnp.concatenate(
        [l.reshape(N, n_in, -1) for l in jax.tree.leaves(nbr)], axis=-1
    )
    # strategy twin of the resolved impl (the bound computation is
    # XLA-level here; pallas rows measure the whole kernel instead)
    resolved = resolve_impl(
        cfg.consensus_impl, n_in, flat.dtype, N, cfg.H
    )
    strategy = (
        "xla_sort" if resolved in ("xla_sort", "pallas_sort") else "xla"
    )
    H_eff = max(cfg.H, 1)  # H=0 short-circuits past the bounds entirely
    bounds = jax.jit(
        jax.vmap(lambda v: _trim_bounds(v, H_eff, strategy))
    )
    out["trim_bounds"] = _timeit(bounds, flat, reps=reps)
    lo, hi = bounds(flat)

    def clip_mean(v, lo, hi):
        own = v[:, 0]
        lower = jnp.minimum(lo, own)
        upper = jnp.maximum(hi, own)
        return jnp.mean(
            jnp.clip(v, lower[:, None], upper[:, None]), axis=1
        )

    out["clip_mean"] = _timeit(jax.jit(clip_mean), flat, lo, hi, reps=reps)

    mask = batch.mask
    x2 = netstack_pair_inputs(cfg, batch.s, batch.sa)
    if fused_family:
        # phase II as the ONE-KERNEL arm runs it: the standalone
        # consensus_block entry (fault-field draw + VMEM-resident
        # kernel + XLA projection/head tail) — gather and fault
        # injection live INSIDE this number, matching the arm's real
        # launch structure, so epoch_other stays a true residual
        loc = state.params.critic_local
        out["consensus"] = _timeit(
            lambda c, t, l: consensus_block(cfg, (c, t, l), batch, key),
            critic, tr, loc, reps=reps,
        )
    elif stacked:
        # phase II as the netstack epoch runs it: ONE fused pair update
        # over the combined (N, n_in, P_c + P_t) gathered block
        pair_nbr = gather(_pair_block(critic, tr), graph)

        cons2 = jax.jit(
            jax.vmap(
                lambda oc, ot, blk: consensus_update_pair(
                    oc, ot, blk, x2, mask, cfg
                ),
                in_axes=(0, 0, 0),
            )
        )
        out["consensus"] = _timeit(cons2, critic, tr, pair_nbr, reps=reps)
    else:
        nbr_t = gather(tr, graph)

        def cons_both(critic_p, tr_p, nc, nt):
            c = jax.vmap(
                lambda own, nb, x: consensus_update_one(own, nb, x, mask, cfg),
                in_axes=(0, 0, None),
            )(critic_p, nc, batch.s)
            t = jax.vmap(
                lambda own, nb, x: consensus_update_one(own, nb, x, mask, cfg),
                in_axes=(0, 0, None),
            )(tr_p, nt, batch.sa)
            return c, t

        out["consensus"] = _timeit(
            jax.jit(cons_both), critic, tr, nbr, nbr_t, reps=reps
        )

    r_agents = jnp.moveaxis(batch.r, 1, 0)  # (N, B, 1)
    r_coop = team_average_reward(cfg, batch.r)
    fused = fitstack_enabled(cfg)
    N = cfg.n_agents

    # ---- fit_coop: the cooperative full-batch critic+TR family, as
    # the active fit arm runs it (fitstack fused scan / netstack pair
    # scan / dual per-tree scans)
    if cfg.n_coop:
        if fused:
            fit_coop = jax.jit(
                lambda c, t, cp, r: coop_fused_fit(
                    c, t, x2,
                    pair_bootstrap_targets(cfg, cp, batch.ns, r),
                    mask, cfg,
                )[0]
            )
            out["fit_coop"] = _timeit(
                fit_coop, critic, tr, critic, r_agents, reps=reps
            )
        elif stacked:
            fits2 = jax.jit(
                lambda p2, cp, r: coop_pair_fit(
                    p2, x2, pair_bootstrap_targets(cfg, cp, batch.ns, r),
                    mask, cfg,
                )[0]
            )
            out["fit_coop"] = _timeit(
                fits2, netstack_stack(critic, tr), critic, r_agents,
                reps=reps,
            )
        else:

            def fits(critic_p, tr_p, r):
                c, _ = jax.vmap(
                    lambda p, rr: coop_local_critic_fit(
                        p, batch.s, batch.ns, rr, mask, cfg
                    )
                )(critic_p, r)
                t, _ = jax.vmap(
                    lambda p, rr: coop_local_tr_fit(p, batch.sa, rr, mask, cfg)
                )(tr_p, r)
                return c, t

            out["fit_coop"] = _timeit(
                jax.jit(fits), critic, tr, r_agents, reps=reps
            )

    # ---- fit_adv: every adversary minibatch flavor present, as the
    # active fit arm runs it (the fused arm batches them all into ONE
    # (flavor·net, agent) scan; the PR-4 arms launch one scan per
    # flavor pair plus the unpaired private critic)
    has_greedy = cfg.has_role(Roles.GREEDY)
    has_mal = cfg.has_role(Roles.MALICIOUS)
    if has_greedy or has_mal:
        critic_local = state.params.critic_local
        neg = jnp.broadcast_to(-r_coop[None], (N, *r_coop.shape))

        def adv_fused(c, t, loc, r, key):
            # the SAME row assembly the epoch runs (agents.updates owns
            # it), so the measured fused arm cannot drift from the real one
            keys, rows, xs, tgts, _ = adv_fused_row_block(
                cfg, c, t, loc, x2, batch.ns, r, r_coop,
                jax.random.split(key, 5),
                has_greedy=has_greedy, has_mal=has_mal,
            )
            return fused_fit_rows(
                keys, rows, xs, tgts, mask, adv_fit_schedule(cfg), cfg
            )[0]

        def adv_pair(c, t, loc, r, key):
            k_gc, k_gt, k_ml, k_mc, k_mt = jax.random.split(key, 5)
            stack2 = netstack_stack(c, t)
            tgt = lambda rr: pair_bootstrap_targets(cfg, c, batch.ns, rr)
            outs = []
            if has_greedy:
                outs.append(adv_pair_fit(
                    jnp.stack([jax.random.split(k_gc, N),
                               jax.random.split(k_gt, N)]),
                    stack2, x2, tgt(r), mask, cfg,
                )[0])
            if has_mal:
                outs.append(adv_pair_fit(
                    jnp.stack([jax.random.split(k_mc, N),
                               jax.random.split(k_mt, N)]),
                    stack2, x2, tgt(neg), mask, cfg,
                )[0])
                outs.append(jax.vmap(
                    lambda k, p, rr: adv_critic_fit(
                        k, p, batch.s, batch.ns, rr, mask, cfg
                    )[0]
                )(jax.random.split(k_ml, N), loc, r))
            return outs

        def adv_dual(c, t, loc, r, key):
            k_gc, k_gt, k_ml, k_mc, k_mt = jax.random.split(key, 5)
            fit_c = lambda k, p, rr: adv_critic_fit(
                k, p, batch.s, batch.ns, rr, mask, cfg
            )[0]
            fit_t = lambda k, p, rr: adv_tr_fit(
                k, p, batch.sa, rr, mask, cfg
            )[0]
            outs = []
            if has_greedy:
                outs.append(jax.vmap(fit_c)(jax.random.split(k_gc, N), c, r))
                outs.append(jax.vmap(fit_t)(jax.random.split(k_gt, N), t, r))
            if has_mal:
                outs.append(jax.vmap(fit_c)(jax.random.split(k_mc, N), c, neg))
                outs.append(jax.vmap(fit_t)(jax.random.split(k_mt, N), t, neg))
                outs.append(jax.vmap(fit_c)(jax.random.split(k_ml, N), loc, r))
            return outs

        adv_fn = adv_fused if fused else (adv_pair if stacked else adv_dual)
        out["fit_adv"] = _timeit(
            jax.jit(adv_fn), critic, tr, critic_local, r_agents, key,
            reps=reps,
        )

    out["phase1_fits"] = out.get("fit_coop", 0.0) + out.get("fit_adv", 0.0)

    # the whole epoch + the residual the micro components don't cover
    epoch = jax.jit(
        lambda p, b, rc, k, g: critic_tr_epoch(
            cfg, (p.critic, p.tr, p.critic_local), b, rc, k, graph=g
        )
    )
    out["epoch"] = _timeit(
        epoch, state.params, batch, r_coop, key, graph, reps=reps
    )
    out["epoch_other"] = (
        out["epoch"]
        - out["gather"]
        - out["consensus"]
        - out["phase1_fits"]
    )
    return out


def serve_tags(cfg, batch: int, mode: str) -> Dict[str, int]:
    """The static knobs a serving crossover policy would key on, for
    tagging serve micro-breakdown rows: the request batch, the agent
    count, the action fan-out, and the per-launch action volume."""
    return {
        "batch": int(batch),
        "n_agents": cfg.n_agents,
        "n_actions": cfg.n_actions,
        "actions_per_launch": int(batch) * cfg.n_agents,
        "greedy": int(mode == "greedy"),
    }


def profile_serve(
    cfg,
    block=None,
    *,
    batch: int = 512,
    mode: str = "sample",
    serve_impl: str = "auto",
    reps: int = 3,
    load_requests: int = 512,
    seed: int = 0,
) -> Dict[str, float]:
    """Time the components of ONE serving launch separately, AS THE
    ACTIVE ``serve_impl`` ARM RUNS THEM.

    The serving-side sibling of :func:`profile_consensus`: where that
    breaks a consensus epoch into the pieces its crossover policies
    tune, this breaks a serve launch into the stages the one-kernel
    serving path fuses —

    - ``forward`` — the stacked actor forward alone (pad + per-agent
      MLP probs over the whole request batch).
    - ``key_derivation`` — the per-(request, agent) counter-based key
      derivation alone (``fold_in(fold_in(key, b), n)`` over B×N).
    - ``sample`` — the categorical draw alone, given precomputed keys
      and probabilities.
    - ``serve`` — the WHOLE launch as the resolved arm actually runs
      it: the XLA :func:`~rcmarl_tpu.serve.engine.serve_block` chain,
      or the fused Pallas program
      (:func:`~rcmarl_tpu.ops.pallas_serve.fused_serve_block`).
    - ``queue_wait`` — mean time a request spends QUEUED (not being
      served) in a short seeded closed-loop replay at ~half the
      measured per-launch capacity, through the same resolved arm
      (``mean_latency - service_mean`` of the
      :func:`~rcmarl_tpu.serve.load.run_load` report).

    Attribution follows the :func:`profile_consensus` honesty
    discipline: under the fused arm there are NO separate
    forward/key/sample launches — the kernel runs all three
    VMEM-resident inside one program — so those keys are an honest 0.0
    and the whole chain is attributed to ``serve``. Greedy mode zeroes
    ``key_derivation``/``sample`` on every arm (the greedy program
    never runs them).
    """
    from rcmarl_tpu.models.mlp import pad_features
    from rcmarl_tpu.ops.pallas_serve import (
        fused_serve_block,
        resolve_serve_impl,
    )
    from rcmarl_tpu.serve.engine import (
        batch_probs,
        serve_block,
        serve_request_keys,
        stack_actor_rows,
    )
    from rcmarl_tpu.serve.load import (
        poisson_arrivals,
        run_load,
        serve_service_fn,
    )

    impl = resolve_serve_impl(serve_impl)
    if block is None:
        from rcmarl_tpu.training.trainer import init_train_state

        block = stack_actor_rows(
            init_train_state(cfg, jax.random.PRNGKey(cfg.seed)).params, cfg
        )
    B, N = int(batch), cfg.n_agents
    obs = jax.random.uniform(
        jax.random.PRNGKey(seed + 7), (B, N, cfg.obs_dim), jnp.float32
    )
    key = jax.random.PRNGKey(seed)
    width = int(block[0][0].shape[-2])
    out: Dict[str, float] = {}

    # ---- the whole launch, exactly as the resolved arm runs it
    if impl == "xla":
        serve_arm = lambda bl, o, k: serve_block(cfg, bl, o, k, mode=mode)
    else:
        interp = impl == "pallas_interpret"
        serve_arm = lambda bl, o, k: fused_serve_block(
            cfg, bl, o, k, mode=mode, interpret=interp
        )
    out["serve"] = _timeit(serve_arm, block, obs, key, reps=reps)

    # ---- per-stage splits: real launches on the XLA arm; honest 0.0
    # under the fused arm (the stages happen in-register inside
    # ``serve`` — there is no separate launch to time)
    if impl == "xla":
        fwd = jax.jit(
            lambda bl, o: batch_probs(cfg, bl, pad_features(o, width))
        )
        out["forward"] = _timeit(fwd, block, obs, reps=reps)
        if mode == "greedy":
            out["key_derivation"] = 0.0
            out["sample"] = 0.0
        else:
            derive = jax.jit(lambda k: serve_request_keys(k, B, N))
            out["key_derivation"] = _timeit(derive, key, reps=reps)
            sample = jax.jit(
                lambda ks, pr: jax.vmap(jax.vmap(jax.random.categorical))(
                    ks, jnp.log(pr)
                ).astype(jnp.int32)
            )
            out["sample"] = _timeit(
                sample, derive(key), fwd(block, obs), reps=reps
            )
    else:
        out["forward"] = 0.0
        out["key_derivation"] = 0.0
        out["sample"] = 0.0

    # ---- queue wait under load, through the SAME resolved arm: a
    # short seeded Poisson replay at ~half the per-launch capacity
    # (comfortably below the knee, so this measures batching-window
    # wait rather than saturation)
    service = serve_service_fn(
        cfg, block, B, mode=mode, seed=seed, serve_impl=impl
    )
    rate = 0.5 * B / max(out["serve"], 1e-9)
    arrivals = poisson_arrivals(seed, load_requests, rate)
    report = run_load(service, arrivals, B, max_wait=out["serve"])
    out["queue_wait"] = max(
        0.0, report["mean_latency"] - report["service_mean"]
    )
    return out
