"""Checkpoint / resume for full training state, plus reference interop.

The reference checkpoints only the agents' weight lists and the goal layout
(``np.save('pretrained_weights.npy', ...)`` / ``desired_state.npy``,
reference ``main.py:119-121``), losing optimizer state and the replay
buffer on resume (SURVEY.md §5 "Checkpoint / resume"). Here a checkpoint is
the COMPLETE :class:`~rcmarl_tpu.training.trainer.TrainState` pytree —
stacked params, Adam moments, replay ring, RNG key, and block counter — so
a resumed run continues bit-for-bit where it stopped.

Format: a single ``.npz`` holding every pytree leaf under a structural key
(``leaf_000``...), plus a JSON header recording the Config the state was
built under. Restore unflattens into a template built from that Config, so
structure mismatches fail loudly instead of silently mis-assigning leaves.

Interop: :func:`export_reference_weights` / :func:`import_reference_weights`
translate between our stacked pytrees and the reference's nested-list
layout (``pretrained_weights[node] = [actor, critic, TR(, critic_local)]``
with Keras ``get_weights()`` order ``[W1, b1, W2, b2, W3, b3]``; reference
``main.py:83-92``), so reference-trained weights can warm-start this
framework and vice versa.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib
from pathlib import Path
from typing import Optional, Tuple

import jax
import numpy as np

from rcmarl_tpu.agents.updates import AgentParams
from rcmarl_tpu.config import Config
from rcmarl_tpu.faults import FaultPlan, ReplicaFaultPlan
from rcmarl_tpu.training.trainer import TrainState, init_train_state


class CheckpointError(ValueError):
    """A checkpoint file is unreadable, truncated, or fails its payload
    checksum — i.e. the FILE is bad, as opposed to a structure/shape
    mismatch against the caller's config (plain ``ValueError``). Resume
    paths catch exactly this to fall back to the previous good
    checkpoint (:func:`load_checkpoint_with_fallback`)."""


# --------------------------------------------------------------------------
# Full-state checkpointing
# --------------------------------------------------------------------------


def _config_to_json(cfg: Config) -> str:
    return json.dumps(dataclasses.asdict(cfg), sort_keys=True)


def config_from_json(s: str) -> Config:
    d = json.loads(s)
    d["agent_roles"] = tuple(d["agent_roles"])
    d["in_nodes"] = tuple(tuple(n) for n in d["in_nodes"])
    d["hidden"] = tuple(d["hidden"])
    # absent in pre-task-axis checkpoints: default ()
    if "task_levels" in d:
        d["task_levels"] = tuple(d["task_levels"])
    # dataclasses.asdict recursed into the nested FaultPlan dataclass;
    # rebuild it (absent in pre-fault checkpoints: default None).
    if d.get("fault_plan") is not None:
        d["fault_plan"] = FaultPlan(**d["fault_plan"])
    if d.get("replica_fault_plan") is not None:
        rp = dict(d["replica_fault_plan"])
        rp["byzantine_replicas"] = tuple(rp.get("byzantine_replicas", ()))
        d["replica_fault_plan"] = ReplicaFaultPlan(**rp)
    return Config(**d)


def _payload_checksum(arrays: dict) -> np.uint32:
    """CRC32 over every array's dtype/shape/bytes in key order — cheap
    (~GB/s) and catches the silent-corruption cases that matter
    (truncated writes, bit rot, partial copies). The ``__checksum__``
    entry itself is excluded."""
    crc = 0
    for k in sorted(arrays):
        if k == "__checksum__":
            continue
        a = np.ascontiguousarray(arrays[k])
        crc = zlib.crc32(f"{k}:{a.dtype.str}:{a.shape}:".encode(), crc)
        crc = zlib.crc32(a.tobytes(), crc)
    return np.uint32(crc & 0xFFFFFFFF)


def save_checkpoint(
    path, state: TrainState, cfg: Config, meta: Optional[dict] = None
) -> None:
    """Write the full TrainState to ``path`` (.npz) with a Config header
    and a payload checksum (verified by :func:`load_checkpoint`). The
    previous checkpoint at ``path``, if any, is rotated to
    ``<path>.prev`` so resume paths always have a fallback.

    ``meta`` (optional, JSON-serializable) rides in a checksummed
    ``__meta__`` header. The gossip trainer stores the REPLICA WORLD
    there — ``{"replicas": R, "gossip_round": k, "excluded": [...]}`` —
    and :func:`load_checkpoint` reads ``"replicas"`` to build the
    replica-stacked template (every leaf with a leading R axis) instead
    of the solo one, so ``cmd_train --replicas`` resume goes through the
    SAME checksummed ``.prev``-rotated format as solo runs."""
    leaves = jax.tree.leaves(state)
    arrays = {f"leaf_{i:03d}": np.asarray(l) for i, l in enumerate(leaves)}
    arrays["__config__"] = np.frombuffer(
        _config_to_json(cfg).encode(), dtype=np.uint8
    )
    if meta is not None:
        arrays["__meta__"] = np.frombuffer(
            json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8
        )
    arrays["__checksum__"] = np.asarray([_payload_checksum(arrays)])
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    # Write-then-rename so a crash mid-write can't destroy the previous
    # good checkpoint (periodic checkpointing exists exactly for kills).
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    if path.exists():
        # Rotate the current file to <path>.prev WITHOUT ever unlinking
        # the primary: hardlink (or copy) it, then atomically replace.
        # Every crash window leaves a loadable primary — the invariant
        # the plain write-then-rename had, which a rename-based rotation
        # would break (kill between the two renames = no primary file).
        prev = Path(str(path) + ".prev")
        try:
            os.unlink(prev)
        except FileNotFoundError:
            pass
        try:
            os.link(path, prev)
        except OSError:  # cross-device/filesystem without hardlinks
            import shutil

            shutil.copy2(path, prev)
    os.replace(tmp, path)


def load_checkpoint(path, cfg: Optional[Config] = None) -> Tuple[TrainState, Config]:
    """Restore ``(TrainState, stored_config)`` from ``path``.

    If ``cfg`` is given it must structurally match the stored one (same
    shapes) and the state is unflattened against it; otherwise the stored
    Config is used. The returned Config is always the STORED one, so
    callers can detect hyperparameter drift between the checkpointed run
    and their active config.

    Raises :class:`CheckpointError` when the file is unreadable,
    truncated, or fails its payload checksum (a bad FILE — resume via
    :func:`load_checkpoint_with_fallback` to fall back to ``.prev``),
    and plain ``ValueError`` on a structure/shape mismatch against
    ``cfg`` (a bad CONFIG).
    """
    try:
        z = np.load(path)
    except FileNotFoundError:
        # A missing file is a caller error (typo'd path), not a corrupted
        # checkpoint — keep it distinguishable and outside the .prev
        # fallback, which would otherwise silently resume older state.
        raise
    except Exception as e:  # zipfile/OSError: truncated or not an npz
        raise CheckpointError(
            f"checkpoint {path} is unreadable ({type(e).__name__}: {e}) — "
            "likely truncated by an interrupted write; resume from the "
            "rotated <path>.prev fallback"
        ) from None
    with z:
        try:
            arrays = {k: z[k] for k in z.files}
        except Exception as e:  # per-member decompression failure
            raise CheckpointError(
                f"checkpoint {path} is corrupted ({type(e).__name__}: {e})"
            ) from None
        if "__checksum__" in arrays:
            want = np.uint32(arrays["__checksum__"][0])
            got = _payload_checksum(arrays)
            if want != got:
                raise CheckpointError(
                    f"checkpoint {path} failed its payload checksum "
                    f"(stored {int(want):#010x}, recomputed {int(got):#010x})"
                    " — the file is corrupted; resume from <path>.prev"
                )
        # (pre-checksum checkpoints load unverified, for compatibility)
        if "__config__" not in arrays:
            raise CheckpointError(
                f"checkpoint {path} has no __config__ header"
            )
        try:
            stored_cfg = config_from_json(bytes(arrays["__config__"]).decode())
        except Exception as e:  # undecodable header = a bad FILE
            raise CheckpointError(
                f"checkpoint {path} has a corrupted __config__ header "
                f"({type(e).__name__}: {e}); resume from <path>.prev"
            ) from None
        if cfg is None:
            cfg = stored_cfg
        meta = {}
        if "__meta__" in arrays:
            try:
                meta = json.loads(bytes(arrays["__meta__"]).decode())
            except Exception as e:
                raise CheckpointError(
                    f"checkpoint {path} has a corrupted __meta__ header "
                    f"({type(e).__name__}: {e}); resume from <path>.prev"
                ) from None
        n_rep = int(meta.get("replicas", 0))
        if n_rep:
            # replica-stacked world: the template is the vmapped init
            # (every leaf with a leading R axis), so a solo checkpoint
            # loaded as a replica one — or vice versa — fails loudly on
            # shape, never silently mis-assigns leaves
            template = jax.eval_shape(
                lambda ks: jax.vmap(lambda k: init_train_state(cfg, k))(ks),
                jax.random.split(jax.random.PRNGKey(0), n_rep),
            )
        else:
            template = jax.eval_shape(
                lambda k: init_train_state(cfg, k), jax.random.PRNGKey(0)
            )
        t_leaves, treedef = jax.tree.flatten(template)
        keys = [f"leaf_{i:03d}" for i in range(len(t_leaves))]
        missing = [k for k in keys if k not in arrays]
        if missing:
            raise ValueError(
                f"checkpoint {path} does not match config structure: "
                f"missing {missing[:3]}... ({len(missing)} leaves)"
            )
        leaves = [arrays[k] for k in keys]
        for k, leaf, tmpl in zip(keys, leaves, t_leaves):
            if tuple(leaf.shape) != tuple(tmpl.shape):
                raise ValueError(
                    f"checkpoint leaf {k} has shape {leaf.shape}, "
                    f"config expects {tmpl.shape}"
                )
    return jax.tree.unflatten(treedef, leaves), stored_cfg


def load_checkpoint_with_fallback(
    path, cfg: Optional[Config] = None
) -> Tuple[TrainState, Config, Path]:
    """:func:`load_checkpoint`, falling back to the rotated
    ``<path>.prev`` when the primary file is corrupted/truncated
    (:class:`CheckpointError` only — a structure mismatch would fail on
    the fallback too, and should stay loud). Returns
    ``(state, stored_cfg, actually_loaded_path)`` so callers can report
    which file served the resume; re-raises the PRIMARY error when no
    fallback exists or the fallback is bad too."""
    path = Path(path)
    try:
        state, stored = load_checkpoint(path, cfg)
        return state, stored, path
    except CheckpointError as primary_err:
        prev = Path(str(path) + ".prev")
        if not prev.exists():
            raise
        try:
            state, stored = load_checkpoint(prev, cfg)
        except CheckpointError:
            raise primary_err from None
        return state, stored, prev


def load_checkpoint_with_meta(
    path, cfg: Optional[Config] = None
) -> Tuple[TrainState, Config, Path, dict]:
    """The ONE checkpoint-discovery chain shared by ``cmd_train``
    resume and the serve watcher: :func:`load_checkpoint_with_fallback`
    (primary, then the rotated ``.prev``) followed by
    :func:`read_checkpoint_meta` of the file that ACTUALLY served the
    load. Returns ``(state, stored_cfg, loaded_path, meta)`` — the meta
    always describes ``loaded_path``, so a fallback load can never pair
    the previous state with the corrupted primary's header."""
    state, stored, loaded = load_checkpoint_with_fallback(path, cfg)
    return state, stored, loaded, read_checkpoint_meta(loaded)


def read_checkpoint_meta(path) -> dict:
    """The ``__meta__`` header of a checkpoint (``{}`` when absent) —
    how the gossip resume recovers its round counter and exclusion mask
    after :func:`load_checkpoint_with_fallback` picked the file."""
    try:
        with np.load(path) as z:
            if "__meta__" not in z.files:
                return {}
            return json.loads(bytes(z["__meta__"]).decode())
    except FileNotFoundError:
        raise
    except Exception as e:  # truncated/corrupt: same class as a bad file
        raise CheckpointError(
            f"checkpoint {path} meta unreadable ({type(e).__name__}: {e})"
        ) from None


# --------------------------------------------------------------------------
# Reference-format interop
# --------------------------------------------------------------------------


def _stacked_to_keras(params, agent: int) -> list:
    """One agent's MLP -> Keras get_weights() order [W1, b1, W2, b2, ...]."""
    out = []
    for W, b in params:
        out.append(np.asarray(W[agent]))
        out.append(np.asarray(b[agent]))
    return out


def _keras_to_layers(flat: list) -> tuple:
    """[W1, b1, W2, b2, ...] -> ((W1, b1), (W2, b2), ...)."""
    return tuple(
        (np.asarray(flat[i]), np.asarray(flat[i + 1]))
        for i in range(0, len(flat), 2)
    )


def export_reference_weights(params: AgentParams, cfg: Config) -> np.ndarray:
    """Stacked params -> the reference's ``pretrained_weights.npy`` object
    layout: per node ``[actor, critic, TR]`` (+ ``critic_local`` appended
    for every node, a superset of the reference's malicious-only 4th entry
    — reference importers index the first 3, ``main.py:83-86``)."""
    out = []
    for i in range(cfg.n_agents):
        out.append(
            [
                _stacked_to_keras(params.actor, i),
                _stacked_to_keras(params.critic, i),
                _stacked_to_keras(params.tr, i),
                _stacked_to_keras(params.critic_local, i),
            ]
        )
    arr = np.empty(len(out), dtype=object)
    arr[:] = out
    return arr


def import_reference_weights(
    weights: np.ndarray, cfg: Config, params: AgentParams
) -> AgentParams:
    """Reference ``pretrained_weights.npy`` content -> AgentParams.

    ``params`` supplies the template (and Adam state, which the reference
    never checkpoints — moments reset on resume there too, SURVEY.md §5).
    Nodes with a 4th entry restore ``critic_local`` (reference
    ``main.py:91-92``); others keep the template's.
    """

    def set_agent(stacked, i, layers):
        if len(stacked) != len(layers):
            raise ValueError(
                f"agent {i}: reference weights have {len(layers)} layers, "
                f"config expects {len(stacked)} — layer-count mismatch"
            )
        return tuple(
            (W.at[i].set(lw), b.at[i].set(lb))
            for (W, b), (lw, lb) in zip(stacked, layers)
        )

    actor, critic, tr = params.actor, params.critic, params.tr
    critic_local = params.critic_local
    for i in range(cfg.n_agents):
        entry = weights[i]
        actor = set_agent(actor, i, _keras_to_layers(entry[0]))
        critic = set_agent(critic, i, _keras_to_layers(entry[1]))
        tr = set_agent(tr, i, _keras_to_layers(entry[2]))
        if len(entry) > 3:
            critic_local = set_agent(critic_local, i, _keras_to_layers(entry[3]))
    return params._replace(
        actor=actor, critic=critic, tr=tr, critic_local=critic_local
    )


def save_reference_artifacts(out_dir, state: TrainState, cfg: Config) -> None:
    """Write ``pretrained_weights.npy`` + ``desired_state.npy`` in the
    reference's layout (reference ``main.py:119-121``) so its resume path
    and analysis scripts accept our runs."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    np.save(
        out_dir / "pretrained_weights.npy",
        export_reference_weights(state.params, cfg),
        allow_pickle=True,
    )
    np.save(
        out_dir / "desired_state.npy",
        np.asarray(state.desired),
        allow_pickle=True,
    )
