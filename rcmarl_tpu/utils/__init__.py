"""Auxiliary subsystems: checkpointing, profiling."""

from rcmarl_tpu.utils.checkpoint import (
    export_reference_weights,
    import_reference_weights,
    load_checkpoint,
    save_checkpoint,
    save_reference_artifacts,
)
from rcmarl_tpu.utils.profiling import Timer, profile_phases, trace

__all__ = [
    "export_reference_weights",
    "import_reference_weights",
    "load_checkpoint",
    "save_checkpoint",
    "save_reference_artifacts",
    "Timer",
    "profile_phases",
    "trace",
]
