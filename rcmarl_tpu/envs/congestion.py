"""Congestion/routing: goal navigation where shared cells carry load.

JAX-native member of the env zoo (``rcmarl_tpu.envs.api``): the
grid-world navigation task (each agent routes to its own goal cell,
the task array — drawn at run start exactly like the grid world's
``desired``) with the north star's "heavy traffic" made LITERAL — a
cell is a shared resource, and every agent occupying it alongside
others pays a per-step congestion toll proportional to the load:

    reward[i] = grid-world shaping               # 0 at-goal-and-stay,
                                                 # else -(L1 before) - 1
                - congestion_weight * load[i]    # load = # OTHER agents
                                                 #   on agent i's cell

The shaping term is bitwise the grid world's observed reward rule
(:func:`rcmarl_tpu.envs.grid_world._step_observed`), so the only new
pressure is the congestion toll: the selfish shortest path through a
shared corridor stops being optimal once enough teammates route
through it. Bounded in ``[-(nrow + ncol - 1) - congestion_weight *
(n_agents - 1), 0]``, scaled by the shared ``/5`` convention. Pure
function of ``(pos, task, actions)`` — no RNG; the task never evolves.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from rcmarl_tpu.envs.grid_world import MOVES


class CongestionWorld(NamedTuple):
    """Static environment description (closed over by jitted code)."""

    nrow: int = 5
    ncol: int = 5
    n_agents: int = 5
    scaling: bool = True
    #: per-step toll per OTHER agent sharing the cell
    congestion_weight: float = 1.0


def env_reset(env: CongestionWorld, key: jax.Array) -> jnp.ndarray:
    """Agent positions ~ U over the grid. (n_agents, 2) int32."""
    return jax.random.randint(
        key,
        (env.n_agents, 2),
        jnp.array([0, 0]),
        jnp.array([env.nrow, env.ncol]),
        dtype=jnp.int32,
    )


def env_task(env: CongestionWorld, key: jax.Array) -> jnp.ndarray:
    """Per-agent goal cells ~ U over the grid (the grid world's
    ``desired`` draw, unchanged)."""
    return env_reset(env, key)


def env_step(
    env: CongestionWorld,
    pos: jnp.ndarray,
    task: jnp.ndarray,
    actions: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One synchronous step. Returns (new_pos, task, reward)."""
    clip_hi = jnp.array([env.nrow - 1, env.ncol - 1], dtype=jnp.int32)
    move = jnp.asarray(MOVES)[actions]
    dist_before = jnp.sum(jnp.abs(pos - task), axis=1)  # (N,)
    npos = jnp.clip(pos + move, 0, clip_hi)
    at_goal_stay = (dist_before == 0) & (actions == 0)
    shaping = jnp.where(
        at_goal_stay, 0.0, -(dist_before.astype(jnp.float32)) - 1.0
    )
    # load: how many OTHER agents landed on my cell this step
    pair = jnp.sum(jnp.abs(npos[:, None, :] - npos[None, :, :]), axis=-1)
    same_cell = (pair == 0).astype(jnp.float32)
    load = jnp.sum(same_cell, axis=1) - 1.0  # exclude self
    reward = shaping - env.congestion_weight * load
    return npos, task, reward


def env_step_scaled(
    env: CongestionWorld,
    pos: jnp.ndarray,
    task: jnp.ndarray,
    actions: jnp.ndarray,
    toll_scale: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """:func:`env_step` with the congestion toll scaled by TRACED data.

    ``toll_scale`` (() float32) multiplies the per-step toll — the
    Diff-DAC task axis (``Config.task_axis``): each vmapped replica
    trains this same compiled program at its own load level
    (``CellSpec.task_scale``). ``toll_scale == 1.0`` is bitwise
    :func:`env_step` (IEEE: ``1.0 * w * load == w * load`` exactly), so
    threading the spec through the rollout costs non-task cells
    nothing, bit-for-bit.
    """
    clip_hi = jnp.array([env.nrow - 1, env.ncol - 1], dtype=jnp.int32)
    move = jnp.asarray(MOVES)[actions]
    dist_before = jnp.sum(jnp.abs(pos - task), axis=1)  # (N,)
    npos = jnp.clip(pos + move, 0, clip_hi)
    at_goal_stay = (dist_before == 0) & (actions == 0)
    shaping = jnp.where(
        at_goal_stay, 0.0, -(dist_before.astype(jnp.float32)) - 1.0
    )
    pair = jnp.sum(jnp.abs(npos[:, None, :] - npos[None, :, :]), axis=-1)
    same_cell = (pair == 0).astype(jnp.float32)
    load = jnp.sum(same_cell, axis=1) - 1.0  # exclude self
    reward = shaping - toll_scale * env.congestion_weight * load
    return npos, task, reward
