"""Coverage/formation: spread out to cover a fixed landmark layout.

JAX-native member of the env zoo (``rcmarl_tpu.envs.api``), the
grid-world twin of the particle-world "simple spread" task: the task
array holds ``n_agents`` landmark cells drawn at run start (the
protocol's ``desired`` slot, static within the run like the grid
world's goals), and the team is rewarded for keeping EVERY landmark
close to SOME agent while not stacking on one cell.

Reward row i (per-landmark credit, so the reward keeps the protocol's
per-agent layout while the objective stays cooperative):

    reward[i] = -(L1 distance of landmark i to its NEAREST agent)
                - 1.0 * [agent i shares a cell with another agent]

Any agent may cover any landmark — the min over agents is what makes
the task a coverage problem rather than N independent navigations; the
collision term penalizes degenerate "everyone sits on one landmark"
solutions. Bounded in ``[-(nrow + ncol - 1), 0]``, scaled by the shared
``/5`` convention. The step is a pure function of
``(pos, task, actions)`` — no RNG, exact dynamics determinism; the
task never evolves (``new_task is task``).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from rcmarl_tpu.envs.grid_world import MOVES


class CoverageWorld(NamedTuple):
    """Static environment description (closed over by jitted code)."""

    nrow: int = 5
    ncol: int = 5
    n_agents: int = 5
    scaling: bool = True
    #: per-step penalty for sharing a cell with another agent
    collide_penalty: float = 1.0


def env_reset(env: CoverageWorld, key: jax.Array) -> jnp.ndarray:
    """Agent positions ~ U over the grid. (n_agents, 2) int32."""
    return jax.random.randint(
        key,
        (env.n_agents, 2),
        jnp.array([0, 0]),
        jnp.array([env.nrow, env.ncol]),
        dtype=jnp.int32,
    )


def env_task(env: CoverageWorld, key: jax.Array) -> jnp.ndarray:
    """The landmark layout: n_agents cells ~ U over the grid (may
    coincide — covering duplicated landmarks is just easier)."""
    return env_reset(env, key)


def env_step(
    env: CoverageWorld,
    pos: jnp.ndarray,
    task: jnp.ndarray,
    actions: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One synchronous step. Returns (new_pos, task, reward)."""
    clip_hi = jnp.array([env.nrow - 1, env.ncol - 1], dtype=jnp.int32)
    move = jnp.asarray(MOVES)[actions]
    npos = jnp.clip(pos + move, 0, clip_hi)
    # (landmark, agent) pairwise L1 distances after the move
    d = jnp.sum(jnp.abs(task[:, None, :] - npos[None, :, :]), axis=-1)
    cover = jnp.min(d, axis=1).astype(jnp.float32)  # (N,) per landmark
    # collision: agent i shares its cell with at least one other agent
    pair = jnp.sum(jnp.abs(npos[:, None, :] - npos[None, :, :]), axis=-1)
    pair = pair + jnp.eye(env.n_agents, dtype=pair.dtype) * 10**6
    crowded = (jnp.min(pair, axis=1) == 0).astype(jnp.float32)
    reward = -cover - env.collide_penalty * crowded
    return npos, task, reward
