"""Drop-in twin of the reference's stateful environment object.

The reference exposes a gym-style mutable object (``Grid_World`` at
``environments/grid_world.py:5-75``) whose surface — ``reset()``,
``step(action)``, ``get_data()``, ``close()`` and the ``state`` /
``reward`` / ``desired_state`` attributes — user scripts drive directly
(e.g. the reference's ``env_test.py:9-23``). This framework's native
environment is the pure-functional :mod:`rcmarl_tpu.envs.grid_world`
(one ``lax.scan``-able step for the whole team); this module wraps those
same pure functions in the reference's object protocol so existing
scripts migrate without rewrites.

Fidelity notes:

- ``reset`` draws from the GLOBAL NumPy RNG exactly like the reference
  (``grid_world.py:41``), so a script that seeds ``np.random`` gets the
  reference's layouts.
- Dynamics route through :func:`rcmarl_tpu.envs.grid_world.env_step`
  with ``reference_clip=True`` by default — bit-identical transitions
  and rewards to the reference loop, including its both-axes-``nrow``
  clip on non-square grids and the dead collision branch's observed
  semantics. There is one deliberate divergence available: pass
  ``collision_physics=True`` for the docstring-*intended* collision
  rule the reference never executes.
- ``get_data`` applies the reference's scaling contract: state
  standardized only when ``scaling=True`` (mean/std of ``arange``),
  reward ALWAYS divided by 5 (``grid_world.py:66-72``).

No gym dependency: the reference only inherits ``gym.Env`` for the
interface convention, which duck typing provides.
"""

from __future__ import annotations

import numpy as np

from rcmarl_tpu.envs.grid_world import GridWorld, env_step

__all__ = ["ReferenceGridWorld"]


class ReferenceGridWorld:
    """Stateful reference-protocol shell over the functional grid world.

    Constructor signature mirrors the reference ``Grid_World.__init__``
    (``grid_world.py:19``): ``nrow, ncol, n_agents, desired_state,
    initial_state, randomize_state, scaling``.
    """

    def __init__(
        self,
        nrow: int = 5,
        ncol: int = 5,
        n_agents: int = 1,
        desired_state=None,
        initial_state=None,
        randomize_state: bool = True,
        scaling: bool = False,
        *,
        collision_physics: bool = False,
        reference_clip: bool = True,
    ):
        self.nrow = nrow
        self.ncol = ncol
        self.n_agents = n_agents
        self.n_states = 2
        self.desired_state = (
            None if desired_state is None else np.asarray(desired_state)
        )
        self.initial_state = (
            None if initial_state is None else np.asarray(initial_state)
        )
        self.randomize_state = randomize_state
        self.scaling = scaling
        self._env = GridWorld(
            nrow=nrow,
            ncol=ncol,
            n_agents=n_agents,
            scaling=scaling,
            collision_physics=collision_physics,
            reference_clip=reference_clip,
        )
        self.reset()

    def reset(self) -> np.ndarray:
        """Reference ``reset`` (``grid_world.py:37-45``): randomized
        positions from the global NumPy stream, or the fixed
        ``initial_state``; zero rewards."""
        if self.randomize_state:
            self.state = np.random.randint(
                [0, 0], [self.nrow, self.ncol], size=(self.n_agents, self.n_states)
            )
        else:
            self.state = np.array(self.initial_state)
        self.reward = np.zeros(self.n_agents)
        return self.state

    def step(self, action) -> None:
        """Reference ``step`` (``grid_world.py:47-64``): apply the global
        action vector, update ``state`` and ``reward`` IN PLACE — scripts
        may hold aliases to these arrays, exactly as with the reference
        object (which writes ``state[node]``/``reward[node]`` per agent)."""
        pos, rew = env_step(
            self._env,
            np.asarray(self.state, dtype=np.int32),
            np.asarray(self.desired_state, dtype=np.int32),
            np.asarray(action, dtype=np.int32),
        )
        self.state[...] = np.asarray(pos)
        self.reward[...] = np.asarray(rew)

    def get_data(self):
        """Reference ``get_data`` (``grid_world.py:66-72``): standardized
        state when ``scaling`` was requested, reward unconditionally /5.
        Statistics in float64, matching the reference's NumPy-default
        precision (``grid_world.py:31-33``)."""
        if self.scaling:
            x, y = np.arange(self.nrow), np.arange(self.ncol)
            mean = np.array([np.mean(x), np.mean(y)])  # float64
            std = np.array([np.std(x), np.std(y)])
            state_scaled = (self.state - mean) / std
        else:
            state_scaled = self.state / 1
        reward_scaled = self.reward / 5
        return state_scaled, reward_scaled

    def close(self) -> None:
        """Reference no-op ``close`` (``grid_world.py:74-75``)."""
