from rcmarl_tpu.envs.grid_world import (  # noqa: F401
    GridWorld,
    env_reset,
    env_step,
    scale_state,
    scale_reward,
)
from rcmarl_tpu.envs.reference_api import ReferenceGridWorld  # noqa: F401

# The env-zoo protocol layer (rcmarl_tpu.envs.api). The grid-world
# names above keep their historical single-env signatures (env_step
# returns a 2-tuple — back-compat for scripts/tests written against
# the seed API); the generic protocol names below are what the
# trainer/serving stack consumes and dispatch over EVERY registered
# world. api.env_reset(GridWorld, key) == env_reset(GridWorld, key).
from rcmarl_tpu.envs.api import (  # noqa: F401
    ENV_REGISTRY,
    env_obs,
    env_reward_scaled,
    env_task,
    env_transition,
    make_env,
)
from rcmarl_tpu.envs.congestion import CongestionWorld  # noqa: F401
from rcmarl_tpu.envs.coverage import CoverageWorld  # noqa: F401
from rcmarl_tpu.envs.pursuit import PursuitWorld  # noqa: F401
