from rcmarl_tpu.envs.grid_world import (  # noqa: F401
    GridWorld,
    env_reset,
    env_step,
    scale_state,
    scale_reward,
)
