from rcmarl_tpu.envs.grid_world import (  # noqa: F401
    GridWorld,
    env_reset,
    env_step,
    scale_state,
    scale_reward,
)
from rcmarl_tpu.envs.reference_api import ReferenceGridWorld  # noqa: F401
