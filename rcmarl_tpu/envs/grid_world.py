"""Pure-functional multi-agent grid-world (cooperative navigation).

TPU-native rebuild of the reference environment
(``environments/grid_world.py:5-75``): instead of a stateful ``gym.Env``
mutated one agent at a time in a Python loop, the environment is a pair of
pure functions ``env_reset`` / ``env_step`` over integer position arrays,
vectorized across agents (and trivially vmappable over batch/seed axes) so
whole episodes run inside one ``lax.scan`` on device.

Behavioral contract (SURVEY.md §7 trap 1): the reference's collision branch
is dead code — ``dist_to_agents = min_j ||state_j - state_node||_1``
includes the agent itself (``grid_world.py:56``) so it is always 0 and the
``dist_to_agents > 0`` branch never fires. The *observed* reward, which we
replicate by default, is::

    reward[i] = 0                          if at goal AND action == stay
              = -(L1 dist BEFORE move) - 1 otherwise

with moves always applied, clipped to the grid (``grid_world.py:52-64``).
The docstring-*intended* collision physics is available behind the opt-in
``collision_physics`` flag (see ``_step_collision``).

Scaling (``grid_world.py:30-35,66-72``): states are standardized with the
mean/std of ``arange(nrow)`` / ``arange(ncol)``; rewards are divided by 5
(a constant, not grid-dependent).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Action table: stay, left, right, down, up (reference grid_world.py:27).
MOVES = np.array([[0, 0], [-1, 0], [1, 0], [0, -1], [0, 1]], dtype=np.int32)
REWARD_SCALE = 5.0  # reference grid_world.py:71


class GridWorld(NamedTuple):
    """Static environment description (closed over by jitted code)."""

    nrow: int = 5
    ncol: int = 5
    n_agents: int = 5
    scaling: bool = True
    collision_physics: bool = False
    #: Reference-exact clipping: the reference clips BOTH coordinates by
    #: nrow-1 (``grid_world.py:55``), which differs from per-axis bounds
    #: only on non-square grids. Default False = evidently-intended
    #: per-axis clip; True reproduces the reference bit-for-bit (needed
    #: for golden parity on nrow != ncol).
    reference_clip: bool = False

    @property
    def clip_hi(self) -> np.ndarray:
        if self.reference_clip:
            return np.array([self.nrow - 1, self.nrow - 1], dtype=np.int32)
        return np.array([self.nrow - 1, self.ncol - 1], dtype=np.int32)

    @property
    def mean_state(self) -> np.ndarray:
        # reference grid_world.py:31-33
        x, y = np.arange(self.nrow), np.arange(self.ncol)
        return np.array([np.mean(x), np.mean(y)], dtype=np.float32)

    @property
    def std_state(self) -> np.ndarray:
        x, y = np.arange(self.nrow), np.arange(self.ncol)
        return np.array([np.std(x), np.std(y)], dtype=np.float32)


def env_reset(env: GridWorld, key: jax.Array) -> jnp.ndarray:
    """Randomized reset: integer positions ~ U{0..nrow-1}x{0..ncol-1}
    (reference grid_world.py:39-40). Returns (n_agents, 2) int32."""
    return jax.random.randint(
        key,
        (env.n_agents, 2),
        jnp.array([0, 0]),
        jnp.array([env.nrow, env.ncol]),
        dtype=jnp.int32,
    )


def _step_observed(
    env: GridWorld, pos: jnp.ndarray, desired: jnp.ndarray, actions: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The reference's *observed* dynamics (dead collision branch elided).

    reference grid_world.py:52-64 with the always-false
    ``dist_to_agents > 0`` branch removed.
    """
    move = jnp.asarray(MOVES)[actions]  # (N, 2)
    dist_before = jnp.sum(jnp.abs(pos - desired), axis=1)  # (N,)
    # Per-axis clip by default; env.reference_clip reproduces the
    # reference's both-axes-nrow bound (grid_world.py:55) exactly.
    npos = jnp.clip(pos + move, 0, jnp.asarray(env.clip_hi))
    at_goal_stay = (dist_before == 0) & (actions == 0)
    reward = jnp.where(at_goal_stay, 0.0, -(dist_before.astype(jnp.float32)) - 1.0)
    return npos, reward


def _step_collision(
    env: GridWorld, pos: jnp.ndarray, desired: jnp.ndarray, actions: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Opt-in *intended* semantics per the reference docstring
    (grid_world.py:8-9): an agent landing on a cell occupied by any OTHER
    agent (after simultaneous moves) gets the dense ``-dist_next`` shaping
    reward replaced by the stay penalty; all agents still move (moves are
    clipped to the grid)."""
    move = jnp.asarray(MOVES)[actions]
    dist_before = jnp.sum(jnp.abs(pos - desired), axis=1)
    npos = jnp.clip(pos + move, 0, jnp.asarray(env.clip_hi))
    dist_next = jnp.sum(jnp.abs(npos - desired), axis=1)
    # pairwise L1 distances after the move, self excluded
    pair = jnp.sum(jnp.abs(npos[:, None, :] - npos[None, :, :]), axis=-1)
    pair = pair + jnp.eye(env.n_agents, dtype=pair.dtype) * 10**6
    alone = jnp.min(pair, axis=1) > 0
    at_goal_stay = (dist_before == 0) & (actions == 0)
    reward = jnp.where(
        alone,
        -dist_next.astype(jnp.float32),
        jnp.where(at_goal_stay, 0.0, -(dist_before.astype(jnp.float32)) - 1.0),
    )
    return npos, reward


def env_step(
    env: GridWorld, pos: jnp.ndarray, desired: jnp.ndarray, actions: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One synchronous step for all agents.

    Args:
      pos: (n_agents, 2) int32 unscaled positions.
      desired: (n_agents, 2) int32 goal positions.
      actions: (n_agents,) int32 in [0, 5).

    Returns:
      (new_pos, reward) with reward UNscaled (scaling is applied by
      ``scale_reward``, mirroring reference ``get_data``).
    """
    if env.collision_physics:
        return _step_collision(env, pos, desired, actions)
    return _step_observed(env, pos, desired, actions)


def scale_state(env: GridWorld, pos: jnp.ndarray) -> jnp.ndarray:
    """(pos - mean)/std per axis (reference grid_world.py:70)."""
    if not env.scaling:
        return pos.astype(jnp.float32)
    return (pos.astype(jnp.float32) - env.mean_state) / env.std_state


def scale_reward(env: GridWorld, reward: jnp.ndarray) -> jnp.ndarray:
    """reward / 5 — applied unconditionally in the reference's ``get_data``
    regardless of the ``scaling`` flag (grid_world.py:71)."""
    return reward / REWARD_SCALE
