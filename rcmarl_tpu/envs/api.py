"""The env-zoo protocol: a registry of pure-functional JAX-native envs.

Every environment in the zoo is the same shape the grid world pioneered
(:mod:`rcmarl_tpu.envs.grid_world`): a STATIC, hashable world
description (a NamedTuple of Python scalars, closed over by jitted
code — the world is part of the compile key exactly like the Config)
plus pure functions over integer state arrays. The protocol, generic
over every env:

- ``make_env(cfg)``       — registry dispatch on ``Config.env``;
- ``env_reset(env, key)`` — initial agent state, ``(N, n_states)`` int32;
- ``env_task(env, key)``  — the task layout drawn at run start (goals /
  landmarks / evader start — the array living in TrainState's
  ``desired`` slot), same ``(N, n_states)`` int32 layout;
- ``env_transition(env, pos, task, actions)`` →
  ``(new_pos, new_task, reward)`` — ONE synchronous vectorized step for
  all agents. The task rides the rollout scan carry, so envs whose task
  state evolves inside an episode (the pursuit evader) fit the same
  compiled program as envs with static tasks (for which
  ``new_task is task`` and XLA carries it for free);
- ``env_obs(env, pos)``   — the scaled observation (the grid-family
  standardization: per-axis ``(pos - mean(arange)) / std(arange)``);
- ``env_reward_scaled(env, r)`` — the shared ``/5`` reward scale.

Dispatch is by the world's TYPE at trace time (the env is jit-static),
so the generic layer costs nothing in the compiled program and the
rollout/trainer/serving stack is written once against this API
(:mod:`rcmarl_tpu.training.rollout` and everything above it).

The registry keys are pinned to :data:`rcmarl_tpu.config.ENV_NAMES`
(jax-free, so Config validation and CLI choices never import an env
module); tests assert the two stay identical.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rcmarl_tpu.config import ENV_NAMES, Config
from rcmarl_tpu.envs import congestion, coverage, grid_world, pursuit
from rcmarl_tpu.envs.congestion import CongestionWorld
from rcmarl_tpu.envs.coverage import CoverageWorld
from rcmarl_tpu.envs.grid_world import REWARD_SCALE, GridWorld
from rcmarl_tpu.envs.pursuit import PursuitWorld


def _make_grid_world(cfg: Config) -> GridWorld:
    return GridWorld(
        nrow=cfg.nrow,
        ncol=cfg.ncol,
        n_agents=cfg.n_agents,
        scaling=cfg.scaling,
        collision_physics=cfg.collision_physics,
        reference_clip=cfg.reference_clip,
    )


def _make_pursuit(cfg: Config) -> PursuitWorld:
    return PursuitWorld(
        nrow=cfg.nrow, ncol=cfg.ncol, n_agents=cfg.n_agents,
        scaling=cfg.scaling,
    )


def _make_coverage(cfg: Config) -> CoverageWorld:
    return CoverageWorld(
        nrow=cfg.nrow, ncol=cfg.ncol, n_agents=cfg.n_agents,
        scaling=cfg.scaling,
    )


def _make_congestion(cfg: Config) -> CongestionWorld:
    return CongestionWorld(
        nrow=cfg.nrow, ncol=cfg.ncol, n_agents=cfg.n_agents,
        scaling=cfg.scaling,
        congestion_weight=cfg.congestion_weight,
    )


#: ``Config.env`` name -> world constructor. Keys are pinned to
#: config.ENV_NAMES (tests/test_envs.py).
ENV_REGISTRY = {
    "grid_world": _make_grid_world,
    "pursuit": _make_pursuit,
    "coverage": _make_coverage,
    "congestion": _make_congestion,
}

assert tuple(ENV_REGISTRY) == ENV_NAMES, (
    "envs/api.py ENV_REGISTRY drifted from config.ENV_NAMES"
)


def make_env(cfg: Config):
    """The registry dispatch: ``cfg.env`` -> static world description.

    ``Config.env='grid_world'`` (the default) builds exactly the
    GridWorld the trainer always built — the pinned seed behavior."""
    try:
        return ENV_REGISTRY[cfg.env](cfg)
    except KeyError:
        raise ValueError(
            f"Config.env={cfg.env!r} is not a registered environment; "
            f"expected one of {tuple(ENV_REGISTRY)}"
        ) from None


def env_reset(env, key: jax.Array) -> jnp.ndarray:
    """Initial agent state for any registered world: (N, n_states) int32."""
    if isinstance(env, GridWorld):
        return grid_world.env_reset(env, key)
    if isinstance(env, PursuitWorld):
        return pursuit.env_reset(env, key)
    if isinstance(env, CoverageWorld):
        return coverage.env_reset(env, key)
    if isinstance(env, CongestionWorld):
        return congestion.env_reset(env, key)
    raise TypeError(f"not a registered env world: {type(env).__name__}")


def env_task(env, key: jax.Array) -> jnp.ndarray:
    """The run-start task layout (TrainState's ``desired`` slot). For
    the grid world this IS ``env_reset`` — bit-for-bit the seed's goal
    draw."""
    if isinstance(env, GridWorld):
        return grid_world.env_reset(env, key)
    if isinstance(env, PursuitWorld):
        return pursuit.env_task(env, key)
    if isinstance(env, CoverageWorld):
        return coverage.env_task(env, key)
    if isinstance(env, CongestionWorld):
        return congestion.env_task(env, key)
    raise TypeError(f"not a registered env world: {type(env).__name__}")


def env_transition(
    env, pos: jnp.ndarray, task: jnp.ndarray, actions: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One synchronous step: ``(new_pos, new_task, reward)`` with the
    reward UNscaled (:func:`env_reward_scaled` applies the shared
    scale, mirroring the grid world's ``get_data`` split). Envs with
    static tasks return ``task`` unchanged."""
    if isinstance(env, GridWorld):
        npos, reward = grid_world.env_step(env, pos, task, actions)
        return npos, task, reward
    if isinstance(env, PursuitWorld):
        return pursuit.env_step(env, pos, task, actions)
    if isinstance(env, CoverageWorld):
        return coverage.env_step(env, pos, task, actions)
    if isinstance(env, CongestionWorld):
        return congestion.env_step(env, pos, task, actions)
    raise TypeError(f"not a registered env world: {type(env).__name__}")


def env_transition_scaled(
    env, pos: jnp.ndarray, task: jnp.ndarray, actions: jnp.ndarray,
    task_scale: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """:func:`env_transition` with the traced Diff-DAC task knob.

    The congestion world scales its toll by ``task_scale``
    (:func:`rcmarl_tpu.envs.congestion.env_step_scaled` —
    ``CellSpec.task_scale``, one load level per vmapped replica); every
    other world has no load knob and ignores the scale. ``task_scale ==
    1.0`` is bitwise :func:`env_transition` for every world."""
    if isinstance(env, CongestionWorld):
        return congestion.env_step_scaled(env, pos, task, actions,
                                          task_scale)
    return env_transition(env, pos, task, actions)


def env_obs(env, pos: jnp.ndarray) -> jnp.ndarray:
    """The scaled observation: per-axis ``(pos - mean)/std`` of
    ``arange(nrow)`` / ``arange(ncol)`` when ``env.scaling``, else a
    plain float cast — the grid family shares one standardization
    (every zoo world lives on the same integer grid)."""
    if isinstance(env, GridWorld):
        return grid_world.scale_state(env, pos)  # the pinned seed path
    if not env.scaling:
        return pos.astype(jnp.float32)
    x, y = np.arange(env.nrow), np.arange(env.ncol)
    mean = np.array([np.mean(x), np.mean(y)], dtype=np.float32)
    std = np.array([np.std(x), np.std(y)], dtype=np.float32)
    return (pos.astype(jnp.float32) - mean) / std


def env_reward_scaled(env, reward: jnp.ndarray) -> jnp.ndarray:
    """``reward / 5`` — the shared scale convention, applied
    unconditionally like the reference's ``get_data``
    (:func:`rcmarl_tpu.envs.grid_world.scale_reward`)."""
    return reward / REWARD_SCALE
