"""Pursuit-evasion: the team chases a deterministically fleeing evader.

JAX-native member of the env zoo (``rcmarl_tpu.envs.api``): the same
pure-functional shape as :mod:`rcmarl_tpu.envs.grid_world` — a static
hashable world description closed over by jitted code, integer
positions, one synchronous vectorized step — but the TASK state (the
evader) evolves inside the episode, which is why the env protocol
threads the task through the rollout scan carry
(:func:`rcmarl_tpu.envs.api.env_transition`).

Dynamics, per step (all simultaneous):

1. every agent applies its move (grid-world action table, clipped);
2. the evader — the shared task state, one position broadcast to every
   task row — flees DETERMINISTICALLY: among the five candidate moves
   (clipped) it takes the one maximizing its distance to the nearest
   pursuer (min over agents of the L1 distance; stable first-max
   tie-break). No RNG: the step is a pure function of
   ``(pos, task, actions)``, so dynamics determinism is exact;
3. a capture pins the evader: when some pursuer stands on the evader's
   cell after the moves, the evader does not flee this step.

Reward (cooperative, grid-world-shaped so the critic scales carry
over): ``0`` for agent i when the team has the evader caught
(min distance 0), else ``-(L1 distance of agent i to the evader) - 1``
— bounded in ``[-(nrow + ncol - 1), 0]``, scaled by the shared ``/5``
convention (:data:`rcmarl_tpu.envs.grid_world.REWARD_SCALE`).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from rcmarl_tpu.envs.grid_world import MOVES


class PursuitWorld(NamedTuple):
    """Static environment description (closed over by jitted code)."""

    nrow: int = 5
    ncol: int = 5
    n_agents: int = 5
    scaling: bool = True


def env_reset(env: PursuitWorld, key: jax.Array) -> jnp.ndarray:
    """Pursuer positions ~ U over the grid. (n_agents, 2) int32."""
    return jax.random.randint(
        key,
        (env.n_agents, 2),
        jnp.array([0, 0]),
        jnp.array([env.nrow, env.ncol]),
        dtype=jnp.int32,
    )


def env_task(env: PursuitWorld, key: jax.Array) -> jnp.ndarray:
    """The evader's start cell, broadcast to every task row — the task
    array keeps the protocol's (n_agents, 2) int32 layout (TrainState's
    ``desired`` slot) with all rows identical."""
    e = jax.random.randint(
        key, (2,), jnp.array([0, 0]), jnp.array([env.nrow, env.ncol]),
        dtype=jnp.int32,
    )
    return jnp.broadcast_to(e, (env.n_agents, 2)).astype(jnp.int32)


def env_step(
    env: PursuitWorld,
    pos: jnp.ndarray,
    task: jnp.ndarray,
    actions: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One synchronous step. Returns (new_pos, new_task, reward)."""
    clip_hi = jnp.array([env.nrow - 1, env.ncol - 1], dtype=jnp.int32)
    move = jnp.asarray(MOVES)[actions]
    npos = jnp.clip(pos + move, 0, clip_hi)
    evader = task[0]
    # the evader's five candidate cells (stay/left/right/down/up), clipped
    cand = jnp.clip(evader[None, :] + jnp.asarray(MOVES), 0, clip_hi)  # (5, 2)
    # distance of each candidate to its NEAREST pursuer (after the moves)
    d = jnp.sum(jnp.abs(cand[None, :, :] - npos[:, None, :]), axis=-1)  # (N, 5)
    nearest = jnp.min(d, axis=0)  # (5,)
    flee = cand[jnp.argmax(nearest)]
    dist_now = jnp.sum(jnp.abs(npos - evader[None, :]), axis=1)  # (N,)
    caught = jnp.min(dist_now) == 0
    new_evader = jnp.where(caught, evader, flee)
    dist = jnp.sum(jnp.abs(npos - new_evader[None, :]), axis=1)  # (N,)
    reward = jnp.where(caught, 0.0, -(dist.astype(jnp.float32)) - 1.0)
    ntask = jnp.broadcast_to(new_evader, (env.n_agents, 2)).astype(jnp.int32)
    return npos, ntask, reward
