"""Backend purity / dtype audit — jaxpr-level contracts of the kernels.

The cross-backend bitwise guarantee (PARITY.md, tests/test_faults.py)
only holds if every aggregation backend stays a PURE, deterministic,
transfer-free function of its inputs with exact dtype preservation. A
callback smuggled into a kernel, a stateful-RNG primitive, or a
``weak_type``/dtype drift between two backends would break the pin in
ways unit tests only catch for the shapes they enumerate. This audit
walks the actual jaxprs:

- every mode in :data:`rcmarl_tpu.ops.aggregation.AUDIT_BACKEND_MODES`
  (the six-backend contract table), with and without ``sanitize``,
  traced over a representative two-leaf message tree;
- both netstack arms' full guarded update-block jaxprs
  (``netstack=True``/``False`` under an active fault plan + sanitize,
  traced once via the shared
  :func:`rcmarl_tpu.utils.profiling.entry_jaxprs`) — asserting
  identical output structure/shape/dtype, so the stacked and
  dual-launch programs cannot drift apart at the type level.

Findings: ``backend-impure`` (forbidden primitive in a jaxpr) and
``backend-dtype-drift`` (dtype/weak-type change, or cross-arm aval
mismatch). Anchored to the owning module; no pragma escape.
"""

from __future__ import annotations

from typing import List

from rcmarl_tpu.lint.findings import Finding

#: Primitives that must never appear in a consensus/epoch jaxpr: host
#: callbacks and device->host transfers (the bitwise pin cannot survive
#: a host round trip) and XLA's stateful RNG (nondeterministic across
#: runs/backends; all sanctioned randomness is keyed threefry). Note
#: ``device_put`` is NOT here: in a jaxpr it is host-constant placement
#: ONTO the device (static config tables entering the program), the
#: benign direction.
FORBIDDEN_PRIMITIVES = frozenset(
    {
        "pure_callback",
        "io_callback",
        "debug_callback",
        "callback",
        "infeed",
        "outfeed",
        "rng_uniform",
        "copy_to_host",
    }
)

_AGG_ANCHOR = "rcmarl_tpu/ops/aggregation.py"
_EPOCH_ANCHOR = "rcmarl_tpu/training/update.py"


def _walk_primitives(jaxpr, acc=None):
    """All primitive names in a jaxpr, recursing into sub-jaxprs
    (scan/cond/pjit/pallas bodies)."""
    acc = set() if acc is None else acc
    for eqn in jaxpr.eqns:
        acc.add(eqn.primitive.name)
        for v in eqn.params.values():
            items = v if isinstance(v, (list, tuple)) else [v]
            for item in items:
                inner = getattr(item, "jaxpr", None)
                if inner is not None:
                    _walk_primitives(inner, acc)
                elif hasattr(item, "eqns"):
                    _walk_primitives(item, acc)
    return acc


def _out_signature(closed_jaxpr):
    return tuple(
        (tuple(v.aval.shape), str(v.aval.dtype), bool(getattr(v.aval, "weak_type", False)))
        for v in closed_jaxpr.jaxpr.outvars
    )


def _audit_aggregation() -> List[Finding]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rcmarl_tpu.ops.aggregation import (
        AUDIT_BACKEND_MODES,
        resilient_aggregate_tree,
    )

    findings: List[Finding] = []
    tree = {
        "w": jnp.ones((5, 3, 4), jnp.float32),
        "b": jnp.ones((5, 7), jnp.float32),
    }
    valid = jnp.asarray(np.array([1.0, 1.0, 1.0, 1.0, 0.0]), jnp.float32)
    signatures = {}
    for name, recipe in AUDIT_BACKEND_MODES:
        for sanitize in (False, True):
            kwargs = {"impl": recipe["impl"], "sanitize": sanitize}
            H = jnp.asarray(1, jnp.int32) if recipe.get("traced_h") else 1
            if recipe.get("masked"):
                kwargs["valid"] = valid
            label = f"{name}{'+sanitize' if sanitize else ''}"
            closed = jax.make_jaxpr(
                lambda t, kw=kwargs, h=H: resilient_aggregate_tree(t, h, **kw)
            )(tree)
            bad = _walk_primitives(closed.jaxpr) & FORBIDDEN_PRIMITIVES
            if bad:
                findings.append(
                    Finding(
                        "backend-impure",
                        _AGG_ANCHOR,
                        1,
                        f"backend {label}: forbidden primitive(s) "
                        f"{sorted(bad)} in the aggregation jaxpr",
                    )
                )
            sig = _out_signature(closed)
            for shape, dtype, weak in sig:
                if dtype != "float32" or weak:
                    findings.append(
                        Finding(
                            "backend-dtype-drift",
                            _AGG_ANCHOR,
                            1,
                            f"backend {label}: output aval "
                            f"({shape}, {dtype}, weak={weak}) drifts from "
                            "the exact strong-f32 contract",
                        )
                    )
            signatures.setdefault(sanitize, {})[name] = sig
    for sanitize, by_name in signatures.items():
        ref_name, ref_sig = next(iter(by_name.items()))
        for name, sig in by_name.items():
            if sig != ref_sig:
                findings.append(
                    Finding(
                        "backend-dtype-drift",
                        _AGG_ANCHOR,
                        1,
                        f"backends {ref_name} and {name} disagree on "
                        f"output avals (sanitize={sanitize}): the "
                        "cross-backend bitwise pin cannot hold across "
                        "differing types",
                    )
                )
    return findings


def _netstack_cfg(netstack: bool):
    from rcmarl_tpu.lint.configs import tiny_faulted_cfg

    return tiny_faulted_cfg(netstack)


def _audit_netstack_arms() -> List[Finding]:
    """Walk the full guarded UPDATE-BLOCK jaxpr of each netstack arm —
    the whole entry point (epoch scan + actor phase + fault plumbing),
    not just the epoch — via the shared memoized
    :func:`rcmarl_tpu.utils.profiling.entry_jaxprs`, so repeat audits
    in one process never re-trace and the cost arm shares the same
    tiny-input pipeline."""
    import jax

    from rcmarl_tpu.utils.profiling import entry_jaxprs, entry_out_shapes

    findings: List[Finding] = []
    arms = {}
    shapes = {}
    for netstack in (False, True):
        cfg = _netstack_cfg(netstack)
        arm = "stacked" if netstack else "dual"
        closed = entry_jaxprs(cfg, with_diag=True, names=("update_block",))[
            "update_block"
        ]
        shapes[arm] = entry_out_shapes(
            cfg, with_diag=True, names=("update_block",)
        )["update_block"]
        bad = _walk_primitives(closed.jaxpr) & FORBIDDEN_PRIMITIVES
        if bad:
            findings.append(
                Finding(
                    "backend-impure",
                    _EPOCH_ANCHOR,
                    1,
                    f"netstack {arm} arm: forbidden primitive(s) "
                    f"{sorted(bad)} in the guarded update-block jaxpr",
                )
            )
        arms[arm] = _out_signature(closed)
    # flat avals (shape/dtype/weak) off the jaxpr, PLUS the original
    # output pytree: a re-nesting with identical flat leaves is still
    # structure drift (tree.map raises ValueError on mismatch)
    try:
        same_tree = jax.tree.all(
            jax.tree.map(
                lambda a, b: tuple(a.shape) == tuple(b.shape)
                and a.dtype == b.dtype,
                shapes["dual"],
                shapes["stacked"],
            )
        )
    except ValueError:  # structure mismatch
        same_tree = False
    if arms["dual"] != arms["stacked"] or not same_tree:
        findings.append(
            Finding(
                "backend-dtype-drift",
                _EPOCH_ANCHOR,
                1,
                "netstack arms disagree on guarded update-block output "
                "structure/shapes/dtypes: the stacked and dual-launch "
                "programs have drifted apart at the type level",
            )
        )
    return findings


def audit_backends() -> List[Finding]:
    """``lint --backends``: the full jaxpr-level purity/dtype audit —
    all six aggregation backends (× sanitize) plus both netstack arms'
    guarded update blocks. Tracing only, apart from the tiny shared
    input pipeline (one rollout compile per arm config, memoized across
    the audit arms); runs on any host."""
    return _audit_aggregation() + _audit_netstack_arms()
