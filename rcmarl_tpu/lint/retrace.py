"""Retrace auditor — the compile-once contract, enforced at runtime.

Podracer-style throughput (arXiv:2104.06272, PERF.md) assumes the
steady-state loop re-dispatches ONE compiled program per entry point:
a shape drift, an unhashable static arg, or a Python-value knob that
changes per block silently turns every block into a recompile, and the
regression surfaces only as mysterious wall-clock (DRIFT.md's week).
This module makes the contract mechanical:

- :class:`RetraceAuditor` — snapshot the tracing-cache sizes of the
  registered jitted entry points
  (:func:`rcmarl_tpu.utils.profiling.jit_entry_points`), run arbitrary
  code under :meth:`~RetraceAuditor.expect_no_compiles`, and get a
  ``retrace`` finding for every entry point that compiled again —
  naming the offender and, via jax's cache-miss explanations, the
  argument that changed.
- :func:`audit_retrace` — the ``lint --retrace`` mode: tiny
  guarded+faulted train runs on BOTH netstack arms plus a clean
  (donated-path) run; one warmup block compiles, every later block must
  hit the cache.

Retrace findings have no pragma escape: a retracing entry point is a
broken contract, not a style choice.
"""

from __future__ import annotations

import contextlib
import io
import logging
import re
from pathlib import Path
from typing import Dict, List, Optional

from rcmarl_tpu.lint.findings import Finding

_MISS = re.compile(r"TRACING CACHE MISS.*?because:\n((?:\s+.*\n?)*)")


def _anchor(fn) -> tuple:
    """(path, line) of a jitted entry point's wrapped function, with
    the path relativized to the package parent so retrace findings use
    the same 'rcmarl_tpu/…' display convention as every other layer."""
    from rcmarl_tpu.lint.findings import package_root

    wrapped = getattr(fn, "__wrapped__", fn)
    code = getattr(wrapped, "__code__", None)
    if code is None:
        return "<jit>", 1
    path = Path(code.co_filename)
    try:
        path = path.relative_to(package_root().parent)
    except ValueError:
        pass
    return str(path), code.co_firstlineno


class RetraceAuditor:
    """Compile-count watchdog over the jitted entry points."""

    def __init__(self, entries: Optional[Dict[str, object]] = None) -> None:
        if entries is None:
            from rcmarl_tpu.utils.profiling import jit_entry_points

            entries = jit_entry_points()
        for name, fn in entries.items():
            if not hasattr(fn, "_cache_size"):
                raise RuntimeError(
                    f"entry point {name!r} exposes no _cache_size(); "
                    "this jax version cannot be audited"
                )
        self.entries = dict(entries)
        self.findings: List[Finding] = []

    def snapshot(self) -> Dict[str, int]:
        return {k: int(f._cache_size()) for k, f in self.entries.items()}

    @contextlib.contextmanager
    def expect_no_compiles(self, context: str = ""):
        """Fail (as findings) any entry-point compile inside the block.

        Enables ``jax_explain_cache_misses`` and captures jax's log so
        a finding can say WHAT changed, not just who recompiled.
        """
        import jax

        before = self.snapshot()
        logger = logging.getLogger("jax")
        buf = io.StringIO()
        handler = logging.StreamHandler(buf)
        handler.setLevel(logging.WARNING)
        prev_level = logger.level
        prev_explain = jax.config.jax_explain_cache_misses
        prev_propagate = logger.propagate
        prev_handlers = list(logger.handlers)
        jax.config.update("jax_explain_cache_misses", True)
        # capture, don't spray: jax hangs its own stderr StreamHandler
        # directly on the 'jax' logger, so the explanations would double
        # as console noise unless the handler list is swapped wholesale;
        # they belong in findings, not on the audited run's stderr
        logger.handlers = [handler]
        logger.propagate = False
        if logger.getEffectiveLevel() > logging.WARNING:
            logger.setLevel(logging.WARNING)
        try:
            yield self
        finally:
            logger.handlers = prev_handlers
            logger.setLevel(prev_level)
            logger.propagate = prev_propagate
            jax.config.update("jax_explain_cache_misses", prev_explain)
        after = self.snapshot()
        explanations = buf.getvalue()
        for name, fn in self.entries.items():
            grew = after[name] - before[name]
            if grew <= 0:
                continue
            path, line = _anchor(fn)
            why = self._explanation(explanations, fn)
            ctx = f" during {context}" if context else ""
            self.findings.append(
                Finding(
                    "retrace",
                    path,
                    line,
                    f"{name} compiled {grew} more time(s) after warmup"
                    f"{ctx}: the steady-state loop must reuse ONE "
                    "program per entry point"
                    + (f" — jax explains: {why}" if why else ""),
                )
            )

    @staticmethod
    def _explanation(captured: str, fn) -> str:
        """The first cache-miss explanation mentioning the entry's
        wrapped function, compressed to one line."""
        wrapped = getattr(fn, "__wrapped__", fn)
        target = getattr(wrapped, "__name__", "")
        best = ""
        for m in _MISS.finditer(captured):
            reason = " ".join(m.group(1).split())
            if target and target in m.group(0):
                return reason[:300]
            best = best or reason
        return best[:300]


def _tiny_cfg(netstack, faulted: bool):
    from rcmarl_tpu.lint.configs import tiny_cfg, tiny_faulted_cfg

    if faulted:
        return tiny_faulted_cfg(netstack)
    return tiny_cfg(netstack=netstack)


def _audit_fitstack_dtypes(
    auditor: "RetraceAuditor", steady_blocks: int
) -> List[Finding]:
    """The alternating-dtype compile-once case: the fused fit entry
    (``fit_block``) driven over a float32 and a bfloat16 config must
    land in exactly TWO distinct jit-cache entries (compute_dtype is
    jit-static, so the dtypes may never share — or leak into — a
    program), and steady-state alternation between them must hit the
    caches with zero recompiles."""
    from rcmarl_tpu.lint.configs import tiny_cfg
    from rcmarl_tpu.training.update import fit_block, team_average_reward
    from rcmarl_tpu.utils.profiling import entry_point_inputs

    findings: List[Finding] = []
    calls = []
    # the tiny all-coop config: dtype-cache separation is what this
    # case proves (the mixed-cast fused program's coverage lives in the
    # AUDIT.jsonl fitstack/fitstack_bf16 cost arms)
    for cfg in (
        tiny_cfg(fitstack=True),
        tiny_cfg(fitstack=True, compute_dtype="bfloat16"),
    ):
        state, batch, _, key = entry_point_inputs(cfg)
        p = state.params
        calls.append((
            cfg,
            (p.critic, p.tr, p.critic_local),
            batch,
            team_average_reward(cfg, batch.r),
            key,
        ))
    before = int(fit_block._cache_size())
    for args in calls:  # warmup: one compile per compute_dtype
        fit_block(*args)
    grew = int(fit_block._cache_size()) - before
    if grew != 2:
        path, line = _anchor(fit_block)
        findings.append(
            Finding(
                "retrace",
                path,
                line,
                f"fit_block compiled {grew} program(s) for the "
                "f32/bf16 config pair — expected exactly one per "
                "compute_dtype (distinct jit caches, no dtype sharing)",
            )
        )
    with auditor.expect_no_compiles(context="alternating f32/bf16 fused fits"):
        for _ in range(steady_blocks):
            for args in calls:
                fit_block(*args)
    return findings


def _audit_scanned_window(
    auditor: "RetraceAuditor", steady_blocks: int
) -> List[Finding]:
    """The stacked-schedule scan compile-once case: a scheduled config
    (``graph_every=2``) drives ``train_window_donated`` — S blocks per
    launch with the ``(S, N, degree)`` window
    (:func:`rcmarl_tpu.config.schedule_window`) as scan data — across
    successive windows whose content DIFFERS (each spans a
    ``graph_every`` resample boundary). One warmup launch compiles; every
    later window must re-dispatch the SAME executable — the window is
    data, so crossing a resample boundary may never be a compile.
    ``train_window_donated`` is deliberately not in the
    ``jit_entry_points`` registry (its inputs are window-shaped, not the
    registry's per-config shapes), so its cache is checked by hand, the
    ``_audit_fitstack_dtypes`` pattern; the registry watchdog still
    covers the inner ``update_block`` family."""
    import jax

    from rcmarl_tpu.config import schedule_window
    from rcmarl_tpu.training.trainer import (
        init_train_state,
        train_window_donated,
    )

    cfg = _tiny_cfg(False, False).replace(
        graph_schedule="random_geometric", graph_degree=3, graph_every=2
    )
    S = 3  # odd window: every window straddles a graph_every boundary
    state = init_train_state(cfg, jax.random.PRNGKey(0))
    findings: List[Finding] = []
    before = int(train_window_donated._cache_size())
    state, _ = train_window_donated(
        cfg, state, S, schedule_window(cfg, 0, S)
    )  # warmup: the one compile
    with auditor.expect_no_compiles(
        context="stacked-schedule windows across resample boundaries"
    ):
        for w in range(1, steady_blocks + 1):
            state, _ = train_window_donated(
                cfg, state, S, schedule_window(cfg, w * S, S)
            )
    grew = int(train_window_donated._cache_size()) - before
    if grew != 1:
        path, line = _anchor(train_window_donated)
        findings.append(
            Finding(
                "retrace",
                path,
                line,
                f"train_window_donated compiled {grew} program(s) "
                f"across {steady_blocks + 1} stacked-schedule windows — "
                "expected exactly ONE (window content is data; a "
                "resample boundary may never be a compile)",
            )
        )
    return findings


def audit_retrace(
    steady_blocks: int = 2,
    fitstack_dtypes: bool = True,
    fused_epoch: bool = True,
    fused_serve: bool = True,
    gala: bool = True,
    scanned_window: bool = True,
) -> List[Finding]:
    """``lint --retrace``: prove exactly-once compilation on tiny runs.

    The cases cover the production paths: a guarded+faulted run on the
    dual arm and on the stacked arms (netstack phase II fed by the
    fused fitstack phase I, mixed cast — the undonated retry-capable
    entries, diag on), the ONE-KERNEL epoch arm
    (``consensus_impl='pallas_fused_interpret'`` +
    ``fitstack='pallas_interpret'``, guarded+faulted — the fused epoch
    compiles exactly once, zero steady-state recompiles; gate with
    ``fused_epoch=False`` to shed it to the slow twin / CI cell), a
    time-varying-graph run (per-block resampled
    random-geometric gather indices fed in as data — a resample may
    never be a compile), the STACKED-SCHEDULE scan (S scheduled blocks
    per donated ``train_window_donated`` launch with the ``(S, N, deg)``
    window as scan data — one compile, zero recompiles across window
    boundaries that straddle a ``graph_every`` resample; gate with
    ``scanned_window=False`` to shed it —
    :func:`_audit_scanned_window`), a clean run (the donated
    steady-state entries),
    the alternating f32/bf16 fused-fit case (exactly one compile per
    compute_dtype, zero steady-state recompiles across alternation —
    :func:`_audit_fitstack_dtypes`), and a Byzantine gossip-replica
    run (the gossip_mix_block entry must re-dispatch one executable
    per round), the ONE-KERNEL serving path (the fused
    forward+keys+sample program, interpret arm — one compile per
    sample/greedy arm, zero recompiles across batches, hot-swaps, and
    fleet re-routes; gate with ``fused_serve=False`` to shed it to the
    slow twin / CI cell), and the autoscale resize discipline (each
    resized serving batch shape compiles exactly ONCE, steady
    alternation across shapes recompiles nothing — a controller resize
    is a cache hit after first sight, never a steady-state recompile).
    Each trains ONE warmup block/round outside the watchdog, then
    ``steady_blocks`` more inside it — any further compile is a
    ``retrace`` finding naming the entry point and jax's explanation of
    what changed.
    """
    import jax

    from rcmarl_tpu.lint.configs import tiny_gossip_cfg
    from rcmarl_tpu.parallel.gossip import train_gossip
    from rcmarl_tpu.training.trainer import train

    auditor = RetraceAuditor()
    cases = [
        ("faulted+guarded, netstack off", _tiny_cfg(False, True)),
        # the time-varying communication graph: every block gets a
        # FRESH random-geometric gather-index array (same shape, new
        # values — data, not program structure), so a resample may
        # never be a compile. This is the env-zoo acceptance proof
        # that indices-as-data works (config.scheduled_in_nodes).
        (
            "per-block resampled communication graph",
            _tiny_cfg(False, False).replace(
                graph_schedule="random_geometric", graph_degree=3
            ),
        ),
        # one stacked case covers BOTH stacked arms: fused cross-flavor
        # phase-I fits (fitstack) feeding the combined netstack
        # phase-II block. Compile-once discipline is role-independent
        # (the mixed-cast fused program's cost/dtype coverage lives in
        # the AUDIT.jsonl fitstack arms), so the case stays on the
        # tiny all-coop config to keep the tier-1 wall budget.
        (
            "faulted+guarded, netstack+fitstack on",
            _tiny_cfg(True, True).replace(fitstack=True),
        ),
        ("clean donated, netstack off", _tiny_cfg(False, False)),
    ]
    if fused_epoch:
        # the ONE-KERNEL epoch (interpret arm): fused phase-II Pallas
        # consensus + fit-scan kernel phase I, guarded+faulted+sanitize
        # — the fused programs must compile exactly once and re-dispatch
        # across steady blocks like every other arm (``fused_epoch=
        # False`` lets the tier-1 pytest wrapper shed it to the slow
        # twin + the CI graftlint cell, the fitstack_dtypes pattern)
        cases.append(
            (
                "faulted+guarded, one-kernel epoch (pallas_fused)",
                _tiny_cfg(True, True).replace(
                    consensus_impl="pallas_fused_interpret",
                    fitstack="pallas_interpret",
                ),
            )
        )
    for label, cfg in cases:
        state, _ = train(cfg, n_episodes=cfg.n_ep_fixed)  # warmup: compiles
        with auditor.expect_no_compiles(context=label):
            train(
                cfg,
                n_episodes=cfg.n_ep_fixed * steady_blocks,
                state=state,
            )
    if fitstack_dtypes:
        # ``fitstack_dtypes=False`` lets the tier-1 pytest wrapper skip
        # this (wall budget); the CI graftlint cell's `lint --retrace`
        # always runs it
        auditor.findings.extend(
            _audit_fitstack_dtypes(auditor, steady_blocks)
        )
    if scanned_window:
        # the stacked-schedule scan: S blocks per donated launch, fresh
        # window data every dispatch — ``scanned_window=False`` sheds it
        # to the slow twin / CI graftlint cell, the fused_epoch pattern
        auditor.findings.extend(
            _audit_scanned_window(auditor, steady_blocks)
        )
    gcfg = tiny_gossip_cfg()
    states, df = train_gossip(gcfg, n_episodes=gcfg.n_ep_fixed)  # warmup round
    with auditor.expect_no_compiles(context="byzantine gossip replicas"):
        train_gossip(
            gcfg,
            n_episodes=gcfg.n_ep_fixed * steady_blocks,
            states=states,
            start_round=df.attrs["gossip"]["gossip_round"],
        )
    auditor.findings.extend(_audit_serve(auditor, steady_blocks))
    auditor.findings.extend(_audit_fleet(auditor, steady_blocks))
    _audit_pipeline(auditor, steady_blocks)
    if gala:
        # the composed pipelined-gossip-fleet case — ``gala=False``
        # lets the tier-1 pytest wrapper shed it to the slow twin /
        # CI graftlint cell, the fused_epoch pattern
        _audit_gala(auditor, steady_blocks)
    if fused_serve:
        # the ONE-KERNEL serving path (interpret arm) + the autoscale
        # resize discipline — ``fused_serve=False`` lets the tier-1
        # pytest wrapper shed both to the slow twin / CI graftlint
        # cell, the fused_epoch pattern
        auditor.findings.extend(_audit_fused_serve(auditor, steady_blocks))
        auditor.findings.extend(
            _audit_autoscale_resize(auditor, steady_blocks)
        )
    return auditor.findings


def _audit_pipeline(auditor: "RetraceAuditor", steady_blocks: int) -> None:
    """The pipelined compile-once case: a depth-2 pipelined train
    (actor tier = ``actor_block`` acting on published params, learner
    tier = the donated ``learner_block``) warms up once, then a resumed
    steady run — spanning publisher hot-swap rounds every block — must
    re-dispatch the same two executables with ZERO recompiles: the
    acting parameters are data, exactly like the serving hot-swap, so a
    publish can never be a compile."""
    from rcmarl_tpu.lint.configs import tiny_cfg
    from rcmarl_tpu.pipeline.trainer import train_pipelined

    cfg = tiny_cfg(pipeline_depth=2)
    # warmup: compiles actor_block + learner_block_donated (prefill +
    # two learner blocks, one publish round)
    state, _ = train_pipelined(cfg, n_episodes=cfg.n_ep_fixed * 2)
    with auditor.expect_no_compiles(
        context="pipelined actor/learner across publish rounds"
    ):
        train_pipelined(
            cfg,
            n_episodes=cfg.n_ep_fixed * (steady_blocks + 1),
            state=state,
        )


def _audit_gala(auditor: "RetraceAuditor", steady_blocks: int) -> None:
    """The COMPOSED compile-once case: a 4-replica pipelined gossip
    fleet (each replica a depth-2 actor/learner pipeline, a trimmed mix
    every 2 blocks, Byzantine NaN replica 3, canary-gated deploy) warms
    up across one full mix round + canary publish, then a resumed
    steady run must re-dispatch the same executables — actor_block,
    learner_block, gala_mix_block, eval_block — with ZERO recompiles:
    published params, mix payloads, exclusion masks, and canary
    candidates are all data, so neither a mix, a publish, nor a canary
    eval may ever be a compile."""
    from rcmarl_tpu.lint.configs import tiny_gala_cfg
    from rcmarl_tpu.parallel.gala import train_gala

    cfg = tiny_gala_cfg()
    # warmup: compiles the pipeline pair + the composed mix + the
    # canary eval (two blocks = one mixed segment, one deploy round)
    states, df = train_gala(cfg, n_episodes=cfg.n_ep_fixed * 2)
    with auditor.expect_no_compiles(
        context="pipelined gossip fleet across mix + canary rounds"
    ):
        train_gala(
            cfg,
            n_episodes=cfg.n_ep_fixed * (steady_blocks + 1),
            states=states,
            start_round=df.attrs["gossip"]["gossip_round"],
        )


def _audit_fleet(
    auditor: "RetraceAuditor", steady_blocks: int
) -> List[Finding]:
    """The fleet-serving compile-once case: ``fleet_block`` warmed once
    per static arm (sample / greedy), then driven across ROUTE CHANGES
    (the per-request member map is data — an A/B re-split or tenant
    re-route may never be a compile), across MEMBER HOT-SWAPS (a fleet
    with one member's slice replaced by fresh same-shaped params — the
    FleetEngine poll path), and across the LOAD-HARNESS batch
    discipline (every micro-batching-queue launch is the PADDED
    ``max_batch`` shape whatever the fill, so distinct fills share one
    program) — zero recompiles throughout, the production-serving
    acceptance contract."""
    import jax
    import jax.numpy as jnp

    from rcmarl_tpu.lint.configs import tiny_cfg
    from rcmarl_tpu.serve.engine import stack_actor_rows
    from rcmarl_tpu.serve.fleet import fleet_block, fleet_set_member, fleet_stack
    from rcmarl_tpu.training.trainer import init_train_state

    cfg = tiny_cfg()
    blocks = [
        stack_actor_rows(
            init_train_state(cfg, jax.random.PRNGKey(s)).params, cfg
        )
        for s in (0, 1, 2)
    ]
    fleet = fleet_stack(blocks[:2])
    # the member hot-swap: member 1's slice replaced wholesale by fresh
    # same-shaped params (the FleetEngine.poll discipline)
    swapped = fleet_set_member(fleet, 1, blocks[2])
    max_batch = 8  # the load harness's one padded launch shape
    obs = [
        jax.random.normal(
            jax.random.PRNGKey(20 + i), (max_batch, cfg.n_agents, cfg.obs_dim)
        )
        for i in range(2)  # distinct fills land on the SAME padded shape
    ]
    routes = [
        jnp.zeros((max_batch,), jnp.int32),
        jnp.arange(max_batch, dtype=jnp.int32) % 2,
        jnp.ones((max_batch,), jnp.int32),
    ]
    key = jax.random.PRNGKey(11)
    findings: List[Finding] = []
    before = int(fleet_block._cache_size())
    fleet_block(cfg, fleet, obs[0], key, routes[0])
    fleet_block(cfg, fleet, obs[0], key, routes[0], mode="greedy")
    grew = int(fleet_block._cache_size()) - before
    if grew != 2:
        path, line = _anchor(fleet_block)
        findings.append(
            Finding(
                "retrace",
                path,
                line,
                f"fleet_block compiled {grew} program(s) for the "
                "sample/greedy warmup pair — expected exactly one per "
                "static mode arm",
            )
        )
    with auditor.expect_no_compiles(
        context="fleet re-routes + member hot-swap + padded load batches"
    ):
        for i in range(steady_blocks):
            for fl in (fleet, swapped):  # the member hot-swap boundary
                for route in routes:  # routing is DATA
                    for o in obs:  # distinct fills, one padded shape
                        fleet_block(
                            cfg, fl, o, jax.random.fold_in(key, i), route
                        )
                        fleet_block(cfg, fl, o, key, route, mode="greedy")
    return findings


def _audit_serve(
    auditor: "RetraceAuditor", steady_blocks: int
) -> List[Finding]:
    """The serving compile-once case: ``serve_block`` warmed once per
    static arm (sample / greedy), then driven across REPEATED request
    batches and across a HOT-SWAP of same-shaped fresh params — the
    block/observations/key are data, so steady-state serving and every
    checkpoint hot-swap must re-dispatch the same two executables with
    zero recompiles (the acceptance contract of the serve subsystem)."""
    import jax

    from rcmarl_tpu.lint.configs import tiny_cfg
    from rcmarl_tpu.serve.engine import serve_block, stack_actor_rows
    from rcmarl_tpu.training.trainer import init_train_state

    cfg = tiny_cfg()
    # two SAME-SHAPED parameter blocks: blocks[1] plays the hot-swapped
    # checkpoint (fresh params, identical avals)
    blocks = [
        stack_actor_rows(
            init_train_state(cfg, jax.random.PRNGKey(s)).params, cfg
        )
        for s in (0, 1)
    ]
    obs = [
        jax.random.normal(
            jax.random.PRNGKey(10 + i), (8, cfg.n_agents, cfg.obs_dim)
        )
        for i in range(2)
    ]
    key = jax.random.PRNGKey(7)
    findings: List[Finding] = []
    # warmup: exactly one compile per static mode arm
    before = int(serve_block._cache_size())
    serve_block(cfg, blocks[0], obs[0], key)
    serve_block(cfg, blocks[0], obs[0], key, mode="greedy")
    grew = int(serve_block._cache_size()) - before
    if grew != 2:
        path, line = _anchor(serve_block)
        findings.append(
            Finding(
                "retrace",
                path,
                line,
                f"serve_block compiled {grew} program(s) for the "
                "sample/greedy warmup pair — expected exactly one per "
                "static mode arm",
            )
        )
    with auditor.expect_no_compiles(context="batched serve + hot-swap"):
        for i in range(steady_blocks):
            for block in blocks:  # the hot-swap boundary
                for o in obs:  # repeated distinct request batches
                    serve_block(
                        cfg, block, o, jax.random.fold_in(key, i)
                    )
                    serve_block(cfg, block, o, key, mode="greedy")
    return findings


def _fused_serve_fixture():
    """(cfg, same-shaped param blocks, padded obs fills, key) shared by
    the fused-serve and autoscale-resize retrace cases."""
    import jax

    from rcmarl_tpu.lint.configs import tiny_cfg
    from rcmarl_tpu.serve.engine import stack_actor_rows
    from rcmarl_tpu.training.trainer import init_train_state

    cfg = tiny_cfg()
    blocks = [
        stack_actor_rows(
            init_train_state(cfg, jax.random.PRNGKey(s)).params, cfg
        )
        for s in (0, 1)
    ]
    obs = [
        jax.random.normal(
            jax.random.PRNGKey(30 + i), (8, cfg.n_agents, cfg.obs_dim)
        )
        for i in range(2)
    ]
    return cfg, blocks, obs, jax.random.PRNGKey(13)


def _audit_fused_serve(
    auditor: "RetraceAuditor", steady_blocks: int
) -> List[Finding]:
    """The ONE-KERNEL serving compile-once case (``fused_serve_block``
    / ``fused_fleet_block``, interpret arm on this host): exactly one
    compile per static sample/greedy arm, then zero recompiles across
    repeated request batches, same-shaped checkpoint HOT-SWAPS, and
    fleet ROUTE CHANGES — params, observations, key, and route are all
    data to the fused program, exactly the XLA arm's contract."""
    import jax
    import jax.numpy as jnp

    from rcmarl_tpu.ops.pallas_serve import (
        fused_fleet_block,
        fused_serve_block,
    )
    from rcmarl_tpu.serve.fleet import fleet_stack

    cfg, blocks, obs, key = _fused_serve_fixture()
    fleet = fleet_stack(blocks)
    routes = [
        jnp.zeros((8,), jnp.int32),
        jnp.arange(8, dtype=jnp.int32) % 2,
    ]
    findings: List[Finding] = []
    before = int(fused_serve_block._cache_size())
    fused_serve_block(cfg, blocks[0], obs[0], key, interpret=True)
    fused_serve_block(
        cfg, blocks[0], obs[0], key, mode="greedy", interpret=True
    )
    grew = int(fused_serve_block._cache_size()) - before
    if grew != 2:
        path, line = _anchor(fused_serve_block)
        findings.append(
            Finding(
                "retrace",
                path,
                line,
                f"fused_serve_block compiled {grew} program(s) for the "
                "sample/greedy warmup pair — expected exactly one per "
                "static mode arm",
            )
        )
    fused_fleet_block(cfg, fleet, obs[0], key, routes[0], interpret=True)
    with auditor.expect_no_compiles(
        context="fused serve + hot-swap + fleet re-routes"
    ):
        for i in range(steady_blocks):
            for block in blocks:  # the hot-swap boundary
                for o in obs:  # repeated distinct request batches
                    fused_serve_block(
                        cfg, block, o, jax.random.fold_in(key, i),
                        interpret=True,
                    )
                    fused_serve_block(
                        cfg, block, o, key, mode="greedy", interpret=True
                    )
            for route in routes:  # routing is DATA
                fused_fleet_block(
                    cfg, fleet, obs[0], key, route, interpret=True
                )
    return findings


def _audit_autoscale_resize(
    auditor: "RetraceAuditor", steady_blocks: int
) -> List[Finding]:
    """The autoscale resize compile-once case: the SLO controller
    resizes ``max_batch`` / the fleet split, so the serving program
    sees a NEW padded batch shape at a resize boundary — each shape
    must compile exactly ONCE (first sight), and steady alternation
    across already-seen shapes must recompile NOTHING: scaling back
    through an old size is a cache hit, never a recompile storm."""
    import jax

    from rcmarl_tpu.ops.pallas_serve import fused_serve_block

    cfg, blocks, obs, key = _fused_serve_fixture()
    resized = [o[:b] for o, b in zip(obs, (8, 4))]  # two resize shapes
    findings: List[Finding] = []
    before = int(fused_serve_block._cache_size())
    for o in resized:  # warmup: one compile per resized shape
        fused_serve_block(cfg, blocks[0], o, key, interpret=True)
    grew = int(fused_serve_block._cache_size()) - before
    # the B=8 sample arm may already be warm from the fused-serve case
    # (shared fixture — the memoization is the point); only a per-shape
    # over-compile is a finding
    if grew > 2:
        path, line = _anchor(fused_serve_block)
        findings.append(
            Finding(
                "retrace",
                path,
                line,
                f"fused_serve_block compiled {grew} program(s) for two "
                "resized batch shapes — expected at most one per shape",
            )
        )
    with auditor.expect_no_compiles(
        context="autoscale resizes across already-seen batch shapes"
    ):
        for i in range(steady_blocks):
            for block in blocks:  # resize + hot-swap interleaved
                for o in resized:  # alternating already-seen shapes
                    fused_serve_block(
                        cfg, block, o, jax.random.fold_in(key, i),
                        interpret=True,
                    )
    return findings
