"""graftlint — static analysis + compiled-artifact audits for rcmarl_tpu.

Two layers, one findings format (:mod:`.findings`), exposed as
``python -m rcmarl_tpu lint``:

**Layer 1 — AST source passes** over the package (no jax import, runs
anywhere):

================== ====================================================
rule id            what it enforces
================== ====================================================
prng-reuse         every key consumed once; no sampling from split
                   parents; no duplicate fold_in streams
prng-split-discard split() entropy never thrown away positionally
prng-int-seed      no PRNGKey/key minted inside jitted hot-path modules
prng-fold-tag      fold_in stream tags are named constants (the
                   faults.py dedicated-stream pattern), not magic ints
host-sync          no device->host pulls (float/int/bool/np.asarray/
                   .item()/device_get on traced values) in hot paths
host-block         no block_until_ready barriers in hot-path modules
static-unhashable  jit-static configs stay hashable (frozen-dataclass
                   fields; mutable displays at static call positions)
================== ====================================================

**Layer 2 — compiled-artifact audits** (import jax, run real tiny
programs; ``lint --retrace/--donation/--backends/--cost/--collectives/
--sharding/--contract/--kernels`` — the kernels arm is pure shape
arithmetic and runs without a backend):

================== ====================================================
retrace            each jitted entry point compiles exactly once after
                   warmup across a guarded+faulted train run, on both
                   netstack arms (:mod:`.retrace`)
donation-dropped   update/train_block_donated keep their declared
                   input->output buffer aliasing in the compiled
                   executable (:mod:`.donation`)
backend-impure     no callbacks/infeed/nondeterministic primitives in
                   any aggregation-backend jaxpr (:mod:`.backends`)
backend-dtype-drift aggregation outputs keep exact input dtype with no
                   weak types, identical across all six backends and
                   both netstack epoch arms (:mod:`.backends`)
cost-regression    a compiled entry point's FLOPs / bytes accessed /
                   buffer bytes grew past tolerance vs the committed
                   AUDIT.jsonl ledger (:mod:`.cost`)
cost-unbaselined   a compiled entry has no (matching) ledger row, or a
                   ledger row went stale — regenerate AUDIT.jsonl in
                   the same PR (:mod:`.cost`)
collective-census  the sharded seed×agent programs' collective set /
                   counts drifted from the ledger, left the enumerated
                   pod-readiness set, or the seed-only program grew a
                   collective (:mod:`.collectives`)
host-transfer      a device->host transfer (infeed/outfeed/host memory
                   space/host callback) inside a compiled train block
                   (:mod:`.collectives`)
sharding-replicated a parameter/optimizer/rollout-buffer-sized operand
                   of a compiled sharded program carries a replicated/
                   maximal sharding instead of a mesh-axis one
                   (:mod:`.sharding`)
sharding-reshard-chain back-to-back resharding: one collective feeds
                   another, moving the same buffer twice per block
                   (:mod:`.sharding`)
device-memory-regression per-device peak/argument bytes fail to shrink
                   with mesh size {1,2,8}, or grew past --cost_tol vs
                   the AUDIT.jsonl device-memory rows (:mod:`.sharding`)
nondeterminism     nondeterministic HLO in a walked module: a float-
                   accumulating scatter with unique_indices=false, a
                   non-threefry rng-bit-generator / legacy rng op, or a
                   cross-replica op outside the certified collective
                   allowlist (:mod:`.sharding`)
contract-drift     a Config field unreachable from any CLI flag (and
                   not exempted), failing the checkpoint-header JSON
                   round-trip, or missing from the docs/api.md table
                   (:mod:`.contract`)
kernel-vmem-budget a Pallas plan's statically derived per-grid-step
                   VMEM residency (double-buffered BlockSpec tiles +
                   scratch live set) exceeds the selected TPU
                   generation's budget on a must-fit lint cell, or a
                   committed ``feasible`` verdict regressed
                   (:mod:`.kernels`)
kernel-smem-budget same, for the scalar-prefetch SMEM residency
                   (:mod:`.kernels`)
kernel-tile-misaligned a CHOSEN tile dimension violates the dtype's
                   (sublane, lane) packing quantum — (8, 128) f32,
                   (16, 128) bf16, (32, 128) int8 (:mod:`.kernels`)
kernel-dma-model-drift a committed ``*_dma_bytes`` closed-form model
                   disagrees with the traffic re-derived from the
                   plan's BlockSpec grid arithmetic past ``--cost_tol``
                   (:mod:`.kernels`)
kernel-budget-regression a ``kernel_budget`` ledger row drifted:
                   residency/traffic grew past tolerance, a row is
                   unbaselined or stale, or the plan fingerprint
                   changed without regenerating AUDIT.jsonl
                   (:mod:`.kernels`)
================== ====================================================

Escape hatch for Layer 1: ``# lint: disable=<rule>`` on the flagged
line (see :mod:`.findings`). The package itself must lint clean — CI
runs the suite fail-fast (scripts/ci_tier1.sh, .github/workflows).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional

from rcmarl_tpu.lint import hostsync, prng, staticargs
from rcmarl_tpu.lint.findings import (
    Finding,
    PragmaIndex,
    filter_pragmas,
    is_hot_path,
    iter_source_files,
    package_root,
    sort_findings,
)

__all__ = [
    "Finding",
    "SOURCE_RULES",
    "AUDIT_RULES",
    "lint_file",
    "run_source_lint",
]

#: Layer-1 rule ids (stable; the pragma escape and docs key on these).
SOURCE_RULES = (
    "prng-reuse",
    "prng-split-discard",
    "prng-int-seed",
    "prng-fold-tag",
    "host-sync",
    "host-block",
    "static-unhashable",
)

#: Layer-2 rule ids.
AUDIT_RULES = (
    "retrace",
    "donation-dropped",
    "backend-impure",
    "backend-dtype-drift",
    "cost-regression",
    "cost-unbaselined",
    "collective-census",
    "host-transfer",
    "sharding-replicated",
    "sharding-reshard-chain",
    "device-memory-regression",
    "nondeterminism",
    "contract-drift",
    "kernel-vmem-budget",
    "kernel-smem-budget",
    "kernel-tile-misaligned",
    "kernel-dma-model-drift",
    "kernel-budget-regression",
)

_PASSES = (prng.run, hostsync.run, staticargs.run)


def lint_file(
    path: Path,
    rel_path: Optional[str] = None,
    hot_path: Optional[bool] = None,
) -> List[Finding]:
    """Run every AST pass over one file; pragma escapes applied.

    ``rel_path`` is the display path (defaults to the path as given);
    ``hot_path`` forces the traced-code rule scope (defaults to the
    package-relative hot-path match — fixtures force it True).
    """
    path = Path(path)
    rel = rel_path if rel_path is not None else str(path)
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as e:
        return [
            Finding(
                "syntax-error", rel, e.lineno or 1, f"cannot parse: {e.msg}"
            )
        ]
    hot = is_hot_path(rel) if hot_path is None else hot_path
    findings: List[Finding] = []
    for p in _PASSES:
        findings.extend(p(rel, tree, hot))
    return filter_pragmas(findings, PragmaIndex.from_source(source))


def run_source_lint(root: "Path | str | None" = None) -> List[Finding]:
    """Layer 1 over every ``.py`` under ``root`` (default: the installed
    ``rcmarl_tpu`` package). Paths report relative to ``root``."""
    root = package_root() if root is None else Path(root)
    findings: List[Finding] = []
    for path in iter_source_files(root):
        # display paths keep the root's own name ('rcmarl_tpu/ops/…')
        # so every layer — AST passes, retrace anchors, donation
        # anchors — reports the same file the same way
        rel = (
            str(Path(root.name) / path.relative_to(root))
            if path != root
            else path.name
        )
        findings.extend(lint_file(path, rel_path=rel))
    return sort_findings(findings)
