"""Donation auditor — declared buffer donation must survive to XLA.

PR 3's steady-state allocation story (PERF.md "buffer donation") rests
on ``donate_argnums`` actually producing input->output buffer aliasing
in the compiled executable. XLA is allowed to DROP a declared donation
(shape/layout mismatch, an input still live in the program) and says so
only in an easily-missed warning — after which the donated entry points
quietly allocate two copies of every parameter again. This audit reads
the compiled artifact itself:

- lower + compile ``update_block_donated`` and ``train_block_donated``
  on a tiny config,
- parse the ``input_output_alias={...}`` directive off the compiled
  ``HloModule`` header,
- fail (``donation-dropped``) when the alias count falls short of the
  donated state's parameter-leaf count, or when XLA warned that donated
  buffers went unused.

Platforms whose compiled text exposes no aliasing metadata yield a
``note`` instead of findings (and the regression test xfails with the
same reason) — absence of evidence is reported, never treated as a
pass of the contract.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from rcmarl_tpu.lint.findings import Finding

_ALIAS_HEADER = re.compile(r"input_output_alias=\{")


def alias_pair_count(compiled_text: str) -> Optional[int]:
    """Number of aliased buffer pairs in a compiled ``HloModule``
    header, or None when the platform exposes no aliasing metadata."""
    header = compiled_text.split("\n", 1)[0]
    if not _ALIAS_HEADER.search(header):
        return None
    return header.count("may-alias") + header.count("must-alias")


def donation_report() -> Dict[str, dict]:
    """Compile both donated entry points and report their aliasing:
    ``{name: {alias_pairs, expected_min, has_metadata, warnings}}``.

    ``expected_min`` is the donated argument's PARAMETER leaf count —
    the stacked nets and optimizer moments whose in-place update is the
    entire point of the donation. XLA may alias more (replay buffer,
    RNG carry); it must not alias fewer.

    The compiles ride the shared memoized helpers
    (:func:`rcmarl_tpu.utils.profiling.compiled_entry_points`, dual-
    launch arm for cross-backend determinism): in a ``lint --all`` run
    the cost arm and this audit read the SAME compiled artifacts, each
    entry point compiled once. Donation-relevant XLA warnings are
    captured at compile time by the helper, whichever arm compiles
    first.
    """
    import jax

    from rcmarl_tpu.lint.configs import tiny_cfg
    from rcmarl_tpu.utils.profiling import (
        compiled_entry_points,
        entry_point_inputs,
    )

    cfg = tiny_cfg(netstack=False)
    state, _, _, _ = entry_point_inputs(cfg)
    n_param_leaves = len(jax.tree.leaves(state.params))
    report: Dict[str, dict] = {}
    entries = compiled_entry_points(
        cfg, names=("update_block_donated", "train_block_donated")
    )
    for name, entry in entries.items():
        pairs = alias_pair_count(entry.compiled.as_text())
        report[name] = {
            "alias_pairs": pairs,
            "expected_min": n_param_leaves,
            "has_metadata": pairs is not None,
            "warnings": [
                w for w in entry.warnings if "donat" in w.lower()
            ],
        }
    return report


def audit_donation() -> Tuple[List[Finding], List[str]]:
    """``lint --donation``: (findings, notes). A dropped or shrunken
    donation is a ``donation-dropped`` finding; a platform without
    aliasing metadata is a note (reported, not passed)."""
    findings: List[Finding] = []
    notes: List[str] = []
    anchor = ("rcmarl_tpu/training/update.py", 1)
    for name, row in donation_report().items():
        path = (
            "rcmarl_tpu/training/trainer.py"
            if name.startswith("train")
            else anchor[0]
        )
        for msg in row["warnings"]:
            findings.append(
                Finding(
                    "donation-dropped",
                    path,
                    1,
                    f"{name}: XLA dropped declared donations — {msg[:200]}",
                )
            )
        if not row["has_metadata"]:
            notes.append(
                f"{name}: compiled module exposes no input_output_alias "
                "metadata on this platform; aliasing unverifiable here"
            )
            continue
        if row["alias_pairs"] < row["expected_min"]:
            findings.append(
                Finding(
                    "donation-dropped",
                    path,
                    1,
                    f"{name}: only {row['alias_pairs']} aliased buffer "
                    f"pair(s) in the compiled executable, expected at "
                    f"least the {row['expected_min']} parameter/optimizer "
                    "leaves — the donated state is being copied, not "
                    "updated in place",
                )
            )
    return findings, notes
