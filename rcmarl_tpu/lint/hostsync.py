"""Host-synchronization AST pass (hot-path modules only).

The throughput story (PERF.md, arXiv:2104.06272's compile-once /
device-resident discipline) depends on the training hot path never
forcing a device→host transfer mid-program: one stray ``float()`` on a
traced value inside the update block serializes the dispatch queue.
These rules police exactly the modules that trace under jit
(:data:`.findings.HOT_PATH_PATTERNS`); host-side orchestration (CLI,
trainer loop, analysis) is free to fetch.

- ``host-sync`` — ``float()`` / ``int()`` / ``bool()`` /
  ``np.asarray`` / ``np.array`` / ``jax.device_get`` applied to an
  expression that is not provably STATIC, or any ``.item()`` call.
  Static means derivable at trace time: literals, ``cfg``/``config``/
  ``plan`` roots and locals assigned from them, module-level
  ``UPPER_CASE`` constants, ``.shape``/``.ndim``/``.size``/``.dtype``
  attributes (static on ANY object under jit), and compositions of
  those through arithmetic, indexing, ``len``/``max``/``np.prod``-style
  calls, and comprehensions. ``float(plan.stale_p)`` and
  ``int(np.prod(l.shape[1:]))`` pass; ``float(loss)`` does not.
- ``host-block`` — ``.block_until_ready()`` or
  ``jax.block_until_ready(...)`` in a hot-path module: a deliberate
  barrier belongs in the profiler/benchmark layers, never inside code
  that traces into the production block.

The static-expression analysis is a single linear pass per function
(assignment order, no branches merged), which is exactly as smart as
the hot-path modules need — anything cleverer should probably not be in
the hot path in the first place.
"""

from __future__ import annotations

import ast
from typing import List, Set

from rcmarl_tpu.lint.findings import Finding

#: Names that are jit-static by convention wherever they appear.
STATIC_NAMES = frozenset({"cfg", "config", "plan"})

#: Builtins/helpers that are static when all their arguments are.
STATIC_CALLS = frozenset(
    {
        "abs", "bool", "dict", "enumerate", "float", "frozenset", "getattr",
        "int", "isinstance", "len", "list", "max", "min", "range", "round",
        "set", "sorted", "str", "sum", "tuple", "zip",
    }
)

#: Modules whose attribute calls are host-side but static-safe on
#: static inputs (shape math, config tables).
STATIC_MODULES = frozenset({"np", "numpy", "math"})

#: Attributes that are static on ANY object under jit (aval metadata).
STATIC_ATTRS = frozenset({"shape", "ndim", "size", "dtype"})

#: Call targets that force a host transfer when fed a traced value.
SYNC_BUILTINS = frozenset({"float", "int", "bool"})
SYNC_NP_FNS = frozenset({"asarray", "array", "float32", "float64", "int32"})


class _FnScope(ast.NodeVisitor):
    """Analyze one function: a linear static-locals dataflow feeding the
    host-sync checks."""

    def __init__(self, outer: "HostSyncPass") -> None:
        self.outer = outer
        self.static: Set[str] = set()

    # ---- static-expression analysis ------------------------------------

    def _static_fn(self, func: ast.expr) -> bool:
        if isinstance(func, ast.Name):
            return func.id in STATIC_CALLS
        if isinstance(func, ast.Attribute):
            root = func.value
            if isinstance(root, ast.Name) and root.id in STATIC_MODULES:
                return True  # np.prod / np.array / math.sqrt on statics
            return self.is_static(root)  # cfg.padded_in_nodes(), plan.to_dict()
        return False

    def is_static(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            return (
                node.id in STATIC_NAMES
                or node.id in self.static
                or node.id in STATIC_MODULES
                or node.id.isupper()
            )
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return True
            return self.is_static(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_static(node.value) and self.is_static(node.slice)
        if isinstance(node, ast.Slice):
            return all(
                part is None or self.is_static(part)
                for part in (node.lower, node.upper, node.step)
            )
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return all(self.is_static(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return all(
                k is not None and self.is_static(k) and self.is_static(v)
                for k, v in zip(node.keys, node.values)
            )
        if isinstance(node, ast.UnaryOp):
            return self.is_static(node.operand)
        if isinstance(node, ast.BinOp):
            return self.is_static(node.left) and self.is_static(node.right)
        if isinstance(node, ast.BoolOp):
            return all(self.is_static(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.is_static(node.left) and all(
                self.is_static(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return (
                self.is_static(node.test)
                and self.is_static(node.body)
                and self.is_static(node.orelse)
            )
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            saved = set(self.static)
            ok = True
            for gen in node.generators:
                ok = ok and self.is_static(gen.iter)
                for name in ast.walk(gen.target):
                    if isinstance(name, ast.Name):
                        self.static.add(name.id)
                ok = ok and all(self.is_static(i) for i in gen.ifs)
            ok = ok and self.is_static(node.elt)
            self.static = saved
            return ok
        if isinstance(node, ast.Call):
            return self._static_fn(node.func) and all(
                self.is_static(a)
                for a in list(node.args)
                + [kw.value for kw in node.keywords if kw.arg != "self"]
            )
        if isinstance(node, ast.Starred):
            return self.is_static(node.value)
        return False

    # ---- dataflow -------------------------------------------------------

    def visit_Assign(self, node):  # noqa: N802
        self.visit(node.value)
        value_static = self.is_static(node.value)
        for target in node.targets:
            names = [
                n.id
                for n in ast.walk(target)
                if isinstance(n, ast.Name)
            ]
            for name in names:
                if value_static:
                    self.static.add(name)
                else:
                    self.static.discard(name)

    def visit_For(self, node):  # noqa: N802
        if self.is_static(node.iter):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    self.static.add(n.id)
        self.generic_visit(node)

    # ---- the checks -----------------------------------------------------

    def _flag(self, rule: str, node: ast.AST, msg: str) -> None:
        self.outer.findings.append(
            Finding(rule, self.outer.path, node.lineno, msg)
        )

    def visit_Call(self, node):  # noqa: N802
        func = node.func
        # .item() / .block_until_ready() method calls
        if isinstance(func, ast.Attribute):
            if func.attr == "item":
                self._flag(
                    "host-sync",
                    node,
                    ".item() forces a device->host transfer inside the "
                    "jitted hot path",
                )
            elif func.attr == "block_until_ready":
                target = (
                    ast.unparse(node.args[0])
                    if isinstance(func.value, ast.Name)
                    and func.value.id in ("jax",)
                    and node.args
                    else ast.unparse(func.value)
                )
                self._flag(
                    "host-block",
                    node,
                    f"block_until_ready on {target!r}: completion "
                    "barriers belong in the profiler/benchmark layers, "
                    "not hot-path modules",
                )
            elif func.attr == "device_get" and isinstance(
                func.value, ast.Name
            ):
                self._flag(
                    "host-sync",
                    node,
                    "jax.device_get inside the jitted hot path is a "
                    "host transfer",
                )
            elif (
                isinstance(func.value, ast.Name)
                and func.value.id in ("np", "numpy")
                and func.attr in SYNC_NP_FNS
                and node.args
                and not all(self.is_static(a) for a in node.args)
            ):
                self._flag(
                    "host-sync",
                    node,
                    f"np.{func.attr}() on a non-static value pulls the "
                    "array to the host mid-trace; use jnp (or keep the "
                    "input config-derived)",
                )
        elif (
            isinstance(func, ast.Name)
            and func.id in SYNC_BUILTINS
            and node.args
            and not all(self.is_static(a) for a in node.args)
        ):
            self._flag(
                "host-sync",
                node,
                f"{func.id}() on a non-static value synchronizes the "
                "device inside the hot path; only config/shape-derived "
                "scalars may cross to Python here",
            )
        self.generic_visit(node)


class HostSyncPass(ast.NodeVisitor):
    """Run one :class:`_FnScope` per function (module-level code in the
    hot-path modules is import-time, not traced — skipped)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.findings: List[Finding] = []

    def visit_FunctionDef(self, node):  # noqa: N802
        scope = _FnScope(self)
        for stmt in node.body:
            scope.visit(stmt)
        # nested defs were visited by the scope walker already

    visit_AsyncFunctionDef = visit_FunctionDef


def run(path: str, tree: ast.Module, hot_path: bool) -> List[Finding]:
    if not hot_path:
        return []
    p = HostSyncPass(path)
    p.visit(tree)
    return p.findings
