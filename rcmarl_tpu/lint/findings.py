"""Finding records, pragma escapes, and source-file discovery.

Every lint rule — AST pass or compiled-artifact audit — reports through
the same :class:`Finding` record: a stable rule id, a ``file:line``
anchor, and a one-line message. Findings are what the CLI prints, what
``tests/test_lint.py`` pins, and what the pragma escape suppresses.

Pragma syntax (checked per physical line of the flagged location):

    x = float(traced_value)  # lint: disable=host-sync
    # lint: disable=host-sync,prng-reuse     (several rules at once)

A file-level escape in the first ``_FILE_PRAGMA_WINDOW`` lines disables
a rule for the whole file:

    # lint: disable-file=prng-int-seed

Runtime-audit findings (retrace/donation/backends) anchor to the module
that owns the audited artifact rather than a source line; they have no
pragma escape — a broken compiled-artifact contract cannot be waived
inline, only fixed (or the audit not requested).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, List, Sequence

#: Modules whose functions run under jit in the training hot path — the
#: scope of the traced-value rules (host-sync, prng-int-seed,
#: prng-fold-tag). Entries ending in ``/`` match a directory anywhere
#: in the path; others match as a path suffix — so the set holds for
#: package-relative, repo-relative, and absolute display paths alike.
HOT_PATH_PATTERNS = (
    "ops/",
    "agents/updates.py",
    "training/update.py",
    "parallel/gala.py",
    "chaos/",
)

_LINE_PRAGMA = re.compile(r"#\s*lint:\s*disable=([\w,\-]+)")
_FILE_PRAGMA = re.compile(r"#\s*lint:\s*disable-file=([\w,\-]+)")
_FILE_PRAGMA_WINDOW = 10


@dataclass(frozen=True)
class Finding:
    """One lint finding: stable rule id + ``file:line`` + message."""

    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


@dataclass
class PragmaIndex:
    """Per-file map of pragma-disabled rules (see module docstring)."""

    line_disables: dict = field(default_factory=dict)  # line -> {rule,...}
    file_disables: set = field(default_factory=set)

    @classmethod
    def from_source(cls, source: str) -> "PragmaIndex":
        idx = cls()
        for lineno, text in enumerate(source.splitlines(), start=1):
            m = _LINE_PRAGMA.search(text)
            if m:
                idx.line_disables[lineno] = set(m.group(1).split(","))
            if lineno <= _FILE_PRAGMA_WINDOW:
                m = _FILE_PRAGMA.search(text)
                if m:
                    idx.file_disables |= set(m.group(1).split(","))
        return idx

    def disabled(self, rule: str, line: int) -> bool:
        return rule in self.file_disables or rule in self.line_disables.get(
            line, ()
        )


def filter_pragmas(
    findings: Iterable[Finding], pragmas: PragmaIndex
) -> List[Finding]:
    return [f for f in findings if not pragmas.disabled(f.rule, f.line)]


def is_hot_path(rel_path: str) -> bool:
    """Whether a display path is in the jitted hot-path set — robust to
    how the caller anchored it ('ops/fit.py', 'rcmarl_tpu/ops/fit.py',
    or an absolute path all match)."""
    rel = "/" + rel_path.replace("\\", "/")
    for p in HOT_PATH_PATTERNS:
        if p.endswith("/"):
            if f"/{p}" in rel + "/":
                return True
        elif rel.endswith("/" + p):
            return True
    return False


def package_root() -> Path:
    """The ``rcmarl_tpu`` package directory (the default lint target)."""
    return Path(__file__).resolve().parent.parent


def iter_source_files(root: Path | None = None) -> List[Path]:
    """Every ``.py`` file under ``root`` (default: the package itself),
    sorted for stable output."""
    root = package_root() if root is None else Path(root)
    if root.is_file():
        return [root]
    return sorted(root.rglob("*.py"))


def sort_findings(findings: Sequence[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
