"""Config-hashability / jit-static-argument AST pass.

Every jitted entry point in this framework closes over ``Config`` as a
STATIC argument (``jax.jit(..., static_argnums=0)``): jit caches on
``hash(cfg)``, so an unhashable or mutable value reaching a static slot
either crashes at dispatch or — worse — hashes by identity and
silently retraces per call (the drift class DRIFT.md documents). Two
rules:

- ``static-unhashable`` (field form) — a ``@dataclass(frozen=True)``
  class declares a field with a mutable container annotation
  (``list``/``dict``/``set``/``List``/``Dict``/``Set``/ndarray) or a
  mutable default. Frozen dataclasses hash by field values; one list
  field makes the whole config unhashable, and an ndarray field hashes
  never (``Config`` and ``FaultPlan`` are the contracts here — tuples
  and scalars only).
- ``static-unhashable`` (call form) — a call site in the same module
  passes a ``list``/``dict``/``set`` display (or ``list()``/``dict()``/
  ``set()`` constructor) in a position that the called name declared
  static via ``jax.jit(..., static_argnums=...)`` or
  ``functools.partial(jax.jit, static_argnums=...)``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from rcmarl_tpu.lint.findings import Finding

MUTABLE_TYPE_NAMES = frozenset(
    {"list", "dict", "set", "List", "Dict", "Set", "MutableMapping",
     "bytearray", "ndarray", "Array"}
)


def _annotation_names(node: ast.expr) -> Set[str]:
    """Base type names mentioned by an annotation expression.

    String annotations (``"bool | str"``) parse too — postponed
    evaluation must not hide a mutable field type.
    """
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return set()
    names: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            names.add(n.id)
        elif isinstance(n, ast.Attribute):
            names.add(n.attr)
    return names


def _is_frozen_dataclass(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        if isinstance(dec, ast.Call):
            fn = dec.func
            name = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", "")
            if name == "dataclass":
                for kw in dec.keywords:
                    if (
                        kw.arg == "frozen"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                    ):
                        return True
    return False


def _jit_static_positions(call: ast.Call) -> "Tuple[int, ...] | None":
    """static_argnums of a ``jax.jit(...)`` / ``partial(jax.jit, ...)``
    call expression, or None when it is not such a call."""
    fn = call.func
    is_jit = (
        isinstance(fn, ast.Attribute)
        and fn.attr == "jit"
        and isinstance(fn.value, ast.Name)
        and fn.value.id == "jax"
    )
    is_partial_jit = (
        (isinstance(fn, ast.Name) and fn.id == "partial")
        or (isinstance(fn, ast.Attribute) and fn.attr == "partial")
    ) and any(
        isinstance(a, ast.Attribute)
        and a.attr == "jit"
        and isinstance(a.value, ast.Name)
        and a.value.id == "jax"
        for a in call.args
    )
    if not (is_jit or is_partial_jit):
        return None
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = tuple(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                )
                return out or ()
    return ()


def _mutable_display(node: ast.expr) -> "str | None":
    if isinstance(node, ast.List):
        return "list"
    if isinstance(node, ast.Dict):
        return "dict"
    if isinstance(node, ast.Set):
        return "set"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("list", "dict", "set", "bytearray"):
            return node.func.id
    return None


class StaticArgsPass(ast.NodeVisitor):
    def __init__(self, path: str) -> None:
        self.path = path
        self.findings: List[Finding] = []
        #: name -> static positions, for jit-wrapped module-level names
        self._static_of: Dict[str, Tuple[int, ...]] = {}

    def visit_ClassDef(self, node):  # noqa: N802
        if _is_frozen_dataclass(node):
            for stmt in node.body:
                if not isinstance(stmt, ast.AnnAssign):
                    continue
                bad = _annotation_names(stmt.annotation) & MUTABLE_TYPE_NAMES
                default_kind = (
                    _mutable_display(stmt.value) if stmt.value else None
                )
                if bad or default_kind:
                    target = (
                        stmt.target.id
                        if isinstance(stmt.target, ast.Name)
                        else ast.unparse(stmt.target)
                    )
                    what = (
                        f"mutable annotation {sorted(bad)}"
                        if bad
                        else f"mutable {default_kind} default"
                    )
                    self.findings.append(
                        Finding(
                            "static-unhashable",
                            self.path,
                            stmt.lineno,
                            f"frozen dataclass field {target!r} has "
                            f"{what}: this config is jit-static and must "
                            "hash — use tuples/scalars",
                        )
                    )
        self.generic_visit(node)

    def visit_Assign(self, node):  # noqa: N802
        if isinstance(node.value, ast.Call):
            statics = _jit_static_positions(node.value)
            if statics is None and isinstance(node.value.func, ast.Call):
                # partial(jax.jit, static_argnums=...)(fn) — the inner
                # call carries the static spec
                statics = _jit_static_positions(node.value.func)
            if statics:
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        self._static_of[target.id] = statics
        self.generic_visit(node)

    def visit_Call(self, node):  # noqa: N802
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in self._static_of:
            for pos in self._static_of[fn.id]:
                if pos < len(node.args):
                    kind = _mutable_display(node.args[pos])
                    if kind:
                        self.findings.append(
                            Finding(
                                "static-unhashable",
                                self.path,
                                node.lineno,
                                f"{fn.id}() receives a {kind} in static "
                                f"position {pos}: unhashable static args "
                                "crash at dispatch (or retrace per call "
                                "when hashed by identity)",
                            )
                        )
        self.generic_visit(node)


def run(path: str, tree: ast.Module, hot_path: bool) -> List[Finding]:
    p = StaticArgsPass(path)
    p.visit(tree)
    return p.findings
