"""The ONE tiny training Config the runtime audits share.

Retrace, donation, and backend audits all exercise the same miniature
scenario (3 cooperative agents, full 3-ring, 3x3 grid, 2-episode
blocks, H=1) and differ only in the netstack / fault-plan / sanitize
knobs they probe. Keeping the base here means a Config signature or
validation change — exactly the drift class this suite polices — is
fixed once, and the three audits provably audit the same workload.
"""

from __future__ import annotations


def tiny_cfg(**overrides):
    """A 3-agent audit config; keyword overrides win over the base."""
    from rcmarl_tpu.config import Config, Roles, circulant_in_nodes

    base = dict(
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE,) * 3,
        in_nodes=circulant_in_nodes(3, 3),
        nrow=3,
        ncol=3,
        n_episodes=6,
        n_ep_fixed=2,
        max_ep_len=4,
        n_epochs=2,
        H=1,
    )
    base.update(overrides)
    return Config(**base)


def tiny_mixed_cfg(**overrides):
    """The MIXED-cast audit variant (1 coop + 1 greedy + 1 malicious):
    every phase-I fit flavor is live, so a fitstack/fused-fit audit row
    covers the whole (flavor·net) row block, not just the cooperative
    pair an all-coop cast would leave."""
    from rcmarl_tpu.config import Roles

    return tiny_cfg(
        agent_roles=(Roles.COOPERATIVE, Roles.GREEDY, Roles.MALICIOUS),
        **overrides,
    )


def tiny_faulted_cfg(netstack, **overrides):
    """The guarded+faulted variant (drop+NaN+stale plan, sanitize on)."""
    from rcmarl_tpu.faults import FaultPlan

    return tiny_cfg(
        netstack=netstack,
        fault_plan=FaultPlan(drop_p=0.2, nan_p=0.2, stale_p=0.1),
        consensus_sanitize=True,
        **overrides,
    )


def tiny_gossip_cfg(**overrides):
    """The gossip-replica audit variant: 4 replicas on a full graph
    (n_in=4, so gossip_H=1 is legal), trimmed mix — the canonical shape
    the gossip_mix_block cost row and the gossip retrace case compile.
    A Byzantine NaN replica keeps the sanitize path live in the audited
    program without touching the probabilistic fault streams."""
    from rcmarl_tpu.faults import ReplicaFaultPlan

    base = dict(
        replicas=4,
        gossip_every=1,
        gossip_graph="full",
        gossip_H=1,
        replica_fault_plan=ReplicaFaultPlan(
            byzantine_replicas=(3,), byzantine_mode="nan"
        ),
    )
    base.update(overrides)
    return tiny_cfg(**base)


def tiny_gala_cfg(**overrides):
    """The composed pipelined-gossip-fleet audit variant: the gossip
    shape (4 replicas, full graph, H=1, Byzantine NaN replica 3) with
    each replica's actor tier running 2 blocks ahead, a mix every 2
    blocks (Config requires ``pipeline_depth <= gossip_every``), and
    a live canary deploy gate — the canonical shape the gala_mix_block
    cost row and the composed retrace case compile."""
    base = dict(
        pipeline_depth=2,
        gossip_every=2,
        canary_band=0.5,
        canary_blocks=1,
    )
    base.update(overrides)
    return tiny_gossip_cfg(**base)


def tiny_sparse_cfg(**overrides):
    """The sparse-exchange audit variant: the time-varying
    random-geometric schedule (degree 3 over 4 agents), so the audited
    gather takes its indices as TRACED data through
    :func:`rcmarl_tpu.ops.exchange.sparse_gather` — the mega-population
    exchange the ``consensus_exchange`` cost rows price."""
    from rcmarl_tpu.config import Roles, circulant_in_nodes

    base = dict(
        n_agents=4,
        agent_roles=(Roles.COOPERATIVE,) * 4,
        in_nodes=circulant_in_nodes(4, 3),
        graph_schedule="random_geometric",
        graph_degree=3,
        H=1,
    )
    base.update(overrides)
    return tiny_cfg(**base)


def megapop_cfg(**overrides):
    """The mega-population sharding-ladder shape: n=1024 agents on the
    sparse random-geometric schedule (degree 8, H=2), tiny (4,) hidden
    — the cell whose agent-sharded flat consensus block the
    ``megapop@sharded`` device-memory ladder compiles at mesh {1,2,8}
    (compile/inspect only; nothing this size ever executes in lint)."""
    from rcmarl_tpu.config import Config, Roles, circulant_in_nodes

    n = 1024
    base = dict(
        n_agents=n,
        agent_roles=(Roles.COOPERATIVE,) * n,
        in_nodes=circulant_in_nodes(n, 5),
        graph_schedule="random_geometric",
        graph_degree=8,
        H=2,
        fit_clip=1.0,
        hidden=(4,),
        env="congestion",
        n_episodes=2,
        n_ep_fixed=2,
        max_ep_len=4,
        n_epochs=1,
    )
    base.update(overrides)
    return Config(**base)


def census_cfg(**overrides):
    """The collective-census variant: 4 cooperative agents on a
    circulant degree-3 ring, so the agent axis tiles evenly over a
    2-wide mesh 'agent' dimension (the seed×agent sharding the census
    compiles; 3 agents would not tile)."""
    from rcmarl_tpu.config import Roles, circulant_in_nodes

    return tiny_cfg(
        n_agents=4,
        agent_roles=(Roles.COOPERATIVE,) * 4,
        in_nodes=circulant_in_nodes(4, 3),
        **overrides,
    )
