"""PRNG-discipline AST pass.

The framework's reproducibility contract (PARITY.md, tests' golden
pins) rests on every PRNG stream staying exactly where the spec puts
it: keys are split or folded into dedicated sub-streams, each sub-key
is consumed exactly once, and jitted code never mints keys from raw
ints (a key baked into a traced program makes every trace replay the
same stream). These rules caught nothing less than the clean structure
the package already has — their job is to keep it that way:

- ``prng-reuse`` — a key expression consumed by more than one direct
  ``jax.random`` sampler call in a scope, consumed after being passed
  to ``split`` (the classic parent-key footgun), split after being
  consumed, or folded twice with the same static tag (two identical
  derived streams). Rebinding the name (``key = fold_in(key, tag)``)
  resets its history.
- ``prng-split-discard`` — ``split()`` entropy thrown away: an
  ``_``-target in the unpack, a direct subscript of the split call, or
  a split whose result is discarded entirely. Use ``fold_in`` (or
  split fewer keys) instead of discarding streams positionally.
- ``prng-int-seed`` — ``jax.random.PRNGKey``/``jax.random.key`` called
  inside the jitted hot-path modules (:data:`.findings.HOT_PATH_PATTERNS`):
  keys must flow in as arguments; a constant seed inside traced code is
  a compile-time constant stream. (Host-side modules — CLI, trainer
  setup, analysis — mint keys freely.)
- ``prng-fold-tag`` — ``fold_in`` with a bare integer-literal tag in a
  hot-path module. Dedicated streams follow the named-constant pattern
  ``faults.py`` established (``_FAULT_STREAM``): the tag is part of the
  RNG-layout spec and must be greppable, not a magic number.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from rcmarl_tpu.lint.findings import Finding

#: Direct jax.random samplers: calls that CONSUME their first-arg key.
CONSUMERS = frozenset(
    {
        "ball", "bernoulli", "beta", "binomial", "bits", "categorical",
        "cauchy", "chisquare", "choice", "dirichlet", "double_sided_maxwell",
        "exponential", "f", "gamma", "generalized_normal", "geometric",
        "gumbel", "laplace", "loggamma", "logistic", "lognormal", "maxwell",
        "multivariate_normal", "normal", "orthogonal", "pareto",
        "permutation", "poisson", "rademacher", "randint", "rayleigh", "t",
        "triangular", "truncated_normal", "uniform", "wald", "weibull_min",
    }
)

KEY_MAKERS = frozenset({"PRNGKey", "key"})


def _random_aliases(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    """(jax module names, jax.random module names) bound by imports."""
    jax_names, random_names = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax":
                    jax_names.add(a.asname or "jax")
                elif a.name == "jax.random":
                    random_names.add(a.asname or "random")
        elif isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name == "random":
                    random_names.add(a.asname or "random")
    return jax_names, random_names


class _Scope:
    """Per-function linear history of key uses (text-keyed)."""

    def __init__(self) -> None:
        self.consumed: Dict[str, int] = {}
        self.split: Dict[str, int] = {}
        self.fold_tags: Dict[Tuple[str, str], int] = {}

    def rebind(self, name: str) -> None:
        for table in (self.consumed, self.split):
            for text in [t for t in table if t == name]:
                del table[text]
        for key in [k for k in self.fold_tags if k[0] == name]:
            del self.fold_tags[key]


class PRNGPass(ast.NodeVisitor):
    """See module docstring. ``hot_path`` gates the traced-code rules."""

    def __init__(self, path: str, tree: ast.Module, hot_path: bool) -> None:
        self.path = path
        self.hot_path = hot_path
        self.findings: List[Finding] = []
        self._jax, self._random = _random_aliases(tree)
        self._scopes: List[_Scope] = [_Scope()]

    # ---- classification -------------------------------------------------

    def _random_fn(self, func: ast.expr) -> Optional[str]:
        """The jax.random function name of a call target, or None."""
        if not isinstance(func, ast.Attribute):
            return None
        value = func.value
        if isinstance(value, ast.Attribute) and value.attr == "random":
            if isinstance(value.value, ast.Name) and value.value.id in self._jax:
                return func.attr
        if isinstance(value, ast.Name) and value.id in self._random:
            return func.attr
        return None

    @staticmethod
    def _text(node: ast.expr) -> str:
        return ast.unparse(node)

    # ---- scope plumbing -------------------------------------------------

    @property
    def _scope(self) -> _Scope:
        return self._scopes[-1]

    def _in_new_scope(self, node: ast.AST) -> None:
        self._scopes.append(_Scope())
        self.generic_visit(node)
        self._scopes.pop()

    def visit_FunctionDef(self, node):  # noqa: N802
        self._in_new_scope(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):  # noqa: N802
        self._in_new_scope(node)

    # ---- events ---------------------------------------------------------

    def _flag(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(Finding(rule, self.path, node.lineno, msg))

    def visit_Assign(self, node):  # noqa: N802
        self.visit(node.value)  # uses happen before the (re)bind
        is_split = (
            isinstance(node.value, ast.Call)
            and self._random_fn(node.value.func) == "split"
        )
        for target in node.targets:
            if isinstance(target, (ast.Tuple, ast.List)):
                if is_split and any(
                    isinstance(e, ast.Name) and e.id == "_"
                    for e in target.elts
                ):
                    self._flag(
                        "prng-split-discard",
                        node,
                        "split() sub-key discarded via '_' unpack; use "
                        "fold_in (or split fewer keys) instead of "
                        "throwing a stream away",
                    )
                for e in target.elts:
                    if isinstance(e, ast.Name):
                        self._scope.rebind(e.id)
            elif isinstance(target, ast.Name):
                self._scope.rebind(target.id)

    def visit_Expr(self, node):  # noqa: N802
        if (
            isinstance(node.value, ast.Call)
            and self._random_fn(node.value.func) == "split"
        ):
            self._flag(
                "prng-split-discard",
                node,
                "split() result discarded entirely (statement has no "
                "effect on any stream)",
            )
        self.generic_visit(node)

    def visit_Subscript(self, node):  # noqa: N802
        if (
            isinstance(node.value, ast.Call)
            and self._random_fn(node.value.func) == "split"
        ):
            self._flag(
                "prng-split-discard",
                node,
                "subscripting split() discards the other sub-keys; "
                "fold_in a dedicated tag instead",
            )
        self.generic_visit(node)

    def visit_Call(self, node):  # noqa: N802
        fn = self._random_fn(node.func)
        scope = self._scope
        if fn in KEY_MAKERS and self.hot_path:
            self._flag(
                "prng-int-seed",
                node,
                f"jax.random.{fn}() inside a jitted hot-path module bakes "
                "a constant stream into the traced program; pass keys in "
                "as arguments",
            )
        elif fn == "split" and node.args:
            text = self._text(node.args[0])
            if text in scope.consumed:
                self._flag(
                    "prng-reuse",
                    node,
                    f"key {text!r} split after already being consumed "
                    f"(line {scope.consumed[text]}); derive sub-keys "
                    "BEFORE sampling from a key",
                )
            scope.split[text] = node.lineno
        elif fn == "fold_in" and len(node.args) >= 2:
            text = self._text(node.args[0])
            tag = node.args[1]
            if (
                self.hot_path
                and isinstance(tag, ast.Constant)
                and isinstance(tag.value, int)
            ):
                self._flag(
                    "prng-fold-tag",
                    node,
                    f"fold_in({text}, {tag.value}) uses a bare literal "
                    "stream tag; name it like faults.py's dedicated "
                    "_FAULT_STREAM so the RNG layout stays greppable",
                )
            pair = (text, ast.dump(tag))
            if pair in scope.fold_tags:
                self._flag(
                    "prng-reuse",
                    node,
                    f"fold_in({text}, {self._text(tag)}) duplicates the "
                    f"stream derived at line {scope.fold_tags[pair]}: two "
                    "identical tags give the SAME sub-stream",
                )
            scope.fold_tags[pair] = node.lineno
        elif fn in CONSUMERS and node.args:
            text = self._text(node.args[0])
            if text in scope.consumed:
                self._flag(
                    "prng-reuse",
                    node,
                    f"key {text!r} consumed again (first consumed at "
                    f"line {scope.consumed[text]}); every sampler call "
                    "needs its own split/fold_in sub-key",
                )
            elif text in scope.split:
                self._flag(
                    "prng-reuse",
                    node,
                    f"key {text!r} consumed after being split "
                    f"(line {scope.split[text]}); sample from the "
                    "sub-keys, not the parent",
                )
            scope.consumed[text] = node.lineno
        self.generic_visit(node)


def run(path: str, tree: ast.Module, hot_path: bool) -> List[Finding]:
    p = PRNGPass(path, tree, hot_path)
    p.visit(tree)
    return p.findings
