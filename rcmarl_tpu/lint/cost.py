"""Compiled-cost ledger — the perf contracts, CI-gated.

graftlint's other arms enforce *correctness* contracts (exactly-once
retrace, donation aliasing, backend purity); this arm enforces the
*cost* contracts the perf PRs fought for. Every registered jitted entry
point (:func:`rcmarl_tpu.utils.profiling.jit_entry_points`) — both
netstack arms, the donated twins, the guarded+faulted diag variant —
plus all six aggregation-backend modes at a canonical tiny shape is
lowered and compiled through the shared memoized helpers, and XLA's own
``cost_analysis()`` / ``memory_analysis()`` are extracted into ledger
rows: FLOPs, bytes accessed, argument/output/temp buffer bytes, and the
derived peak. The committed ``AUDIT.jsonl`` is the baseline; ``python
-m rcmarl_tpu lint --cost --baseline AUDIT.jsonl`` fails with a
per-entry finding when any metric grows beyond a small tolerance
without a ledger update, so "the one-launch consensus block got
cheaper" stops being a bench-only claim and becomes a CI invariant.

Rules: ``cost-regression`` (a gated metric grew past the tolerance) and
``cost-unbaselined`` (a compiled entry has no matching ledger row — new
entry, changed canonical config fingerprint, or a stale ledger row
whose entry no longer exists). Platforms exposing no cost metadata
yield notes (donation-audit style), never silent passes. When a perf PR
legitimately changes costs, regenerate and commit the ledger in the
same PR: ``python -m rcmarl_tpu lint --cost --collectives
--write_baseline``.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from rcmarl_tpu.lint.findings import Finding

#: Default relative growth tolerance for the gated cost metrics
#: (absorbs constant-folding jitter across minor toolchain revisions; a
#: real regression — a widened layer, a dropped donation, a second
#: gather — moves these numbers by far more).
COST_TOLERANCE = 0.01

#: Absolute slack in metric units (bytes / flops) applied ONLY to
#: zero baselines, where the relative gate is meaningless — keeps a
#: 0 -> 64-byte scratch buffer from tripping, without loosening the
#: tiny canonical rows (flops in the low thousands) whose full
#: relative sensitivity is the point of the gate.
COST_ABS_SLACK = 256.0

#: The metrics the gate compares (growth beyond tolerance = finding).
#: ``alias_bytes`` is recorded but NOT gated: the donation audit owns
#: that contract with leaf-count semantics, and here a donation gain
#: would read as "regression" under a naive growth gate.
GATED_METRICS = (
    "flops",
    "bytes_accessed",
    "argument_bytes",
    "output_bytes",
    "temp_bytes",
    "peak_bytes",
)

_ANCHORS = {
    "update_block": "rcmarl_tpu/training/update.py",
    "train_block": "rcmarl_tpu/training/trainer.py",
    "gossip_mix_block": "rcmarl_tpu/parallel/gossip.py",
    "fit_block": "rcmarl_tpu/training/update.py",
    "serve_block": "rcmarl_tpu/serve/engine.py",
    "eval_block": "rcmarl_tpu/serve/engine.py",
    "actor_block": "rcmarl_tpu/serve/engine.py",
    "learner_block": "rcmarl_tpu/pipeline/trainer.py",
    "aggregation": "rcmarl_tpu/ops/aggregation.py",
}


def _anchor_for(entry: str) -> str:
    for prefix, path in _ANCHORS.items():
        if entry.startswith(prefix):
            return path
    return "rcmarl_tpu/lint/cost.py"


# --------------------------------------------------------------------------
# Ledger IO — canonical, sorted, byte-stable
# --------------------------------------------------------------------------


def canonical_rows(rows: Sequence[dict]) -> List[dict]:
    """Rows in the committed order: sorted by (kind, entry) with sorted
    keys inside each row — regenerating an unchanged ledger must leave
    a byte-identical file, whatever order the arms produced rows in."""
    return sorted(
        (json.loads(json.dumps(r, sort_keys=True)) for r in rows),
        key=lambda r: (r.get("kind", ""), r.get("entry", "")),
    )


def write_ledger(path, rows: Sequence[dict]) -> None:
    """One canonical JSON object per line, trailing newline."""
    lines = [json.dumps(r, sort_keys=True) for r in canonical_rows(rows)]
    Path(path).write_text("\n".join(lines) + "\n" if lines else "")


def read_ledger(path) -> List[dict]:
    """Parse an AUDIT.jsonl; missing file reads as an empty ledger (the
    comparison then reports every fresh row unbaselined, which is the
    correct loud failure for a deleted baseline)."""
    p = Path(path)
    if not p.exists():
        return []
    return [
        json.loads(line)
        for line in p.read_text().splitlines()
        if line.strip()
    ]


# --------------------------------------------------------------------------
# Row extraction
# --------------------------------------------------------------------------


def _compiled_metrics(compiled) -> Optional[Dict[str, float]]:
    """The gated metric dict off a jax.stages.Compiled, or None when
    the platform exposes no cost metadata (reported as a note)."""
    try:
        cost = compiled.cost_analysis()
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — platform without the API
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if cost is None or mem is None:
        return None
    arg = float(getattr(mem, "argument_size_in_bytes", 0))
    out = float(getattr(mem, "output_size_in_bytes", 0))
    tmp = float(getattr(mem, "temp_size_in_bytes", 0))
    alias = float(getattr(mem, "alias_size_in_bytes", 0))
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": tmp,
        "alias_bytes": alias,
        # live-at-once upper bound: arguments + outputs + scratch,
        # minus the buffers donation lets XLA reuse in place
        "peak_bytes": arg + out + tmp - alias,
    }


def _row(entry: str, fingerprint: str, program: str, metrics) -> dict:
    import jax

    return {
        "v": 1,
        "kind": "cost",
        "entry": entry,
        "fingerprint": fingerprint,
        "program": program,
        "platform": jax.devices()[0].platform,
        "jax": jax.__version__,
        "metrics": metrics,
    }


def cost_arms() -> Dict[str, tuple]:
    """The entry-point compile matrix: arm name -> (config, with_diag,
    entry names). Dual covers the donated twins (the donation audit's
    exact programs, shared via the compile cache); guarded is the
    undonated diag path the fault-plan trainer actually runs; gossip is
    the replica-level trimmed-mean mix launch
    (rcmarl_tpu.parallel.gossip) at its canonical 4-replica shape."""
    from rcmarl_tpu.lint.configs import (
        tiny_cfg,
        tiny_faulted_cfg,
        tiny_gossip_cfg,
        tiny_mixed_cfg,
    )

    return {
        "gossip": (
            tiny_gossip_cfg(),
            False,
            ("gossip_mix_block",),
        ),
        "dual": (
            tiny_cfg(netstack=False),
            False,
            (
                "update_block",
                "train_block",
                "update_block_donated",
                "train_block_donated",
            ),
        ),
        "stacked": (
            tiny_cfg(netstack=True),
            False,
            ("update_block", "train_block"),
        ),
        "guarded": (
            tiny_faulted_cfg(False),
            True,
            ("update_block", "train_block"),
        ),
        # the cross-flavor fused fit scan (Config.fitstack) and the
        # bf16 compute arm: the fused standalone fit program plus the
        # whole update/train block at each knob, so "the fused fit got
        # cheaper/narrower" is a ledger fact at BOTH dtypes — a mixed
        # cast (one greedy, one malicious) keeps every flavor row live
        # in the audited fused program
        "fitstack": (
            tiny_mixed_cfg(fitstack=True),
            False,
            ("update_block", "train_block", "fit_block"),
        ),
        "fitstack_bf16": (
            tiny_mixed_cfg(fitstack=True, compute_dtype="bfloat16"),
            False,
            ("update_block", "train_block", "fit_block"),
        ),
        "bf16": (
            tiny_cfg(compute_dtype="bfloat16"),
            False,
            ("update_block", "train_block"),
        ),
        # the serving subsystem: the batched inference launch and the
        # evaluate rollout block, on the dual arm's config so the
        # memoized tiny inputs are shared — "the serve program got
        # wider/heavier" becomes a ledger fact like every hot path
        "serve": (
            tiny_cfg(netstack=False),
            False,
            ("serve_block", "eval_block"),
        ),
        # the async pipeline's two tiers: the actor-tier rollout
        # program and the learner block (undonated + donated twins) at
        # a pipelined-depth config — "the decoupled tiers grew
        # heavier/diverged from the fused block" is a ledger fact, and
        # the donated twin's alias_bytes are on record next to it
        "pipeline": (
            tiny_cfg(pipeline_depth=2),
            False,
            ("actor_block", "learner_block", "learner_block_donated"),
        ),
    }


def entry_cost_rows(
    arms: Optional[Dict[str, tuple]] = None,
) -> Tuple[List[dict], List[str], set]:
    """Ledger rows for the jitted entry points, via the shared memoized
    compile helpers. Returns (rows, notes, skipped entry names) —
    skipped entries are unverifiable HERE (noted), and the comparison
    must not read their ledger rows as stale."""
    from rcmarl_tpu.utils.profiling import (
        compiled_entry_points,
        config_fingerprint,
    )

    rows: List[dict] = []
    notes: List[str] = []
    skipped: set = set()
    for arm, (cfg, with_diag, names) in (arms or cost_arms()).items():
        fp = config_fingerprint(cfg) + ("+diag" if with_diag else "")
        for name, ce in compiled_entry_points(cfg, with_diag, names).items():
            entry = f"{name}@{arm}"
            metrics = _compiled_metrics(ce.compiled)
            if metrics is None:
                notes.append(
                    f"{entry}: platform exposes no cost/memory analysis; "
                    "cost unverifiable here"
                )
                skipped.add(entry)
                continue
            rows.append(_row(entry, fp, ce.fingerprint, metrics))
    return rows, notes, skipped


def aggregation_cost_rows() -> Tuple[List[dict], List[str], set]:
    """Ledger rows for all six aggregation-backend modes (× sanitize)
    at the canonical tiny shape the backend purity audit uses. Returns
    (rows, notes, skipped entry names)."""
    import hashlib

    import jax
    import jax.numpy as jnp
    import numpy as np

    from rcmarl_tpu.ops.aggregation import (
        AUDIT_BACKEND_MODES,
        resilient_aggregate_tree,
    )
    from rcmarl_tpu.utils.profiling import program_fingerprint

    rows: List[dict] = []
    notes: List[str] = []
    skipped: set = set()
    tree = {
        "w": jnp.ones((5, 3, 4), jnp.float32),
        "b": jnp.ones((5, 7), jnp.float32),
    }
    valid = jnp.asarray(np.array([1.0, 1.0, 1.0, 1.0, 0.0]), jnp.float32)
    for name, recipe in AUDIT_BACKEND_MODES:
        for sanitize in (False, True):
            kwargs = {"impl": recipe["impl"], "sanitize": sanitize}
            H = jnp.asarray(1, jnp.int32) if recipe.get("traced_h") else 1
            if recipe.get("masked"):
                kwargs["valid"] = valid
            entry = f"aggregation[{name}{'+sanitize' if sanitize else ''}]"
            fp = hashlib.sha256(
                repr((name, sorted(kwargs.items()), "5x3x4+5x7")).encode()
            ).hexdigest()[:12]
            try:
                lowered = jax.jit(
                    lambda t, kw=kwargs, h=H: resilient_aggregate_tree(
                        t, h, **kw
                    )
                ).lower(tree)
                compiled = lowered.compile()
            except Exception as e:  # noqa: BLE001 — e.g. a real Pallas
                # kernel on a CPU host: not compilable here, so its cost
                # is noted as unverifiable, never silently passed
                notes.append(
                    f"{entry}: not compilable on this platform "
                    f"({type(e).__name__}: {str(e)[:120]}); cost "
                    "unverifiable here"
                )
                skipped.add(entry)
                continue
            metrics = _compiled_metrics(compiled)
            if metrics is None:
                notes.append(
                    f"{entry}: platform exposes no cost/memory analysis; "
                    "cost unverifiable here"
                )
                skipped.add(entry)
                continue
            rows.append(_row(entry, fp, program_fingerprint(lowered), metrics))
    return rows, notes, skipped


def cost_rows() -> Tuple[List[dict], List[str], set]:
    """All cost-kind ledger rows: entry points + aggregation modes.
    Returns (rows, notes, skipped entry names)."""
    rows, notes, skipped = entry_cost_rows()
    arows, anotes, askipped = aggregation_cost_rows()
    return rows + arows, notes + anotes, skipped | askipped


# --------------------------------------------------------------------------
# The gate
# --------------------------------------------------------------------------


def _grew(old: float, new: float, tol: float) -> bool:
    """``new`` grew past ``old``: relative tolerance on a nonzero
    baseline; on a ZERO baseline the absolute :data:`COST_ABS_SLACK`
    (a 0 -> tiny scratch buffer is noise, anything bigger is real)."""
    return new > (old * (1.0 + tol) if old else COST_ABS_SLACK)


def compare_cost(
    baseline: Sequence[dict],
    fresh: Sequence[dict],
    tol: float = COST_TOLERANCE,
    skipped=frozenset(),
) -> Tuple[List[Finding], List[str]]:
    """Diff fresh cost rows against the committed ledger.

    Findings: ``cost-regression`` when a gated metric grows beyond
    ``tol`` (relative; :data:`COST_ABS_SLACK` absolute on a zero
    baseline);
    ``cost-unbaselined`` for fresh entries with no ledger row, ledger
    rows whose config fingerprint no longer matches (the canonical
    audit shape changed), and stale ledger rows with no fresh
    counterpart — except entries in ``skipped``, which this host could
    not measure (already noted, not stale). Notes: platform mismatches
    (not comparable here) and metrics that SHRANK beyond tolerance (an
    unclaimed win — refresh the ledger to lock it in).
    """
    findings: List[Finding] = []
    notes: List[str] = []
    base_by_entry = {
        r["entry"]: r for r in baseline if r.get("kind") == "cost"
    }
    fresh_entries = set()
    for row in fresh:
        entry = row["entry"]
        fresh_entries.add(entry)
        anchor = _anchor_for(entry)
        base = base_by_entry.get(entry)
        if base is None:
            findings.append(
                Finding(
                    "cost-unbaselined",
                    anchor,
                    1,
                    f"{entry}: no row in the baseline ledger — regenerate "
                    "and commit AUDIT.jsonl in this PR "
                    "(lint --cost --collectives --write_baseline)",
                )
            )
            continue
        if base.get("fingerprint") != row.get("fingerprint"):
            findings.append(
                Finding(
                    "cost-unbaselined",
                    anchor,
                    1,
                    f"{entry}: canonical audit config changed "
                    f"(ledger fingerprint {base.get('fingerprint')} != "
                    f"{row.get('fingerprint')}); regenerate AUDIT.jsonl",
                )
            )
            continue
        if base.get("platform") != row.get("platform"):
            notes.append(
                f"{entry}: ledger measured on {base.get('platform')!r}, "
                f"running on {row.get('platform')!r}; cost not comparable "
                "here"
            )
            continue
        jax_skew = (
            f" (ledger generated under jax {base.get('jax')}, running "
            f"{row.get('jax')} — regenerate if this is a toolchain bump)"
            if base.get("jax") != row.get("jax")
            else ""
        )
        for metric in GATED_METRICS:
            old = float(base["metrics"].get(metric, 0.0))
            new = float(row["metrics"].get(metric, 0.0))
            if _grew(old, new, tol):
                ratio = new / old if old else float("inf")
                findings.append(
                    Finding(
                        "cost-regression",
                        anchor,
                        1,
                        f"{entry}: {metric} grew {old:.0f} -> {new:.0f} "
                        f"({ratio:.3f}x > 1+{tol:g} tolerance) without a "
                        f"ledger update{jax_skew}",
                    )
                )
            elif _grew(new, old, tol):
                notes.append(
                    f"{entry}: {metric} shrank {old:.0f} -> {new:.0f}; "
                    "refresh AUDIT.jsonl to lock the improvement in"
                )
    for entry in sorted(set(base_by_entry) - fresh_entries - set(skipped)):
        findings.append(
            Finding(
                "cost-unbaselined",
                _anchor_for(entry),
                1,
                f"{entry}: ledger row has no current counterpart (entry "
                "removed or renamed); regenerate AUDIT.jsonl",
            )
        )
    return findings, notes


def audit_cost(
    baseline_path="AUDIT.jsonl", tol: float = COST_TOLERANCE
) -> Tuple[List[Finding], List[str], List[dict]]:
    """``lint --cost``: (findings, notes, fresh rows). The fresh rows
    are returned so the CLI can write them next to a failing baseline
    (the one-click ledger diff CI uploads)."""
    fresh, notes, skipped = cost_rows()
    baseline = read_ledger(baseline_path)
    if not baseline:
        notes.append(
            f"baseline ledger {baseline_path} missing or empty; every "
            "entry below reports unbaselined"
        )
    findings, cmp_notes = compare_cost(baseline, fresh, tol, skipped)
    return findings, notes + cmp_notes, fresh
