"""Compiled-cost ledger — the perf contracts, CI-gated.

graftlint's other arms enforce *correctness* contracts (exactly-once
retrace, donation aliasing, backend purity); this arm enforces the
*cost* contracts the perf PRs fought for. Every registered jitted entry
point (:func:`rcmarl_tpu.utils.profiling.jit_entry_points`) — both
netstack arms, the donated twins, the guarded+faulted diag variant —
plus all six aggregation-backend modes at a canonical tiny shape is
lowered and compiled through the shared memoized helpers, and XLA's own
``cost_analysis()`` / ``memory_analysis()`` are extracted into ledger
rows: FLOPs, bytes accessed, argument/output/temp buffer bytes, and the
derived peak. The committed ``AUDIT.jsonl`` is the baseline; ``python
-m rcmarl_tpu lint --cost --baseline AUDIT.jsonl`` fails with a
per-entry finding when any metric grows beyond a small tolerance
without a ledger update, so "the one-launch consensus block got
cheaper" stops being a bench-only claim and becomes a CI invariant.

Rules: ``cost-regression`` (a gated metric grew past the tolerance) and
``cost-unbaselined`` (a compiled entry has no matching ledger row — new
entry, changed canonical config fingerprint, or a stale ledger row
whose entry no longer exists). Platforms exposing no cost metadata
yield notes (donation-audit style), never silent passes. When a perf PR
legitimately changes costs, regenerate and commit the ledger in the
same PR: ``python -m rcmarl_tpu lint --cost --collectives
--write_baseline``.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from rcmarl_tpu.lint.findings import Finding

#: Default relative growth tolerance for the gated cost metrics
#: (absorbs constant-folding jitter across minor toolchain revisions; a
#: real regression — a widened layer, a dropped donation, a second
#: gather — moves these numbers by far more).
COST_TOLERANCE = 0.01

#: Absolute slack in metric units (bytes / flops) applied ONLY to
#: zero baselines, where the relative gate is meaningless — keeps a
#: 0 -> 64-byte scratch buffer from tripping, without loosening the
#: tiny canonical rows (flops in the low thousands) whose full
#: relative sensitivity is the point of the gate.
COST_ABS_SLACK = 256.0

#: The metrics the gate compares (growth beyond tolerance = finding).
#: ``alias_bytes`` is recorded but NOT gated: the donation audit owns
#: that contract with leaf-count semantics, and here a donation gain
#: would read as "regression" under a naive growth gate.
GATED_METRICS = (
    "flops",
    "bytes_accessed",
    "argument_bytes",
    "output_bytes",
    "temp_bytes",
    "peak_bytes",
)

_ANCHORS = {
    "update_block": "rcmarl_tpu/training/update.py",
    "train_block": "rcmarl_tpu/training/trainer.py",
    "gossip_mix_block": "rcmarl_tpu/parallel/gossip.py",
    "gala_mix_block": "rcmarl_tpu/parallel/gala.py",
    "fit_block": "rcmarl_tpu/training/update.py",
    "consensus_block": "rcmarl_tpu/training/update.py",
    "consensus_trunk": "rcmarl_tpu/ops/pallas_consensus.py",
    "fit_scan": "rcmarl_tpu/ops/pallas_fit.py",
    "serve_block": "rcmarl_tpu/serve/engine.py",
    "fleet_block": "rcmarl_tpu/serve/fleet.py",
    "fused_serve_block": "rcmarl_tpu/ops/pallas_serve.py",
    "fused_fleet_block": "rcmarl_tpu/ops/pallas_serve.py",
    "serve_path": "rcmarl_tpu/ops/pallas_serve.py",
    "eval_block": "rcmarl_tpu/serve/engine.py",
    "actor_block": "rcmarl_tpu/serve/engine.py",
    "learner_block": "rcmarl_tpu/pipeline/trainer.py",
    "aggregation": "rcmarl_tpu/ops/aggregation.py",
    "consensus_exchange": "rcmarl_tpu/ops/exchange.py",
    "sparse_consensus": "rcmarl_tpu/ops/pallas_consensus.py",
}


def _anchor_for(entry: str) -> str:
    for prefix, path in _ANCHORS.items():
        if entry.startswith(prefix):
            return path
    return "rcmarl_tpu/lint/cost.py"


# --------------------------------------------------------------------------
# Ledger IO — canonical, sorted, byte-stable
# --------------------------------------------------------------------------


def canonical_rows(rows: Sequence[dict]) -> List[dict]:
    """Rows in the committed order: sorted by (kind, entry) with sorted
    keys inside each row — regenerating an unchanged ledger must leave
    a byte-identical file, whatever order the arms produced rows in."""
    return sorted(
        (json.loads(json.dumps(r, sort_keys=True)) for r in rows),
        key=lambda r: (r.get("kind", ""), r.get("entry", "")),
    )


def write_ledger(path, rows: Sequence[dict]) -> None:
    """One canonical JSON object per line, trailing newline."""
    lines = [json.dumps(r, sort_keys=True) for r in canonical_rows(rows)]
    Path(path).write_text("\n".join(lines) + "\n" if lines else "")


def read_ledger(path) -> List[dict]:
    """Parse an AUDIT.jsonl; missing file reads as an empty ledger (the
    comparison then reports every fresh row unbaselined, which is the
    correct loud failure for a deleted baseline)."""
    p = Path(path)
    if not p.exists():
        return []
    return [
        json.loads(line)
        for line in p.read_text().splitlines()
        if line.strip()
    ]


# --------------------------------------------------------------------------
# Row extraction
# --------------------------------------------------------------------------


def _compiled_metrics(compiled) -> Optional[Dict[str, float]]:
    """The gated metric dict off a jax.stages.Compiled, or None when
    the platform exposes no cost metadata (reported as a note)."""
    try:
        cost = compiled.cost_analysis()
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — platform without the API
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if cost is None or mem is None:
        return None
    arg = float(getattr(mem, "argument_size_in_bytes", 0))
    out = float(getattr(mem, "output_size_in_bytes", 0))
    tmp = float(getattr(mem, "temp_size_in_bytes", 0))
    alias = float(getattr(mem, "alias_size_in_bytes", 0))
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": tmp,
        "alias_bytes": alias,
        # live-at-once upper bound: arguments + outputs + scratch,
        # minus the buffers donation lets XLA reuse in place
        "peak_bytes": arg + out + tmp - alias,
    }


def _row(entry: str, fingerprint: str, program: str, metrics) -> dict:
    import jax

    return {
        "v": 1,
        "kind": "cost",
        "entry": entry,
        "fingerprint": fingerprint,
        "program": program,
        "platform": jax.devices()[0].platform,
        "jax": jax.__version__,
        "metrics": metrics,
    }


def cost_arms() -> Dict[str, tuple]:
    """The entry-point compile matrix: arm name -> (config, with_diag,
    entry names). Dual covers the donated twins (the donation audit's
    exact programs, shared via the compile cache); guarded is the
    undonated diag path the fault-plan trainer actually runs; gossip is
    the replica-level trimmed-mean mix launch
    (rcmarl_tpu.parallel.gossip) at its canonical 4-replica shape."""
    from rcmarl_tpu.lint.configs import (
        tiny_cfg,
        tiny_faulted_cfg,
        tiny_gala_cfg,
        tiny_gossip_cfg,
        tiny_mixed_cfg,
    )

    return {
        "gossip": (
            tiny_gossip_cfg(),
            False,
            ("gossip_mix_block",),
        ),
        # the composed fleet's stack->mix->unstack launch over solo
        # replica trees (rcmarl_tpu.parallel.gala) at the same
        # canonical 4-replica shape
        "gala": (
            tiny_gala_cfg(),
            False,
            ("gala_mix_block",),
        ),
        "dual": (
            tiny_cfg(netstack=False),
            False,
            (
                "update_block",
                "train_block",
                "update_block_donated",
                "train_block_donated",
            ),
        ),
        "stacked": (
            tiny_cfg(netstack=True),
            False,
            ("update_block", "train_block"),
        ),
        "guarded": (
            tiny_faulted_cfg(False),
            True,
            ("update_block", "train_block"),
        ),
        # the cross-flavor fused fit scan (Config.fitstack) and the
        # bf16 compute arm: the fused standalone fit program plus the
        # whole update/train block at each knob, so "the fused fit got
        # cheaper/narrower" is a ledger fact at BOTH dtypes — a mixed
        # cast (one greedy, one malicious) keeps every flavor row live
        # in the audited fused program
        "fitstack": (
            tiny_mixed_cfg(fitstack=True),
            False,
            ("update_block", "train_block", "fit_block"),
        ),
        "fitstack_bf16": (
            tiny_mixed_cfg(fitstack=True, compute_dtype="bfloat16"),
            False,
            ("update_block", "train_block", "fit_block"),
        ),
        "bf16": (
            tiny_cfg(compute_dtype="bfloat16"),
            False,
            ("update_block", "train_block"),
        ),
        # the serving subsystem: the batched inference launch and the
        # evaluate rollout block, on the dual arm's config so the
        # memoized tiny inputs are shared — "the serve program got
        # wider/heavier" becomes a ledger fact like every hot path
        "serve": (
            tiny_cfg(netstack=False),
            False,
            ("serve_block", "eval_block"),
        ),
        # fleet serving (ROADMAP item 4b): the F=2 stacked multi-policy
        # launch on the same shared-inputs config — the ledger is what
        # makes "F members cost F x one member plus a routing gather, no
        # more" a CI fact: fleet_block@fleet's flops vs
        # serve_block@serve's at the same batch pin the linear-in-F
        # scaling, and any silently quadratic re-route would trip here
        "fleet": (
            tiny_cfg(netstack=False),
            False,
            ("fleet_block",),
        ),
        # the ONE-KERNEL serving path (interpret arm on this host): the
        # fused solo + fleet programs at the canonical tiny serving
        # shape — like the fused-epoch arm below, interpret-mode rows
        # are regression anchors (deterministic per jax version), not
        # HBM claims; the headline serving bytes gate lives in the
        # serve_path rows (fused_serve_cost_rows)
        "serve_fused": (
            tiny_cfg(netstack=False),
            False,
            ("fused_serve_block", "fused_fleet_block"),
        ),
        # the async pipeline's two tiers: the actor-tier rollout
        # program and the learner block (undonated + donated twins) at
        # a pipelined-depth config — "the decoupled tiers grew
        # heavier/diverged from the fused block" is a ledger fact, and
        # the donated twin's alias_bytes are on record next to it
        "pipeline": (
            tiny_cfg(pipeline_depth=2),
            False,
            ("actor_block", "learner_block", "learner_block_donated"),
        ),
        # the ONE-KERNEL epoch (interpret arm on this host): the fused
        # phase-II standalone entry plus the whole epoch programs with
        # the fused consensus AND the fit-scan kernel active, at the
        # guarded+faulted+sanitize shape — interpret-mode rows are
        # regression anchors (deterministic per jax version), not HBM
        # claims; the headline bytes gate lives in the
        # consensus_trunk/fit_scan rows (fused_consensus_cost_rows).
        # Real-Pallas-on-CPU compiles stay notes, never passes (the
        # aggregation arm below probes exactly that).
        "fused": (
            tiny_faulted_cfg(
                True,
                consensus_impl="pallas_fused_interpret",
                fitstack="pallas_interpret",
            ),
            False,
            ("update_block", "train_block", "consensus_block", "fit_block"),
        ),
        # the stacked XLA reference phase II standalone — the
        # two-launch comparison arm the fused entry is diffed against
        "consensus_ref": (
            tiny_faulted_cfg(True),
            False,
            ("consensus_block",),
        ),
    }


def entry_cost_rows(
    arms: Optional[Dict[str, tuple]] = None,
) -> Tuple[List[dict], List[str], set]:
    """Ledger rows for the jitted entry points, via the shared memoized
    compile helpers. Returns (rows, notes, skipped entry names) —
    skipped entries are unverifiable HERE (noted), and the comparison
    must not read their ledger rows as stale."""
    from rcmarl_tpu.utils.profiling import (
        compiled_entry_points,
        config_fingerprint,
    )

    rows: List[dict] = []
    notes: List[str] = []
    skipped: set = set()
    for arm, (cfg, with_diag, names) in (arms or cost_arms()).items():
        fp = config_fingerprint(cfg) + ("+diag" if with_diag else "")
        for name, ce in compiled_entry_points(cfg, with_diag, names).items():
            entry = f"{name}@{arm}"
            metrics = _compiled_metrics(ce.compiled)
            if metrics is None:
                notes.append(
                    f"{entry}: platform exposes no cost/memory analysis; "
                    "cost unverifiable here"
                )
                skipped.add(entry)
                continue
            rows.append(_row(entry, fp, ce.fingerprint, metrics))
    return rows, notes, skipped


def aggregation_cost_rows() -> Tuple[List[dict], List[str], set]:
    """Ledger rows for all six aggregation-backend modes (× sanitize)
    at the canonical tiny shape the backend purity audit uses. Returns
    (rows, notes, skipped entry names)."""
    import hashlib

    import jax
    import jax.numpy as jnp
    import numpy as np

    from rcmarl_tpu.ops.aggregation import (
        AUDIT_BACKEND_MODES,
        resilient_aggregate_tree,
    )
    from rcmarl_tpu.utils.profiling import program_fingerprint

    rows: List[dict] = []
    notes: List[str] = []
    skipped: set = set()
    tree = {
        "w": jnp.ones((5, 3, 4), jnp.float32),
        "b": jnp.ones((5, 7), jnp.float32),
    }
    valid = jnp.asarray(np.array([1.0, 1.0, 1.0, 1.0, 0.0]), jnp.float32)
    for name, recipe in AUDIT_BACKEND_MODES:
        for sanitize in (False, True):
            kwargs = {"impl": recipe["impl"], "sanitize": sanitize}
            H = jnp.asarray(1, jnp.int32) if recipe.get("traced_h") else 1
            if recipe.get("masked"):
                kwargs["valid"] = valid
            entry = f"aggregation[{name}{'+sanitize' if sanitize else ''}]"
            fp = hashlib.sha256(
                repr((name, sorted(kwargs.items()), "5x3x4+5x7")).encode()
            ).hexdigest()[:12]
            try:
                lowered = jax.jit(
                    lambda t, kw=kwargs, h=H: resilient_aggregate_tree(
                        t, h, **kw
                    )
                ).lower(tree)
                compiled = lowered.compile()
            except Exception as e:  # noqa: BLE001 — e.g. a real Pallas
                # kernel on a CPU host: not compilable here, so its cost
                # is noted as unverifiable, never silently passed
                notes.append(
                    f"{entry}: not compilable on this platform "
                    f"({type(e).__name__}: {str(e)[:120]}); cost "
                    "unverifiable here"
                )
                skipped.add(entry)
                continue
            metrics = _compiled_metrics(compiled)
            if metrics is None:
                notes.append(
                    f"{entry}: platform exposes no cost/memory analysis; "
                    "cost unverifiable here"
                )
                skipped.add(entry)
                continue
            rows.append(_row(entry, fp, program_fingerprint(lowered), metrics))
    return rows, notes, skipped


def consensus_cost_programs(cfg):
    """The three programs behind the ``consensus_trunk`` ledger rows,
    plus their canonical inputs: ``two_launch_1`` (gather + transport
    fault — materializes the ``(N, n_in, P_trunk)`` block),
    ``two_launch_2`` (per-agent trim/clip/mean of that block), and
    ``math_twin`` (the same math as ONE XLA program — its compiled
    FLOPs are the fused kernel's arithmetic, since the in-register
    gather adds none). All three are jittable closures over the config;
    shapes come from the REAL pair-block layout of ``cfg``. Lives with
    the audit (not in ops/): these programs exist to be compiled for
    the ledger, never to run in the hot path.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rcmarl_tpu.models.mlp import init_stacked_mlp
    from rcmarl_tpu.ops.aggregation import resilient_aggregate
    from rcmarl_tpu.training.update import (
        _pair_block,
        _pair_segments,
        _pair_trunk_split,
    )

    critic = init_stacked_mlp(
        jax.random.PRNGKey(0), cfg.n_agents, cfg.obs_dim, cfg.hidden, 1
    )
    tr = init_stacked_mlp(
        jax.random.PRNGKey(1), cfg.n_agents, cfg.sa_dim, cfg.hidden, 1
    )
    segments = _pair_segments(critic, tr)
    n_trunk, _ = _pair_trunk_split(segments)
    pair = _pair_block(critic, tr)[:, :n_trunk]
    stale_blk = _pair_block(
        jax.tree.map(lambda l: l * 0.5, critic),
        jax.tree.map(lambda l: l * 0.5, tr),
    )[:, :n_trunk]
    in_arr = np.asarray(cfg.padded_in_nodes()[0])
    plan = cfg.fault_plan
    sanitize = cfg.consensus_sanitize
    H = cfg.H
    trunk_segments = tuple(s for s in segments if s[2] < n_trunk)

    def gather(block):
        return block[jnp.asarray(in_arr)]

    def fault(fkey, nbr, stale_nbr):
        if plan is None or not plan.active:
            return nbr
        from rcmarl_tpu.faults import apply_link_faults_flat

        return apply_link_faults_flat(
            fkey, nbr, stale_nbr, plan, trunk_segments
        )

    def two_launch_1(msgs, stale, fkey):
        return fault(fkey, gather(msgs), gather(stale))

    def two_launch_2(nbr):
        return jax.vmap(
            lambda v: resilient_aggregate(
                v, H, "xla", n_agents=cfg.n_agents, sanitize=sanitize
            )
        )(nbr)

    def math_twin(msgs, stale, fkey):
        return two_launch_2(two_launch_1(msgs, stale, fkey))

    inputs = (pair, stale_blk, jax.random.PRNGKey(7))
    return {
        "two_launch_1": two_launch_1,
        "two_launch_2": two_launch_2,
        "math_twin": math_twin,
        "inputs": inputs,
        "n_trunk": n_trunk,
        "n_in": int(in_arr.shape[1]),
    }


def fused_consensus_cost_rows() -> Tuple[List[dict], List[str], set]:
    """The one-kernel-epoch HBM ledger: ``consensus_trunk[two_launch]``
    vs ``consensus_trunk[pallas_fused]`` and ``fit_scan[xla_carry]`` vs
    ``fit_scan[pallas_resident]`` — the row pairs
    :func:`fused_gate_findings` compares (bytes strictly lower at equal
    FLOPs, the ISSUE-13 acceptance gate).

    Honesty model, spelled out on every row:

    - the TWO-LAUNCH consensus arm is MEASURED: XLA ``cost_analysis``
      of (1) the gather + transport-fault launch that materializes the
      ``(N, n_in, P_trunk)`` block and (2) the trim/clip/mean launch
      that re-reads it, summed (``bytes_model: 'xla-cost-analysis'``).
    - the FUSED consensus arm's FLOPs are the compiled FLOPs of the
      math twin — the same gather+fault+aggregate arithmetic as ONE XLA
      program (the kernel executes the identical op sequence and the
      in-register gather adds none), and its bytes are the kernel's
      exact BlockSpec DMA arithmetic
      (:func:`rcmarl_tpu.ops.pallas_consensus.fused_consensus_dma_bytes`)
      — deterministic traffic, not an estimate (``bytes_model:
      'pallas-blockspec-dma'``). Interpret-mode cost analysis is
      useless for this claim (the interpreter's grid loop pollutes
      every metric), and the real lowering cannot compile on a CPU
      host — the BlockSpec arithmetic is the one honest source.
    - the fit rows are BOTH analytic (``bytes_model:
      'analytic-scan-carry'``): an XLA scan round-trips its parameter
      carry through HBM every step (``2*steps*P``) where the kernel
      holds it VMEM-resident (``2*P``); data/plan bytes count once for
      both, FLOPs are the measured XLA scan program's for both (the
      kernel traces the identical per-step math).
    """
    import jax

    from rcmarl_tpu.lint.configs import tiny_faulted_cfg, tiny_mixed_cfg
    from rcmarl_tpu.ops.pallas_consensus import fused_consensus_dma_bytes
    from rcmarl_tpu.utils.profiling import (
        config_fingerprint,
        program_fingerprint,
    )

    rows: List[dict] = []
    notes: List[str] = []
    skipped: set = set()

    def measure(fn, *args):
        lowered = jax.jit(fn).lower(*args)
        compiled = lowered.compile()
        return _compiled_metrics(compiled), program_fingerprint(lowered)

    # ---- consensus_trunk pair (guarded+faulted+sanitize tiny shape)
    cfg = tiny_faulted_cfg(True)
    fp = config_fingerprint(cfg)
    progs = consensus_cost_programs(cfg)
    msgs, stale, fkey = progs["inputs"]
    m1, _ = measure(progs["two_launch_1"], msgs, stale, fkey)
    # abstract shapes suffice to lower launch 2 — no second compile or
    # device execution of launch 1 on the lint hot path
    nbr = jax.eval_shape(progs["two_launch_1"], msgs, stale, fkey)
    m2, _ = measure(progs["two_launch_2"], nbr)
    twin, fp_twin = measure(progs["math_twin"], msgs, stale, fkey)
    if m1 is None or m2 is None or twin is None:
        notes.append(
            "consensus_trunk: platform exposes no cost/memory analysis; "
            "the fused HBM gate is unverifiable here"
        )
        skipped.update(
            {"consensus_trunk[two_launch]", "consensus_trunk[pallas_fused]"}
        )
    else:
        two = {k: m1[k] + m2[k] for k in m1}
        two["peak_bytes"] = (
            two["argument_bytes"]
            + two["output_bytes"]
            + two["temp_bytes"]
            - two["alias_bytes"]
        )
        row_two = _row("consensus_trunk[two_launch]", fp, fp_twin, two)
        row_two["bytes_model"] = "xla-cost-analysis"
        rows.append(row_two)
        kernel_bytes = fused_consensus_dma_bytes(
            cfg.n_agents, progs["n_in"], progs["n_trunk"], cfg.fault_plan
        )
        arg_bytes = float(msgs.size * 4 + stale.size * 4 + fkey.size * 4)
        out_bytes = float(cfg.n_agents * progs["n_trunk"] * 4)
        fused = {
            "flops": twin["flops"],
            "bytes_accessed": kernel_bytes,
            "argument_bytes": arg_bytes,
            "output_bytes": out_bytes,
            "temp_bytes": 0.0,
            "alias_bytes": 0.0,
            "peak_bytes": arg_bytes + out_bytes,
        }
        row_fused = _row("consensus_trunk[pallas_fused]", fp, fp_twin, fused)
        row_fused["bytes_model"] = "pallas-blockspec-dma"
        row_fused["flops_model"] = "math-twin-xla"
        rows.append(row_fused)

    # ---- fit_scan pair (mixed cast: every adversary flavor stacked)
    mcfg = tiny_mixed_cfg(fitstack=True)
    mfp = config_fingerprint(mcfg)
    try:
        from rcmarl_tpu.agents.updates import (
            adv_fit_schedule,
            adv_fused_row_block,
            fused_fit_rows,
        )
        from rcmarl_tpu.ops.pallas_fit import fit_scan_hbm_bytes
        from rcmarl_tpu.training.update import team_average_reward
        from rcmarl_tpu.utils.profiling import entry_point_inputs

        state, batch, _, key = entry_point_inputs(mcfg)
        p = state.params
        from rcmarl_tpu.agents.updates import netstack_pair_inputs
        import jax.numpy as jnp

        x2 = netstack_pair_inputs(mcfg, batch.s, batch.sa)
        r_agents = jnp.moveaxis(batch.r, 1, 0)
        r_coop = team_average_reward(mcfg, batch.r)
        block = adv_fused_row_block(
            mcfg, p.critic, p.tr, p.critic_local, x2, batch.ns,
            r_agents, r_coop, jax.random.split(key, 5),
        )
        keys_rows, params_rows, x_rows, targets_rows, _ = block
        sched = adv_fit_schedule(mcfg)
        mscan, fp_scan = measure(
            lambda k, pr, x, t, m: fused_fit_rows(
                k, pr, x, t, m, sched, mcfg
            ),
            keys_rows, params_rows, x_rows, targets_rows, batch.mask,
        )
    except Exception as e:  # noqa: BLE001 — platform without the API
        notes.append(
            f"fit_scan: reference scan not compilable here "
            f"({type(e).__name__}: {str(e)[:120]}); fit HBM gate "
            "unverifiable"
        )
        skipped.update({"fit_scan[xla_carry]", "fit_scan[pallas_resident]"})
        mscan = None
    if mscan is not None:
        for entry, resident in (
            ("fit_scan[xla_carry]", False),
            ("fit_scan[pallas_resident]", True),
        ):
            b = fit_scan_hbm_bytes(
                params_rows, x_rows, targets_rows, sched, resident
            )
            metrics = {
                "flops": mscan["flops"],
                "bytes_accessed": b,
                "argument_bytes": mscan["argument_bytes"],
                "output_bytes": mscan["output_bytes"],
                "temp_bytes": 0.0,
                "alias_bytes": 0.0,
                "peak_bytes": mscan["argument_bytes"]
                + mscan["output_bytes"],
            }
            row = _row(entry, mfp, fp_scan, metrics)
            row["bytes_model"] = "analytic-scan-carry"
            rows.append(row)
    return rows, notes, skipped


#: Canonical serving batch for the serve_path HBM gate — larger than
#: the entry-arm SERVE_AUDIT_BATCH so the gate compares at a shape
#: where the fused kernel's per-tile parameter broadcast is amortized
#: the way deployment amortizes it (one ``block_b`` tile's worth of
#: requests), keeping the bytes comparison robust rather than razor-
#: thin at a degenerate batch.
SERVE_COST_BATCH = 128


def serve_cost_programs(cfg, batch: int):
    """The programs behind the ``serve_path`` ledger rows, plus their
    canonical inputs: the three-launch XLA serving chain —
    ``forward`` (actor block -> ``(B, N, A)`` probabilities),
    ``derive_keys`` (base key -> the ``(B, N)`` per-(request, agent)
    fold-in keys), ``sample`` (keys + probabilities -> actions, the
    categorical read-back) — and ``math_twin``, the same math as ONE
    XLA program (its compiled FLOPs are the fused kernel's arithmetic,
    since the kernel executes the identical op sequence and the
    in-kernel threefry derivation adds exactly the same ARX work).
    Lives with the audit (not in ops/): these programs exist to be
    compiled for the ledger, never to run in the hot path."""
    import jax
    import jax.numpy as jnp

    from rcmarl_tpu.models.mlp import pad_features
    from rcmarl_tpu.serve.engine import batch_probs, serve_request_keys
    from rcmarl_tpu.utils.profiling import serve_entry_inputs

    block, _, key = serve_entry_inputs(cfg)
    obs = jnp.zeros((batch, cfg.n_agents, cfg.obs_dim), jnp.float32)
    N = cfg.n_agents
    width = int(block[0][0].shape[-2])

    def forward(blk, o):
        return batch_probs(cfg, blk, pad_features(o, width))

    def derive_keys(k):
        return serve_request_keys(k, batch, N)

    def sample(keys, probs):
        return jax.vmap(jax.vmap(jax.random.categorical))(
            keys, jnp.log(probs)
        ).astype(jnp.int32)

    def math_twin(blk, o, k):
        probs = forward(blk, o)
        return sample(derive_keys(k), probs), probs

    return {
        "forward": forward,
        "derive_keys": derive_keys,
        "sample": sample,
        "math_twin": math_twin,
        "inputs": (block, obs, key),
    }


def fused_serve_cost_rows() -> Tuple[List[dict], List[str], set]:
    """The one-kernel-serving HBM ledger: ``serve_path[xla_chain]`` vs
    ``serve_path[pallas_fused]`` — the row pair
    :func:`fused_gate_findings` compares (bytes strictly lower at equal
    FLOPs, the ISSUE-16 acceptance gate).

    Honesty model, the PR-13 discipline verbatim:

    - the XLA CHAIN arm is MEASURED: ``cost_analysis`` of the three
      launches the unfused path pays — forward (writes the ``(B, N,
      A)`` probabilities), key derivation (writes the ``(B, N)`` key
      block), sample (reads both back) — summed (``bytes_model:
      'xla-cost-analysis'``).
    - the FUSED arm's FLOPs are the compiled FLOPs of the math twin —
      the same forward+derive+sample arithmetic as ONE XLA program (the
      kernel executes the identical op sequence), and its bytes are the
      kernel's exact BlockSpec DMA arithmetic
      (:func:`rcmarl_tpu.ops.pallas_serve.fused_serve_dma_bytes`) —
      deterministic traffic, not an estimate (``bytes_model:
      'pallas-blockspec-dma'``). Interpret-mode cost analysis is
      useless for this claim and the real lowering cannot compile on a
      CPU host — the BlockSpec arithmetic is the one honest source.
    """
    import jax

    from rcmarl_tpu.lint.configs import tiny_cfg
    from rcmarl_tpu.ops.pallas_serve import fused_serve_dma_bytes
    from rcmarl_tpu.utils.profiling import (
        config_fingerprint,
        program_fingerprint,
    )

    rows: List[dict] = []
    notes: List[str] = []
    skipped: set = set()

    def measure(fn, *args):
        lowered = jax.jit(fn).lower(*args)
        compiled = lowered.compile()
        return _compiled_metrics(compiled), program_fingerprint(lowered)

    cfg = tiny_cfg(netstack=False)
    fp = config_fingerprint(cfg)
    progs = serve_cost_programs(cfg, SERVE_COST_BATCH)
    block, obs, key = progs["inputs"]
    m1, _ = measure(progs["forward"], block, obs)
    m2, _ = measure(progs["derive_keys"], key)
    # abstract shapes suffice to lower the sample launch — no device
    # execution of the upstream launches on the lint hot path
    keys_s = jax.eval_shape(progs["derive_keys"], key)
    probs_s = jax.eval_shape(progs["forward"], block, obs)
    m3, _ = measure(progs["sample"], keys_s, probs_s)
    twin, fp_twin = measure(progs["math_twin"], block, obs, key)
    if m1 is None or m2 is None or m3 is None or twin is None:
        notes.append(
            "serve_path: platform exposes no cost/memory analysis; "
            "the fused serving HBM gate is unverifiable here"
        )
        skipped.update({"serve_path[xla_chain]", "serve_path[pallas_fused]"})
        return rows, notes, skipped
    chain = {k: m1[k] + m2[k] + m3[k] for k in m1}
    chain["peak_bytes"] = (
        chain["argument_bytes"]
        + chain["output_bytes"]
        + chain["temp_bytes"]
        - chain["alias_bytes"]
    )
    row_chain = _row("serve_path[xla_chain]", fp, fp_twin, chain)
    row_chain["bytes_model"] = "xla-cost-analysis"
    rows.append(row_chain)
    kernel_bytes = fused_serve_dma_bytes(cfg, SERVE_COST_BATCH, mode="sample")
    leaf_bytes = float(
        sum(
            l.size * l.dtype.itemsize
            for l in jax.tree.leaves(block)
        )
    )
    arg_bytes = leaf_bytes + float(obs.size * 4) + 8.0
    out_bytes = float(
        SERVE_COST_BATCH * cfg.n_agents * 4
        + SERVE_COST_BATCH * cfg.n_agents * cfg.n_actions * 4
    )
    fused = {
        "flops": twin["flops"],
        "bytes_accessed": kernel_bytes,
        "argument_bytes": arg_bytes,
        "output_bytes": out_bytes,
        "temp_bytes": 0.0,
        "alias_bytes": 0.0,
        "peak_bytes": arg_bytes + out_bytes,
    }
    row_fused = _row("serve_path[pallas_fused]", fp, fp_twin, fused)
    row_fused["bytes_model"] = "pallas-blockspec-dma"
    row_fused["flops_model"] = "math-twin-xla"
    rows.append(row_fused)
    return rows, notes, skipped


#: Population the sparse-vs-dense exchange ledger rows measure at
#: (matching the committed PERF.jsonl mega-population bench cells).
SPARSE_EXCHANGE_N = 256


def sparse_exchange_cost_rows() -> Tuple[List[dict], List[str], set]:
    """The mega-population exchange ledger:
    ``consensus_exchange[sparse]`` vs ``consensus_exchange[dense]`` —
    the same advanced-indexing gather program
    (:func:`rcmarl_tpu.ops.exchange.sparse_gather`) compiled at n=256
    over the real flat critic+TR consensus block, once with the
    scheduled ``(N, graph_degree)`` index array and once with the dense
    ``(N, N)`` full neighborhood. Both arms are MEASURED (XLA
    ``cost_analysis``, ``bytes_model: 'xla-cost-analysis'``) and
    lowered from abstract shapes — nothing allocates. The gate
    (:data:`FUSED_GATE_PAIRS`) requires sparse ``bytes_accessed``
    strictly below dense: the exchange scales with ``n * graph_degree *
    P``, not ``n^2 * P`` — the ISSUE-18 acceptance invariant. The
    sparse row also carries the analytic byte model
    (:func:`rcmarl_tpu.ops.exchange.exchange_cost_model`) for honest
    cross-checking of the measured number."""
    import jax
    import jax.numpy as jnp

    from rcmarl_tpu.config import Roles, circulant_in_nodes
    from rcmarl_tpu.lint.configs import megapop_cfg
    from rcmarl_tpu.ops.exchange import exchange_cost_model, sparse_gather
    from rcmarl_tpu.parallel.megapop import consensus_block_struct
    from rcmarl_tpu.utils.profiling import (
        config_fingerprint,
        program_fingerprint,
    )

    rows: List[dict] = []
    notes: List[str] = []
    skipped: set = set()
    n = SPARSE_EXCHANGE_N
    cfg = megapop_cfg(
        n_agents=n,
        agent_roles=(Roles.COOPERATIVE,) * n,
        in_nodes=circulant_in_nodes(n, 5),
    )
    fp = config_fingerprint(cfg)
    block = consensus_block_struct(cfg)  # (N, P_total), abstract
    deg = cfg.resolved_graph_degree
    arms = {
        "consensus_exchange[sparse]": jax.ShapeDtypeStruct(
            (n, deg), jnp.int32
        ),
        "consensus_exchange[dense]": jax.ShapeDtypeStruct(
            (n, n), jnp.int32
        ),
    }
    for entry, idx in arms.items():
        lowered = jax.jit(sparse_gather).lower(block, idx)
        compiled = lowered.compile()
        metrics = _compiled_metrics(compiled)
        if metrics is None:
            notes.append(
                f"{entry}: platform exposes no cost/memory analysis; "
                "the sparse-exchange gate is unverifiable here"
            )
            skipped.add(entry)
            continue
        row = _row(entry, fp, program_fingerprint(lowered), metrics)
        row["bytes_model"] = "xla-cost-analysis"
        if entry.endswith("[sparse]"):
            row["analytic_bytes"] = exchange_cost_model(
                n, deg, int(block.shape[1])
            )["total"]
        rows.append(row)
    return rows, notes, skipped


def sparse_consensus_cost_rows() -> Tuple[List[dict], List[str], set]:
    """The SPARSE one-kernel-epoch ledger: ``sparse_consensus[xla_chain]``
    vs ``sparse_consensus[pallas_fused]`` — the mega-population fused
    consensus gate (ISSUE-19), measured at n=:data:`SPARSE_EXCHANGE_N`
    over the real flat critic+TR consensus block with the scheduled
    ``(N, graph_degree)`` graph as a TRACED operand.

    Honesty model, same split as :func:`fused_consensus_cost_rows`:

    - the XLA CHAIN arm is MEASURED (``bytes_model:
      'xla-cost-analysis'``): (1) the ``sparse_gather`` launch that
      materializes the ``(N, deg, P_total)`` gathered block in HBM and
      (2) the vmapped sanitize/trim/clip/mean launch that re-reads it,
      summed — the launch boundary forces the gathered block through
      HBM exactly as the pre-fusion mega-population path did.
    - the FUSED arm's FLOPs are the compiled FLOPs of the math twin
      (the same gather+aggregate arithmetic as ONE XLA program — the
      kernel's in-register ``dynamic_index_in_dim`` gather adds none),
      and its bytes are the kernel's exact BlockSpec DMA arithmetic
      plus the one scalar-prefetch DMA of the schedule block
      (:func:`rcmarl_tpu.ops.pallas_consensus.sparse_fused_dma_bytes`,
      ``bytes_model: 'pallas-blockspec-dma'``). The ``(N, deg, P)``
      gathered block appears in NEITHER term — that is the claim the
      gate pins.

    Everything lowers from abstract shapes; the 5 MB block never
    allocates on the lint hot path.
    """
    import jax
    import jax.numpy as jnp

    from rcmarl_tpu.config import Roles, circulant_in_nodes
    from rcmarl_tpu.lint.configs import megapop_cfg
    from rcmarl_tpu.ops.aggregation import resilient_aggregate
    from rcmarl_tpu.ops.exchange import sparse_gather
    from rcmarl_tpu.ops.pallas_consensus import sparse_fused_dma_bytes
    from rcmarl_tpu.parallel.megapop import consensus_block_struct
    from rcmarl_tpu.utils.profiling import (
        config_fingerprint,
        program_fingerprint,
    )

    rows: List[dict] = []
    notes: List[str] = []
    skipped: set = set()
    n = SPARSE_EXCHANGE_N
    cfg = megapop_cfg(
        n_agents=n,
        agent_roles=(Roles.COOPERATIVE,) * n,
        in_nodes=circulant_in_nodes(n, 5),
    )
    fp = config_fingerprint(cfg)
    block = consensus_block_struct(cfg)  # (N, P_total), abstract
    deg = cfg.resolved_graph_degree
    idx = jax.ShapeDtypeStruct((n, deg), jnp.int32)

    def chain_1(blk, g):
        return sparse_gather(blk, g)  # materializes (N, deg, P_total)

    def chain_2(gathered):
        return jax.vmap(
            lambda v: resilient_aggregate(
                v, cfg.H, impl="xla", n_agents=n, sanitize=True
            )
        )(gathered)

    def math_twin(blk, g):
        return chain_2(chain_1(blk, g))

    def measure(fn, *args):
        lowered = jax.jit(fn).lower(*args)
        compiled = lowered.compile()
        return _compiled_metrics(compiled), program_fingerprint(lowered)

    m1, _ = measure(chain_1, block, idx)
    # abstract shapes suffice to lower launch 2 — no execution of the
    # gather on the lint hot path
    gathered = jax.eval_shape(chain_1, block, idx)
    m2, _ = measure(chain_2, gathered)
    twin, fp_twin = measure(math_twin, block, idx)
    if m1 is None or m2 is None or twin is None:
        notes.append(
            "sparse_consensus: platform exposes no cost/memory "
            "analysis; the sparse-fused HBM gate is unverifiable here"
        )
        skipped.update(
            {"sparse_consensus[xla_chain]", "sparse_consensus[pallas_fused]"}
        )
        return rows, notes, skipped
    chain = {k: m1[k] + m2[k] for k in m1}
    chain["peak_bytes"] = (
        chain["argument_bytes"]
        + chain["output_bytes"]
        + chain["temp_bytes"]
        - chain["alias_bytes"]
    )
    row_chain = _row("sparse_consensus[xla_chain]", fp, fp_twin, chain)
    row_chain["bytes_model"] = "xla-cost-analysis"
    rows.append(row_chain)
    p_total = int(block.shape[1])
    kernel_bytes = sparse_fused_dma_bytes(n, deg, p_total, None)
    arg_bytes = float(n * p_total * 4 + n * deg * 4)
    out_bytes = float(n * p_total * 4)
    fused = {
        "flops": twin["flops"],
        "bytes_accessed": kernel_bytes,
        "argument_bytes": arg_bytes,
        "output_bytes": out_bytes,
        "temp_bytes": 0.0,
        "alias_bytes": 0.0,
        "peak_bytes": arg_bytes + out_bytes,
    }
    row_fused = _row("sparse_consensus[pallas_fused]", fp, fp_twin, fused)
    row_fused["bytes_model"] = "pallas-blockspec-dma"
    row_fused["flops_model"] = "math-twin-xla"
    rows.append(row_fused)
    return rows, notes, skipped


#: The (fused entry, two-launch reference) row pairs the HBM gate
#: compares: fused bytes_accessed strictly below the reference's at
#: FLOPs equal within :data:`COST_TOLERANCE`.
FUSED_GATE_PAIRS = (
    ("consensus_trunk[pallas_fused]", "consensus_trunk[two_launch]"),
    ("fit_scan[pallas_resident]", "fit_scan[xla_carry]"),
    ("serve_path[pallas_fused]", "serve_path[xla_chain]"),
    ("sparse_consensus[pallas_fused]", "sparse_consensus[xla_chain]"),
)


def sparse_exchange_gate_findings(
    rows: Sequence[dict], skipped=frozenset()
) -> List[Finding]:
    """``cost-sparse-gate``: the ISSUE-18 acceptance invariant as a CI
    rule — ``consensus_exchange[sparse]`` must be STRICTLY below
    ``consensus_exchange[dense]`` in BOTH ``bytes_accessed`` and
    ``flops``. Unlike the fused-kernel gate (same arithmetic, fewer
    bytes), the sparse exchange wins by doing LESS of both: the gather
    touches ``n * graph_degree`` neighbor rows instead of ``n * n``."""
    findings: List[Finding] = []
    by = {r["entry"]: r for r in rows if r.get("kind") == "cost"}
    sparse_e = "consensus_exchange[sparse]"
    dense_e = "consensus_exchange[dense]"
    if sparse_e in skipped or dense_e in skipped:
        return findings
    s, d = by.get(sparse_e), by.get(dense_e)
    if s is None or d is None:
        findings.append(
            Finding(
                "cost-sparse-gate",
                _anchor_for(sparse_e),
                1,
                f"{sparse_e} vs {dense_e}: gate pair incomplete ("
                + ", ".join(
                    f"missing {e}"
                    for e, row in ((sparse_e, s), (dense_e, d))
                    if row is None
                )
                + ")",
            )
        )
        return findings
    for metric in ("bytes_accessed", "flops"):
        sv = float(s["metrics"][metric])
        dv = float(d["metrics"][metric])
        if not sv < dv:
            findings.append(
                Finding(
                    "cost-sparse-gate",
                    _anchor_for(sparse_e),
                    1,
                    f"{sparse_e}: {metric} {sv:.0f} is not strictly "
                    f"below the dense arm's {dv:.0f} — the sparse "
                    "exchange lost its O(n*degree) scaling claim",
                )
            )
    return findings


def fused_gate_findings(
    rows: Sequence[dict], skipped=frozenset(), tol: float = COST_TOLERANCE
) -> List[Finding]:
    """``cost-fused-gate``: the ISSUE-13 acceptance invariant as a CI
    rule — for each :data:`FUSED_GATE_PAIRS` pair present in the fresh
    rows, the fused entry's ``bytes_accessed`` must be STRICTLY below
    the two-launch arm's sum at equal (±tol) FLOPs. Pairs this host
    could not measure (in ``skipped``) are already noted upstream."""
    findings: List[Finding] = []
    by = {r["entry"]: r for r in rows if r.get("kind") == "cost"}
    for fused_e, ref_e in FUSED_GATE_PAIRS:
        if fused_e in skipped or ref_e in skipped:
            continue
        f, r = by.get(fused_e), by.get(ref_e)
        if f is None or r is None:
            findings.append(
                Finding(
                    "cost-fused-gate",
                    _anchor_for(fused_e),
                    1,
                    f"{fused_e} vs {ref_e}: gate pair incomplete ("
                    + ", ".join(
                        f"missing {e}"
                        for e, row in ((fused_e, f), (ref_e, r))
                        if row is None
                    )
                    + ")",
                )
            )
            continue
        fb = float(f["metrics"]["bytes_accessed"])
        rb = float(r["metrics"]["bytes_accessed"])
        ff = float(f["metrics"]["flops"])
        rf = float(r["metrics"]["flops"])
        if not fb < rb:
            findings.append(
                Finding(
                    "cost-fused-gate",
                    _anchor_for(fused_e),
                    1,
                    f"{fused_e}: bytes_accessed {fb:.0f} is not strictly "
                    f"below the two-launch arm's {rb:.0f} — the fused "
                    "kernel lost its HBM-traffic claim",
                )
            )
        if rf and abs(ff - rf) > tol * rf:
            findings.append(
                Finding(
                    "cost-fused-gate",
                    _anchor_for(fused_e),
                    1,
                    f"{fused_e}: flops {ff:.0f} vs the two-launch arm's "
                    f"{rf:.0f} drift beyond ±{tol:g} — the bytes claim "
                    "only holds at equal arithmetic",
                )
            )
    return findings


def cost_rows() -> Tuple[List[dict], List[str], set]:
    """All cost-kind ledger rows: entry points + aggregation modes +
    the fused-epoch HBM gate pairs.
    Returns (rows, notes, skipped entry names)."""
    rows, notes, skipped = entry_cost_rows()
    arows, anotes, askipped = aggregation_cost_rows()
    frows, fnotes, fskipped = fused_consensus_cost_rows()
    srows, snotes, sskipped = fused_serve_cost_rows()
    xrows, xnotes, xskipped = sparse_exchange_cost_rows()
    crows, cnotes, cskipped = sparse_consensus_cost_rows()
    return (
        rows + arows + frows + srows + xrows + crows,
        notes + anotes + fnotes + snotes + xnotes + cnotes,
        skipped | askipped | fskipped | sskipped | xskipped | cskipped,
    )


# --------------------------------------------------------------------------
# The gate
# --------------------------------------------------------------------------


def _grew(old: float, new: float, tol: float) -> bool:
    """``new`` grew past ``old``: relative tolerance on a nonzero
    baseline; on a ZERO baseline the absolute :data:`COST_ABS_SLACK`
    (a 0 -> tiny scratch buffer is noise, anything bigger is real)."""
    return new > (old * (1.0 + tol) if old else COST_ABS_SLACK)


def compare_cost(
    baseline: Sequence[dict],
    fresh: Sequence[dict],
    tol: float = COST_TOLERANCE,
    skipped=frozenset(),
) -> Tuple[List[Finding], List[str]]:
    """Diff fresh cost rows against the committed ledger.

    Findings: ``cost-regression`` when a gated metric grows beyond
    ``tol`` (relative; :data:`COST_ABS_SLACK` absolute on a zero
    baseline);
    ``cost-unbaselined`` for fresh entries with no ledger row, ledger
    rows whose config fingerprint no longer matches (the canonical
    audit shape changed), and stale ledger rows with no fresh
    counterpart — except entries in ``skipped``, which this host could
    not measure (already noted, not stale). Notes: platform mismatches
    (not comparable here) and metrics that SHRANK beyond tolerance (an
    unclaimed win — refresh the ledger to lock it in).
    """
    findings: List[Finding] = []
    notes: List[str] = []
    base_by_entry = {
        r["entry"]: r for r in baseline if r.get("kind") == "cost"
    }
    fresh_entries = set()
    for row in fresh:
        entry = row["entry"]
        fresh_entries.add(entry)
        anchor = _anchor_for(entry)
        base = base_by_entry.get(entry)
        if base is None:
            findings.append(
                Finding(
                    "cost-unbaselined",
                    anchor,
                    1,
                    f"{entry}: no row in the baseline ledger — regenerate "
                    "and commit AUDIT.jsonl in this PR "
                    "(lint --cost --collectives --write_baseline)",
                )
            )
            continue
        if base.get("fingerprint") != row.get("fingerprint"):
            findings.append(
                Finding(
                    "cost-unbaselined",
                    anchor,
                    1,
                    f"{entry}: canonical audit config changed "
                    f"(ledger fingerprint {base.get('fingerprint')} != "
                    f"{row.get('fingerprint')}); regenerate AUDIT.jsonl",
                )
            )
            continue
        if base.get("platform") != row.get("platform"):
            notes.append(
                f"{entry}: ledger measured on {base.get('platform')!r}, "
                f"running on {row.get('platform')!r}; cost not comparable "
                "here"
            )
            continue
        jax_skew = (
            f" (ledger generated under jax {base.get('jax')}, running "
            f"{row.get('jax')} — regenerate if this is a toolchain bump)"
            if base.get("jax") != row.get("jax")
            else ""
        )
        for metric in GATED_METRICS:
            old = float(base["metrics"].get(metric, 0.0))
            new = float(row["metrics"].get(metric, 0.0))
            if _grew(old, new, tol):
                ratio = new / old if old else float("inf")
                findings.append(
                    Finding(
                        "cost-regression",
                        anchor,
                        1,
                        f"{entry}: {metric} grew {old:.0f} -> {new:.0f} "
                        f"({ratio:.3f}x > 1+{tol:g} tolerance) without a "
                        f"ledger update{jax_skew}",
                    )
                )
            elif _grew(new, old, tol):
                notes.append(
                    f"{entry}: {metric} shrank {old:.0f} -> {new:.0f}; "
                    "refresh AUDIT.jsonl to lock the improvement in"
                )
    for entry in sorted(set(base_by_entry) - fresh_entries - set(skipped)):
        findings.append(
            Finding(
                "cost-unbaselined",
                _anchor_for(entry),
                1,
                f"{entry}: ledger row has no current counterpart (entry "
                "removed or renamed); regenerate AUDIT.jsonl",
            )
        )
    return findings, notes


def audit_cost(
    baseline_path="AUDIT.jsonl", tol: float = COST_TOLERANCE
) -> Tuple[List[Finding], List[str], List[dict]]:
    """``lint --cost``: (findings, notes, fresh rows). The fresh rows
    are returned so the CLI can write them next to a failing baseline
    (the one-click ledger diff CI uploads). On top of the
    baseline diff, the fused-epoch HBM gate
    (:func:`fused_gate_findings`) re-derives the bytes-below-at-equal-
    flops invariant from the FRESH rows every run — the ledger records
    the claim, the gate keeps it true."""
    fresh, notes, skipped = cost_rows()
    baseline = read_ledger(baseline_path)
    if not baseline:
        notes.append(
            f"baseline ledger {baseline_path} missing or empty; every "
            "entry below reports unbaselined"
        )
    findings, cmp_notes = compare_cost(baseline, fresh, tol, skipped)
    findings.extend(fused_gate_findings(fresh, skipped, tol))
    findings.extend(sparse_exchange_gate_findings(fresh, skipped))
    return findings, notes + cmp_notes, fresh
