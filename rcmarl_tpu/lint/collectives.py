"""Collective census — the pod-readiness contract of the sharded programs.

ROADMAP item 2 (the Podracer/Anakin story, arXiv:2104.06272) promotes
the ``mesh={'seed', 'agent'}`` matrix program to a real multi-chip pod.
That promotion is only safe if the compiled programs' communication
stays what PARALLELISM.md measured: the seed axis embarrassingly
parallel (ZERO collectives), the agent-sharded consensus gather a
bounded, enumerated set of ICI collectives (all-gather / all-reduce /
collective-permute from the flat ``(n_in, P_total)`` block's halo
exchange), and — non-negotiably — no device->host transfer anywhere in
a train block. This module compiles the :mod:`rcmarl_tpu.parallel`
programs under a seed×agent mesh (lowering only; the collectives are
never executed, so single-core hosts are safe) and takes an HLO census:

- ``seeds@unsharded`` — replica program, agent axis unsharded: any
  collective at all is a finding (the zero-collective invariant).
- ``seeds@sharded`` / ``matrix@sharded`` — agent axis partitioned: the
  collective kinds must stay inside :data:`ALLOWED_COLLECTIVES` (the
  matrix program additionally carries the ledger-pinned all-to-all
  reshards of :data:`EXTRA_ALLOWED_COLLECTIVES` between its
  heterogeneous cell layouts), and the per-kind counts are ledger rows
  gated EXACTLY (integer counts, zero tolerance) against the committed
  ``AUDIT.jsonl``.
- every program — host transfers (infeed/outfeed/copy-to-host, host
  memory spaces, host-callback custom-calls) fail unconditionally,
  baseline or not.

Rules: ``collective-census`` (out-of-set kind, count drift vs the
ledger, unbaselined/stale rows, zero-collective violation) and
``host-transfer``. Hosts with too few devices for a mesh yield notes,
never silent passes.
"""

from __future__ import annotations

import re
from typing import Dict, List, Sequence, Tuple

from rcmarl_tpu.lint.findings import Finding

#: The enumerated collective set the flat consensus block is allowed to
#: lower to under the seed×agent mesh — the pod-readiness precondition
#: for sharding the neighbor axis (all-reduce only the trim bounds).
#: Anything else (an all-to-all in a seeds program, a ragged fallback's
#: gather-of-everything) is a census finding even before the ledger
#: comparison.
ALLOWED_COLLECTIVES = frozenset(
    {"all-gather", "all-reduce", "collective-permute", "reduce-scatter"}
)

#: Per-program-family extensions to the allowed set, keyed by entry
#: prefix. The fused heterogeneous matrix program (`train_matrix`)
#: additionally reshards activations between its cells' agent-sharded
#: layouts, which GSPMD lowers to tuple-variant ``all-to-all`` ops —
#: ICI-native on a pod and pinned to an exact ledger count like every
#: other kind. The seeds programs get NO extension: the flat
#: ``(n_in, P_total)`` consensus block must stay inside
#: :data:`ALLOWED_COLLECTIVES` alone.
EXTRA_ALLOWED_COLLECTIVES = {"matrix": frozenset({"all-to-all"})}

#: HLO op kinds the census counts (async -start/-done pairs count once,
#: on the -start). The op name is matched at its call position
#: (whitespace-preceded, directly followed by the operand paren) rather
#: than anchored on the result type, because async -start ops and
#: infeed carry TUPLE result types with internal whitespace (e.g.
#: ``%ags = (f32[2]{0}, f32[8]{0}) all-gather-start(...)``) that a
#: single-token type anchor would miss — undercounting exactly on the
#: TPU platform the pod-readiness invariant exists for. ``-done`` ops
#: never match (the alternation requires ``(`` right after the kind or
#: its ``-start`` suffix), and operand/attr references (``%all-gather.1``,
#: ``calls=%...``) are never followed by ``(``.
_COLLECTIVE_RE = re.compile(
    r"\s(all-gather|all-reduce|collective-permute|reduce-scatter|"
    r"all-to-all)(?:-start)?\("
)

#: Device->host transfer signatures: infeed/outfeed ops, explicit
#: copy-to-host, buffers placed in a host memory space (``S(5)``
#: layout annotations), and host-callback custom-calls (pure_callback /
#: io_callback lower to ``xla_*_callback`` targets).
_HOST_TRANSFER_PATTERNS = (
    # call-position match, not a result-type anchor: infeed's result is
    # a tuple type with internal whitespace (see _COLLECTIVE_RE note)
    re.compile(r"\s(infeed|outfeed|copy-to-host)(?:-start)?\("),
    re.compile(r"\{[0-9,]*:\s*\S*S\(5\)\S*\}"),
    re.compile(r'custom-call.*custom_call_target="[^"]*(callback|host)'),
)

_ANCHORS = {
    "seeds": "rcmarl_tpu/parallel/seeds.py",
    "matrix": "rcmarl_tpu/parallel/matrix.py",
}


def collective_census(hlo_text: str) -> Dict[str, int]:
    """Per-kind collective-op counts in a compiled HLO module."""
    counts: Dict[str, int] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    return counts


def host_transfer_ops(hlo_text: str) -> List[str]:
    """The HLO lines (trimmed) that smuggle a device->host transfer."""
    hits: List[str] = []
    for line in hlo_text.splitlines():
        if any(p.search(line) for p in _HOST_TRANSFER_PATTERNS):
            hits.append(line.strip()[:160])
    return hits


def _census_programs():
    """entry name -> (build_lowered, min_devices, mesh shape, sharded).

    Builders are thunks so a too-small host can note-and-skip without
    paying any tracing.
    """
    from rcmarl_tpu.config import Roles
    from rcmarl_tpu.lint.configs import census_cfg
    from rcmarl_tpu.parallel.matrix import lower_matrix
    from rcmarl_tpu.parallel.seeds import lower_parallel, make_mesh

    cfg = census_cfg()
    mal = cfg.replace(
        agent_roles=(Roles.COOPERATIVE,) * 3 + (Roles.MALICIOUS,)
    )
    return {
        "seeds@unsharded": (
            lambda: lower_parallel(
                cfg, [0, 1], 1, make_mesh(2, seed_axis=2), False
            ),
            2,
            {"seed": 2, "agent": 1},
            False,
        ),
        "seeds@sharded": (
            lambda: lower_parallel(
                cfg, [0, 1], 1, make_mesh(4, seed_axis=2), True
            ),
            4,
            {"seed": 2, "agent": 2},
            True,
        ),
        # the fused cross-flavor fit arm under the same sharded mesh:
        # phase-I fits are agent-local, so the fitstack row block must
        # add NO collectives beyond the consensus set — the ledger pins
        # its counts exactly like the base sharded program
        "seeds@sharded+fitstack": (
            lambda: lower_parallel(
                cfg.replace(fitstack=True),
                [0, 1], 1, make_mesh(4, seed_axis=2), True,
            ),
            4,
            {"seed": 2, "agent": 2},
            True,
        ),
        "matrix@sharded": (
            lambda: lower_matrix(
                cfg, [cfg, mal], [0, 1], 1, make_mesh(4, seed_axis=2), True
            ),
            4,
            {"seed": 2, "agent": 2},
            True,
        ),
    }


def census_rows(
    programs=None,
) -> Tuple[List[dict], List[Finding], List[str], set]:
    """Compile the census programs and extract ledger rows.

    Returns (rows, unconditional findings, notes, skipped entry names).
    The unconditional findings — host transfers, out-of-set collective
    kinds, collectives in the seed-only program — hold with or without
    a baseline: they are invariants, not regressions. Skipped entries
    (too few devices for the mesh) are noted, and the comparison must
    not read their ledger rows as stale. ``programs`` overrides the
    default :func:`_census_programs` table (the planted-regression
    tests feed deliberately bad programs through the same finding
    pipeline).
    """
    import jax

    from rcmarl_tpu.lint.configs import census_cfg
    from rcmarl_tpu.utils.profiling import (
        config_fingerprint,
        program_fingerprint,
    )

    rows: List[dict] = []
    findings: List[Finding] = []
    notes: List[str] = []
    skipped: set = set()
    n_dev = len(jax.devices())
    fp = config_fingerprint(census_cfg())
    if programs is None:
        programs = _census_programs()
    for entry, (build, min_dev, mesh_shape, sharded) in programs.items():
        anchor = _ANCHORS.get(
            entry.split("@", 1)[0], "rcmarl_tpu/lint/collectives.py"
        )
        if n_dev < min_dev:
            notes.append(
                f"{entry}: needs >= {min_dev} devices for the "
                f"{mesh_shape} mesh, host has {n_dev}; census skipped here"
            )
            skipped.add(entry)
            continue
        lowered = build()
        text = lowered.compile().as_text()
        counts = collective_census(text)
        hosts = host_transfer_ops(text)
        for line in hosts[:3]:
            findings.append(
                Finding(
                    "host-transfer",
                    anchor,
                    1,
                    f"{entry}: device->host transfer inside the compiled "
                    f"train block: {line}",
                )
            )
        if hosts[3:]:
            findings.append(
                Finding(
                    "host-transfer",
                    anchor,
                    1,
                    f"{entry}: ... and {len(hosts) - 3} more host-transfer "
                    "op(s)",
                )
            )
        if not sharded and counts:
            findings.append(
                Finding(
                    "collective-census",
                    anchor,
                    1,
                    f"{entry}: the seed-only program must contain ZERO "
                    f"collectives (data parallelism is embarrassingly "
                    f"parallel), found {counts}",
                )
            )
        allowed = ALLOWED_COLLECTIVES | EXTRA_ALLOWED_COLLECTIVES.get(
            entry.split("@", 1)[0], frozenset()
        )
        bad_kinds = set(counts) - allowed
        if bad_kinds:
            findings.append(
                Finding(
                    "collective-census",
                    anchor,
                    1,
                    f"{entry}: collective kind(s) {sorted(bad_kinds)} "
                    f"outside the enumerated pod-readiness set "
                    f"{sorted(allowed)}",
                )
            )
        rows.append(
            {
                "v": 1,
                "kind": "collectives",
                "entry": entry,
                "fingerprint": fp,
                "program": program_fingerprint(lowered),
                "platform": jax.devices()[0].platform,
                "jax": jax.__version__,
                "n_devices": n_dev,
                "mesh": mesh_shape,
                "collectives": counts,
                "host_transfers": len(hosts),
            }
        )
    return rows, findings, notes, skipped


def compare_census(
    baseline: Sequence[dict], fresh: Sequence[dict], skipped=frozenset()
) -> Tuple[List[Finding], List[str]]:
    """Diff fresh census rows against the ledger — EXACT (integer
    counts, zero tolerance). Any drift means either a regression or a
    deliberate communication change that must regenerate AUDIT.jsonl in
    the same PR. Entries in ``skipped`` could not be measured on this
    host (already noted) and are exempt from the stale-row check."""
    findings: List[Finding] = []
    notes: List[str] = []
    base_by_entry = {
        r["entry"]: r for r in baseline if r.get("kind") == "collectives"
    }
    fresh_entries = set()
    for row in fresh:
        entry = row["entry"]
        fresh_entries.add(entry)
        anchor = _ANCHORS.get(
            entry.split("@", 1)[0], "rcmarl_tpu/lint/collectives.py"
        )
        base = base_by_entry.get(entry)
        if base is None:
            findings.append(
                Finding(
                    "collective-census",
                    anchor,
                    1,
                    f"{entry}: no row in the baseline ledger — regenerate "
                    "and commit AUDIT.jsonl in this PR "
                    "(lint --cost --collectives --write_baseline)",
                )
            )
            continue
        if base.get("fingerprint") != row.get("fingerprint"):
            findings.append(
                Finding(
                    "collective-census",
                    anchor,
                    1,
                    f"{entry}: canonical census config changed (ledger "
                    f"fingerprint {base.get('fingerprint')} != "
                    f"{row.get('fingerprint')}); regenerate AUDIT.jsonl",
                )
            )
            continue
        if (
            base.get("platform") != row.get("platform")
            or base.get("n_devices") != row.get("n_devices")
        ):
            notes.append(
                f"{entry}: ledger measured on {base.get('platform')!r} x "
                f"{base.get('n_devices')} device(s), running "
                f"{row.get('platform')!r} x {row.get('n_devices')}; "
                "census not comparable here"
            )
            continue
        if base.get("collectives", {}) != row.get("collectives", {}):
            findings.append(
                Finding(
                    "collective-census",
                    anchor,
                    1,
                    f"{entry}: collective set drifted from the ledger — "
                    f"{base.get('collectives')} -> {row.get('collectives')} "
                    "(a deliberate communication change must regenerate "
                    "AUDIT.jsonl in the same PR)",
                )
            )
    for entry in sorted(set(base_by_entry) - fresh_entries - set(skipped)):
        findings.append(
            Finding(
                "collective-census",
                _ANCHORS.get(
                    entry.split("@", 1)[0], "rcmarl_tpu/lint/collectives.py"
                ),
                1,
                f"{entry}: ledger row has no current counterpart (entry "
                "removed or renamed); regenerate AUDIT.jsonl",
            )
        )
    return findings, notes


def audit_collectives(
    baseline_path="AUDIT.jsonl",
) -> Tuple[List[Finding], List[str], List[dict]]:
    """``lint --collectives``: (findings, notes, fresh rows)."""
    from rcmarl_tpu.lint.cost import read_ledger

    fresh, findings, notes, skipped = census_rows()
    baseline = read_ledger(baseline_path)
    if not baseline:
        notes.append(
            f"baseline ledger {baseline_path} missing or empty; every "
            "census row below reports unbaselined"
        )
    cmp_findings, cmp_notes = compare_census(baseline, fresh, skipped)
    return findings + cmp_findings, notes + cmp_notes, fresh
