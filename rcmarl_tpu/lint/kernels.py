"""``lint --kernels`` — static kernel-budget audit over every Pallas plan.

For every Pallas entry point x a shape matrix spanning (a) the tiny
lint configs the CPU suite itself compiles, (b) the BENCH_CONFIGS
scaling cells, and (c) every shape queued in ``scripts/tpu_session.sh``
(each cell carries its session step tags), the audit statically derives
per-grid-step on-chip residency from the kernel's own
``kernel_plan()`` seam — BlockSpec block shapes, scratch live sets,
scalar-prefetch operands, accumulator dtypes — and then:

================== ====================================================
rule id            what it enforces
================== ====================================================
kernel-vmem-budget a plan's per-grid-step VMEM residency (double-
                   buffered pipelined blocks + scratch) exceeds the
                   selected TPU generation's budget on a cell that must
                   fit (the tiny lint cells), or regressed a committed
                   ``feasible`` verdict
kernel-smem-budget same, for the scalar-memory residency of the
                   scalar-prefetch operands
kernel-tile-misaligned a CHOSEN tile dimension violates the dtype's
                   (sublane, lane) packing quantum — (8, 128) f32,
                   (16, 128) bf16, (32, 128) int8
kernel-dma-model-drift a committed ``*_dma_bytes`` closed-form model
                   disagrees with the traffic re-derived from the
                   plan's grid arithmetic beyond ``--cost_tol``
kernel-budget-regression a ``kernel_budget`` ledger row drifted:
                   residency/traffic grew past tolerance, a row is
                   unbaselined or stale, or the plan fingerprint
                   changed without regenerating AUDIT.jsonl
================== ====================================================

Everything here is pure shape arithmetic: plans come from
``jax.eval_shape`` of the real init/rollout/stacking chains
(:mod:`rcmarl_tpu.utils.profiling`), so mega-population session cells
price in milliseconds on any host, with no backend and no allocation.
The ``kernel_budget`` rows are therefore platform-free (no
``platform``/``jax`` keys): byte-identical wherever they are
regenerated.

Session/bench cells that exceed a generation's budget are NOT findings
— they are honest ``infeasible`` verdicts (recorded per generation in
the ledger) that the ``tpu_session.sh`` preflight uses to abort exactly
the queued steps that could not run. A finding fires only when a
must-fit lint cell busts the budget or a committed verdict regresses.

Residency model (the conservative Mosaic reading): every pipelined
VMEM block is double-buffered whenever the grid has more than one step
(compute on tile i overlaps the DMA of tile i+1), scratch is resident
once, SMEM operands live in scalar memory for the launch. Grids price
ONE launch — a vmapped launch (the per-agent aggregation) adds grid
steps, not per-step residency.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Set, Tuple

from rcmarl_tpu.lint.cost import COST_TOLERANCE, read_ledger
from rcmarl_tpu.lint.findings import Finding
from rcmarl_tpu.ops.dma_model import KernelPlan, plan_dma_bytes

#: Per-generation on-chip budgets in bytes. v4 cores carry 16 MiB of
#: VMEM; v5e/v5p carry 128 MiB. SMEM is 1 MiB everywhere. The audit
#: defaults to the STRICTEST generation (v4): a plan that fits there
#: fits everywhere.
TPU_GENERATIONS = {
    "v4": {"vmem": 16 * 2**20, "smem": 1 * 2**20},
    "v5e": {"vmem": 128 * 2**20, "smem": 1 * 2**20},
    "v5p": {"vmem": 128 * 2**20, "smem": 1 * 2**20},
}

#: Ledger row order (and the strictest-first default).
GEN_ORDER = ("v4", "v5e", "v5p")
DEFAULT_GEN = "v4"

#: Minimum sublane (second-minor) tile extent per dtype — the TPU
#: packing rule: a (sublane, 128) tile holds 8 f32 rows, 16 bf16 rows,
#: 32 int8 rows. The lane (minor) quantum is 128 for every dtype.
SUBLANE_MIN = {
    "float32": 8,
    "int32": 8,
    "uint32": 8,
    "bfloat16": 16,
    "float16": 16,
    "int16": 16,
    "uint16": 16,
    "int8": 32,
    "uint8": 32,
}
LANE_MIN = 128

#: Absolute slack (bytes) on the DMA-model drift gate: the fit scan's
#: derivation counts the (R, N) first-epoch-loss output (4·R·N bytes)
#: that the committed scan-carry model leaves out of its parameter
#: traffic — structural, bounded, and far below any real model error.
KERNEL_DRIFT_ABS_SLACK = 4096.0

#: The residency/traffic metrics the regression gate compares.
KERNEL_GATED_METRICS = ("vmem_bytes", "smem_bytes", "dma_derived_bytes")

_KERNEL_ANCHORS = {
    "fused_consensus": "rcmarl_tpu/ops/pallas_consensus.py",
    "sparse_consensus": "rcmarl_tpu/ops/pallas_consensus.py",
    "aggregation_select": "rcmarl_tpu/ops/pallas_aggregation.py",
    "aggregation_sort": "rcmarl_tpu/ops/pallas_aggregation.py",
    "fit_scan": "rcmarl_tpu/ops/pallas_fit.py",
    "fused_serve": "rcmarl_tpu/ops/pallas_serve.py",
    "fused_fleet": "rcmarl_tpu/ops/pallas_serve.py",
}


def _anchor(entry: str) -> str:
    return _KERNEL_ANCHORS.get(
        entry.split("[", 1)[0], "rcmarl_tpu/lint/kernels.py"
    )


# --------------------------------------------------------------------------
# Residency, tiling, fingerprint — pure plan arithmetic
# --------------------------------------------------------------------------


def plan_vmem_bytes(plan: KernelPlan) -> int:
    """Per-grid-step VMEM residency: every pipelined block pays double
    (Mosaic overlaps tile i's compute with tile i+1's DMA) whenever the
    grid has more than one step; scratch is resident once."""
    mult = 2 if plan.grid_steps() > 1 else 1
    total = 0
    for op in plan.inputs + plan.outputs:
        if op.memory == "vmem":
            total += op.block_bytes() * mult
    for op in plan.scratch:
        total += op.block_bytes()
    return int(total)


def plan_smem_bytes(plan: KernelPlan) -> int:
    """Scalar-memory residency: the scalar-prefetch operands, resident
    for the whole launch."""
    return int(
        sum(
            op.block_bytes()
            for op in plan.inputs + plan.outputs
            if op.memory == "smem"
        )
    )


def plan_fingerprint(plan: KernelPlan) -> str:
    """A short stable hash of the plan's full static signature (grid,
    refetch discipline, every operand's shape/dtype/memory/variance) —
    the ``kernel_budget`` rows' config-drift key."""
    sig = {
        "name": plan.name,
        "grid": [int(g) for g in plan.grid],
        "refetch": plan.refetch,
        "operands": [
            [
                role,
                op.name,
                [int(d) for d in op.block_shape],
                op.dtype,
                [bool(v) for v in op.varies],
                op.memory,
                [int(d) for d in op.tiled_dims],
            ]
            for role, ops in (
                ("in", plan.inputs),
                ("out", plan.outputs),
                ("scratch", plan.scratch),
            )
            for op in ops
        ],
    }
    blob = json.dumps(sig, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def tile_findings(plan: KernelPlan, entry: str) -> List[Finding]:
    """``kernel-tile-misaligned``: a CHOSEN tile extent (``tiled_dims``
    only — problem-determined dims like an obs width are the problem's
    business, not the tiling's) that violates the dtype packing quantum
    at the sublane (second-minor) or lane (minor) position."""
    findings: List[Finding] = []
    anchor = _anchor(entry)
    for op in plan.inputs + plan.outputs:
        nd = len(op.block_shape)
        for d in op.tiled_dims:
            if d == nd - 1:
                quantum, axis = LANE_MIN, "lane"
            elif d == nd - 2:
                quantum = SUBLANE_MIN.get(op.dtype, 8)
                axis = "sublane"
            else:
                continue
            if op.block_shape[d] % quantum:
                findings.append(
                    Finding(
                        "kernel-tile-misaligned",
                        anchor,
                        1,
                        f"{entry}: operand {op.name!r} tile dim {d} = "
                        f"{op.block_shape[d]} is not a multiple of the "
                        f"{op.dtype} {axis} quantum {quantum} "
                        f"(block {tuple(op.block_shape)}) — the tile "
                        "wastes packed registers or fails to lower",
                    )
                )
    return findings


def drift_findings(
    entry: str, model_bytes: float, derived_bytes: float, tol: float
) -> List[Finding]:
    """``kernel-dma-model-drift``: the committed closed-form model vs
    the traffic re-derived from the plan's grid arithmetic. Fires in
    BOTH directions — this is a model-accuracy check, not a growth
    gate."""
    gap = abs(derived_bytes - model_bytes)
    if gap <= max(tol * model_bytes, KERNEL_DRIFT_ABS_SLACK):
        return []
    return [
        Finding(
            "kernel-dma-model-drift",
            _anchor(entry),
            1,
            f"{entry}: committed DMA model says {model_bytes:.0f} bytes "
            f"but the BlockSpec grid arithmetic derives "
            f"{derived_bytes:.0f} ({gap:.0f} apart > "
            f"max({tol:g} rel, {KERNEL_DRIFT_ABS_SLACK:.0f} abs)) — "
            "the model and the kernel plan no longer describe the same "
            "launch",
        )
    ]


# --------------------------------------------------------------------------
# The cell matrix
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class KernelCell:
    """One (kernel, shape) audit cell. ``steps`` are the
    ``scripts/tpu_session.sh`` step tags whose queued work launches this
    plan (empty for lint-only shapes); ``must_fit`` marks the tiny lint
    cells whose infeasibility is a finding rather than a verdict.
    ``build()`` returns ``(plan, committed_model_bytes_or_None)``."""

    entry: str
    steps: Tuple[str, ...]
    must_fit: bool
    build: Callable[[], Tuple[KernelPlan, Optional[float]]]


def _bench_cfg(name: str):
    from rcmarl_tpu.cli import _bench_config

    # impl/dtype knobs don't move any shape; n_ep_fixed=10 is the
    # bench/profile CLI default the session steps inherit
    return _bench_config(name, "xla", 10)


def _agg_cell(entry, steps, cfg_fn, variant, must_fit=False) -> KernelCell:
    def build():
        from rcmarl_tpu.ops import pallas_aggregation
        from rcmarl_tpu.utils.profiling import pair_trunk_struct

        cfg = cfg_fn()
        _, _, p_pair = pair_trunk_struct(cfg)
        plan = pallas_aggregation.kernel_plan(
            cfg.n_in, p_pair, cfg.H, variant=variant
        )
        return plan, None  # no committed DMA model for the leaf kernel

    return KernelCell(entry, steps, must_fit, build)


def _consensus_cell(
    entry, steps, cfg_fn, *, faulted=False, must_fit=False
) -> KernelCell:
    def build():
        from rcmarl_tpu.ops import pallas_consensus
        from rcmarl_tpu.ops.dma_model import consensus_model_bytes
        from rcmarl_tpu.utils.profiling import pair_trunk_struct

        cfg = cfg_fn()
        n_trunk, _, _ = pair_trunk_struct(cfg)
        plan = pallas_consensus.kernel_plan(
            cfg.n_agents,
            cfg.n_in,
            n_trunk,
            active=faulted,
            has_stale=faulted,
            trim_h=cfg.H,
            sanitize=faulted,
        )
        model = consensus_model_bytes(
            cfg.n_agents,
            cfg.n_in,
            n_trunk,
            active=faulted,
            has_stale=faulted,
        )
        return plan, model

    return KernelCell(entry, steps, must_fit, build)


def _sparse_cell(entry, steps, cfg_fn, must_fit=False) -> KernelCell:
    def build():
        from rcmarl_tpu.ops import pallas_consensus
        from rcmarl_tpu.ops.dma_model import sparse_consensus_model_bytes
        from rcmarl_tpu.utils.profiling import pair_trunk_struct

        cfg = cfg_fn()
        n_trunk, _, _ = pair_trunk_struct(cfg)
        degree = cfg.resolved_graph_degree
        plan = pallas_consensus.kernel_plan(
            cfg.n_agents, degree, n_trunk, sparse=True, trim_h=cfg.H
        )
        model = sparse_consensus_model_bytes(cfg.n_agents, degree, n_trunk)
        return plan, model

    return KernelCell(entry, steps, must_fit, build)


def _fit_cell(entry, steps, cfg_fn, flavor, must_fit=False) -> KernelCell:
    def build():
        from rcmarl_tpu.ops import pallas_fit
        from rcmarl_tpu.utils.profiling import (
            coop_fit_row_structs,
            fit_row_structs,
        )

        cfg = cfg_fn()
        structs = (
            fit_row_structs(cfg)
            if flavor == "adv"
            else coop_fit_row_structs(cfg)
        )
        _, params_rows, x_rows, targets_rows, schedule = structs
        plan = pallas_fit.kernel_plan(
            params_rows, x_rows, targets_rows, schedule
        )
        model = pallas_fit.fit_scan_hbm_bytes(
            params_rows, x_rows, targets_rows, schedule, resident=True
        )
        return plan, model

    return KernelCell(entry, steps, must_fit, build)


def _serve_cell(
    entry, steps, cfg_fn, batch, *, members=0, must_fit=False
) -> KernelCell:
    def build():
        import jax

        from rcmarl_tpu.ops import pallas_serve
        from rcmarl_tpu.ops.dma_model import serve_model_bytes
        from rcmarl_tpu.utils.profiling import serve_block_struct

        cfg = cfg_fn()
        block = serve_block_struct(cfg)
        if members:
            from rcmarl_tpu.serve.fleet import fleet_stack

            block = jax.eval_shape(
                lambda b: fleet_stack([b] * members), block
            )
        plan = pallas_serve.kernel_plan(
            block, batch, cfg.n_agents, mode="sample", fleet=bool(members)
        )
        model = serve_model_bytes(
            cfg.n_agents,
            cfg.obs_dim,
            tuple(cfg.hidden),
            cfg.n_actions,
            batch,
            mode="sample",
            n_members=members,
        )
        return plan, model

    return KernelCell(entry, steps, must_fit, build)


def kernel_cells() -> List[KernelCell]:
    """The full (kernel x shape) audit matrix: every tiny lint shape
    (``must_fit``) plus every shape the TPU session queues, tagged with
    the session step(s) that launch it. Builders defer all imports and
    derive shapes through ``jax.eval_shape`` — a cell is milliseconds,
    megapop included."""
    from rcmarl_tpu.lint.configs import (
        megapop_cfg,
        tiny_cfg,
        tiny_faulted_cfg,
        tiny_mixed_cfg,
        tiny_sparse_cfg,
    )

    def default_cfg():
        from rcmarl_tpu.config import Config

        return Config()

    from rcmarl_tpu.lint.cost import SERVE_COST_BATCH

    cells: List[KernelCell] = []

    # ---- leaf aggregation (select + sorting-network arms)
    agg_steps = {
        "ref5_ring": (("2",), ("2",)),
        "n16_full": (("2", "9"), ("2",)),
        "n64_ring": (("1",), ("1",)),
        "n64_full": (("1", "2", "9"), ("1", "2")),
        "n64_large_h2": (("1", "2", "9"), ("1", "2")),
        "n256_ring": (("1", "14b"), ("1",)),
    }
    for variant in ("select", "sort"):
        cells.append(
            _agg_cell(
                f"aggregation_{variant}[tiny]",
                (),
                tiny_cfg,
                variant,
                must_fit=True,
            )
        )
        for name, (sel_tags, sort_tags) in agg_steps.items():
            cells.append(
                _agg_cell(
                    f"aggregation_{variant}[{name}]",
                    sel_tags if variant == "select" else sort_tags,
                    lambda name=name: _bench_cfg(name),
                    variant,
                )
            )

    # ---- dense fused consensus (the one-kernel epoch, step 9)
    cells.append(
        _consensus_cell(
            "fused_consensus[tiny_faulted]",
            (),
            lambda: tiny_faulted_cfg(netstack=True),
            faulted=True,
            must_fit=True,
        )
    )
    for name in ("n16_full", "n64_full", "n64_large_h2"):
        cells.append(
            _consensus_cell(
                f"fused_consensus[{name}]",
                ("9",),
                lambda name=name: _bench_cfg(name),
            )
        )

    # ---- sparse (scheduled-graph) consensus
    cells.append(
        _sparse_cell(
            "sparse_consensus[tiny_sparse]", (), tiny_sparse_cfg,
            must_fit=True,
        )
    )
    cells.append(
        _sparse_cell(
            "sparse_consensus[n256_sparse]",
            ("14", "14b", "15"),
            lambda: _bench_cfg("n256_sparse"),
        )
    )
    cells.append(
        _sparse_cell(
            "sparse_consensus[n1024_sparse]",
            ("14", "15b"),
            lambda: _bench_cfg("n1024_sparse"),
        )
    )
    cells.append(
        _sparse_cell("sparse_consensus[megapop]", (), megapop_cfg)
    )

    # ---- the fit scan (adversary minibatch rows + cooperative
    # full-batch rows — all-coop session cells launch the coop shape)
    cells.append(
        _fit_cell(
            "fit_scan[tiny_mixed]", (), tiny_mixed_cfg, "adv", must_fit=True
        )
    )
    cells.append(
        _fit_cell("fit_scan[tiny_coop]", (), tiny_cfg, "coop", must_fit=True)
    )
    cells.append(
        _fit_cell(
            "fit_scan[n16_mixed_adv]",
            ("9b",),
            lambda: _bench_cfg("n16_mixed"),
            "adv",
        )
    )
    cells.append(
        _fit_cell(
            "fit_scan[n16_mixed_coop]",
            ("9b",),
            lambda: _bench_cfg("n16_mixed"),
            "coop",
        )
    )
    cells.append(
        _fit_cell(
            "fit_scan[n64_full_coop]",
            ("9b",),
            lambda: _bench_cfg("n64_full"),
            "coop",
        )
    )

    # ---- fused serving (solo + fleet)
    cells.append(
        _serve_cell(
            f"fused_serve[tiny@{SERVE_COST_BATCH}]",
            (),
            tiny_cfg,
            SERVE_COST_BATCH,
            must_fit=True,
        )
    )
    cells.append(
        _serve_cell(
            f"fused_fleet[tiny_f2@{SERVE_COST_BATCH}]",
            (),
            tiny_cfg,
            SERVE_COST_BATCH,
            members=2,
            must_fit=True,
        )
    )
    cells.append(
        _serve_cell(
            "fused_serve[ref5@4096]", ("12", "12b"), default_cfg, 4096
        )
    )
    cells.append(
        _serve_cell(
            "fused_fleet[ref5_f4@4096]", ("10b",), default_cfg, 4096,
            members=4,
        )
    )
    return cells


# --------------------------------------------------------------------------
# Rows, comparison, audit
# --------------------------------------------------------------------------


def kernel_rows(
    tpu_gen: Optional[str] = None,
    tol: float = COST_TOLERANCE,
    cells: Optional[Sequence[KernelCell]] = None,
) -> Tuple[List[dict], List[Finding], List[str], Set[str]]:
    """Derive every cell's plan and extract ``kernel_budget`` ledger
    rows (one per generation), plus the unconditional findings.

    Returns ``(rows, findings, notes, skipped entry names)`` — the
    collectives-arm contract. Tile misalignment and DMA-model drift are
    invariants (they hold with or without a baseline, and under
    ``--write_baseline``); budget violations are findings only on
    must-fit cells at the selected generation — session cells record
    verdicts, and an infeasible one is a note here and a loud abort in
    the session preflight. Underivable cells (a shape chain that
    raises) are noted and skipped — never silently passed. ``cells``
    overrides the matrix (the planted-regression tests feed
    deliberately bad plans through the same pipeline)."""
    gen = tpu_gen or DEFAULT_GEN
    if gen not in TPU_GENERATIONS:
        raise ValueError(
            f"tpu_gen={gen!r}: expected one of {sorted(TPU_GENERATIONS)}"
        )
    rows: List[dict] = []
    findings: List[Finding] = []
    notes: List[str] = []
    skipped: Set[str] = set()
    for cell in kernel_cells() if cells is None else cells:
        try:
            plan, model = cell.build()
        except Exception as e:  # noqa: BLE001 — cost-arm discipline:
            # an underivable shape is a note + skip, never a pass
            notes.append(
                f"{cell.entry}: shape derivation failed "
                f"({type(e).__name__}: {e}); kernel cell skipped here"
            )
            skipped.update(f"{cell.entry}@{g}" for g in GEN_ORDER)
            continue
        fp = plan_fingerprint(plan)
        vmem = plan_vmem_bytes(plan)
        smem = plan_smem_bytes(plan)
        derived = float(plan_dma_bytes(plan))
        findings.extend(tile_findings(plan, cell.entry))
        if model is not None:
            findings.extend(
                drift_findings(cell.entry, float(model), derived, tol)
            )
        metrics = {
            "vmem_bytes": float(vmem),
            "smem_bytes": float(smem),
            "dma_model_bytes": float(model) if model is not None else 0.0,
            "dma_derived_bytes": derived,
        }
        for g in GEN_ORDER:
            budget = TPU_GENERATIONS[g]
            feasible = vmem <= budget["vmem"] and smem <= budget["smem"]
            rows.append(
                {
                    "v": 1,
                    "kind": "kernel_budget",
                    "entry": f"{cell.entry}@{g}",
                    "fingerprint": fp,
                    "program": plan.name,
                    "gen": g,
                    "steps": list(cell.steps),
                    "grid": [int(x) for x in plan.grid],
                    "must_fit": cell.must_fit,
                    "verdict": "feasible" if feasible else "infeasible",
                    # per-row copy: rows are independently mutable (the
                    # compare tests patch one generation's row alone)
                    "metrics": dict(metrics),
                }
            )
        budget = TPU_GENERATIONS[gen]
        over_vmem = vmem > budget["vmem"]
        over_smem = smem > budget["smem"]
        if not (over_vmem or over_smem):
            continue
        if cell.must_fit:
            if over_vmem:
                findings.append(
                    Finding(
                        "kernel-vmem-budget",
                        _anchor(cell.entry),
                        1,
                        f"{cell.entry}: per-grid-step VMEM residency "
                        f"{vmem} bytes exceeds the {gen} budget "
                        f"{budget['vmem']} on a must-fit lint cell — "
                        "shrink the block/tile or the scratch live set",
                    )
                )
            if over_smem:
                findings.append(
                    Finding(
                        "kernel-smem-budget",
                        _anchor(cell.entry),
                        1,
                        f"{cell.entry}: scalar-prefetch residency "
                        f"{smem} bytes exceeds the {gen} SMEM budget "
                        f"{budget['smem']} on a must-fit lint cell",
                    )
                )
        else:
            which = "VMEM" if over_vmem else "SMEM"
            notes.append(
                f"{cell.entry}: infeasible at {gen} ({which} "
                f"{vmem if over_vmem else smem} bytes > budget); the "
                "session preflight aborts step(s) "
                f"{list(cell.steps) or ['(lint-only shape)']} on {gen} "
                "hosts"
            )
    return rows, findings, notes, skipped


def compare_kernels(
    baseline: Sequence[dict],
    fresh: Sequence[dict],
    tol: float = COST_TOLERANCE,
    skipped=frozenset(),
) -> Tuple[List[Finding], List[str]]:
    """Diff fresh ``kernel_budget`` rows against the committed ledger.

    ``kernel-budget-regression``: a gated metric grew past ``tol``, a
    fresh row is unbaselined, a plan fingerprint changed, or a ledger
    row went stale (``skipped`` entries exempt — this host could not
    derive them, already noted). A committed ``feasible`` verdict that
    flips to ``infeasible`` fires the budget rule itself
    (``kernel-vmem-budget``/``kernel-smem-budget``) — that is the
    regression the budget table exists to catch. Shrunk metrics and
    verdicts that IMPROVED are notes: refresh the ledger to lock the
    win in. Rows are platform-free, so there is no platform skew path
    here (module docstring)."""
    findings: List[Finding] = []
    notes: List[str] = []
    base_by_entry = {
        r["entry"]: r for r in baseline if r.get("kind") == "kernel_budget"
    }
    fresh_entries = set()
    for row in fresh:
        entry = row["entry"]
        fresh_entries.add(entry)
        anchor = _anchor(entry)
        base = base_by_entry.get(entry)
        if base is None:
            findings.append(
                Finding(
                    "kernel-budget-regression",
                    anchor,
                    1,
                    f"{entry}: no row in the baseline ledger — regenerate "
                    "and commit AUDIT.jsonl in this PR "
                    "(lint --kernels --write_baseline)",
                )
            )
            continue
        if base.get("fingerprint") != row.get("fingerprint"):
            findings.append(
                Finding(
                    "kernel-budget-regression",
                    anchor,
                    1,
                    f"{entry}: kernel plan changed (ledger fingerprint "
                    f"{base.get('fingerprint')} != "
                    f"{row.get('fingerprint')}); regenerate AUDIT.jsonl",
                )
            )
            continue
        if base.get("verdict") == "feasible" and row.get("verdict") == (
            "infeasible"
        ):
            gen = row.get("gen", "?")
            budget = TPU_GENERATIONS.get(gen, TPU_GENERATIONS[DEFAULT_GEN])
            over_smem = (
                float(row["metrics"].get("smem_bytes", 0.0))
                > budget["smem"]
            )
            rule = (
                "kernel-smem-budget" if over_smem else "kernel-vmem-budget"
            )
            findings.append(
                Finding(
                    rule,
                    anchor,
                    1,
                    f"{entry}: committed verdict 'feasible' regressed to "
                    "'infeasible' — the plan no longer fits the "
                    f"{gen} budget it shipped under",
                )
            )
            continue
        if base.get("verdict") == "infeasible" and row.get("verdict") == (
            "feasible"
        ):
            notes.append(
                f"{entry}: verdict improved infeasible -> feasible; "
                "refresh AUDIT.jsonl to lock the win in"
            )
            continue
        for metric in KERNEL_GATED_METRICS:
            old = float(base["metrics"].get(metric, 0.0))
            new = float(row["metrics"].get(metric, 0.0))
            if new > old * (1.0 + tol) + 1e-9:
                findings.append(
                    Finding(
                        "kernel-budget-regression",
                        anchor,
                        1,
                        f"{entry}: {metric} grew {old:.0f} -> {new:.0f} "
                        f"(> 1+{tol:g} tolerance) without a ledger "
                        "update",
                    )
                )
            elif old > new * (1.0 + tol) + 1e-9:
                notes.append(
                    f"{entry}: {metric} shrank {old:.0f} -> {new:.0f}; "
                    "refresh AUDIT.jsonl to lock the improvement in"
                )
    for entry in sorted(set(base_by_entry) - fresh_entries - set(skipped)):
        findings.append(
            Finding(
                "kernel-budget-regression",
                _anchor(entry),
                1,
                f"{entry}: ledger row has no current counterpart (cell "
                "removed or renamed); regenerate AUDIT.jsonl",
            )
        )
    return findings, notes


def audit_kernels(
    baseline_path="AUDIT.jsonl",
    tol: float = COST_TOLERANCE,
    tpu_gen: Optional[str] = None,
) -> Tuple[List[Finding], List[str], List[dict]]:
    """``lint --kernels``: (findings, notes, fresh rows). Fresh rows
    ride back so the CLI can write them next to a failing baseline."""
    fresh, findings, notes, skipped = kernel_rows(tpu_gen, tol)
    baseline = read_ledger(baseline_path)
    if not baseline:
        notes.append(
            f"baseline ledger {baseline_path} missing or empty; every "
            "kernel row below reports unbaselined"
        )
    cmp_findings, cmp_notes = compare_kernels(baseline, fresh, tol, skipped)
    return findings + cmp_findings, notes + cmp_notes, fresh


def feasibility_lines(
    tpu_gen: Optional[str] = None, tol: float = COST_TOLERANCE
) -> List[str]:
    """The ``tpu_session.sh`` preflight feed: one
    ``step:<tag> kernel=<k> shape=<s> gen=<g> verdict=<v>`` line per
    (session step, kernel cell) pair at the selected generation.
    Underivable cells report ``verdict=unverified`` (the preflight
    aborts only on ``infeasible``)."""
    gen = tpu_gen or DEFAULT_GEN
    rows, _, notes, skipped = kernel_rows(gen, tol)
    lines = []
    for row in rows:
        if row.get("gen") != gen or not row.get("steps"):
            continue
        kernel, shape = row["entry"].rsplit("@", 1)[0].split("[", 1)
        mib = row["metrics"]["vmem_bytes"] / 2**20
        for step in row["steps"]:
            lines.append(
                f"step:{step} kernel={kernel} shape={shape.rstrip(']')} "
                f"gen={gen} verdict={row['verdict']} vmem_mib={mib:.2f}"
            )
    seen_skipped = {e.rsplit("@", 1)[0] for e in skipped}
    for cell in kernel_cells():
        if cell.entry in seen_skipped and cell.steps:
            kernel, shape = cell.entry.split("[", 1)
            for step in cell.steps:
                lines.append(
                    f"step:{step} kernel={kernel} "
                    f"shape={shape.rstrip(']')} gen={gen} "
                    "verdict=unverified vmem_mib=nan"
                )
    return sorted(lines)
