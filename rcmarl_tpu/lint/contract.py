"""Config ⇄ CLI ⇄ docs contract — the flag-surface regression net.

PR 8 grew ``Config`` fast (``fitstack``, ``compute_dtype``); nothing
machine-checks that a new field actually reaches users. This pass pins
the three surfaces a field must land on, firing ``contract-drift`` with
the field's real ``rcmarl_tpu/config.py:line`` anchor when one is
missed:

1. **CLI reachability** — every ``Config`` field must be wired from a
   CLI flag in :func:`rcmarl_tpu.cli.config_from_args` (the keyword's
   value expression must derive from ``args``), or be explicitly
   exempted in :data:`CLI_EXEMPT` with a reason (reference-parity
   constants that exist only for the Python API).
2. **JSON round-trip** — the checkpoint header format: canonical
   configs (defaults, faulted, gossip/Byzantine) must survive
   ``config_from_json(_config_to_json(cfg)) == cfg`` field for field,
   so a new field that forgets its rebuild step (tuples, nested fault
   plans) cannot silently corrupt resume.
3. **Documentation** — every field must appear as a backticked token
   in ``docs/api.md`` (the Config row enumerates them all).

Static AST + a couple of dataclass round-trips: no jax, runs anywhere.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from rcmarl_tpu.lint.findings import Finding

_CONFIG_ANCHOR = "rcmarl_tpu/config.py"

#: Fields deliberately NOT reachable from a CLI flag, with the reason —
#: an exemption is a documented decision, not a hole. Everything else
#: must be wired through :func:`rcmarl_tpu.cli.config_from_args`.
CLI_EXEMPT = {
    "leaky_alpha": "reference architecture constant (LeakyReLU 0.1, "
    "resilient_CAC_agents.py:208); Python-API only",
    "collision_physics": "opt-in *intended* collision semantics; the "
    "parity evidence is pinned to the observed-reference default — "
    "Python-API only",
    "scaling": "reference-parity constant (state/reward scaling is part "
    "of the reproduced protocol); Python-API only",
    "randomize_state": "reference-parity constant (episode-reset "
    "randomization is part of the reproduced protocol); Python-API only",
    "adv_fit_epochs": "reference adversary fit-schedule constant "
    "(adversarial_CAC_agents.py:133); Python-API only",
    "adv_fit_batch": "reference adversary fit-schedule constant "
    "(adversarial_CAC_agents.py:41); Python-API only",
    "coop_fit_steps": "reference cooperative fit constant "
    "(resilient_CAC_agents.py:118,136); Python-API only",
}


def config_field_lines() -> Dict[str, int]:
    """Every ``Config`` dataclass field -> its declaration line in
    ``rcmarl_tpu/config.py`` (the ``contract-drift`` anchor)."""
    import rcmarl_tpu.config as config_mod

    tree = ast.parse(Path(config_mod.__file__).read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return {
                stmt.target.id: stmt.lineno
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            }
    return {}


def _references(node: ast.AST, names: Set[str]) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id in names for n in ast.walk(node)
    )


def cli_reachable_fields(source: Optional[str] = None) -> Set[str]:
    """The ``Config`` fields :func:`rcmarl_tpu.cli.config_from_args`
    wires from CLI input: keywords of its ``Config(...)`` call whose
    value expression derives from ``args`` (directly or through a
    local assigned from ``args`` — a hard-coded constant keyword is NOT
    reachable; that is exactly the removed-flag drift this rule nets).

    ``source`` overrides the real ``cli.py`` text (the planted-drift
    tests feed a doctored copy through the same analysis)."""
    if source is None:
        import rcmarl_tpu.cli as cli_mod

        source = Path(cli_mod.__file__).read_text()
    tree = ast.parse(source)
    fn = next(
        (
            node
            for node in ast.walk(tree)
            if isinstance(node, ast.FunctionDef)
            and node.name == "config_from_args"
        ),
        None,
    )
    if fn is None:
        return set()
    # args-derived locals, to a fixpoint (labels/common/in_nodes chain
    # through one another before reaching the Config call)
    derived: Set[str] = {a.arg for a in fn.args.args}
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _references(
                node.value, derived
            ):
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if (
                            isinstance(n, ast.Name)
                            and n.id not in derived
                        ):
                            derived.add(n.id)
                            changed = True
    reachable: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and (
            (isinstance(node.func, ast.Name) and node.func.id == "Config")
        ):
            for kw in node.keywords:
                if kw.arg and _references(kw.value, derived):
                    reachable.add(kw.arg)
    return reachable


def documented_fields(text: Optional[str] = None) -> Set[str]:
    """Backticked tokens in ``docs/api.md`` — the documentation surface
    a Config field must appear on."""
    if text is None:
        from rcmarl_tpu.lint.findings import package_root

        path = package_root().parent / "docs" / "api.md"
        if not path.exists():
            return set()
        text = path.read_text()
    return set(re.findall(r"`([A-Za-z_]\w*)`", text))


def _roundtrip_configs():
    from rcmarl_tpu.lint.configs import (
        tiny_cfg,
        tiny_faulted_cfg,
        tiny_gossip_cfg,
    )

    return {
        "tiny": tiny_cfg(),
        "faulted": tiny_faulted_cfg(False),
        "gossip+byzantine": tiny_gossip_cfg(),
    }


def roundtrip_drift() -> List[Tuple[str, str]]:
    """Fields that fail the checkpoint-header JSON round-trip, as
    ``(field, which canonical config exposed it)`` pairs."""
    import dataclasses

    from rcmarl_tpu.utils.checkpoint import _config_to_json, config_from_json

    bad: List[Tuple[str, str]] = []
    for label, cfg in _roundtrip_configs().items():
        back = config_from_json(_config_to_json(cfg))
        for f in dataclasses.fields(cfg):
            if getattr(back, f.name) != getattr(cfg, f.name):
                bad.append((f.name, label))
    return bad


def audit_contract(
    cli_source: Optional[str] = None, api_md_text: Optional[str] = None
) -> Tuple[List[Finding], List[str]]:
    """``lint --contract``: (findings, notes). The three surface checks
    over every Config field, each finding anchored at the field's
    declaration line."""
    findings: List[Finding] = []
    notes: List[str] = []
    lines = config_field_lines()
    reachable = cli_reachable_fields(cli_source)
    for name, lineno in lines.items():
        if name in CLI_EXEMPT:
            if name in reachable:
                notes.append(
                    f"Config.{name} is CLI-exempt "
                    f"({CLI_EXEMPT[name]!r}) but IS wired from a flag "
                    "now — drop the stale exemption"
                )
            continue
        if name not in reachable:
            findings.append(
                Finding(
                    "contract-drift",
                    _CONFIG_ANCHOR,
                    lineno,
                    f"Config.{name} is not reachable from any CLI flag "
                    "(config_from_args never wires it from args) and "
                    "is not exempted in lint/contract.py:CLI_EXEMPT — "
                    "a field users cannot set is a silent API hole",
                )
            )
    stale = sorted(set(CLI_EXEMPT) - set(lines))
    for name in stale:
        findings.append(
            Finding(
                "contract-drift",
                _CONFIG_ANCHOR,
                1,
                f"CLI_EXEMPT entry {name!r} names no current Config "
                "field; drop it",
            )
        )
    for name, label in roundtrip_drift():
        findings.append(
            Finding(
                "contract-drift",
                _CONFIG_ANCHOR,
                lines.get(name, 1),
                f"Config.{name} does not survive the checkpoint-header "
                f"JSON round-trip (config_from_json, {label} config) — "
                "resume would rebuild a different experiment",
            )
        )
    docs = documented_fields(api_md_text)
    if not docs:
        notes.append(
            "docs/api.md not found; documentation contract "
            "unverifiable here"
        )
    else:
        for name, lineno in lines.items():
            if name not in docs:
                findings.append(
                    Finding(
                        "contract-drift",
                        _CONFIG_ANCHOR,
                        lineno,
                        f"Config.{name} does not appear (backticked) in "
                        "docs/api.md — every field rides the Config "
                        "table row",
                    )
                )
    return findings, notes
