"""Sharding / per-device memory / determinism audits — pod-scale proof.

ROADMAP item 2 promotes the ``mesh={'seed', 'agent'}`` programs from
dryrun to a real multi-chip pod. On this 1-core host those programs
have only ever EXECUTED unsharded (MULTICHIP_r05), so three claims the
promotion rests on have never been machine-checked:

1. **The big buffers actually shard.** A silently replicated parameter
   / optimizer / replay-ring operand costs a whole TPU session to
   discover at pod scale. This arm parses the sharding annotations off
   the compiled SPMD modules (entry operands carry their per-shard
   shape + ``sharding={...}`` in the partitioned HLO) and fires
   ``sharding-replicated`` when any operand above
   :data:`SHARDING_MIN_BYTES` carries a ``replicated`` or ``maximal``
   sharding under a >1-device mesh, and ``sharding-reshard-chain`` when
   one collective feeds another (through ``-done``/copy/reshape
   pass-throughs) — the same buffer moved twice per block.

2. **Per-device memory shrinks with the mesh.** The machine-checked
   form of "pod-ready": XLA's ``memory_analysis()`` of the partitioned
   module is PER-DEVICE, so compiling the same program at mesh sizes
   :data:`MESH_POINTS` = {1, 2, 8} and extracting
   argument/output/temp/peak bytes into canonical ``AUDIT.jsonl`` rows
   (kind ``device_memory``, same fingerprint/byte-stability discipline
   as the cost arm) turns scaling into a CI invariant:
   ``device-memory-regression`` fires when per-device peak or argument
   bytes fail to shrink from the 1-device mesh to the largest, grow
   along the mesh ladder, or grow past ``--cost_tol`` vs the ledger.

3. **The compiled programs are deterministic.** Every prior PR's
   equivalence evidence is leaf-for-leaf BITWISE; one
   implementation-defined op breaks it silently. The determinism
   census walks the entry points' StableHLO lowerings, all six
   aggregation backends, and the compiled sharded modules for
   nondeterministic HLO — float-accumulating scatters with
   ``unique_indices=false`` (duplicate-index ordering is
   implementation-defined), non-threefry ``rng_bit_generator`` /
   legacy ``rng`` ops, and cross-replica ops outside the enumerated
   collective allowlist — and fires ``nondeterminism``.

All three join ``lint --all`` / ``--write_baseline``; hosts without
enough (virtual) devices for a mesh point note-and-skip, never pass.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

from rcmarl_tpu.lint.findings import Finding

#: Operand-size floor (bytes, PER SHARD) for the replication audit: big
#: enough to skip the legitimately replicated scalars (ring pointers,
#: block counters, PRNG keys), small enough that every parameter /
#: optimizer-moment / replay-ring leaf of the canonical audit configs is
#: covered. A replicated buffer's per-shard bytes are its FULL bytes —
#: exactly the per-device cost the rule polices.
SHARDING_MIN_BYTES = 4096

#: The mesh ladder the device-memory ledger measures: per-device peak
#: must shrink monotonically 1 -> 2 -> 8 (the 8-device point is the
#: virtual-host stand-in for a pod slice).
MESH_POINTS = (1, 2, 8)

#: Minimum shrink of per-device peak/argument bytes from the 1-device
#: mesh to the largest: strictly below 1.0x (any real sharding shrinks
#: the dominant buffers by the axis extent; a flat curve means the big
#: operands replicated).
SHRINK_BELOW = 1.0

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_ANCHORS = {
    "seeds": "rcmarl_tpu/parallel/seeds.py",
    "matrix": "rcmarl_tpu/parallel/matrix.py",
    "gossip": "rcmarl_tpu/parallel/gossip.py",
    "megapop": "rcmarl_tpu/parallel/megapop.py",
}


def _anchor_for(entry: str) -> str:
    return _ANCHORS.get(
        entry.split("@", 1)[0], "rcmarl_tpu/lint/sharding.py"
    )


# --------------------------------------------------------------------------
# HLO sharding-annotation parsing
# --------------------------------------------------------------------------

#: Entry-computation operands of a partitioned module:
#: ``%p = f32[2,2000,2,2]{3,2,1,0} parameter(37), sharding={devices=
#: [1,1,2,1]<=[2]}, metadata={op_name="s.buffer.s"}`` — the shape is
#: the PER-SHARD shape, the annotation the global sharding, op_name the
#: pytree path. Only annotated parameters match (sub-computation
#: parameters carry neither sharding nor metadata).
_PARAM_RE = re.compile(
    r"%\S+ = (\w+)\[([\d,]*)\]\S* parameter\(\d+\)"
    r", sharding=\{([^}]*)\}"
    r"(?:, metadata=\{[^}]*op_name=\"([^\"]*)\"[^}]*\})?"
)


def sharded_parameters(hlo_text: str) -> List[dict]:
    """Every sharding-annotated entry operand of a compiled module:
    ``{path, dtype, bytes (per shard), sharding, kind}`` with ``kind``
    in ``'replicated'`` / ``'maximal'`` / ``'sharded'``."""
    out: List[dict] = []
    for m in _PARAM_RE.finditer(hlo_text):
        dtype, dims, sharding, path = m.groups()
        if dtype not in _DTYPE_BYTES:
            continue  # token / opaque types carry no audit-relevant bytes
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        kind = (
            "replicated"
            if sharding.strip() == "replicated"
            else "maximal"
            if sharding.strip().startswith("maximal")
            else "sharded"
        )
        out.append(
            {
                "path": path or "<unnamed>",
                "dtype": dtype,
                "bytes": n * _DTYPE_BYTES[dtype],
                "sharding": sharding.strip(),
                "kind": kind,
            }
        )
    return out


def replicated_big_operands(
    hlo_text: str, min_bytes: int = SHARDING_MIN_BYTES
) -> List[dict]:
    """The operands the sharding audit flags: parameter/optimizer/
    rollout-buffer-sized (>= ``min_bytes`` per shard) yet carrying a
    replicated or maximal sharding instead of a mesh-axis one."""
    return [
        p
        for p in sharded_parameters(hlo_text)
        if p["kind"] in ("replicated", "maximal") and p["bytes"] >= min_bytes
    ]


# --------------------------------------------------------------------------
# Reshard-chain detection
# --------------------------------------------------------------------------

#: Every cross-replica HLO op kind the walkers know about — the ONE
#: name list the chain detector, its ``-done`` pass-through set, and
#: the determinism census's broad scan all derive from, so a newly
#: taught kind is visible to all three at once.
_COLLECTIVE_KINDS = (
    "all-gather",
    "all-reduce",
    "collective-permute",
    "reduce-scatter",
    "all-to-all",
    "collective-broadcast",
    "ragged-all-to-all",
)

_KINDS_ALT = "|".join(_COLLECTIVE_KINDS)

_COLL_DEF_RE = re.compile(
    r"%([\w\.\-]+)\s*=\s*.*?\s(" + _KINDS_ALT + r")(?:-start)?\("
)

#: Ops a buffer flows through unchanged between two collectives —
#: following these keeps a ``collective -> copy -> collective`` chain
#: visible while an intervening compute op (a real consumer) breaks it.
_PASSTHROUGH_RE = re.compile(
    r"%([\w\.\-]+)\s*=\s*.*?\s(?:copy|bitcast|bitcast-convert|"
    r"reshape|transpose|convert|get-tuple-element|"
    r"(?:" + _KINDS_ALT + r")-done)\("
)

_NAME_RE = re.compile(r"%([\w\.\-]+)")
_ATTR_NAME_RE = re.compile(r"\w+=\s*%([\w\.\-]+)")


def _operand_names(line: str) -> List[str]:
    """The %names a line's op consumes (result and attr references —
    ``to_apply=%add`` etc. — excluded)."""
    head, _, rest = line.partition("(")
    attr_refs = set(_ATTR_NAME_RE.findall(line))
    result = _NAME_RE.findall(head)[:1]
    return [
        n
        for n in _NAME_RE.findall(rest)
        if n not in attr_refs and n not in result
    ]


def reshard_chains(hlo_text: str) -> List[str]:
    """Collective ops fed (through ``-done``/copy/reshape pass-throughs)
    by another collective's result — the same buffer resharded more
    than once per block. Returns the offending HLO lines, trimmed."""
    coll: Dict[str, str] = {}
    alias: Dict[str, str] = {}
    lines = hlo_text.splitlines()
    for line in lines:
        m = _COLL_DEF_RE.search(line)
        if m:
            coll[m.group(1)] = m.group(2)
        m = _PASSTHROUGH_RE.search(line)
        if m:
            ops = _operand_names(line)
            if ops:
                alias[m.group(1)] = ops[0]

    def resolve(name: str) -> str:
        for _ in range(16):
            if name in coll or name not in alias:
                return name
            name = alias[name]
        return name

    hits: List[str] = []
    for line in lines:
        m = _COLL_DEF_RE.search(line)
        if not m:
            continue
        for op in _operand_names(line):
            src = resolve(op)
            if src in coll and src != m.group(1):
                hits.append(line.strip()[:160])
                break
    return hits


# --------------------------------------------------------------------------
# Program table + compile memo
# --------------------------------------------------------------------------


def _seeds_mesh(n: int):
    from rcmarl_tpu.parallel.seeds import make_mesh

    return make_mesh(n, seed_axis=1 if n < 8 else 2)


def _gossip_cfg():
    """The gossip sharding variant: 8 replicas (so every mesh point in
    :data:`MESH_POINTS` tiles the replica axis evenly) on the canonical
    full graph with the Byzantine NaN replica keeping sanitize live."""
    from rcmarl_tpu.lint.configs import tiny_gossip_cfg

    return tiny_gossip_cfg(replicas=8)


def _sharding_programs() -> Dict[str, tuple]:
    """entry -> (config, mesh_factory(n) -> Mesh, build(mesh) ->
    Lowered).

    The Mesh is built ONCE per rung and handed to the builder, and the
    ledger row's ``mesh``/``mesh_fingerprint`` are derived from that
    same Mesh object — the row can never describe a mesh the program
    did not compile on. Builders are thunks so a too-small host can
    note-and-skip a single rung without paying any tracing. The
    canonical configs are the census/gossip audit shapes, so the
    sharded programs audited here are the ones the collective census
    already pins.
    """
    from rcmarl_tpu.config import Roles
    from rcmarl_tpu.lint.configs import census_cfg, megapop_cfg
    from rcmarl_tpu.parallel.gossip import lower_gossip_mix
    from rcmarl_tpu.parallel.matrix import lower_matrix
    from rcmarl_tpu.parallel.megapop import lower_megapop_consensus
    from rcmarl_tpu.parallel.seeds import lower_parallel, make_mesh

    cfg = census_cfg()
    mal = cfg.replace(
        agent_roles=(Roles.COOPERATIVE,) * 3 + (Roles.MALICIOUS,)
    )
    gcfg = _gossip_cfg()
    mcfg = megapop_cfg()
    return {
        "megapop@sharded": (
            mcfg,
            lambda n: make_mesh(n, seed_axis=1),
            lambda mesh: lower_megapop_consensus(mcfg, mesh),
        ),
        "seeds@sharded": (
            cfg,
            _seeds_mesh,
            lambda mesh: lower_parallel(cfg, [0, 1], 1, mesh, True),
        ),
        "matrix@sharded": (
            cfg,
            _seeds_mesh,
            lambda mesh: lower_matrix(
                cfg, [cfg, mal], [0, 1], 1, mesh, True
            ),
        ),
        "gossip@sharded": (
            gcfg,
            lambda n: make_mesh(n, seed_axis=n),
            lambda mesh: lower_gossip_mix(gcfg, mesh),
        ),
    }


#: (entry, config fingerprint, mesh fingerprint) -> (compiled text,
#: metric dict, program fp, mesh fp, mesh dict) — one compile per rung
#: per process, shared by the ledger rows, the replication/chain audit,
#: and the determinism census's compiled walk. The config and mesh
#: fingerprints in the key mean an overriding ``programs=`` table that
#: reuses an entry name with a different config/mesh (the planted
#: regression tests) can never be served another program's cache line.
_COMPILE_MEMO: dict = {}


def _compiled_at(entry: str, cfg_fp: str, build, mesh):
    from rcmarl_tpu.utils.profiling import (
        mesh_fingerprint,
        program_fingerprint,
    )

    mesh_fp = mesh_fingerprint(mesh)
    key = (entry, cfg_fp, mesh_fp)
    if key not in _COMPILE_MEMO:
        lowered = build(mesh)
        compiled = lowered.compile()
        mem = compiled.memory_analysis()
        metrics = None
        if mem is not None:
            arg = float(getattr(mem, "argument_size_in_bytes", 0))
            out = float(getattr(mem, "output_size_in_bytes", 0))
            tmp = float(getattr(mem, "temp_size_in_bytes", 0))
            alias = float(getattr(mem, "alias_size_in_bytes", 0))
            metrics = {
                "argument_bytes": arg,
                "output_bytes": out,
                "temp_bytes": tmp,
                "alias_bytes": alias,
                "peak_bytes": arg + out + tmp - alias,
            }
        _COMPILE_MEMO[key] = (
            compiled.as_text(),
            metrics,
            program_fingerprint(lowered),
            mesh_fp,
            {k: int(v) for k, v in dict(mesh.shape).items()},
        )
    return _COMPILE_MEMO[key]


# --------------------------------------------------------------------------
# Rows + unconditional findings
# --------------------------------------------------------------------------


def sharding_rows(
    programs=None, mesh_points: Sequence[int] = MESH_POINTS
) -> Tuple[List[dict], List[Finding], List[str], set]:
    """Compile the sharded programs at every mesh rung; extract ledger
    rows and the baseline-free invariant findings.

    Returns ``(rows, findings, notes, skipped entry names)``. Findings
    hold with or without a ledger: ``sharding-replicated`` /
    ``sharding-reshard-chain`` on any >1-device rung, and the
    per-device shrink invariant (:func:`shrink_findings`) over the
    rungs this host could measure. ``programs`` overrides the default
    table (the planted-regression tests feed deliberately bad programs
    through the same pipeline).
    """
    import jax

    from rcmarl_tpu.utils.profiling import config_fingerprint

    rows: List[dict] = []
    findings: List[Finding] = []
    notes: List[str] = []
    skipped: set = set()
    n_dev_host = len(jax.devices())
    if programs is None:
        programs = _sharding_programs()
    for entry, (cfg, mesh_factory, build) in programs.items():
        anchor = _anchor_for(entry)
        fp = config_fingerprint(cfg)
        for n in mesh_points:
            row_entry = f"{entry}@mesh{n}"
            if n > n_dev_host:
                notes.append(
                    f"{row_entry}: needs {n} devices, host has "
                    f"{n_dev_host}; per-device memory unverifiable here"
                )
                skipped.add(row_entry)
                continue
            text, metrics, program_fp, mesh_fp, mesh_dict = _compiled_at(
                entry, fp, build, mesh_factory(n)
            )
            if n > 1:
                for p in replicated_big_operands(text):
                    findings.append(
                        Finding(
                            "sharding-replicated",
                            anchor,
                            1,
                            f"{row_entry}: operand {p['path']} "
                            f"({p['bytes']} bytes/shard, {p['dtype']}) "
                            f"carries {p['kind']} sharding "
                            f"'{p['sharding']}' instead of a mesh-axis "
                            "sharding — at pod scale every device pays "
                            "its full bytes",
                        )
                    )
                for line in reshard_chains(text)[:5]:
                    findings.append(
                        Finding(
                            "sharding-reshard-chain",
                            anchor,
                            1,
                            f"{row_entry}: a collective feeds another "
                            f"collective (the same buffer resharded "
                            f"twice per block): {line}",
                        )
                    )
            if metrics is None:
                notes.append(
                    f"{row_entry}: platform exposes no memory analysis; "
                    "per-device memory unverifiable here"
                )
                skipped.add(row_entry)
                continue
            rows.append(
                {
                    "v": 1,
                    "kind": "device_memory",
                    "entry": row_entry,
                    "fingerprint": fp,
                    "program": program_fp,
                    "mesh_fingerprint": mesh_fp,
                    "mesh": mesh_dict,
                    "platform": jax.devices()[0].platform,
                    "jax": jax.__version__,
                    "metrics": metrics,
                }
            )
    findings += shrink_findings(rows, mesh_points)
    return rows, findings, notes, skipped


def shrink_findings(
    rows: Sequence[dict], mesh_points: Sequence[int] = MESH_POINTS
) -> List[Finding]:
    """The pod-readiness invariant over fresh rows (no baseline needed):
    along the measured mesh ladder, per-device peak bytes must never
    grow from one rung to the next, and both peak and argument bytes at
    the largest measured rung must be strictly below the 1-device
    point. A flat or rising curve means the big operands replicate and
    a pod would pay single-host memory on every chip."""
    from rcmarl_tpu.lint.cost import COST_TOLERANCE

    findings: List[Finding] = []
    by_base: Dict[str, Dict[int, dict]] = {}
    for r in rows:
        if r.get("kind") != "device_memory":
            continue
        base, _, mesh = r["entry"].rpartition("@mesh")
        by_base.setdefault(base, {})[int(mesh)] = r
    for base, ladder in by_base.items():
        anchor = _anchor_for(base)
        measured = sorted(n for n in ladder if n in mesh_points)
        for a, b in zip(measured, measured[1:]):
            pa = ladder[a]["metrics"]["peak_bytes"]
            pb = ladder[b]["metrics"]["peak_bytes"]
            if pb > pa * (1.0 + COST_TOLERANCE):
                findings.append(
                    Finding(
                        "device-memory-regression",
                        anchor,
                        1,
                        f"{base}: per-device peak GREW along the mesh "
                        f"ladder ({a} -> {b} devices: {pa:.0f} -> "
                        f"{pb:.0f} bytes) — sharding is losing, not "
                        "winning, memory",
                    )
                )
        if len(measured) >= 2 and measured[0] == 1:
            lo, hi = measured[0], measured[-1]
            for metric in ("peak_bytes", "argument_bytes"):
                v1 = ladder[lo]["metrics"][metric]
                vh = ladder[hi]["metrics"][metric]
                if vh >= v1 * SHRINK_BELOW:
                    findings.append(
                        Finding(
                            "device-memory-regression",
                            anchor,
                            1,
                            f"{base}: per-device {metric} fails to "
                            f"shrink with mesh size ({v1:.0f} bytes on "
                            f"1 device vs {vh:.0f} on {hi}) — the big "
                            "buffers are replicated, not sharded",
                        )
                    )
    return findings


# --------------------------------------------------------------------------
# Ledger gate
# --------------------------------------------------------------------------

_GATED = ("argument_bytes", "output_bytes", "temp_bytes", "peak_bytes")


def compare_device_memory(
    baseline: Sequence[dict],
    fresh: Sequence[dict],
    tol: Optional[float] = None,
    skipped=frozenset(),
) -> Tuple[List[Finding], List[str]]:
    """Diff fresh device-memory rows against the committed ledger —
    the cost arm's discipline (growth past ``tol`` is
    ``device-memory-regression``; missing/fingerprint-mismatched/stale
    rows are ``cost-unbaselined``; platform mismatches and shrinks are
    notes; ``skipped`` entries are exempt from the stale-row check)."""
    from rcmarl_tpu.lint.cost import COST_TOLERANCE, _grew

    tol = COST_TOLERANCE if tol is None else tol
    findings: List[Finding] = []
    notes: List[str] = []
    base_by_entry = {
        r["entry"]: r for r in baseline if r.get("kind") == "device_memory"
    }
    fresh_entries = set()
    for row in fresh:
        entry = row["entry"]
        fresh_entries.add(entry)
        anchor = _anchor_for(entry)
        base = base_by_entry.get(entry)
        if base is None:
            findings.append(
                Finding(
                    "cost-unbaselined",
                    anchor,
                    1,
                    f"{entry}: no device-memory row in the baseline "
                    "ledger — regenerate and commit AUDIT.jsonl in this "
                    "PR (lint --sharding --write_baseline)",
                )
            )
            continue
        if base.get("fingerprint") != row.get("fingerprint") or base.get(
            "mesh_fingerprint"
        ) != row.get("mesh_fingerprint"):
            findings.append(
                Finding(
                    "cost-unbaselined",
                    anchor,
                    1,
                    f"{entry}: canonical audit config or mesh changed "
                    f"(ledger {base.get('fingerprint')}/"
                    f"{base.get('mesh_fingerprint')} != "
                    f"{row.get('fingerprint')}/"
                    f"{row.get('mesh_fingerprint')}); regenerate "
                    "AUDIT.jsonl",
                )
            )
            continue
        if base.get("platform") != row.get("platform"):
            notes.append(
                f"{entry}: ledger measured on {base.get('platform')!r}, "
                f"running on {row.get('platform')!r}; per-device memory "
                "not comparable here"
            )
            continue
        for metric in _GATED:
            old = float(base["metrics"].get(metric, 0.0))
            new = float(row["metrics"].get(metric, 0.0))
            if _grew(old, new, tol):
                ratio = new / old if old else float("inf")
                findings.append(
                    Finding(
                        "device-memory-regression",
                        anchor,
                        1,
                        f"{entry}: per-device {metric} grew {old:.0f} "
                        f"-> {new:.0f} ({ratio:.3f}x > 1+{tol:g} "
                        "tolerance) without a ledger update",
                    )
                )
            elif _grew(new, old, tol):
                notes.append(
                    f"{entry}: per-device {metric} shrank {old:.0f} -> "
                    f"{new:.0f}; refresh AUDIT.jsonl to lock the "
                    "improvement in"
                )
    for entry in sorted(set(base_by_entry) - fresh_entries - set(skipped)):
        findings.append(
            Finding(
                "cost-unbaselined",
                _anchor_for(entry),
                1,
                f"{entry}: device-memory ledger row has no current "
                "counterpart (entry removed or renamed); regenerate "
                "AUDIT.jsonl",
            )
        )
    return findings, notes


def audit_sharding(
    baseline_path="AUDIT.jsonl", tol: Optional[float] = None
) -> Tuple[List[Finding], List[str], List[dict]]:
    """``lint --sharding`` (ledger half): (findings, notes, fresh rows).
    Invariant findings (replication, reshard chains, failure to shrink)
    plus the per-device memory gate against the committed ledger."""
    from rcmarl_tpu.lint.cost import read_ledger

    fresh, findings, notes, skipped = sharding_rows()
    baseline = read_ledger(baseline_path)
    if not baseline:
        notes.append(
            f"baseline ledger {baseline_path} missing or empty; every "
            "device-memory row below reports unbaselined"
        )
    cmp_findings, cmp_notes = compare_device_memory(
        baseline, fresh, tol, skipped
    )
    return findings + cmp_findings, notes + cmp_notes, fresh


# --------------------------------------------------------------------------
# Determinism census
# --------------------------------------------------------------------------

#: Cross-replica ops certified deterministic for these programs: the
#: collective census's enumerated pod-readiness set plus the matrix
#: program's ledger-pinned all-to-all reshards. Anything else found in
#: a walked module is an uncertified communication op — a
#: ``nondeterminism`` finding, not a count to baseline.
DETERMINISM_COLLECTIVE_ALLOWLIST = frozenset(
    {
        "all-gather",
        "all-reduce",
        "collective-permute",
        "reduce-scatter",
        "all-to-all",
    }
)

_BROAD_COLLECTIVE_RE = re.compile(
    r"\s(" + _KINDS_ALT + r")(?:-start)?\("
)

#: StableHLO float-arithmetic combiner ops whose accumulation order is
#: observable in the result bits (min/max/overwrite are order-safe).
_SCATTER_ARITH_RE = re.compile(
    r"stablehlo\.(add|subtract|multiply|divide)\s.*tensor<(f16|bf16|"
    r"f32|f64)>"
)

_RNG_BIT_RE = re.compile(r"rng[-_]bit[-_]generator")
_LEGACY_RNG_RE = re.compile(r"(stablehlo\.rng\s)|(\srng\()")


def nondeterministic_ops(
    text: str, compiled: bool = False
) -> List[str]:
    """The nondeterminism hazards in one module's text.

    ``compiled=False`` walks a StableHLO lowering (scatters keep their
    ``unique_indices`` attribute and combiner regions there — the
    partitioned/optimized module may have expanded them); ``True``
    walks compiled HLO (where uncertified collectives appear).
    Returns human-readable hazard descriptions, empty = clean.
    """
    hits: List[str] = []
    lines = text.splitlines()
    for i, line in enumerate(lines):
        if _RNG_BIT_RE.search(line) and not re.search(
            r"three[-_ ]?fry", line, re.IGNORECASE
        ):
            hits.append(
                "non-threefry rng_bit_generator (run-to-run/"
                f"cross-backend bits not pinned): {line.strip()[:140]}"
            )
        if _LEGACY_RNG_RE.search(line):
            hits.append(
                f"legacy stateful rng op: {line.strip()[:140]}"
            )
        if not compiled and "stablehlo.scatter" in line:
            if "unique_indices = false" in line:
                # the combiner region follows on the next few lines;
                # float accumulation there is order-dependent exactly
                # when indices may repeat
                for j in range(i + 1, min(i + 8, len(lines))):
                    if _SCATTER_ARITH_RE.search(lines[j]):
                        hits.append(
                            "float-accumulating scatter with "
                            "unique_indices=false (duplicate-index "
                            "order is implementation-defined): "
                            f"{line.strip()[:140]}"
                        )
                        break
                    if "stablehlo.return" in lines[j]:
                        break
        if compiled:
            m = _BROAD_COLLECTIVE_RE.search(line)
            if m and m.group(1) not in DETERMINISM_COLLECTIVE_ALLOWLIST:
                hits.append(
                    f"cross-replica op {m.group(1)!r} outside the "
                    f"certified collective allowlist: {line.strip()[:140]}"
                )
    return hits


def _determinism_lowering_walk() -> Tuple[List[Finding], List[str]]:
    """StableHLO walk of the jitted entry points (every cost-arm
    config, via the shared memoized lowering caches — free inside
    ``lint --all``) and all six aggregation backends."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rcmarl_tpu.lint.configs import (
        tiny_cfg,
        tiny_faulted_cfg,
        tiny_gossip_cfg,
        tiny_mixed_cfg,
    )
    from rcmarl_tpu.lint.cost import _anchor_for as cost_anchor
    from rcmarl_tpu.ops.aggregation import (
        AUDIT_BACKEND_MODES,
        resilient_aggregate_tree,
    )
    from rcmarl_tpu.utils.profiling import lowered_entry_points

    findings: List[Finding] = []
    notes: List[str] = []
    arms = {
        "dual": (tiny_cfg(netstack=False), False,
                 ("update_block", "train_block")),
        "stacked": (tiny_cfg(netstack=True), False,
                    ("update_block", "train_block")),
        "guarded": (tiny_faulted_cfg(False), True,
                    ("update_block", "train_block")),
        "fitstack": (tiny_mixed_cfg(fitstack=True), False,
                     ("update_block", "train_block", "fit_block")),
        "gossip": (tiny_gossip_cfg(), False, ("gossip_mix_block",)),
        "serve": (tiny_cfg(netstack=False), False,
                  ("serve_block", "eval_block")),
        "pipeline": (tiny_cfg(pipeline_depth=2), False,
                     ("actor_block", "learner_block",
                      "learner_block_donated")),
    }
    for arm, (cfg, with_diag, names) in arms.items():
        for name, low in lowered_entry_points(cfg, with_diag, names).items():
            for hit in nondeterministic_ops(low.as_text(), compiled=False):
                findings.append(
                    Finding(
                        "nondeterminism",
                        cost_anchor(name),
                        1,
                        f"{name}@{arm}: {hit}",
                    )
                )
    tree = {
        "w": jnp.ones((5, 3, 4), jnp.float32),
        "b": jnp.ones((5, 7), jnp.float32),
    }
    valid = jnp.asarray(np.array([1.0, 1.0, 1.0, 1.0, 0.0]), jnp.float32)
    for name, recipe in AUDIT_BACKEND_MODES:
        kwargs = {"impl": recipe["impl"], "sanitize": True}
        H = jnp.asarray(1, jnp.int32) if recipe.get("traced_h") else 1
        if recipe.get("masked"):
            kwargs["valid"] = valid
        try:
            low = jax.jit(
                lambda t, kw=kwargs, h=H: resilient_aggregate_tree(
                    t, h, **kw
                )
            ).lower(tree)
        except Exception as e:  # noqa: BLE001 — e.g. real Pallas on CPU
            notes.append(
                f"aggregation[{name}]: not lowerable on this platform "
                f"({type(e).__name__}); determinism unverifiable here"
            )
            continue
        for hit in nondeterministic_ops(low.as_text(), compiled=False):
            findings.append(
                Finding(
                    "nondeterminism",
                    "rcmarl_tpu/ops/aggregation.py",
                    1,
                    f"aggregation[{name}]: {hit}",
                )
            )
    return findings, notes


def _determinism_compiled_walk() -> Tuple[List[Finding], List[str]]:
    """Compiled-HLO walk of the sharded programs (via the sharding
    arm's compile memo — free when the ledger half already ran) at the
    largest mesh rung this host can build."""
    import jax

    findings: List[Finding] = []
    notes: List[str] = []
    n_dev = len(jax.devices())
    measurable = [n for n in MESH_POINTS if n <= n_dev]
    if not measurable:
        notes.append(
            "no mesh point measurable on this host; compiled "
            "determinism walk skipped"
        )
        return findings, notes
    from rcmarl_tpu.utils.profiling import config_fingerprint

    n = measurable[-1]
    for entry, (cfg, mesh_factory, build) in _sharding_programs().items():
        text, _, _, _, _ = _compiled_at(
            entry, config_fingerprint(cfg), build, mesh_factory(n)
        )
        for hit in nondeterministic_ops(text, compiled=True):
            findings.append(
                Finding(
                    "nondeterminism",
                    _anchor_for(entry),
                    1,
                    f"{entry}@mesh{n}: {hit}",
                )
            )
    return findings, notes


def audit_determinism() -> Tuple[List[Finding], List[str]]:
    """``lint --sharding`` (determinism half): the full census —
    entry-point lowerings, aggregation backends, compiled sharded
    modules."""
    f1, n1 = _determinism_lowering_walk()
    f2, n2 = _determinism_compiled_walk()
    return f1 + f2, n1 + n2
