"""Multi-host (multi-process) execution over ICI + DCN.

The reference's only scale-out mechanism is submitting more SGE jobs
(SURVEY.md C15). Here multi-host runs are the same single jitted program
as :func:`rcmarl_tpu.parallel.seeds.train_parallel`, launched once per
host with a shared coordinator — the JAX SPMD model (one controller per
process, XLA partitions globally).

Axis-to-fabric mapping (the design rule, not an accident):

- The ``seed`` axis carries ZERO collectives (replicas are independent),
  so it is the axis that may span hosts — traffic over DCN is nil except
  for the final metrics gather.
- The ``agent`` axis carries the consensus gather/all-gather every epoch,
  so agent groups must stay within one host's chips where XLA lowers the
  collectives onto ICI. :func:`multihost_mesh` enforces this by keeping
  the agent dimension inside each process's local devices.

None of this requires code changes elsewhere: ``Mesh`` axes are named, and
``train_parallel`` accepts any mesh.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

#: Env vars consulted by :func:`initialize` (the standard JAX cluster set).
_COORD_ENV = "JAX_COORDINATOR_ADDRESS"
_NPROC_ENV = "JAX_NUM_PROCESSES"
_PID_ENV = "JAX_PROCESS_ID"


_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    auto: bool = False,
) -> None:
    """Join (or start) a multi-host JAX cluster.

    Thin wrapper over ``jax.distributed.initialize`` that (a) reads the
    standard env vars when args are omitted, (b) is a no-op when no
    cluster configuration is present so the same launch script works on a
    single host, and (c) is idempotent.

    Args left as None are passed through as None so JAX's cluster
    auto-detection (TPU pod metadata, SLURM, ...) can fill them in; on a
    managed TPU pod with no env vars set, pass ``auto=True`` to force
    full auto-detection instead of the single-host no-op.

    MUST run before any other JAX call: querying devices (even
    ``jax.process_count()``) initializes the local backend, after which
    distributed initialization is rejected.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(_COORD_ENV)
    if num_processes is None and _NPROC_ENV in os.environ:
        num_processes = int(os.environ[_NPROC_ENV])
    if process_id is None and _PID_ENV in os.environ:
        process_id = int(os.environ[_PID_ENV])
    no_cluster_config = (
        coordinator_address is None
        and num_processes is None
        and process_id is None
    )
    if no_cluster_config and not auto:
        return  # single host, nothing to coordinate
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # CPU clusters (tests, laptops, CI) need an explicit cross-process
        # collectives backend; gloo ships in jaxlib. Must be set before
        # the backend initializes — i.e. exactly here.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def multihost_mesh(agent_axis: int = 1) -> Mesh:
    """A global ('seed', 'agent') mesh with agent groups pinned to hosts.

    ``jax.devices()`` orders devices process-by-process, so reshaping to
    (n_global // agent_axis, agent_axis) makes each agent group a
    contiguous run of one process's local devices — consensus collectives
    ride ICI, the host-spanning seed axis carries no traffic.

    Args:
      agent_axis: devices per agent-sharding group; must divide the LOCAL
        device count (an agent group must not straddle hosts).
    """
    local = jax.local_device_count()
    if agent_axis < 1 or local % agent_axis != 0:
        raise ValueError(
            f"agent_axis={agent_axis} must divide the local device count "
            f"{local} so consensus collectives stay on ICI"
        )
    # jax.devices() does NOT guarantee process grouping (on some slice
    # topologies global order follows physical coordinates), so group
    # explicitly and verify the invariant instead of assuming it.
    devs = np.asarray(
        sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    ).reshape(-1, agent_axis)
    for row in devs:
        procs = {d.process_index for d in row}
        if len(procs) != 1:  # pragma: no cover - needs >1 process
            raise AssertionError(
                f"agent group {[d.id for d in row]} spans processes {procs}"
            )
    return Mesh(devs, ("seed", "agent"))


def gather_metrics(metrics):
    """All-gather per-replica metrics across hosts (the run's only DCN
    traffic), returning host-local numpy with the global seed axis.

    Inputs are expected to be either globally-sharded ``jax.Array``s from
    :func:`~rcmarl_tpu.parallel.seeds.train_parallel` (for which
    ``process_allgather`` assembles the global value on every host) or
    host-local arrays sharded on their leading axis, for which
    ``tiled=True`` concatenates along that axis instead of stacking a new
    process dimension — either way the result keeps the documented
    (global_seed, ...) shape.

    On a single process this is just ``jax.device_get``.
    """
    if jax.process_count() == 1:
        return jax.tree.map(np.asarray, jax.device_get(metrics))
    from jax.experimental import multihost_utils

    return jax.tree.map(
        np.asarray, multihost_utils.process_allgather(metrics, tiled=True)
    )
