"""Mega-population consensus: the agent-sharded flat exchange block.

At n=1024 the consensus exchange dominates the step: the flat
``(N, P_total)`` critic+TR parameter block is ~84 MB and a DENSE
``(N, N, P)`` gather would be quadratic — which is why the
mega-population path mandates the sparse scheduled exchange
(:mod:`rcmarl_tpu.ops.exchange`, ``O(n · graph_degree · P)``) and
shards the AGENT axis of the flat block over the mesh, the
``parallel/matrix.py`` convention applied to population instead of
cells.

This module is the sharding-certified form of that block:
:func:`megapop_consensus_block` is one launch — sparse gather over the
traced ``(N, deg)`` schedule, then the sanitized trimmed mix per agent
— and :func:`lower_megapop_consensus` lowers it with every big operand
(the parameter block AND the graph) partitioned over the mesh 'agent'
axis. The graftlint device-memory ladder compiles this lowering at mesh
{1, 2, 8} (``lint --sharding``, entry ``megapop@sharded``) and gates
that per-device peak bytes shrink endpoint-wise — the proof, before any
chip time is spent, that n=1024 consensus actually partitions instead
of replicating. Nothing here ever executes in lint: lowering uses
abstract ``ShapeDtypeStruct`` operands, so the 84 MB block costs zero
host memory to certify.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from rcmarl_tpu.config import Config


def consensus_block_struct(cfg: Config) -> jax.ShapeDtypeStruct:
    """The abstract ``(N, P_total)`` flat consensus payload for ``cfg``:
    every agent's critic + TR nets raveled row-wise
    (:func:`rcmarl_tpu.ops.aggregation.ravel_neighbor_tree` — the same
    layout the netstack pair block and the gossip mix flatten to).
    Shape-only: built under ``jax.eval_shape``, no allocation."""
    from rcmarl_tpu.models.mlp import init_stacked_mlp
    from rcmarl_tpu.ops.aggregation import ravel_neighbor_tree

    def build(key):
        k_c, k_t = jax.random.split(key)
        critic = init_stacked_mlp(
            k_c, cfg.n_agents, cfg.obs_dim, cfg.hidden, 1
        )
        tr = init_stacked_mlp(k_t, cfg.n_agents, cfg.sa_dim, cfg.hidden, 1)
        flat, _ = ravel_neighbor_tree((critic, tr))
        return flat

    return jax.eval_shape(build, jax.random.PRNGKey(0))


def _megapop_consensus_block(cfg: Config, block, graph):
    """ONE mega-population consensus launch.

    ``block``: (N, P_total) flat per-agent payload rows. ``graph``:
    (N, degree) int32 scheduled in-neighbors, TRACED data (own index
    first — :func:`rcmarl_tpu.config.scheduled_in_nodes`, validated at
    the host boundary by :func:`rcmarl_tpu.ops.exchange.validate_graph`).
    Returns the (N, P_total) mixed block: sparse gather, then the
    sanitized own-anchored trim/clip/mean per agent — elementwise
    exclusion of non-finite payloads with the degree-deficit fallback,
    exactly the solo path's hardening.

    Two arms per ``cfg.consensus_impl``: the XLA sparse chain (the
    default — materializes the ``(N, deg, P_total)`` gathered block),
    or the SPARSE one-kernel arm for the fused impls
    (:func:`rcmarl_tpu.ops.pallas_consensus.fused_pair_consensus` with
    the graph as a scalar-prefetch operand — the gathered block never
    reaches HBM), pinned bitwise against each other in
    tests/test_sparse_fused.py and cost-gated by the
    ``sparse_consensus`` AUDIT.jsonl rows.
    """
    from rcmarl_tpu.config import FUSED_CONSENSUS_IMPLS
    from rcmarl_tpu.ops.aggregation import resilient_aggregate
    from rcmarl_tpu.ops.exchange import sparse_gather

    if cfg.consensus_impl in FUSED_CONSENSUS_IMPLS:
        from rcmarl_tpu.ops.pallas_consensus import fused_pair_consensus

        return fused_pair_consensus(
            block,
            cfg.H,
            in_nodes=graph,
            tree_split=int(block.shape[1]),  # one payload family: all tree-0
            sanitize=True,
            interpret=cfg.consensus_impl == "pallas_fused_interpret",
        )
    gathered = sparse_gather(block, graph)  # (N, deg, P_total)
    return jax.vmap(
        lambda v: resilient_aggregate(
            v,
            cfg.H,
            impl="xla",
            n_agents=cfg.n_agents,
            sanitize=True,
        )
    )(gathered)


#: The jitted entry point (compiles once per Config; every scheduled
#: block re-dispatches with that block's graph as data).
megapop_consensus_block = partial(jax.jit, static_argnums=0)(
    _megapop_consensus_block
)


def lower_megapop_consensus(cfg: Config, mesh=None):
    """Lower (without executing) the mega-population consensus with the
    AGENT axis sharded over the mesh — each device owns ``N/d`` rows of
    the flat block and of the graph; the cross-device neighbor reads
    lower to ICI collectives (all-gather of the payload rows).

    Compile/inspect only, like
    :func:`rcmarl_tpu.parallel.gossip.lower_gossip_mix`: operands are
    abstract ``ShapeDtypeStruct``s, so the graftlint ladder certifies
    the n=1024 sharding (``megapop@sharded``, mesh {1,2,8}) without
    materializing a single payload byte.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from rcmarl_tpu.parallel.seeds import make_mesh

    if mesh is None:
        mesh = make_mesh(seed_axis=1)
    block = consensus_block_struct(cfg)
    graph = jax.ShapeDtypeStruct(
        (cfg.n_agents, cfg.resolved_graph_degree), jnp.int32
    )
    shard = NamedSharding(mesh, P("agent"))
    fn = jax.jit(
        _megapop_consensus_block,
        static_argnums=0,
        in_shardings=(shard, shard),
        out_shardings=shard,
    )
    return fn.lower(cfg, block, graph)
