"""Gossip-replicated learners — Byzantine-hardened parameter exchange.

GALA-style gossip-replicated learners (arXiv:1906.04585) in the
Podracer whole-program-on-device tradition (arXiv:2104.06272), built on
the repo's own resilient-consensus kernel: R learner replicas train as
ONE vmapped/sharded seed-axis program (riding the
:mod:`rcmarl_tpu.parallel.seeds` machinery — replicas ARE seeds that
periodically talk), and every ``cfg.gossip_every`` blocks their
parameter trees mix through the SAME flat ``(n_in, P_total)``
trimmed-mean block the in-graph consensus uses
(:mod:`rcmarl_tpu.ops.aggregation`: ravel + log-depth tournament
selection, so the whole mix is ONE launch). The resilient aggregation
this repo already owns IS the gossip-mixing operator: a slow, stale, or
corrupted learner replica is trimmed away at the infra level exactly as
a malicious agent is trimmed away in-graph.

Threat model (:class:`rcmarl_tpu.faults.ReplicaFaultPlan`,
``cfg.replica_fault_plan``): per-replica-link drop / stale-replay of
last-round params / corrupt / sign-flip / NaN-bomb probabilities, plus
a deterministic ``byzantine_replicas`` mask of always-adversarial
replicas. Faults are injected between the exchange (gather) and the mix
(aggregation) from a DEDICATED fold_in stream off ``cfg.gossip_seed``
(:data:`_GOSSIP_STREAM`), so ``replica_fault_plan=None`` — and, with
``gossip_every=0``, the whole module — is bitwise-identical to
independent per-replica seed-axis training
(tests/test_gossip.py pins this leaf for leaf).

Guard rails (:func:`train_gossip` with ``guard`` on, auto-enabled under
any active fault plan): per-replica non-finite detection
(:func:`rcmarl_tpu.faults.tree_finite_per_replica` — the factored twin
of the solo trainer's ``_block_healthy``, so one poisoned replica never
forces a global rollback) rolls ONLY the poisoned replica back to its
last good post-mix state, and excludes it from the next mix by NaN-ing
its outgoing payloads — the sanitize/degree-deficit path of the trimmed
mix then drops it per element exactly like a NaN-bombing link.
Degradation counters (mix rounds, rollbacks, exclusions, non-finite
payload entries, degree-deficit fallbacks) land in
``df.attrs['gossip']``, FaultDiag-style.

Readmission (``readmit_after``): the PR-7 exclusion is ONE-round — a
rolled-back replica sits out the very next mix and re-enters
unconditionally. That is the right default for transient poisonings,
but a FLAPPING sender (poisoned this segment, clean the next, poisoned
again — e.g. a probabilistic agent-level NaN plan without sanitize)
re-enters the mix exactly when its luck turns, every time.
``readmit_after=K > 0`` makes the quarantine sticky: an excluded
replica must first PROVE ``K`` consecutive healthy (finite post-segment
params/metrics) probe rounds before its payloads re-enter the mix; an
unhealthy segment resets the streak. The quarantined replica keeps
TRAINING and keeps RECEIVING mixes (its own slot-0 row is never
excluded), so readmission is recovery, not resurrection.
``readmit_after=0`` (default) is the PR-7 behavior bit-for-bit — pinned
in tests/test_gossip.py. Counters (``readmitted``, the live
``quarantined`` mask) ride ``df.attrs['gossip']``; the checkpoint meta
carries the union exclusion mask, so a resumed quarantined replica
restarts its probe streak (the conservative direction).
"""

from __future__ import annotations

import warnings
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rcmarl_tpu.config import (
    Config,
    circulant_in_nodes,
    full_in_nodes,
    random_geometric_in_nodes,
)

#: fold_in tag deriving the replica-fault stream from the gossip round
#: key — a DEDICATED stream (the training replicas' RNG streams and the
#: agent-level _FAULT_STREAM are untouched), so a clean-plan mix is
#: bitwise the fault-free mix.
_GOSSIP_STREAM = 0x605B

#: fold_in tag perturbing a rolled-back replica's RNG so its next
#: segment does not replay the failing draw (the solo guard's skip
#: discipline, per replica).
_ROLLBACK_STREAM = 0x5C1C


def replica_seeds(cfg: Config) -> Tuple[int, ...]:
    """The R training seeds behind the replica axis: ``cfg.seed + i``.

    Replica ``i`` with gossip disabled is therefore bitwise the
    independent :mod:`~rcmarl_tpu.parallel.seeds` run with seed
    ``cfg.seed + i`` (the no-mix pin in tests/test_gossip.py)."""
    return tuple(cfg.seed + i for i in range(cfg.replicas))


def replica_in_nodes(cfg: Config) -> Tuple[Tuple[int, ...], ...]:
    """The static replica gossip graph, self first (``Config`` row
    convention): 'ring' = directed circulant of in-degree
    ``gossip_degree``; 'full' = fully connected; 'random_geometric' =
    deterministic positions in the unit square drawn from
    ``cfg.gossip_seed``, each replica wired to its ``gossip_degree - 1``
    nearest others — the classic gossip topology whose degree stays
    bounded as R grows."""
    R = cfg.replicas
    if R < 1:
        raise ValueError("replica_in_nodes needs cfg.replicas >= 1")
    if cfg.gossip_graph == "full":
        return full_in_nodes(R)
    if cfg.gossip_graph == "ring":
        return circulant_in_nodes(R, cfg.gossip_degree)
    # random_geometric: host-side, deterministic in gossip_seed alone —
    # the graph is static data here (regenerating per run would
    # retrace). The builder is SHARED with the agent-level time-varying
    # schedule (config.py:random_geometric_in_nodes), which resamples
    # it per block and feeds the indices in as data instead.
    return random_geometric_in_nodes(R, cfg.gossip_degree, cfg.gossip_seed)


def _mix_tree(params):
    """The parameter families a gossip mix exchanges: the four nets.
    Adam moments stay replica-local (GALA convention — mixing unbiased
    moment estimates through a clipping mean has no clean semantics)."""
    return (params.actor, params.critic, params.tr, params.critic_local)


def _gossip_mix_block(cfg: Config, params, prev_params, round_idx, exclude):
    """ONE gossip round: exchange -> fault injection -> trimmed mix.

    Args:
      cfg: static config (``replicas``/``gossip_*``/``replica_fault_plan``).
      params: replica-stacked :class:`~rcmarl_tpu.agents.updates.AgentParams`
        (leaves ``(R, ...)``).
      prev_params: the PREVIOUS round's post-mix params — the payload a
        stale link replays. Pass ``params`` again when no plan needs it
        (the stale gather is gated on ``stale_p > 0``, like the agent
        level).
      round_idx: () int32 gossip-round counter — namespaces the
        per-round fault draws so a resumed run replays its exact fault
        pattern.
      exclude: (R,) bool — replicas the guard excluded from THIS mix:
        their outgoing payloads become NaN on every non-self link, which
        the sanitized trimmed mix turns into per-element exclusions
        (degree-deficit fallback keeps the receiver's own value when too
        few finite payloads survive).

    Returns ``(mixed params, FaultDiag)`` — the diag counts non-finite
    payload entries seen in the exchange and elementwise deficit events
    of the mix, summable across rounds.

    The whole round is one jitted launch (:data:`gossip_mix_block`):
    every replica's four nets ravel into one ``(R, P_total)`` block, the
    graph gather/fault/trim/clip/mean run on the single combined
    ``(R, n_in, P_total)`` array, and the result unravels back — the
    PR 3/4 one-launch layout, reused verbatim.
    """
    from rcmarl_tpu.faults import apply_replica_faults, fault_diagnostics
    from rcmarl_tpu.ops.aggregation import (
        ravel_neighbor_tree,
        resilient_aggregate,
    )

    R = cfg.replicas
    in_nodes = replica_in_nodes(cfg)
    in_arr = jnp.asarray(np.array(in_nodes))  # (R, n_in)
    flat, unravel = ravel_neighbor_tree(_mix_tree(params))  # (R, P_total)
    gathered = flat[in_arr]  # (R, n_in, P_total), own payload at slot 0
    plan = cfg.replica_fault_plan
    if plan is not None and plan.active:
        fkey = jax.random.fold_in(
            jax.random.fold_in(
                jax.random.PRNGKey(cfg.gossip_seed), _GOSSIP_STREAM
            ),
            round_idx,
        )
        if float(plan.stale_p) > 0.0:
            prev_flat, _ = ravel_neighbor_tree(_mix_tree(prev_params))
            stale = prev_flat[in_arr]
        else:
            stale = gathered
        gathered = apply_replica_faults(fkey, gathered, stale, plan, in_nodes)
    # Guard exclusion: a rolled-back replica's payload is suspect for
    # one round — NaN it on every non-self link so the sanitize path
    # excludes it elementwise (its own slot-0 row stays: the replica
    # itself still receives the mix and recovers).
    sender_excluded = exclude[in_arr].at[:, 0].set(False)  # (R, n_in)
    gathered = jnp.where(sender_excluded[:, :, None], jnp.nan, gathered)
    diag = fault_diagnostics(gathered, cfg.gossip_H)
    if cfg.gossip_mix == "mean":
        # The unhardened comparison arm: one NaN replica poisons every
        # in-neighbor (the regression tests/test_gossip.py pins).
        mixed = jnp.mean(gathered, axis=1)
    else:
        mixed = jax.vmap(
            lambda v: resilient_aggregate(
                v,
                cfg.gossip_H,
                impl=cfg.consensus_impl,
                n_agents=R,
                sanitize=True,
            )
        )(gathered)
    actor, critic, tr, critic_local = jax.vmap(unravel)(mixed)
    return (
        params._replace(
            actor=actor, critic=critic, tr=tr, critic_local=critic_local
        ),
        diag,
    )


#: The jitted gossip-mix entry point — registered in
#: :func:`rcmarl_tpu.utils.profiling.jit_entry_points`, so the retrace /
#: cost / backend lint arms audit it like every other steady-state
#: program. Compiles once per Config; every gossip round re-dispatches
#: the same executable.
gossip_mix_block = partial(jax.jit, static_argnums=0)(_gossip_mix_block)


def lower_gossip_mix(cfg: Config, mesh=None):
    """Lower (without executing) the gossip mix with the REPLICA axis
    sharded over the mesh 'seed' axis — the pod-scale form of the mix,
    where each learner replica's parameter block lives on its own
    device and the graph gather crosses chips as ICI collectives.

    :func:`train_gossip` deliberately runs the mix on one device on
    this host (single-core-safe dispatch); this lowering is what the
    graftlint sharding arm audits instead — proving, before any chip
    time is spent, that the sharded mix keeps its big ``(R, ...)``
    parameter operands mesh-sharded and that its per-device argument
    bytes shrink with the mesh (``lint --sharding``). Compile/inspect
    only, like :func:`rcmarl_tpu.parallel.seeds.lower_parallel`.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from rcmarl_tpu.parallel.seeds import init_states, make_mesh

    if mesh is None:
        mesh = make_mesh()
    states = init_states(cfg, replica_seeds(cfg))
    params_shard = jax.tree.map(
        lambda _: NamedSharding(mesh, P("seed")), states.params
    )
    scalar = NamedSharding(mesh, P())
    fn = jax.jit(
        _gossip_mix_block,
        static_argnums=0,
        in_shardings=(params_shard, params_shard, scalar, scalar),
    )
    return fn.lower(
        cfg,
        states.params,
        states.params,
        jnp.zeros((), jnp.int32),
        jnp.zeros((cfg.replicas,), bool),
    )


def _select_replicas(mask, a, b):
    """Per-replica select over replica-stacked pytrees: leaves carry the
    replica axis at 0; ``mask`` is (R,) bool (True -> ``a``)."""
    m = jnp.asarray(mask)
    return jax.tree.map(
        lambda x, y: jnp.where(m.reshape(m.shape + (1,) * (x.ndim - 1)), x, y),
        a,
        b,
    )


def _segment_lengths(n_blocks: int, gossip_every: int):
    """The block-count segments between mixes: ``gossip_every``-sized
    chunks, each followed by a mix, plus an unmixed remainder (the mix
    cadence has not been reached). ``gossip_every=0`` = one unmixed
    segment (independent replicas)."""
    if gossip_every <= 0:
        return [(n_blocks, False)] if n_blocks else []
    full, rem = divmod(n_blocks, gossip_every)
    segs = [(gossip_every, True)] * full
    if rem:
        segs.append((rem, False))
    return segs


def train_gossip(
    cfg: Config,
    n_episodes: Optional[int] = None,
    states=None,
    verbose: bool = False,
    block_callback=None,
    guard: Optional[bool] = None,
    start_round: int = 0,
    excluded=None,
    readmit_after: int = 0,
):
    """Host-looped gossip-replicated training run.

    ``cfg.replicas`` learner replicas train as one vmapped seed-axis
    program (:func:`rcmarl_tpu.parallel.seeds.train_parallel` — the
    sharded machinery, so a multi-chip host shards the replica axis for
    free) in segments of ``cfg.gossip_every`` blocks; after each full
    segment the replicas' parameter trees mix through the trimmed-mean
    block (:data:`gossip_mix_block`, one launch per round).

    Args:
      n_episodes: per-replica episodes (default ``cfg.n_episodes``);
        must be a multiple of ``cfg.n_ep_fixed``.
      states: resume from a previously returned replica-stacked
        TrainState (pass ``start_round``/``excluded`` from the
        checkpoint meta so fault draws and exclusions continue exactly).
      guard: per-replica guard rails — after each segment, each
        replica's params and metric rows are checked for non-finites; an
        unhealthy replica ROLLS BACK alone to its last good post-mix
        state (RNG perturbed, block counter advanced — the solo guard's
        skip semantics, per replica) and is EXCLUDED from the next mix
        via the sanitize/degree-deficit path. ``None`` (default)
        auto-enables exactly when a fault plan (replica- or agent-level)
        is active.
      start_round: the gossip round counter to resume from (namespaces
        the per-round fault draws).
      excluded: (R,) bools carried over from a checkpointed run (under
        ``readmit_after > 0`` they seed the sticky quarantine mask; the
        probe streak restarts at zero — the conservative direction).
      readmit_after: 0 (default) = the PR-7 one-round exclusion,
        bit-for-bit; K > 0 = sticky quarantine — an excluded replica
        re-enters the mix only after K consecutive healthy probe
        rounds (see the module docstring; the flapping-sender defense).

    Returns ``(replica-stacked TrainState, sim_data DataFrame)``. The
    frame's rows are the per-episode mean over the NON-Byzantine
    replicas; ``df.attrs['gossip']`` carries the degradation counters
    (``rounds``/``rollbacks``/``excluded``/``nonfinite``/``deficit``),
    the per-replica final health, and the run's gossip shape.
    """
    from rcmarl_tpu.parallel.seeds import init_states, train_parallel
    from rcmarl_tpu.training.trainer import (
        _replica_block_healthy,
        metrics_to_dataframe,
    )
    from rcmarl_tpu.faults import tree_finite_per_replica

    R = cfg.replicas
    if R < 1:
        raise ValueError(
            f"train_gossip needs cfg.replicas >= 1 (got {R}); the solo "
            "trainer is rcmarl_tpu.training.trainer.train"
        )
    n_eps = cfg.n_episodes if n_episodes is None else n_episodes
    if n_eps % cfg.n_ep_fixed != 0:
        raise ValueError(
            f"n_episodes={n_eps} must be a multiple of "
            f"n_ep_fixed={cfg.n_ep_fixed}"
        )
    n_blocks = n_eps // cfg.n_ep_fixed
    if guard is None:
        guard = (
            cfg.replica_fault_plan is not None and cfg.replica_fault_plan.active
        ) or (cfg.fault_plan is not None and cfg.fault_plan.active)
    if readmit_after < 0:
        raise ValueError(f"readmit_after={readmit_after} must be >= 0")

    stats = {
        "rounds": 0,
        "rollbacks": 0,
        "excluded": 0,
        "readmitted": 0,
        "nonfinite": 0,
        "deficit": 0,
    }
    plan = cfg.replica_fault_plan
    byz = set(plan.byzantine_replicas) if plan is not None else set()
    carried = (
        np.zeros(R, bool) if excluded is None else np.asarray(excluded, bool)
    )
    # readmit_after=0: the PR-7 one-round accumulator (cleared after
    # every mix). K>0: the carried mask seeds the STICKY quarantine
    # instead, and `excluded` stays a per-round scratch of zeros.
    excluded = carried if readmit_after == 0 else np.zeros(R, bool)
    quarantine = carried.copy() if readmit_after > 0 else np.zeros(R, bool)
    streak = np.zeros(R, np.int64)
    round_idx = int(start_round)
    specs = None
    if cfg.task_axis:
        # Diff-DAC (PAPERS.md 1710.10363): the replica axis IS the task
        # axis — replica r trains the congestion world at load level
        # resolved_task_levels[r] (traced CellSpec.task_scale data, one
        # compiled program for the whole family), and the gossip mix
        # below doubles as Diff-DAC's cross-task consensus step: the
        # trimmed mean over the tasks' parameter blocks.
        from rcmarl_tpu.training.update import spec_from_config

        base_spec = spec_from_config(cfg)
        specs = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (R,) + x.shape), base_spec
        )
        specs = specs._replace(
            task_scale=jnp.asarray(cfg.resolved_task_levels, jnp.float32)
        )
    if states is None:
        states = init_states(cfg, replica_seeds(cfg))
    last_good = states  # per-replica rollback target (last good post-mix)
    all_metrics = []
    blocks_done = 0

    for seg_len, mix_after in _segment_lengths(n_blocks, cfg.gossip_every):
        # stale-replay payload: the previous round's post-mix params
        prev_params = last_good.params
        states, metrics = train_parallel(
            cfg, states=states, n_blocks=seg_len, specs=specs
        )
        blocks_done += seg_len
        if guard:
            healthy = np.asarray(_replica_block_healthy(states, metrics))
            if not healthy.all():
                stats["rollbacks"] += int((~healthy).sum())
                # the poisoned replicas alone roll back to their last
                # good post-mix state; RNG perturbed + block counter
                # advanced so the next segment does not replay the
                # failing draw (the solo guard's skip, per replica)
                skipped = last_good._replace(
                    key=jax.vmap(
                        lambda k: jax.random.fold_in(
                            k, _ROLLBACK_STREAM + round_idx
                        )
                    )(last_good.key),
                    block=last_good.block + seg_len,
                )
                # align placements first: post-mix snapshots carry
                # single-device params while fresh segment outputs are
                # mesh-sharded — a select across mismatched placements
                # would fail on multi-device hosts
                skipped = jax.device_put(
                    skipped, jax.tree.map(lambda x: x.sharding, states)
                )
                states = _select_replicas(healthy, states, skipped)
            if readmit_after > 0:
                # sticky quarantine: a quarantined replica's healthy
                # segment is one finite PROBE round; readmit_after of
                # them in a row earn re-entry, an unhealthy one resets
                # the streak (the flapping-sender defense)
                streak = np.where(quarantine & healthy, streak + 1, streak)
                readmit = quarantine & healthy & (streak >= readmit_after)
                if readmit.any():
                    stats["readmitted"] += int(readmit.sum())
                    quarantine &= ~readmit
                    streak[readmit] = 0
                quarantine |= ~healthy
                streak[~healthy] = 0
            else:
                excluded = excluded | ~healthy
        all_metrics.append(metrics)
        if mix_after:
            # The mix runs on ONE device: the replica axis may be
            # seed-sharded by train_parallel's mesh, and the gossip
            # gather crosses replicas — materializing it locally keeps
            # the mix collective-free (the next segment's device_put
            # re-shards). One R×P_total copy per round.
            dev0 = jax.devices()[0]
            mix_exclude = excluded | quarantine
            mixed_params, diag = gossip_mix_block(
                cfg,
                jax.device_put(states.params, dev0),
                jax.device_put(prev_params, dev0),
                jnp.asarray(round_idx, jnp.int32),
                jnp.asarray(mix_exclude),
            )
            states = states._replace(params=mixed_params)
            stats["rounds"] += 1
            stats["excluded"] += int(mix_exclude.sum())
            stats["nonfinite"] += int(diag.nonfinite)
            stats["deficit"] += int(diag.deficit)
            excluded = np.zeros(R, bool)
            round_idx += 1
            if guard:
                # only replicas whose post-mix params are finite refresh
                # their rollback snapshot (under the mean arm a poisoned
                # mix must not become the "good" state)
                mix_ok = np.asarray(tree_finite_per_replica(states.params))
                if mix_ok.all():
                    last_good = states
                else:
                    last_good = _select_replicas(
                        mix_ok,
                        states,
                        jax.device_put(
                            last_good,
                            jax.tree.map(lambda x: x.sharding, states),
                        ),
                    )
            else:
                last_good = states
        if verbose:
            tt = np.asarray(metrics.true_team_returns)
            keep = [r for r in range(R) if r not in byz] or list(range(R))
            with warnings.catch_warnings():
                # all-poisoned segment rows (mean-mix arm) print as nan
                warnings.filterwarnings(
                    "ignore", message="Mean of empty slice"
                )
                seg_return = np.nanmean(tt[keep])
            print(
                f"| blocks {blocks_done}/{n_blocks} | round {round_idx} "
                f"| team return {seg_return:.3f}"
                + (" | mixed" if mix_after else "")
            )
        if block_callback is not None:
            block_callback(
                states,
                blocks_done - 1,
                {
                    "replicas": R,
                    "gossip_round": round_idx,
                    # the union mask: a checkpoint taken here must carry
                    # the sticky quarantine, not just the round scratch
                    "excluded": [int(x) for x in (excluded | quarantine)],
                    "segment_blocks": seg_len,
                },
            )

    # one row per episode: the non-Byzantine replicas' mean (a Byzantine
    # replica's own training is infrastructure noise, not evidence).
    # Host-side numpy: fancy-indexing a seed-sharded replica axis on
    # device would gather across shards; a D2H fetch never does.
    metrics = jax.tree.map(
        lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=1),
        *all_metrics,
    )
    keep = [r for r in range(R) if r not in byz] or list(range(R))
    with warnings.catch_warnings():
        # an all-poisoned episode column (the mean-mix comparison arm)
        # is a legitimate all-NaN row, not a numpy usage bug
        warnings.filterwarnings("ignore", message="Mean of empty slice")
        mean_metrics = jax.tree.map(
            lambda l: np.nanmean(l[np.array(keep)], axis=0), metrics
        )
    df = metrics_to_dataframe(mean_metrics)
    healthy_final = np.asarray(tree_finite_per_replica(states.params))
    df.attrs["gossip"] = {
        **stats,
        "replicas": R,
        "gossip_every": cfg.gossip_every,
        "graph": cfg.gossip_graph,
        "mix": cfg.gossip_mix,
        "H": cfg.gossip_H,
        "byzantine": sorted(byz),
        "replica_healthy": [bool(h) for h in healthy_final],
        "gossip_round": round_idx,
        # the LIVE exclusion mask (one-round scratch ∪ sticky
        # quarantine): resume must carry it so an excluded/quarantined
        # replica still sits out its next mix
        "excluded_mask": [int(x) for x in (excluded | quarantine)],
        "readmit_after": readmit_after,
        "quarantined": [int(x) for x in quarantine],
        "task_axis": bool(cfg.task_axis),
        "task_levels": [float(l) for l in cfg.resolved_task_levels],
    }
    return states, df
