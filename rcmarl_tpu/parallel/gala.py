"""Pipelined gossip fleets — pipeline × gossip × canary, one topology.

The repo's three hardened parallel axes were mutually exclusive by
construction: the async actor-learner pipeline
(:mod:`rcmarl_tpu.pipeline`), Byzantine-resilient gossip learners
(:mod:`rcmarl_tpu.parallel.gossip`), and canary-gated publishing
(:mod:`rcmarl_tpu.serve.canary`). This module composes them into the
GALA architecture (gossip-based actor-learner, arXiv:1906.04585, with
TorchBeast's queue discipline, arXiv:1910.03552):

- **R per-replica pipelines** — each of ``cfg.replicas`` learner
  replicas owns a SOLO async pipeline: its own actor tier
  (:func:`rcmarl_tpu.serve.engine.actor_block` dispatched
  ``cfg.pipeline_depth`` blocks ahead through a
  :class:`~rcmarl_tpu.pipeline.queue.BlockQueue`), its own
  :class:`~rcmarl_tpu.pipeline.publish.PolicyPublisher`, its own
  key chain, window-redraw guard, and staleness counters. The replicas
  dispatch the EXISTING solo jitted entries (``actor_block``,
  ``learner_block``/``learner_block_donated``) — R dispatches of the
  same compiled executables per block, zero new steady-state programs
  on the training path.
- **gossip mixes at segment boundaries** — every ``cfg.gossip_every``
  blocks each replica's actor tier DRAINS (Config validates
  ``pipeline_depth <= gossip_every``, so steady-state pipelining is
  never lost to the drain) and the replicas' parameter trees mix
  through :data:`gala_mix_block`: the replica trees stack to the
  ``(R, P_total)`` block, run the exact
  :func:`~rcmarl_tpu.parallel.gossip._gossip_mix_block` exchange →
  fault injection → trimmed mix, and unstack back to solo trees — ONE
  launch per round, the registered jitted entry point of the composed
  topology. Post-mix parameters are force-republished to every actor
  tier, so acting params are data and a mix is never a compile.
- **canary-gated deploy** — after every segment the WINNING replica
  (best segment mean return among healthy, non-quarantined,
  non-Byzantine replicas) is offered to a deploy
  :class:`~rcmarl_tpu.pipeline.publish.PolicyPublisher` with
  ``validate=True`` and, when ``cfg.canary_band > 0``, a
  :class:`~rcmarl_tpu.serve.canary.CanaryGate` bound as the admission
  callable: a finite-but-regressed winner is rejected at the gate, a
  poisoned winner at the finiteness guard, and the serving fleet keeps
  the last good policy either way. ``deploy.acting`` IS the
  fleet-facing policy (the in-memory twin of the checkpoint chain).

**Resilience composes, not coexists.** Per-replica window redraws and
learner retries/skips run inside each replica's pipeline exactly as in
the solo pipelined trainer; per-replica rollback / exclusion / sticky
quarantine / readmission run at segment boundaries exactly as in the
synchronous gossip trainer — a replica whose segment ends with
non-finite params/metrics rolls back alone to its last good post-mix
state, and a replica that SKIPPED blocks this segment (the pipeline
guard already contained the poison) is excluded from the next mix
without a rollback. All counters merge onto one ``df.attrs`` surface
(``pipeline`` / ``guard`` / ``gossip`` / ``canary``) and one summary
line (:func:`gala_summary` — the CI smoke cell's grep target).

**RNG discipline.** Each replica's segment walks its key chain from
the replica's STORED key — a segment boundary behaves exactly like a
checkpoint-resume boundary, so a skip's or rollback's stored-key fold
takes effect at the next segment precisely as it would on resume (the
solo pipeline applies in-run folds only at resume too; within a
segment the dispatch chain stays unperturbed, the solo contract).

**Lint posture.** The module is in the hot-path set so the traced-value
rules bind on its jitted entries (:data:`gala_mix_block`, the dispatched
solo blocks). The ORCHESTRATION loop around them is host code whose
device->host pulls are the design: segment-boundary guard decisions
(finiteness, quarantine, winner selection) and the ``df.attrs`` ledgers
must read device diagnostics on the host between jitted segments, and
``PRNGKey(seed)`` mints per-replica roots outside any trace. Those
lines carry per-line pragma waivers.

**Degenerate arms delegate.** ``pipeline_depth == 0`` IS the
synchronous gossip trainer (:func:`~rcmarl_tpu.parallel.gossip.
train_gossip` — and with ``gossip_every == 0`` therefore bitwise the
independent seed-axis run, the existing pin chain); ``replicas == 1``
IS the solo pipelined trainer (:func:`~rcmarl_tpu.pipeline.trainer.
train_pipelined`). Both pins hold by CONSTRUCTION — delegation, not a
hand-maintained twin loop — and are still pinned leaf-for-leaf in
tests/test_gala.py as the regression net.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from rcmarl_tpu.config import Config
from rcmarl_tpu.parallel.gossip import (
    _ROLLBACK_STREAM,
    _gossip_mix_block,
    _segment_lengths,
    replica_seeds,
)
from rcmarl_tpu.pipeline.publish import PolicyPublisher
from rcmarl_tpu.pipeline.queue import BlockQueue
from rcmarl_tpu.pipeline.trainer import (
    _REDRAW_STREAM,
    _skip_stored_key,
    _window_healthy,
    learner_block,
    learner_block_donated,
)


def _gala_mix_block(cfg: Config, params, prev_params, round_idx, exclude):
    """ONE composed gossip round over a TUPLE of R solo parameter trees.

    The replicas of a composed run live as solo trees (each drives its
    own pipeline through the solo jitted entries), so the mix stacks
    them to the replica-axis layout, runs the EXACT synchronous
    exchange → fault injection → trimmed mix
    (:func:`~rcmarl_tpu.parallel.gossip._gossip_mix_block` — one
    ``(R, n_in, P_total)`` gather/trim/clip/mean), and unstacks the
    result back to a tuple of solo trees. Stack and unstack fuse into
    the mix launch: the whole round stays ONE program
    (:data:`gala_mix_block`, the composed topology's registered entry
    point).

    Args mirror the synchronous mix: ``params``/``prev_params`` are
    length-R tuples of solo AgentParams (``prev_params`` is the stale
    replay payload — pass ``params`` again when no plan needs it),
    ``round_idx`` a () int32, ``exclude`` an (R,) bool guard-exclusion
    mask. Returns ``(tuple of R mixed solo trees, FaultDiag)``.
    """
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *params)
    prev = jax.tree.map(lambda *xs: jnp.stack(xs), *prev_params)
    mixed, diag = _gossip_mix_block(cfg, stacked, prev, round_idx, exclude)
    outs = tuple(
        jax.tree.map(lambda x, r=r: x[r], mixed)
        for r in range(cfg.replicas)
    )
    return outs, diag


#: The composed topology's jitted mix entry point — registered in
#: :func:`rcmarl_tpu.utils.profiling.jit_entry_points`, audited by the
#: retrace / cost lint arms like every steady-state program. Compiles
#: once per Config; every mix round re-dispatches the same executable.
gala_mix_block = partial(jax.jit, static_argnums=0)(_gala_mix_block)


def gala_fingerprint(cfg: Config) -> str:
    """The ``cost_fingerprint`` of a composed measurement: one hash over
    the three steady-state programs a composed run dispatches (the
    actor-tier rollout block, the donated learner block, the composed
    mix), abstract lowering only — the
    :func:`~rcmarl_tpu.pipeline.trainer.pipeline_fingerprint` ledger
    convention extended to the three-program arm."""
    from rcmarl_tpu.pipeline.trainer import pipeline_fingerprint
    from rcmarl_tpu.training.trainer import init_train_state
    from rcmarl_tpu.utils.profiling import program_fingerprint

    params = tuple(
        jax.eval_shape(
            lambda k: init_train_state(cfg, k).params, jax.random.PRNGKey(0)  # lint: disable=prng-int-seed
        )
        for _ in range(cfg.replicas)
    )
    mix = gala_mix_block.lower(
        cfg,
        params,
        params,
        jnp.zeros((), jnp.int32),
        jnp.zeros((cfg.replicas,), bool),
    )
    return program_fingerprint(pipeline_fingerprint(cfg) + mix.as_text())


def gala_summary(attrs: dict) -> str:
    """THE one merged counters line of a composed run (cmd_train prints
    it; the CI smoke cell greps staleness + gossip + canary off it)."""
    p = attrs["pipeline"]
    g = attrs["gossip"]
    c = attrs["canary"]
    return (
        f"gala: {g['replicas']} replicas × depth {p['depth']} — "
        f"staleness mean {p['staleness_mean']:.2f} / max "
        f"{p['staleness_max']}, {p['publishes']} publishes, "
        f"{p['rejects']} rejects | gossip: {g['rounds']} rounds, "
        f"{g['rollbacks']} rollbacks, {g['excluded']} exclusions, "
        f"{sum(g['quarantined'])} quarantined, healthy "
        f"{sum(g['replica_healthy'])}/{g['replicas']} | canary: "
        f"{c['accepts']} accepted, {c['rejects']} rejected over "
        f"{c['evals']} evals, {c['deploys']} deploys, "
        f"{c['deploy_rejects'] + c['canary_rejects']} deploy rejects"
    )


def _stack_states(states_list):
    """Solo TrainStates -> the replica-stacked layout every replica
    trainer returns (checkpoint meta carries ``replicas``, so the
    stacked file round-trips through the gossip resume path)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states_list)


def _unstack_states(states, R: int):
    """Replica-stacked TrainState -> list of R solo TrainStates (fresh
    buffers — slicing gathers, so the solo trees are donation-safe)."""
    return [jax.tree.map(lambda x, r=r: x[r], states) for r in range(R)]


def train_gala(
    cfg: Config,
    n_episodes: Optional[int] = None,
    states=None,
    verbose: bool = False,
    block_callback=None,
    guard: Optional[bool] = None,
    max_retries: int = 1,
    window_fault=None,
    start_round: int = 0,
    excluded=None,
    readmit_after: int = 0,
):
    """Host-looped composed run: R gossiping pipelined learner replicas
    behind one canary-gated deploy publisher (see module docstring).

    The :func:`~rcmarl_tpu.parallel.gossip.train_gossip` signature and
    return contract (replica-stacked TrainState + non-Byzantine-mean
    DataFrame) merged with the pipelined trainer's guard knobs:

    Args:
      guard: per-block pipeline guard AND per-segment replica guard
        (``None`` auto-enables under any active fault plan, both
        levels together — the composed run has one threat model).
      max_retries: the pipeline guard's per-block redraw/retry budget.
      window_fault: the composed chaos seam —
        ``window_fault(replica, block, attempt, fresh, metrics)``,
        the solo pipeline's transit seam with the replica index
        prepended, so the chaos campaign can poison ONE replica's
        actor tier inside a live fleet.
      states / start_round / excluded / readmit_after: the gossip
        resume/quarantine protocol, verbatim.

    ``df.attrs`` carries the MERGED counter surface: ``pipeline``
    (per-dispatch staleness across all replicas, publishes/rejects
    summed — mix-round force republishes included), ``guard`` (summed
    retries/redraws/skips plus per-replica breakdowns), ``gossip``
    (the synchronous trainer's full key set), ``canary`` (gate +
    deploy-publisher counters), and ``gala`` (the topology marker
    cmd_train keys the merged summary line on).
    """
    R = cfg.replicas
    depth = cfg.pipeline_depth
    if R < 1:
        raise ValueError(
            f"train_gala needs cfg.replicas >= 1 (got {R}); the solo "
            "pipelined trainer is rcmarl_tpu.pipeline.trainer."
            "train_pipelined"
        )
    n_eps = cfg.n_episodes if n_episodes is None else n_episodes
    if n_eps % cfg.n_ep_fixed != 0:
        raise ValueError(
            f"n_episodes={n_eps} must be a multiple of "
            f"n_ep_fixed={cfg.n_ep_fixed}"
        )
    if max_retries < 0:
        raise ValueError(f"max_retries={max_retries} must be >= 0")
    if readmit_after < 0:
        raise ValueError(f"readmit_after={readmit_after} must be >= 0")

    if depth == 0:
        # ---- the synchronous-gossip reference arm IS the synchronous
        # gossip trainer: delegate, so the (R, depth=0) pin — and,
        # through its own pin, the (R, depth=0, gossip_every=0) pin to
        # train_parallel — is bitwise by construction
        if window_fault is not None:
            raise ValueError(
                "window_fault is the decoupled tiers' transit seam; "
                "the depth-0 synchronous handoff has no actor->learner "
                "transit to fault (run pipeline_depth >= 1)"
            )
        from rcmarl_tpu.parallel.gossip import train_gossip

        states, df = train_gossip(
            cfg,
            n_episodes=n_eps,
            states=states,
            verbose=verbose,
            block_callback=block_callback,
            guard=guard,
            start_round=start_round,
            excluded=excluded,
            readmit_after=readmit_after,
        )
        n_blocks = n_eps // cfg.n_ep_fixed
        df.attrs["pipeline"] = {
            "depth": 0,
            "publish_every": cfg.publish_every,
            "blocks": n_blocks,
            "staleness": [0] * n_blocks,
            "staleness_mean": 0.0,
            "staleness_max": 0,
            "publishes": n_blocks,
            "rejects": 0,
        }
        return states, df

    if R == 1:
        # ---- a one-replica fleet IS the solo pipelined trainer (a
        # self-mix is an identity): delegate, so the (depth>0, R=1)
        # pin is bitwise by construction; the returned state gains the
        # replica axis so the checkpoint layout matches the fleet path
        from rcmarl_tpu.pipeline.trainer import train_pipelined

        wf = None
        if window_fault is not None:
            wf = lambda b, a, f, m: window_fault(0, b, a, f, m)  # noqa: E731
        solo = None if states is None else _unstack_states(states, 1)[0]
        solo, df = train_pipelined(
            cfg,
            n_episodes=n_eps,
            state=solo,
            verbose=verbose,
            block_callback=(
                None
                if block_callback is None
                else lambda s, b: block_callback(
                    _stack_states([s]),
                    b,
                    {"replicas": 1, "gossip_round": start_round,
                     "excluded": [0], "segment_blocks": 1},
                )
            ),
            guard=guard,
            max_retries=max_retries,
            window_fault=wf,
        )
        df.attrs["gossip"] = {
            "rounds": 0, "rollbacks": 0, "excluded": 0, "readmitted": 0,
            "nonfinite": 0, "deficit": 0, "replicas": 1,
            "gossip_every": cfg.gossip_every, "graph": cfg.gossip_graph,
            "mix": cfg.gossip_mix, "H": cfg.gossip_H, "byzantine": [],
            "replica_healthy": [True], "gossip_round": int(start_round),  # lint: disable=host-sync
            "excluded_mask": [0], "readmit_after": readmit_after,
            "quarantined": [0],
        }
        return _stack_states([solo]), df

    # ---- the composed fleet
    from rcmarl_tpu.faults import params_finite, tree_all_finite
    from rcmarl_tpu.serve.engine import actor_block
    from rcmarl_tpu.training.trainer import (
        _block_healthy,
        init_train_state,
        metrics_to_dataframe,
    )

    n_blocks = n_eps // cfg.n_ep_fixed
    if guard is None:
        guard = (
            cfg.fault_plan is not None and cfg.fault_plan.active
        ) or (
            cfg.replica_fault_plan is not None
            and cfg.replica_fault_plan.active
        )
    with_diag = cfg.fault_plan is not None and cfg.fault_plan.active
    donate = not guard
    learner = learner_block if guard else learner_block_donated

    if states is None:
        state = [
            init_train_state(cfg, jax.random.PRNGKey(s))  # lint: disable=prng-int-seed
            for s in replica_seeds(cfg)
        ]
    else:
        # slicing the stacked resume state gathers into fresh buffers,
        # so the caller's state stays alive whatever the donate policy
        state = _unstack_states(states, R)

    plan = cfg.replica_fault_plan
    byz = set(plan.byzantine_replicas) if plan is not None else set()
    stale_replay = plan is not None and plan.active and float(plan.stale_p) > 0
    carried = (
        np.zeros(R, bool) if excluded is None else np.asarray(excluded, bool)  # lint: disable=host-sync
    )
    excluded_mask = carried if readmit_after == 0 else np.zeros(R, bool)
    quarantine = carried.copy() if readmit_after > 0 else np.zeros(R, bool)
    streak = np.zeros(R, np.int64)
    round_idx = int(start_round)  # lint: disable=host-sync

    # ---- per-replica pipeline plumbing (the solo trainer's, times R)
    publisher = [
        PolicyPublisher(state[r].params, cfg.publish_every, copy=donate)
        for r in range(R)
    ]
    desired0 = [jnp.copy(state[r].desired) for r in range(R)]
    initial0 = [jnp.copy(state[r].initial) for r in range(R)]
    staleness = [[] for _ in range(R)]
    rep_stats = [
        {"retries": 0, "redraws": 0, "skipped": 0, "nonfinite": 0,
         "deficit": 0}
        for _ in range(R)
    ]
    all_metrics = [[] for _ in range(R)]

    # ---- the canary-gated deploy publisher (the fleet-facing policy)
    gate = None
    if cfg.canary_band:
        from rcmarl_tpu.serve.canary import CanaryGate

        gate = CanaryGate(
            cfg,
            desired0[0],
            initial0[0],
            band=cfg.canary_band,
            blocks=cfg.canary_blocks,
            eval_seed=cfg.gossip_seed,
        )
        gate.set_incumbent(state[0].params)
    deploy = PolicyPublisher(
        state[0].params,
        1,
        copy=donate,
        validate=True,
        canary=gate.admit if gate is not None else None,
    )

    # gossip-level rollback targets / stale-replay payloads: post-mix
    # snapshots. With guard on the learner keeps inputs alive, so the
    # states themselves are safe to hold; the donated (unguarded) loop
    # consumes its state buffers, so stale payloads must be copies.
    last_good = list(state) if guard else None
    prev_payload = (
        [jax.tree.map(jnp.copy, state[r].params) for r in range(R)]
        if stale_replay
        else None
    )

    stats_g = {
        "rounds": 0, "rollbacks": 0, "excluded": 0, "readmitted": 0,
        "nonfinite": 0, "deficit": 0,
    }
    deploy_round = 0
    blocks_done = 0

    def _run_segment(r: int, start: int, seg_len: int):
        """One replica's pipelined segment: the solo pipelined loop over
        blocks [start, start+seg_len), chain walked from the replica's
        stored key (the resume discipline — see module docstring),
        queue drained by construction at the boundary."""
        st = state[r]
        pub = publisher[r]
        stats = rep_stats[r]
        chain = [st.key]
        keys = []

        def block_keys(j_local: int):
            while len(keys) <= j_local:
                nk, kr, ku = jax.random.split(chain[-1], 3)
                chain.append(nk)
                keys.append((kr, ku))
            return keys[j_local]

        queue = BlockQueue(depth)
        seg_metrics = []

        def dispatch_actor(j_local: int) -> None:
            k_roll, _ = block_keys(j_local)
            fresh, m = actor_block(
                cfg, pub.acting, desired0[r], k_roll, initial0[r]
            )
            staleness[r].append(start + j_local - pub.published_block)
            queue.put((j_local, fresh, m))

        for j in range(min(depth, seg_len)):
            dispatch_actor(j)
        for bl in range(seg_len):
            b = start + bl  # the global block index
            j, fresh, m = queue.get()
            assert j == bl, f"pipeline order broke: got block {j} at {bl}"
            if window_fault is not None:
                fresh, m = window_fault(r, b, 0, fresh, m)
            _, k_upd = block_keys(bl)
            new_key = chain[bl + 1]
            attempt = 0
            accepted = True
            diag = None
            window_ok = True
            if guard:
                window_ok = _window_healthy(fresh, m)
                redraw = 0
                while not window_ok and redraw < max_retries:
                    redraw += 1
                    stats["redraws"] += 1
                    if verbose:
                        print(
                            f"| replica {r} block {b + 1} | non-finite "
                            f"rollout window — redrawing (redraw "
                            f"{redraw}/{max_retries})"
                        )
                    k_roll = jax.random.fold_in(
                        jax.random.fold_in(chain[bl], _REDRAW_STREAM),
                        redraw,
                    )
                    fresh, m = actor_block(
                        cfg, pub.acting, desired0[r], k_roll, initial0[r]
                    )
                    if window_fault is not None:
                        fresh, m = window_fault(r, b, redraw, fresh, m)
                    window_ok = _window_healthy(fresh, m)
            if not window_ok:
                stats["skipped"] += 1
                if verbose:
                    print(
                        f"| replica {r} block {b + 1} | rollout window "
                        f"still non-finite after {max_retries} redraws "
                        "— skipping (no learner launch)"
                    )
                st = _skip_stored_key(st, b)
                accepted = False
            else:
                while True:
                    if attempt:
                        k_upd = jax.random.fold_in(chain[bl], attempt)
                    diag = None
                    if with_diag:
                        new_state, diag = learner(
                            cfg, st, fresh, k_upd, new_key, with_diag=True
                        )
                    else:
                        new_state = learner(cfg, st, fresh, k_upd, new_key)
                    if not guard or _block_healthy(new_state, m):
                        st = new_state
                        break
                    if attempt < max_retries:
                        attempt += 1
                        stats["retries"] += 1
                        if verbose:
                            print(
                                f"| replica {r} block {b + 1} | "
                                f"non-finite learner output — rolling "
                                f"back (retry {attempt}/{max_retries})"
                            )
                        continue
                    stats["skipped"] += 1
                    if verbose:
                        print(
                            f"| replica {r} block {b + 1} | still "
                            f"non-finite after {max_retries} retries — "
                            "skipping (params rolled back)"
                        )
                    st = _skip_stored_key(st, b)
                    accepted = False
                    break
            if diag is not None:
                stats["nonfinite"] += int(diag.nonfinite)  # lint: disable=host-sync
                stats["deficit"] += int(diag.deficit)  # lint: disable=host-sync
            seg_metrics.append(m)
            all_metrics[r].append(m)
            if accepted:
                pub.offer(st.params, b + 1)
            if bl + depth < seg_len:
                dispatch_actor(bl + depth)
        state[r] = st
        return seg_metrics

    for seg_len, mix_after in _segment_lengths(n_blocks, cfg.gossip_every):
        seg_start = blocks_done
        skipped_before = [rep_stats[r]["skipped"] for r in range(R)]
        seg_metrics = [_run_segment(r, seg_start, seg_len) for r in range(R)]
        blocks_done += seg_len
        healthy = np.ones(R, bool)
        if guard:
            for r in range(R):
                finite = bool(  # lint: disable=host-sync
                    tree_all_finite(
                        (state[r].params, tuple(seg_metrics[r]))
                    )
                )
                skipped_seg = rep_stats[r]["skipped"] - skipped_before[r]
                # a replica whose pipeline guard SKIPPED blocks this
                # segment already contained its poison (params rolled
                # back block-locally, nothing published) — no gossip
                # rollback, but its params sit out the next mix; a
                # replica that ends the segment NON-FINITE (guard off
                # at the block level never happens here, but metrics
                # can go non-finite under an unsanitized plan) rolls
                # back to its last good post-mix state
                healthy[r] = finite and skipped_seg == 0
                if not finite:
                    stats_g["rollbacks"] += 1
                    lg = last_good[r]
                    state[r] = lg._replace(
                        key=jax.random.fold_in(
                            lg.key, _ROLLBACK_STREAM + round_idx
                        ),
                        block=lg.block + seg_len,
                    )
                    # the actor tier must not keep acting on the
                    # poisoned publish chain
                    publisher[r].offer(
                        state[r].params, blocks_done, force=True
                    )
            if readmit_after > 0:
                streak = np.where(quarantine & healthy, streak + 1, streak)
                readmit = quarantine & healthy & (streak >= readmit_after)
                if readmit.any():
                    stats_g["readmitted"] += int(readmit.sum())  # lint: disable=host-sync
                    quarantine &= ~readmit
                    streak[readmit] = 0
                quarantine |= ~healthy
                streak[~healthy] = 0
            else:
                excluded_mask = excluded_mask | ~healthy
        if mix_after:
            mix_exclude = excluded_mask | quarantine
            params_tuple = tuple(state[r].params for r in range(R))
            prev_tuple = (
                tuple(prev_payload) if stale_replay else params_tuple
            )
            mixed, diag = gala_mix_block(
                cfg,
                params_tuple,
                prev_tuple,
                jnp.asarray(round_idx, jnp.int32),
                jnp.asarray(mix_exclude),
            )
            stats_g["rounds"] += 1
            stats_g["excluded"] += int(mix_exclude.sum())  # lint: disable=host-sync
            stats_g["nonfinite"] += int(diag.nonfinite)  # lint: disable=host-sync
            stats_g["deficit"] += int(diag.deficit)  # lint: disable=host-sync
            excluded_mask = np.zeros(R, bool)
            round_idx += 1
            for r in range(R):
                state[r] = state[r]._replace(params=mixed[r])
                # the mix is a publish event whatever the cadence: the
                # actor tier must act on post-mix params, or queued
                # windows would roll under a policy no learner holds
                publisher[r].offer(state[r].params, blocks_done, force=True)
            if guard:
                for r in range(R):
                    # only a finite post-mix tree may become the new
                    # rollback target (the mean arm's poisoned mix must
                    # not become the "good" state)
                    if bool(params_finite(state[r].params)):  # lint: disable=host-sync
                        last_good[r] = state[r]
            if stale_replay:
                prev_payload = [
                    jax.tree.map(jnp.copy, state[r].params) for r in range(R)
                ]
        # ---- the canary-gated deploy: the winning replica's (post-mix)
        # policy is offered to the fleet after every segment
        deploy_round += 1
        seg_means = np.full(R, np.nan)
        for r in range(R):
            tt = np.concatenate(
                [np.asarray(m.true_team_returns) for m in seg_metrics[r]]  # lint: disable=host-sync
            )
            if np.isfinite(tt).any():
                seg_means[r] = np.nanmean(tt)
        eligible = [
            r
            for r in range(R)
            if healthy[r]
            and not quarantine[r]
            and r not in byz
            and np.isfinite(seg_means[r])
        ]
        if eligible:
            winner = max(eligible, key=lambda r: seg_means[r])
            deploy.offer(state[winner].params, deploy_round)
        if verbose:
            keep = [r for r in range(R) if r not in byz] or list(range(R))
            import warnings as _warnings

            with _warnings.catch_warnings():
                _warnings.filterwarnings(
                    "ignore", message="Mean of empty slice"
                )
                seg_return = float(np.nanmean(seg_means[np.array(keep)]))  # lint: disable=host-sync
            print(
                f"| blocks {blocks_done}/{n_blocks} | round {round_idx} "
                f"| team return {seg_return:.3f}"
                + (" | mixed" if mix_after else "")
            )
        if block_callback is not None:
            block_callback(
                _stack_states(state),
                blocks_done - 1,
                {
                    "replicas": R,
                    "gossip_round": round_idx,
                    "excluded": [
                        int(x) for x in (excluded_mask | quarantine)  # lint: disable=host-sync
                    ],
                    "segment_blocks": seg_len,
                    "pipeline_depth": depth,
                },
            )

    # ---- merge the metrics: one row per episode, the non-Byzantine
    # replicas' nanmean (the synchronous gossip trainer's convention)
    import warnings as _warnings

    metrics = [
        jax.tree.map(
            lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0),  # lint: disable=host-sync
            *all_metrics[r],
        )
        for r in range(R)
    ]
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *metrics)
    keep = [r for r in range(R) if r not in byz] or list(range(R))
    with _warnings.catch_warnings():
        _warnings.filterwarnings("ignore", message="Mean of empty slice")
        mean_metrics = jax.tree.map(
            lambda l: np.nanmean(l[np.array(keep)], axis=0), stacked
        )
    df = metrics_to_dataframe(mean_metrics)

    # ---- the merged counter surface
    flat_staleness = [s for r in range(R) for s in staleness[r]]
    df.attrs["pipeline"] = {
        "depth": depth,
        "publish_every": cfg.publish_every,
        "blocks": n_blocks,
        "staleness": flat_staleness,
        "staleness_mean": (
            sum(flat_staleness) / len(flat_staleness)
            if flat_staleness
            else 0.0
        ),
        "staleness_max": max(flat_staleness, default=0),
        "publishes": sum(p.counters["publishes"] for p in publisher),
        "rejects": sum(p.counters["rejects"] for p in publisher),
    }
    if guard or with_diag:
        df.attrs["guard"] = {
            "retries": sum(s["retries"] for s in rep_stats),
            "redraws": sum(s["redraws"] for s in rep_stats),
            "skipped": sum(s["skipped"] for s in rep_stats),
            "nonfinite": sum(s["nonfinite"] for s in rep_stats),
            "deficit": sum(s["deficit"] for s in rep_stats),
            "replica_retries": [s["retries"] for s in rep_stats],
            "replica_redraws": [s["redraws"] for s in rep_stats],
            "replica_skipped": [s["skipped"] for s in rep_stats],
        }
    healthy_final = [
        bool(params_finite(state[r].params)) for r in range(R)  # lint: disable=host-sync
    ]
    df.attrs["gossip"] = {
        **stats_g,
        "replicas": R,
        "gossip_every": cfg.gossip_every,
        "graph": cfg.gossip_graph,
        "mix": cfg.gossip_mix,
        "H": cfg.gossip_H,
        "byzantine": sorted(byz),
        "replica_healthy": healthy_final,
        "gossip_round": round_idx,
        "excluded_mask": [int(x) for x in (excluded_mask | quarantine)],  # lint: disable=host-sync
        "readmit_after": readmit_after,
        "quarantined": [int(x) for x in quarantine],  # lint: disable=host-sync
    }
    df.attrs["canary"] = {
        "band": cfg.canary_band,
        "blocks": cfg.canary_blocks,
        "evals": gate.counters["evals"] if gate is not None else 0,
        "accepts": gate.counters["accepts"] if gate is not None else 0,
        "rejects": gate.counters["rejects"] if gate is not None else 0,
        "incumbent_return": (
            gate.incumbent_return if gate is not None else None
        ),
        "deploys": deploy.counters["publishes"],
        "deploy_rejects": deploy.counters["rejects"],
        "canary_rejects": deploy.counters["canary_rejects"],
        "deploy_healthy": bool(params_finite(deploy.acting)),  # lint: disable=host-sync
    }
    df.attrs["gala"] = {"replicas": R, "depth": depth}
    return _stack_states(state), df
