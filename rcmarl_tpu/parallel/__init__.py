from rcmarl_tpu.parallel.distributed import (  # noqa: F401
    gather_metrics,
    initialize,
    multihost_mesh,
)
from rcmarl_tpu.parallel.seeds import (  # noqa: F401
    init_states,
    make_mesh,
    state_shardings,
    train_block_parallel,
    train_parallel,
)
