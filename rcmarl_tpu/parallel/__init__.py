from rcmarl_tpu.parallel.distributed import (  # noqa: F401
    gather_metrics,
    initialize,
    multihost_mesh,
)
from rcmarl_tpu.parallel.gala import (  # noqa: F401
    gala_fingerprint,
    gala_mix_block,
    gala_summary,
    train_gala,
)
from rcmarl_tpu.parallel.gossip import (  # noqa: F401
    gossip_mix_block,
    replica_in_nodes,
    replica_seeds,
    train_gossip,
)
from rcmarl_tpu.parallel.matrix import (  # noqa: F401
    matrix_specs,
    reset_matrix_for_phase,
    split_matrix_metrics,
    train_matrix,
)
from rcmarl_tpu.parallel.seeds import (  # noqa: F401
    init_states,
    make_mesh,
    state_shardings,
    train_block_parallel,
    train_parallel,
)
