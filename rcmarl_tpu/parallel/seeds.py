"""Seed- and agent-axis parallelism over a TPU device mesh.

The reference achieves seed parallelism by submitting independent SGE jobs
(``simulation_results/raw_data/*/job.sh``, SURVEY.md C15) and has no other
parallel axis. Here both axes are first-class sharding dimensions of ONE
jitted program over a ``jax.sharding.Mesh``:

- ``seed`` axis (data parallel): independent training replicas, vmapped
  over a leading seed axis and sharded across chips. No cross-replica
  communication — XLA partitions the program with zero collectives, so it
  scales embarrassingly over ICI and DCN alike.
- ``agent`` axis (model parallel): the stacked per-agent parameters can
  additionally be sharded over agents. The consensus gather
  ``msgs[in_nodes]`` then lowers to an XLA all-gather/collective-permute
  over ICI — the TPU-native twin of the reference's in-memory weight-list
  exchange (SURVEY.md C16).

The entry point is :func:`train_parallel`; sharding specs are derived
structurally from the TrainState field layout by :func:`state_shardings`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rcmarl_tpu.config import Config
from rcmarl_tpu.training.rollout import EpisodeMetrics
from rcmarl_tpu.training.trainer import (
    TrainState,
    init_train_state,
    train_block,
    train_scanned,
)


def make_mesh(
    n_devices: Optional[int] = None, seed_axis: Optional[int] = None
) -> Mesh:
    """A ('seed', 'agent') mesh over the first ``n_devices`` devices.

    ``seed_axis`` fixes the seed-parallel extent; the agent axis gets the
    rest. Defaults put everything on the seed axis (the scaling axis that
    matters at reference model sizes)."""
    all_devs = jax.devices()
    if n_devices is not None and n_devices > len(all_devs):
        raise ValueError(
            f"requested {n_devices} devices, only {len(all_devs)} available"
        )
    devs = all_devs if n_devices is None else all_devs[:n_devices]
    n = len(devs)
    if seed_axis is None:
        seed_axis = n
    if n % seed_axis != 0:
        raise ValueError(f"seed_axis={seed_axis} must divide device count {n}")
    import numpy as np

    return Mesh(
        np.asarray(devs).reshape(seed_axis, n // seed_axis), ("seed", "agent")
    )


def state_shardings(
    mesh: Mesh, state_batched: TrainState, shard_agents: bool = True
) -> TrainState:
    """NamedShardings for a seed-batched TrainState (leaves carry a leading
    seed axis), built structurally field by field.

    Field layout (axis holding the agent dimension, after the seed axis):
      params.*        (S, N, ...)    -> agent at 1
      buffer.s/ns/a/r (S, C, N, ...) -> agent at 2
      buffer.ptr/count, key, block   -> seed only
      desired/initial (S, N, 2)      -> agent at 1
    """
    a = "agent" if shard_agents else None

    def ns(spec):
        return NamedSharding(mesh, spec)

    def fill(subtree, spec):
        return jax.tree.map(lambda _: ns(spec), subtree)

    buf = state_batched.buffer
    return TrainState(
        params=fill(state_batched.params, P("seed", a)),
        buffer=buf._replace(
            s=ns(P("seed", None, a)),
            ns=ns(P("seed", None, a)),
            a=ns(P("seed", None, a)),
            r=ns(P("seed", None, a)),
            ptr=ns(P("seed")),
            count=ns(P("seed")),
        ),
        desired=ns(P("seed", a)),
        initial=ns(P("seed", a)),
        key=ns(P("seed")),
        block=ns(P("seed")),
    )


def init_states(cfg: Config, seeds) -> TrainState:
    """vmapped :func:`init_train_state` over a batch of integer seeds —
    each replica draws its own goal layout, initial layout, and parameter
    init, exactly like independent reference jobs."""
    keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray(seeds, jnp.uint32))
    return jax.vmap(lambda k: init_train_state(cfg, k))(keys)


def reset_state_for_phase(cfg: Config, state: TrainState, seed) -> TrainState:
    """The phase-restart boundary for ONE replica (weights + goal kept;
    Adam moments, buffer, and RNG reset — see
    :func:`reset_states_for_phase` for the protocol provenance). The
    solo form exists for the time-varying-graph sweep cells, whose
    per-block host resample keeps them off the vmapped seed program."""
    from rcmarl_tpu.ops.optim import adam_init

    params = state.params._replace(
        actor_opt=jax.vmap(adam_init)(state.params.actor)
    )
    return init_train_state(
        cfg,
        jax.random.PRNGKey(seed),
        desired=state.desired,
        params=params,
    )


def reset_states_for_phase(cfg: Config, states: TrainState, seeds) -> TrainState:
    """Reference two-phase protocol boundary (SURVEY.md §5): the published
    runs are 4000+4000 episodes as two processes, where the restart
    restores weights and the goal layout (``--pretrained_agents``,
    reference ``main.py:52-54,83-86``) but resets the actor's Adam
    moments, the replay buffer, and the RNG streams (``main.py:46-47``
    re-seeds with the same ``--random_seed``). Applies that boundary to a
    batch of replicas: params + desired carry over, everything else
    re-initializes from each replica's seed exactly as phase 1 did."""
    return jax.vmap(lambda s, sd: reset_state_for_phase(cfg, s, sd))(
        states, jnp.asarray(seeds, jnp.uint32)
    )


#: Compiled-program cache for :func:`train_parallel` and
#: :func:`rcmarl_tpu.parallel.matrix.train_matrix` (bounded FIFO: the CLI
#: touches a handful of configs; tests churn many tiny ones).
_JIT_CACHE: dict = {}
_JIT_CACHE_MAX = 32


def cached_jit(key, build):
    """Bounded-FIFO memo for compiled multi-replica programs: repeated
    calls with the same program shape (phase 2 of a sweep, benchmark
    reps) reuse the executable instead of re-tracing a fresh closure."""
    fn = _JIT_CACHE.get(key)
    if fn is None:
        fn = build()
        if len(_JIT_CACHE) >= _JIT_CACHE_MAX:
            _JIT_CACHE.pop(next(iter(_JIT_CACHE)))
        _JIT_CACHE[key] = fn
    return fn


def _parallel_program(
    cfg: Config,
    states: TrainState,
    n_blocks: int,
    mesh: Mesh,
    shard_agents: bool,
    specs=None,
):
    """(jitted fn, device-placed states[, device-placed specs]): the
    sharded multi-replica executable, shared by :func:`train_parallel`
    (which executes it) and :func:`lower_parallel` (which only inspects
    its lowering — the graftlint collective census). One ``cached_jit``
    slot per program shape either way.

    ``specs`` (optional): a replica-batched ``CellSpec`` pytree — one
    traced scenario per replica (the Diff-DAC task axis threads
    per-replica ``task_scale`` load levels through here,
    :func:`rcmarl_tpu.parallel.gossip.train_gossip`). ``None`` keeps
    the historical trace-time-specialized program bit-for-bit."""
    in_shard = state_shardings(mesh, states, shard_agents)
    states = jax.device_put(states, in_shard)
    if specs is None:
        fn = cached_jit(
            ("seeds", cfg, n_blocks, mesh, shard_agents),
            lambda: jax.jit(
                jax.vmap(lambda s: train_scanned(cfg, s, n_blocks)),
                in_shardings=(in_shard,),
                out_shardings=(in_shard, NamedSharding(mesh, P("seed"))),
            ),
        )
        return fn, states
    a = "agent" if shard_agents else None
    spec_shard = jax.tree.map(
        lambda x: NamedSharding(
            mesh, P("seed", a) if x.ndim > 1 else P("seed")
        ),
        specs,
    )
    specs = jax.device_put(specs, spec_shard)
    fn = cached_jit(
        ("seeds+spec", cfg, n_blocks, mesh, shard_agents),
        lambda: jax.jit(
            jax.vmap(lambda s, sp: train_scanned(cfg, s, n_blocks, sp)),
            in_shardings=(in_shard, spec_shard),
            out_shardings=(in_shard, NamedSharding(mesh, P("seed"))),
        ),
    )
    return fn, states, specs


def lower_parallel(
    cfg: Config,
    seeds,
    n_blocks: int = 1,
    mesh: Optional[Mesh] = None,
    shard_agents: bool = False,
):
    """Lower (without executing) the sharded replica program: the
    ``jax.stages.Lowered`` whose compiled HLO the collective census
    audits. Safe on single-core hosts — nothing here runs the
    collectives, it only compiles them."""
    states = init_states(cfg, seeds)
    if mesh is None:
        mesh = make_mesh()
    fn, states = _parallel_program(cfg, states, n_blocks, mesh, shard_agents)
    return fn.lower(states)


def train_parallel(
    cfg: Config,
    seeds=None,
    n_blocks: int = 1,
    mesh: Optional[Mesh] = None,
    shard_agents: bool = False,
    states: Optional[TrainState] = None,
    specs=None,
) -> Tuple[TrainState, EpisodeMetrics]:
    """Run independent replicas as one sharded XLA program.

    Args:
      seeds: integer seeds for FRESH replicas, length divisible by the
        mesh 'seed' axis. Mutually exclusive with ``states``.
      n_blocks: training blocks per replica (n_ep_fixed episodes each).
      mesh: ('seed', 'agent') mesh; defaults to all devices on 'seed'.
      shard_agents: also partition the agent axis over the mesh's 'agent'
        dimension (consensus gathers become ICI collectives).
      states: resume from previously returned batched states (their RNG
        streams continue; seeds must then be None).
      specs: optional replica-batched ``CellSpec`` — one traced scenario
        per replica (the Diff-DAC task axis rides here; ``None`` is the
        historical bit-for-bit path).

    Returns (batched TrainState, EpisodeMetrics with leading seed axis).
    """
    if (seeds is None) == (states is None):
        raise ValueError("pass exactly one of `seeds` (fresh) or `states` (resume)")
    if cfg.graph_schedule != "static":
        raise ValueError(
            "train_parallel cannot run a time-varying graph_schedule "
            "(the per-block resample is host-side data the device scan "
            "cannot regenerate); use train() (the solo host loop)"
        )
    if mesh is None:
        # Default mesh must evenly shard the replica axis: use the largest
        # device count that divides the replica count, all on 'seed'.
        n_rep = (
            len(seeds)
            if seeds is not None
            else int(jax.tree.leaves(states)[0].shape[0])
        )
        n_dev = max(
            d for d in range(1, len(jax.devices()) + 1) if n_rep % d == 0
        )
        mesh = make_mesh(n_dev)
    if states is None:
        states = init_states(cfg, seeds)

    if specs is None:
        fn, states = _parallel_program(
            cfg, states, n_blocks, mesh, shard_agents
        )
        return fn(states)
    fn, states, specs = _parallel_program(
        cfg, states, n_blocks, mesh, shard_agents, specs
    )
    return fn(states, specs)


def train_block_parallel(
    cfg: Config,
    states: TrainState,
    mesh: Mesh,
    shard_agents: bool = False,
) -> Tuple[TrainState, EpisodeMetrics]:
    """One sharded multi-replica block (the checkpointable granularity)."""
    in_shard = state_shardings(mesh, states, shard_agents)
    states = jax.device_put(states, in_shard)
    fn = jax.jit(
        jax.vmap(lambda s: train_block(cfg, s)),
        in_shardings=(in_shard,),
        out_shardings=(in_shard, NamedSharding(mesh, P("seed"))),
    )
    return fn(states)
