"""The whole heterogeneous experiment matrix as ONE sharded program.

The reference runs its scenario x H x seed experiment matrix as
independent SGE jobs, ~8.8 h each (``simulation_results/raw_data/*/
job.sh``, BASELINE.md); this framework's ``sweep`` already collapses the
seed axis of each cell into one vmapped program. This module collapses
the remaining loop: cells with DIFFERENT scenarios (role composition,
trim parameter H, private vs team-average reward) become replicas of a
single jitted, mesh-sharded program, by passing each cell's knobs as
traced data (:class:`~rcmarl_tpu.agents.updates.CellSpec`) instead of
trace-time constants.

What makes this sound:

- Cells may differ ONLY in ``agent_roles`` / ``H`` / ``common_reward``
  (checked at entry): everything shape-relevant (N, graph, model sizes,
  schedule) is shared, so one compiled executable serves all replicas.
- A spec-mode replica is numerically identical to its statically
  specialized solo twin (``tests/test_matrix.py`` pins bitwise equality
  at the update-block level and float32-rounding equality end-to-end),
  so fusing the matrix changes wall-clock, not science.
- Heterogeneity costs compute-all-then-mask across the three role
  branches — the trade SURVEY.md §7 endorses at these model sizes — and
  one XLA program means the chip sees ``n_cells x n_seeds`` replicas to
  batch (the regime where TPU throughput scales almost for free,
  bench.py's replica sweep).

Traced H rides the XLA consensus path (the Pallas kernel fixes trim
indices at lowering time, ops/aggregation.py) and requires a uniform-
degree graph — both true of every reference scenario.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rcmarl_tpu.agents.updates import CellSpec
from rcmarl_tpu.config import Config
from rcmarl_tpu.training.rollout import EpisodeMetrics
from rcmarl_tpu.training.trainer import TrainState, train_scanned
from rcmarl_tpu.training.update import spec_from_config
from rcmarl_tpu.parallel.seeds import (
    cached_jit,
    init_states,
    make_mesh,
    reset_states_for_phase,
    state_shardings,
)

__all__ = [
    "matrix_specs",
    "train_matrix",
    "lower_matrix",
    "reset_matrix_for_phase",
    "split_matrix_metrics",
]


def _check_fusable(base: Config, cells: Sequence[Config]) -> None:
    """Every cell must be the base config modulo the traced knobs."""
    for i, cell in enumerate(cells):
        norm = cell.replace(
            agent_roles=base.agent_roles,
            H=base.H,
            common_reward=base.common_reward,
        )
        if norm != base:
            raise ValueError(
                f"cell {i} differs from the base config beyond "
                "agent_roles/H/common_reward; the fused matrix needs one "
                "shared program shape"
            )
        if cell.padded_in_nodes()[1] is not None:
            raise ValueError(
                "the fused matrix requires a uniform-degree graph "
                "(traced H excludes the padded-neighborhood path)"
            )
    if base.consensus_impl not in ("xla", "xla_sort", "auto"):
        raise ValueError(
            "the fused matrix runs consensus on the XLA path (traced H); "
            f"consensus_impl={base.consensus_impl!r} cannot apply"
        )
    if base.graph_schedule != "static":
        raise ValueError(
            "the fused matrix cannot run a time-varying graph_schedule "
            "(the per-block resample is host-side data the device scan "
            "cannot regenerate); use the solo trainer"
        )
    from rcmarl_tpu.config import Roles

    if any(Roles.ADAPTIVE in c.agent_roles for c in cells):
        raise ValueError(
            "the fused matrix (traced CellSpec) does not model the "
            "ADAPTIVE colluding adversary; run adaptive cells through "
            "the per-cell sweep or the solo trainer"
        )


def matrix_specs(cells: Sequence[Config], n_seeds: int) -> CellSpec:
    """Stack each cell's :class:`CellSpec` and repeat it across the seed
    axis: replica layout is CELL-MAJOR, ``replica = cell * n_seeds +
    seed_index`` — the layout :func:`train_matrix` and its callers use to
    slice results back into (cell, seed) order."""
    specs = [spec_from_config(c) for c in cells]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *specs)
    return jax.tree.map(
        lambda x: jnp.repeat(x, n_seeds, axis=0), stacked
    )


def _tile_states(states: TrainState, n_cells: int) -> TrainState:
    """Tile seed-batched states across the cell axis (cell-major): every
    cell starts from the same per-seed init, exactly as the solo sweep's
    cells do (init depends on the seed, never on roles/H)."""
    return jax.tree.map(
        lambda x: jnp.tile(x, (n_cells,) + (1,) * (x.ndim - 1)), states
    )


def train_matrix(
    base: Config,
    cells: Sequence[Config],
    seeds: Sequence[int],
    n_blocks: int,
    mesh: Optional[Mesh] = None,
    states: Optional[TrainState] = None,
    shard_agents: bool = False,
    compile_only: bool = False,
) -> Optional[Tuple[TrainState, EpisodeMetrics]]:
    """Train every (cell, seed) replica in one sharded XLA program.

    Args:
      base: the shared program shape (any of the cells works).
      cells: per-cell configs differing only in roles/H/common_reward.
      seeds: integer seeds; replicas = len(cells) * len(seeds),
        cell-major.
      n_blocks: training blocks per replica.
      mesh: ('seed', 'agent') mesh; defaults to the largest device count
        dividing the replica count, all on 'seed'.
      states: resume from previously returned batched states (phase 2 of
        the published protocol; see :func:`reset_matrix_for_phase`).
      shard_agents: additionally partition the agent axis over the
        mesh's 'agent' dimension (consensus gathers become ICI
        collectives, PARALLELISM.md) — composes with cell fusion.
      compile_only: lower and compile the sharded program, execute
        nothing, return None. Validates shardings and collective
        lowering on hosts where collective EXECUTION cannot run (e.g.
        single-core virtual meshes, where XLA's in-process rendezvous
        watchdog would abort — tests/conftest.py:needs_multicore).

    Returns (batched TrainState, EpisodeMetrics), leading axis
    ``len(cells) * len(seeds)`` in cell-major order; None when
    ``compile_only``.
    """
    fn, states, specs = _matrix_program(
        base, cells, seeds, n_blocks, mesh, states, shard_agents
    )
    if compile_only:
        fn.lower(states, specs).compile()
        return None
    return fn(states, specs)


def _matrix_program(
    base: Config,
    cells: Sequence[Config],
    seeds: Sequence[int],
    n_blocks: int,
    mesh: Optional[Mesh] = None,
    states: Optional[TrainState] = None,
    shard_agents: bool = False,
):
    """(jitted fn, device-placed states, device-placed specs): the fused
    matrix executable, shared by :func:`train_matrix` and
    :func:`lower_matrix`."""
    _check_fusable(base, cells)
    n_rep = len(cells) * len(seeds)
    if mesh is None:
        n_dev = max(
            d for d in range(1, len(jax.devices()) + 1) if n_rep % d == 0
        )
        mesh = make_mesh(n_dev)
    if states is None:
        states = _tile_states(init_states(base, list(seeds)), len(cells))
    specs = matrix_specs(cells, len(seeds))

    in_shard = state_shardings(mesh, states, shard_agents)
    a = "agent" if shard_agents else None
    spec_shard = CellSpec(
        coop=NamedSharding(mesh, P("seed", a)),
        greedy=NamedSharding(mesh, P("seed", a)),
        malicious=NamedSharding(mesh, P("seed", a)),
        H=NamedSharding(mesh, P("seed")),
        common_reward=NamedSharding(mesh, P("seed")),
        task_scale=NamedSharding(mesh, P("seed")),
    )
    states = jax.device_put(states, in_shard)
    specs = jax.device_put(specs, spec_shard)

    # The compiled executable depends only on program SHAPE — cell knobs
    # are data — so phase 2 of a sweep (and any repeated/resumed call)
    # must reuse it: that is the "one compile for the whole matrix"
    # benefit.
    fn = cached_jit(
        ("matrix", base, n_blocks, mesh, shard_agents, n_rep),
        lambda: jax.jit(
            jax.vmap(lambda st, sp: train_scanned(base, st, n_blocks, sp)),
            in_shardings=(in_shard, spec_shard),
            out_shardings=(in_shard, NamedSharding(mesh, P("seed"))),
        ),
    )
    return fn, states, specs


def lower_matrix(
    base: Config,
    cells: Sequence[Config],
    seeds: Sequence[int],
    n_blocks: int = 1,
    mesh: Optional[Mesh] = None,
    shard_agents: bool = False,
):
    """Lower (without executing) the fused-matrix program — the
    ``jax.stages.Lowered`` the graftlint collective census audits for
    the heterogeneous seed×agent mesh. Inspects lowering only; never
    runs the collectives."""
    fn, states, specs = _matrix_program(
        base, cells, seeds, n_blocks, mesh, None, shard_agents
    )
    return fn.lower(states, specs)


def reset_matrix_for_phase(
    base: Config, states: TrainState, cells: Sequence[Config], seeds
) -> TrainState:
    """The published two-phase restart boundary over the whole matrix:
    per replica, weights + goal layout carry over while Adam moments,
    buffer, and RNG re-initialize from the replica's seed
    (:func:`rcmarl_tpu.parallel.seeds.reset_states_for_phase`; reference
    ``main.py:46-54,83-86``)."""
    tiled_seeds = jnp.tile(jnp.asarray(seeds, jnp.uint32), len(cells))
    return reset_states_for_phase(base, states, tiled_seeds)


def split_matrix_metrics(
    metrics: EpisodeMetrics, n_cells: int, n_seeds: int
) -> List[List[EpisodeMetrics]]:
    """Slice flat cell-major replica metrics back into [cell][seed]
    :class:`EpisodeMetrics` (host-side convenience for writers)."""
    out: List[List[EpisodeMetrics]] = []
    for c in range(n_cells):
        row = []
        for s in range(n_seeds):
            i = c * n_seeds + s
            row.append(type(metrics)(*(leaf[i] for leaf in metrics)))
        out.append(row)
    return out
