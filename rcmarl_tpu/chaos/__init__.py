"""Chaos campaign — the fault surface as one named, swept, CI-gated object.

The repo's fault machinery grew piecewise: link faults (PR 2), Byzantine
replicas (PR 7), adaptive collusion (PR 12), checkpoint/serve rejects
(PR 10/14), pipeline skip semantics (PR 11) — each proven in its own
test or one-off script. This package is the single artifact that says
"this is the fault surface, and here is how the system degrades at each
point":

- :mod:`rcmarl_tpu.chaos.registry` — every injectable fault as a named
  :class:`ChaosPoint` (subsystem, injector, intensity knob, expected
  degradation, guard + test-pin pointers).
- :mod:`rcmarl_tpu.chaos.campaign` — the runner that sweeps points ×
  intensities as short REAL runs (per-cell fault isolation, the sweep
  discipline), classifies each cell survived/degraded/failed, and
  gates the committed ``RESILIENCE.jsonl`` ledger every CI run
  (``python -m rcmarl_tpu chaos --check``).
"""

from rcmarl_tpu.chaos.registry import (  # noqa: F401
    CHAOS_POINTS,
    OUTCOMES,
    CellFailed,
    ChaosPoint,
    ChaosSkip,
    registry_cells,
)
from rcmarl_tpu.chaos.campaign import (  # noqa: F401
    compare_rows,
    read_resilience,
    run_campaign,
    run_cell,
    write_resilience,
)
