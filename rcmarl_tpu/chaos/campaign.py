"""The chaos campaign runner + the CI-gated RESILIENCE.jsonl ledger.

One cell = one (:class:`~rcmarl_tpu.chaos.registry.ChaosPoint`,
intensity) pair run as a short REAL run with the sweep's per-cell fault
isolation (PR 2): a crashing cell is recorded ``failed`` with its error
and the sweep continues. Rows are canonical (sorted cells, sorted keys,
no timestamps), so regenerating on unchanged code is byte-stable —
exactly the AUDIT.jsonl discipline applied to resilience.

The gate (``python -m rcmarl_tpu chaos --check``) re-runs the cells and
compares against the committed ledger:

- ``chaos-regression`` — a cell's outcome moved DOWN the ladder
  (survived -> degraded/failed, degraded -> failed). The system lost
  containment it used to have.
- ``chaos-envelope``  — a cell's degradation envelope WIDENED: the
  |final - clean| return gap grew past ``ENVELOPE_TOL`` beyond the
  committed gap. Still contained, but measurably worse.
- ``chaos-unbaselined`` — a registry cell has no committed row (or the
  row's knobs/expectation drifted): regenerate the ledger in the same
  PR (``chaos --run``).
- ``chaos-stale`` — a committed row no longer names a registry cell.

Cost-arm discipline: a cell the host cannot run (``ChaosSkip``) is a
NOTE, never a stale/regression finding, and ``--run`` keeps skipped
cells' committed rows. An outcome moving UP the ladder is a note too —
an unclaimed win to regenerate, not a failure.
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from rcmarl_tpu.chaos.registry import (
    OUTCOMES,
    CellFailed,
    ChaosSkip,
    point_by_name,
    registry_cells,
)

#: Absolute widening (return units) the envelope gate tolerates on top
#: of the committed |final - clean| gap — tiny-cell returns are exactly
#: reproducible on one host, but the gate must survive a platform move.
ENVELOPE_TOL = 0.25

_RANK = {o: i for i, o in enumerate(OUTCOMES)}


def _cell_key(row: dict) -> Tuple[str, str]:
    return (row["point"], row["intensity"])


def _round(x: Optional[float]) -> Optional[float]:
    # ledger canonicalisation of an already-host float — no device pull
    if x is None or not math.isfinite(x):
        return None
    return round(float(x), 4)  # lint: disable=host-sync


def run_cell(point_name: str, intensity: str, runner=None) -> dict:
    """Run ONE campaign cell (fault-isolated) and return its canonical
    row. ``runner`` overrides the registry runner — the planted-
    regression tests inject a sabotaged variant through it."""
    point = point_by_name(point_name)
    if point is None:
        raise ValueError(f"unknown chaos point {point_name!r}")
    expected = dict(point.cells).get(intensity)
    if expected is None:
        raise ValueError(
            f"chaos point {point_name!r} has no intensity {intensity!r} "
            f"(cells: {[c for c, _ in point.cells]})"
        )
    run = runner if runner is not None else point.runner
    try:
        res = run(intensity)
    except ChaosSkip as e:
        res = {
            "outcome": "skipped",
            "counters": {},
            "final_return": None,
            "clean_return": None,
            "detail": str(e),
        }
    except CellFailed as e:
        res = {
            "outcome": "failed",
            "counters": {},
            "final_return": None,
            "clean_return": None,
            "detail": f"containment contract violated: {e}",
        }
    except Exception as e:  # noqa: BLE001 — per-cell fault isolation
        res = {
            "outcome": "failed",
            "counters": {},
            "final_return": None,
            "clean_return": None,
            "detail": f"{type(e).__name__}: {e}"[:300],
        }
    final = _round(res.get("final_return"))
    clean = _round(res.get("clean_return"))
    delta = (
        _round(final - clean)
        if final is not None and clean is not None
        else None
    )
    return {
        "kind": "chaos",
        "point": point.name,
        "subsystem": point.subsystem,
        "intensity": intensity,
        "expected": expected,
        "outcome": res["outcome"],
        "counters": {k: res["counters"][k] for k in sorted(res["counters"])},
        "final_return": final,
        "clean_return": clean,
        "return_delta": delta,
        "detail": res.get("detail", ""),
    }


def _select_cells(cells: Optional[Sequence[str]]) -> List[Tuple[str, str]]:
    """Resolve ``--cells`` tokens (``point`` or ``point@intensity``)
    against the registry; None = every cell."""
    all_cells = list(registry_cells())
    if not cells:
        return all_cells
    chosen: List[Tuple[str, str]] = []
    for token in cells:
        name, _, intensity = token.partition("@")
        matches = [
            c
            for c in all_cells
            if c[0] == name and (not intensity or c[1] == intensity)
        ]
        if not matches:
            raise ValueError(
                f"--cells {token!r} matches no registry cell; see "
                "`chaos --list`"
            )
        chosen += [c for c in matches if c not in chosen]
    return chosen


def run_campaign(
    cells: Optional[Sequence[str]] = None, verbose: bool = True
) -> Tuple[List[dict], List[str]]:
    """Run the selected cells (default: ALL); returns (rows, notes).
    Skipped cells become notes, not rows — the ledger only holds cells
    this run actually measured."""
    rows, notes = [], []
    for name, intensity in _select_cells(cells):
        row = run_cell(name, intensity)
        if row["outcome"] == "skipped":
            notes.append(
                f"{name}@{intensity} skipped on this host: {row['detail']}"
            )
            continue
        if verbose:
            print(
                f"# chaos {name}@{intensity}: {row['outcome']}"
                + (
                    f" (expected {row['expected']})"
                    if row["outcome"] != row["expected"]
                    else ""
                )
            )
        rows.append(row)
    rows.sort(key=lambda r: (r["subsystem"], r["point"], r["intensity"]))
    return rows, notes


# --------------------------------------------------------------------------
# ledger IO (the AUDIT.jsonl discipline: canonical, byte-stable)
# --------------------------------------------------------------------------


def read_resilience(path) -> List[dict]:
    p = Path(path)
    if not p.exists():
        return []
    rows = []
    for line in p.read_text().splitlines():
        line = line.strip()
        if line:
            rows.append(json.loads(line))
    return rows


def write_resilience(path, rows: Iterable[dict]) -> None:
    rows = sorted(
        rows, key=lambda r: (r["subsystem"], r["point"], r["intensity"])
    )
    text = "".join(json.dumps(r, sort_keys=True) + "\n" for r in rows)
    Path(path).write_text(text)


# --------------------------------------------------------------------------
# the gate
# --------------------------------------------------------------------------


def compare_rows(
    baseline: List[dict],
    fresh: List[dict],
    checked: Optional[Sequence[Tuple[str, str]]] = None,
) -> Tuple[List[str], List[str]]:
    """Findings + notes of a fresh (sub)campaign vs the committed
    ledger. ``checked`` is the cell set this run actually executed
    (``--cells`` subsets only judge what they ran); stale-row detection
    only applies on FULL checks (checked=None)."""
    findings, notes = [], []
    base = {_cell_key(r): r for r in baseline}
    new = {_cell_key(r): r for r in fresh}
    cells = list(new) if checked is None else list(checked)
    for key in cells:
        name = f"{key[0]}@{key[1]}"
        f = new.get(key)
        if f is None:
            continue  # skipped on this host — noted by the runner
        b = base.get(key)
        if b is None:
            findings.append(
                f"chaos-unbaselined: {name} has no committed "
                "RESILIENCE.jsonl row — regenerate with `chaos --run` "
                "and commit it in the same PR"
            )
            continue
        if b.get("expected") != f.get("expected"):
            findings.append(
                f"chaos-unbaselined: {name} expectation drifted "
                f"({b.get('expected')!r} -> {f.get('expected')!r}) — "
                "the registry changed; regenerate the ledger"
            )
            continue
        rb, rf = _RANK[b["outcome"]], _RANK[f["outcome"]]
        if rf > rb:
            findings.append(
                f"chaos-regression: {name} was {b['outcome']!r}, now "
                f"{f['outcome']!r} — {f['detail']}"
            )
            continue
        if rf < rb:
            notes.append(
                f"{name} improved {b['outcome']!r} -> {f['outcome']!r} "
                "(unclaimed win — regenerate the ledger to bank it)"
            )
        db, df_ = b.get("return_delta"), f.get("return_delta")
        if db is not None and df_ is not None:
            if abs(df_) > abs(db) + ENVELOPE_TOL:
                findings.append(
                    f"chaos-envelope: {name} degradation envelope "
                    f"widened |{df_}| > |{db}| + {ENVELOPE_TOL} "
                    "(final-vs-clean return gap)"
                )
        elif (db is None) != (df_ is None):
            notes.append(
                f"{name} return-delta availability changed "
                f"({db} -> {df_}); counters: {f.get('counters')}"
            )
    if checked is None:
        known = set(registry_cells())
        for key, b in base.items():
            if key not in known:
                findings.append(
                    f"chaos-stale: committed row {key[0]}@{key[1]} names "
                    "no registry cell — regenerate the ledger"
                )
    return findings, notes


def check_campaign(
    baseline_path, cells: Optional[Sequence[str]] = None
) -> Tuple[List[str], List[str], List[dict]]:
    """The full ``chaos --check``: run the (sub)campaign, compare, and
    return (findings, notes, fresh rows)."""
    baseline = read_resilience(baseline_path)
    if not baseline:
        return (
            [
                f"chaos-unbaselined: no committed ledger at "
                f"{baseline_path} — generate one with `chaos --run`"
            ],
            [],
            [],
        )
    checked = _select_cells(cells)
    fresh, notes = run_campaign(cells)
    findings, cmp_notes = compare_rows(
        baseline, fresh, checked=None if cells is None else checked
    )
    return findings, notes + cmp_notes, fresh
